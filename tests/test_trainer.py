"""ContinuousTrainer (round-17 tentpole): the train → bundle → canary →
promote loop, tier-1 slice.

The test vehicle is ``StreamLR`` — a streaming least-squares estimator
riding ``ChunkedFitLoop.run_one`` exactly like ``MiniBatchKMeans`` does
(same protocol, tiny closed-form solve), chosen because its predictions
decode to an exact oracle: the export pipeline's intercept encodes
(tenant, generation) as ``1000·(tenant+1) + 10·gen``, so every routed
response names which generation answered.  One module-scoped run drives
three generations through a live ModelRouter plus an explicit rollback;
the tests assert on its captured ledger/stats/decodes (compile-cache
friendly — the expensive loop runs once).  The slow end-to-end soak with
faults at every seam is ``tests/test_chaos_soak.py::
test_chaos_trainer_soak``; this file keeps the fast, deterministic
pins: ledger/checksum integrity, export retry/backoff + the
atomic-no-partial-artifact invariant, canary budget exhaustion to the
typed ``PromotionFailed``, and quarantine accounting across generations.
"""

import json
import os
import zlib
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.runtime import (ContinuousTrainer, PromotionFailed, Retry,
                                fitloop as _fitloop)
from dislib_tpu.runtime import fetch as _fetch
from dislib_tpu.runtime import health as _health
from dislib_tpu.serving import ModelRouter, ServePipeline
from dislib_tpu.utils.checkpoint import FitCheckpoint, SnapshotCorrupt
from dislib_tpu.utils.faults import (CanaryGateTrip, FlakyCall,
                                     TornBundleWrite)
from dislib_tpu.utils.profiling import profiled_jit as _pjit

NF = 4
BUCKETS = (8,)
TENANT = "alpha"
BASE = 1000.0           # intercept encodes tenant...
STEP = 10.0             # ...and generation: 1000·(tenant+1) + 10·gen


@partial(_pjit, name="stream_lr_step")
def _slr_step(b, xtx, xty):
    """One streaming normal-equations accumulation — the whole batch is
    ONE fused dispatch, health vector included (the fitloop recipe)."""
    x = b[:, :-1]
    y = b[:, -1]
    x1 = jnp.concatenate([x, jnp.ones((x.shape[0], 1), x.dtype)], axis=1)
    new_xtx = xtx + x1.T @ x1
    new_xty = xty + x1.T @ y
    hvec = _health.health_vec(carries=(new_xtx, new_xty), inputs=(x1,))
    return new_xtx, new_xty, hvec


class StreamLR:
    """Streaming least squares on combined ``[x | y]`` host batches,
    riding ``ChunkedFitLoop.run_one`` — zero bespoke resilience code;
    rollback/watchdog/preemption/capacity all come from the driver (the
    ``MiniBatchKMeans`` recipe, linear-model edition so the trainer soak
    gets an exact decode oracle and a closed-form quality measure)."""

    def __init__(self, n_features):
        self.n_features = int(n_features)
        self._n1 = self.n_features + 1
        self._loop = None

    def partial_fit(self, batch, y=None, checkpoint=None, health=None):
        b = np.asarray(batch, np.float32)
        n1 = self._n1
        if self._loop is None:
            self._batch = {}
            self._loop = _fitloop.ChunkedFitLoop(
                "stream_lr", checkpoint=checkpoint, health=health,
                carry_names=("xtx", "xty"),
                carry_shapes=((n1, n1), (n1,)),
                save_every=checkpoint.every if checkpoint is not None else 1,
                # host-replicated carries: nothing to re-lay out on a
                # resize, but the hook's presence arms the elastic tier
                # and the capacity-driven resizes
                elastic=lambda mesh: None)
        loop = self._loop
        self._batch["b"] = jnp.asarray(b)

        def init(rem):
            return _fitloop.LoopState(
                (jnp.asarray(rem.perturb(np.zeros((n1, n1), np.float32))),
                 jnp.asarray(rem.perturb(np.zeros((n1,), np.float32)))))

        def restore(snap, rem):
            xtx = np.asarray(snap["xtx"])
            if xtx.shape != (n1, n1):
                raise ValueError(f"checkpoint xtx shape {xtx.shape} does "
                                 f"not match this stream {(n1, n1)}")
            return _fitloop.LoopState(
                (jnp.asarray(rem.perturb(xtx)),
                 jnp.asarray(rem.perturb(np.asarray(snap["xty"])))),
                it=int(snap["n_batches"]))

        def step(st, chunk):
            xtx, xty, hvec = _slr_step(self._batch["b"], *st.carries)
            return _fitloop.ChunkOutcome(
                lambda: _fitloop.LoopState((xtx, xty), st.it + 1),
                hvec=hvec)

        def snapshot(st):
            return {"xtx": _fetch(st.carries[0], blocking=False),
                    "xty": _fetch(st.carries[1], blocking=False),
                    "n_batches": st.it}

        st = loop.run_one(init=init, step=step, restore=restore,
                          snapshot=snapshot)
        xtx = np.asarray(jax.device_get(st.carries[0]), np.float64)
        xty = np.asarray(jax.device_get(st.carries[1]), np.float64)
        w = np.linalg.solve(xtx + 1e-6 * np.eye(n1), xty)
        self.coef_ = w[:-1].astype(np.float32).reshape(-1, 1)
        self.intercept_ = np.float32(w[-1])
        self.n_batches_ = st.it
        self.fit_info_ = loop.info
        return self


def _pipeline_of(tenant_idx=0):
    """pipeline_of factory: the exported model's intercept encodes
    (tenant, generation) — every response decodes to who answered."""
    def factory(est, gen):
        lr = ds.LinearRegression()
        lr.coef_ = np.asarray(est.coef_, np.float32).reshape(NF, 1)
        lr.intercept_ = np.asarray(
            [float(est.intercept_) + BASE * (tenant_idx + 1) + STEP * gen],
            np.float32)
        return ServePipeline(lr, n_features=NF)
    return factory


def _stream(seed=0, rows=32, sigma=0.0):
    """Infinite [x | y] batch stream with y = Σx (+ noise)."""
    rng = np.random.RandomState(seed)
    while True:
        x = rng.rand(rows, NF).astype(np.float32)
        y = x.sum(axis=1, keepdims=True) \
            + sigma * rng.randn(rows, 1).astype(np.float32)
        yield np.concatenate([x, y], axis=1)


def _decode(router, rng, n=6, tenant=TENANT, tenant_idx=0):
    """Submit n mixed-size requests; return the set of generations that
    answered (asserting every response is whole — no torn batches)."""
    gens = set()
    for i in range(n):
        k = int(rng.randint(1, BUCKETS[0] + 1))
        rows = rng.rand(k, NF).astype(np.float32)
        r = router.submit(rows, tenant, key=f"d{i}").result(timeout=60)
        vals = np.asarray(r.values).ravel() - rows.sum(axis=1) \
            - BASE * (tenant_idx + 1)
        g = np.unique(np.round(vals / STEP).astype(int))
        assert len(g) == 1, f"torn response: {vals}"
        gens.add(int(g[0]))
    return gens


@pytest.fixture(scope="module")
def ctx(tmp_path_factory):
    """One full trainer run: three generations promoted through a live
    router, a decode burst per promotion, then an explicit rollback.
    Everything the module's tests assert on is captured here — the
    expensive loop runs once."""
    from dislib_tpu.utils import profiling as prof
    root = tmp_path_factory.mktemp("trainer")
    ck = FitCheckpoint(str(root / "ck.npz"), every=1, keep=2)
    est = StreamLR(NF)
    router = ModelRouter(name="t-router")
    rng = np.random.RandomState(7)
    out = {"bundle_dir": str(root / "bundles"), "decoded": [],
           "trace_deltas": []}
    with router:
        tr = ContinuousTrainer(
            est, _stream(), ck, _pipeline_of(0), out["bundle_dir"],
            router=router, tenant=TENANT, buckets=BUCKETS,
            batches_per_generation=2, canary_fraction=0.5,
            promote_budget=2, retry=Retry(attempts=3, backoff=0.0),
            probe=rng.rand(4, NF).astype(np.float32))
        records = [tr.step() for _ in range(3)]
        # decode burst against the served generation — and pin the
        # serving hot path's zero-retrace discipline while training idles
        t0 = prof.trace_count()
        out["decoded"].append(_decode(router, rng))
        out["trace_deltas"].append(prof.trace_count() - t0)
        rb = tr.rollback()
        t0 = prof.trace_count()
        out["decoded"].append(_decode(router, rng))
        out["trace_deltas"].append(prof.trace_count() - t0)
        out.update(records=records, rollback_record=rb,
                   ledger=list(tr.ledger), stats=tr.stats(),
                   router_stats=router.stats(), est=est)
        tr.close()
    return out


class TestTrainerLoop:
    def test_three_generations_promoted(self, ctx):
        assert [r["verdict"] for r in ctx["records"]] == ["promoted"] * 3
        assert [r["generation"] for r in ctx["records"]] == [1, 2, 3]
        s = ctx["stats"]
        assert s["promotions"] == 3 and s["exports"] == 3
        assert s["canary_rejections"] == 0 and s["promote_failures"] == 0

    def test_served_generation_monotone_then_explicit_rollback(self, ctx):
        served = [r["served"] for r in ctx["ledger"]]
        assert served == [1, 2, 3, 2]       # monotone, then rollback
        assert ctx["rollback_record"]["verdict"] == "rollback"
        assert ctx["stats"]["served_generation"] == 2
        assert ctx["stats"]["rollbacks_of_served"] == 1

    def test_decode_oracle_tracks_promotion_and_rollback(self, ctx):
        # after 3 promotions every response comes from gen 3; after the
        # explicit rollback every response comes from gen 2
        assert ctx["decoded"][0] == {3}
        assert ctx["decoded"][1] == {2}

    def test_zero_retrace_on_the_serving_path(self, ctx):
        assert ctx["trace_deltas"] == [0, 0]

    def test_ledger_checksums_match_artifacts(self, ctx):
        for rec in ctx["ledger"]:
            with open(rec["path"], "rb") as f:
                assert rec["checksum"] == zlib.crc32(f.read()), rec

    def test_ledger_jsonl_mirrors_memory(self, ctx):
        path = os.path.join(ctx["bundle_dir"], "ledger.jsonl")
        rows = [json.loads(line) for line in open(path)]
        assert rows == ctx["ledger"]

    def test_stats_surface(self, ctx):
        s = ctx["stats"]
        for key in ("promotions", "canary_rejections", "promote_failures",
                    "rollbacks", "rollbacks_of_served", "exports",
                    "export_retries", "batches", "batches_skipped",
                    "preemptions", "generation", "served_generation",
                    "last_good", "quarantine", "stream"):
            assert key in s, key
        assert s["generation"] == 3 and s["last_good"] == 2
        assert s["batches"] == 6 and s["stream"]["chunks"] == 6

    def test_router_stats_gain_failure_and_rollback_counts(self, ctx):
        rs = ctx["router_stats"][TENANT]
        # gen 1 is the initial deploy (add_tenant), gens 2 and 3 are
        # router promotions; the explicit rollback is counted once
        assert rs["promotions"] == 2
        assert rs["promote_failures"] == 0
        assert rs["rollbacks"] == 1

    def test_model_actually_learned(self, ctx):
        est = ctx["est"]
        np.testing.assert_allclose(np.asarray(est.coef_).ravel(),
                                   np.ones(NF), atol=1e-3)
        assert abs(float(est.intercept_)) < 1e-2


class TestExportRetry:
    """Satellite: the bundle-export retry/backoff seam — transient IO,
    torn artifacts, budget exhaustion, and the atomic no-partial-artifact
    invariant."""

    def _trainer(self, tmp_path, retry):
        ck = FitCheckpoint(str(tmp_path / "ck.npz"), every=1)
        return ContinuousTrainer(
            StreamLR(NF), _stream(seed=3), ck, _pipeline_of(0),
            str(tmp_path / "bundles"), batches_per_generation=1,
            buckets=BUCKETS, retry=retry)

    def test_eintr_style_transient_succeeds_within_budget(
            self, tmp_path, monkeypatch):
        from dislib_tpu.runtime.bundle_io import write_bundle as real
        flaky = FlakyCall(real, failures=2,
                          exc_factory=lambda: InterruptedError("EINTR"))
        monkeypatch.setattr("dislib_tpu.serving.bundle.write_bundle", flaky)
        tr = self._trainer(tmp_path, Retry(attempts=4, backoff=0.0))
        rec = tr.step()
        assert rec["verdict"] == "exported"
        assert flaky.calls == 3
        assert tr.stats()["export_retries"] == 2

    def test_torn_then_clean_succeeds_and_artifact_verifies(
            self, tmp_path, monkeypatch):
        torn = TornBundleWrite(failures=1, mode="truncate")
        monkeypatch.setattr("dislib_tpu.serving.bundle.write_bundle", torn)
        tr = self._trainer(tmp_path, Retry(
            attempts=3, backoff=0.0,
            classify=ContinuousTrainer._classify_export))
        rec = tr.step()
        assert rec["verdict"] == "exported" and torn.calls == 2
        assert tr.stats()["export_retries"] == 1
        # the artifact that survived is the CLEAN rewrite — loads whole
        from dislib_tpu.serving.bundle import load_bundle
        assert load_bundle(rec["path"]).buckets == BUCKETS

    def test_corrupt_on_disk_exhausts_to_typed_error(
            self, tmp_path, monkeypatch):
        torn = TornBundleWrite(failures=10, mode="flip")
        monkeypatch.setattr("dislib_tpu.serving.bundle.write_bundle", torn)
        tr = self._trainer(tmp_path, Retry(
            attempts=2, backoff=0.0,
            classify=ContinuousTrainer._classify_export))
        with pytest.raises(SnapshotCorrupt):
            tr.step()
        assert torn.calls == 2              # budget spent, typed raise

    def test_transient_exhaustion_leaves_no_partial_artifact(
            self, tmp_path, monkeypatch):
        def _always_eintr(path, arrays):
            raise InterruptedError("EINTR")
        monkeypatch.setattr("dislib_tpu.serving.bundle.write_bundle",
                            _always_eintr)
        tr = self._trainer(tmp_path, Retry(attempts=3, backoff=0.0))
        with pytest.raises(InterruptedError):
            tr.step()
        # atomic invariant, counter-asserted: nothing — no bundle, no
        # tmp file — is visible in the bundle dir after exhaustion
        assert os.listdir(tmp_path / "bundles") == []

    def test_snapshot_corrupt_is_fatal_without_export_classify(self):
        # regression pin: SnapshotCorrupt is a ValueError, so the DEFAULT
        # classification calls it fatal — the trainer's export seam must
        # override (a torn artifact is fixed by rewriting it)
        from dislib_tpu.runtime.retry import is_transient_error
        exc = SnapshotCorrupt("torn")
        assert not is_transient_error(exc)
        assert ContinuousTrainer._classify_export(exc) is True
        assert ContinuousTrainer._classify_export(OSError(5, "eio")) is None


class TestPromotionGate:
    def test_canary_budget_exhausts_to_promotion_failed(self, tmp_path):
        trip = CanaryGateTrip(times=99)

        def gate(loaded, g):
            return True if g == 1 else trip(loaded, g)

        ck = FitCheckpoint(str(tmp_path / "ck.npz"), every=1)
        router = ModelRouter(name="gate-router")
        rng = np.random.RandomState(11)
        with router:
            tr = ContinuousTrainer(
                StreamLR(NF), _stream(seed=5), ck, _pipeline_of(0),
                str(tmp_path / "bundles"), router=router, tenant=TENANT,
                buckets=BUCKETS, batches_per_generation=1,
                promote_budget=2, health_gate=gate,
                retry=Retry(attempts=2, backoff=0.0))
            assert tr.step()["verdict"] == "promoted"      # initial deploy
            assert tr.step()["verdict"] == "rejected"      # stays on 1
            assert _decode(router, rng, n=3) == {1}
            with pytest.raises(PromotionFailed) as ei:
                tr.step()
            err = ei.value
            assert err.tenant == TENANT and err.last_good == 1
            assert err.attempts == 2 and err.generation == 3
            # the rejected canaries never took the primary: last-good
            # still answers every request
            assert _decode(router, rng, n=3) == {1}
            s = tr.stats()
            assert s["canary_rejections"] == 2 and s["rollbacks"] == 2
            assert s["promote_failures"] == 1
            assert s["served_generation"] == 1
            rs = router.stats()[TENANT]
            assert rs["promote_failures"] == 2 and rs["promotions"] == 0
            verdicts = [r["verdict"] for r in tr.ledger]
            assert verdicts == ["promoted", "rejected", "rejected+budget"]
            tr.close()

    def test_gate_exception_counts_as_veto_not_crash(self, tmp_path):
        def gate(loaded, g):
            if g == 1:
                return True
            raise RuntimeError("probe service down")

        ck = FitCheckpoint(str(tmp_path / "ck.npz"), every=1)
        router = ModelRouter(name="veto-router")
        with router:
            tr = ContinuousTrainer(
                StreamLR(NF), _stream(seed=6), ck, _pipeline_of(0),
                str(tmp_path / "bundles"), router=router, tenant=TENANT,
                buckets=BUCKETS, batches_per_generation=1,
                promote_budget=3, health_gate=gate,
                retry=Retry(attempts=2, backoff=0.0))
            tr.step()
            rec = tr.step()
            assert rec["verdict"] == "rejected"
            assert "probe service down" in rec["gate_error"]
            assert tr.stats()["served_generation"] == 1
            tr.close()


class TestQuarantineSeam:
    """Satellite: the trainer's stream rides the QuarantineLedger per
    batch — totals accumulate across generations, reports stay capped."""

    def test_totals_accumulate_and_reports_cap_under_always_dirty(
            self, tmp_path, monkeypatch):
        from dislib_tpu.data import io as dio
        led = dio.QuarantineLedger(max_reports=3)
        monkeypatch.setattr(dio, "_LEDGER", led)
        ck = FitCheckpoint(str(tmp_path / "ck.npz"), every=1)
        dirty = (np.full((4, NF + 1), np.nan, np.float32)
                 for _ in range(8))
        tr = ContinuousTrainer(
            StreamLR(NF), dirty, ck, _pipeline_of(0),
            str(tmp_path / "bundles"), batches_per_generation=4)
        with pytest.warns(RuntimeWarning):
            assert tr.train_generation()    # all 4 batches skipped
            assert tr.train_generation()
        s = tr.stats()
        assert s["batches"] == 0 and s["batches_skipped"] == 8
        # exact totals survive past the retained-report cap
        assert s["quarantine"]["n_quarantined"] == 32
        assert s["quarantine"]["reports_retained"] == 3
        assert led.n_quarantined == 32 and len(led.reports) == 3

    def test_mixed_stream_feeds_clean_rows_only(self, tmp_path,
                                                monkeypatch):
        from dislib_tpu.data import io as dio
        monkeypatch.setattr(dio, "_LEDGER", dio.QuarantineLedger())

        def mixed():
            for b in _stream(seed=9, rows=16):
                b[0, 0] = np.nan            # one dirty row per batch
                yield b

        ck = FitCheckpoint(str(tmp_path / "ck.npz"), every=1)
        tr = ContinuousTrainer(
            StreamLR(NF), mixed(), ck, _pipeline_of(0),
            str(tmp_path / "bundles"), batches_per_generation=3)
        with pytest.warns(RuntimeWarning):
            assert tr.train_generation()
        s = tr.stats()
        assert s["batches"] == 3 and s["batches_skipped"] == 0
        assert s["quarantine"]["n_quarantined"] == 3
        assert s["quarantine"]["n_loaded"] == 45
        # the model never saw the poison: it still solves exactly
        np.testing.assert_allclose(
            np.asarray(tr.estimator.coef_).ravel(), np.ones(NF), atol=1e-3)


class TestStreamEnd:
    def test_finite_stream_exhausts_cleanly(self, tmp_path):
        finite = (b for b in [next(_stream(seed=13)) for _ in range(3)])
        ck = FitCheckpoint(str(tmp_path / "ck.npz"), every=1)
        tr = ContinuousTrainer(
            StreamLR(NF), finite, ck, _pipeline_of(0),
            str(tmp_path / "bundles"), batches_per_generation=2,
            buckets=BUCKETS, retry=Retry(attempts=2, backoff=0.0))
        assert tr.step()["verdict"] == "exported"   # 2 batches
        assert tr.step()["verdict"] == "exported"   # final partial (1)
        assert tr.step() is None                    # exhausted
        s = tr.stats()
        assert s["stream_exhausted"] and s["generation"] == 2
        assert s["batches"] == 3
