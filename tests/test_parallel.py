"""Mesh + multi-host init tests (SURVEY.md §3.7/§6: topology is a named
Mesh; `initialize` is the runcompss analog — single-process path must be a
clean no-op)."""

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu import parallel


class TestDistributedInit:
    def test_single_process_noop(self, monkeypatch):
        monkeypatch.delenv("DSLIB_COORDINATOR", raising=False)
        monkeypatch.delenv("DSLIB_NUM_PROCS", raising=False)
        parallel.initialize()            # no args, no env: must not raise
        assert not parallel.is_initialized()

    def test_process_info_single(self):
        idx, cnt = parallel.process_info()
        assert (idx, cnt) == (0, 1)


class TestMesh:
    def test_default_mesh_spans_devices(self):
        import jax
        ds.init()
        r, c = parallel.mesh_shape()
        assert r * c == len(jax.devices())

    def test_explicit_shape_and_quantum(self):
        from conftest import skip_unless_devices
        skip_unless_devices(8)
        ds.init((2, 4))
        assert parallel.mesh_shape() == (2, 4)
        assert parallel.pad_quantum() == 4
        ds.init((4, 2))
        assert parallel.pad_quantum() == 4

    def test_env_mesh(self, monkeypatch):
        from conftest import skip_unless_devices
        skip_unless_devices(4)
        monkeypatch.setenv("DSLIB_MESH", "2,2")
        ds.init()
        assert parallel.mesh_shape() == (2, 2)

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError):
            ds.init((100, 100))

    def test_library_does_not_touch_global_precision(self):
        import jax
        before = jax.config.jax_default_matmul_precision
        x = ds.random_array((32, 8), random_state=0)
        ds.cluster.KMeans(n_clusters=2, random_state=0).fit(x)
        assert jax.config.jax_default_matmul_precision == before
