"""Ring-parallel kNN (ops/ring.py): oracle equivalence with the direct path
on the 8-virtual-device mesh, including irregular shapes and padded rows."""

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.neighbors import NearestNeighbors
from dislib_tpu.parallel import mesh as _mesh


def _oracle_knn(q, f, k):
    d = ((q * q).sum(1)[:, None] - 2.0 * (q @ f.T)
         + (f * f).sum(1)[None, :])
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    dist = np.sqrt(np.maximum(np.take_along_axis(d, idx, axis=1), 0.0))
    return dist, idx


@pytest.mark.parametrize("mq,mf,n,k", [
    (40, 64, 6, 3),
    (37, 53, 5, 5),       # irregular: pad rows on both operands
    (16, 200, 3, 7),
])
def test_ring_matches_direct_and_oracle(mq, mf, n, k):
    rng = np.random.RandomState(0)
    q = rng.rand(mq, n).astype(np.float32)
    f = rng.rand(mf, n).astype(np.float32)
    xq = ds.array(q, block_size=(8, n))
    xf = ds.array(f, block_size=(8, n))

    nn_ring = NearestNeighbors(n_neighbors=k, ring=True).fit(xf)
    d_r, i_r = nn_ring.kneighbors(xq)
    nn_dir = NearestNeighbors(n_neighbors=k, ring=False).fit(xf)
    d_d, i_d = nn_dir.kneighbors(xq)

    d_o, i_o = _oracle_knn(q, f, k)
    np.testing.assert_allclose(np.asarray(d_r.collect()), d_o,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(d_d.collect()), d_o,
                               rtol=1e-4, atol=1e-4)
    # random data → distinct distances → index agreement is well-defined
    np.testing.assert_array_equal(np.asarray(i_r.collect()), i_o)
    np.testing.assert_array_equal(np.asarray(i_d.collect()), i_o)


def test_ring_auto_routing_threshold():
    from dislib_tpu.neighbors import base as nb
    rng = np.random.RandomState(1)
    f = rng.rand(64, 4).astype(np.float32)
    x = ds.array(f, block_size=(16, 4))
    old = nb._RING_MIN
    nb._RING_MIN = 32          # force auto-route on small data
    try:
        nn = NearestNeighbors(n_neighbors=2).fit(x)     # ring=None → auto
        d_auto, i_auto = nn.kneighbors(x)
    finally:
        nb._RING_MIN = old
    d_o, i_o = _oracle_knn(f, f, 2)
    # self-distances: the ‖q‖²−2qᵀf+‖f‖² expansion leaves O(√eps) noise
    # where the true distance is 0, hence the looser atol
    np.testing.assert_allclose(np.asarray(d_auto.collect()), d_o,
                               rtol=1e-4, atol=1e-3)
    np.testing.assert_array_equal(np.asarray(i_auto.collect()), i_o)


def test_ring_dbscan_matches_dense():
    """DBSCAN with ε-passes ring-distributed over the mesh rows axis gives
    the exact labels of the dense single-program path."""
    from dislib_tpu.cluster import dbscan as dbm
    rng = np.random.RandomState(3)
    # three separated blobs + outliers
    blobs = [rng.randn(30, 3) * 0.05 + c for c in
             ([0, 0, 0], [3, 3, 3], [-3, 2, 0])]
    pts = np.vstack(blobs + [rng.uniform(-8, 8, (7, 3))]).astype(np.float32)
    x = ds.array(pts, block_size=(16, 3))

    ref = dbm.DBSCAN(eps=0.5, min_samples=4).fit(x)        # dense path
    old = dbm._RING
    dbm._RING = True
    try:
        got = dbm.DBSCAN(eps=0.5, min_samples=4).fit(x)    # ring path
    finally:
        dbm._RING = old
    np.testing.assert_array_equal(got.labels_, ref.labels_)
    np.testing.assert_array_equal(got.core_sample_indices_,
                                  ref.core_sample_indices_)
    assert got.n_clusters_ == ref.n_clusters_ == 3


def test_ring_daura_matches_dense():
    from dislib_tpu.cluster import daura as dm
    rng = np.random.RandomState(4)
    # frames = 3*n_atoms coords; two tight conformation clusters + strays
    f1 = rng.randn(20, 12) * 0.02
    f2 = rng.randn(20, 12) * 0.02 + 2.0
    pts = np.vstack([f1, f2, rng.uniform(-5, 5, (5, 12))]).astype(np.float32)
    x = ds.array(pts, block_size=(16, 12))

    ref = dm.Daura(cutoff=0.5).fit(x)
    old = dm._RING
    dm._RING = True
    try:
        got = dm.Daura(cutoff=0.5).fit(x)
    finally:
        dm._RING = old
    np.testing.assert_array_equal(got.labels_, ref.labels_)
    assert len(got.clusters_) == len(ref.clusters_)
    for a, b in zip(got.clusters_, ref.clusters_):
        np.testing.assert_array_equal(a, b)


def test_ring_k_exceeds_per_shard_rows():
    """k larger than any single shard's fitted rows: the running merge must
    accumulate across ring steps, not rely on one visiting shard."""
    rng = np.random.RandomState(2)
    q = rng.rand(24, 4).astype(np.float32)
    f = rng.rand(32, 4).astype(np.float32)
    xq, xf = ds.array(q, block_size=(8, 4)), ds.array(f, block_size=(8, 4))
    k = 20  # > 32/4 = 8 rows per shard on the 4-row mesh
    d_r, i_r = NearestNeighbors(n_neighbors=k, ring=True).fit(xf) \
        .kneighbors(xq)
    d_o, i_o = _oracle_knn(q, f, k)
    np.testing.assert_allclose(np.asarray(d_r.collect()), d_o,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i_r.collect()), i_o)
