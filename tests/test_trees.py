"""Trees tests (reference: test_rf_classifier.py, test_rf_regressor.py,
test_decision_tree.py — SURVEY.md §5 oracle pattern: accuracy/R² vs sklearn
on the same data)."""

import warnings

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.trees import (
    RandomForestClassifier, RandomForestRegressor,
    DecisionTreeClassifier, DecisionTreeRegressor,
)


def _class_data(rng, n=300, d=6, k=3):
    centers = rng.randn(k, d) * 3
    x = np.vstack([centers[i] + rng.randn(n // k, d) * 0.7 for i in range(k)])
    y = np.repeat(np.arange(k), n // k).astype(np.float32)
    p = rng.permutation(len(y))
    return x[p].astype(np.float32), y[p]


def _reg_data(rng, n=300, d=5):
    x = rng.rand(n, d).astype(np.float32) * 4
    y = (np.sin(x[:, 0]) * 3 + x[:, 1] ** 2 - 2 * x[:, 2]).astype(np.float32)
    return x, y


class TestRandomForestClassifier:
    def test_separable_accuracy(self, rng):
        x, y = _class_data(rng)
        rf = RandomForestClassifier(n_estimators=8, random_state=0)
        rf.fit(ds.array(x), ds.array(y[:, None]))
        assert rf.score(ds.array(x), ds.array(y[:, None])) >= 0.95

    def test_vs_sklearn_holdout(self, rng):
        from sklearn.ensemble import RandomForestClassifier as SkRF
        x, y = _class_data(rng, n=400, d=5, k=2)
        xt, yt = x[:300], y[:300]
        xv, yv = x[300:], y[300:]
        rf = RandomForestClassifier(n_estimators=10, random_state=0)
        rf.fit(ds.array(xt), ds.array(yt[:, None]))
        mine = rf.score(ds.array(xv), ds.array(yv[:, None]))
        sk = SkRF(n_estimators=10, random_state=0).fit(xt, yt).score(xv, yv)
        assert mine >= sk - 0.07

    def test_hard_vote(self, rng):
        x, y = _class_data(rng, n=150, d=4, k=2)
        rf = RandomForestClassifier(n_estimators=5, hard_vote=True,
                                    random_state=0)
        rf.fit(ds.array(x), ds.array(y[:, None]))
        assert rf.score(ds.array(x), ds.array(y[:, None])) >= 0.9

    def test_predict_proba(self, rng):
        x, y = _class_data(rng, n=120, d=4, k=3)
        rf = RandomForestClassifier(n_estimators=4, random_state=0)
        rf.fit(ds.array(x), ds.array(y[:, None]))
        proba = rf.predict_proba(ds.array(x)).collect()
        assert proba.shape == (120, 3)
        assert np.allclose(proba.sum(axis=1), 1.0, atol=1e-5)

    def test_original_labels(self, rng):
        x, y = _class_data(rng, n=90, d=3, k=2)
        y2 = np.where(y > 0, 5.0, -2.0).astype(np.float32)
        rf = RandomForestClassifier(n_estimators=3, random_state=0)
        rf.fit(ds.array(x), ds.array(y2[:, None]))
        pred = rf.predict(ds.array(x)).collect().ravel()
        assert set(np.unique(pred)) <= {-2.0, 5.0}

    def test_not_fitted(self, rng):
        with pytest.raises(RuntimeError):
            RandomForestClassifier().predict(ds.array(rng.rand(4, 2)))


class TestRandomForestRegressor:
    def test_r2_train(self, rng):
        x, y = _reg_data(rng)
        rf = RandomForestRegressor(n_estimators=8, random_state=0)
        rf.fit(ds.array(x), ds.array(y[:, None]))
        assert rf.score(ds.array(x), ds.array(y[:, None])) >= 0.8

    def test_vs_sklearn_holdout(self, rng):
        from sklearn.ensemble import RandomForestRegressor as SkRF
        x, y = _reg_data(rng, n=400)
        xt, yt, xv, yv = x[:300], y[:300], x[300:], y[300:]
        rf = RandomForestRegressor(n_estimators=10, random_state=0)
        rf.fit(ds.array(xt), ds.array(yt[:, None]))
        mine = rf.score(ds.array(xv), ds.array(yv[:, None]))
        sk = SkRF(n_estimators=10, random_state=0).fit(xt, yt).score(xv, yv)
        assert mine >= sk - 0.15


class TestDecisionTree:
    def test_classifier_overfits_train(self, rng):
        x, y = _class_data(rng, n=200, d=5, k=3)
        dt = DecisionTreeClassifier(random_state=0)
        dt.fit(ds.array(x), ds.array(y[:, None]))
        assert dt.score(ds.array(x), ds.array(y[:, None])) >= 0.97

    def test_regressor_fits_train(self, rng):
        x, y = _reg_data(rng, n=200)
        dt = DecisionTreeRegressor(random_state=0)
        dt.fit(ds.array(x), ds.array(y[:, None]))
        assert dt.score(ds.array(x), ds.array(y[:, None])) >= 0.9

    def test_max_depth_limits(self, rng):
        x, y = _class_data(rng, n=100, d=3, k=2)
        dt = DecisionTreeClassifier(max_depth=2, random_state=0)
        dt.fit(ds.array(x), ds.array(y[:, None]))
        assert dt._depth == 2


class TestNBinsContract:
    """The discretisation contract (decision_tree.py module docstring):
    quantile-histogram splits at n_bins granularity, with n_bins a
    constructor knob — including a distribution where the default 32 bins
    provably lose the minority structure and n_bins=256 recovers it."""

    def _fine_boundary(self):
        # 1% minority class below x=0.01 on a uniform feature: 32 quantile
        # bins put the first edge at the ~3.1% quantile, so bin 0 mixes
        # the whole minority with twice as many majority rows — majority
        # vote erases the minority. 256 bins resolve it.
        x = np.linspace(0.0, 1.0, 10_000, dtype=np.float32)[:, None]
        y = (x[:, 0] < 0.01).astype(np.float32)[:, None]
        return x, y

    def _minority_recall(self, clf, x, y):
        pred = np.asarray(
            clf.predict(ds.array(x)).collect()).ravel()
        mask = y.ravel() == 1.0
        return float((pred[mask] == 1.0).mean())

    def test_n_bins_contract(self):
        x, y = self._fine_boundary()
        lose = DecisionTreeClassifier(max_depth=6, random_state=0)
        lose.fit(ds.array(x), ds.array(y))
        win = DecisionTreeClassifier(max_depth=6, random_state=0, n_bins=256)
        win.fit(ds.array(x), ds.array(y))
        assert self._minority_recall(lose, x, y) < 0.2   # 32 bins: erased
        assert self._minority_recall(win, x, y) > 0.7    # 256 bins: found

    def test_n_bins_forest_and_validation(self, rng):
        from dislib_tpu.trees import RandomForestClassifier
        x = rng.rand(200, 4).astype(np.float32)
        y = (x[:, 0] > 0.5).astype(np.float32)[:, None]
        rf = RandomForestClassifier(n_estimators=4, random_state=0, n_bins=64)
        rf.fit(ds.array(x), ds.array(y))
        assert rf.score(ds.array(x), ds.array(y)) > 0.9
        with pytest.raises(ValueError, match="n_bins"):
            DecisionTreeClassifier(n_bins=1).fit(ds.array(x), ds.array(y))
        with pytest.raises(ValueError, match="n_bins"):
            DecisionTreeClassifier(n_bins=0).fit(ds.array(x), ds.array(y))

    def test_depth_cap_warns(self, rng):
        x, y = _class_data(rng, n=100, d=3, k=2)
        dt = DecisionTreeClassifier(max_depth=40, random_state=0)
        with pytest.warns(UserWarning, match="depth cap"):
            dt.fit(ds.array(x), ds.array(y[:, None]))
        assert dt._depth <= 12

    def test_pre_n_bins_snapshot_refused_as_version_change(self, rng,
                                                           tmp_path):
        # a checkpoint written before n_bins joined the fingerprint (8
        # elements vs 9) must be refused with the version message, not the
        # data-mismatch one
        from dislib_tpu.utils import FitCheckpoint
        from dislib_tpu.trees import RandomForestClassifier
        x, y = _class_data(rng, n=120, d=4, k=2)
        path = str(tmp_path / "rf.npz")
        rf = RandomForestClassifier(n_estimators=2, random_state=0)
        rf.fit(ds.array(x), ds.array(y[:, None]),
               checkpoint=FitCheckpoint(path, every=1))
        from dislib_tpu.utils import checkpoint as ckm
        snap = dict(np.load(path, allow_pickle=False))
        snap.pop(ckm._CRC_KEY)
        snap["fp"] = snap["fp"][:-1]            # simulate the old 8-knob fp
        # rewrite through save() so the integrity checksum matches the
        # tampered payload — otherwise load() classifies it corrupt and
        # falls back to the rotated previous generation instead of
        # reaching the fp version check
        ck = FitCheckpoint(path, every=1)
        ck.delete()                             # drop rotated generations
        ck.save(snap)
        with pytest.raises(ValueError, match="different library version"):
            RandomForestClassifier(n_estimators=2, random_state=0).fit(
                ds.array(x), ds.array(y[:, None]),
                checkpoint=FitCheckpoint(path, every=1))


# ---------------------------------------------------------------------------
# round-17 Pallas tier two: the level histogram as a one-hot GEMM
# ---------------------------------------------------------------------------

class TestHistogramKernel:
    """The forest's (node, feature, bin) scatter-add re-expressed as a
    Pallas one-hot GEMM must be BIT-equal to the XLA scatter (the
    forest's contributions — Poisson weights × count/target stats — are
    integer-representable, so both summation orders are exact), routed
    once at the fit boundary, and counter-observable."""

    def _inputs(self, rng, m, n, n_nodes, n_bins, s, dtype=np.float32):
        node = rng.randint(0, n_nodes, m).astype(np.int32)
        bx = rng.randint(0, n_bins, (m, n)).astype(np.int32)
        w = rng.poisson(1.0, m).astype(dtype)
        stats = rng.randint(0, 3, (m, s)).astype(dtype)
        return node, bx, w, stats

    @pytest.mark.parametrize("shape", [(64, 3, 2, 4, 2),
                                       (128, 5, 4, 8, 3),
                                       (200, 2, 8, 32, 1)])
    def test_pallas_bit_equal_to_xla_scatter(self, rng, shape):
        import jax.numpy as jnp
        from dislib_tpu.ops import pallas_kernels as _pk
        from dislib_tpu.trees.decision_tree import _node_histogram
        if not _pk.hist_available():
            pytest.skip("pallas histogram kernel unavailable")
        m, n, n_nodes, n_bins, s = shape
        node, bx, w, stats = self._inputs(rng, m, n, n_nodes, n_bins, s)
        outs = {}
        for sched in ("xla", "pallas"):
            outs[sched] = np.asarray(_node_histogram(
                jnp.asarray(node), jnp.asarray(bx), jnp.asarray(w),
                jnp.asarray(stats), n_nodes, n_bins, hist=sched))
        assert outs["xla"].dtype == outs["pallas"].dtype
        np.testing.assert_array_equal(outs["xla"], outs["pallas"])
        # and the histogram is the histogram: a plain numpy scatter oracle
        want = np.zeros((n_nodes, n, n_bins, s), np.float32)
        for i in range(m):
            for f in range(n):
                want[node[i], f, bx[i, f]] += w[i] * stats[i]
        np.testing.assert_array_equal(outs["xla"], want)

    def test_bit_equal_f64_x64_mode(self, rng):
        import jax
        import jax.numpy as jnp
        from dislib_tpu.ops import pallas_kernels as _pk
        from dislib_tpu.trees.decision_tree import _node_histogram
        if not _pk.hist_available():
            pytest.skip("pallas histogram kernel unavailable")
        with jax.enable_x64(True):
            node, bx, w, stats = self._inputs(rng, 96, 3, 4, 8, 2,
                                              dtype=np.float64)
            a = np.asarray(_node_histogram(
                jnp.asarray(node), jnp.asarray(bx), jnp.asarray(w),
                jnp.asarray(stats), 4, 8, hist="xla"))
            b = np.asarray(_node_histogram(
                jnp.asarray(node), jnp.asarray(bx), jnp.asarray(w),
                jnp.asarray(stats), 4, 8, hist="pallas"))
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(a, b)

    def test_schedule_routed_counted_and_forest_bit_equal(self, rng,
                                                          monkeypatch):
        """DSLIB_OVERLAP resolves the histogram schedule ONCE at the fit
        boundary (`hist:<sched>` counter), and the FITTED forests agree
        bit-for-bit across schedules — same splits, same probabilities."""
        from dislib_tpu.ops import pallas_kernels as _pk
        from dislib_tpu.utils import profiling as prof
        if not _pk.hist_available():
            pytest.skip("pallas histogram kernel unavailable")
        x, y = _class_data(rng, n=120, d=4, k=2)
        proba = {}
        for env, sched in (("db", "xla"), ("pallas", "pallas")):
            monkeypatch.setenv("DSLIB_OVERLAP", env)
            prof.reset_counters()
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")   # pallas warns off-TPU
                rf = RandomForestClassifier(n_estimators=4, random_state=0)
                rf.fit(ds.array(x), ds.array(y[:, None]))
                assert prof.schedule_counters().get(f"hist:{sched}", 0) >= 1
                proba[sched] = np.asarray(
                    rf.predict_proba(ds.array(x)).collect())
        assert (proba["xla"] == proba["pallas"]).all()

    def test_degrades_to_xla_when_hist_probe_fails(self, rng, monkeypatch):
        """A Mosaic rejection of THIS kernel's shapes degrades the fit to
        the XLA scatter — never a crash mid-growth."""
        from dislib_tpu.ops import pallas_kernels as _pk
        from dislib_tpu.utils import profiling as prof
        monkeypatch.setenv("DSLIB_OVERLAP", "pallas")
        monkeypatch.setattr(_pk, "_HIST_AVAILABLE", False)
        x, y = _class_data(rng, n=90, d=3, k=2)
        prof.reset_counters()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            rf = RandomForestClassifier(n_estimators=3, random_state=0)
            rf.fit(ds.array(x), ds.array(y[:, None]))
        sc = prof.schedule_counters()
        assert sc.get("hist:xla", 0) >= 1 and "hist:pallas" not in sc
        assert rf.score(ds.array(x), ds.array(y[:, None])) >= 0.85

    def test_warm_refit_traces_nothing_new(self, rng, monkeypatch):
        """The routed kernel is a jit STATIC resolved at the fit
        boundary: a second same-shape fit under the pallas route compiles
        zero new programs (the zero-new-hot-path-traces acceptance)."""
        from dislib_tpu.ops import pallas_kernels as _pk
        from dislib_tpu.utils import profiling as prof
        if not _pk.hist_available():
            pytest.skip("pallas histogram kernel unavailable")
        monkeypatch.setenv("DSLIB_OVERLAP", "pallas")
        x, y = _class_data(rng, n=120, d=4, k=2)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            RandomForestClassifier(n_estimators=4, random_state=0).fit(
                ds.array(x), ds.array(y[:, None]))      # warm
            prof.reset_counters()
            RandomForestClassifier(n_estimators=4, random_state=0).fit(
                ds.array(x), ds.array(y[:, None]))
        assert prof.trace_count() == 0
