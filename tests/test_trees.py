"""Trees tests (reference: test_rf_classifier.py, test_rf_regressor.py,
test_decision_tree.py — SURVEY.md §5 oracle pattern: accuracy/R² vs sklearn
on the same data)."""

import numpy as np
import pytest

import dislib_tpu as ds
from dislib_tpu.trees import (
    RandomForestClassifier, RandomForestRegressor,
    DecisionTreeClassifier, DecisionTreeRegressor,
)


def _class_data(rng, n=300, d=6, k=3):
    centers = rng.randn(k, d) * 3
    x = np.vstack([centers[i] + rng.randn(n // k, d) * 0.7 for i in range(k)])
    y = np.repeat(np.arange(k), n // k).astype(np.float32)
    p = rng.permutation(len(y))
    return x[p].astype(np.float32), y[p]


def _reg_data(rng, n=300, d=5):
    x = rng.rand(n, d).astype(np.float32) * 4
    y = (np.sin(x[:, 0]) * 3 + x[:, 1] ** 2 - 2 * x[:, 2]).astype(np.float32)
    return x, y


class TestRandomForestClassifier:
    def test_separable_accuracy(self, rng):
        x, y = _class_data(rng)
        rf = RandomForestClassifier(n_estimators=8, random_state=0)
        rf.fit(ds.array(x), ds.array(y[:, None]))
        assert rf.score(ds.array(x), ds.array(y[:, None])) >= 0.95

    def test_vs_sklearn_holdout(self, rng):
        from sklearn.ensemble import RandomForestClassifier as SkRF
        x, y = _class_data(rng, n=400, d=5, k=2)
        xt, yt = x[:300], y[:300]
        xv, yv = x[300:], y[300:]
        rf = RandomForestClassifier(n_estimators=10, random_state=0)
        rf.fit(ds.array(xt), ds.array(yt[:, None]))
        mine = rf.score(ds.array(xv), ds.array(yv[:, None]))
        sk = SkRF(n_estimators=10, random_state=0).fit(xt, yt).score(xv, yv)
        assert mine >= sk - 0.07

    def test_hard_vote(self, rng):
        x, y = _class_data(rng, n=150, d=4, k=2)
        rf = RandomForestClassifier(n_estimators=5, hard_vote=True,
                                    random_state=0)
        rf.fit(ds.array(x), ds.array(y[:, None]))
        assert rf.score(ds.array(x), ds.array(y[:, None])) >= 0.9

    def test_predict_proba(self, rng):
        x, y = _class_data(rng, n=120, d=4, k=3)
        rf = RandomForestClassifier(n_estimators=4, random_state=0)
        rf.fit(ds.array(x), ds.array(y[:, None]))
        proba = rf.predict_proba(ds.array(x)).collect()
        assert proba.shape == (120, 3)
        assert np.allclose(proba.sum(axis=1), 1.0, atol=1e-5)

    def test_original_labels(self, rng):
        x, y = _class_data(rng, n=90, d=3, k=2)
        y2 = np.where(y > 0, 5.0, -2.0).astype(np.float32)
        rf = RandomForestClassifier(n_estimators=3, random_state=0)
        rf.fit(ds.array(x), ds.array(y2[:, None]))
        pred = rf.predict(ds.array(x)).collect().ravel()
        assert set(np.unique(pred)) <= {-2.0, 5.0}

    def test_not_fitted(self, rng):
        with pytest.raises(RuntimeError):
            RandomForestClassifier().predict(ds.array(rng.rand(4, 2)))


class TestRandomForestRegressor:
    def test_r2_train(self, rng):
        x, y = _reg_data(rng)
        rf = RandomForestRegressor(n_estimators=8, random_state=0)
        rf.fit(ds.array(x), ds.array(y[:, None]))
        assert rf.score(ds.array(x), ds.array(y[:, None])) >= 0.8

    def test_vs_sklearn_holdout(self, rng):
        from sklearn.ensemble import RandomForestRegressor as SkRF
        x, y = _reg_data(rng, n=400)
        xt, yt, xv, yv = x[:300], y[:300], x[300:], y[300:]
        rf = RandomForestRegressor(n_estimators=10, random_state=0)
        rf.fit(ds.array(xt), ds.array(yt[:, None]))
        mine = rf.score(ds.array(xv), ds.array(yv[:, None]))
        sk = SkRF(n_estimators=10, random_state=0).fit(xt, yt).score(xv, yv)
        assert mine >= sk - 0.15


class TestDecisionTree:
    def test_classifier_overfits_train(self, rng):
        x, y = _class_data(rng, n=200, d=5, k=3)
        dt = DecisionTreeClassifier(random_state=0)
        dt.fit(ds.array(x), ds.array(y[:, None]))
        assert dt.score(ds.array(x), ds.array(y[:, None])) >= 0.97

    def test_regressor_fits_train(self, rng):
        x, y = _reg_data(rng, n=200)
        dt = DecisionTreeRegressor(random_state=0)
        dt.fit(ds.array(x), ds.array(y[:, None]))
        assert dt.score(ds.array(x), ds.array(y[:, None])) >= 0.9

    def test_max_depth_limits(self, rng):
        x, y = _class_data(rng, n=100, d=3, k=2)
        dt = DecisionTreeClassifier(max_depth=2, random_state=0)
        dt.fit(ds.array(x), ds.array(y[:, None]))
        assert dt._depth == 2


class TestNBinsContract:
    """The discretisation contract (decision_tree.py module docstring):
    quantile-histogram splits at n_bins granularity, with n_bins a
    constructor knob — including a distribution where the default 32 bins
    provably lose the minority structure and n_bins=256 recovers it."""

    def _fine_boundary(self):
        # 1% minority class below x=0.01 on a uniform feature: 32 quantile
        # bins put the first edge at the ~3.1% quantile, so bin 0 mixes
        # the whole minority with twice as many majority rows — majority
        # vote erases the minority. 256 bins resolve it.
        x = np.linspace(0.0, 1.0, 10_000, dtype=np.float32)[:, None]
        y = (x[:, 0] < 0.01).astype(np.float32)[:, None]
        return x, y

    def _minority_recall(self, clf, x, y):
        pred = np.asarray(
            clf.predict(ds.array(x)).collect()).ravel()
        mask = y.ravel() == 1.0
        return float((pred[mask] == 1.0).mean())

    def test_n_bins_contract(self):
        x, y = self._fine_boundary()
        lose = DecisionTreeClassifier(max_depth=6, random_state=0)
        lose.fit(ds.array(x), ds.array(y))
        win = DecisionTreeClassifier(max_depth=6, random_state=0, n_bins=256)
        win.fit(ds.array(x), ds.array(y))
        assert self._minority_recall(lose, x, y) < 0.2   # 32 bins: erased
        assert self._minority_recall(win, x, y) > 0.7    # 256 bins: found

    def test_n_bins_forest_and_validation(self, rng):
        from dislib_tpu.trees import RandomForestClassifier
        x = rng.rand(200, 4).astype(np.float32)
        y = (x[:, 0] > 0.5).astype(np.float32)[:, None]
        rf = RandomForestClassifier(n_estimators=4, random_state=0, n_bins=64)
        rf.fit(ds.array(x), ds.array(y))
        assert rf.score(ds.array(x), ds.array(y)) > 0.9
        with pytest.raises(ValueError, match="n_bins"):
            DecisionTreeClassifier(n_bins=1).fit(ds.array(x), ds.array(y))
        with pytest.raises(ValueError, match="n_bins"):
            DecisionTreeClassifier(n_bins=0).fit(ds.array(x), ds.array(y))

    def test_depth_cap_warns(self, rng):
        x, y = _class_data(rng, n=100, d=3, k=2)
        dt = DecisionTreeClassifier(max_depth=40, random_state=0)
        with pytest.warns(UserWarning, match="depth cap"):
            dt.fit(ds.array(x), ds.array(y[:, None]))
        assert dt._depth <= 12

    def test_pre_n_bins_snapshot_refused_as_version_change(self, rng,
                                                           tmp_path):
        # a checkpoint written before n_bins joined the fingerprint (8
        # elements vs 9) must be refused with the version message, not the
        # data-mismatch one
        from dislib_tpu.utils import FitCheckpoint
        from dislib_tpu.trees import RandomForestClassifier
        x, y = _class_data(rng, n=120, d=4, k=2)
        path = str(tmp_path / "rf.npz")
        rf = RandomForestClassifier(n_estimators=2, random_state=0)
        rf.fit(ds.array(x), ds.array(y[:, None]),
               checkpoint=FitCheckpoint(path, every=1))
        from dislib_tpu.utils import checkpoint as ckm
        snap = dict(np.load(path, allow_pickle=False))
        snap.pop(ckm._CRC_KEY)
        snap["fp"] = snap["fp"][:-1]            # simulate the old 8-knob fp
        # rewrite through save() so the integrity checksum matches the
        # tampered payload — otherwise load() classifies it corrupt and
        # falls back to the rotated previous generation instead of
        # reaching the fp version check
        ck = FitCheckpoint(path, every=1)
        ck.delete()                             # drop rotated generations
        ck.save(snap)
        with pytest.raises(ValueError, match="different library version"):
            RandomForestClassifier(n_estimators=2, random_state=0).fit(
                ds.array(x), ds.array(y[:, None]),
                checkpoint=FitCheckpoint(path, every=1))
