"""Hyperparameter search over a random forest with cross-validation.

Run: `python examples/gridsearch_forest.py`
"""

import numpy as np

import dislib_tpu as ds
from dislib_tpu.model_selection import GridSearchCV
from dislib_tpu.trees import RandomForestClassifier

ds.init()

rng = np.random.RandomState(0)
x_host = rng.rand(600, 10).astype(np.float32)
y_host = (x_host[:, 0] + x_host[:, 3] > 1.0).astype(np.float32)

x = ds.array(x_host, block_size=(100, 10))
y = ds.array(y_host.reshape(-1, 1), block_size=(100, 1))

gs = GridSearchCV(RandomForestClassifier(random_state=0),
                  {"n_estimators": [5, 15], "max_depth": [4, 8]},
                  cv=3, scoring="accuracy")
gs.fit(x, y)
print("best params:", gs.best_params_)
print("mean test scores:", np.round(gs.cv_results_["mean_test_score"], 3))
print("refit score:", gs.best_estimator_.score(x, y))
