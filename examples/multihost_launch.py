"""Multi-host launch — the `runcompss` replacement, end to end.

The reference starts a cluster job with `runcompss` + XML resource files;
here the whole of that stack is `ds.parallel.initialize()` (one call per
host process) and a mesh over the joined devices (SURVEY §3.7,
`dislib_tpu/parallel/distributed.py`).

Run with no arguments and this script *demonstrates* a 4-process job on
one machine: it re-launches itself as 4 gloo-connected worker processes
(2 virtual CPU devices each) on a 2-D (4, 2) PROCESS mesh — one mesh row
per process, so rows-axis collectives are pure cross-process traffic —
then fits a sharded KMeans and verifies every process agrees on the
centers.  On a real cluster you run one copy per host instead:

    # host i of N (same for TPU pods — jax auto-detects and every
    # argument may be omitted):
    DSLIB_COORDINATOR=host0:8476 DSLIB_NUM_PROCS=N DSLIB_PROC_ID=i \
        python your_fit.py
"""

import json
import os
import subprocess
import sys
import tempfile

# python examples/foo.py puts examples/ (not the repo root) on sys.path
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_PROCS = 4


def worker(rank: int, port: str, out_path: str) -> None:
    os.environ["PALLAS_AXON_POOL_IPS"] = ""        # demo runs on CPU
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import dislib_tpu as ds

    ds.parallel.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=N_PROCS, process_id=rank)
    ds.init((N_PROCS, 2))                          # 2-D process mesh

    rng = np.random.RandomState(0)                 # same data every rank
    xh = rng.rand(256, 8).astype(np.float32)
    x = ds.array(xh, block_size=(32, 8))
    km = ds.KMeans(n_clusters=4, init=xh[:4].copy(), max_iter=10,
                   tol=0.0).fit(x)
    centers = np.asarray(km.centers_)
    assert np.isfinite(centers).all()
    # EVERY rank writes its centers; the launcher compares all four — the
    # whole point of the demo is that the sharded fit agrees across hosts
    with open(f"{out_path}.rank{rank}", "w") as f:
        json.dump(centers.tolist(), f)
    print(f"[rank {rank}] fit done; centers[0,0]={centers[0, 0]:.4f}",
          flush=True)


def launch() -> None:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = str(s.getsockname()[1])
    s.close()
    out = os.path.join(tempfile.mkdtemp(), "centers.json")
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), str(r), port, out])
        for r in range(N_PROCS)]
    try:
        rcs = [p.wait(timeout=300) for p in procs]
    except subprocess.TimeoutExpired:
        # a worker stuck in a collective would strand its peers forever
        for p in procs:
            p.kill()
        raise
    assert rcs == [0] * N_PROCS, f"worker exit codes {rcs}"
    import numpy as np
    all_centers = []
    for r in range(N_PROCS):
        with open(f"{out}.rank{r}") as f:
            all_centers.append(np.asarray(json.load(f)))
    for r in range(1, N_PROCS):
        np.testing.assert_allclose(all_centers[r], all_centers[0],
                                   rtol=1e-6, atol=1e-7)
    print(f"4-process job OK — all {N_PROCS} ranks agree on "
          f"{all_centers[0].shape[0]} centers across the 2-D process mesh")


if __name__ == "__main__":
    if len(sys.argv) == 4:
        worker(int(sys.argv[1]), sys.argv[2], sys.argv[3])
    else:
        launch()
