"""End-to-end clustering pipeline: CSV ingest → scale → PCA → KMeans →
save/load roundtrip.

Run anywhere: `python examples/clustering_pipeline.py` (real TPU under the
default env; CPU with JAX_PLATFORMS=cpu).
"""

import os
import tempfile

import numpy as np

import dislib_tpu as ds
from dislib_tpu.cluster import KMeans
from dislib_tpu.decomposition import PCA
from dislib_tpu.preprocessing import StandardScaler

ds.init()

# three gaussian blobs, written to a CSV and loaded back (native C++ parser)
rng = np.random.RandomState(0)
blobs = np.vstack([rng.randn(400, 8) * 0.3 + c
                   for c in (0.0, 3.0, -3.0)]).astype(np.float32)
workdir = tempfile.mkdtemp()
csv = os.path.join(workdir, "blobs.csv")
np.savetxt(csv, blobs, delimiter=",")

x = ds.load_txt_file(csv, block_size=(200, 8))
print("loaded:", x)

xs = StandardScaler().fit_transform(x)
xp = PCA(n_components=4).fit_transform(xs)
km = KMeans(n_clusters=3, random_state=0, max_iter=50).fit(xp)
print(f"fit: n_iter={km.n_iter_} inertia={km.inertia_:.2f}")

model_path = os.path.join(workdir, "model.json")
ds.save_model(km, model_path)
km2 = ds.load_model(model_path)
labels = np.asarray(km2.predict(xp).collect()).ravel()
print("cluster sizes after save/load roundtrip:", np.bincount(labels))
