"""Mid-fit checkpoint / resume across estimator families (SURVEY §6).

Every iterative fit accepts ``checkpoint=FitCheckpoint(path, every=k)``:
KMeans/GMM/ALS/CSVM snapshot iteration state, forests snapshot per grown
LEVEL, tiled DBSCAN/Daura snapshot per propagation-round/extraction chunk.
A killed job re-run with the same checkpoint resumes where it died and
lands on the uninterrupted run's model.

The last leg demos the preemption-safe runtime: a SIGTERM mid-fit makes
the chunked loop snapshot and raise a clean ``Preempted`` (instead of
dying mid-collective), and the resume works even on a different mesh
shape (elastic resume).

Run anywhere: `python examples/fault_tolerant_fits.py` (real TPU under
the default env; CPU with JAX_PLATFORMS=cpu).
"""

import os
import tempfile

import numpy as np

import dislib_tpu as ds
from dislib_tpu.cluster import DBSCAN, KMeans
from dislib_tpu.trees import RandomForestClassifier
from dislib_tpu.utils import FitCheckpoint

ds.init()
workdir = tempfile.mkdtemp()

rng = np.random.RandomState(0)
centers = np.asarray([[0, 0, 0], [6, 6, 6], [0, 6, 0]], np.float32)
xh = np.vstack([c + 0.4 * rng.randn(200, 3) for c in centers]) \
    .astype(np.float32)
yh = np.repeat(np.arange(3), 200).astype(np.float32)
perm = rng.permutation(len(xh))
x, y = ds.array(xh[perm]), ds.array(yh[perm].reshape(-1, 1))

# --- KMeans: simulate preemption by capping max_iter, then resume -------
path = os.path.join(workdir, "km.npz")
init = np.ascontiguousarray(xh[perm][:3])
KMeans(n_clusters=3, init=init, max_iter=4, tol=0.0).fit(
    x, checkpoint=FitCheckpoint(path, every=2))     # "dies" after 4 iters
km = KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(
    x, checkpoint=FitCheckpoint(path, every=2))     # resumes at iter 4
print("kmeans resumed to", km.n_iter_, "iters, inertia", round(km.inertia_, 2))

# --- RandomForest: per-level snapshots; resume is bit-identical ---------
path = os.path.join(workdir, "rf.npz")
rf = RandomForestClassifier(n_estimators=8, max_depth=8, random_state=7)
rf.fit(x, y, checkpoint=FitCheckpoint(path, every=2))
print("forest grown with level snapshots; train acc", rf.score(x, y))

# --- DBSCAN: per-propagation-round snapshots on the tiled tier ----------
path = os.path.join(workdir, "db.npz")
db = DBSCAN(eps=1.5, min_samples=5).fit(
    x, checkpoint=FitCheckpoint(path, every=1))
print("dbscan clusters:", db.n_clusters_)

# A stale snapshot (different data/hyperparameters) always REFUSES:
try:
    DBSCAN(eps=9.9, min_samples=5).fit(
        x, checkpoint=FitCheckpoint(path, every=1))
except ValueError as e:
    print("stale checkpoint refused:", str(e)[:60], "...")

# --- Preemption-safe drain: SIGTERM → snapshot → clean Preempted --------
from dislib_tpu.runtime import Preempted, PreemptionWatcher, \
    clear_preemption  # noqa: E402
from dislib_tpu.utils.faults import SigtermAtNthSave  # noqa: E402

path = os.path.join(workdir, "km_preempt.npz")
with PreemptionWatcher():                    # SIGTERM sets the drain flag
    try:
        # the harness delivers a real SIGTERM right after snapshot #1;
        # the fit notices at the next chunk boundary, snapshots, raises
        KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(
            x, checkpoint=SigtermAtNthSave(path, every=2, after=1))
    except Preempted as p:
        print("preempted cleanly; snapshot at", p.checkpoint_path)
clear_preemption()                           # this process carries on

# elastic resume: the snapshot restores onto a DIFFERENT mesh shape —
# here the library default mesh re-initialised fresh; on a real fleet
# the replacement job may have half the devices
ds.init()
x2 = ds.array(xh[perm])
km2 = KMeans(n_clusters=3, init=init, max_iter=12, tol=0.0).fit(
    x2, checkpoint=FitCheckpoint(path, every=2))
print("elastic resume finished at iter", km2.n_iter_,
      "inertia", round(km2.inertia_, 2))
