"""Sparse collaborative filtering: svmlight-style sparse ratings → ALS →
per-user recommendations.

Run: `python examples/sparse_recommender.py`
"""

import numpy as np
import scipy.sparse as sp

import dislib_tpu as ds
from dislib_tpu.data.sparse import SparseArray
from dislib_tpu.recommendation import ALS

ds.init()

# synthetic low-rank ratings, 85% unobserved — stays sparse end to end
rng = np.random.RandomState(0)
true_u = rng.rand(200, 6).astype(np.float32)
true_v = rng.rand(120, 6).astype(np.float32)
mask = rng.rand(200, 120) < 0.15
ratings = sp.csr_matrix(np.where(mask, true_u @ true_v.T, 0.0)
                        .astype(np.float32))
x = SparseArray.from_scipy(ratings)
print(f"ratings: {x.shape}, nnz={x.nnz}")

als = ALS(n_f=6, lambda_=0.01, max_iter=40, tol=1e-6, random_state=0)
als.fit(x)
print(f"converged={als.converged_} n_iter={als.n_iter_} rmse={als.rmse_:.4f}")

user = 7
scores = als.predict_user(user)
unseen = np.asarray(ratings[user].todense()).ravel() == 0
top = np.argsort(-np.where(unseen, scores, -np.inf))[:5]
print(f"top-5 unseen items for user {user}: {top.tolist()}")

# -- a BRAND-NEW user: fold-in, no refit (round 14) -------------------------
new_user = np.where(rng.rand(120) < 0.2,
                    rng.rand(6).astype(np.float32) @ true_v.T, 0.0) \
    .astype(np.float32)
preds = als.fold_in(new_user)           # one fused dispatch
print(f"fold-in: predicted {preds.shape[1]} item scores for a new user")

# -- and the same scoring served as padded sparse batches -------------------
from dislib_tpu.serving import PredictServer, SparseFoldInPipeline

pipe = SparseFoldInPipeline(als, nse_cap=64)
with PredictServer(pipeline=pipe, buckets=(1, 8, 64)) as srv:
    out = srv.predict(pipe.pack(new_user))
    top_new = np.argsort(-out[0])[:5]
print(f"served top-5 for the folded-in user: {top_new.tolist()}")
