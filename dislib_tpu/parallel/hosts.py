"""Host topology model — which devices share a process (ICI) and which
pairs only reach each other over the data-center network (DCN).

The paper-scale story (arXiv:2112.09017, 2048 cores) is multi-host: a
mesh axis that spans processes pays DCN latency/bandwidth per collective
hop, while the axis inside one host rides ICI.  Every DCN-aware schedule
in the library (the ``dcn`` rechunk tier, the cross-host grow placement,
the sharded-bundle mesh contract) needs the same two facts about a mesh:
*which host owns each device* and *whether the row axis is hierarchical*
— contiguous, equal-sized blocks of whole mesh rows per host, the layout
``parallel.distributed`` documents (each host's local devices are
contiguous in ``jax.devices()`` order).

Real topology comes from ``device.process_index``.  Because this rig's
tier-1 suite is single-process, ``DSLIB_MOCK_HOSTS=N`` overlays a mock
map — the flat ``jax.devices()`` order partitioned into N contiguous
groups — so every protocol decision (schedule routing, message
accounting, shard placement) executes and is asserted in-process,
exactly as it would across real processes.  The mock changes NO
numerics: schedules stay bit-equal; only the collective structure and
the accounting change.
"""

from __future__ import annotations

import os

import numpy as np

__all__ = ["host_of", "host_map", "n_hosts", "mock_hosts", "row_hosts",
           "host_blocks"]


def mock_hosts() -> int | None:
    """The ``DSLIB_MOCK_HOSTS`` overlay: partition the flat device order
    into this many contiguous fake hosts (None = real topology)."""
    raw = os.environ.get("DSLIB_MOCK_HOSTS")
    if not raw:
        return None
    n = int(raw)
    if n < 1:
        raise ValueError(f"DSLIB_MOCK_HOSTS={raw!r}: need a positive count")
    return n


def host_of(device) -> int:
    """The host (process) index owning ``device`` — the mock partition
    when ``DSLIB_MOCK_HOSTS`` is set, else the device's real
    ``process_index``."""
    mock = mock_hosts()
    if mock is None:
        return int(getattr(device, "process_index", 0))
    import jax
    devs = jax.devices()
    try:
        i = devs.index(device)
    except ValueError:
        return int(getattr(device, "process_index", 0))
    return i * mock // len(devs)


def host_map(mesh) -> np.ndarray:
    """Host index per mesh position (same shape as ``mesh.devices``)."""
    return np.vectorize(host_of, otypes=[np.int64])(mesh.devices)


def n_hosts(mesh) -> int:
    """Distinct hosts under ``mesh`` (mock-aware)."""
    return len(set(host_map(mesh).flat))


def row_hosts(mesh):
    """Per-mesh-row host index list when every row lives entirely on ONE
    host, else None.  A row split across hosts means the 'cols' axis
    would pay DCN — the hierarchical schedules refuse that layout."""
    hm = host_map(mesh)
    if hm.ndim != 2 or not (hm == hm[:, :1]).all():
        return None
    return [int(h) for h in hm[:, 0]]


def host_blocks(mesh):
    """``(n_blocks, rows_per_block, block_hosts)`` when the mesh's row
    axis is HIERARCHICAL — contiguous, equal-sized blocks of whole rows,
    one host per block (the ``distributed.initialize`` device order) —
    else None.  ``block_hosts[b]`` is the host owning block ``b``."""
    rh = row_hosts(mesh)
    if rh is None:
        return None
    hosts: list[int] = []
    for h in rh:
        if not hosts or hosts[-1] != h:
            if h in hosts:
                return None             # host's rows are not contiguous
            hosts.append(h)
    n_blocks = len(hosts)
    rows = len(rh)
    if rows % n_blocks:
        return None
    per = rows // n_blocks
    for b, h in enumerate(hosts):
        if any(rh[b * per + k] != h for k in range(per)):
            return None                 # unequal block sizes
    return n_blocks, per, hosts
