"""Device-mesh management for dislib_tpu.

The reference (dislib) describes cluster topology outside the library, in the
COMPSs resource files (``project.xml``/``resources.xml``) and the ``runcompss``
launcher (SURVEY.md §6 "Config / flag system").  In the TPU-native rebuild the
topology is a :class:`jax.sharding.Mesh` with two named axes:

- ``"rows"`` — the data axis.  Row blocks of every ds-array live here; all the
  map-over-row-blocks estimators (KMeans, GMM, scalers, ...) shard along it.
- ``"cols"`` — the model/feature axis, used by 2-D blocked linear algebra
  (matmul / QR trailing updates) the way the reference partitions its block
  grid in two dimensions.

``init()`` builds the default mesh; ``get_mesh()`` returns it (building a
1-D-over-all-devices default lazily).  Multi-host jobs call
:func:`dislib_tpu.parallel.distributed.initialize` first so ``jax.devices()``
spans hosts and the outer mesh dimension rides DCN while the inner rides ICI.
"""

from __future__ import annotations

import math
import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROWS = "rows"
COLS = "cols"
AXIS_NAMES = (ROWS, COLS)

_default_mesh: Mesh | None = None


def init(mesh_shape: tuple[int, int] | None = None, devices=None) -> Mesh:
    """Initialise (or re-initialise) the library-wide default mesh.

    Parameters
    ----------
    mesh_shape : (rows, cols) or None
        Device grid shape.  ``None`` reads the ``DSLIB_MESH`` env var
        (``"4,2"``) and otherwise defaults to ``(n_devices, 1)`` — pure data
        parallelism, the reference's dominant pattern (SURVEY.md §3.6).
    devices : sequence of jax devices, optional
        Defaults to ``jax.devices()``.

    Matmul precision note: the library's own kernels always trace their
    GEMMs at float32-faithful precision (see ``dislib_tpu.ops.base.precise``)
    — no global JAX configuration is touched, so user code keeps whatever
    ``jax_default_matmul_precision`` it set.
    """
    global _default_mesh
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if mesh_shape is None:
        env = os.environ.get("DSLIB_MESH")
        if env:
            mesh_shape = tuple(int(s) for s in env.split(","))  # type: ignore
        else:
            mesh_shape = (n, 1)
    r, c = mesh_shape
    if r * c > n:
        raise ValueError(f"mesh_shape {mesh_shape} needs {r * c} devices, have {n}")
    dev_grid = np.asarray(devices[: r * c]).reshape(r, c)
    # changing the DEVICE SET (not just the grid shape) invalidates every
    # cached trace whose sharding constraints were baked for the old set:
    # jit replays such a trace against arrays on the new set and dies with
    # "incompatible devices" (the round-6 stale-constraint failure mode —
    # fitloop._resize_mesh clears for the same reason).  Same-set re-inits
    # (the overwhelmingly common case: reshaping the grid over all
    # devices) keep their caches — re-layouts already retrace.
    if _default_mesh is not None and \
            set(d.id for d in _default_mesh.devices.reshape(-1)) != \
            set(d.id for d in dev_grid.reshape(-1)):
        jax.clear_caches()
    _default_mesh = Mesh(dev_grid, AXIS_NAMES)
    return _default_mesh


def get_mesh() -> Mesh:
    """Return the default mesh, creating the (n_devices, 1) default lazily."""
    global _default_mesh
    if _default_mesh is None:
        init()
    return _default_mesh


def set_mesh(mesh: Mesh) -> None:
    """Install `mesh` as the library-wide default."""
    global _default_mesh
    _default_mesh = mesh


def mesh_shape(mesh: Mesh | None = None) -> tuple[int, int]:
    mesh = mesh or get_mesh()
    return (mesh.shape[ROWS], mesh.shape[COLS])


def pad_quantum(mesh: Mesh | None = None) -> int:
    """Every ds-array dimension is padded to a multiple of this.

    lcm(rows, cols) so that either logical dimension can be sharded over
    either mesh axis without remainder — required by ``shard_map`` and it
    keeps XLA's SPMD partitioner from introducing halo/pad ops of its own.
    """
    r, c = mesh_shape(mesh)
    return r * c // math.gcd(r, c)


def data_sharding(mesh: Mesh | None = None) -> NamedSharding:
    """The canonical 2-D ds-array sharding: rows over 'rows', cols over 'cols'."""
    mesh = mesh or get_mesh()
    return NamedSharding(mesh, P(ROWS, COLS))


def row_sharding(mesh: Mesh | None = None) -> NamedSharding:
    mesh = mesh or get_mesh()
    return NamedSharding(mesh, P(ROWS, None))


def replicated(mesh: Mesh | None = None) -> NamedSharding:
    mesh = mesh or get_mesh()
    return NamedSharding(mesh, P(None, None))
