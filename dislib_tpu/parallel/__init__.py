"""Mesh, sharding and collective infrastructure (the COMPSs-runtime role)."""

from dislib_tpu.parallel.mesh import (
    ROWS, COLS, init, get_mesh, set_mesh, mesh_shape, pad_quantum,
    data_sharding, row_sharding, replicated,
)

__all__ = [
    "ROWS", "COLS", "init", "get_mesh", "set_mesh", "mesh_shape",
    "pad_quantum", "data_sharding", "row_sharding", "replicated",
]
