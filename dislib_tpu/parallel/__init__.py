"""Mesh, sharding and collective infrastructure (the COMPSs-runtime role)."""

from dislib_tpu.parallel.mesh import (
    ROWS, COLS, init, get_mesh, set_mesh, mesh_shape, pad_quantum,
    data_sharding, row_sharding, replicated,
)
from dislib_tpu.parallel.distributed import (
    initialize, is_initialized, process_info, shutdown,
)
from dislib_tpu.parallel.hosts import (
    host_of, host_map, n_hosts, mock_hosts, row_hosts, host_blocks,
)

__all__ = [
    "ROWS", "COLS", "init", "get_mesh", "set_mesh", "mesh_shape",
    "pad_quantum", "data_sharding", "row_sharding", "replicated",
    "initialize", "is_initialized", "process_info", "shutdown",
    "host_of", "host_map", "n_hosts", "mock_hosts", "row_hosts",
    "host_blocks",
]
