"""Multi-host initialisation — the `runcompss` / COMPSs-resources analog
(SURVEY.md §3.7 "Distributed communication backend" and §6 "Config / flag
system").

The reference describes cluster topology in COMPSs XML resource files and
starts the job through `runcompss`/`enqueue_compss`; the Java runtime then
wires master↔worker sockets.  TPU-native, the whole of that stack is
`jax.distributed.initialize`: one controller process per host joins a GRPC
coordinator, after which `jax.devices()` spans every host and XLA
collectives ride ICI within a slice and DCN across hosts/slices.

Usage (per host)::

    import dislib_tpu as ds
    ds.parallel.initialize(coordinator_address="host0:8476",
                           num_processes=4, process_id=rank)
    ds.init()          # mesh over ALL hosts' devices; 'rows' axis spans DCN

On a single process (or under a TPU runtime that auto-detects, e.g. GKE
with megascale env vars) every argument may be omitted.  Keep reductions
hierarchical by putting the host-spanning dimension on the mesh 'rows'
axis — `init()`'s device order already groups each host's local devices
contiguously, so a (n_hosts·local, 1) mesh reduces ICI-first, DCN-second;
a (n_hosts, local) 2-D PROCESS mesh gives each host exactly one mesh row
(rows collectives are pure-DCN, cols pure-intra-host).

Exercised for real by `tests/test_multiprocess.py`: 2-process × 4-device
jobs on the (n·local, 1) layout, and a 4-process × 2-device job on the
(4, 2) 2-D process mesh (KMeans, collect, all_to_all shuffle, and
kill+resume all crossing the gloo process boundary).
"""

from __future__ import annotations

import os

import jax

_initialized = False


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               local_device_ids=None) -> None:
    """Join (or form) the multi-host job.  Arguments default to the
    ``DSLIB_COORDINATOR`` / ``DSLIB_NUM_PROCS`` / ``DSLIB_PROC_ID`` env vars
    (the launch-script interface, replacing the reference's XML files), then
    to JAX's own auto-detection.  No-op if already initialised or if neither
    arguments nor env vars request a multi-process job."""
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get("DSLIB_COORDINATOR")
    if num_processes is None and "DSLIB_NUM_PROCS" in os.environ:
        num_processes = int(os.environ["DSLIB_NUM_PROCS"])
    if process_id is None and "DSLIB_PROC_ID" in os.environ:
        process_id = int(os.environ["DSLIB_PROC_ID"])
    if coordinator_address is None and num_processes is None:
        return  # single-process job: nothing to join
    # the coordinator races worker bring-up (head pod scheduled last, DNS
    # not yet propagated, ...) — joining is the textbook transient failure,
    # so the gRPC connect retries under the env-tunable Retry policy
    # (DSLIB_RETRY_* overrides); config errors classify fatal and raise
    # immediately (SURVEY §6 failure-detection row)
    from dislib_tpu.runtime import Retry
    Retry.from_env(attempts=5, backoff=1.0, max_backoff=15.0).call(
        jax.distributed.initialize,
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _initialized = True


def is_initialized() -> bool:
    """True once this process has joined a multi-host job."""
    return _initialized


def process_info() -> tuple[int, int]:
    """(process_index, process_count) of this controller."""
    return jax.process_index(), jax.process_count()


def shutdown() -> None:
    """Leave the multi-host job (jax.distributed.shutdown), if joined."""
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False
