from dislib_tpu.neighbors.base import NearestNeighbors

__all__ = ["NearestNeighbors"]
