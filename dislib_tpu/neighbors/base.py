"""Nearest neighbors (reference: `dislib/neighbors` — per (query-block ×
fitted-block) local kNN tasks, pairwise merge keeping the global k-best;
SURVEY.md §3.3 "all-pairs block product then min-merge").

TPU-native: the all-pairs block product is one distance GEMM on the sharded
operands (‖q‖² − 2qᵀx + ‖x‖²) and the k-best merge is a single `lax.top_k`
— the reference's merge tree exists because no worker sees all distances;
on a mesh the row-axis reduction is XLA's problem.  Padded fit rows are
masked to +inf so they can never be neighbors.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array, _repad
from dislib_tpu.ops.base import distances_sq, precise


class NearestNeighbors(BaseEstimator):
    """Exact brute-force kNN index over a ds-array."""

    _private_fitted_attrs = ("_fit_data",)

    def __init__(self, n_neighbors=5):
        self.n_neighbors = n_neighbors

    def fit(self, x: Array, y=None):
        self._fit_data = x
        return self

    def kneighbors(self, x: Array, n_neighbors=None, return_distance=True):
        """Distances/indices of the k nearest fitted rows for each query row.

        Returns (distances (mq, k) Array, indices (mq, k) int32 Array) — the
        ds-array being the library's single container (reference returns
        ds-arrays too)."""
        if not hasattr(self, "_fit_data"):
            raise RuntimeError("NearestNeighbors is not fitted")
        k = self.n_neighbors if n_neighbors is None else n_neighbors
        f = self._fit_data
        if not 1 <= k <= f.shape[0]:
            raise ValueError(f"n_neighbors {k} not in [1, {f.shape[0]}]")
        d, idx = _kneighbors(x._data, f._data, x.shape, f.shape, k)
        d_arr = Array._from_logical_padded(_repad(d, (x.shape[0], k)), (x.shape[0], k))
        # indices stay int32 (exact for any realistic row count; float32 would
        # corrupt indices past 2^24)
        i_arr = Array._from_logical_padded(_repad(idx, (x.shape[0], k)), (x.shape[0], k))
        if return_distance:
            return d_arr, i_arr
        return i_arr


@partial(jax.jit, static_argnames=("q_shape", "f_shape", "k"))
@precise
def _kneighbors(qp, fp, q_shape, f_shape, k):
    mq, d = q_shape
    mf = f_shape[0]
    qv = qp[:, :d]
    fv = fp[:, :d]
    dist = distances_sq(qv, fv)                               # (mq_pad, mf_pad)
    invalid = lax.broadcasted_iota(jnp.int32, (1, fv.shape[0]), 1) >= mf
    dist = jnp.where(invalid, jnp.inf, dist)
    neg, idx = lax.top_k(-dist, k)
    dist_k = jnp.sqrt(jnp.maximum(-neg, 0.0))
    valid_q = lax.broadcasted_iota(jnp.int32, (qv.shape[0], 1), 0) < mq
    dist_k = jnp.where(valid_q, dist_k, 0.0)
    idx = jnp.where(valid_q, idx, 0)
    return dist_k, idx.astype(jnp.int32)
