"""Nearest neighbors (reference: `dislib/neighbors` — per (query-block ×
fitted-block) local kNN tasks, pairwise merge keeping the global k-best;
SURVEY.md §3.3 "all-pairs block product then min-merge").

TPU-native: the all-pairs block product is a distance GEMM on the sharded
operands (‖q‖² − 2qᵀx + ‖x‖²) and the k-best merge is `lax.top_k`.  Small
fit sets take the direct path (one (mq, mf) distance matrix).  Large fit
sets stream in fitted-row chunks with a running top-k merge — top_k over
[current best ∥ chunk distances] per step — so peak memory is
O(mq·(k + chunk)), never O(mq·mf); this is the reference's own pairwise
merge tree, collapsed to a `lax.scan`.  Padded fit rows are masked to +inf
so they can never be neighbors.

Sparse inputs (SURVEY §8 hard part 2) are NATIVE — no densification of the
whole matrix ever happens: a sparse fit set streams as skew-bounded
row-step triplet buffers (`SparseArray.row_steps`: steps capped by both a
row count and an nnz budget) scatter-added into a bounded (chunk, n) dense
window on device, a sparse query contributes its cross-term as one spmm
per step, and ‖·‖² terms come from segment-sums over the nonzeros — the
same economics as the sparse KMeans path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array, _repad
from dislib_tpu.ops import overlap as _ov
from dislib_tpu.ops.base import distances_sq, precise
from dislib_tpu.ops.ring import ring_auto, ring_kneighbors
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.utils import profiling as _prof


class NearestNeighbors(BaseEstimator):
    """Exact brute-force kNN index over a ds-array.

    ``ring`` selects the multi-device schedule for DENSE fit sets: True
    rotates fitted shards around the mesh 'rows' axis via ppermute with a
    running top-k (the fitted set never materialises on one chip —
    `ops/ring.py`); False forces the single-program path (direct or
    fitted-row-chunked GEMM); None (default) auto-picks ring when the mesh
    has >1 row shard and the fit set is large enough for scale-out to
    matter.  Sparse inputs ignore ``ring``: they always stream the fit
    rows as bounded dense windows, query-row-sharded by hand (`shard_map`)
    on a multi-row mesh, single-program otherwise — ring's
    shard-the-FIT-set trade-off does not apply to a streamed fit set."""

    _private_fitted_attrs = ("_fit_data",)

    def __init__(self, n_neighbors=5, ring=None):
        self.n_neighbors = n_neighbors
        self.ring = ring

    def fit(self, x: Array, y=None):
        self._fit_data = x
        return self

    def kneighbors(self, x: Array, n_neighbors=None, return_distance=True):
        """Distances/indices of the k nearest fitted rows for each query row.

        Returns (distances (mq, k) Array, indices (mq, k) int32 Array) — the
        ds-array being the library's single container (reference returns
        ds-arrays too)."""
        if not hasattr(self, "_fit_data"):
            raise RuntimeError("NearestNeighbors is not fitted")
        k = self.n_neighbors if n_neighbors is None else n_neighbors
        f = self._fit_data
        if not 1 <= k <= f.shape[0]:
            raise ValueError(f"n_neighbors {k} not in [1, {f.shape[0]}]")
        from dislib_tpu.data.sparse import SparseArray
        if isinstance(f, SparseArray) or isinstance(x, SparseArray):
            if getattr(self, "ring", None):
                import warnings
                warnings.warn(
                    "NearestNeighbors(ring=True) does not apply to sparse "
                    "inputs; using the streamed sparse schedule (bounded "
                    "dense fit windows; query-row-sharded via shard_map on "
                    "a multi-row mesh, single-program otherwise)",
                    UserWarning, stacklevel=2)
            d, idx = _kneighbors_sparse(x, f, k)
            d_arr = Array._from_logical_padded(
                _repad(d, (x.shape[0], k)), (x.shape[0], k))
            i_arr = Array._from_logical_padded(
                _repad(idx, (x.shape[0], k)), (x.shape[0], k))
            return (d_arr, i_arr) if return_distance else i_arr
        mesh = _mesh.get_mesh()
        # getattr: models loaded from pre-`ring` snapshots lack the attr.
        # The trailing rows>1 guard stays even for forced ring=True: unlike
        # the ε-pass, ring_kneighbors is not inner-tiled, so on a 1-row
        # mesh it would materialise the full (mq, mf) distance block —
        # the chunked single-program path is the memory-safe equivalent.
        if ring_auto(getattr(self, "ring", None), mesh,
                     f.shape[0] >= _RING_MIN) \
                and mesh.shape[_mesh.ROWS] > 1:
            # rotate/compute schedule: resolved at this host boundary so a
            # DSLIB_OVERLAP flip retraces via the kernel static (and the
            # routing is observable through the schedule counters)
            sched = _ov.resolve()
            _prof.count_schedule("ring_kneighbors", sched)
            d, idx = _kneighbors_ring(x._data.astype(jnp.float32),
                                      f._data.astype(jnp.float32),
                                      mesh, k, x.shape[0], f.shape[0],
                                      overlap=sched)
        else:
            d, idx = _kneighbors(x._data, f._data, x.shape, f.shape, k,
                                 chunk=_CHUNK)
        d_arr = Array._from_logical_padded(_repad(d, (x.shape[0], k)), (x.shape[0], k))
        # indices stay int32 (exact for any realistic row count; float32 would
        # corrupt indices past 2^24)
        i_arr = Array._from_logical_padded(_repad(idx, (x.shape[0], k)), (x.shape[0], k))
        if return_distance:
            return d_arr, i_arr
        return i_arr


# fitted-row chunk for the streaming path; fit sets up to 2×_CHUNK rows use
# the direct single-GEMM path (module-level so tests can shrink it)
_CHUNK = 4096

# fit-set size above which a >1-row mesh auto-routes to the ring schedule
_RING_MIN = 1 << 16


@partial(_prof.profiled_jit, static_argnames=("mesh", "k", "mq", "m_fit",
                                              "overlap"),
         name="ring_kneighbors")
def _kneighbors_ring(qp, fp, mesh, k, mq, m_fit, overlap="db"):
    # profiled (round-13): this is a HOST dispatch boundary — one program
    # per ring kneighbors call — so "the ring schedule is still exactly
    # one dispatch" is a counter assertion (tests/test_overlap, bench)
    d2, idx = ring_kneighbors(qp, fp, mesh, k, m_fit, overlap=overlap)
    dist = jnp.sqrt(jnp.maximum(d2, 0.0))
    valid_q = lax.broadcasted_iota(jnp.int32, (dist.shape[0], 1), 0) < mq
    return jnp.where(valid_q, dist, 0.0), jnp.where(valid_q, idx, 0)


@partial(jax.jit, static_argnames=("q_shape", "f_shape", "k", "chunk"))
@precise
def _kneighbors(qp, fp, q_shape, f_shape, k, chunk=None):
    mq, d = q_shape
    mf = f_shape[0]
    qv = qp[:, :d]
    fv = fp[:, :d]
    # chunk is a static cache key; None (internal callers) reads the module
    # default at trace time
    chunk = _CHUNK if chunk is None else chunk
    if fv.shape[0] <= 2 * chunk:
        dist = distances_sq(qv, fv)                           # (mq_pad, mf_pad)
        invalid = lax.broadcasted_iota(jnp.int32, (1, fv.shape[0]), 1) >= mf
        dist = jnp.where(invalid, jnp.inf, dist)
        neg, idx = lax.top_k(-dist, k)
        idx = idx.astype(jnp.int32)
    else:
        neg, idx = _kneighbors_chunked(qv, fv, mf, k, chunk)
    dist_k = jnp.sqrt(jnp.maximum(-neg, 0.0))
    valid_q = lax.broadcasted_iota(jnp.int32, (qv.shape[0], 1), 0) < mq
    dist_k = jnp.where(valid_q, dist_k, 0.0)
    idx = jnp.where(valid_q, idx, 0)
    return dist_k, idx


def _kneighbors_sparse(x, f, k):
    """kNN with a sparse fit set and/or sparse queries — streams the fit
    rows as bounded dense windows, never densifies a whole matrix.

    Dense queries take the SHARDED schedule (`shard_map` over 'rows': each
    device scores its own query shard against the replicated bounded
    windows — manual SPMD, because GSPMD replicates a row-sharded operand
    to partition `top_k`, which the round-4 comm audit pins).  Sparse
    queries on a >1-row mesh shard the same way via the rectangular
    `sharded_rows` buffers (BCOO itself doesn't mesh-shard); on a 1-row
    mesh they take the single-program BCOO kernel."""
    from dislib_tpu.data.sparse import SparseArray
    n = f.shape[1]
    chunk = min(_CHUNK, max(1, f.shape[0]))
    if isinstance(f, SparseArray):
        f_args = (*f.row_steps(chunk), None)
    else:
        # dense fit as full-row steps: the same kernel shape, windows cut
        # by dynamic_slice instead of scatter
        n_chunks = -(-f.shape[0] // chunk)
        row_off = jnp.arange(n_chunks, dtype=jnp.int32) * chunk
        rows_in = jnp.minimum(chunk, f.shape[0] - row_off).astype(jnp.int32)
        f_args = (None, None, None, row_off, rows_in,
                  f._data[: f.shape[0], : f.shape[1]])
    mesh = _mesh.get_mesh()
    if isinstance(x, SparseArray):
        if mesh.shape[_mesh.ROWS] > 1 or x._sharded_rep is not None:
            # row-sharded schedule: each shard rebuilds its local BCOO
            # from the rectangular `sharded_rows` buffers and streams the
            # replicated fit windows — same shard_map reasoning as the
            # dense-query path (GSPMD would gather the top-k operand).
            # Sharded-BACKED queries take it even on a 1-row mesh: the
            # buffers are already device-resident, while the BCOO kernel
            # below would materialise host triplets first.
            qdat, qlr, qcol, qrsq = x.sharded_rows(mesh)
            return _kneighbors_sparse_sharded_sq(
                qdat, qlr, qcol, qrsq, *f_args, n=n, mq=x.shape[0],
                mf=f.shape[0], k=k, chunk=chunk, mesh=mesh)
        q_bcoo = x._bcoo
        q_rowsq = x.row_norms_sq()
        return _kneighbors_sparse_kernel(
            q_bcoo, None, q_rowsq, *f_args, n=n, mq=x.shape[0],
            mf=f.shape[0], k=k, chunk=chunk)
    return _kneighbors_sparse_sharded_q(
        x._data, *f_args[:5], n=n, mq=x.shape[0], mf=f.shape[0], k=k,
        chunk=chunk, mesh=mesh)


@partial(jax.jit, static_argnames=("n", "mq", "mf", "k", "chunk", "mesh"))
@precise
def _kneighbors_sparse_sharded_q(qp, fdat, flr, fcol, row_off, rows_in,
                                 n, mq, mf, k, chunk, mesh):
    """Dense queries over a streamed sparse fit set, row-sharded BY HAND
    (`shard_map`): queries and the running top-k never leave their shard;
    the only replicated tensors are the O(chunk·n) step windows and their
    triplet buffers.  Manual because GSPMD replicates a row-sharded
    operand to partition `lax.top_k` (observed on the 8-device rig: an
    all-gather of the whole candidate buffer), exactly the gather the comm
    audit forbids — the same reason `ops/ring.py` is a shard_map."""
    p = mesh.shape[_mesh.ROWS]
    mq_loc = qp.shape[0] // p

    def local(q_s, fdat_s, flr_s, fcol_s, ro_s, ri_s):
        qv = q_s[:, :n]
        q_rowsq = jnp.sum(qv * qv, axis=1)
        neg, idx = _stream_topk(qv, q_rowsq, None, fdat_s, flr_s, fcol_s,
                                ro_s, ri_s, None, n, mf, k, chunk,
                                varying_axes=(_mesh.ROWS,))
        d = jnp.sqrt(jnp.maximum(-neg, 0.0))
        # zero this shard's padded query rows (global pad-and-mask invariant)
        my = lax.axis_index(_mesh.ROWS)
        valid = (my * mq_loc
                 + lax.broadcasted_iota(jnp.int32, (qv.shape[0], 1), 0)) < mq
        return jnp.where(valid, d, 0.0), jnp.where(valid, idx, 0)

    repl = [P(*([None] * a.ndim)) for a in (fdat, flr, fcol, row_off,
                                            rows_in)]
    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(_mesh.ROWS, None), *repl),
        out_specs=(P(_mesh.ROWS, None), P(_mesh.ROWS, None)),
        check_vma=True,
    )(qp, fdat, flr, fcol, row_off, rows_in)


@partial(jax.jit, static_argnames=("n", "mq", "mf", "k", "chunk", "mesh"))
@precise
def _kneighbors_sparse_sharded_sq(qdat, qlr, qcol, qrsq, fdat, flr, fcol,
                                  row_off, rows_in, f_dense, n, mq, mf, k,
                                  chunk, mesh):
    """SPARSE queries over a streamed fit set, row-sharded by hand: each
    shard rebuilds its local-row BCOO from the rectangular `sharded_rows`
    buffers (padding entries are v=0 → contribute nothing) and runs the
    same streamed top-k; per-shard spmm work is O(nnz/p · chunk), the
    same economics as the sparse KMeans E-step."""
    from jax.experimental import sparse as jsparse
    p = mesh.shape[_mesh.ROWS]
    m_loc = qrsq.shape[1]

    def local(qd_s, qlr_s, qcol_s, qrsq_s, *f_s):
        fs = iter(f_s)
        fdat_l = next(fs) if fdat is not None else None
        flr_l = next(fs) if flr is not None else None
        fcol_l = next(fs) if fcol is not None else None
        ro_l = next(fs)
        ri_l = next(fs)
        fd_l = next(fs) if f_dense is not None else None
        idx = jnp.stack([qlr_s[0], qcol_s[0]], axis=1)
        bcoo = jsparse.BCOO((qd_s[0], idx), shape=(m_loc, n))
        neg, idxk = _stream_topk(None, qrsq_s[0], bcoo, fdat_l, flr_l,
                                 fcol_l, ro_l, ri_l, fd_l, n, mf, k, chunk,
                                 varying_axes=(_mesh.ROWS,))
        d = jnp.sqrt(jnp.maximum(-neg, 0.0))
        my = lax.axis_index(_mesh.ROWS)
        valid = (my * m_loc
                 + lax.broadcasted_iota(jnp.int32, (m_loc, 1), 0)) < mq
        return (jnp.where(valid, d, 0.0)[None],
                jnp.where(valid, idxk, 0)[None])

    f_ops = [a for a in (fdat, flr, fcol, row_off, rows_in, f_dense)
             if a is not None]
    repl = [P(*([None] * a.ndim)) for a in f_ops]
    d, idxk = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(_mesh.ROWS), P(_mesh.ROWS), P(_mesh.ROWS),
                  P(_mesh.ROWS), *repl),
        out_specs=(P(_mesh.ROWS), P(_mesh.ROWS)),
        check_vma=True,
    )(qdat, qlr, qcol, qrsq, *f_ops)
    return d.reshape(p * m_loc, k), idxk.reshape(p * m_loc, k)


def _stream_topk(qv, q_rowsq, q_bcoo, fdat, flr, fcol, row_off, rows_in,
                 f_dense, n, mf, k, chunk, varying_axes=None):
    """Running top-k over fit-row steps (same merge as the dense chunked
    path).  Each step covers rows [row_off, row_off+rows_in) — its dense
    window materialises by scatter-add from the step's triplet buffer
    (sparse fit) or a dynamic slice (dense fit); the cross-term is one
    GEMM (dense queries ``qv``) or one spmm (sparse queries ``q_bcoo``).
    Window rows beyond rows_in belong to OTHER steps and are masked to
    +inf.  Traced inside both the single-program kernel and the per-shard
    body of the sharded dense-query schedule.  Returns the NEGATED best
    squared distances and indices."""
    n_steps = row_off.shape[0]

    def window(i, ro):
        if fdat is not None:
            d_e, lr, cc = fdat[i], flr[i], fcol[i]
            dense = jnp.zeros((chunk, n), q_rowsq.dtype).at[lr, cc].add(d_e)
            rowsq = jax.ops.segment_sum(d_e * d_e, lr, num_segments=chunk)
        else:
            fpad = jnp.pad(f_dense,
                           ((0, n_steps * chunk - f_dense.shape[0]), (0, 0)))
            dense = lax.dynamic_slice(fpad, (ro, 0), (chunk, n))
            rowsq = jnp.sum(dense * dense, axis=1)
        return dense, rowsq

    def body(carry, xs):
        best_neg, best_idx = carry
        i, ro, rc = xs
        dense, f_rowsq = window(i, ro)
        if q_bcoo is not None:
            from dislib_tpu.data.sparse import _spmm
            cross = _spmm(q_bcoo, dense.T)                   # (mq, chunk)
        else:
            cross = qv @ dense.T
        dist = jnp.maximum(q_rowsq[:, None] - 2.0 * cross + f_rowsq[None, :],
                           0.0)
        col = ro + lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
        in_step = lax.broadcasted_iota(jnp.int32, (1, chunk), 1) < rc
        dist = jnp.where(in_step & (col < mf), dist, jnp.inf)
        cand_neg = jnp.concatenate([best_neg, -dist], axis=1)
        cand_idx = jnp.concatenate(
            [best_idx, jnp.broadcast_to(col, (dist.shape[0], chunk))], axis=1)
        neg, sel = lax.top_k(cand_neg, k)
        return (neg, jnp.take_along_axis(cand_idx, sel, axis=1)), None

    mq_rows = q_rowsq.shape[0]
    init = (jnp.full((mq_rows, k), -jnp.inf, q_rowsq.dtype),
            jnp.zeros((mq_rows, k), jnp.int32))
    if varying_axes:
        # inside a shard_map the constant seeds become shard-varying on the
        # first merge; declaring it up front keeps check_vma provable (the
        # same pattern as ops/ring.py)
        init = tuple(lax.pcast(b, varying_axes, to="varying") for b in init)
    (best_neg, best_idx), _ = lax.scan(
        body, init,
        (jnp.arange(n_steps, dtype=jnp.int32), row_off, rows_in))
    return best_neg, best_idx


@partial(jax.jit, static_argnames=("n", "mq", "mf", "k", "chunk"))
@precise
def _kneighbors_sparse_kernel(q_bcoo, q_dense, q_rowsq, fdat, flr, fcol,
                              row_off, rows_in, f_dense, n, mq, mf, k,
                              chunk):
    """Single-program wrapper over `_stream_topk` (sparse queries; also
    the dense-fit-with-sparse-query combination)."""
    neg, idx = _stream_topk(q_dense, q_rowsq, q_bcoo, fdat, flr, fcol,
                            row_off, rows_in, f_dense, n, mf, k, chunk)
    return jnp.sqrt(jnp.maximum(-neg, 0.0)), idx


def _kneighbors_chunked(qv, fv, mf, k, chunk):
    """Running top-k over fitted-row chunks: each scan step merges the
    carried k-best with one chunk's distances.  Ties keep the earlier
    (lower) index — carried candidates precede the chunk in the merge, and
    chunks arrive in index order, so tie-breaking matches the direct path."""
    mq_pad = qv.shape[0]
    n_chunks = -(-fv.shape[0] // chunk)
    fpad = jnp.pad(fv, ((0, n_chunks * chunk - fv.shape[0]), (0, 0)))
    f_chunks = fpad.reshape(n_chunks, chunk, fv.shape[1])
    offsets = jnp.arange(n_chunks, dtype=jnp.int32) * chunk

    def body(carry, xs):
        best_neg, best_idx = carry
        f_chunk, off = xs
        dist = distances_sq(qv, f_chunk)                      # (mq_pad, chunk)
        col = off + lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
        dist = jnp.where(col >= mf, jnp.inf, dist)
        cand_neg = jnp.concatenate([best_neg, -dist], axis=1)
        cand_idx = jnp.concatenate(
            [best_idx, jnp.broadcast_to(col, (mq_pad, chunk))], axis=1)
        neg, sel = lax.top_k(cand_neg, k)
        return (neg, jnp.take_along_axis(cand_idx, sel, axis=1)), None

    init = (jnp.full((mq_pad, k), -jnp.inf, qv.dtype),
            jnp.zeros((mq_pad, k), jnp.int32))
    (best_neg, best_idx), _ = lax.scan(body, init, (f_chunks, offsets))
    return best_neg, best_idx
