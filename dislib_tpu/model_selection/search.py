"""Hyperparameter search (reference: `dislib/model_selection/_search.py` —
sklearn-mirroring GridSearchCV / RandomizedSearchCV that submit ALL candidate
fits before waiting on any, so search-level parallelism multiplies
estimator-internal parallelism; SURVEY.md §3.4, §4.5).

TPU-native concurrency contract: every candidate's fit is dispatched
through the estimator's `_fit_async` protocol (device handles, no host
reads) BEFORE any score is read, and folds are pipelined two-deep — fold
f's host reads happen only after fold f+1's programs are dispatched — so
JAX async dispatch pipelines the trials' device programs back-to-back
across the whole search while memory stays bounded at two folds.
Backend caveat: the pipelining above is the TPU behavior; on the cpu
backend the auto policy (`_PIPELINE_FOLDS`, below) instead BLOCKS each
trial's dispatched state before the next dispatch — see the policy
comment for the XLA:CPU rendezvous-starvation rationale.  Estimators without an async path
fall back to synchronous fit inside the dispatch loop (their device work
still overlaps; only their own convergence-scalar reads serialise).
Scoring accepts the estimator's `score`, a callable, or a scorer string
('accuracy', 'r2', 'neg_mean_squared_error') mirroring the reference's
sklearn scorer checks.
"""

from __future__ import annotations

from itertools import product

import numpy as np

from dislib_tpu.base import BaseEstimator, clone
from dislib_tpu.model_selection.split import KFold


#: Concurrency policy, None = auto by backend.  On TPU, dispatched programs
#: execute strictly in order per core, so the search keeps everything in
#: flight (fold pipelining ON, no throttle).  XLA:CPU instead runs multiple
#: multi-device programs concurrently on one shared thread pool; enough
#: in-flight collective programs starve an all-reduce rendezvous into its
#: 40 s termination timeout and ABORT the process (reproduced with the
#: forest search fanning out ~50 collective programs on the 8-virtual-device
#: rig; `jax_cpu_enable_async_dispatch=False` does not prevent it on
#: jax 0.9).  The cpu auto policy therefore blocks each trial's dispatched
#: state before dispatching the next — the rig is for correctness, and its
#: "devices" share one machine, so nothing real is lost.  True/False force
#: pipelining; the throttle is the negation of the same switch.
_PIPELINE_FOLDS = None


def _pipeline_folds():
    if _PIPELINE_FOLDS is not None:
        return _PIPELINE_FOLDS
    import jax
    return jax.default_backend() != "cpu"


def _block_tree(state):
    """Block on every blockable leaf of an async-state handle (cpu throttle)."""
    import jax
    for leaf in jax.tree_util.tree_leaves(state):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()


def _score(est, xv, yv):
    if hasattr(est, "score"):
        return est.score(xv, yv) if yv is not None else est.score(xv)
    raise TypeError(f"{type(est).__name__} has no score(); pass scoring=")


def _pred_np(est, xv):
    return np.asarray(est.predict(xv).collect()).ravel()


def _truth_np(yv):
    return np.asarray(yv.collect()).ravel()


def _accuracy(est, xv, yv):
    return float(np.mean(_pred_np(est, xv) == _truth_np(yv)))


def _r2(est, xv, yv):
    y = _truth_np(yv)
    resid = ((y - _pred_np(est, xv)) ** 2).sum()
    total = ((y - y.mean()) ** 2).sum()
    return float(1.0 - resid / max(total, 1e-12))


def _neg_mse(est, xv, yv):
    y = _truth_np(yv)
    return float(-np.mean((y - _pred_np(est, xv)) ** 2))


_SCORERS = {"accuracy": _accuracy, "r2": _r2,
            "neg_mean_squared_error": _neg_mse}


def _resolve_scorer(scoring):
    if scoring is None:
        return None
    if callable(scoring):
        return scoring
    if isinstance(scoring, str):
        if scoring not in _SCORERS:
            raise ValueError(f"unknown scorer {scoring!r}; known: "
                             f"{sorted(_SCORERS)} (or pass a callable)")
        return _SCORERS[scoring]
    raise TypeError(f"scoring must be None, str or callable, got "
                    f"{type(scoring).__name__}")


class GridSearchCV(BaseEstimator):
    """Exhaustive search over a parameter grid with K-fold CV.

    Attributes: cv_results_, best_params_, best_score_, best_index_,
    best_estimator_ (when refit=True).
    """

    def __init__(self, estimator, param_grid, cv=5, scoring=None, refit=True):
        self.estimator = estimator
        self.param_grid = param_grid
        self.cv = cv
        self.scoring = scoring
        self.refit = refit

    def _candidates(self):
        grid = self.param_grid
        if isinstance(grid, dict):
            grid = [grid]
        out = []
        for g in grid:
            keys = sorted(g)
            for combo in product(*(g[k] for k in keys)):
                out.append(dict(zip(keys, combo)))
        return out

    def fit(self, x, y=None):
        candidates = self._candidates()
        cv = self.cv if isinstance(self.cv, KFold) else KFold(n_splits=self.cv)
        n_folds = cv.get_n_splits()
        scorer = _resolve_scorer(self.scoring)

        # fold-pipelined loop: at most TWO folds' train/validation copies
        # are device-resident at a time, bounding memory regardless of cv
        # or candidate count, while fold f's host reads happen only AFTER
        # fold f+1's fits and scores are dispatched — the reference's
        # submit-all-before-wait contract holds across folds as well as
        # across candidates (SURVEY §4.5 "no artificial serialization").
        all_scores = np.zeros((len(candidates), n_folds))

        throttle = not _pipeline_folds()   # cpu rig: bound in-flight programs

        def _dispatch_fold(fold):
            xt, yt, xv, yv = fold
            pend = []
            for ci, params in enumerate(candidates):
                est = clone(self.estimator).set_params(**params)
                state = est._fit_async(xt, yt) if yt is not None \
                    else est._fit_async(xt)
                if throttle:
                    _block_tree(state)
                pend.append((ci, est, state))
            vals = []
            for ci, est, state in pend:
                if scorer is None:
                    v = est._score_async(state, xv, yv)
                    if throttle and hasattr(v, "block_until_ready"):
                        v.block_until_ready()
                    vals.append((ci, v))
                else:
                    est._fit_finalize(state)
                    vals.append((ci, scorer(est, xv, yv)))
            return vals

        pipelined = _pipeline_folds()
        prev = None                       # (fold_index, pending device scores)
        for fi, fold in enumerate(cv.split(x, y)):
            vals = _dispatch_fold(fold)
            if prev is not None:
                pfi, pvals = prev
                for ci, v in pvals:       # host sync for fold f-1 only now
                    all_scores[ci, pfi] = float(v)
            if pipelined:
                prev = (fi, vals)
            else:                         # cpu backend: read before fold f+1
                for ci, v in vals:
                    all_scores[ci, fi] = float(v)
        if prev is not None:
            pfi, pvals = prev
            for ci, v in pvals:
                all_scores[ci, pfi] = float(v)

        mean = all_scores.mean(axis=1)
        std = all_scores.std(axis=1)
        rank = np.argsort(-mean).argsort() + 1
        self.cv_results_ = {
            "params": candidates,
            "mean_test_score": mean,
            "std_test_score": std,
            "rank_test_score": rank.astype(int),
            **{f"split{j}_test_score": all_scores[:, j] for j in range(n_folds)},
        }
        self.best_index_ = int(np.argmax(mean))
        self.best_params_ = candidates[self.best_index_]
        self.best_score_ = float(mean[self.best_index_])
        if self.refit:
            self.best_estimator_ = clone(self.estimator).set_params(**self.best_params_)
            self.best_estimator_.fit(x, y) if y is not None else self.best_estimator_.fit(x)
        return self

    def predict(self, x):
        self._check_refit()
        return self.best_estimator_.predict(x)

    def score(self, x, y=None):
        self._check_refit()
        return _score(self.best_estimator_, x, y)

    def _check_refit(self):
        if not hasattr(self, "best_estimator_"):
            raise RuntimeError("search not fitted with refit=True")


class RandomizedSearchCV(GridSearchCV):
    """Randomized search: samples ``n_iter`` candidates from distributions
    (lists are sampled uniformly; scipy frozen distributions via .rvs)."""

    def __init__(self, estimator, param_distributions, n_iter=10, cv=5,
                 scoring=None, refit=True, random_state=None):
        super().__init__(estimator, param_grid=None, cv=cv, scoring=scoring,
                         refit=refit)
        self.param_distributions = param_distributions
        self.n_iter = n_iter
        self.random_state = random_state

    def _candidates(self):
        rng = np.random.RandomState(self.random_state)
        dists = self.param_distributions
        if isinstance(dists, dict):
            dists = [dists]
        out = []
        for _ in range(self.n_iter):
            d = dists[rng.randint(len(dists))]
            params = {}
            for k, v in d.items():
                if hasattr(v, "rvs"):
                    params[k] = v.rvs(random_state=rng)
                else:
                    params[k] = v[rng.randint(len(v))]
            out.append(params)
        return out
