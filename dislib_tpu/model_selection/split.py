"""K-fold splitting (reference: `dislib/model_selection/_split.py` — splits
by row blocks with a shuffle option, yielding (train, validation) ds-array
pairs without copying blocks where possible; SURVEY.md §3.4).

TPU-native: folds are row index ranges; slicing a sharded global array is an
XLA gather — no host round-trip.
"""

from __future__ import annotations

import numpy as np

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array


class KFold(BaseEstimator):
    """K-fold cross-validator over ds-array rows."""

    def __init__(self, n_splits=5, shuffle=False, random_state=None):
        self.n_splits = n_splits
        self.shuffle = shuffle
        self.random_state = random_state

    def get_n_splits(self):
        return self.n_splits

    def split(self, x: Array, y: Array | None = None):
        """Yield (train_x, train_y, test_x, test_y) tuples (y entries None if
        y is None)."""
        n = x.shape[0]
        if self.n_splits < 2 or self.n_splits > n:
            raise ValueError(f"n_splits must be in [2, {n}]")
        idx = np.arange(n)
        if self.shuffle:
            np.random.RandomState(self.random_state).shuffle(idx)
        fold_sizes = np.full(self.n_splits, n // self.n_splits, int)
        fold_sizes[: n % self.n_splits] += 1
        start = 0
        for size in fold_sizes:
            test = idx[start:start + size]
            train = np.concatenate([idx[:start], idx[start + size:]])
            start += size
            xt, xv = x[train, :], x[test, :]
            if y is None:
                yield xt, None, xv, None
            else:
                yield xt, y[train, :], xv, y[test, :]
