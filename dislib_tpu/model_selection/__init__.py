from dislib_tpu.model_selection.split import KFold
from dislib_tpu.model_selection.search import GridSearchCV, RandomizedSearchCV

__all__ = ["KFold", "GridSearchCV", "RandomizedSearchCV"]
