"""On-device collective rechunk/redistribute (ROADMAP item 4).

Reference regime: "Memory-efficient array redistribution through portable
collective communication" (arXiv:2112.01075) — express a resharding as a
short sequence of collectives whose peak memory is bounded by the output
plus one in-flight panel, never a full gathered copy.  dislib_tpu needs
exactly that move at three seams:

1. **Quantum re-padding** on one mesh: a ds-array built under an older
   mesh carries a pad quantum the current grid doesn't divide.  The fix
   is a traced crop/place/re-mask (:func:`requantize_body`) that rides
   the dispatch-fusion graph as a ``"rechunk"`` instruction — a
   mid-pipeline reshard costs ZERO extra dispatches in a fused chain.
2. **Mesh-layout change over the same devices** (elastic reshape,
   1-D ↔ 2-D): the explicit *panel-exchange* schedule
   (:func:`panel_rechunk`) — a ``shard_map`` over the SOURCE mesh that
   walks the array in k row panels, broadcasting each panel with the
   masked-``psum`` idiom of ``ops/summa.py`` (one collective per panel
   per mesh axis) while every device gathers its TARGET-layout block
   from the passing panel.  The per-device output blocks are then
   re-wrapped zero-copy (``jax.make_array_from_single_device_arrays``)
   as a global array of the target mesh.  ONE jitted program; in-flight
   panel bytes ≈ ``|array| / panels``, so peak live ≈ (1 + 1/k)·|array|
   beyond the source — never a gathered copy, never the host.
3. **Device-set change** (elastic shrink/grow): the runtime's own
   device-to-device copy (:func:`deviceput_rechunk`) — still no host
   materialization; the collective schedule is XLA's (the arXiv paper
   describes exactly that implementation).

Schedule selection (``DSLIB_RECHUNK_SCHEDULE`` overrides ``"auto"``):
``"xla"`` = the fused/jit requantize path (same layout, or leave the
cross-layout collectives to the SPMD partitioner), ``"panels"`` = the
explicit exchange, ``"deviceput"`` = the runtime copy.  ``"auto"`` picks
the fused path for same-layout operands, panels for a layout change over
the same device set, deviceput otherwise.  ``DSLIB_RECHUNK_PANELS``
(default 4) sets k, the per-source-rank panel count.

The pad-and-mask invariant is re-asserted by EVERY schedule: the region
outside the logical shape is rebuilt from a zero canvas (or masked to
zero), so a poisoned pad tail cannot survive a reshard — the same
``grow_canvas`` discipline the round-10 precision PR pinned for the
blocked factorizations.
"""

from __future__ import annotations

import math
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dislib_tpu.ops import overlap as _ov
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.utils import profiling as _prof
from dislib_tpu.utils.profiling import profiled_jit as _pjit

__all__ = [
    "requantize_body", "repad_axis", "panel_rechunk", "deviceput_rechunk",
    "reshard", "panel_memory_analysis", "panel_comm_probe",
]

SCHEDULES = ("auto", "xla", "panels", "deviceput")


def _padded_dim(n: int, quantum: int) -> int:
    return max(quantum, int(math.ceil(n / quantum)) * quantum)


def _out_pshape(logical_shape, mesh) -> tuple[int, int]:
    q = _mesh.pad_quantum(mesh)
    return tuple(_padded_dim(int(s), q) for s in logical_shape)


# ---------------------------------------------------------------------------
# the traced re-quantize body (shared by the fused "rechunk" instruction
# and the eager kernel) — same-mesh pad-quantum moves
# ---------------------------------------------------------------------------

def requantize_body(data, logical_shape, out_pshape, mesh="default"):
    """Re-pad ``data`` (any padded canvas holding ``logical_shape`` at its
    origin) onto a zero canvas of ``out_pshape``, re-zero everything
    outside the logical region, and constrain to the canonical sharding.

    Traced: this is the ``"rechunk"`` fusion-instruction body, so a
    mid-chain reshard fuses into the chain's ONE dispatch.  The output
    pad region is zero BY CONSTRUCTION (fresh canvas + mask), so the
    pad-and-mask invariant holds even for a poisoned input tail.

    ``mesh``: a Mesh to constrain the result to, the string "default"
    for the library default mesh, or None for no constraint (the
    deviceput path, whose input devices may not be the default mesh's)."""
    m, n = (int(s) for s in logical_shape)
    r = min(data.shape[0], out_pshape[0])
    c = min(data.shape[1], out_pshape[1])
    cropped = data[:r, :c]
    if tuple(cropped.shape) != tuple(out_pshape):
        canvas = jnp.zeros(out_pshape, data.dtype)
        out = lax.dynamic_update_slice(canvas, cropped, (0, 0))
    else:
        out = cropped
    ri = lax.broadcasted_iota(jnp.int32, out.shape, 0)
    ci = lax.broadcasted_iota(jnp.int32, out.shape, 1)
    out = jnp.where((ri < m) & (ci < n), out, jnp.zeros((), out.dtype))
    if mesh is None:
        return out
    sharding = _mesh.data_sharding(None if mesh == "default" else mesh)
    return lax.with_sharding_constraint(out, sharding)


@partial(_pjit, static_argnames=("logical_shape", "out_pshape", "mesh"),
         name="rechunk_requantize")
def _requantize_op(data, logical_shape, out_pshape, mesh):
    return requantize_body(data, logical_shape, out_pshape, mesh)


@partial(_pjit, static_argnames=("logical", "target", "axis"),
         name="repad_axis")
def repad_axis(a, logical, target, axis=0):
    """On-device :func:`dislib_tpu.runtime.repad_rows`: crop to the first
    ``logical`` slices along ``axis`` and zero-fill out to ``target`` —
    one jitted kernel, no host round trip.  N-dimensional (elastic state
    arrays are 1/2/3-D)."""
    idx = [slice(None)] * a.ndim
    idx[axis] = slice(0, logical)
    cropped = a[tuple(idx)]
    if target == logical:
        return cropped
    shape = list(cropped.shape)
    shape[axis] = target
    out = jnp.zeros(tuple(shape), a.dtype)
    return lax.dynamic_update_slice(out, cropped, (0,) * a.ndim)


# ---------------------------------------------------------------------------
# the explicit panel-exchange schedule (same device set, new mesh layout)
# ---------------------------------------------------------------------------

def _panels_per_rank(m_loc: int, requested: int) -> int:
    """Largest divisor of the per-rank row count ≤ the requested panel
    count (panels must tile a source rank's rows exactly)."""
    for j in range(max(1, min(m_loc, requested)), 0, -1):
        if m_loc % j == 0:
            return j
    return 1


def _requested_panels(panels) -> int:
    if panels is not None:
        return max(1, int(panels))
    return max(1, int(os.environ.get("DSLIB_RECHUNK_PANELS", "4")))


def _target_coord_tables(src_mesh: Mesh, dst_mesh: Mesh):
    """Per-source-linear-index target (row, col) coordinates.  A source
    device absent from the target grid gets (0, 0) — it computes a
    duplicate of the (0, 0) block that the rewrap simply drops."""
    src_flat = list(src_mesh.devices.flat)
    dst_pos = {}
    rp, cp = dst_mesh.devices.shape
    for r in range(rp):
        for c in range(cp):
            dst_pos[dst_mesh.devices[r, c]] = (r, c)
    tr = np.zeros((len(src_flat),), np.int32)
    tc = np.zeros((len(src_flat),), np.int32)
    for i, d in enumerate(src_flat):
        tr[i], tc[i] = dst_pos.get(d, (0, 0))
    return tr, tc


@partial(_pjit, static_argnames=("logical_shape", "out_pshape", "src_mesh",
                                 "dst_shape", "tr_key", "tc_key", "steps",
                                 "overlap", "comm_only"),
         name="rechunk_panels")
def _panel_exchange(data, logical_shape, out_pshape, src_mesh, dst_shape,
                    tr_key, tc_key, steps, overlap="db", comm_only=False):
    """ONE jitted program: shard_map over the SOURCE mesh; each device
    assembles its TARGET-layout block from ``steps`` masked-psum panel
    broadcasts (the ``ops/summa.py`` collective idiom, ``check_vma`` on).

    The exchange/assemble loop runs through ``ops/overlap.panel_pipeline``
    (round-13): under the default double-buffered schedule panel t+1's
    rows-axis broadcast is issued before panel t's cols-broadcast/gather
    assembly consumes it — one extra in-flight panel of live memory
    (verified by :func:`panel_memory_analysis`), bit-equal to the
    sequential schedule (``overlap="seq"``).  ``comm_only=True`` is the
    bench tier's broadcast-only variant: the identical collectives with
    the gather/assemble compute replaced by a (1, 1) touch per panel.

    ``tr_key``/``tc_key`` are the target-coordinate tables as hashable
    tuples (they ride the jit cache key: a different device mapping is a
    different program)."""
    m, n = logical_shape
    rows_s, cols_s = src_mesh.shape[_mesh.ROWS], src_mesh.shape[_mesh.COLS]
    rows_d, cols_d = dst_shape
    m_loc1, n_loc1 = data.shape[0] // rows_s, data.shape[1] // cols_s
    m_loc2, n_loc2 = out_pshape[0] // rows_d, out_pshape[1] // cols_d
    j = steps // rows_s                     # panels per source row-rank
    h = m_loc1 // j                         # panel height (global rows)
    tr_tab = jnp.asarray(np.asarray(tr_key, np.int32))
    tc_tab = jnp.asarray(np.asarray(tc_key, np.int32))

    def local(x_loc):
        my_r = lax.axis_index(_mesh.ROWS)
        my_c = lax.axis_index(_mesh.COLS)
        my_lin = my_r * cols_s + my_c
        row0 = tr_tab[my_lin] * m_loc2      # my target block origin
        col0 = tc_tab[my_lin] * n_loc2
        ri = row0 + lax.iota(jnp.int32, m_loc2)   # global coords of my
        ci = col0 + lax.iota(jnp.int32, n_loc2)   # target block entries

        def fetch(t, prev):
            del prev                        # panels slice by step
            owner_r = t // j
            pan = lax.dynamic_slice(x_loc, ((t % j) * h, 0), (h, n_loc1))
            pan = jnp.where(my_r == owner_r, pan, jnp.zeros((), pan.dtype))
            return lax.psum(pan, _mesh.ROWS)

        def _col_blocks(pan):
            """The per-col-rank broadcasts of one row panel (static loop:
            one masked psum per source col-rank)."""
            for s in range(cols_s):
                if cols_s > 1:
                    blk = jnp.where(my_c == s, pan,
                                    jnp.zeros((), pan.dtype))
                    blk = lax.psum(blk, _mesh.COLS)
                else:
                    blk = pan
                yield s, blk

        if comm_only:
            def consume(t, acc, pan):
                for _, blk in _col_blocks(pan):
                    acc = acc + blk[:1, :1]
                return acc

            acc_shape = (1, 1)
        else:
            def consume(t, acc, pan):
                owner_r = t // j
                gr0 = owner_r * m_loc1 + (t % j) * h  # panel's global rows
                r_in = (ri >= gr0) & (ri < gr0 + h)
                r_idx = jnp.clip(ri - gr0, 0, h - 1)
                for s, blk in _col_blocks(pan):
                    gc0 = s * n_loc1
                    c_in = (ci >= gc0) & (ci < gc0 + n_loc1)
                    c_idx = jnp.clip(ci - gc0, 0, n_loc1 - 1)
                    gathered = blk[r_idx][:, c_idx]
                    acc = jnp.where(r_in[:, None] & c_in[None, :],
                                    gathered, acc)
                return acc

            acc_shape = (m_loc2, n_loc2)

        acc0 = lax.pcast(jnp.zeros(acc_shape, x_loc.dtype),
                         (_mesh.ROWS, _mesh.COLS), to="varying")
        acc = _ov.panel_pipeline(steps, fetch(0, None), fetch, consume,
                                 acc0, _ov.overlapped(overlap))
        if comm_only:
            return acc
        # re-assert the pad-and-mask invariant on the NEW canvas: entries
        # outside the logical region are zero no matter what the source
        # pad tail carried
        keep = (ri < m)[:, None] & (ci < n)[None, :]
        return jnp.where(keep, acc, jnp.zeros((), acc.dtype))

    return jax.shard_map(
        local, mesh=src_mesh,
        in_specs=P(_mesh.ROWS, _mesh.COLS),
        out_specs=P(_mesh.ROWS, _mesh.COLS),
        check_vma=True,
    )(data)


def _panel_args(data, logical_shape, dst_mesh, panels, overlap=None):
    """Static argument pack for :func:`_panel_exchange` (shared by the
    run path and the AOT memory-analysis probe).  ``overlap`` resolves
    through the ``DSLIB_OVERLAP`` router here, at the host boundary, so
    an env flip retraces (the precision-policy static contract)."""
    sharding = data.sharding
    src_mesh = sharding.mesh
    out_pshape = _out_pshape(logical_shape, dst_mesh)
    rows_s = src_mesh.shape[_mesh.ROWS]
    m_loc1 = data.shape[0] // rows_s
    j = _panels_per_rank(m_loc1, _requested_panels(panels))
    tr, tc = _target_coord_tables(src_mesh, dst_mesh)
    return dict(logical_shape=tuple(int(s) for s in logical_shape),
                out_pshape=out_pshape, src_mesh=src_mesh,
                dst_shape=(dst_mesh.shape[_mesh.ROWS],
                           dst_mesh.shape[_mesh.COLS]),
                tr_key=tuple(int(v) for v in tr),
                tc_key=tuple(int(v) for v in tc),
                steps=rows_s * j,
                overlap=_ov.resolve(overlap))


def panel_supported(data, dst_mesh) -> bool:
    """True when the explicit panel exchange can run: the source backing
    is a fully-addressable NamedSharding over our named mesh whose grid
    divides the padded shape, and every target device already holds a
    source shard (same-device-set relayout — the elastic reshape case)."""
    sharding = getattr(data, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return False
    src_mesh = sharding.mesh
    if not isinstance(src_mesh, Mesh) or \
            tuple(src_mesh.axis_names) != _mesh.AXIS_NAMES:
        return False
    if not getattr(data, "is_fully_addressable", False):
        return False
    rows_s = src_mesh.shape[_mesh.ROWS]
    cols_s = src_mesh.shape[_mesh.COLS]
    if data.shape[0] % rows_s or data.shape[1] % cols_s:
        return False
    src_devs = set(src_mesh.devices.flat)
    return set(dst_mesh.devices.flat) <= src_devs


def panel_rechunk(data, logical_shape, dst_mesh, panels=None, overlap=None):
    """The explicit collective reshard: ONE jitted panel-exchange program
    over the source mesh, then a ZERO-COPY rewrap of the per-device
    target blocks as a global array of ``dst_mesh`` — no host, no
    gathered copy, peak in-flight panel bytes ≈ |array| / panels (one
    extra panel under the default double-buffered ``overlap`` schedule —
    see :func:`panel_memory_analysis`)."""
    kw = _panel_args(data, logical_shape, dst_mesh, panels, overlap)
    _prof.count_schedule("rechunk_panels", kw["overlap"])
    out_perm = _panel_exchange(data, **kw)
    out_pshape = kw["out_pshape"]
    by_dev = {s.device: s.data for s in out_perm.addressable_shards}
    bufs = [by_dev[d] for d in dst_mesh.devices.flat]
    return jax.make_array_from_single_device_arrays(
        out_pshape, NamedSharding(dst_mesh, P(*_mesh.AXIS_NAMES)), bufs)


def panel_comm_probe(data, logical_shape, dst_mesh, panels=None,
                     overlap="seq"):
    """Broadcast-only variant of the SAME panel-exchange program — the
    identical masked-psum collectives with the gather/assemble compute
    replaced by a (1, 1) touch per panel, so the collectives survive
    DCE.  The bench overlap tier's t_comm_alone denominator."""
    kw = _panel_args(data, logical_shape, dst_mesh, panels, overlap)
    return _panel_exchange(data, comm_only=True, **kw)


def panel_memory_analysis(data, logical_shape, dst_mesh, panels=None,
                          overlap=None):
    """XLA's own memory accounting of the compiled panel-exchange program
    — the bench tier's peak-live-buffer proxy.  Returns a dict with
    ``in_bytes``/``out_bytes``/``temp_bytes`` and ``peak_live_ratio`` =
    (out + temp) / in: a schedule that gathered a full copy would sit at
    ≥ 2.0; the sequential panel schedule stays ≈ 1 + 1/panels and the
    double-buffered one ≈ 1 + 2/panels (the pipelined carry holds ONE
    extra in-flight panel, never a copy of the operand — the bench
    overlap tier's documented bound).  ``temp_bytes`` is None when the
    backend exposes no memory analysis (the analytic panel bound is
    reported alongside either way)."""
    kw = _panel_args(data, logical_shape, dst_mesh, panels, overlap)
    in_bytes = data.size * data.dtype.itemsize
    out_bytes = int(np.prod(kw["out_pshape"])) * data.dtype.itemsize
    n_dev = int(np.prod(kw["src_mesh"].devices.shape))
    # analytic in-flight bound: every device holds one (h, n_loc1) panel
    # (+ its cols-broadcast twin, + the pipelined next panel when
    # double-buffered) during a step
    cols_s = kw["src_mesh"].shape[_mesh.COLS]
    panel_bytes = in_bytes // kw["steps"]
    analytic_temp = panel_bytes * ((2 if cols_s > 1 else 1)
                                   + (1 if _ov.overlapped(kw["overlap"])
                                      else 0))
    res = {"in_bytes": in_bytes, "out_bytes": out_bytes,
           "panels": kw["steps"], "analytic_temp_bytes": analytic_temp,
           "analytic_ratio": round((out_bytes + analytic_temp) / in_bytes, 3),
           "temp_bytes": None, "peak_live_ratio": None, "n_devices": n_dev,
           "overlap": kw["overlap"]}
    try:
        compiled = _panel_exchange.lower(data, **kw).compile()
        ma = compiled.memory_analysis()
        temp = int(getattr(ma, "temp_size_in_bytes", 0))
        res["temp_bytes"] = temp
        res["peak_live_ratio"] = round((out_bytes + temp) / in_bytes, 3)
    except Exception:  # noqa: BLE001 — backend without memory analysis
        pass
    return res


# ---------------------------------------------------------------------------
# device-set change: the runtime's device-to-device copy
# ---------------------------------------------------------------------------

def deviceput_rechunk(data, logical_shape, dst_mesh):
    """Reshard onto a mesh with a DIFFERENT device set (elastic shrink /
    grow): re-quantize under the source layout, then hand the layout
    change to the runtime's device-to-device copy.  Still no host
    materialization — ``jax.device_put`` between shardings moves shards
    directly (and ITS collective schedule is the arXiv:2112.01075
    implementation inside XLA)."""
    out_pshape = _out_pshape(logical_shape, dst_mesh)
    out = _requantize_op(data, tuple(int(s) for s in logical_shape),
                         out_pshape, None)
    return jax.device_put(out, NamedSharding(dst_mesh, P(*_mesh.AXIS_NAMES)))


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

def pick_schedule(data, dst_mesh, schedule="auto") -> str:
    """The rechunk routing rule (the ``math.matmul`` algorithm= pattern):
    an explicit ``schedule=`` wins; ``"auto"`` consults
    ``DSLIB_RECHUNK_SCHEDULE`` and then the layouts — same-layout
    operands take the jit requantize, a relayout over the same device
    set takes the explicit panel exchange, a device-set change falls
    back to the runtime copy."""
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown rechunk schedule {schedule!r}: expected "
                         f"one of {SCHEDULES}")
    if schedule == "auto":
        env = os.environ.get("DSLIB_RECHUNK_SCHEDULE", "auto")
        if env not in SCHEDULES:
            raise ValueError(f"bad DSLIB_RECHUNK_SCHEDULE={env!r}")
        schedule = env
    if schedule != "auto":
        return schedule
    sharding = getattr(data, "sharding", None)
    if isinstance(sharding, NamedSharding) and \
            sharding == _mesh.data_sharding(dst_mesh):
        return "xla"
    if panel_supported(data, dst_mesh):
        return "panels"
    return "deviceput"


def reshard(data, logical_shape, dst_mesh, schedule="auto", panels=None,
            overlap=None):
    """Reshard a padded device backing for ``dst_mesh``'s quantum and
    layout.  Returns ``(new_backing, schedule_used)``; never touches the
    host for an on-device operand.  ``overlap`` picks the panel
    exchange's loop schedule (None → the ``DSLIB_OVERLAP`` router)."""
    sched = pick_schedule(data, dst_mesh, schedule)
    if sched == "panels":
        if not panel_supported(data, dst_mesh):
            raise ValueError(
                "schedule='panels' needs a fully-addressable source over "
                "the named mesh whose device set covers the target mesh — "
                "use schedule='deviceput' (or 'auto') for a device-set "
                "change")
        return panel_rechunk(data, logical_shape, dst_mesh, panels,
                             overlap), sched
    if sched == "deviceput":
        return deviceput_rechunk(data, logical_shape, dst_mesh), sched
    # "xla": one jitted requantize; any residual layout change is the SPMD
    # partitioner's (same-device-set inputs only, as for any jit)
    out_pshape = _out_pshape(logical_shape, dst_mesh)
    out = _requantize_op(data, tuple(int(s) for s in logical_shape),
                         out_pshape, dst_mesh)
    return out, sched
