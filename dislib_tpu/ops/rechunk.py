"""On-device collective rechunk/redistribute (ROADMAP item 4).

Reference regime: "Memory-efficient array redistribution through portable
collective communication" (arXiv:2112.01075) — express a resharding as a
short sequence of collectives whose peak memory is bounded by the output
plus one in-flight panel, never a full gathered copy.  dislib_tpu needs
exactly that move at three seams:

1. **Quantum re-padding** on one mesh: a ds-array built under an older
   mesh carries a pad quantum the current grid doesn't divide.  The fix
   is a traced crop/place/re-mask (:func:`requantize_body`) that rides
   the dispatch-fusion graph as a ``"rechunk"`` instruction — a
   mid-pipeline reshard costs ZERO extra dispatches in a fused chain.
2. **Mesh-layout change over the same devices** (elastic reshape,
   1-D ↔ 2-D): the explicit *panel-exchange* schedule
   (:func:`panel_rechunk`) — a ``shard_map`` over the SOURCE mesh that
   walks the array in k row panels, broadcasting each panel with the
   masked-``psum`` idiom of ``ops/summa.py`` (one collective per panel
   per mesh axis) while every device gathers its TARGET-layout block
   from the passing panel.  The per-device output blocks are then
   re-wrapped zero-copy (``jax.make_array_from_single_device_arrays``)
   as a global array of the target mesh.  ONE jitted program; in-flight
   panel bytes ≈ ``|array| / panels``, so peak live ≈ (1 + 1/k)·|array|
   beyond the source — never a gathered copy, never the host.
3. **Device-set change** (elastic shrink/grow): the runtime's own
   device-to-device copy (:func:`deviceput_rechunk`) — still no host
   materialization; the collective schedule is XLA's (the arXiv paper
   describes exactly that implementation).

Schedule selection (``DSLIB_RECHUNK_SCHEDULE`` overrides ``"auto"``):
``"xla"`` = the fused/jit requantize path (same layout, or leave the
cross-layout collectives to the SPMD partitioner), ``"panels"`` = the
explicit exchange, ``"deviceput"`` = the runtime copy.  ``"auto"`` picks
the fused path for same-layout operands, panels for a layout change over
the same device set AND for a device-set expansion (the grow-back
schedule: panels assemble every target block on the source devices, new
devices each receive exactly one block — :func:`panel_grow_rechunk`),
deviceput otherwise.  ``DSLIB_RECHUNK_PANELS``
(default 4) sets k, the per-source-rank panel count.

The pad-and-mask invariant is re-asserted by EVERY schedule: the region
outside the logical shape is rebuilt from a zero canvas (or masked to
zero), so a poisoned pad tail cannot survive a reshard — the same
``grow_canvas`` discipline the round-10 precision PR pinned for the
blocked factorizations.
"""

from __future__ import annotations

import math
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dislib_tpu.ops import overlap as _ov
from dislib_tpu.parallel import hosts as _hosts
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.utils import profiling as _prof
from dislib_tpu.utils.profiling import profiled_jit as _pjit

__all__ = [
    "requantize_body", "repad_axis", "panel_rechunk", "panel_grow_rechunk",
    "deviceput_rechunk", "reshard", "panel_memory_analysis",
    "panel_comm_probe", "reshard_sparse", "pick_sparse_schedule",
    "dcn_rechunk", "dcn_supported", "dcn_accounting",
]

SCHEDULES = ("auto", "xla", "panels", "deviceput", "dcn")

# the hierarchical schedule's outer mesh axis: whole-row blocks of the
# source mesh grouped by owning host (parallel.hosts.host_blocks)
_HOSTS = "hosts"


def _padded_dim(n: int, quantum: int) -> int:
    return max(quantum, int(math.ceil(n / quantum)) * quantum)


def _out_pshape(logical_shape, mesh) -> tuple[int, int]:
    q = _mesh.pad_quantum(mesh)
    return tuple(_padded_dim(int(s), q) for s in logical_shape)


# ---------------------------------------------------------------------------
# the traced re-quantize body (shared by the fused "rechunk" instruction
# and the eager kernel) — same-mesh pad-quantum moves
# ---------------------------------------------------------------------------

def requantize_body(data, logical_shape, out_pshape, mesh="default"):
    """Re-pad ``data`` (any padded canvas holding ``logical_shape`` at its
    origin) onto a zero canvas of ``out_pshape``, re-zero everything
    outside the logical region, and constrain to the canonical sharding.

    Traced: this is the ``"rechunk"`` fusion-instruction body, so a
    mid-chain reshard fuses into the chain's ONE dispatch.  The output
    pad region is zero BY CONSTRUCTION (fresh canvas + mask), so the
    pad-and-mask invariant holds even for a poisoned input tail.

    ``mesh``: a Mesh to constrain the result to, the string "default"
    for the library default mesh, or None for no constraint (the
    deviceput path, whose input devices may not be the default mesh's)."""
    m, n = (int(s) for s in logical_shape)
    r = min(data.shape[0], out_pshape[0])
    c = min(data.shape[1], out_pshape[1])
    cropped = data[:r, :c]
    if tuple(cropped.shape) != tuple(out_pshape):
        canvas = jnp.zeros(out_pshape, data.dtype)
        out = lax.dynamic_update_slice(canvas, cropped, (0, 0))
    else:
        out = cropped
    ri = lax.broadcasted_iota(jnp.int32, out.shape, 0)
    ci = lax.broadcasted_iota(jnp.int32, out.shape, 1)
    out = jnp.where((ri < m) & (ci < n), out, jnp.zeros((), out.dtype))
    if mesh is None:
        return out
    sharding = _mesh.data_sharding(None if mesh == "default" else mesh)
    return lax.with_sharding_constraint(out, sharding)


@partial(_pjit, static_argnames=("logical_shape", "out_pshape", "mesh"),
         name="rechunk_requantize")
def _requantize_op(data, logical_shape, out_pshape, mesh):
    return requantize_body(data, logical_shape, out_pshape, mesh)


@partial(_pjit, static_argnames=("logical", "target", "axis"),
         name="repad_axis")
def repad_axis(a, logical, target, axis=0):
    """On-device :func:`dislib_tpu.runtime.repad_rows`: crop to the first
    ``logical`` slices along ``axis`` and zero-fill out to ``target`` —
    one jitted kernel, no host round trip.  N-dimensional (elastic state
    arrays are 1/2/3-D)."""
    idx = [slice(None)] * a.ndim
    idx[axis] = slice(0, logical)
    cropped = a[tuple(idx)]
    if target == logical:
        return cropped
    shape = list(cropped.shape)
    shape[axis] = target
    out = jnp.zeros(tuple(shape), a.dtype)
    return lax.dynamic_update_slice(out, cropped, (0,) * a.ndim)


# ---------------------------------------------------------------------------
# the explicit panel-exchange schedule (same device set, new mesh layout)
# ---------------------------------------------------------------------------

def _panels_per_rank(m_loc: int, requested: int) -> int:
    """Largest divisor of the per-rank row count ≤ the requested panel
    count (panels must tile a source rank's rows exactly)."""
    for j in range(max(1, min(m_loc, requested)), 0, -1):
        if m_loc % j == 0:
            return j
    return 1


def _requested_panels(panels) -> int:
    if panels is not None:
        return max(1, int(panels))
    return max(1, int(os.environ.get("DSLIB_RECHUNK_PANELS", "4")))


def _target_coord_tables(src_mesh: Mesh, dst_mesh: Mesh):
    """Per-source-linear-index target (row, col) coordinates.  A source
    device absent from the target grid gets (0, 0) — it computes a
    duplicate of the (0, 0) block that the rewrap simply drops."""
    src_flat = list(src_mesh.devices.flat)
    dst_pos = {}
    rp, cp = dst_mesh.devices.shape
    for r in range(rp):
        for c in range(cp):
            dst_pos[dst_mesh.devices[r, c]] = (r, c)
    tr = np.zeros((len(src_flat),), np.int32)
    tc = np.zeros((len(src_flat),), np.int32)
    for i, d in enumerate(src_flat):
        tr[i], tc[i] = dst_pos.get(d, (0, 0))
    return tr, tc


@partial(_pjit, static_argnames=("logical_shape", "out_pshape", "src_mesh",
                                 "dst_shape", "tr_key", "tc_key", "steps",
                                 "overlap", "comm_only"),
         name="rechunk_panels")
def _panel_exchange(data, logical_shape, out_pshape, src_mesh, dst_shape,
                    tr_key, tc_key, steps, overlap="db", comm_only=False):
    """ONE jitted program: shard_map over the SOURCE mesh; each device
    assembles its TARGET-layout block from ``steps`` masked-psum panel
    broadcasts (the ``ops/summa.py`` collective idiom, ``check_vma`` on).

    The exchange/assemble loop runs through ``ops/overlap.panel_pipeline``
    (round-13): under the default double-buffered schedule panel t+1's
    rows-axis broadcast is issued before panel t's cols-broadcast/gather
    assembly consumes it — one extra in-flight panel of live memory
    (verified by :func:`panel_memory_analysis`), bit-equal to the
    sequential schedule (``overlap="seq"``).  ``comm_only=True`` is the
    bench tier's broadcast-only variant: the identical collectives with
    the gather/assemble compute replaced by a (1, 1) touch per panel.

    ``tr_key``/``tc_key`` are the target-coordinate tables as hashable
    tuples (they ride the jit cache key: a different device mapping is a
    different program)."""
    m, n = logical_shape
    rows_s, cols_s = src_mesh.shape[_mesh.ROWS], src_mesh.shape[_mesh.COLS]
    rows_d, cols_d = dst_shape
    m_loc1, n_loc1 = data.shape[0] // rows_s, data.shape[1] // cols_s
    m_loc2, n_loc2 = out_pshape[0] // rows_d, out_pshape[1] // cols_d
    j = steps // rows_s                     # panels per source row-rank
    h = m_loc1 // j                         # panel height (global rows)
    tr_tab = jnp.asarray(np.asarray(tr_key, np.int32))
    tc_tab = jnp.asarray(np.asarray(tc_key, np.int32))

    def local(x_loc):
        my_r = lax.axis_index(_mesh.ROWS)
        my_c = lax.axis_index(_mesh.COLS)
        my_lin = my_r * cols_s + my_c
        row0 = tr_tab[my_lin] * m_loc2      # my target block origin
        col0 = tc_tab[my_lin] * n_loc2
        ri = row0 + lax.iota(jnp.int32, m_loc2)   # global coords of my
        ci = col0 + lax.iota(jnp.int32, n_loc2)   # target block entries

        def fetch(t, prev):
            del prev                        # panels slice by step
            owner_r = t // j
            pan = lax.dynamic_slice(x_loc, ((t % j) * h, 0), (h, n_loc1))
            pan = jnp.where(my_r == owner_r, pan, jnp.zeros((), pan.dtype))
            return lax.psum(pan, _mesh.ROWS)

        def _col_blocks(pan):
            """The per-col-rank broadcasts of one row panel (static loop:
            one masked psum per source col-rank)."""
            for s in range(cols_s):
                if cols_s > 1:
                    blk = jnp.where(my_c == s, pan,
                                    jnp.zeros((), pan.dtype))
                    blk = lax.psum(blk, _mesh.COLS)
                else:
                    blk = pan
                yield s, blk

        if comm_only:
            def consume(t, acc, pan):
                for _, blk in _col_blocks(pan):
                    acc = acc + blk[:1, :1]
                return acc

            acc_shape = (1, 1)
        else:
            def consume(t, acc, pan):
                owner_r = t // j
                gr0 = owner_r * m_loc1 + (t % j) * h  # panel's global rows
                r_in = (ri >= gr0) & (ri < gr0 + h)
                r_idx = jnp.clip(ri - gr0, 0, h - 1)
                for s, blk in _col_blocks(pan):
                    gc0 = s * n_loc1
                    c_in = (ci >= gc0) & (ci < gc0 + n_loc1)
                    c_idx = jnp.clip(ci - gc0, 0, n_loc1 - 1)
                    gathered = blk[r_idx][:, c_idx]
                    acc = jnp.where(r_in[:, None] & c_in[None, :],
                                    gathered, acc)
                return acc

            acc_shape = (m_loc2, n_loc2)

        acc0 = lax.pcast(jnp.zeros(acc_shape, x_loc.dtype),
                         (_mesh.ROWS, _mesh.COLS), to="varying")
        acc = _ov.panel_pipeline(steps, fetch(0, None), fetch, consume,
                                 acc0, _ov.overlapped(overlap))
        if comm_only:
            return acc
        # re-assert the pad-and-mask invariant on the NEW canvas: entries
        # outside the logical region are zero no matter what the source
        # pad tail carried
        keep = (ri < m)[:, None] & (ci < n)[None, :]
        return jnp.where(keep, acc, jnp.zeros((), acc.dtype))

    return jax.shard_map(
        local, mesh=src_mesh,
        in_specs=P(_mesh.ROWS, _mesh.COLS),
        out_specs=P(_mesh.ROWS, _mesh.COLS),
        check_vma=True,
    )(data)


def _panel_args(data, logical_shape, dst_mesh, panels, overlap=None):
    """Static argument pack for :func:`_panel_exchange` (shared by the
    run path and the AOT memory-analysis probe).  ``overlap`` resolves
    through the ``DSLIB_OVERLAP`` router here, at the host boundary, so
    an env flip retraces (the precision-policy static contract)."""
    sharding = data.sharding
    src_mesh = sharding.mesh
    out_pshape = _out_pshape(logical_shape, dst_mesh)
    rows_s = src_mesh.shape[_mesh.ROWS]
    m_loc1 = data.shape[0] // rows_s
    j = _panels_per_rank(m_loc1, _requested_panels(panels))
    tr, tc = _target_coord_tables(src_mesh, dst_mesh)
    return dict(logical_shape=tuple(int(s) for s in logical_shape),
                out_pshape=out_pshape, src_mesh=src_mesh,
                dst_shape=(dst_mesh.shape[_mesh.ROWS],
                           dst_mesh.shape[_mesh.COLS]),
                tr_key=tuple(int(v) for v in tr),
                tc_key=tuple(int(v) for v in tc),
                steps=rows_s * j,
                overlap=_ov.resolve(overlap))


def panel_supported(data, dst_mesh) -> bool:
    """True when the explicit panel exchange can run: the source backing
    is a fully-addressable NamedSharding over our named mesh whose grid
    divides the padded shape, and every target device already holds a
    source shard (same-device-set relayout — the elastic reshape case)."""
    sharding = getattr(data, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return False
    src_mesh = sharding.mesh
    if not isinstance(src_mesh, Mesh) or \
            tuple(src_mesh.axis_names) != _mesh.AXIS_NAMES:
        return False
    if not getattr(data, "is_fully_addressable", False):
        return False
    rows_s = src_mesh.shape[_mesh.ROWS]
    cols_s = src_mesh.shape[_mesh.COLS]
    if data.shape[0] % rows_s or data.shape[1] % cols_s:
        return False
    src_devs = set(src_mesh.devices.flat)
    return set(dst_mesh.devices.flat) <= src_devs


def panel_rechunk(data, logical_shape, dst_mesh, panels=None, overlap=None):
    """The explicit collective reshard: ONE jitted panel-exchange program
    over the source mesh, then a ZERO-COPY rewrap of the per-device
    target blocks as a global array of ``dst_mesh`` — no host, no
    gathered copy, peak in-flight panel bytes ≈ |array| / panels (one
    extra panel under the default double-buffered ``overlap`` schedule —
    see :func:`panel_memory_analysis`)."""
    kw = _panel_args(data, logical_shape, dst_mesh, panels, overlap)
    _prof.count_schedule("rechunk_panels", kw["overlap"])
    out_perm = _panel_exchange(data, **kw)
    out_pshape = kw["out_pshape"]
    by_dev = {s.device: s.data for s in out_perm.addressable_shards}
    bufs = [by_dev[d] for d in dst_mesh.devices.flat]
    return jax.make_array_from_single_device_arrays(
        out_pshape, NamedSharding(dst_mesh, P(*_mesh.AXIS_NAMES)), bufs)


def _grow_assignment(src_mesh: Mesh, dst_mesh: Mesh):
    """Which source device assembles each destination block, and in
    which output slot: ``assign[t] = (q, i)`` maps destination flat
    index ``t`` to slot ``q`` of source flat index ``i``.  Blocks are
    handed out round-robin WITHIN each host — a destination device's
    block is always assembled by a source device on ITS host, so the
    placement put rides ICI and never DCN (the cross-host grow rung:
    the panel collectives already moved the data between hosts).  On a
    single host this reduces exactly to the global round-robin
    ``t = i + q * n_src``.  Returns ``(assign, slots)``."""
    src_flat = list(src_mesh.devices.flat)
    dst_flat = list(dst_mesh.devices.flat)
    src_by_host: dict[int, list[int]] = {}
    for i, d in enumerate(src_flat):
        src_by_host.setdefault(_hosts.host_of(d), []).append(i)
    taken = {h: 0 for h in src_by_host}
    assign: list[tuple[int, int]] = []
    for d in dst_flat:
        h = _hosts.host_of(d)
        owners = src_by_host.get(h)
        if owners is None:
            # no source shard on this host (panel_grow_supported refused
            # this layout); keep a defined mapping for robustness
            owners = list(range(len(src_flat)))
            h = None
            taken.setdefault(None, 0)
        k = taken[h]
        taken[h] = k + 1
        assign.append((k // len(owners), owners[k % len(owners)]))
    slots = 1 + max(q for q, _ in assign)
    return assign, slots


def _grow_coord_tables(src_mesh: Mesh, dst_mesh: Mesh):
    """Per-(slot, source-linear-index) target (row, col) coordinates for
    the GROW exchange, from the host-aware :func:`_grow_assignment`.
    An unused slot duplicates block (0, 0) — the rewrap drops it."""
    assign, slots = _grow_assignment(src_mesh, dst_mesh)
    n_src = int(src_mesh.devices.size)
    cols_d = int(dst_mesh.devices.shape[1])
    tr = np.zeros((slots, n_src), np.int32)
    tc = np.zeros((slots, n_src), np.int32)
    for t, (q, i) in enumerate(assign):
        tr[q, i], tc[q, i] = divmod(t, cols_d)
    return tr, tc


@partial(_pjit, static_argnames=("logical_shape", "out_pshape", "src_mesh",
                                 "dst_shape", "tr_key", "tc_key", "steps",
                                 "overlap"),
         name="rechunk_panels_grow")
def _panel_exchange_grow(data, logical_shape, out_pshape, src_mesh,
                         dst_shape, tr_key, tc_key, steps, overlap="db"):
    """The grow-direction panel exchange: the SAME masked-psum panel
    broadcasts as :func:`_panel_exchange` (one jitted shard_map over the
    SOURCE mesh, ``ops/overlap.panel_pipeline`` schedule), but every
    source device assembles ``slots = len(tr_key)`` TARGET blocks from
    each passing panel instead of one — the target grid has more devices
    than the source, so the blocks for the new devices must be built
    somewhere before they can be placed.  A separate jit from the
    shrink/relayout exchange: its output arity depends on the slot
    count, and keeping it apart leaves the existing compiled paths (and
    their cache keys) untouched."""
    m, n = logical_shape
    rows_s, cols_s = src_mesh.shape[_mesh.ROWS], src_mesh.shape[_mesh.COLS]
    rows_d, cols_d = dst_shape
    m_loc1, n_loc1 = data.shape[0] // rows_s, data.shape[1] // cols_s
    m_loc2, n_loc2 = out_pshape[0] // rows_d, out_pshape[1] // cols_d
    j = steps // rows_s                     # panels per source row-rank
    h = m_loc1 // j                         # panel height (global rows)
    slots = len(tr_key)
    tr_tab = jnp.asarray(np.asarray(tr_key, np.int32))
    tc_tab = jnp.asarray(np.asarray(tc_key, np.int32))

    def local(x_loc):
        my_r = lax.axis_index(_mesh.ROWS)
        my_c = lax.axis_index(_mesh.COLS)
        my_lin = my_r * cols_s + my_c
        coords = []                         # global coords per target slot
        for q in range(slots):
            row0 = tr_tab[q, my_lin] * m_loc2
            col0 = tc_tab[q, my_lin] * n_loc2
            coords.append((row0 + lax.iota(jnp.int32, m_loc2),
                           col0 + lax.iota(jnp.int32, n_loc2)))

        def fetch(t, prev):
            del prev                        # panels slice by step
            owner_r = t // j
            pan = lax.dynamic_slice(x_loc, ((t % j) * h, 0), (h, n_loc1))
            pan = jnp.where(my_r == owner_r, pan, jnp.zeros((), pan.dtype))
            return lax.psum(pan, _mesh.ROWS)

        def consume(t, acc, pan):
            owner_r = t // j
            gr0 = owner_r * m_loc1 + (t % j) * h  # panel's global rows
            acc = list(acc)
            for s in range(cols_s):         # ONE cols-broadcast per panel,
                if cols_s > 1:              # shared by every slot's gather
                    blk = jnp.where(my_c == s, pan,
                                    jnp.zeros((), pan.dtype))
                    blk = lax.psum(blk, _mesh.COLS)
                else:
                    blk = pan
                gc0 = s * n_loc1
                for q, (ri, ci) in enumerate(coords):
                    r_in = (ri >= gr0) & (ri < gr0 + h)
                    r_idx = jnp.clip(ri - gr0, 0, h - 1)
                    c_in = (ci >= gc0) & (ci < gc0 + n_loc1)
                    c_idx = jnp.clip(ci - gc0, 0, n_loc1 - 1)
                    gathered = blk[r_idx][:, c_idx]
                    acc[q] = jnp.where(r_in[:, None] & c_in[None, :],
                                       gathered, acc[q])
            return tuple(acc)

        acc0 = tuple(
            lax.pcast(jnp.zeros((m_loc2, n_loc2), x_loc.dtype),
                      (_mesh.ROWS, _mesh.COLS), to="varying")
            for _ in range(slots))
        accs = _ov.panel_pipeline(steps, fetch(0, None), fetch, consume,
                                  acc0, _ov.overlapped(overlap))
        # re-assert the pad-and-mask invariant on every NEW canvas
        out = []
        for q, (ri, ci) in enumerate(coords):
            keep = (ri < m)[:, None] & (ci < n)[None, :]
            out.append(jnp.where(keep, accs[q],
                                 jnp.zeros((), accs[q].dtype)))
        return tuple(out)

    return jax.shard_map(
        local, mesh=src_mesh,
        in_specs=P(_mesh.ROWS, _mesh.COLS),
        out_specs=(P(_mesh.ROWS, _mesh.COLS),) * slots,
        check_vma=True,
    )(data)


def panel_grow_supported(data, dst_mesh) -> bool:
    """True when the grow-direction panel exchange can run: the source
    backing passes the same NamedSharding/addressability/divisibility
    gates as :func:`panel_supported`, the target device set strictly
    CONTAINS the source's (elastic grow-back), and every target device's
    HOST already holds a source shard — so each new device's block is
    placed by an intra-host put (the cross-host rung: before round 19
    this required ``dst ⊆ local_devices`` and degraded any multi-host
    grow to per-array ``device_put``).  A host gaining devices without
    a single surviving source shard falls back to deviceput."""
    sharding = getattr(data, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return False
    src_mesh = sharding.mesh
    if not isinstance(src_mesh, Mesh) or \
            tuple(src_mesh.axis_names) != _mesh.AXIS_NAMES:
        return False
    if not getattr(data, "is_fully_addressable", False):
        return False
    rows_s = src_mesh.shape[_mesh.ROWS]
    cols_s = src_mesh.shape[_mesh.COLS]
    if data.shape[0] % rows_s or data.shape[1] % cols_s:
        return False
    src_devs = set(src_mesh.devices.flat)
    dst_devs = set(dst_mesh.devices.flat)
    if not src_devs < dst_devs:
        return False
    src_hosts = {_hosts.host_of(d) for d in src_devs}
    return all(_hosts.host_of(d) in src_hosts for d in dst_devs)


def panel_grow_rechunk(data, logical_shape, dst_mesh, panels=None,
                       overlap=None):
    """The grow-direction panel reshard (device-set EXPANSION — the
    elastic grow-back): ONE jitted panel-exchange program over the
    SOURCE mesh assembling every target block (see
    :func:`_panel_exchange_grow`), then the placement pass — a block
    whose target device already holds a source shard rewraps ZERO-COPY,
    and each NEW device receives exactly its one block via a single
    direct device-to-device put.  Per-device moved bytes are one target
    block, not the deviceput fallback's partitioner-chosen schedule; the
    host never sees the data either way."""
    kw = _panel_args_grow(data, logical_shape, dst_mesh, panels, overlap)
    _prof.count_schedule("rechunk_panels_grow", kw["overlap"])
    outs = _panel_exchange_grow(data, **kw)
    out_pshape = kw["out_pshape"]
    src_flat = list(kw["src_mesh"].devices.flat)
    dst_flat = list(dst_mesh.devices.flat)
    assign, _slots = _grow_assignment(kw["src_mesh"], dst_mesh)
    per_src = [{s.device: s.data for s in arr.addressable_shards}
               for arr in outs]
    by_dev = {}
    for t, (q, i) in enumerate(assign):
        d_src, d_dst = src_flat[i], dst_flat[t]
        blk = per_src[q].get(d_src)
        if blk is None:
            continue                # another process's shard: it places it
        by_dev[d_dst] = blk if d_dst == d_src \
            else jax.device_put(blk, d_dst)
    bufs = [by_dev[d] for d in dst_flat if d in by_dev]
    return jax.make_array_from_single_device_arrays(
        out_pshape, NamedSharding(dst_mesh, P(*_mesh.AXIS_NAMES)), bufs)


def _panel_args_grow(data, logical_shape, dst_mesh, panels, overlap=None):
    """Static argument pack for :func:`_panel_exchange_grow` — the
    :func:`_panel_args` shape with the 2-D slot coordinate tables."""
    src_mesh = data.sharding.mesh
    out_pshape = _out_pshape(logical_shape, dst_mesh)
    rows_s = src_mesh.shape[_mesh.ROWS]
    m_loc1 = data.shape[0] // rows_s
    j = _panels_per_rank(m_loc1, _requested_panels(panels))
    tr, tc = _grow_coord_tables(src_mesh, dst_mesh)
    return dict(logical_shape=tuple(int(s) for s in logical_shape),
                out_pshape=out_pshape, src_mesh=src_mesh,
                dst_shape=(dst_mesh.shape[_mesh.ROWS],
                           dst_mesh.shape[_mesh.COLS]),
                tr_key=tuple(tuple(int(v) for v in row) for row in tr),
                tc_key=tuple(tuple(int(v) for v in row) for row in tc),
                steps=rows_s * j,
                overlap=_ov.resolve(overlap))


def panel_comm_probe(data, logical_shape, dst_mesh, panels=None,
                     overlap="seq"):
    """Broadcast-only variant of the SAME panel-exchange program — the
    identical masked-psum collectives with the gather/assemble compute
    replaced by a (1, 1) touch per panel, so the collectives survive
    DCE.  The bench overlap tier's t_comm_alone denominator."""
    kw = _panel_args(data, logical_shape, dst_mesh, panels, overlap)
    return _panel_exchange(data, comm_only=True, **kw)


def panel_memory_analysis(data, logical_shape, dst_mesh, panels=None,
                          overlap=None):
    """XLA's own memory accounting of the compiled panel-exchange program
    — the bench tier's peak-live-buffer proxy.  Returns a dict with
    ``in_bytes``/``out_bytes``/``temp_bytes`` and ``peak_live_ratio`` =
    (out + temp) / in: a schedule that gathered a full copy would sit at
    ≥ 2.0; the sequential panel schedule stays ≈ 1 + 1/panels and the
    double-buffered one ≈ 1 + 2/panels (the pipelined carry holds ONE
    extra in-flight panel, never a copy of the operand — the bench
    overlap tier's documented bound).  ``temp_bytes`` is None when the
    backend exposes no memory analysis (the analytic panel bound is
    reported alongside either way)."""
    kw = _panel_args(data, logical_shape, dst_mesh, panels, overlap)
    in_bytes = data.size * data.dtype.itemsize
    out_bytes = int(np.prod(kw["out_pshape"])) * data.dtype.itemsize
    n_dev = int(np.prod(kw["src_mesh"].devices.shape))
    # analytic in-flight bound: every device holds one (h, n_loc1) panel
    # (+ its cols-broadcast twin, + the pipelined next panel when
    # double-buffered) during a step
    cols_s = kw["src_mesh"].shape[_mesh.COLS]
    panel_bytes = in_bytes // kw["steps"]
    analytic_temp = panel_bytes * ((2 if cols_s > 1 else 1)
                                   + (1 if _ov.overlapped(kw["overlap"])
                                      else 0))
    res = {"in_bytes": in_bytes, "out_bytes": out_bytes,
           "panels": kw["steps"], "analytic_temp_bytes": analytic_temp,
           "analytic_ratio": round((out_bytes + analytic_temp) / in_bytes, 3),
           "temp_bytes": None, "peak_live_ratio": None, "n_devices": n_dev,
           "overlap": kw["overlap"]}
    try:
        compiled = _panel_exchange.lower(data, **kw).compile()
        ma = compiled.memory_analysis()
        temp = int(getattr(ma, "temp_size_in_bytes", 0))
        res["temp_bytes"] = temp
        res["peak_live_ratio"] = round((out_bytes + temp) / in_bytes, 3)
    except Exception:  # noqa: BLE001 — backend without memory analysis
        pass
    return res


# ---------------------------------------------------------------------------
# the hierarchical DCN schedule (multi-host relayout over the same devices)
#
# The flat panel exchange broadcasts one panel per (source row-rank ×
# panel) step along the FULL rows axis — on a mesh whose rows span
# hosts, every one of those O(panels) broadcasts is an inter-host
# message.  The ``dcn`` schedule restructures the loop hierarchically
# (arXiv:2112.01075's few-large-collectives shape): the source mesh is
# refactored as (hosts, local_rows, cols) and each step assembles ONE
# panel of a DESTINATION host's row block — every source host's
# contribution to that panel (the contiguous intersection of its row
# interval with the panel's) coalesces into a single (src-host →
# dst-host) message carried by one collective over the ('hosts', 'rows')
# axes; the per-local-shard gathers and the cols broadcasts stay
# intra-host (ICI).  Messages per step = O(hosts), never O(panels);
# inter-host bytes = the interval intersections — exactly the bytes any
# schedule must move (the deviceput baseline) — with both quantities
# accounted analytically by :func:`dcn_accounting` (the
# ``spmm_masking_work`` exposure pattern).  The assembled values are
# pure selections of source entries, so the schedule is BIT-EQUAL to
# ``panels``/``xla`` on any topology, including a single host (where it
# degenerates to a pure-ICI exchange with zero DCN messages).
# ---------------------------------------------------------------------------


def dcn_supported(data, dst_mesh) -> bool:
    """True when the hierarchical schedule can run: the same
    NamedSharding/divisibility gates as :func:`panel_supported`, the SAME
    device set on both meshes (relayout, not a device-set change), and a
    hierarchical row axis on BOTH meshes — contiguous equal blocks of
    whole rows per host (:func:`~dislib_tpu.parallel.hosts.host_blocks`),
    so the cols axis and the local gathers never pay DCN."""
    sharding = getattr(data, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return False
    src_mesh = sharding.mesh
    if not isinstance(src_mesh, Mesh) or \
            tuple(src_mesh.axis_names) != _mesh.AXIS_NAMES:
        return False
    rows_s = src_mesh.shape[_mesh.ROWS]
    cols_s = src_mesh.shape[_mesh.COLS]
    if data.shape[0] % rows_s or data.shape[1] % cols_s:
        return False
    if set(dst_mesh.devices.flat) != set(src_mesh.devices.flat):
        return False
    return _hosts.host_blocks(src_mesh) is not None and \
        _hosts.host_blocks(dst_mesh) is not None


@partial(_pjit, static_argnames=("logical_shape", "out_pshape", "mesh3",
                                 "dst_shape", "hblocks", "tr_key", "tc_key",
                                 "steps", "overlap"),
         name="rechunk_dcn")
def _dcn_exchange(data, logical_shape, out_pshape, mesh3, dst_shape,
                  hblocks, tr_key, tc_key, steps, overlap="db"):
    """ONE jitted program: shard_map over the source mesh refactored as
    ('hosts', 'rows', 'cols').  Step ``t`` assembles panel ``t % j`` of
    destination host-block ``t // j``: every device contributes the
    intersection of its row interval with the panel (a local gather),
    and ONE ``psum`` over ``('hosts', 'rows')`` coalesces all
    contributions — the batched inter-host exchange, one message per
    (src-host, dst-host) pair per step.  The per-col-rank broadcasts and
    the target-block gather are the flat exchange's, unchanged (and
    intra-host by the ``dcn_supported`` row-alignment gate).  Runs
    through ``ops/overlap.panel_pipeline`` like every panel loop."""
    m, n = logical_shape
    hosts_n = mesh3.shape[_HOSTS]
    rows_l = mesh3.shape[_mesh.ROWS]        # local row-ranks per host
    cols_s = mesh3.shape[_mesh.COLS]
    rows_d, cols_d = dst_shape
    m_loc1 = data.shape[0] // (hosts_n * rows_l)
    n_loc1 = data.shape[1] // cols_s
    m_loc2, n_loc2 = out_pshape[0] // rows_d, out_pshape[1] // cols_d
    block_h = (rows_d // hblocks) * m_loc2  # dst host-block height (rows)
    j = steps // hblocks                    # panels per dst host-block
    hp = block_h // j                       # panel height (global rows)
    tr_tab = jnp.asarray(np.asarray(tr_key, np.int32))
    tc_tab = jnp.asarray(np.asarray(tc_key, np.int32))

    def local(x_loc):
        hh = lax.axis_index(_HOSTS)
        rr = lax.axis_index(_mesh.ROWS)
        my_c = lax.axis_index(_mesh.COLS)
        my_lin = (hh * rows_l + rr) * cols_s + my_c
        row0 = tr_tab[my_lin] * m_loc2      # my target block origin
        col0 = tc_tab[my_lin] * n_loc2
        ri = row0 + lax.iota(jnp.int32, m_loc2)   # global coords of my
        ci = col0 + lax.iota(jnp.int32, n_loc2)   # target block entries
        r0 = (hh * rows_l + rr) * m_loc1    # my SOURCE row interval start

        def fetch(t, prev):
            del prev                        # panels slice by step
            g0 = (t // j) * block_h + (t % j) * hp
            gi = g0 + lax.iota(jnp.int32, hp)     # panel's global rows
            idx = jnp.clip(gi - r0, 0, m_loc1 - 1)
            mine = x_loc[idx, :]
            keep = (gi >= r0) & (gi < r0 + m_loc1)
            pan = jnp.where(keep[:, None], mine, jnp.zeros((), mine.dtype))
            # the coalesced exchange: every source host's contiguous
            # contribution to this dst-host panel rides ONE collective
            return lax.psum(pan, (_HOSTS, _mesh.ROWS))

        def consume(t, acc, pan):
            gr0 = (t // j) * block_h + (t % j) * hp
            r_in = (ri >= gr0) & (ri < gr0 + hp)
            r_idx = jnp.clip(ri - gr0, 0, hp - 1)
            for s in range(cols_s):         # intra-host cols broadcasts
                if cols_s > 1:
                    blk = jnp.where(my_c == s, pan,
                                    jnp.zeros((), pan.dtype))
                    blk = lax.psum(blk, _mesh.COLS)
                else:
                    blk = pan
                gc0 = s * n_loc1
                c_in = (ci >= gc0) & (ci < gc0 + n_loc1)
                c_idx = jnp.clip(ci - gc0, 0, n_loc1 - 1)
                gathered = blk[r_idx][:, c_idx]
                acc = jnp.where(r_in[:, None] & c_in[None, :],
                                gathered, acc)
            return acc

        acc0 = lax.pcast(jnp.zeros((m_loc2, n_loc2), x_loc.dtype),
                         (_HOSTS, _mesh.ROWS, _mesh.COLS), to="varying")
        acc = _ov.panel_pipeline(steps, fetch(0, None), fetch, consume,
                                 acc0, _ov.overlapped(overlap))
        # re-assert the pad-and-mask invariant on the NEW canvas
        keep = (ri < m)[:, None] & (ci < n)[None, :]
        return jnp.where(keep, acc, jnp.zeros((), acc.dtype))

    return jax.shard_map(
        local, mesh=mesh3,
        in_specs=P((_HOSTS, _mesh.ROWS), _mesh.COLS),
        out_specs=P((_HOSTS, _mesh.ROWS), _mesh.COLS),
        check_vma=True,
    )(data)


def _dcn_args(data, logical_shape, dst_mesh, panels, overlap=None):
    """Static argument pack for :func:`_dcn_exchange`: the source mesh
    refactored as ('hosts', 'rows', 'cols') from its host-block
    structure, the destination host-block count, and the panel count
    chosen as a divisor of the DST host-block height (panels subdivide
    the inter-host steps; the knob is the same ``DSLIB_RECHUNK_PANELS``)."""
    src_mesh = data.sharding.mesh
    out_pshape = _out_pshape(logical_shape, dst_mesh)
    h1, l1, _ = _hosts.host_blocks(src_mesh)
    cols_s = src_mesh.shape[_mesh.COLS]
    mesh3 = Mesh(src_mesh.devices.reshape(h1, l1, cols_s),
                 (_HOSTS, _mesh.ROWS, _mesh.COLS))
    h2, l2, _ = _hosts.host_blocks(dst_mesh)
    rows_d = dst_mesh.shape[_mesh.ROWS]
    m_loc2 = out_pshape[0] // rows_d
    j = _panels_per_rank(l2 * m_loc2, _requested_panels(panels))
    tr, tc = _target_coord_tables(src_mesh, dst_mesh)
    return dict(logical_shape=tuple(int(s) for s in logical_shape),
                out_pshape=out_pshape, mesh3=mesh3,
                dst_shape=(rows_d, dst_mesh.shape[_mesh.COLS]),
                hblocks=h2,
                tr_key=tuple(int(v) for v in tr),
                tc_key=tuple(int(v) for v in tc),
                steps=h2 * j,
                overlap=_ov.resolve(overlap))


def dcn_rechunk(data, logical_shape, dst_mesh, panels=None, overlap=None):
    """The hierarchical (DCN-aware) reshard: ONE jitted exchange over the
    host-refactored source mesh, then the zero-copy rewrap onto
    ``dst_mesh`` — :func:`panel_rechunk`'s contract with the collective
    loop restructured so inter-host messages are O(hosts) per step (see
    :func:`dcn_accounting` for the counted claim).  The rewrap places
    this process's ADDRESSABLE shards only, so every process of a
    multi-host job runs the same call on its view of the global array."""
    kw = _dcn_args(data, logical_shape, dst_mesh, panels, overlap)
    _prof.count_schedule("rechunk_dcn", kw["overlap"])
    out = _dcn_exchange(data, **kw)
    out_pshape = kw["out_pshape"]
    by_dev = {s.device: s.data for s in out.addressable_shards}
    bufs = [by_dev[d] for d in dst_mesh.devices.flat if d in by_dev]
    return jax.make_array_from_single_device_arrays(
        out_pshape, NamedSharding(dst_mesh, P(*_mesh.AXIS_NAMES)), bufs)


def dcn_accounting(data, logical_shape, dst_mesh, panels=None) -> dict:
    """Analytic inter-host traffic of the ``dcn`` schedule for this
    relayout (host-side, no dispatch — the ``spmm_masking_work``
    exposure pattern):

    - ``dcn_messages`` / ``dcn_bytes_moved`` — coalesced (src-host →
      dst-host) messages over the whole schedule and the bytes they
      carry (each step's message per pair is the contiguous intersection
      of the pair's row intervals with the step's panel);
    - ``messages_per_step_max`` — the per-step gate: ≤ hosts − 1, never
      a function of the panel count;
    - ``deviceput_bytes`` — the bytes ANY schedule must move across
      hosts (rows whose owning host changes), the bench floor;
    - ``flat_messages`` / ``flat_bytes_moved`` — what the FLAT panel
      exchange would cost on the same topology: every per-rank panel
      broadcast crosses to every other host (O(panels) messages).
    """
    src_mesh = data.sharding.mesh
    out_pshape = _out_pshape(logical_shape, dst_mesh)
    h1, l1, hosts_src = _hosts.host_blocks(src_mesh)
    h2, l2, hosts_dst = _hosts.host_blocks(dst_mesh)
    rows_s = src_mesh.shape[_mesh.ROWS]
    rows_d = dst_mesh.shape[_mesh.ROWS]
    m_loc1 = data.shape[0] // rows_s
    m_loc2 = out_pshape[0] // rows_d
    block_h = l2 * m_loc2
    j = _panels_per_rank(block_h, _requested_panels(panels))
    hp = block_h // j
    itemsize = data.dtype.itemsize
    row_bytes = int(data.shape[1]) * itemsize
    src_iv = [(b * l1 * m_loc1, (b + 1) * l1 * m_loc1) for b in range(h1)]
    msgs = 0
    bytes_moved = 0
    per_step_max = 0
    for d_blk in range(h2):
        for p in range(j):
            g0 = d_blk * block_h + p * hp
            step_msgs = 0
            for b in range(h1):
                if hosts_src[b] == hosts_dst[d_blk]:
                    continue            # intra-host: ICI, not DCN
                ov = min(g0 + hp, src_iv[b][1]) - max(g0, src_iv[b][0])
                if ov > 0:
                    step_msgs += 1
                    bytes_moved += ov * row_bytes
            msgs += step_msgs
            per_step_max = max(per_step_max, step_msgs)
    # the floor: rows whose owning host changes must cross DCN once
    # under ANY schedule (deviceput's XLA copy included)
    dp_bytes = 0
    for d_blk in range(h2):
        d0, d1 = d_blk * block_h, (d_blk + 1) * block_h
        for b in range(h1):
            if hosts_src[b] == hosts_dst[d_blk]:
                continue
            ov = min(d1, src_iv[b][1]) - max(d0, src_iv[b][0])
            if ov > 0:
                dp_bytes += ov * row_bytes
    all_hosts = len(set(hosts_src) | set(hosts_dst))
    j_flat = _panels_per_rank(m_loc1, _requested_panels(panels))
    flat_steps = rows_s * j_flat
    in_bytes = int(data.shape[0]) * row_bytes
    return {
        "hosts": all_hosts, "steps": h2 * j, "panels": j,
        "dcn_messages": msgs, "dcn_bytes_moved": bytes_moved,
        "messages_per_step_max": per_step_max,
        "deviceput_bytes": dp_bytes,
        "flat_messages": flat_steps * max(0, all_hosts - 1),
        "flat_bytes_moved": in_bytes * max(0, all_hosts - 1),
        "in_bytes": in_bytes,
    }


# ---------------------------------------------------------------------------
# device-set change: the runtime's device-to-device copy
# ---------------------------------------------------------------------------

def deviceput_rechunk(data, logical_shape, dst_mesh):
    """Reshard onto a mesh with a DIFFERENT device set (elastic shrink /
    grow): re-quantize under the source layout, then hand the layout
    change to the runtime's device-to-device copy.  Still no host
    materialization — ``jax.device_put`` between shardings moves shards
    directly (and ITS collective schedule is the arXiv:2112.01075
    implementation inside XLA)."""
    out_pshape = _out_pshape(logical_shape, dst_mesh)
    out = _requantize_op(data, tuple(int(s) for s in logical_shape),
                         out_pshape, None)
    return jax.device_put(out, NamedSharding(dst_mesh, P(*_mesh.AXIS_NAMES)))


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

def pick_schedule(data, dst_mesh, schedule="auto") -> str:
    """The rechunk routing rule (the ``math.matmul`` algorithm= pattern):
    an explicit ``schedule=`` wins; ``"auto"`` consults
    ``DSLIB_RECHUNK_SCHEDULE`` and then the layouts — same-layout
    operands take the jit requantize, a relayout over the same device
    set (or a device-set EXPANSION, the elastic grow-back) takes the
    explicit panel exchange, any other device-set change falls back to
    the runtime copy."""
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown rechunk schedule {schedule!r}: expected "
                         f"one of {SCHEDULES}")
    if schedule == "auto":
        env = os.environ.get("DSLIB_RECHUNK_SCHEDULE", "auto")
        if env not in SCHEDULES:
            raise ValueError(f"bad DSLIB_RECHUNK_SCHEDULE={env!r}")
        schedule = env
    if schedule != "auto":
        return schedule
    sharding = getattr(data, "sharding", None)
    if isinstance(sharding, NamedSharding) and \
            sharding == _mesh.data_sharding(dst_mesh):
        return "xla"
    if dcn_supported(data, dst_mesh) and \
            _hosts.n_hosts(sharding.mesh) > 1:
        return "dcn"                    # hierarchical: coalesce DCN traffic
    if panel_supported(data, dst_mesh) or panel_grow_supported(data, dst_mesh):
        return "panels"
    return "deviceput"


def reshard(data, logical_shape, dst_mesh, schedule="auto", panels=None,
            overlap=None):
    """Reshard a padded device backing for ``dst_mesh``'s quantum and
    layout.  Returns ``(new_backing, schedule_used)``; never touches the
    host for an on-device operand.  ``overlap`` picks the panel
    exchange's loop schedule (None → the ``DSLIB_OVERLAP`` router)."""
    sched = pick_schedule(data, dst_mesh, schedule)
    if sched == "dcn":
        if not dcn_supported(data, dst_mesh):
            raise ValueError(
                "schedule='dcn' needs same-device-set meshes whose row "
                "axes both split into contiguous equal host blocks (the "
                "hierarchical layout `distributed.initialize` documents); "
                "use schedule='panels'/'deviceput' (or 'auto') otherwise")
        return dcn_rechunk(data, logical_shape, dst_mesh, panels,
                           overlap), sched
    if sched == "panels":
        if panel_supported(data, dst_mesh):
            return panel_rechunk(data, logical_shape, dst_mesh, panels,
                                 overlap), sched
        if panel_grow_supported(data, dst_mesh):
            return panel_grow_rechunk(data, logical_shape, dst_mesh,
                                      panels, overlap), sched
        raise ValueError(
            "schedule='panels' needs a fully-addressable source over "
            "the named mesh whose device set covers — or is strictly "
            "contained in (grow-back) — the target mesh's; use "
            "schedule='deviceput' (or 'auto') for any other device-set "
            "change")
    if sched == "deviceput":
        return deviceput_rechunk(data, logical_shape, dst_mesh), sched
    # "xla": one jitted requantize; any residual layout change is the SPMD
    # partitioner's (same-device-set inputs only, as for any jit)
    out_pshape = _out_pshape(logical_shape, dst_mesh)
    out = _requantize_op(data, tuple(int(s) for s in logical_shape),
                         out_pshape, dst_mesh)
    return out, sched


# ---------------------------------------------------------------------------
# sparse rechunk: the same three-schedule router over the row-panel-sharded
# sparse representation (round-14 sparse PR).  Block size / nse quantum /
# mesh shape are deployment details for sparse arrays too: the schedules
# move the ShardedSparse buffers between layouts ON DEVICE — never the
# host, never a densification.
#
# What makes sparse relayout cheap here is the representation's
# row-sorted / tail-padded invariant (data/sparse.py): the live entries
# form ONE global stream ordered by row, so any target layout is pure
# STATIC addressing — per-shard stream offsets computed on host from the
# layout-independent `row_nnz` histogram (control plane), with the data
# plane moved by masked-psum panel broadcasts (the summa idiom) or one
# gather.  arXiv:2112.01075's portable-redistribution shape, applied to
# a sparse payload.
# ---------------------------------------------------------------------------


def _sparse_layout(rep, dst_mesh, nse=None):
    """Host-side target-layout plan: per-dest-shard stream offsets and
    counts from the row histogram, the uniform target nse, and the
    source stream offsets from the source counts.  All O(device-count)
    host metadata — no device sync ever decides a shape."""
    from dislib_tpu.data.sparse import _padded_rows, _round_nse
    m = rep.shape[0]
    p2 = dst_mesh.shape[_mesh.ROWS]
    m_local2 = _padded_rows(m, dst_mesh) // p2
    cum = np.concatenate([[0], np.cumsum(rep.row_nnz)])
    e0_dst = tuple(int(cum[min(s * m_local2, m)]) for s in range(p2 + 1))
    cnt_dst = tuple(e0_dst[s + 1] - e0_dst[s] for s in range(p2))
    e0_src = tuple(int(v) for v in
                   np.concatenate([[0], np.cumsum(rep.counts)]))
    nse2 = _round_nse(max(cnt_dst, default=0), nse)
    return dict(e0_src=e0_src, e0_dst=e0_dst, cnt_dst=cnt_dst,
                nse2=nse2, m_local2=m_local2, p2=p2)


def pick_sparse_schedule(rep, dst_mesh, schedule="auto") -> str:
    """The sparse rechunk routing rule (the dense ``pick_schedule``
    pattern, same env override): same-device-grid moves take the fused
    nse requantize ("xla"), a relayout whose target devices all hold
    source shards takes the explicit masked-psum panel exchange
    ("panels"), a device-set change takes the gather + runtime
    device-to-device copy ("deviceput")."""
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown rechunk schedule {schedule!r}: expected "
                         f"one of {SCHEDULES}")
    if schedule == "auto":
        env = os.environ.get("DSLIB_RECHUNK_SCHEDULE", "auto")
        if env not in SCHEDULES:
            raise ValueError(f"bad DSLIB_RECHUNK_SCHEDULE={env!r}")
        schedule = env
    if schedule == "dcn":
        # no hierarchical sparse tier yet: the dense coalescing story
        # does not apply to the row-stream layout — take the panel path
        schedule = "panels"
    if schedule != "auto":
        return schedule
    src = rep.mesh
    if src.devices.shape == dst_mesh.devices.shape and \
            (src.devices == dst_mesh.devices).all():
        return "xla"
    if set(dst_mesh.devices.flat) <= set(src.devices.flat):
        return "panels"
    return "deviceput"


def reshard_sparse(rep, dst_mesh, schedule="auto", nse=None, overlap=None):
    """Re-lay out a :class:`~dislib_tpu.data.sparse.ShardedSparse` for
    ``dst_mesh`` (and/or a new uniform ``nse``) on device.  Returns the
    new representation; every schedule rebuilds the nse pads from zero
    (value 0 at the sentinel column — the poisoned-pad discipline), so a
    poisoned input tail cannot survive the reshard."""
    from dislib_tpu.data.sparse import ShardedSparse
    sched = pick_sparse_schedule(rep, dst_mesh, schedule)
    plan = _sparse_layout(rep, dst_mesh, nse)
    if sched == "xla":
        if not (rep.mesh.devices.shape == dst_mesh.devices.shape
                and (rep.mesh.devices == dst_mesh.devices).all()):
            raise ValueError(
                "schedule='xla' is the same-device-grid nse requantize — "
                "use 'panels'/'deviceput' (or 'auto') for a layout change")
        if plan["nse2"] == rep.nse and dst_mesh is rep.mesh:
            return rep                  # already canonical: metadata no-op
        d, lr, cc = _sparse_requantize(rep.data, rep.lrows, rep.cols,
                                       rep.counts_dev, plan["nse2"],
                                       dst_mesh)
        counts_dev = rep.counts_dev if dst_mesh is rep.mesh else None
        # `cols_host` rides along unchanged: relayout permutes entries
        # between shards but never reorders the global row-sorted stream
        return ShardedSparse(d, lr, cc, counts_dev, rep.counts,
                             rep.row_nnz, rep.shape, dst_mesh,
                             cols_host=rep.cols_host)
    if sched == "panels":
        if not set(dst_mesh.devices.flat) <= set(rep.mesh.devices.flat):
            raise ValueError(
                "schedule='panels' needs every target device to hold a "
                "source shard — use schedule='deviceput' (or 'auto') for "
                "a device-set change")
        return _sparse_panels_run(rep, dst_mesh, plan, overlap)
    # "deviceput": one gather re-bucketing under the source mesh, then
    # the runtime's device-to-device copy onto the target sharding
    idxmap = _sparse_index_map(plan, rep.nse)
    d, lr, cc = _sparse_regather(rep.data, rep.lrows, rep.cols,
                                 jnp.asarray(idxmap),
                                 rep.m_local, plan["m_local2"], rep.nse)
    sh1 = NamedSharding(dst_mesh, P(_mesh.ROWS))
    return ShardedSparse(
        jax.device_put(d, sh1), jax.device_put(lr, sh1),
        jax.device_put(cc, sh1), None,
        plan["cnt_dst"], rep.row_nnz, rep.shape, dst_mesh,
        cols_host=rep.cols_host)


def _sparse_index_map(plan, nse1):
    """(p2, nse2) int32 table: flat source slot feeding each target slot
    (−1 = pad) — host-built from the static stream offsets."""
    p2, nse2 = plan["p2"], plan["nse2"]
    e0s = np.asarray(plan["e0_src"], np.int64)
    out = np.full((p2, nse2), -1, np.int32)
    for s2 in range(p2):
        k = plan["cnt_dst"][s2]
        if not k:
            continue
        g = plan["e0_dst"][s2] + np.arange(k, dtype=np.int64)
        src = np.searchsorted(e0s, g, side="right") - 1
        out[s2, :k] = src * nse1 + (g - e0s[src])
    return out


@partial(_pjit, static_argnames=("nse2", "mesh"),
         name="rechunk_sparse_requantize")
def _sparse_requantize(data, lrows, cols, counts, nse2, mesh):
    """Fused nse re-pad: crop/zero-grow every buffer's nse axis to the
    new quantum and re-zero the slots past each shard's live count —
    pads rebuilt from the zero canvas whatever the input tail carried.
    ONE dispatch for all three buffers."""
    sharding = NamedSharding(mesh, P(_mesh.ROWS))
    p = data.shape[0]
    ok = lax.broadcasted_iota(jnp.int32, (p, nse2), 1) < counts[:, None]

    def one(x):
        keep = min(int(x.shape[1]), nse2)
        out = jnp.zeros((p, nse2), x.dtype)
        out = lax.dynamic_update_slice(out, x[:, :keep], (0, 0))
        out = jnp.where(ok, out, jnp.zeros((), x.dtype))
        return lax.with_sharding_constraint(out, sharding)

    return one(data), one(lrows), one(cols)


@partial(_pjit, static_argnames=("m_local1", "m_local2", "nse1"),
         name="rechunk_sparse_gather")
def _sparse_regather(data, lrows, cols, idxmap, m_local1, m_local2, nse1):
    """Re-bucket the entry stream via the host-built index map (the
    "xla"-collectives gather: the SPMD partitioner owns the movement) —
    the deviceput schedule's compute half.  Local row ids rebase from
    the source/target shard strides; pads land exactly (0, 0, 0)."""
    ok = idxmap >= 0
    li = jnp.clip(idxmap, 0, None)
    src_shard = li // nse1
    dst_shard = lax.broadcasted_iota(jnp.int32, idxmap.shape, 0)
    gd = data.reshape(-1)[li.reshape(-1)].reshape(idxmap.shape)
    glr = lrows.reshape(-1)[li.reshape(-1)].reshape(idxmap.shape) \
        + src_shard * m_local1 - dst_shard * m_local2
    gcc = cols.reshape(-1)[li.reshape(-1)].reshape(idxmap.shape)
    z32 = jnp.zeros((), jnp.int32)
    return (jnp.where(ok, gd, jnp.zeros((), data.dtype)),
            jnp.where(ok, glr.astype(jnp.int32), z32),
            jnp.where(ok, gcc, z32))


def _sparse_panels_run(rep, dst_mesh, plan, overlap=None):
    """The explicit sparse panel exchange: ONE jitted shard_map over the
    SOURCE mesh (one masked-psum broadcast of each source shard's
    buffers along 'rows', every device assembling its TARGET shard by
    static stream addressing), then a zero-copy rewrap onto the target
    mesh — the dense ``panel_rechunk`` shape with a sparse payload."""
    from dislib_tpu.data.sparse import ShardedSparse
    src_mesh = rep.mesh
    tr, _ = _target_coord_tables(src_mesh, dst_mesh)
    sched = _ov.resolve(overlap)
    _prof.count_schedule("rechunk_sparse_panels", sched)
    outs = _sparse_panel_exchange(
        rep.data, rep.lrows, rep.cols,
        src_mesh=src_mesh, tr_key=tuple(int(v) for v in tr),
        e0_src=plan["e0_src"], e0_dst=plan["e0_dst"],
        cnt_dst=plan["cnt_dst"], m_local1=rep.m_local,
        m_local2=plan["m_local2"], nse2=plan["nse2"], overlap=sched)
    sh1 = NamedSharding(dst_mesh, P(_mesh.ROWS))
    p2, nse2 = plan["p2"], plan["nse2"]

    def rewrap(arr):
        by_dev = {s.device: s.data for s in arr.addressable_shards}
        bufs = [by_dev[d] for d in dst_mesh.devices.flat]
        return jax.make_array_from_single_device_arrays(
            (p2, nse2), sh1, bufs)

    d, lr, cc = (rewrap(a) for a in outs)
    return ShardedSparse(d, lr, cc, None, plan["cnt_dst"],
                         rep.row_nnz, rep.shape, dst_mesh,
                         cols_host=rep.cols_host)


@partial(_pjit, static_argnames=("src_mesh", "tr_key", "e0_src", "e0_dst",
                                 "cnt_dst", "m_local1", "m_local2", "nse2",
                                 "overlap"),
         name="rechunk_sparse_panels")
def _sparse_panel_exchange(data, lrows, cols, src_mesh, tr_key, e0_src,
                           e0_dst, cnt_dst, m_local1, m_local2, nse2,
                           overlap="db"):
    """One masked-psum broadcast per source shard (the panel loop, run
    through ``ops/overlap.panel_pipeline`` under the ``DSLIB_OVERLAP``
    router); every device assembles its target shard's (nse2,) buffers
    by static stream addressing — slot i of target shard s' is global
    entry e0_dst[s'] + i, gathered out of whichever source panel's
    stream range covers it.  Pads assemble from the zero accumulator:
    (value 0, row 0, sentinel column 0) by construction."""
    rows_s = src_mesh.shape[_mesh.ROWS]
    cols_s = src_mesh.shape[_mesh.COLS]
    nse1 = data.shape[1]
    steps = rows_s

    def local(d_s, lr_s, cc_s):
        d, lr, cc = d_s[0], lr_s[0], cc_s[0]
        my_r = lax.axis_index(_mesh.ROWS)
        my_c = lax.axis_index(_mesh.COLS)
        my_lin = my_r * cols_s + my_c
        # stream ids fit int32: one relayout moves < 2^31 stored
        # entries (the int32 ceiling of the BCOO indices themselves)
        tr_tab = jnp.asarray(np.asarray(tr_key, np.int32))
        e0s = jnp.asarray(np.asarray(e0_src, np.int32))
        e0d = jnp.asarray(np.asarray(e0_dst, np.int32))
        cnt_tab = jnp.asarray(np.asarray(cnt_dst, np.int32))
        me = tr_tab[my_lin]                 # my TARGET row-rank
        i = lax.iota(jnp.int32, nse2)
        g = e0d[me] + i                     # global stream ids I assemble
        ok_i = i < cnt_tab[me]

        def fetch(t, prev):
            del prev                        # panels broadcast by source rank
            pan = tuple(jnp.where(my_r == t, x, jnp.zeros((), x.dtype))
                        for x in (d, lr, cc))
            return tuple(lax.psum(x, _mesh.ROWS) for x in pan)

        def consume(t, acc, pan):
            pd, plr, pcc = pan
            loc = g - e0s[t]
            ok = ok_i & (loc >= 0) & (g < e0s[t + 1])
            li = jnp.clip(loc, 0, nse1 - 1)
            glr = plr[li] + t * m_local1 - me * m_local2
            ad, alr, acc_cc = acc
            return (jnp.where(ok, pd[li], ad),
                    jnp.where(ok, glr, alr),
                    jnp.where(ok, pcc[li], acc_cc))

        acc0 = tuple(
            lax.pcast(jnp.zeros((nse2,), dt), (_mesh.ROWS, _mesh.COLS),
                      to="varying")
            for dt in (d.dtype, jnp.int32, jnp.int32))
        out = _ov.panel_pipeline(steps, fetch(0, None), fetch, consume,
                                 acc0, _ov.overlapped(overlap))
        return tuple(x[None, :] for x in out)

    return jax.shard_map(
        local, mesh=src_mesh,
        in_specs=(P(_mesh.ROWS), P(_mesh.ROWS), P(_mesh.ROWS)),
        out_specs=(P(_mesh.ROWS, _mesh.COLS),) * 3,
        check_vma=True,
    )(data, lrows, cols)
