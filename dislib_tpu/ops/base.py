"""Shared device kernels used across estimators.

These are the hot inner ops the reference computes per block inside NumPy
`@task`s (e.g. `scipy cdist` in `dislib/cluster/kmeans._partial_sum`);
here each is a single MXU-friendly formulation shared by every caller so
numerical fixes land in one place.
"""

from __future__ import annotations

import jax.numpy as jnp

# the f32-faithful trace scope lives in the precision-policy module (the
# one place compute precision is decided — see ops/precision.py and the
# precision-policy lint); re-exported here for the package-wide import
# path every kernel already uses
from dislib_tpu.ops.precision import precise  # noqa: F401


def distances_sq(a, b, precision=None, use_pallas=False):
    """Pairwise squared euclidean distances (m, k) between rows of `a` (m, d)
    and rows of `b` (k, d): one GEMM + norms (‖a‖² − 2a·bᵀ + ‖b‖²), clamped
    at zero against cancellation.

    Dense ds-array operands return a ds-array and join the dispatch-fusion
    graph (`data/array.py`): the distance GEMM rides the operands' deferred
    chains and dispatches with the first force — under ``DSLIB_EAGER=1`` it
    is one dedicated kernel dispatch instead.

    ``precision=None`` inherits the enclosing scope's matmul precision —
    inside the library's kernels that is the float32-faithful scope set by
    :func:`precise`.  At TPU-native bf16 the cross-term error (~‖x‖²/256)
    dwarfs ε-thresholds — a point's distance to ITSELF comes out ≫ 0,
    breaking radius comparisons (DBSCAN/Daura) — so callers outside a
    ``precise`` kernel should pass an explicit precision.

    ``use_pallas=True`` (raw jax operands only) lowers the whole
    formulation through the ``ops/pallas_kernels`` tile kernel — the
    ``DSLIB_OVERLAP=pallas`` route for the ring/tiled ε-pass inner loop;
    callers thread it as a jit static (``ops/overlap.resolve``)."""
    import importlib
    # deferred import, cycle-free at load; the data package re-exports an
    # `array` FUNCTION, so resolve the module by its dotted name
    _arr = importlib.import_module("dislib_tpu.data.array")
    if isinstance(a, _arr.Array) or isinstance(b, _arr.Array):
        if not (type(a) is _arr.Array and type(b) is _arr.Array):
            raise TypeError(
                "distances_sq over ds-arrays needs BOTH operands as dense "
                f"Arrays, got {type(a).__name__} and {type(b).__name__}")
        return _arr._array_distances(a, b, precision)
    if use_pallas:
        from dislib_tpu.ops import pallas_kernels as _pk
        return _pk.distances_sq(a, b, precision=precision)
    a_sq = jnp.sum(a * a, axis=1, keepdims=True)
    b_sq = jnp.sum(b * b, axis=1)
    cross = jnp.matmul(a, b.T, precision=precision)
    return jnp.maximum(a_sq - 2.0 * cross + b_sq[None, :], 0.0)
