"""Streamed ε-neighborhood passes over row/column tiles.

The quadratic estimators (DBSCAN, Daura — reference:
`dislib/cluster/dbscan` region grids, `dislib/cluster/daura` block-pair
RMSD-count tasks) need per-row reductions over the ε-adjacency relation of
the whole dataset.  The reference partitions *space* into regions because no
CPU worker can hold all pairwise distances; the TPU-native equivalent keeps
the algorithms' semantics but streams the adjacency in (tile × tile) pieces
of the distance GEMM — peak memory is O(tile²) + O(m·n) for the resident
points, never O(m²).  FLOPs are recomputed per pass (distance GEMMs are
MXU-cheap; HBM capacity is the scarce resource).

One primitive covers every consumer: for each row i,

    count_i = |{ j : adj(i,j) ∧ colmask_j }|
    min_i   = min{ vals_j : adj(i,j) ∧ colmask_j }      (sentinel if empty)

where adj(i,j) = (‖x_i − x_j‖² ≤ eps2) ∨ (i = j) — the structural diagonal
keeps every point its own neighbor regardless of fp rounding.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from dislib_tpu.ops.base import distances_sq

# tile edge for the streamed passes (module-level so tests can shrink it)
TILE = 2048


def pad_to_tiles(xv, tile):
    """Zero-pad rows to a tile multiple; returns (padded, n_tiles)."""
    n_tiles = -(-xv.shape[0] // tile)
    return jnp.pad(xv, ((0, n_tiles * tile - xv.shape[0]), (0, 0))), n_tiles


def neigh_count_min(xv, eps2, vals, colmask, sentinel, tile,
                    use_pallas=False):
    """Per-row (count, min) over the ε-adjacency, streamed in tiles.

    xv: (mp, n) with mp % tile == 0.  vals/colmask: (mp,).  Rows are NOT
    masked here — callers mask invalid rows in their own domain.
    ``use_pallas`` routes the tile distance kernel through
    ``ops/pallas_kernels`` (the ``DSLIB_OVERLAP=pallas`` inner-loop
    route; a jit static for the enclosing kernel — the single-device
    tier has no collective to overlap, so this is the only knob that
    applies to it)."""
    mp, n = xv.shape
    nt = mp // tile
    x_tiles = xv.reshape(nt, tile, n)
    offs = jnp.arange(nt, dtype=jnp.int32) * tile
    vals_t = vals.reshape(nt, tile)
    mask_t = colmask.reshape(nt, tile)

    def row_body(_, rx):
        xrow, roff = rx
        row_ids = roff + jnp.arange(tile, dtype=jnp.int32)

        def col_body(acc, cx):
            xcol, coff, v, cm = cx
            col_ids = coff + jnp.arange(tile, dtype=jnp.int32)
            d2 = distances_sq(xrow, xcol, use_pallas=use_pallas)
            adj = ((d2 <= eps2) | (row_ids[:, None] == col_ids[None, :])) \
                & cm[None, :]
            cnt = acc[0] + jnp.sum(adj, axis=1)
            mn = jnp.minimum(acc[1],
                             jnp.min(jnp.where(adj, v[None, :], sentinel),
                                     axis=1))
            return (cnt, mn), None

        acc0 = (jnp.zeros((tile,), jnp.int32),
                jnp.full((tile,), sentinel, vals.dtype))
        (cnt, mn), _ = lax.scan(col_body, acc0, (x_tiles, offs, vals_t, mask_t))
        return None, (cnt, mn)

    _, (counts, mins) = lax.scan(row_body, None, (x_tiles, offs))
    return counts.reshape(mp), mins.reshape(mp)
