"""Masked-psum SpMM — sparse @ dense riding the SUMMA fast path.

The recommender workload's matmul is ``ratings @ factors`` with ratings
at ≤1% density: densifying it costs O(m·n) memory and FLOPs for O(nnz)
information.  This kernel contracts the row-panel-sharded sparse buffers
(:class:`~dislib_tpu.data.sparse.ShardedSparse`) against a canonically
sharded dense operand in ONE jitted ``shard_map``, using exactly the
SUMMA panel-broadcast idiom (``ops/summa.py``): the dense operand's row
dim — the contraction dim, sharded over the mesh 'rows' axis — walks in
panels; each step the owner rank masked-``psum``-broadcasts its panel
along 'rows' (one collective per panel, ``check_vma`` on), and every
device folds the panel into its output block with a gather + segment-sum
over its LOCAL sparse entries (DrJAX's per-shard-update decomposition,
arXiv:2403.07128 — the rows of C are owned where the entries live, so
the only cross-shard movement is the B panel broadcast).

Panel schedule: the loop runs through ``ops/overlap.panel_pipeline`` —
``DSLIB_OVERLAP`` routes it (db = double-buffered default / seq /
pallas, a jit static, schedule-counter-observable as ``spmm:<sched>``),
panel t+1's broadcast issuing under panel t's gather/segment-sum.  All
schedules consume panels in identical order, so they are bit-equal
(``pallas`` pipelines like ``db``: the inner gather/scatter has no
Pallas variant, the ``panel_rechunk`` precedent).

Mixed precision: the per-entry products follow the library policy —
operands round to the policy compute dtype (``ops/precision.to_compute``)
and the segment-sums accumulate at the policy accumulation dtype (f32;
f64 for x64-mode f64 operands under the float32-floor policy) — the
``pdot`` contract expressed over a scatter contraction.

Memory: per device, the live set is the local sparse buffers (O(nnz/p)),
the local B block, the output block, and ONE in-flight panel (two under
db) of B — never a densified A, never a gathered B.  The bench sparse
tier pins this through ``compiled.memory_analysis()``.

Entry locality (the round-17 fix of the measured 0.87× panel-count
inflation): the default ``layout="slots"`` path consumes the
COL-PARTITIONED derived view (``ShardedSparse.panel_view``) — each
shard's live entries re-sorted into per-panel slot ranges, stored with
panel-local columns — so panel t touches ONLY its own contiguous
``nse_p`` slots: total per-entry work is O(nse + steps·quantum) instead
of the legacy masked path's O(steps·nse) re-mask of every entry per
panel.  That makes ``DSLIB_SPMM_PANELS`` a pure memory knob (in-flight
panel bytes ∝ 1/steps) with no arithmetic tax — the arXiv:1304.1835
discipline: move the schedule to the data.  ``layout="masked"`` remains
the view-free fallback (and the comm-probe body); the two layouts are
allclose, not bit-equal (slot regrouping reorders the segment sums),
while WITHIN a layout every overlap schedule stays bit-equal.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from dislib_tpu.ops import overlap as _ov
from dislib_tpu.ops import precision as px
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.utils import profiling as _prof
from dislib_tpu.utils.profiling import profiled_jit as _pjit

__all__ = ["spmm", "spmm_panels", "spmm_steps", "spmm_memory_analysis",
           "spmm_masking_work"]


def _fit_steps(requested, k_pad):
    """Largest step count ≤ requested that divides the padded
    contraction dim (panels must tile it exactly — the dense
    ``_panels_per_rank`` precedent)."""
    for st in range(max(2, min(int(requested), k_pad)), 1, -1):
        if k_pad % st == 0:
            return st
    return 1


def spmm_steps(mesh=None, panels=None) -> int:
    """Panel count of the SpMM schedule: ``DSLIB_SPMM_PANELS`` (default
    4), clamped to ≥ 2 so the double-buffered pipeline has something to
    overlap.  The kernel's own step formula, exposed for the bench
    tier's memory gate (the ``summa_steps`` precedent).

    Unlike SUMMA's lcm-locked panel count, SpMM's panels DECOUPLE from
    the mesh: a panel may span several owner row-ranks (each
    masked-psum assembles the panel from every contributing rank).
    Under the default slot-range layout the panel count is a pure
    MEMORY knob — in-flight panel bytes ∝ 1/steps, per-entry work
    O(nse + steps·quantum) — so the default 4 keeps the panel at 1/4
    of B with no masking tax (the legacy ``layout="masked"`` path paid
    O(steps·nse): every entry re-masked per panel)."""
    del mesh
    if panels is None:
        panels = int(os.environ.get("DSLIB_SPMM_PANELS", "4"))
    return max(2, int(panels))


@partial(_pjit, static_argnames=("mesh", "policy", "overlap", "steps",
                                 "m_local", "comm_only", "layout"),
         name="spmm_panels")
@px.precise
def spmm_panels(data, lrows, cols, counts, bp, mesh, policy, steps,
                m_local, overlap="db", comm_only=False, layout="masked"):
    """C = A @ B: sharded sparse buffers × canonically sharded dense.

    Under ``layout="masked"``, ``data``/``lrows``/``cols``/``counts``
    are the :class:`ShardedSparse` primary buffers (P('rows')-sharded);
    under ``layout="slots"`` they are the col-partitioned
    :class:`~dislib_tpu.data.sparse.SparsePanelView` buffers for THIS
    ``steps`` (panel-major slot ranges, panel-local columns, (p, steps)
    per-panel counts) and each panel step consumes only its own slot
    range.  ``bp`` is the dense padded (K_pad, N_pad) operand under the
    canonical (rows, cols) sharding, zero-pad invariant assumed.
    Returns the (M_pad, N_pad) product at the policy accumulation
    dtype, canonically sharded — M_pad = p · m_local by the
    representation's canonical-row-split invariant, so the output IS a
    valid dense ds-array backing.

    ``comm_only=True`` is the bench tier's broadcast-only variant of the
    SAME program (identical collectives, the gather/segment compute
    replaced by a (1, 1) panel touch) — the t_comm_alone denominator.

    ONE dispatch end to end under every ``overlap`` schedule: the panel
    loop is a ``fori_loop`` inside this single jitted program.
    """
    k_pad = bp.shape[0]
    if k_pad % steps:
        raise ValueError(f"spmm: contraction dim {k_pad} not divisible "
                         f"by {steps} panels")
    if layout not in ("masked", "slots"):
        raise ValueError(f"spmm: unknown layout {layout!r}")
    if layout == "slots" and data.shape[1] % steps:
        raise ValueError(f"spmm: slot-range buffers of width "
                         f"{data.shape[1]} do not tile {steps} panels")
    h = k_pad // steps
    nse = data.shape[1]

    def local(d_s, lr_s, cc_s, cnt_s, b_loc):
        d_e, lr, cc, cnt = d_s[0], lr_s[0], cc_s[0], cnt_s[0]
        my_r = lax.axis_index(_mesh.ROWS)
        k_loc, n_loc = b_loc.shape
        bc = px.to_compute(b_loc, policy)
        if layout == "slots":
            nse_p = nse // steps
            vd = px.to_compute(d_e, policy).reshape(steps, nse_p)
            lrd = lr.reshape(steps, nse_p)
            ccd = cc.reshape(steps, nse_p)
            acc_dt = jnp.promote_types(px.accum_dtype(policy),
                                       jnp.promote_types(vd.dtype, bc.dtype))
        else:
            slot_ok = lax.broadcasted_iota(jnp.int32, (nse,), 0) < cnt
            vc = jnp.where(slot_ok, px.to_compute(d_e, policy),
                           jnp.zeros((), px.compute_dtype(policy)))
            acc_dt = jnp.promote_types(px.accum_dtype(policy),
                                       jnp.promote_types(vc.dtype, bc.dtype))

        def fetch(t, prev):
            del prev                     # broadcast panels slice by step
            # panel t covers global B rows [t·h, t·h + h); EVERY rank
            # contributes the slice it owns (zero elsewhere) and one
            # masked psum assembles the panel — a panel may span
            # several owner ranks, so the step count is a free knob
            i = lax.iota(jnp.int32, h)
            src = t * h + i - my_r * k_loc
            ok = (src >= 0) & (src < k_loc)
            pan = jnp.where(ok[:, None],
                            bc[jnp.clip(src, 0, k_loc - 1)],
                            jnp.zeros((), bc.dtype))
            return lax.psum(pan, _mesh.ROWS)

        if comm_only:
            def consume(t, acc, pan):
                return acc + pan[:1, :1].astype(acc.dtype)

            acc_shape = (1, 1)
        elif layout == "slots":
            def consume(t, acc, pan):
                # panel t's OWN slot range: nse_p entries, not nse — the
                # per-panel count masks the quantum tail (poisoned view
                # slots stay inert), the clip keeps a poisoned column
                # in-bounds for the (zero-weighted) gather
                ok = lax.broadcasted_iota(jnp.int32, (nse_p,), 0) < cnt[t]
                g = pan[jnp.clip(ccd[t], 0, h - 1)]       # (nse_p, n_loc)
                w = jnp.where(ok, vd[t], jnp.zeros((), vd.dtype))
                contrib = (g * w[:, None]).astype(acc.dtype)
                return acc + jax.ops.segment_sum(contrib, lrd[t],
                                                 num_segments=m_local)

            acc_shape = (m_local, n_loc)
        else:
            def consume(t, acc, pan):
                off = t * h              # the panel's global B-row window
                in_pan = (cc >= off) & (cc < off + h)
                g = pan[jnp.clip(cc - off, 0, h - 1)]        # (nse, n_loc)
                w = jnp.where(in_pan, vc, jnp.zeros((), vc.dtype))
                contrib = (g * w[:, None]).astype(acc.dtype)
                return acc + jax.ops.segment_sum(contrib, lr,
                                                 num_segments=m_local)

            acc_shape = (m_local, n_loc)

        acc0 = lax.pcast(jnp.zeros(acc_shape, acc_dt),
                         (_mesh.ROWS, _mesh.COLS), to="varying")
        return _ov.panel_pipeline(steps, fetch(0, None), fetch, consume,
                                  acc0, _ov.overlapped(overlap))

    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(_mesh.ROWS), P(_mesh.ROWS), P(_mesh.ROWS),
                  P(_mesh.ROWS), P(_mesh.ROWS, _mesh.COLS)),
        out_specs=P(_mesh.ROWS, _mesh.COLS),
        check_vma=True,
    )(data, lrows, cols, counts, bp)


def spmm(a, b, *, precision=None, overlap=None, panels=None, layout=None):
    """sparse @ dense as one sharded masked-psum dispatch.

    ``a`` is a :class:`~dislib_tpu.data.sparse.SparseArray`, ``b`` a
    dense ds-array (re-laid-out to the canonical sharding if needed —
    the ``ensure_canonical`` ingest-guard contract).  Returns a dense
    ds-array.  This is a host routing boundary (the SUMMA entry
    precedent): the overlap schedule AND entry layout resolve here so a
    ``DSLIB_OVERLAP`` flip retraces, and the run is observable as
    ``spmm:<sched>`` + ``spmm_layout:<layout>`` schedule counters.
    ``layout`` defaults to ``"slots"`` (the col-partitioned slot-range
    view, cached on the backing); ``"masked"`` forces the legacy
    view-free path."""
    from dislib_tpu.data.array import Array, ensure_canonical
    from dislib_tpu.data.sparse import SparseArray
    if not isinstance(a, SparseArray):
        raise TypeError(f"spmm needs a SparseArray lhs, got {type(a)}")
    if a.shape[1] != b.shape[0]:
        raise ValueError(f"spmm shape mismatch: {a.shape} @ {b.shape}")
    mesh = _mesh.get_mesh()
    rep = a.sharded(mesh)
    b = ensure_canonical(b)
    sched = _ov.resolve(overlap)
    policy = px.resolve(precision)
    lay = "slots" if layout is None else layout
    _prof.count_schedule("spmm", sched)
    _prof.count_schedule("spmm_layout", lay)
    bd = b._data
    steps = _fit_steps(spmm_steps(mesh, panels), bd.shape[0])
    if lay == "slots":
        view = rep.panel_view(steps, bd.shape[0] // steps)
        out = spmm_panels(view.data, view.lrows, view.cols,
                          view.counts_dev, bd, mesh, policy, steps,
                          rep.m_local, overlap=sched, layout="slots")
    else:
        out = spmm_panels(rep.data, rep.lrows, rep.cols, rep.counts_dev,
                          bd, mesh, policy, steps, rep.m_local,
                          overlap=sched, layout="masked")
    return Array(out, (a.shape[0], b.shape[1]),
                 reg_shape=(a.block_size[0], b._reg_shape[1]))


def spmm_comm_probe(a, b, overlap="seq"):
    """Broadcast-only variant of the SAME SpMM program (identical
    collectives, compute replaced by a (1, 1) panel touch) — the bench
    tier's t_comm_alone denominator."""
    from dislib_tpu.data.array import ensure_canonical
    mesh = _mesh.get_mesh()
    rep = a.sharded(mesh)
    b = ensure_canonical(b)
    bd = b._data
    return spmm_panels(rep.data, rep.lrows, rep.cols, rep.counts_dev,
                       bd, mesh, px.resolve(None),
                       _fit_steps(spmm_steps(mesh), bd.shape[0]),
                       rep.m_local, overlap=overlap, comm_only=True)


def spmm_memory_analysis(a, b, *, precision=None, overlap=None,
                         panels=None, layout=None):
    """XLA's own accounting of the compiled SpMM program — the bench
    tier's O(nnz)-scaled peak-live proxy.  Returns input/output/temp
    bytes plus ``temp_vs_dense``: temp as a fraction of what a densified
    A alone would allocate (the densify route's floor) — the number the
    O(nnz) claim gates on.  Analyses the DEFAULT (slot-range) program
    unless ``layout="masked"``."""
    from dislib_tpu.data.array import ensure_canonical, _padded_shape
    mesh = _mesh.get_mesh()
    rep = a.sharded(mesh)
    b = ensure_canonical(b)
    lay = "slots" if layout is None else layout
    steps = _fit_steps(spmm_steps(mesh, panels), b._data.shape[0])
    kw = dict(mesh=mesh, policy=px.resolve(precision), steps=steps,
              m_local=rep.m_local, overlap=_ov.resolve(overlap),
              layout=lay)
    if lay == "slots":
        view = rep.panel_view(steps, b._data.shape[0] // steps)
        ops = (view.data, view.lrows, view.cols, view.counts_dev)
    else:
        ops = (rep.data, rep.lrows, rep.cols, rep.counts_dev)
    pm, pn = _padded_shape(a.shape, _mesh.pad_quantum(mesh))
    dense_a_bytes = 4 * pm * pn
    sparse_bytes = sum(int(x.size) * x.dtype.itemsize
                       for x in (rep.data, rep.lrows, rep.cols))
    res = {"sparse_in_bytes": sparse_bytes,
           "dense_b_bytes": int(b._data.size) * b._data.dtype.itemsize,
           "dense_a_bytes": dense_a_bytes, "temp_bytes": None,
           "temp_vs_dense": None, "steps": steps, "layout": lay}
    try:
        compiled = spmm_panels.lower(*ops, b._data, **kw).compile()
        ma = compiled.memory_analysis()
        temp = int(getattr(ma, "temp_size_in_bytes", 0))
        res["temp_bytes"] = temp
        res["temp_vs_dense"] = round(temp / max(dense_a_bytes, 1), 4)
    except Exception:  # noqa: BLE001 — backend without memory analysis
        pass
    return res


def spmm_masking_work(a, b=None, *, panels=None):
    """Per-dispatch entry-touch accounting of the two SpMM layouts — the
    bench tier's masking-inflation evidence.  ``masked_work`` is what
    the legacy layout executes (every one of the nse slots re-masked on
    every panel: steps·nse); ``slots_work`` is what the slot-range
    layout executes (one nse_p slot range per panel: steps·nse_p ≈
    nnz + steps·quantum).  ``inflation`` = masked/slots — the factor
    the col-partitioned view removes, which is what turns the panel
    count into a pure memory knob."""
    from dislib_tpu.data.array import _padded_shape
    mesh = _mesh.get_mesh()
    rep = a.sharded(mesh)
    k = a.shape[1] if b is None else b.shape[0]
    k_pad = _padded_shape((k, 1), _mesh.pad_quantum(mesh))[0]
    steps = _fit_steps(spmm_steps(mesh, panels), k_pad)
    view = rep.panel_view(steps, k_pad // steps)
    masked = steps * rep.nse
    slots = steps * view.nse_p
    return {"steps": steps, "nse": rep.nse, "nse_p": view.nse_p,
            "masked_work": masked, "slots_work": slots,
            "inflation": round(masked / max(slots, 1), 4)}
