"""Fused KMeans E-step as a Pallas TPU kernel (SURVEY.md §8: "Pallas only
where XLA fusion measurably falls short (candidate: KMeans E-step fused
distance/argmin/scatter-add)").

Why a kernel: the XLA path runs TWO passes over the row-sharded data per
Lloyd iteration — the distance GEMM (reads x) and the per-cluster-sum GEMM
``onehotᵀ @ x`` (reads x again) — so at 1M×100/k=10 the iteration is HBM-
bound at ~2 dataset reads/iter.  This kernel streams each row tile through
VMEM ONCE: distances, masked argmin (as a first-occurrence one-hot),
per-cluster partial sums, counts and inertia all come out of the single
pass, halving HBM traffic.  Accumulation exploits the sequential TPU grid:
every grid step revisits the same output block (constant index_map) and
adds its tile's partials.

The kernel is single-shard compute; `cluster.kmeans._kmeans_fit_fused` runs
it per shard inside `shard_map` and combines partials with `lax.psum` —
identical communication structure to the XLA path.  `centers` must fit VMEM
(k_pad·n_pad floats).  Off-TPU the caller uses the XLA path;
``interpret=True`` runs the same kernel in the interpreter for CPU tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# row-tile height: 64 × the f32 sublane quantum; 512×128 f32 = 256 KB VMEM
TILE_M = 512


def _estep_kernel(mvalid_ref, x_ref, c_ref, sums_ref, counts_ref, stats_ref,
                  *, k, tile_m):
    """One row tile: distances → one-hot argmin → partial (Σx, count, inertia).

    mvalid_ref (SMEM, (1,1)): number of valid rows in THIS shard — rows at or
    beyond it are padding and carry weight 0."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)
        stats_ref[:] = jnp.zeros_like(stats_ref)

    row = i * tile_m + lax.broadcasted_iota(jnp.int32, (tile_m, 1), 0)
    valid = (row < mvalid_ref[0, 0]).astype(jnp.float32)   # (TILE_M, 1)
    x = x_ref[:] * valid                            # zero padded rows: no NaNs
    c = c_ref[:]                                    # (k_pad, n_pad)
    x_sq = jnp.sum(x * x, axis=1, keepdims=True)
    c_sq = jnp.sum(c * c, axis=1)
    cross = lax.dot_general(x, c, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=lax.Precision.HIGHEST)
    d = jnp.maximum(x_sq - 2.0 * cross + c_sq[None, :], 0.0)

    # padded center slots can never win the argmin
    col = lax.broadcasted_iota(jnp.int32, d.shape, 1)
    d = jnp.where(col < k, d, jnp.inf)

    d_min = jnp.min(d, axis=1, keepdims=True)
    # one-hot of the LOWEST index achieving the min (argmin tie-break),
    # without cumsum (not lowerable on TPU Pallas): take the min column
    # index among the argmin ties
    am = jnp.min(jnp.where(d == d_min, col, d.shape[1]), axis=1,
                 keepdims=True)
    onehot = (col == am).astype(jnp.float32) * valid

    sums_ref[:] += lax.dot_general(onehot, x, (((0,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32,
                                   precision=lax.Precision.HIGHEST)
    counts_ref[:] += jnp.sum(onehot, axis=0)[None, :]
    stats_ref[0, 0] += jnp.sum(d_min * valid)


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def fused_estep(x_local, centers_pad, mvalid, k, interpret=False):
    """One fused E-step pass over this shard's rows (m_local, n_pad).

    centers_pad: (k_pad, n_pad); mvalid: int32 (1, 1) — valid-row count.
    Returns (sums (k_pad, n_pad), counts (1, k_pad), inertia scalar)."""
    m_local, n_pad = x_local.shape
    k_pad = centers_pad.shape[0]
    tile = min(TILE_M, m_local)
    grid = pl.cdiv(m_local, tile)
    kernel = functools.partial(_estep_kernel, k=k, tile_m=tile)
    sums, counts, stats = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((tile, n_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, n_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k_pad, n_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k_pad, n_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(mvalid, x_local, centers_pad)
    return sums, counts, stats[0, 0]
