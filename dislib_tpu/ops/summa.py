"""SUMMA — explicitly-scheduled sharded GEMM over the 2-D device mesh.

Reference regime: "Large Scale Distributed Linear Algebra With TPUs"
(arXiv:2112.09017) runs its blocked matmul as SUMMA (Scalable Universal
Matrix Multiplication Algorithm): the (R x C) processor grid steps over
panels of the contraction dimension; at each step the column of the grid
owning the A panel broadcasts it along the mesh rows, the row owning the
B panel broadcasts it along the mesh columns, and every device
accumulates one local GEMM.  Peak per-device memory is the two resident
operand blocks plus the in-flight (panel-width) broadcast pairs plus the
output block — the panel loop is what keeps paper-scale operands (which
exist only sharded) from ever materialising per device.

`math.matmul` routes here when the mesh is genuinely 2-D (both axes > 1)
— the layout where an explicit panel schedule beats leaving the
partitioning to XLA SPMD (which on a 1-D mesh already emits the optimal
all-gather/psum form, so those shapes keep the fusion-graph dot).  The
broadcast is expressed as a masked ``lax.psum`` — the library's standard
provably-replicated collective idiom (``check_vma`` stays ON, the
SURVEY §6 race-detection row), one collective per panel per operand.

Panel schedule (round-13 overlap PR): the loop runs through
``ops/overlap.panel_pipeline``.  Under the default double-buffered
schedule panel t+1's broadcast pair is issued BEFORE panel t's local
GEMM consumes its buffers (prologue fetch, epilogue drain — still ONE
dispatch, the pipeline lives inside this jitted ``shard_map``), so the
latency-hiding scheduler can run the next collective under the current
MXU work; ``overlap="seq"`` restores the strict fetch-then-multiply
chain, and ``overlap="pallas"`` lowers the panel GEMM through
``ops/pallas_kernels``.  All schedules consume panels in identical
order, so ``db`` and ``seq`` are bit-equal (``tests/test_overlap``); the
double buffer's cost is ONE extra in-flight panel pair, never a copy of
an operand (bench overlap tier verifies via XLA memory analysis).

Mixed precision: the local panel GEMMs contract via the library precision
policy (``ops/precision.pdot``) — bf16-compute / f32-accumulate under the
bfloat16 policy, float32-faithful by default.  The accumulator is always
float32.  Zero padding is exact in both dtypes, so the padded contraction
equals the logical one with no masking.
"""

from __future__ import annotations

import math
from functools import partial

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

import jax

from dislib_tpu.ops import overlap as _ov
from dislib_tpu.ops import precision as px
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.utils.profiling import profiled_jit as _pjit


def summa_supported(mesh=None) -> bool:
    """True when the mesh shape makes the explicit SUMMA schedule the
    right algorithm: both mesh axes > 1 (a genuine 2-D processor grid).
    On 1-D meshes XLA's SPMD partitioner already emits the optimal
    collective schedule for a plain sharded dot."""
    r, c = _mesh.mesh_shape(mesh)
    return r > 1 and c > 1


def summa_steps(mesh=None) -> int:
    """Panel count of the SUMMA schedule on ``mesh``: lcm(rows, cols) —
    the panel width is the largest chunk that lives whole on exactly one
    cols-rank of A AND one rows-rank of B.  THE step-count formula of
    :func:`summa_matmul` (the kernel calls this too), exposed so
    per-panel consumers (the bench overlap tier's one-extra-panel memory
    gate) stay anchored to the kernel instead of re-deriving it."""
    r, c = _mesh.mesh_shape(mesh)
    return r * c // math.gcd(r, c)


@partial(_pjit, static_argnames=("mesh", "policy", "overlap", "comm_only"),
         name="summa_matmul")
@px.precise
def summa_matmul(ap, bp, mesh, policy, overlap="db", comm_only=False):
    """C = A @ B over canonically (rows, cols)-sharded padded operands.

    ``ap`` (M_pad, K_pad) and ``bp`` (K_pad, N_pad) must agree on K_pad
    (the caller repads a quantum mismatch) and carry the zero-pad
    invariant.  Returns the (M_pad, N_pad) product, float32
    (the policy's accumulation dtype), canonically sharded.

    ``overlap`` is the resolved panel schedule (``ops/overlap.resolve``
    — callers resolve so the ``DSLIB_OVERLAP`` env flip retraces as a
    static).  ``comm_only=True`` is the bench overlap tier's
    broadcast-only variant of the SAME program: the identical panel
    fetch loop with the GEMMs replaced by a (1, 1) touch of each panel
    (so the collectives survive DCE) — the t_comm_alone denominator of
    the comm-hidden fraction.

    ONE dispatch end to end under every schedule: the panel loop is a
    ``lax.fori_loop`` inside this single jitted program — counter-pinned
    by ``tests/test_precision.py``/``tests/test_overlap.py`` and the
    bench tier's ``dispatches_per_op``.
    """
    nrows = mesh.shape[_mesh.ROWS]
    ncols = mesh.shape[_mesh.COLS]
    k_pad = ap.shape[1]
    if bp.shape[0] != k_pad:
        raise ValueError(
            f"summa: padded contraction dims differ ({k_pad} vs "
            f"{bp.shape[0]}) — repad before the kernel")
    # panel width: lcm(rows, cols) panels (K_pad is a pad_quantum
    # multiple, and pad_quantum = lcm(rows, cols), so this is exact)
    steps = summa_steps(mesh)
    kb = k_pad // steps

    def local(a, b):
        m_loc, ka = a.shape          # A block: (M/R, K/C)
        kb_loc, n_loc = b.shape      # B block: (K/R, N/C)
        my_r = lax.axis_index(_mesh.ROWS)
        my_c = lax.axis_index(_mesh.COLS)
        ac = px.to_compute(a, policy)
        bc = px.to_compute(b, policy)
        # the accumulator matches pdot's output dtype — f32 accumulation,
        # EXCEPT x64-mode f64 operands under the float32-floor policy,
        # which accumulate f64 (a f32 seed would break the fori_loop
        # carry; review-found with a live f64 repro)
        acc_dt = jnp.promote_types(px.accum_dtype(policy),
                                   jnp.promote_types(ac.dtype, bc.dtype))

        def fetch(t, prev):
            del prev                 # broadcast panels slice by step
            off = t * kb
            # broadcast the A panel from its owner cols-rank along 'cols'
            # (masked psum: non-owners contribute exact zeros); offsets
            # are computed identically on every rank, so the slice is
            # in-bounds everywhere and the mask picks the owner's panel
            owner_c = off // ka
            a_pan = lax.dynamic_slice(ac, (0, off - owner_c * ka),
                                      (m_loc, kb))
            a_pan = jnp.where(my_c == owner_c, a_pan,
                              jnp.zeros((), a_pan.dtype))
            a_pan = lax.psum(a_pan, _mesh.COLS)
            # broadcast the B panel from its owner rows-rank along 'rows'
            owner_r = off // kb_loc
            b_pan = lax.dynamic_slice(bc, (off - owner_r * kb_loc, 0),
                                      (kb, n_loc))
            b_pan = jnp.where(my_r == owner_r, b_pan,
                              jnp.zeros((), b_pan.dtype))
            b_pan = lax.psum(b_pan, _mesh.ROWS)
            return a_pan, b_pan

        if comm_only:
            def consume(t, acc, pan):
                a_pan, b_pan = pan
                return acc + a_pan[:1, :1] + b_pan[:1, :1]

            acc_shape = (1, 1)
        else:
            def consume(t, acc, pan):
                a_pan, b_pan = pan
                if overlap == "pallas":
                    from dislib_tpu.ops import pallas_kernels as _pk
                    return acc + _pk.panel_gemm(a_pan, b_pan, policy)
                return acc + px.pdot(a_pan, b_pan, policy)

            acc_shape = (m_loc, n_loc)

        # seed the accumulator as device-varying up front so the fori_loop
        # carry's replication type is stable round over round (the ring
        # kernels' check_vma idiom)
        acc0 = lax.pcast(jnp.zeros(acc_shape, acc_dt),
                         (_mesh.ROWS, _mesh.COLS), to="varying")
        return _ov.panel_pipeline(steps, fetch(0, None), fetch, consume,
                                  acc0, _ov.overlapped(overlap))

    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(_mesh.ROWS, _mesh.COLS), P(_mesh.ROWS, _mesh.COLS)),
        out_specs=P(_mesh.ROWS, _mesh.COLS),
        check_vma=True,
    )(ap, bp)
