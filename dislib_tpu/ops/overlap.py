"""Comm–compute overlap: the library-wide panel-schedule contract.

Every collective panel loop in the library (SUMMA's broadcast/GEMM steps,
``panel_rechunk``'s exchange/assemble steps, the DBSCAN/Daura/neighbors
ring rotate/compute steps) used to be *sequential-phase*: fetch panel t,
THEN consume panel t, so the interconnect and the MXU take turns.  The
locality/overlap discipline of arXiv:1304.1835 (communication-optimal
panel schedules) and the weak-scaling analysis of arXiv:2112.09017 both
put the remaining roofline gap exactly there: at paper scale the
per-panel broadcast time is comparable to the per-panel FLOP time, so a
schedule that hides one under the other claims it back.

:func:`panel_pipeline` is the ONE implementation of that discipline — a
software pipeline with a prologue fetch and an epilogue drain:

- ``overlap=False`` (sequential): each loop body is ``fetch(t);
  consume(t)`` — the collective's result feeds the compute directly, so
  XLA serializes them into one strict chain (the pre-round-13 schedule,
  kept as the always-available fallback).
- ``overlap=True`` (double-buffered, the default): panel t+1's fetch is
  issued BEFORE panel t's consume inside each loop body.  The two are
  data-independent, so the latency-hiding scheduler may run the
  collective concurrently with the GEMM; the loop carry holds exactly
  ONE extra in-flight panel (one panel of live memory, never a copy of
  the operand — verified per kernel via ``compiled.memory_analysis()``
  in the bench overlap tier).

Both schedules consume panels in the identical order with identical ops,
so they are BIT-EQUAL by construction (pinned by ``tests/test_overlap``
over a schedule × mesh × dtype grid), and both remain ONE dispatch — the
pipeline lives inside the kernel's existing jitted ``shard_map``.

:func:`host_pipeline` is the same discipline for the fit drivers' HOST
loops (dispatch → blocking read per step): issue step t+1's async device
work before blocking on step t, one extra step in flight, bit-equal
orders.

Routing (``DSLIB_OVERLAP``, the ``DSLIB_MATMUL_ALGO`` pattern): ``db``
(default) = double-buffered, ``seq`` = sequential-phase, ``pallas`` =
double-buffered with the hot inner compute (SUMMA's panel GEMM, the ring
ε-pass ``distances_sq``) lowered through a Pallas kernel
(``ops/pallas_kernels``) — for backends where XLA refuses to schedule
the overlap out of the plain HLO.  ``pallas`` degrades to ``db`` with a
warning when the backend can't run Pallas; ``seq`` is always available.
The resolved schedule threads through every kernel as a jit STATIC, so
flipping the env var retraces instead of being silently ignored (the
precision-policy contract).
"""

from __future__ import annotations

import os
import warnings

from jax import lax

SCHEDULES = ("db", "seq", "pallas")

_ALIASES = {
    "": "db", "db": "db", "auto": "db", "on": "db", "1": "db",
    "overlap": "db",
    "seq": "seq", "off": "seq", "0": "seq", "sequential": "seq",
    "pallas": "pallas",
}


def resolve(explicit=None) -> str:
    """The overlap-schedule routing rule: an explicit value wins,
    otherwise ``DSLIB_OVERLAP``, otherwise the double-buffered default.
    Returns a canonical schedule name from :data:`SCHEDULES`; ``pallas``
    falls back to ``db`` (with a one-time warning) when the backend
    can't run the Pallas kernels — the sequential schedule never routes
    implicitly: it is the explicit opt-out."""
    raw = explicit if explicit is not None \
        else os.environ.get("DSLIB_OVERLAP", "db")
    key = _ALIASES.get(str(raw).lower())
    if key is None:
        raise ValueError(
            f"unknown overlap schedule {raw!r}: expected one of "
            f"{SCHEDULES} (DSLIB_OVERLAP accepts the same values)")
    if key == "pallas":
        from dislib_tpu.ops import pallas_kernels as _pk
        if not _pk.available():
            _warn_pallas_unavailable()
            return "db"
    return key


# pallas-degradation dedupe registry (the ``__warningregistry__`` shape:
# one key per distinct warning).  Every dispatch site funnels through
# :func:`resolve` with a DIFFERENT caller frame, so stacklevel-keyed
# registry entries — or no dedupe at all — would fire once per site per
# filter reset; this module-owned registry makes it exactly once per
# process, independent of the active warning filters.  Tests clear it to
# re-observe the warning (pinned in tests/test_overlap).
_WARN_REGISTRY: dict = {}


def _warn_pallas_unavailable():
    if "pallas_unavailable" in _WARN_REGISTRY:
        return
    _WARN_REGISTRY["pallas_unavailable"] = 1
    warnings.warn(
        "DSLIB_OVERLAP=pallas requested but the backend can't run the "
        "Pallas kernels — falling back to the double-buffered XLA "
        "schedule ('db')", RuntimeWarning, stacklevel=3)


def overlapped(schedule: str) -> bool:
    """True when ``schedule`` software-pipelines the panel loop (``db``
    and ``pallas``); False for the sequential-phase fallback."""
    if schedule not in SCHEDULES:
        raise ValueError(f"unknown overlap schedule {schedule!r}")
    return schedule != "seq"


def panel_pipeline(steps, pan0, fetch, consume, acc0, overlap):
    """THE shared panel-loop schedule (traced; runs inside the caller's
    jitted ``shard_map``).  Computes::

        acc = consume(steps-1, ... consume(1, consume(0, acc0, pan0),
                                           fetch(1, pan0)) ...)

    ``pan0`` is panel 0 (the prologue fetch — callers produce it with the
    same code path as ``fetch``); ``fetch(t, prev)`` produces panel ``t``
    from panel ``t-1`` (broadcast-style panels ignore ``prev`` and slice
    by ``t``; ring-style panels rotate ``prev``) and is only ever called
    with ``t >= 1``; ``consume(t, acc, pan)`` folds panel ``t`` into the
    accumulator pytree.  ``steps`` is static.

    ``overlap=False``: strict phase alternation — each body fetches its
    own panel then consumes it, so the collective feeds the compute in
    one dependence chain (the sequential baseline).
    ``overlap=True``: software pipeline — each body issues the NEXT
    panel's fetch before consuming the current one (independent ops, so
    the scheduler may overlap them), with consume(0) folded in-loop and
    the last panel drained in an epilogue.  Both orders consume panels
    identically, so the two schedules are bit-equal; the pipelined carry
    holds exactly one extra panel."""
    steps = int(steps)
    if steps <= 0:
        return acc0
    if overlap:
        def body(t, carry):
            acc, pan = carry
            nxt = fetch(t + 1, pan)        # issue panel t+1's collective
            acc = consume(t, acc, pan)     # ... under panel t's compute
            return acc, nxt
        acc, last = lax.fori_loop(0, steps - 1, body, (acc0, pan0))
        return consume(steps - 1, acc, last)   # epilogue drain
    acc = consume(0, acc0, pan0)
    if steps == 1:
        return acc

    def body(t, carry):
        acc, prev = carry
        pan = fetch(t, prev)               # collective ...
        acc = consume(t, acc, pan)         # ... THEN compute (strict chain)
        return acc, pan
    acc, _ = lax.fori_loop(1, steps, body, (acc, pan0))
    return acc


def host_pipeline(steps, fetch, consume, overlap=True):
    """:func:`panel_pipeline`'s discipline lifted to HOST loops — the fit
    drivers' dispatch→read sequences (the CSVM cascade's per-level node
    batches, the forest's per-level snapshot/adoption fetches), where the
    "collective" is an async device dispatch or device→host copy and the
    "compute" is the blocking host read.

    ``fetch(t)`` ISSUES step t's async work (a jitted dispatch, a
    ``copy_to_host_async``) and returns its handle without blocking;
    ``consume(t, handle)`` blocks on the handle and returns the step's
    host result.  ``overlap=True`` issues fetch(t+1) before consume(t) —
    step t's blocking read runs under step t+1's device work, with
    exactly ONE extra step in flight (panel_pipeline's carry discipline,
    so the memory gate transfers unchanged).  ``overlap=False`` is the
    strict fetch-then-consume chain.  Both orders evaluate the same
    ``consume(t, fetch(t))`` pairs in the same order, so the schedules
    are bit-equal by construction.  Returns ``[consume(0, ...), ...,
    consume(steps-1, ...)]``."""
    steps = int(steps)
    out = []
    if steps <= 0:
        return out
    if overlap:
        pending = fetch(0)
        for t in range(1, steps):
            nxt = fetch(t)                 # issue step t (async) ...
            out.append(consume(t - 1, pending))   # ... under t-1's read
            pending = nxt
        out.append(consume(steps - 1, pending))   # epilogue drain
        return out
    for t in range(steps):
        out.append(consume(t, fetch(t)))
    return out
