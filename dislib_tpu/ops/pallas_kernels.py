"""Pallas fallback kernels for the overlap schedules' hot inner loops.

``DSLIB_OVERLAP=pallas`` routes the two FLOP-dominant inner computations
of the panel pipelines — SUMMA's per-panel GEMM and the ring ε-pass's
``distances_sq`` — through explicit Pallas kernels instead of plain HLO.
The escape hatch exists for backends where XLA's scheduler refuses to
hide the panel collective under the previous panel's compute (verified
by the compiled-HLO audit in ``tests/test_overlap``): a Pallas call is
an opaque compute region the latency-hiding scheduler treats as one
unit, so the pipelined loop's independent collective can slide past it.

Contract (mirrors ``ops/precision``): operands are rounded to the
policy's compute dtype, contractions accumulate in the policy's
accumulation dtype, outputs match what the plain-HLO path produces — the
Pallas route changes the SCHEDULE, not the numerics contract (values are
allclose-tested, not bit-tested: a different GEMM tiling reassociates
sums).  On non-TPU backends the kernels run in Pallas interpret mode —
semantically identical, which keeps the whole router testable on the CPU
rig; :func:`available` probes the backend once and the overlap router
degrades ``pallas`` → ``db`` (with a warning) when the probe fails, so
the sequential and double-buffered XLA schedules are always available.

Kernels keep the library's precision-lint contract: no hardcoded compute
dtypes — every cast routes through ``ops/precision`` or derives from a
value's own dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dislib_tpu.ops import precision as px

# grid tile target for the row-tiled kernels: MXU-friendly on chip, and
# a no-op cap for the small interpreted blocks on host rigs
_TILE_ROWS = 128


def _interpret() -> bool:
    """Pallas interpret mode everywhere but real TPUs — same semantics,
    no Mosaic lowering requirement (the CPU-rig test path)."""
    return jax.default_backend() != "tpu"


_AVAILABLE: bool | None = None


def available() -> bool:
    """One cached probe: can this process run a Pallas kernel at all?
    (Import failure, an old jaxlib, or a backend without interpret
    support all land here as False — the overlap router then degrades
    ``pallas`` to the plain double-buffered schedule.)"""
    global _AVAILABLE
    if _AVAILABLE is None:
        try:
            import numpy as np
            x = jnp.ones((8, 4), px.compute_dtype(px.FLOAT32))
            out = panel_gemm(x, x.T, px.FLOAT32)
            _AVAILABLE = bool(abs(float(np.asarray(out)[0, 0]) - 4.0) < 1e-6)
        except Exception:  # noqa: BLE001 — any failure means "not here"
            _AVAILABLE = False
    return _AVAILABLE


def _row_block(m: int) -> int:
    """Largest divisor of ``m`` ≤ the tile target (grid blocks must tile
    the row dim exactly; padded dims are quantum multiples, so this is
    almost always the target itself)."""
    for b in range(min(m, _TILE_ROWS), 0, -1):
        if m % b == 0:
            return b
    return m


def panel_gemm(a, b, policy=px.FLOAT32):
    """``A @ B`` as a row-tiled Pallas kernel — the SUMMA panel GEMM.

    Same numerics contract as :func:`ops.precision.pdot`: operands round
    to the policy compute dtype, the contraction accumulates in the
    policy accumulation dtype (promoted for x64-mode f64 operands under
    the float32-floor policy), output is the accumulation dtype."""
    from jax.experimental import pallas as pl

    a = px.to_compute(a, policy)
    b = px.to_compute(b, policy)
    acc_dt = jnp.promote_types(px.accum_dtype(policy),
                               jnp.promote_types(a.dtype, b.dtype))
    m, k = a.shape
    _, n = b.shape
    bm = _row_block(m)

    def kern(a_ref, b_ref, o_ref):
        # pdot's MXU-precision guarantee must survive the Pallas route —
        # without the explicit precision a f32 FLOAT32-policy call
        # outside a `precise` scope would run the backend default
        o_ref[:, :] = jnp.dot(a_ref[:, :], b_ref[:, :],
                              preferred_element_type=acc_dt,
                              precision=policy.dot_precision)

    return pl.pallas_call(
        kern,
        grid=(m // bm,),
        in_specs=[pl.BlockSpec((bm, k), lambda i: (i, 0)),
                  pl.BlockSpec((k, n), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), acc_dt),
        interpret=_interpret(),
    )(a, b)


def distances_sq(a, b, precision=None):
    """Pairwise squared euclidean distances as a row-tiled Pallas kernel
    — the ring/tiled ε-pass inner loop (``ops/base.distances_sq``'s
    ‖a‖² − 2a·bᵀ + ‖b‖² formulation, clamped at zero against
    cancellation).  Output dtype matches the plain-HLO path (the
    operands' promoted float dtype); ``precision`` threads to the cross
    GEMM exactly as the plain path threads it to ``jnp.matmul`` — the
    Pallas route must not silently drop a caller's explicit MXU
    precision (``None`` inherits the enclosing scope, as there)."""
    from jax.experimental import pallas as pl

    out_dt = jnp.promote_types(a.dtype, b.dtype)
    m, _ = a.shape
    kf, d = b.shape
    bm = _row_block(m)

    def kern(a_ref, b_ref, o_ref):
        av = a_ref[:, :]
        bv = b_ref[:, :]
        cross = jnp.dot(av, bv.T, preferred_element_type=out_dt,
                        precision=precision)
        a_sq = jnp.sum(av * av, axis=1, keepdims=True)
        b_sq = jnp.sum(bv * bv, axis=1)
        o_ref[:, :] = jnp.maximum(a_sq - 2.0 * cross + b_sq[None, :],
                                  jnp.zeros((), out_dt))

    return pl.pallas_call(
        kern,
        grid=(m // bm,),
        in_specs=[pl.BlockSpec((bm, d), lambda i: (i, 0)),
                  pl.BlockSpec((kf, d), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bm, kf), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, kf), out_dt),
        interpret=_interpret(),
    )(a, b)


def node_histogram(node, bx, contrib, n_nodes, n_bins, policy=px.FLOAT32):
    """The tree level's (node, feature, bin) weighted histogram as a
    row-tiled Pallas kernel — the forest fit's scatter-shaped hot loop
    (``trees/decision_tree._node_histogram``) re-expressed as an MXU
    contraction: per feature, the (node, bin) scatter index one-hot
    encodes into a (rows, n_nodes·n_bins) matrix whose transpose-GEMM
    against the per-sample stats IS the histogram.  XLA schedules the
    scatter as a serialized loop; the one-hot GEMM is dense MXU work
    with a (feature, row-tile) grid, the output block revisited across
    row tiles (zero-init at tile 0) so each feature's histogram
    accumulates in-register.

    ``node`` (m,) int32, ``bx`` (m, n) int32 bin ids, ``contrib``
    (m, S) per-sample weighted stats (w·stats — computed by the caller
    so the kernel stays a pure contraction).  Returns (n_nodes, n,
    n_bins, S) at the policy accumulation dtype promoted with the
    contribution dtype — the plain path's f32, f64 for x64-mode f64
    stats.  With integer-representable contributions (Poisson-weight ×
    count stats — the forest's actual regime) the sums are exact, so
    this route is BIT-equal to the XLA scatter, not merely allclose."""
    from jax.experimental import pallas as pl

    contrib = px.to_compute(contrib, policy)
    acc_dt = jnp.promote_types(px.accum_dtype(policy), contrib.dtype)
    m, n = bx.shape
    s = contrib.shape[1]
    nb = int(n_nodes) * int(n_bins)
    bm = _row_block(m)

    def kern(n_ref, b_ref, c_ref, o_ref):
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _init():
            o_ref[...] = jnp.zeros(o_ref.shape, o_ref.dtype)

        idx = n_ref[:] * n_bins + b_ref[:, 0]               # (bm,)
        onehot = (idx[:, None] == jax.lax.broadcasted_iota(
            jnp.int32, (bm, nb), 1)).astype(acc_dt)
        o_ref[0, :, :] += jnp.dot(onehot.T, c_ref[:, :],
                                  preferred_element_type=acc_dt,
                                  precision=policy.dot_precision)

    out = pl.pallas_call(
        kern,
        grid=(n, m // bm),
        in_specs=[pl.BlockSpec((bm,), lambda f, i: (i,)),
                  pl.BlockSpec((bm, 1), lambda f, i: (i, f)),
                  pl.BlockSpec((bm, s), lambda f, i: (i, 0))],
        out_specs=pl.BlockSpec((1, nb, s), lambda f, i: (f, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, nb, s), acc_dt),
        interpret=_interpret(),
    )(node, bx, contrib)
    # (n, n_nodes·n_bins, S) → the scatter path's (n_nodes, n, n_bins, S)
    return out.reshape(n, n_nodes, n_bins, s).transpose(1, 0, 2, 3)


_HIST_AVAILABLE: bool | None = None


def hist_available() -> bool:
    """Cached probe for the histogram kernel specifically: its grid /
    block shapes (tiny lane dims, 1-D blocks) stress different Mosaic
    paths than :func:`panel_gemm`, so the forest router probes THIS
    kernel before trusting it — a failure degrades the fit to the XLA
    scatter, never to a crash mid-growth."""
    global _HIST_AVAILABLE
    if _HIST_AVAILABLE is None:
        try:
            import numpy as np
            node = jnp.asarray([0, 0, 1, 1, 1, 0, 1, 0], jnp.int32)
            bx = jnp.asarray(np.arange(8, dtype=np.int32)[:, None] % 2)
            contrib = jnp.ones((8, 1), px.compute_dtype(px.FLOAT32))
            out = np.asarray(node_histogram(node, bx, contrib, 2, 2))
            _HIST_AVAILABLE = bool(out.shape == (2, 1, 2, 1)
                                   and abs(float(out.sum()) - 8.0) < 1e-6)
        except Exception:  # noqa: BLE001 — any failure means "not here"
            _HIST_AVAILABLE = False
    return _HIST_AVAILABLE
