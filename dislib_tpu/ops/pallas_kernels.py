"""Pallas fallback kernels for the overlap schedules' hot inner loops.

``DSLIB_OVERLAP=pallas`` routes the two FLOP-dominant inner computations
of the panel pipelines — SUMMA's per-panel GEMM and the ring ε-pass's
``distances_sq`` — through explicit Pallas kernels instead of plain HLO.
The escape hatch exists for backends where XLA's scheduler refuses to
hide the panel collective under the previous panel's compute (verified
by the compiled-HLO audit in ``tests/test_overlap``): a Pallas call is
an opaque compute region the latency-hiding scheduler treats as one
unit, so the pipelined loop's independent collective can slide past it.

Contract (mirrors ``ops/precision``): operands are rounded to the
policy's compute dtype, contractions accumulate in the policy's
accumulation dtype, outputs match what the plain-HLO path produces — the
Pallas route changes the SCHEDULE, not the numerics contract (values are
allclose-tested, not bit-tested: a different GEMM tiling reassociates
sums).  On non-TPU backends the kernels run in Pallas interpret mode —
semantically identical, which keeps the whole router testable on the CPU
rig; :func:`available` probes the backend once and the overlap router
degrades ``pallas`` → ``db`` (with a warning) when the probe fails, so
the sequential and double-buffered XLA schedules are always available.

Kernels keep the library's precision-lint contract: no hardcoded compute
dtypes — every cast routes through ``ops/precision`` or derives from a
value's own dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dislib_tpu.ops import precision as px

# grid tile target for the row-tiled kernels: MXU-friendly on chip, and
# a no-op cap for the small interpreted blocks on host rigs
_TILE_ROWS = 128


def _interpret() -> bool:
    """Pallas interpret mode everywhere but real TPUs — same semantics,
    no Mosaic lowering requirement (the CPU-rig test path)."""
    return jax.default_backend() != "tpu"


_AVAILABLE: bool | None = None


def available() -> bool:
    """One cached probe: can this process run a Pallas kernel at all?
    (Import failure, an old jaxlib, or a backend without interpret
    support all land here as False — the overlap router then degrades
    ``pallas`` to the plain double-buffered schedule.)"""
    global _AVAILABLE
    if _AVAILABLE is None:
        try:
            import numpy as np
            x = jnp.ones((8, 4), px.compute_dtype(px.FLOAT32))
            out = panel_gemm(x, x.T, px.FLOAT32)
            _AVAILABLE = bool(abs(float(np.asarray(out)[0, 0]) - 4.0) < 1e-6)
        except Exception:  # noqa: BLE001 — any failure means "not here"
            _AVAILABLE = False
    return _AVAILABLE


def _row_block(m: int) -> int:
    """Largest divisor of ``m`` ≤ the tile target (grid blocks must tile
    the row dim exactly; padded dims are quantum multiples, so this is
    almost always the target itself)."""
    for b in range(min(m, _TILE_ROWS), 0, -1):
        if m % b == 0:
            return b
    return m


def panel_gemm(a, b, policy=px.FLOAT32):
    """``A @ B`` as a row-tiled Pallas kernel — the SUMMA panel GEMM.

    Same numerics contract as :func:`ops.precision.pdot`: operands round
    to the policy compute dtype, the contraction accumulates in the
    policy accumulation dtype (promoted for x64-mode f64 operands under
    the float32-floor policy), output is the accumulation dtype."""
    from jax.experimental import pallas as pl

    a = px.to_compute(a, policy)
    b = px.to_compute(b, policy)
    acc_dt = jnp.promote_types(px.accum_dtype(policy),
                               jnp.promote_types(a.dtype, b.dtype))
    m, k = a.shape
    _, n = b.shape
    bm = _row_block(m)

    def kern(a_ref, b_ref, o_ref):
        # pdot's MXU-precision guarantee must survive the Pallas route —
        # without the explicit precision a f32 FLOAT32-policy call
        # outside a `precise` scope would run the backend default
        o_ref[:, :] = jnp.dot(a_ref[:, :], b_ref[:, :],
                              preferred_element_type=acc_dt,
                              precision=policy.dot_precision)

    return pl.pallas_call(
        kern,
        grid=(m // bm,),
        in_specs=[pl.BlockSpec((bm, k), lambda i: (i, 0)),
                  pl.BlockSpec((k, n), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), acc_dt),
        interpret=_interpret(),
    )(a, b)


def distances_sq(a, b, precision=None):
    """Pairwise squared euclidean distances as a row-tiled Pallas kernel
    — the ring/tiled ε-pass inner loop (``ops/base.distances_sq``'s
    ‖a‖² − 2a·bᵀ + ‖b‖² formulation, clamped at zero against
    cancellation).  Output dtype matches the plain-HLO path (the
    operands' promoted float dtype); ``precision`` threads to the cross
    GEMM exactly as the plain path threads it to ``jnp.matmul`` — the
    Pallas route must not silently drop a caller's explicit MXU
    precision (``None`` inherits the enclosing scope, as there)."""
    from jax.experimental import pallas as pl

    out_dt = jnp.promote_types(a.dtype, b.dtype)
    m, _ = a.shape
    kf, d = b.shape
    bm = _row_block(m)

    def kern(a_ref, b_ref, o_ref):
        av = a_ref[:, :]
        bv = b_ref[:, :]
        cross = jnp.dot(av, bv.T, preferred_element_type=out_dt,
                        precision=precision)
        a_sq = jnp.sum(av * av, axis=1, keepdims=True)
        b_sq = jnp.sum(bv * bv, axis=1)
        o_ref[:, :] = jnp.maximum(a_sq - 2.0 * cross + b_sq[None, :],
                                  jnp.zeros((), out_dt))

    return pl.pallas_call(
        kern,
        grid=(m // bm,),
        in_specs=[pl.BlockSpec((bm, d), lambda i: (i, 0)),
                  pl.BlockSpec((kf, d), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bm, kf), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, kf), out_dt),
        interpret=_interpret(),
    )(a, b)
