from dislib_tpu.ops.base import distances_sq, precise

__all__ = ["distances_sq", "precise"]
