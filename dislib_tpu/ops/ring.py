"""Ring-parallel pairwise-distance kNN over the mesh 'rows' axis.

The reference's NearestNeighbors is "all-pairs block product then pairwise
min-merge" (SURVEY.md §3.3 neighbors row) — every (query-block × fit-block)
pair becomes a task and the COMPSs runtime ships fitted blocks between
workers on demand.  The TPU-native scale-out form is a **ring**: query rows
stay resident on their shard, fitted shards rotate around the 'rows' axis
via `lax.ppermute` (one ICI hop per step), and each step folds the visiting
shard into a running top-k — the same schedule ring attention uses for long
sequences, applied to the library's long axis (rows).  After R steps every
query shard has seen every fitted row; peak memory per device is
O(mq_loc·(k + mf_loc)) and the fitted set never materialises on one chip.

Feature columns stay sharded over 'cols': each step's distance GEMM computes
a per-cols-shard partial and one `psum` over 'cols' completes it, which also
makes the result provably replicated across 'cols' (check_vma stays ON,
SURVEY §6 race-detection row).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from dislib_tpu.ops.base import precise
from dislib_tpu.parallel import mesh as _mesh


@partial(jax.jit, static_argnames=("mesh", "k", "m_fit"))
@precise
def ring_kneighbors(qp, fp, mesh, k, m_fit):
    """(distances², indices) of the k nearest fitted rows per query row.

    qp, fp: canonically sharded padded backings (rows over 'rows', features
    over 'cols').  Returns (d² (mq_pad, k), idx (mq_pad, k) int32), both
    row-sharded; invalid (padded) query rows carry garbage — callers crop.
    """
    nrows = mesh.shape[_mesh.ROWS]

    def local(q, f):
        mf_loc = f.shape[0]
        my = lax.axis_index(_mesh.ROWS)
        # full squared norms (features are col-sharded → psum over 'cols')
        q_sq = lax.psum(jnp.sum(q * q, axis=1), _mesh.COLS)
        f_sq0 = lax.psum(jnp.sum(f * f, axis=1), _mesh.COLS)
        ids0 = my * mf_loc + lax.broadcasted_iota(jnp.int32, (mf_loc,), 0)
        perm = [(i, (i + 1) % nrows) for i in range(nrows)]

        def step(s, carry):
            f_cur, fsq_cur, ids_cur, best_d, best_i = carry
            part = lax.psum(q @ f_cur.T, _mesh.COLS)       # (mq_loc, mf_loc)
            d2 = q_sq[:, None] - 2.0 * part + fsq_cur[None, :]
            d2 = jnp.where(ids_cur[None, :] < m_fit, d2, jnp.inf)
            cand_d = jnp.concatenate([best_d, d2], axis=1)
            cand_i = jnp.concatenate(
                [best_i, jnp.broadcast_to(ids_cur[None, :],
                                          (q.shape[0], mf_loc))], axis=1)
            neg, pos = lax.top_k(-cand_d, k)
            best_d = -neg
            best_i = jnp.take_along_axis(cand_i, pos, axis=1)
            # rotate the fitted shard one hop around the ring (ICI)
            f_cur = lax.ppermute(f_cur, _mesh.ROWS, perm)
            fsq_cur = lax.ppermute(fsq_cur, _mesh.ROWS, perm)
            ids_cur = lax.ppermute(ids_cur, _mesh.ROWS, perm)
            return f_cur, fsq_cur, ids_cur, best_d, best_i

        # the constant top-k seeds become row-varying on the first merge;
        # declaring it up front keeps check_vma provable
        init = (f, f_sq0, ids0,
                lax.pcast(jnp.full((q.shape[0], k), jnp.inf, q.dtype),
                          (_mesh.ROWS,), to="varying"),
                lax.pcast(jnp.full((q.shape[0], k), -1, jnp.int32),
                          (_mesh.ROWS,), to="varying"))
        _, _, _, best_d, best_i = lax.fori_loop(0, nrows, step, init)
        return jnp.maximum(best_d, 0.0), best_i

    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(_mesh.ROWS, _mesh.COLS), P(_mesh.ROWS, _mesh.COLS)),
        out_specs=(P(_mesh.ROWS, None), P(_mesh.ROWS, None)),
        check_vma=True,
    )(qp, fp)
