"""Ring-parallel pairwise-distance kNN over the mesh 'rows' axis.

The reference's NearestNeighbors is "all-pairs block product then pairwise
min-merge" (SURVEY.md §3.3 neighbors row) — every (query-block × fit-block)
pair becomes a task and the COMPSs runtime ships fitted blocks between
workers on demand.  The TPU-native scale-out form is a **ring**: query rows
stay resident on their shard, fitted shards rotate around the 'rows' axis
via `lax.ppermute` (one ICI hop per step), and each step folds the visiting
shard into a running top-k — the same schedule ring attention uses for long
sequences, applied to the library's long axis (rows).  After R steps every
query shard has seen every fitted row; peak memory per device is
O(mq_loc·(k + mf_loc)) and the fitted set never materialises on one chip.

Feature columns stay sharded over 'cols': each step's distance GEMM computes
a per-cols-shard partial and one `psum` over 'cols' completes it, which also
makes the result provably replicated across 'cols' (check_vma stays ON,
SURVEY §6 race-detection row).

Rotate/compute schedule (round-13 overlap PR): both ring kernels run their
step loop through ``ops/overlap.panel_pipeline``.  Under the default
double-buffered schedule the NEXT shard's ``ppermute`` hops are issued
before the current shard's distance fold consumes it, so the rotation
rides the ICI while the MXU folds — bit-equal to the sequential
rotate-then-compute schedule (``overlap="seq"``), still one jitted
program.  ``overlap="pallas"`` additionally lowers the fold's distance
kernel through ``ops/pallas_kernels``.  The ``overlap`` argument is a
jit static resolved by the CALLERS via ``ops/overlap.resolve`` (the
estimator tier pickers), so a ``DSLIB_OVERLAP`` flip retraces; both
kernels stay plain ``jax.jit`` (NOT profiled) because they are invoked
from inside other jitted programs — the dispatch-count boundary is their
outer kernel.  ``comm_only=True`` builds the rotation-only variant of
the same program (the bench overlap tier's t_comm_alone denominator).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from dislib_tpu.ops import overlap as _ov
from dislib_tpu.ops.base import distances_sq, precise
from dislib_tpu.parallel import mesh as _mesh


def _rotate(perm, *arrays):
    """One ring hop of every carried array (the panel fetch)."""
    return tuple(lax.ppermute(a, _mesh.ROWS, perm) for a in arrays)


@partial(jax.jit, static_argnames=("mesh", "k", "m_fit", "overlap",
                                   "comm_only"))
@precise
def ring_kneighbors(qp, fp, mesh, k, m_fit, overlap="db", comm_only=False):
    """(distances², indices) of the k nearest fitted rows per query row.

    qp, fp: canonically sharded padded backings (rows over 'rows', features
    over 'cols').  Returns (d² (mq_pad, k), idx (mq_pad, k) int32), both
    row-sharded; invalid (padded) query rows carry garbage — callers crop.
    """
    nrows = mesh.shape[_mesh.ROWS]

    def local(q, f):
        mf_loc = f.shape[0]
        my = lax.axis_index(_mesh.ROWS)
        # full squared norms (features are col-sharded → psum over 'cols')
        q_sq = lax.psum(jnp.sum(q * q, axis=1), _mesh.COLS)
        f_sq0 = lax.psum(jnp.sum(f * f, axis=1), _mesh.COLS)
        ids0 = my * mf_loc + lax.broadcasted_iota(jnp.int32, (mf_loc,), 0)
        perm = [(i, (i + 1) % nrows) for i in range(nrows)]

        def fetch(t, prev):
            return _rotate(perm, *prev)     # one ICI hop per carried array

        pan0 = (f, f_sq0, ids0)

        if comm_only:
            def consume(t, acc, pan):
                f_cur, fsq_cur, ids_cur = pan
                return (acc + f_cur[:1, :1] + fsq_cur[:1][None]
                        + ids_cur[:1][None].astype(acc.dtype))

            acc0 = lax.pcast(jnp.zeros((1, 1), q.dtype),
                             (_mesh.ROWS, _mesh.COLS), to="varying")
            return _ov.panel_pipeline(nrows, pan0, fetch, consume, acc0,
                                      _ov.overlapped(overlap))

        def consume(t, carry, pan):
            best_d, best_i = carry
            f_cur, fsq_cur, ids_cur = pan
            if overlap == "pallas":
                from dislib_tpu.ops import pallas_kernels as _pk
                part = lax.psum(_pk.panel_gemm(q, f_cur.T), _mesh.COLS)
            else:
                part = lax.psum(q @ f_cur.T, _mesh.COLS)   # (mq_loc, mf_loc)
            d2 = q_sq[:, None] - 2.0 * part + fsq_cur[None, :]
            d2 = jnp.where(ids_cur[None, :] < m_fit, d2, jnp.inf)
            cand_d = jnp.concatenate([best_d, d2], axis=1)
            cand_i = jnp.concatenate(
                [best_i, jnp.broadcast_to(ids_cur[None, :],
                                          (q.shape[0], mf_loc))], axis=1)
            neg, pos = lax.top_k(-cand_d, k)
            best_d = -neg
            best_i = jnp.take_along_axis(cand_i, pos, axis=1)
            return best_d, best_i

        # the constant top-k seeds become row-varying on the first merge;
        # declaring it up front keeps check_vma provable
        acc0 = (lax.pcast(jnp.full((q.shape[0], k), jnp.inf, q.dtype),
                          (_mesh.ROWS,), to="varying"),
                lax.pcast(jnp.full((q.shape[0], k), -1, jnp.int32),
                          (_mesh.ROWS,), to="varying"))
        best_d, best_i = _ov.panel_pipeline(nrows, pan0, fetch, consume,
                                            acc0, _ov.overlapped(overlap))
        return jnp.maximum(best_d, 0.0), best_i

    out_specs = P(_mesh.ROWS, _mesh.COLS) if comm_only \
        else (P(_mesh.ROWS, None), P(_mesh.ROWS, None))
    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(_mesh.ROWS, _mesh.COLS), P(_mesh.ROWS, _mesh.COLS)),
        out_specs=out_specs,
        check_vma=True,
    )(qp, fp)


# ---------------------------------------------------------------------------
# ring ε-neighborhood pass (DBSCAN / Daura scale-out)
# ---------------------------------------------------------------------------

# inner streaming tile edge within one ring step (per-device memory is
# O(tile²) for the distance piece; module-level so tests can shrink it)
RING_TILE = 2048


def ring_auto(flag, mesh, large):
    """Shared ring-routing policy: ``flag`` True forces the ring schedule,
    False forces it off, None auto-picks it when the mesh has >1 row shard
    and the caller's own size predicate ``large`` holds (each consumer owns
    its threshold semantics)."""
    if flag is not None:
        return bool(flag)
    return mesh.shape[_mesh.ROWS] > 1 and large


@partial(jax.jit, static_argnames=("mesh", "overlap", "comm_only"))
@precise
def ring_neigh_count_min(xp, eps2, vals, colmask, sentinel, mesh,
                         overlap="db", comm_only=False):
    """Per-row (ε-neighbor count, min over neighbor vals) of a row-sharded
    dataset against itself — `ops/tiled.neigh_count_min` distributed over
    the mesh 'rows' axis.

    Schedule: features are all-gathered over 'cols' once (contracting-dim
    gather, paid once per call), then each device's row shard stays resident
    while (shard, vals, colmask, ids) rotate around the 'rows' ring via
    ppermute; each visit streams in (tile × tile) distance pieces so peak
    memory per device is O(tile²).  adj(i,j) = (d²(i,j) ≤ eps2 ∨ i = j) ∧
    colmask_j, exactly the single-device contract.  Under the default
    double-buffered ``overlap`` the next hop's ppermutes are issued before
    the visiting shard's tile pass consumes it (see module docstring).

    xp (mp, np) canonically sharded; vals/colmask (mp,) row-sharded.
    Returns (counts int32 (mp,), mins (mp,) of vals.dtype), row-sharded.
    """
    nrows = mesh.shape[_mesh.ROWS]

    def local(x, v, cm):
        x = lax.all_gather(x, _mesh.COLS, axis=1, tiled=True)  # (m_loc, np)
        m_loc = x.shape[0]
        my = lax.axis_index(_mesh.ROWS)
        row_ids = my * m_loc + lax.broadcasted_iota(jnp.int32, (m_loc,), 0)
        perm = [(i, (i + 1) % nrows) for i in range(nrows)]
        # pad the shard to a tile multiple (shapes are static in-shard):
        # pad rows carry id −1 and colmask False, so they can never be
        # neighbors of anything; their own outputs are cropped below
        tile = min(RING_TILE, m_loc)
        nt = -(-m_loc // tile)
        m_t = nt * tile
        x = jnp.pad(x, ((0, m_t - m_loc), (0, 0)))
        row_ids = jnp.pad(row_ids, (0, m_t - m_loc), constant_values=-1)
        v = jnp.pad(v, (0, m_t - m_loc), constant_values=sentinel)
        cm = jnp.pad(cm, (0, m_t - m_loc), constant_values=False)

        def pair_pass(xc, idc, vc, cmc, cnt, mn):
            """Accumulate (cnt, mn) of local rows vs the visiting shard."""
            x_t = x.reshape(nt, tile, x.shape[1])
            r_t = row_ids.reshape(nt, tile)
            xc_t = xc.reshape(nt, tile, x.shape[1])
            id_t = idc.reshape(nt, tile)
            v_t = vc.reshape(nt, tile)
            cm_t = cmc.reshape(nt, tile)
            cnt_t = cnt.reshape(nt, tile)
            mn_t = mn.reshape(nt, tile)

            def row_body(_, rx):
                xrow, rid, c0, m0 = rx

                def col_body(acc, cx):
                    xcol, cid, vv, cmm = cx
                    d2 = distances_sq(xrow, xcol,
                                      use_pallas=(overlap == "pallas"))
                    adj = ((d2 <= eps2)
                           | (rid[:, None] == cid[None, :])) & cmm[None, :]
                    c_acc = acc[0] + jnp.sum(adj, axis=1)
                    m_acc = jnp.minimum(
                        acc[1], jnp.min(jnp.where(adj, vv[None, :], sentinel),
                                        axis=1))
                    return (c_acc, m_acc), None

                (c_out, m_out), _ = lax.scan(col_body, (c0, m0),
                                             (xc_t, id_t, v_t, cm_t))
                return None, (c_out, m_out)

            _, (cnt_o, mn_o) = lax.scan(row_body, None,
                                        (x_t, r_t, cnt_t, mn_t))
            return cnt_o.reshape(m_t), mn_o.reshape(m_t)

        def fetch(t, prev):
            return _rotate(perm, *prev)

        pan0 = (x, row_ids, v, cm)

        if comm_only:
            def consume(t, acc, pan):
                xc, idc, vc, cmc = pan
                return (acc + xc[:1, :1] + vc[:1][None]
                        + idc[:1][None].astype(acc.dtype)
                        + cmc[:1][None].astype(acc.dtype))

            acc0 = lax.pcast(jnp.zeros((1, 1), x.dtype),
                             (_mesh.ROWS, _mesh.COLS), to="varying")
            return _ov.panel_pipeline(nrows, pan0, fetch, consume, acc0,
                                      _ov.overlapped(overlap))

        def consume(t, acc, pan):
            xc, idc, vc, cmc = pan
            cnt, mn = pair_pass(xc, idc, vc, cmc, acc[0], acc[1])
            return cnt, mn

        acc0 = (lax.pcast(jnp.zeros((m_t,), jnp.int32),
                          (_mesh.ROWS, _mesh.COLS), to="varying"),
                lax.pcast(jnp.full((m_t,), sentinel, v.dtype),
                          (_mesh.ROWS, _mesh.COLS), to="varying"))
        cnt, mn = _ov.panel_pipeline(nrows, pan0, fetch, consume, acc0,
                                     _ov.overlapped(overlap))
        cnt, mn = cnt[:m_loc], mn[:m_loc]      # crop the tile pad
        # every rank in a mesh row computes identical results from the
        # all-gathered features; pmax makes that invariance provable so
        # check_vma stays ON
        cnt = lax.pmax(cnt, _mesh.COLS)
        mn = lax.pmin(mn, _mesh.COLS)
        return cnt, mn

    out_specs = P(_mesh.ROWS, _mesh.COLS) if comm_only \
        else (P(_mesh.ROWS), P(_mesh.ROWS))
    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(_mesh.ROWS, _mesh.COLS), P(_mesh.ROWS), P(_mesh.ROWS)),
        out_specs=out_specs,
        check_vma=True,
    )(xp, vals, colmask)
