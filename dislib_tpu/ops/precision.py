"""Mixed-precision policy — the ONE place library kernels get compute dtypes.

The paper regime ("Large Scale Distributed Linear Algebra With TPUs",
arXiv:2112.09017) runs the MXU at its native bf16 input throughput
(~2x f32 per chip; this rig's r05 capture measured 2.6x) while
accumulating partial sums in float32 — "bf16-compute / f32-accumulate".
dislib_tpu exposes that as a *policy*:

- ``float32`` (default): operands contract at float32-faithful precision
  (``'highest'`` — on TPU a 6-pass bf16 decomposition, exactly the
  pre-policy behavior of every library kernel).
- ``bfloat16``: GEMM operands are rounded to bfloat16 and contracted with
  float32 accumulation (``preferred_element_type``).  Input rounding is
  2^-9 relative per operand, so results carry ~0.2-2% relative error —
  the documented bounds live in :data:`ERROR_BOUNDS` and are asserted by
  ``tests/test_precision.py``.

Selection order: an explicit ``precision=`` kwarg on the public entry
points (``math.matmul``, ``math.qr``, ``math.polar``, ``math.svd``,
``tsqr``, ``random_svd``, ``lanczos_svd``, ``PCA``) wins; otherwise the
``DSLIB_MATMUL_PRECISION`` env var; otherwise ``float32``.  Policies are
hashable named tuples and ride the jit cache key as static arguments, so
flipping the env var retraces instead of being silently ignored (the
``_use_cholqr`` precedent).

Scope of a policy inside composite factorisations (QR, tsQR, randomized
SVD, block-Jacobi SVD, Lanczos, PCA): the FLOP-dominant applied GEMMs
(panel updates, Q assembly/application, power-iteration products,
Gram/scatter products, Jacobi pair updates) follow the policy; the small
dense factorisations (Householder QR of a panel, Cholesky of a Gram, the
(sketch x sketch) or (2b x 2b) SVD) are ALWAYS pinned float32 — rounding a factorisation's interior would destroy its
backward stability for no meaningful FLOP win.  Pure-GEMM kernels
(matmul, SUMMA, Newton-Schulz polar, distances) follow the policy end to
end.

Lint contract (``tests/test_precision_lint.py``): library kernels under
``dislib_tpu/{math,ops,decomposition}`` may not hardcode compute dtypes
(``.astype(jnp.float32)`` and friends), call
``jax.default_matmul_precision`` directly, or pass literal ``precision=``
strings to dots — they route through :func:`f32` / :func:`to_compute` /
:func:`pdot` / :func:`precise` here, so a precision decision is a greppable
one-module audit instead of a per-kernel archaeology dig.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp


class Policy(NamedTuple):
    """A compute/accumulate precision pair for library GEMMs.

    Hashable (strings only) so it can thread through ``jax.jit`` static
    arguments — a kernel traced under one policy retraces under another.
    """

    name: str             # canonical policy name ("float32" | "bfloat16")
    compute: str          # dtype operands are rounded to for GEMM passes
    accum: str            # accumulation dtype (always float32)
    dot_precision: str | None  # lax precision for f32-operand dots


FLOAT32 = Policy("float32", "float32", "float32", "highest")
BFLOAT16 = Policy("bfloat16", "bfloat16", "float32", None)

_POLICIES = {"float32": FLOAT32, "bfloat16": BFLOAT16}
_ALIASES = {
    "float32": "float32", "f32": "float32", "fp32": "float32",
    "highest": "float32",
    "bfloat16": "bfloat16", "bf16": "bfloat16",
}

# Documented relative-error bounds of the bfloat16 policy vs the float32
# reference, asserted by tests/test_precision.py and quoted in the user
# guide.  bf16 unit roundoff is 2^-9 ~= 2e-3 per operand; with f32
# accumulation the dominant term is input rounding, so well-conditioned
# results sit at a few 1e-3 and the bounds below carry ~4-8x headroom for
# shape/conditioning spread (measured on this rig across the test grid).
ERROR_BOUNDS = {
    # max_ij |C - C_ref| / (||A||_F ||B||_F / sqrt(k)) — normalized entry error
    ("matmul", "bfloat16"): 2e-2,
    # ||Q^T Q - I||_max of the assembled Q (policy applies to panel
    # updates + Q assembly; panel factorisations stay f32)
    ("qr_orth", "bfloat16"): 4e-2,
    # ||A - Q R||_F / ||A||_F
    ("qr_resid", "bfloat16"): 2e-2,
    ("tsqr_orth", "bfloat16"): 4e-2,
    ("tsqr_resid", "bfloat16"): 2e-2,
    # top singular values, relative: |s - s_ref| / s_ref[0]
    ("randomsvd_values", "bfloat16"): 2e-2,
    # the GKL recurrence AMPLIFIES matvec rounding (each step feeds the
    # next), so Lanczos carries a wider bound than the one-shot sketches;
    # prefer random_svd when running the bfloat16 policy
    ("lanczos_values", "bfloat16"): 1e-1,
    # polar orthogonality floor: ||U^T U - I||_max (Newton-Schulz is
    # self-correcting down to the compute dtype's roundoff)
    ("polar_orth", "bfloat16"): 5e-2,
    ("polar_resid", "bfloat16"): 3e-2,
    # block-Jacobi SVD (round-11 satellite): policy on the pair-update
    # GEMMs only; sweeps re-orthogonalize each round, so errors sit at
    # the per-update rounding (~2-8e-3 measured across the test grid),
    # not an accumulation of it.  values: |s - s_ref| / s_ref[0];
    # resid: ||A - U S Vt||_F / ||A||_F
    ("svd_values", "bfloat16"): 2e-2,
    ("svd_resid", "bfloat16"): 4e-2,
    # float32 policy: the f32-faithful reference itself; listed so the
    # test grid exercises both policies through one table
    ("matmul", "float32"): 1e-6,
    ("qr_orth", "float32"): 1e-4,
    ("qr_resid", "float32"): 1e-5,
    ("tsqr_orth", "float32"): 1e-4,
    ("tsqr_resid", "float32"): 1e-5,
    ("randomsvd_values", "float32"): 1e-4,
    # Lanczos at float32 is TRUNCATION-dominated (k singular values from
    # ~2k GKL steps), not rounding-dominated — the bound reflects the
    # solver's approximation error at the tested depth, same as the
    # reference's tolerance semantics
    ("lanczos_values", "float32"): 1e-2,
    ("polar_orth", "float32"): 1e-4,
    ("polar_resid", "float32"): 1e-4,
    ("svd_values", "float32"): 1e-4,
    ("svd_resid", "float32"): 1e-4,
}


def resolve(precision=None) -> Policy:
    """The library's ONE precision-selection rule.

    ``precision`` may be a :class:`Policy`, a name/alias (``"float32"``,
    ``"f32"``, ``"bfloat16"``, ``"bf16"``), or None — None reads
    ``DSLIB_MATMUL_PRECISION`` (same aliases) and falls back to float32.
    """
    if precision is None:
        precision = os.environ.get("DSLIB_MATMUL_PRECISION") or "float32"
    if isinstance(precision, Policy):
        return precision
    key = _ALIASES.get(str(precision).lower())
    if key is None:
        raise ValueError(
            f"unknown precision policy {precision!r}: expected one of "
            f"{sorted(set(_ALIASES))} (or a dislib_tpu.ops.precision.Policy)")
    return _POLICIES[key]


def of_name(name: str) -> Policy:
    """Policy from its canonical name (fused-instruction statics store the
    name, not the tuple, to keep program cache keys minimal)."""
    return _POLICIES[name]


def compute_dtype(policy: Policy):
    return jnp.dtype(policy.compute)


def accum_dtype(policy: Policy):
    return jnp.dtype(policy.accum)


def to_compute(x, policy: Policy = FLOAT32):
    """Round an operand to the policy's GEMM compute dtype (the ONE place
    library kernels cast operand precision).  Zero is exact in every
    policy dtype, so the pad-and-mask invariant survives the cast.

    The float32 policy is a *floor*, not a ceiling: float64 operands on
    an x64-mode rig pass through untouched (narrowing full-precision user
    data is never implicit — the ``ds.array`` dtype-policy precedent).
    The bfloat16 policy is an explicit opt-in to reduced precision and
    rounds every float input, float64 included."""
    dt = jnp.dtype(policy.compute)
    if policy.name == "float32" and x.dtype == jnp.float64:
        return x
    return x if x.dtype == dt else x.astype(dt)


def f32(x):
    """Pin an operand to exactly float32 — the ingest cast for
    panel/small-matrix factorisations that stay f32 under EVERY policy
    (see module docstring), and for integral inputs entering float
    kernels.  Unlike :func:`to_compute`'s float32 policy this IS a
    ceiling: the f32 kernels' shapes/numerics assume it."""
    dt = jnp.dtype(jnp.float32)
    return x if x.dtype == dt else x.astype(dt)


def pdot(a, b, policy: Policy = FLOAT32):
    """THE library GEMM: operands rounded to the policy compute dtype,
    contracted with float32 accumulation.

    float32 policy: ``precision='highest'`` — bit-identical to the
    pre-policy kernels (f32 @ f32 at float32-faithful precision).
    bfloat16 policy: operands round to bf16 and the dot accumulates f32
    via ``preferred_element_type`` — on the MXU that is the native
    single-pass bf16 systolic contraction, on CPU a bf16-input GEMM
    (measurably faster on this rig: 2.3x in the r08 smoke capture).
    Output dtype is the accumulation dtype (float32; float64 on x64-mode
    float64 operands under the float32-floor policy).  ``jnp.matmul``
    semantics, so batched (3-D) operands contract per batch."""
    a = to_compute(a, policy)
    b = to_compute(b, policy)
    acc = jnp.promote_types(jnp.dtype(policy.accum),
                            jnp.promote_types(a.dtype, b.dtype))
    return jnp.matmul(a, b, precision=policy.dot_precision,
                      preferred_element_type=acc)


def peinsum(subscripts, a, b, policy: Policy = FLOAT32):
    """The library's policy-routed einsum — :func:`pdot` for contractions
    a plain matmul can't spell (the block-Jacobi SVD's batched pair
    updates).  Operands round to the policy compute dtype, the
    contraction accumulates float32 (``preferred_element_type``), output
    is the accumulation dtype — same contract as :func:`pdot`."""
    a = to_compute(a, policy)
    b = to_compute(b, policy)
    acc = jnp.promote_types(jnp.dtype(policy.accum),
                            jnp.promote_types(a.dtype, b.dtype))
    return jnp.einsum(subscripts, a, b, precision=policy.dot_precision,
                      preferred_element_type=acc)


def precise(fn):
    """Trace-time float32-faithful matmul scope for library kernels.

    TPU matmuls default to bfloat16 passes; the reference's per-block
    kernels are NumPy float64, so dislib_tpu's own GEMMs run
    float32-faithful ('highest') unless a caller explicitly opts a kernel
    into the bfloat16 policy via ``precision=``/:func:`pdot`.  Scoped
    here (under each kernel's ``jax.jit``, active during tracing) rather
    than via the global ``jax_default_matmul_precision`` flag so user
    code's own precision configuration is never touched.  An explicit
    ``precision=`` on a dot (what :func:`pdot` passes) overrides the
    scope, so policy-routed GEMMs inside a ``precise`` kernel behave per
    their policy while every other dot stays f32-faithful."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.default_matmul_precision("highest"):
            return fn(*args, **kwargs)
    return wrapped
