"""Transient-failure retry policy (SURVEY §6 failure-detection row).

The reference's COMPSs runtime resubmits failed tasks transparently; the
TPU-native analogs of "a task failed for environmental reasons" are a
coordinator that is not up yet (`jax.distributed.initialize` racing the
head node), a flaky shared filesystem under the ingest loaders, and the
occasional transient host↔device transfer error.  :class:`Retry` retries
exactly those — bounded attempts, exponential backoff with deterministic
seedable jitter, an optional wall-clock deadline — and re-raises anything
classified fatal (shape errors, missing files, user bugs) immediately.

Classification is conservative: a retried fatal error wastes attempts at
worst, but a non-retried transient kills a job that would have survived,
so network/IO error *types* are transient by default and everything else
must match a known transient *message* (gRPC status text et al.).
"""

from __future__ import annotations

import os
import random
import re
import time

__all__ = ["Retry", "retry_call", "is_transient_error"]

# gRPC status text and kernel-ish error strings that mark an exception of
# an otherwise-opaque type (RuntimeError, XlaRuntimeError) as transient
_TRANSIENT_MSG = re.compile(
    r"(?i)\b(unavailable|deadline.?exceeded|timed.?out"
    r"|connection (reset|refused|closed|aborted)|broken pipe|socket closed"
    r"|temporarily unavailable|resource.?exhausted|try again|heartbeat"
    r"|failed to connect)")

# OSError subclasses that mean "the request itself is wrong", not "the
# environment hiccuped" — never retried
_FATAL_OSERRORS = (FileNotFoundError, IsADirectoryError, NotADirectoryError,
                   PermissionError, FileExistsError)


def is_transient_error(exc: BaseException) -> bool:
    """Default transient-vs-fatal classification (see module docstring)."""
    from dislib_tpu.runtime.preemption import Preempted
    from dislib_tpu.runtime.coord import CoordinationTimeout, RankDead
    if isinstance(exc, (Preempted, KeyboardInterrupt, SystemExit)):
        return False                      # control flow, not a failure
    if isinstance(exc, RankDead):
        return False                      # confirmed death: retrying cannot
        #                                   resurrect it — heal via capacity
    if isinstance(exc, CoordinationTimeout):
        return True                       # slow peer / torn file: retry
    if isinstance(exc, (ConnectionError, TimeoutError, InterruptedError,
                        BlockingIOError)):
        return True
    if isinstance(exc, OSError):
        return not isinstance(exc, _FATAL_OSERRORS)
    if isinstance(exc, (ValueError, TypeError, KeyError, IndexError,
                        AssertionError, ArithmeticError)):
        return False                      # user/programming errors
    return bool(_TRANSIENT_MSG.search(str(exc)))


class Retry:
    """Bounded-retry policy with exponential backoff + jitter.

    Parameters
    ----------
    attempts : int, default 3 — total tries (1 = no retry).
    backoff : float, default 0.5 — first retry delay, seconds; doubles per
        attempt up to ``max_backoff``.
    max_backoff : float, default 30.0.
    jitter : float, default 0.25 — each delay is scaled by
        ``1 + jitter·u`` with ``u ~ U[0, 1)``; seed it (``seed=``) for a
        deterministic schedule (the fault-injection tests do).
    deadline : float or None — wall-clock budget in seconds; once the next
        sleep would overrun it, the last error re-raises.
    classify : callable(exc) -> bool | None — overrides the default
        transient classification; ``None`` falls through to the default.
    sleep : callable(seconds) — injection point for tests.
    """

    def __init__(self, attempts: int = 3, backoff: float = 0.5,
                 max_backoff: float = 30.0, jitter: float = 0.25,
                 deadline: float | None = None, classify=None, seed=None,
                 sleep=time.sleep):
        if attempts < 1:
            raise ValueError("attempts must be >= 1")
        self.attempts = int(attempts)
        self.backoff = float(backoff)
        self.max_backoff = float(max_backoff)
        self.jitter = float(jitter)
        self.deadline = None if deadline is None else float(deadline)
        self.classify = classify
        self._rng = random.Random(seed)
        self._sleep = sleep

    @classmethod
    def from_env(cls, **defaults) -> "Retry":
        """Policy with env overrides — the launch-script knob surface:
        ``DSLIB_RETRY_ATTEMPTS`` / ``DSLIB_RETRY_BACKOFF`` /
        ``DSLIB_RETRY_MAX_BACKOFF`` / ``DSLIB_RETRY_DEADLINE`` (empty
        string = no deadline).  ``defaults`` seed the call-site policy."""
        env = os.environ
        kw = dict(defaults)
        if "DSLIB_RETRY_ATTEMPTS" in env:
            kw["attempts"] = int(env["DSLIB_RETRY_ATTEMPTS"])
        if "DSLIB_RETRY_BACKOFF" in env:
            kw["backoff"] = float(env["DSLIB_RETRY_BACKOFF"])
        if "DSLIB_RETRY_MAX_BACKOFF" in env:
            kw["max_backoff"] = float(env["DSLIB_RETRY_MAX_BACKOFF"])
        if env.get("DSLIB_RETRY_DEADLINE"):
            kw["deadline"] = float(env["DSLIB_RETRY_DEADLINE"])
        return cls(**kw)

    def is_transient(self, exc: BaseException) -> bool:
        if self.classify is not None:
            verdict = self.classify(exc)
            if verdict is not None:
                return bool(verdict)
        return is_transient_error(exc)

    def call(self, fn, *args, **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying transient failures.  The
        last exception re-raises with its original type and traceback."""
        start = time.monotonic()
        for attempt in range(1, self.attempts + 1):
            try:
                return fn(*args, **kwargs)
            except BaseException as exc:  # noqa: BLE001 — classified below
                if attempt >= self.attempts or not self.is_transient(exc):
                    raise
                delay = min(self.max_backoff,
                            self.backoff * (2.0 ** (attempt - 1)))
                delay *= 1.0 + self.jitter * self._rng.random()
                if self.deadline is not None and \
                        time.monotonic() - start + delay > self.deadline:
                    raise
                self._sleep(delay)
        raise AssertionError("unreachable")  # loop always returns or raises


def retry_call(fn, *args, retry: Retry | None = None, **kwargs):
    """``(retry or Retry.from_env()).call(fn, *args, **kwargs)``."""
    return (retry if retry is not None else Retry.from_env()) \
        .call(fn, *args, **kwargs)
