"""Continuous-learning trainer daemon (round-17 tentpole): train →
bundle → canary → promote, forever, surviving every seam.

Every piece of the continuous-learning loop existed before this module —
the streaming :meth:`~dislib_tpu.runtime.fitloop.ChunkedFitLoop.run_one`
driver (PR 10), rotating :class:`~dislib_tpu.utils.checkpoint.FitCheckpoint`
generations (PR 1/6), AOT deployment bundles and the
:class:`~dislib_tpu.serving.router.ModelRouter` canary/promote seam
(PR 15) — but nothing connected them end-to-end.
:class:`ContinuousTrainer` is that connection, and it is designed around
failure at every seam, because a loop that must run *forever* meets every
failure eventually:

- **stream seam** — each raw host batch rides the ingest quarantine
  (:func:`dislib_tpu.data.io.quarantine_batch`) before it reaches the
  estimator: non-finite rows are isolated into the process-wide
  :class:`~dislib_tpu.data.io.QuarantineLedger` (exact totals across
  generations, bounded retained reports) instead of poisoning the fit.
  A batch that quarantines to nothing is skipped and counted, never fed.
- **training seam** — the estimator's ``partial_fit`` rides
  ``ChunkedFitLoop.run_one``, so rollback-to-last-good, the chunk
  watchdog, preemption polling, and bidirectional capacity elasticity
  (mesh shrink/grow mid-stream) are all inherited, not reimplemented.  A
  mid-stream :class:`~dislib_tpu.runtime.preemption.Preempted` flushes
  the snapshot, is counted, and propagates typed — the restarted trainer
  resumes the stream from the snapshot.
- **export seam** — one deployment bundle per generation, written
  through :class:`~dislib_tpu.runtime.retry.Retry` (exponential backoff,
  transient-vs-fatal classification).  The artifact is read BACK through
  the CRC-verified loader before anything serves it: a torn or
  bit-corrupt bundle surfaces as
  :class:`~dislib_tpu.utils.checkpoint.SnapshotCorrupt`, classifies
  transient *at this seam* (the fix is rewriting the artifact), and the
  export retries — a damaged bundle is never handed to the router.
- **promotion seam** — each verified bundle serves first as a
  :meth:`~dislib_tpu.serving.router.ModelRouter.set_canary` arm, and is
  promoted only through the health gate.  An unhealthy canary is
  aborted — traffic automatically rolls back to the last-good
  generation — and after ``promote_budget`` consecutive rejections the
  trainer raises the typed :class:`PromotionFailed` (the operator
  signal) with the last-good generation still serving.

A **promotion ledger** (in memory and appended to
``<bundle_dir>/ledger.jsonl``) records every generation's (generation,
checksum, verdict, counters, wall times).  The served generation is
**monotone except by explicit** :meth:`ContinuousTrainer.rollback` —
enforced at promote time, recorded per ledger entry, and soak-asserted
with faults at every seam (``tests/test_chaos_soak.py`` /
``tools/chaos_soak.sh --trainer``).

DrJAX's per-shard-update + cross-shard-reduce decomposition
(arXiv:2403.07128) is the reference shape for the streaming updates the
loop consumes; the promotion path obeys the 2112.09017 scale discipline —
zero hot-path retraces, ever (the canary serves deserialized AOT
executables; the soak counter-asserts no trace after warmup).

Env knobs (the ``DSLIB_TRAINER_*`` surface; constructor args override):

- ``DSLIB_TRAINER_BATCHES`` — batches consumed per generation (8);
- ``DSLIB_TRAINER_CANARY_FRACTION`` — canary traffic split (0.5);
- ``DSLIB_TRAINER_PROMOTE_BUDGET`` — consecutive canary rejections
  before the typed :class:`PromotionFailed` (3);
- ``DSLIB_TRAINER_EXPORT_ATTEMPTS`` — bundle-export retry budget (4);
  backoff/jitter ride the standard ``DSLIB_RETRY_*`` knobs.
"""

from __future__ import annotations

import json
import os
import time
import zlib

import numpy as np

from dislib_tpu.runtime.preemption import Preempted
from dislib_tpu.runtime.retry import Retry
from dislib_tpu.utils.checkpoint import SnapshotCorrupt
from dislib_tpu.utils.profiling import count_resilience

__all__ = ["ContinuousTrainer", "PromotionFailed"]


def _env_int(name, default):
    return int(os.environ.get(name, default))


def _env_float(name, default):
    return float(os.environ.get(name, default))


class PromotionFailed(RuntimeError):
    """The canary health gate refused ``attempts`` consecutive
    generations — the promote budget is exhausted and an operator must
    look.  The LAST-GOOD generation is still serving (the trainer never
    leaves a tenant dark); carries ``tenant``, ``generation`` (the last
    refused one), ``attempts``, and ``last_good`` (the generation still
    serving, or None when nothing ever promoted)."""

    def __init__(self, message, tenant=None, generation=None, attempts=0,
                 last_good=None):
        super().__init__(message)
        self.tenant = tenant
        self.generation = generation
        self.attempts = int(attempts)
        self.last_good = last_good


def _file_crc(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(1 << 20)
            if not buf:
                return crc & 0xFFFFFFFF
            crc = zlib.crc32(buf, crc)


class ContinuousTrainer:
    """The train → bundle → canary → promote daemon (module docstring).

    Parameters
    ----------
    estimator : streaming estimator — anything with the
        ``partial_fit(batch, checkpoint=, health=)`` contract riding
        ``ChunkedFitLoop.run_one`` (``MiniBatchKMeans`` is the in-tree
        reference; its ``fit_info_`` feeds :meth:`stats`).
    stream : iterable of host batches (ndarray rows).  May be infinite —
        the trainer consumes ``batches_per_generation`` per cadence.
        Each batch is quarantine-screened before the estimator sees it.
    checkpoint : FitCheckpoint — the stream's rotating snapshot sink
        (rollback target, preemption resume point, and the
        adoption-gated state embedded in every exported bundle).
    pipeline_of : callable(estimator, generation) -> ServePipeline —
        builds the servable chain from the live model for one
        generation's export.
    bundle_dir : str — one ``gen_NNNNNN.dsb.npz`` artifact per
        generation plus the ``ledger.jsonl`` promotion ledger.
    router, tenant : the serving side.  None disables canary/promote —
        the trainer still trains and exports verified bundles
        (``verdict="exported"``).  The FIRST generation registers the
        tenant (initial deploy, gated before any traffic); later ones
        canary against the serving primary.
    buckets : bucket ladder for the exported executables (default per
        ``serving.buckets.bucket_ladder``).
    batches_per_generation / canary_fraction / promote_budget : the
        ``DSLIB_TRAINER_*`` knobs (module docstring).
    retry : Retry — the export-seam policy; default
        ``Retry.from_env(attempts=DSLIB_TRAINER_EXPORT_ATTEMPTS,
        backoff=0.1)`` with ``SnapshotCorrupt`` classified transient at
        this seam (a torn artifact is fixed by rewriting it).
    health : HealthPolicy | None — passed through to the estimator's
        stream (fault injectors are policies; see ``utils.faults``).
    health_gate : callable(LoadedBundle, generation) -> bool — the
        promotion gate.  None gates on the default probe predict (all
        outputs finite).  A gate that RAISES counts as unhealthy (the
        error is recorded in the ledger entry), except control-flow
        exceptions which propagate.
    probe : ndarray (rows, n_features) | None — rows for the default
        gate's warmup predict; None with no ``health_gate`` accepts
        every verified bundle.
    quota_rows / deadline_ms : forwarded to the tenant registration and
        the per-generation ``PredictServer``.
    quarantine : tri-state passed to the batch screen (None reads
        ``DSLIB_QUARANTINE``).
    """

    def __init__(self, estimator, stream, checkpoint, pipeline_of,
                 bundle_dir, router=None, tenant=None, buckets=None,
                 batches_per_generation=None, canary_fraction=None,
                 promote_budget=None, retry=None, health=None,
                 health_gate=None, probe=None, quota_rows=None,
                 deadline_ms=None, quarantine=None, name="trainer",
                 membership=None):
        self.estimator = estimator
        self._stream = iter(stream)
        self.checkpoint = checkpoint
        self.pipeline_of = pipeline_of
        self.bundle_dir = str(bundle_dir)
        os.makedirs(self.bundle_dir, exist_ok=True)
        self.router = router
        self.tenant = tenant
        self.buckets = buckets
        self.batches_per_generation = \
            _env_int("DSLIB_TRAINER_BATCHES", 8) \
            if batches_per_generation is None else int(batches_per_generation)
        self.canary_fraction = \
            _env_float("DSLIB_TRAINER_CANARY_FRACTION", 0.5) \
            if canary_fraction is None else float(canary_fraction)
        self.promote_budget = _env_int("DSLIB_TRAINER_PROMOTE_BUDGET", 3) \
            if promote_budget is None else int(promote_budget)
        self.retry = retry if retry is not None else Retry.from_env(
            attempts=_env_int("DSLIB_TRAINER_EXPORT_ATTEMPTS", 4),
            backoff=0.1, classify=self._classify_export)
        self.health = health
        self.health_gate = health_gate
        self.probe = None if probe is None else np.asarray(probe, np.float32)
        self.quota_rows = quota_rows
        self.deadline_ms = deadline_ms
        self.quarantine = quarantine
        self.name = name
        # fleet membership (round 20): when this trainer is one rank of
        # a multi-host fleet, run() keeps its lease renewed and watches
        # the peers' — a confirmed peer death publishes the shrunk
        # capacity statement and the NEXT partial_fit heals through the
        # fit loop's elastic rungs; a rejoin grows the fleet back
        self.membership = membership
        self._keeper = None

        self.generation = 0             # last trained generation
        self.served_generation = None   # what the tenant's primary serves
        self.ledger: list[dict] = []    # promotion ledger, oldest first
        self._last_good = None          # (generation, bundle path)
        self._primary_server = None     # the server this trainer installed
        self._consecutive_rejections = 0
        self._exhausted = False
        self._counters = {
            "promotions": 0,            # generations made primary
            "canary_rejections": 0,     # health gate said no
            "promote_failures": 0,      # budget exhaustions (typed raise)
            "rollbacks": 0,             # automatic stay-on-last-good
            "rollbacks_of_served": 0,   # explicit rollback() calls
            "exports": 0,
            "export_retries": 0,
            "batches": 0,
            "batches_skipped": 0,       # quarantined to nothing
            "preemptions": 0,
        }

    # -- export-seam classification ---------------------------------------

    @staticmethod
    def _classify_export(exc):
        """At the export seam a torn/bit-corrupt artifact
        (``SnapshotCorrupt`` from the read-back) is TRANSIENT: the fix
        is rewriting the artifact, which is exactly what a retry does.
        Everything else falls through to the default classification."""
        if isinstance(exc, SnapshotCorrupt):
            return True
        return None

    # -- stream side -------------------------------------------------------

    def train_generation(self) -> bool:
        """Consume one generation's cadence of batches from the stream —
        each screened through the ingest quarantine, then fed to the
        estimator's ``partial_fit`` (checkpoint/health stream-wide).
        Returns False when the stream is exhausted before yielding a
        single batch (the daemon's clean shutdown signal); a partial
        cadence at stream end still forms a final generation.  A
        mid-stream ``Preempted`` is counted and propagates typed — the
        snapshot is already flushed, so a restarted trainer resumes."""
        from dislib_tpu.data import io as _dio
        g = self.generation + 1
        pulled = 0
        while pulled < self.batches_per_generation:
            try:
                batch = next(self._stream)
            except StopIteration:
                self._exhausted = True
                break
            pulled += 1
            src = f"{self.name}/gen{g}/batch{self._counters['batches'] + 1}"
            try:
                clean, _ = _dio.quarantine_batch(batch, source=src,
                                                 quarantine=self.quarantine)
            except ValueError:
                # every row quarantined: nothing to learn from — skip,
                # count, keep the loop alive (the ledger holds the audit)
                self._counters["batches_skipped"] += 1
                continue
            try:
                self.estimator.partial_fit(clean, checkpoint=self.checkpoint,
                                           health=self.health)
            except Preempted:
                self._counters["preemptions"] += 1
                count_resilience("trainer_preemptions")
                raise
            self._counters["batches"] += 1
        if pulled:
            self.generation = g
        return pulled > 0

    # -- export seam -------------------------------------------------------

    def _bundle_path(self, g: int) -> str:
        return os.path.join(self.bundle_dir, f"gen_{g:06d}.dsb.npz")

    def export_generation(self):
        """Export generation ``self.generation`` as a deployment bundle
        through the retry policy, CRC-verified end-to-end: the
        checkpoint flushes (the embedded state reads through the
        adoption gate), the artifact writes atomically, and the bundle
        is read BACK through the verified loader before anyone serves
        it.  A torn/corrupt artifact retries with backoff; budget
        exhaustion re-raises the last typed error.  Returns
        ``(path, LoadedBundle)``."""
        from dislib_tpu.serving.bundle import export_bundle, load_bundle
        g = self.generation
        if self.checkpoint is not None:
            self.checkpoint.flush()
        pipe = self.pipeline_of(self.estimator, g)
        path = self._bundle_path(g)
        attempts = [0]

        def _attempt():
            attempts[0] += 1
            export_bundle(pipe, path, buckets=self.buckets,
                          checkpoint=self.checkpoint)
            # the read-back IS the verification: CRC over every entry,
            # zero-retrace executables rehydrated — what serving will use
            return load_bundle(path)

        loaded = self.retry.call(_attempt)
        self._counters["exports"] += 1
        if attempts[0] > 1:
            self._counters["export_retries"] += attempts[0] - 1
            count_resilience("trainer_export_retries", attempts[0] - 1)
        return path, loaded

    # -- promotion seam ----------------------------------------------------

    def _gate(self, loaded, g, record):
        """Health-gate one loaded bundle.  The user gate wins; the
        default probe gate requires every probe prediction finite; no
        gate and no probe accepts (the bundle already CRC-verified)."""
        if self.health_gate is not None:
            try:
                return bool(self.health_gate(loaded, g))
            except (Preempted, KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:  # noqa: BLE001 — a raising gate is a veto
                record["gate_error"] = f"{type(e).__name__}: {e}"
                return False
        if self.probe is None:
            return True
        rows = self.probe
        fit = [b for b in loaded.buckets if b >= rows.shape[0]]
        bucket = min(fit) if fit else max(loaded.buckets)
        rows = rows[: bucket]
        vals = loaded.pipeline.predict_bucket(rows, bucket)
        return bool(np.all(np.isfinite(vals)))

    def _make_server(self, loaded, g):
        from dislib_tpu.serving.server import PredictServer
        srv = PredictServer(pipeline=loaded.pipeline, buckets=loaded.buckets,
                            deadline_ms=self.deadline_ms,
                            name=f"{self.name}-g{g}")
        srv.start()
        return srv

    def _commit_record(self, record):
        record["served"] = self.served_generation
        record["counters"] = dict(self._counters)
        self.ledger.append(record)
        try:
            with open(os.path.join(self.bundle_dir, "ledger.jsonl"),
                      "a") as f:
                f.write(json.dumps(record) + "\n")
        except OSError:
            pass                        # the in-memory ledger is canonical

    def publish_generation(self) -> dict:
        """Export → canary → health gate → promote (or automatic
        rollback to last-good) for the current generation; returns the
        ledger record.  The served generation moves FORWARD only here
        (``g > served`` enforced) and BACKWARD only in
        :meth:`rollback`."""
        t0 = time.perf_counter()
        path, loaded = self.export_generation()
        export_s = time.perf_counter() - t0
        g = self.generation
        record = {"generation": g, "path": path,
                  "checksum": _file_crc(path), "verdict": None,
                  "export_s": round(export_s, 4)}
        if self.router is None or self.tenant is None:
            record["verdict"] = "exported"
            self._last_good = (g, path)
            self._commit_record(record)
            return record
        if self.served_generation is not None \
                and g <= self.served_generation:
            raise RuntimeError(
                f"{self.name}: refusing to publish generation {g} over "
                f"served generation {self.served_generation} — the served "
                "generation moves backward only via rollback()")
        t0 = time.perf_counter()
        srv = self._make_server(loaded, g)
        fresh = self.tenant not in self.router.tenants()
        if not fresh:
            self.router.set_canary(self.tenant, srv,
                                   fraction=self.canary_fraction)
        if self._gate(loaded, g, record):
            if fresh:
                # initial deploy: gated BEFORE any traffic ever routed
                self.router.add_tenant(self.tenant, srv,
                                       quota_rows=self.quota_rows)
            else:
                self.router.promote(self.tenant)
            old, self._primary_server = self._primary_server, srv
            self.served_generation = g
            self._last_good = (g, path)
            self._counters["promotions"] += 1
            self._consecutive_rejections = 0
            record["verdict"] = "promoted"
            record["promote_s"] = round(time.perf_counter() - t0, 4)
            count_resilience("trainer_promotions")
            self._commit_record(record)
            if old is not None:
                old.stop()              # drained; new primary has traffic
            return record
        # unhealthy canary: route 100% back to last-good (automatic
        # rollback), retire the canary server, spend promote budget
        if not fresh:
            self.router.abort_canary(self.tenant, failed=True)
        srv.stop()
        self._counters["canary_rejections"] += 1
        self._counters["rollbacks"] += 1
        self._consecutive_rejections += 1
        record["verdict"] = "rejected"
        record["promote_s"] = round(time.perf_counter() - t0, 4)
        count_resilience("trainer_canary_rejections")
        if self._consecutive_rejections >= self.promote_budget:
            self._counters["promote_failures"] += 1
            record["verdict"] = "rejected+budget"
            self._commit_record(record)
            last = self._last_good[0] if self._last_good else None
            raise PromotionFailed(
                f"{self.name}: canary health gate refused "
                f"{self._consecutive_rejections} consecutive generations "
                f"(budget {self.promote_budget}); generation "
                f"{last!r} is still serving — operator attention required",
                tenant=self.tenant, generation=g,
                attempts=self._consecutive_rejections, last_good=last)
        self._commit_record(record)
        return record

    def rollback(self, to_generation=None) -> dict:
        """EXPLICITLY move the served generation backward: reload an
        earlier *promoted* generation's bundle (default: the newest one
        below the served generation) through the verified loader, and
        re-point the tenant's primary at it via
        :meth:`ModelRouter.rollback`.  The one sanctioned backwards
        move — recorded in the ledger (``verdict="rollback"``) and
        counted (``rollbacks_of_served``)."""
        if self.router is None or self.tenant is None:
            raise RuntimeError(f"{self.name}: no router/tenant to roll back")
        if self.served_generation is None:
            raise RuntimeError(f"{self.name}: nothing promoted yet")
        promoted = [r for r in self.ledger if r["verdict"] == "promoted"
                    and r["generation"] < self.served_generation]
        if to_generation is not None:
            promoted = [r for r in promoted
                        if r["generation"] == int(to_generation)]
        if not promoted:
            raise RuntimeError(
                f"{self.name}: no promoted generation below "
                f"{self.served_generation}"
                + (f" matching {to_generation}" if to_generation is not None
                   else "") + " to roll back to")
        target = promoted[-1]
        from dislib_tpu.serving.bundle import load_bundle
        loaded = load_bundle(target["path"])    # CRC-verified, typed
        g = target["generation"]
        srv = self._make_server(loaded, g)
        self.router.rollback(self.tenant, srv)
        old, self._primary_server = self._primary_server, srv
        self.served_generation = g
        self._last_good = (g, target["path"])
        self._consecutive_rejections = 0
        self._counters["rollbacks_of_served"] += 1
        count_resilience("trainer_rollbacks_of_served")
        record = {"generation": g, "path": target["path"],
                  "checksum": target["checksum"], "verdict": "rollback"}
        self._commit_record(record)
        if old is not None:
            old.stop()
        return record

    # -- daemon loop -------------------------------------------------------

    def step(self) -> dict | None:
        """One full cadence: train a generation, publish it.  None when
        the stream is exhausted."""
        if not self.train_generation():
            return None
        return self.publish_generation()

    def run(self, generations=None) -> dict:
        """Drive :meth:`step` until the stream exhausts or ``generations``
        cadences complete (None = forever).  ``Preempted`` and
        :class:`PromotionFailed` propagate typed — the orchestrator
        decides restart vs page; a re-instantiated trainer resumes the
        stream from the checkpoint.  Returns :meth:`stats`.  With
        ``membership=`` this rank's lease is kept renewed for the whole
        run (a :class:`~dislib_tpu.runtime.LeaseKeeper`), and peer
        deaths/rejoins are converted into capacity statements the
        training loop heals through between batches."""
        from dislib_tpu.runtime.coord import LeaseKeeper, set_membership
        if self.membership is not None and self._keeper is None:
            set_membership(self.membership)
            self._keeper = LeaseKeeper(self.membership, watch=True)
            self._keeper.start()
        try:
            done = 0
            while generations is None or done < generations:
                if self.step() is None:
                    break
                done += 1
            return self.stats()
        finally:
            if self._keeper is not None:
                self._keeper.stop()
                self._keeper = None
                set_membership(None)

    def close(self) -> None:
        """Stop the primary server this trainer installed (canary
        servers are retired as they lose; the router only stops servers
        it started itself)."""
        srv, self._primary_server = self._primary_server, None
        if srv is not None:
            srv.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """The resilience + promotion counters, end-to-end: the
        trainer's own seam counters, the stream driver's ``fit_info_``
        (rollbacks / mesh resizes inherited from ``ChunkedFitLoop``),
        and the process quarantine ledger's exact stream totals."""
        from dislib_tpu.data.io import quarantine_ledger
        led = quarantine_ledger()
        info = getattr(self.estimator, "fit_info_", None) or {}
        out = dict(self._counters)
        out.update({
            "generation": self.generation,
            "served_generation": self.served_generation,
            "last_good": self._last_good[0] if self._last_good else None,
            "stream_exhausted": self._exhausted,
            "ledger_entries": len(self.ledger),
            "quarantine": {"n_quarantined": led.n_quarantined,
                           "n_loaded": led.n_loaded,
                           "reports_retained": len(led.reports)},
            "stream": {"chunks": info.get("chunks", 0),
                       "rollbacks": info.get("rollbacks", 0),
                       "mesh_shrinks": info.get("mesh_shrinks", 0),
                       "mesh_grows": info.get("mesh_grows", 0)},
        })
        # fleet view (round 20): who died, who came back, what this
        # rank's lease says — stats()-visible whether or not the
        # orchestrator reads the process-wide resilience counters
        from dislib_tpu.utils.profiling import resilience_counters
        res = resilience_counters()
        out["fleet"] = {
            "rank_deaths": res.get("rank_deaths", 0),
            "rank_rejoins": res.get("rank_rejoins", 0),
            **(self.membership.stats() if self.membership is not None
               else {}),
        }
        return out
