"""The ONE place allowed to mutate ``XLA_FLAGS`` (lint-enforced by
``tests/test_xla_flags_policy.py``; a handful of test/example rigs may set
the universally-supported device-count flag, nothing else).

Why centralised: the package used to inject
``--xla_cpu_collective_call_terminate_timeout_seconds`` /
``--xla_cpu_collective_call_warn_stuck_timeout_seconds`` unconditionally at
import.  XLA treats unknown flags as FATAL — jaxlib builds that predate the
flags abort the whole process at first backend init (``Unknown flags in
XLA_FLAGS``), which turned the mitigation into a guaranteed crash on
jaxlib 0.4.36.  Every injection is therefore gated on jaxlib version here,
and nowhere else is allowed to spell the flag names.

The timeout flags themselves remain valuable where they exist: XLA:CPU
aborts the process when a collective participant waits >40 s, and on a
thread-starved CI rig (8 virtual devices on one core) a long compile can
legitimately stall a participant that long.
"""

from __future__ import annotations

import os

# (flag, default) pairs injected by inject_cpu_collective_timeouts()
_TIMEOUT_FLAGS = (
    ("xla_cpu_collective_call_terminate_timeout_seconds", 600),
    ("xla_cpu_collective_call_warn_stuck_timeout_seconds", 60),
)

# First jaxlib line where the collective-call timeout flags are assumed to
# parse.  0.4.x verifiably rejects them (fatal abort observed on 0.4.36);
# the threshold is deliberately conservative — missing the mitigation on a
# version that would have accepted it costs a slower abort on a stall,
# while injecting into a version that rejects it crashes every process at
# import.  ``DSLIB_XLA_CPU_TIMEOUT_FLAGS=1`` force-enables on rigs known
# to support them; ``=0`` force-disables.
_MIN_JAXLIB_FOR_TIMEOUT_FLAGS = (0, 6, 0)


def _jaxlib_version() -> tuple | None:
    try:
        import jaxlib
        parts = jaxlib.__version__.split(".")[:3]
        return tuple(int("".join(c for c in p if c.isdigit()) or 0)
                     for p in parts)
    except Exception:  # noqa: BLE001 — unknown jaxlib: treat as unsupported
        return None


def cpu_collective_timeout_flags_supported() -> bool:
    """True when this jaxlib is believed to parse the XLA:CPU collective
    timeout flags.  Env override: ``DSLIB_XLA_CPU_TIMEOUT_FLAGS=1``/``0``."""
    forced = os.environ.get("DSLIB_XLA_CPU_TIMEOUT_FLAGS")
    if forced in ("0", "1"):
        return forced == "1"
    v = _jaxlib_version()
    return v is not None and v >= _MIN_JAXLIB_FOR_TIMEOUT_FLAGS


def _append_flag(name: str, value) -> None:
    """Append ``--name=value`` to XLA_FLAGS unless the name is already
    present (a user-provided value always wins)."""
    cur = os.environ.get("XLA_FLAGS", "")
    if name in cur:
        return
    os.environ["XLA_FLAGS"] = (cur + f" --{name}={value}").strip()


def inject_cpu_collective_timeouts() -> bool:
    """Raise the XLA:CPU collective-rendezvous abort threshold (warn log
    stays early).  Must run before the backend initialises.  No-op —
    returning False — when this jaxlib does not support the flags; returns
    True when the flags are (or already were) in place."""
    if not cpu_collective_timeout_flags_supported():
        return False
    for name, default in _TIMEOUT_FLAGS:
        _append_flag(name, default)
    return True


def force_host_platform_device_count(n: int) -> None:
    """Request ``n`` virtual CPU devices (the multi-chip CI rig).  Must run
    before the backend initialises; a pre-existing user value wins."""
    _append_flag("xla_force_host_platform_device_count", int(n))
