"""Elastic-resume helpers: restore a snapshot onto a DIFFERENT mesh.

Snapshots keep recovery state as host-side logical arrays (the host/device
split of arXiv:2112.09017): small replicated results (centers, mixture
parameters, SV sets) are mesh-independent as stored, and the only
mesh-dependent artifact is the pad width of row-padded state (ds-arrays
pad every dimension to the mesh quantum).  Resharding on restore
(arXiv:2112.01075 discipline) therefore reduces to :func:`repad_rows` —
crop the writing mesh's pad rows (zero by the pad-and-mask invariant) and
zero-fill to the restoring mesh's quantum — after which the normal
``device_put`` of the fit path lays the state out for the new topology.
An 8-device snapshot restores onto a 4-device or 2-D mesh this way.

:func:`fetch` is the host↔device transfer boundary with the
transient-failure :class:`~dislib_tpu.runtime.retry.Retry` policy applied
— the read every snapshot goes through.
"""

from __future__ import annotations

import numpy as np

__all__ = ["repad_rows", "fetch"]


def repad_rows(a, logical: int, target: int, axis: int = 0):
    """Re-pad snapshot state along ``axis`` for the restoring mesh: keep
    the first ``logical`` (real) slices, zero-fill out to ``target`` (the
    restoring mesh's padded extent).  Exact because pad slices carry zeros
    under the pad-and-mask invariant.  Raises when the snapshot holds
    fewer than ``logical`` slices (foreign/stale state)."""
    a = np.asarray(a)
    if a.shape[axis] < logical:
        raise ValueError(
            f"snapshot state has {a.shape[axis]} rows along axis {axis} but "
            f"the logical state needs {logical} — stale or foreign snapshot")
    if target < logical:
        raise ValueError(
            f"target padded extent {target} is smaller than the logical "
            f"extent {logical}")
    sl = [slice(None)] * a.ndim
    sl[axis] = slice(0, logical)
    a = a[tuple(sl)]
    if target == logical:
        return a
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, target - logical)
    return np.pad(a, pad)


def fetch(x) -> np.ndarray:
    """Device→host read (``jax.device_get`` → ndarray) with transient
    failures retried under the env-tunable default policy — the snapshot
    write path's half of the host↔device boundary."""
    import jax

    from dislib_tpu.runtime.retry import Retry
    return Retry.from_env().call(lambda: np.asarray(jax.device_get(x)))
