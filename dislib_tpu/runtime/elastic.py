"""Elastic-resume helpers: restore a snapshot onto a DIFFERENT mesh.

Snapshots keep recovery state as host-side logical arrays (the host/device
split of arXiv:2112.09017): small replicated results (centers, mixture
parameters, SV sets) are mesh-independent as stored, and the only
mesh-dependent artifact is the pad width of row-padded state (ds-arrays
pad every dimension to the mesh quantum).  Resharding on restore
(arXiv:2112.01075 discipline) therefore reduces to :func:`repad_rows` —
crop the writing mesh's pad rows (zero by the pad-and-mask invariant) and
zero-fill to the restoring mesh's quantum — after which the normal
``device_put`` of the fit path lays the state out for the new topology.
An 8-device snapshot restores onto a 4-device or 2-D mesh this way.

:func:`fetch` is the host↔device transfer boundary with the
transient-failure :class:`~dislib_tpu.runtime.retry.Retry` policy applied
— the read every snapshot goes through.  ``fetch(x, blocking=False)``
returns an :class:`AsyncFetch` handle instead: the device→host copy is
enqueued immediately (before any later dispatch), but the blocking
resolution happens at ``result()`` — on the snapshot worker thread for
``FitCheckpoint.save_async``, so the copy and the file write overlap the
next chunk's compute instead of stalling the fit loop (round-7 perf PR).
"""

from __future__ import annotations

import numpy as np

__all__ = ["repad_rows", "fetch", "AsyncFetch"]


def repad_rows(a, logical: int, target: int, axis: int = 0):
    """Re-pad state along ``axis`` for the restoring mesh: keep the
    first ``logical`` (real) slices, zero-fill out to ``target`` (the
    restoring mesh's padded extent).  Exact because pad slices carry
    zeros under the pad-and-mask invariant.  Raises when the state holds
    fewer than ``logical`` slices (foreign/stale snapshot).

    Two routes (round-11 rechunk PR): a ``jax.Array`` input — state
    already ON DEVICE at an elastic mesh change — re-pads in one jitted
    kernel (``ops/rechunk.repad_axis``) and STAYS on device, no host
    round trip; anything else takes the original host-NumPy path, kept
    as the snapshot-restore fallback (checkpoint state arrives as host
    ndarrays by design)."""
    if not isinstance(a, np.ndarray):
        import jax
        if isinstance(a, jax.Array):
            if a.shape[axis] < logical:
                raise ValueError(
                    f"snapshot state has {a.shape[axis]} rows along axis "
                    f"{axis} but the logical state needs {logical} — stale "
                    "or foreign snapshot")
            if target < logical:
                raise ValueError(
                    f"target padded extent {target} is smaller than the "
                    f"logical extent {logical}")
            from dislib_tpu.ops.rechunk import repad_axis
            return repad_axis(a, int(logical), int(target), axis)
    a = np.asarray(a)
    if a.shape[axis] < logical:
        raise ValueError(
            f"snapshot state has {a.shape[axis]} rows along axis {axis} but "
            f"the logical state needs {logical} — stale or foreign snapshot")
    if target < logical:
        raise ValueError(
            f"target padded extent {target} is smaller than the logical "
            f"extent {logical}")
    sl = [slice(None)] * a.ndim
    sl[axis] = slice(0, logical)
    a = a[tuple(sl)]
    if target == logical:
        return a
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, target - logical)
    return np.pad(a, pad)


class AsyncFetch:
    """Deferred device→host read started by ``fetch(x, blocking=False)``.

    The copy is enqueued at construction (``copy_to_host_async``) so it
    runs concurrently with whatever the caller dispatches next;
    :meth:`result` blocks until the bytes are on host (retried under the
    default transient policy) and caches the ndarray.

    NOT safe for buffers a later kernel call DONATES: donation
    invalidates the device buffer at dispatch time, before an un-resolved
    copy may have landed.  Estimators whose snapshot state is also a
    donated loop carry (ALS factors, GMM parameters, forest node arrays)
    fetch those blocking and overlap only the file write.
    """

    def __init__(self, x):
        self._x = x
        self._value = None
        self._resolved = False
        try:
            x.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass                        # host values / exotic backends

    def result(self) -> np.ndarray:
        if not self._resolved:
            import jax

            from dislib_tpu.runtime.retry import Retry
            from dislib_tpu.utils.profiling import count_transfer
            count_transfer()
            try:
                self._value = Retry.from_env().call(
                    lambda: np.asarray(jax.device_get(self._x)))
            except RuntimeError as e:
                if "deleted" in str(e) or "donated" in str(e):
                    raise RuntimeError(
                        "async fetch source buffer was donated before the "
                        "copy resolved — snapshot donated loop carries with "
                        "fetch(x, blocking=True) (see the user guide's "
                        "'Dispatch, fusion & donation' section)") from e
                raise
            self._resolved = True
            self._x = None
        return self._value


def fetch(x, blocking: bool = True):
    """Device→host read (``jax.device_get`` → ndarray) with transient
    failures retried under the env-tunable default policy — the snapshot
    write path's half of the host↔device boundary.

    A ds-array input is a force point: its deferred op chain runs as one
    program before the copy.  ``blocking=False`` returns an
    :class:`AsyncFetch` whose copy overlaps later host work;
    ``FitCheckpoint.save`` resolves such handles at write time."""
    if hasattr(x, "_data"):             # ds-array → padded device backing
        x = x._data
    if not blocking:
        return AsyncFetch(x)
    import jax

    from dislib_tpu.runtime.retry import Retry
    from dislib_tpu.utils.profiling import count_transfer
    count_transfer()
    return Retry.from_env().call(lambda: np.asarray(jax.device_get(x)))
