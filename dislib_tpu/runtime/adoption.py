"""Checkpoint adoption — the gated read side of model hot-swap.

PR 1 made `FitCheckpoint` crash-consistent on the WRITE side: rotating
generations, embedded checksums, atomic rename, corrupt-newest fallback.
This module is the matching READ-side contract for a consumer that wants
to serve generation N while generation N+1 trains (ROADMAP item 1): a
reader polls the rotating checkpoint and adopts a new generation ONLY
after

1. the checksum-verified load succeeds (``checkpoint.load()`` — a torn or
   bit-corrupt newest generation falls back to the previous good one, so
   a reader can never observe a torn model), and
2. a **health-gated warmup probe** passes: the caller's ``probe`` runs one
   real prediction through the candidate model and the PR-3 health layer
   judges the output (non-finite predictions refuse adoption with a typed
   :class:`AdoptionRejected` instead of silently serving NaNs).

The serving layer (`dislib_tpu.serving`) is REQUIRED to come through
:func:`adopt_latest` for every model read — enforced by an AST lint
(`tests/test_serving.py::TestAdoptionGateLint`), the same pattern that
keeps snapshot writes behind the PR-3 guard gate.

Writers and readers share only the checkpoint PATH (cross-process
hot-swap works the same way): each side builds its own
:class:`~dislib_tpu.utils.checkpoint.FitCheckpoint`, and the atomic
rename discipline guarantees every file a reader opens is a complete
snapshot of SOME generation.
"""

from __future__ import annotations

import os

import numpy as np

__all__ = ["Adoption", "AdoptionRejected", "adopt_latest",
           "generation_token"]


class AdoptionRejected(RuntimeError):
    """A candidate generation failed the adoption gate (non-finite warmup
    predictions, or the caller's ``validate`` refused it).  Carries the
    generation ``token`` and the health ``detail`` for the postmortem."""

    def __init__(self, message, token=None, detail=None):
        super().__init__(message)
        self.token = token
        self.detail = detail or {}


class Adoption:
    """One successful adoption: the generation ``token`` (pass it back as
    ``last_token`` on the next poll), the verified snapshot ``state``
    dict, the built ``model``, and ``mtime_ns`` — the write time of the
    file the state actually came from (pass it back as ``min_mtime_ns``
    so a later disk fallback can never move the served model BACKWARDS)."""

    __slots__ = ("token", "state", "model", "mtime_ns")

    def __init__(self, token, state, model, mtime_ns=None):
        self.token = token
        self.state = state
        self.model = model
        self.mtime_ns = mtime_ns

    def __repr__(self):
        return f"Adoption(token={self.token!r})"


def generation_token(checkpoint):
    """Cheap change-detection token for the newest generation on disk:
    ``(inode, mtime_ns, size)`` of the first generation file that exists,
    or None when the checkpoint has no generation at all.  Every
    ``FitCheckpoint.save`` lands via an atomic rename of a fresh temp
    file, so a new generation ALWAYS changes the inode — a poller
    comparing tokens cannot miss a swap or be fooled by an in-place
    mtime collision."""
    for i in range(checkpoint.keep):
        p = checkpoint._gen_path(i)
        try:
            st = os.stat(p)
        except OSError:
            continue
        return (i, st.st_ino, st.st_mtime_ns, st.st_size)
    return None


def adopt_latest(checkpoint, build, probe=None, validate=None,
                 last_token=None, min_mtime_ns=None, name="adoption"):
    """Adopt the newest verified-and-healthy checkpoint generation.

    Parameters
    ----------
    checkpoint : FitCheckpoint — the rotating snapshot a writer updates.
    build : callable(state_dict) -> model — turn the verified snapshot
        into a servable model (e.g. restore estimator attributes).
    probe : callable(model) -> prediction, optional — the warmup predict.
        Its output (ds-array or ndarray) is judged by the PR-3 health
        layer's non-finite guard; a tripped guard raises
        :class:`AdoptionRejected` and the caller keeps serving the old
        generation.
    validate : callable(model, state), optional — extra caller-side gate;
        raise :class:`AdoptionRejected` inside it to refuse.
    last_token : token from the previous :class:`Adoption`, or None.
    min_mtime_ns : the previous Adoption's ``mtime_ns``, or None.  The
        monotonicity guard: when the verified load FALLS BACK (newest
        file corrupt) to a generation whose file is not newer than the
        one already served, return None instead of adopting — the
        in-memory model passed its gate when it was adopted, and disk rot
        AFTER adoption must never downgrade the served generation (the
        serving soak's no-stale-after-adoption invariant).
    name : str — guard label in health diagnostics.

    Returns None when there is nothing new to adopt (no generation on
    disk, or the newest one is the already-adopted ``last_token``);
    otherwise an :class:`Adoption`.  Raises ``SnapshotCorrupt`` only when
    EVERY generation on disk is damaged (the `FitCheckpoint.load`
    contract), and :class:`AdoptionRejected` when the candidate fails the
    health gate.

    The token is captured BEFORE the load: if the newest file is corrupt,
    ``load()`` falls back to (and cleans up to) an older good generation,
    and the next poll re-adopts once against the settled state — a benign
    duplicate, where capturing after the load could instead MISS a
    generation written mid-adoption.
    """
    token = generation_token(checkpoint)
    if token is None or token == last_token:
        return None
    state = checkpoint.load()
    if state is None:
        return None
    # the monotonicity floor must UNDERESTIMATE the loaded state's write
    # time: too high and a newer generation gets skipped forever (stale
    # serving); too low and the next poll merely re-adopts (benign).
    # Neither single stat is safe alone — after a corrupt-newest
    # fallback the pre-load token is the corrupt file's (too high), and
    # when a writer lands a brand-new generation mid-load the post-load
    # token is that newer file's (too high).  The min of the two is
    # correct in both cases and exact in the common no-race path.
    post = generation_token(checkpoint)
    mtime_ns = min(token[2], post[2]) if post is not None else token[2]
    if min_mtime_ns is not None and mtime_ns <= min_mtime_ns:
        return None
    from dislib_tpu.runtime import health as _health
    # gate 1 — the snapshot PARAMETERS must be finite.  The probe alone
    # is vacuous for integer-label pipelines (argmin over all-NaN scores
    # yields perfectly finite int32 labels), so NaN centers/means/coefs
    # are caught here, at the state they live in — the read-side twin of
    # the PR-3 "snapshot writes gated on healthy chunks" invariant.
    numeric = {k: v for k, v in state.items()
               if np.issubdtype(np.asarray(v).dtype, np.number)}
    verdict = _health.guard(name).check_host(numeric)
    if not verdict.ok:
        raise AdoptionRejected(
            f"{name}: candidate generation carries non-finite state "
            f"(guard {verdict.guard!r}, detail: {verdict.detail}) — "
            "keeping the previous generation",
            token=token, detail=verdict.detail)
    model = build(state)
    if probe is not None:
        # gate 2 — the warmup predict's own outputs (catches a compute
        # path that manufactures non-finite values from finite state)
        out = probe(model)
        from dislib_tpu.runtime import fetch as _fetch
        host = _fetch(out) if hasattr(out, "_data") else np.asarray(out)
        verdict = _health.guard(name).check_host({"warmup_predict": host})
        if not verdict.ok:
            raise AdoptionRejected(
                f"{name}: candidate generation failed its health-gated "
                f"warmup predict (guard {verdict.guard!r}, detail: "
                f"{verdict.detail}) — keeping the previous generation",
                token=token, detail=verdict.detail)
    if validate is not None:
        validate(model, state)
    return Adoption(token, state, model, mtime_ns)
