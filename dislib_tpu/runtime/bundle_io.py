"""Verified byte-level IO for AOT deployment bundles (round-15).

A deployment bundle is ONE versioned artifact holding everything a fresh
serving process needs to serve its first batch with zero retraces: the
serialized compiled predict executables for the whole bucket ladder, the
operand leaves each executable closes over (model parameters, already
padded and canonicalized), the bucket ladder + statics, and the
checksum-verified model state.  The *assembly* of that artifact lives in
``dislib_tpu.serving.bundle``; THIS module is the runtime-side seam that
owns the bytes — the same split as checkpoints (``utils.checkpoint``
owns the format, ``runtime.adoption`` gates the read).

Why a separate seam: the serving package is lint-bound to never touch
snapshot/model bytes directly (no raw ``open()``/``np.load``/``np.savez``
— ``tests/test_serving.py::TestAdoptionGateLint``), so bundle reads and
writes MUST flow through here, where they inherit the checkpoint
format's integrity discipline verbatim:

- writes are atomic (unique tmp file + fsync + rename, directory fsync)
  and embed a CRC-32 over every entry's name/dtype/shape/bytes;
- reads verify that checksum and raise a typed
  :class:`~dislib_tpu.utils.checkpoint.SnapshotCorrupt` on truncation,
  bit rot, or a foreign file — a serving process can never build a
  pipeline from damaged bytes.

Compatibility (wrong jaxlib/topology for the serialized executables) is
the layer ABOVE: :class:`BundleIncompatible` is defined here so the
runtime package exports the typed error, but the fingerprint check runs
in ``serving.bundle`` where the fingerprint is computed.
"""

from __future__ import annotations

import os
import tempfile
import zlib

import numpy as np

from dislib_tpu.utils.checkpoint import (_CRC_KEY, _fsync_dir, _load_verified,
                                         _state_crc)

__all__ = ["BundleIncompatible", "BundleShardCorrupt", "read_bundle",
           "write_bundle", "shard_path", "file_crc"]


class BundleIncompatible(RuntimeError):
    """A deployment bundle whose serialized executables cannot run in
    this process: jax/jaxlib version, device platform/kind, device
    count, mesh shape, or pad quantum differ from the exporting process
    (or the executable bytes fail to deserialize).  Carries the
    ``expected`` (bundle) and ``found`` (this process) fingerprint dicts
    for the postmortem.  The model STATE inside the bundle is still
    checksum-verified and usable — ``load_bundle(..., build=)`` falls
    back to a fresh trace+compile from it, loudly."""

    def __init__(self, message, expected=None, found=None):
        super().__init__(message)
        self.expected = expected or {}
        self.found = found or {}


class BundleShardCorrupt(RuntimeError):
    """A SHARDED bundle failed its coordinated load barrier: some host's
    shard is damaged, missing, or fails the manifest's per-shard
    checksum — so NO host serves (round-19 contract: a fleet either
    loads the whole bundle or none of it).  ``host`` is the rank whose
    shard failed (-1 when unknown) and ``reason`` the shard-local
    diagnosis; every participating process raises the same error."""

    def __init__(self, message, host=-1, reason=""):
        super().__init__(message)
        self.host = int(host)
        self.reason = str(reason)


def shard_path(path: str, host: int) -> str:
    """The per-host shard artifact for a sharded bundle rooted at
    ``path`` (the manifest file): ``<path>.shard<host>``."""
    return f"{path}.shard{int(host)}"


def file_crc(path: str) -> int:
    """CRC-32 over a file's raw bytes — the manifest's per-shard
    integrity record, checked by every host at the load barrier (cheaper
    than a full parse when deciding whether to even vote "ok")."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return crc & 0xFFFFFFFF
            crc = zlib.crc32(chunk, crc)


def write_bundle(path: str, arrays: dict) -> None:
    """Atomically persist a bundle entry dict (ndarrays only; executable
    payloads travel as uint8 arrays, metadata as str arrays) with the
    checkpoint format's embedded CRC-32.  Same crash discipline as
    ``FitCheckpoint.save``: unique tmp in the target directory, fsync
    before the rename, directory fsync after — a torn write can never
    leave a file that :func:`read_bundle` would trust."""
    arrs = {k: np.asarray(v) for k, v in arrays.items()}
    if _CRC_KEY in arrs:
        raise ValueError(f"{_CRC_KEY!r} is a reserved bundle key")
    arrs[_CRC_KEY] = np.asarray([_state_crc(arrs)], np.uint32)
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(suffix=".npz", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrs)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(d)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


def read_bundle(path: str) -> dict:
    """Checksum-verified read of a bundle artifact.  Raises the typed
    :class:`~dislib_tpu.utils.checkpoint.SnapshotCorrupt` when the file
    is truncated, bit-corrupt, or foreign (no integrity record) — the
    read-side twin of :func:`write_bundle`, sharing the checkpoint
    verifier so the two formats cannot drift."""
    return _load_verified(path)
