"""Preemption watcher (SURVEY §6 "Failure detection / elastic recovery").

On a preemptible TPU fleet the eviction notice arrives as SIGTERM (plus,
on some schedulers, a sentinel file) shortly before the hard kill.  Dying
mid-collective loses everything since the last snapshot and can wedge the
peers of a multi-host job at their next rendezvous.  The watcher instead
sets a process-wide flag; checkpointed fit loops poll it BETWEEN
k-iteration device chunks — never inside a collective — write their
snapshot, and raise a clean :class:`Preempted` whose snapshot is the
resume point for the replacement job (possibly on a different mesh; see
``dislib_tpu.runtime.elastic``).

Two trigger paths feed the same flag:

- **signals** — ``PreemptionWatcher`` installs SIGTERM/SIGINT handlers
  (opt-in, context-manager scoped: libraries must not steal signal
  handlers behind the application's back);
- **sentinel file** — ``DSLIB_PREEMPTION_FILE`` names a path polled by
  ``preemption_requested()``; the scheduler (or an operator) touches it
  to request a graceful drain.  The poll is one ``os.path.exists`` per
  chunk boundary — chunk boundaries are seconds apart, so no throttling
  is needed.

Preemption is a one-way drain; **capacity** is a level.  On a fleet
whose device availability OSCILLATES (spot reclaims that later return),
the scheduler publishes the currently usable device count through
``DSLIB_CAPACITY_FILE`` (the file's content is the integer target) or a
process-level :func:`request_capacity` override.  ``capacity_target()``
is NON-sticky — it reports the current level each poll, so the elastic
fit loop can shrink when capacity drops AND grow back when it returns
(``fitloop.ChunkedFitLoop`` polls it at the same chunk boundaries as the
preemption flag; see the mesh grow-back tier there).
"""

from __future__ import annotations

import os
import signal
import threading

__all__ = ["Preempted", "PreemptionWatcher", "preemption_requested",
           "request_preemption", "clear_preemption", "raise_if_preempted",
           "capacity_target", "request_capacity", "clear_capacity"]


class Preempted(Exception):
    """Raised by a checkpointed fit at a chunk boundary once preemption is
    requested: the snapshot on disk (``checkpoint_path``) is consistent
    and the fit resumes from it — on the same mesh or a different one."""

    def __init__(self, message: str, checkpoint_path: str | None = None):
        super().__init__(message)
        self.checkpoint_path = checkpoint_path


_EVENT = threading.Event()
_SIGNUM: int | None = None


def preemption_requested() -> bool:
    """True once a preemption has been signalled (watcher signal, explicit
    :func:`request_preemption`, or the ``DSLIB_PREEMPTION_FILE`` sentinel
    existing).  Sticky until :func:`clear_preemption`."""
    if _EVENT.is_set():
        return True
    path = os.environ.get("DSLIB_PREEMPTION_FILE")
    if path and os.path.exists(path):
        _EVENT.set()
        return True
    return False


def request_preemption() -> None:
    """Set the preemption flag directly (tests, manual drains)."""
    _EVENT.set()


def clear_preemption() -> None:
    """Reset the flag — call after handling a :class:`Preempted` when the
    same process goes on to resume (e.g. the SIGTERM turned out survivable,
    or a test rig reuses the process)."""
    global _SIGNUM
    _SIGNUM = None
    _EVENT.clear()


def last_signal() -> int | None:
    """The signal number that set the flag, if a watcher did."""
    return _SIGNUM


# Device-availability LEVEL (not a sticky event): the scheduler keeps the
# published target current, and every poll re-reads it — shrink when it
# drops, grow back when it returns.
_CAP: dict = {"target": None}


def capacity_target() -> int | None:
    """The scheduler's currently usable device count, or None when no
    capacity source is configured (fixed-capacity deployments never pay
    more than this dict lookup + one env read per chunk boundary).

    Sources, in precedence order: a :func:`request_capacity` process
    override (tests, embedded schedulers), then the integer contents of
    the file named by ``DSLIB_CAPACITY_FILE``, then the fleet-wide
    ledger named by ``DSLIB_CAPACITY_LEDGER`` (round 19: one coherent
    level shared by every process — see ``runtime.coord``).  An absent,
    empty, unparseable, or checksum-failing source means "no statement"
    — None, never a shrink."""
    if _CAP["target"] is not None:
        return int(_CAP["target"])
    path = os.environ.get("DSLIB_CAPACITY_FILE")
    if path:
        try:
            with open(path) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None
    ledger = os.environ.get("DSLIB_CAPACITY_LEDGER")
    if ledger:
        from dislib_tpu.runtime.coord import CapacityLedger
        target, _epoch = CapacityLedger(ledger).read()
        return target
    return None


def request_capacity(n_devices: int, writer: str | None = None) -> None:
    """Set the process-level capacity target directly (tests, manual
    drills, embedded schedulers).  Overrides the capacity file.  When
    ``DSLIB_CAPACITY_LEDGER`` names the fleet ledger, the level is ALSO
    published there — one process's chaos policy (``CapacityAtSave``
    oscillation) or scheduler steers the whole fleet coherently.
    ``writer`` attributes the ledger record (round 20 stamps rank-death
    shrinks ``death:rank<r>`` and rejoin grow-backs ``rejoin:rank<r>``
    so a postmortem can read WHY the fleet resized)."""
    _CAP["target"] = int(n_devices)
    _publish_to_ledger(int(n_devices), writer)


def clear_capacity(writer: str | None = None) -> None:
    """Drop the process-level capacity override — the file (if any)
    becomes the source again, else capacity is unmanaged.  Published to
    the ``DSLIB_CAPACITY_LEDGER`` fleet ledger too, when configured."""
    _CAP["target"] = None
    _publish_to_ledger(None, writer)


def _publish_to_ledger(target, writer: str | None = None) -> None:
    path = os.environ.get("DSLIB_CAPACITY_LEDGER")
    if not path:
        return
    from dislib_tpu.runtime.coord import CapacityLedger
    if writer is None:
        writer = f"proc{os.environ.get('DSLIB_PROC_ID', '0')}"
    CapacityLedger(path).publish(target, writer=writer)


def raise_if_preempted(checkpoint=None) -> None:
    """Estimator hook: call right AFTER a snapshot lands (or its async
    write starts), at the chunk boundary.  Raises :class:`Preempted` when
    the flag is set; no-op otherwise.  The snapshot-first ordering is what
    makes the raise safe: an in-flight ``save_async`` is flushed before
    raising, so whatever is on disk at raise time is a complete resume
    point."""
    if not preemption_requested():
        return
    flush = getattr(checkpoint, "flush", None)
    if flush is not None:
        flush()                         # async snapshot must land first
    path = getattr(checkpoint, "path", None)
    msg = "fit preempted at a chunk boundary"
    if path:
        msg += f" — resume from the snapshot at {path}"
    raise Preempted(msg, checkpoint_path=path)


class PreemptionWatcher:
    """Scoped signal → preemption-flag bridge.

    Usage::

        with dislib_tpu.runtime.PreemptionWatcher():   # SIGTERM by default
            model.fit(x, checkpoint=FitCheckpoint(path, every=10))

    ``install()``/``uninstall()`` are also exposed for long-lived services
    that keep the watcher for the process lifetime.  Previous handlers are
    restored on uninstall.  Signal handlers can only be installed from the
    main thread (Python restriction) — worker threads rely on the sentinel
    file instead.
    """

    def __init__(self, signals=(signal.SIGTERM,)):
        self.signals = tuple(signals)
        self._previous: dict = {}

    def _handler(self, signum, frame):
        global _SIGNUM
        _SIGNUM = signum
        _EVENT.set()

    def install(self) -> "PreemptionWatcher":
        for s in self.signals:
            self._previous[s] = signal.signal(s, self._handler)
        return self

    def uninstall(self) -> None:
        for s, prev in self._previous.items():
            # getsignal can report None for handlers not set from Python;
            # restoring None is invalid — fall back to the default action
            signal.signal(s, prev if prev is not None else signal.SIG_DFL)
        self._previous.clear()

    def __enter__(self) -> "PreemptionWatcher":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False
