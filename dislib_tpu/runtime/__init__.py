"""dislib_tpu.runtime — the preemption-safe elastic runtime layer.

The reference's fault tolerance is runtime-level (COMPSs resubmits failed
tasks); on TPU a preemption or chip failure kills the whole SPMD job, so
the survival story is built from four pieces that compose (SURVEY §6
"Failure detection / elastic recovery"):

- **preemption** — SIGTERM/sentinel-file watcher + the
  :class:`Preempted` contract checkpointed fits honour at chunk
  boundaries (``preemption.py``);
- **retry** — transient-vs-fatal classified retries with backoff for the
  coordinator join, ingest IO, and host↔device transfers (``retry.py``);
- **elastic** — restore snapshots onto a different device count/mesh
  shape by re-padding host-side logical state (``elastic.py``);
- **xla_flags** — the single guarded site allowed to mutate ``XLA_FLAGS``
  (version-gated XLA:CPU collective-timeout mitigation; ``xla_flags.py``);
- **health** — the round-8 *internal*-fault layer: fused numerical-health
  guards on every chunked fit loop, a chunk watchdog, snapshot writes
  gated on healthy chunks, and rollback-to-last-good remediation
  (``health.py``);
- **adoption** — the round-9 read-side hot-swap gate: serve checkpoint
  generation N while N+1 trains; a reader adopts a new generation only
  after the checksum-verified load AND a health-gated warmup predict
  (``adoption.py``; the serving layer is lint-bound to it);
- **bundle_io** — the round-15 deployment-bundle byte seam: atomic
  checksum-embedding writes and verified reads of the AOT serving
  artifact, plus the typed :class:`BundleIncompatible`
  (``bundle_io.py``; ``serving.bundle`` assembles the artifact, this
  module owns its bytes — serving code never touches them raw);
- **coord** — the round-19 cross-process coordination seam: the named
  ranked ``exchange`` primitive over three transports (in-memory /
  shared directory / ``jax.distributed`` KV) behind the sharded-bundle
  load barrier, plus the atomically-replaced :class:`CapacityLedger`
  that makes the capacity level fleet-wide; round 20 adds lease-based
  :class:`Membership` (heartbeats, epoch fencing, the typed attributed
  :class:`RankDead`) and the death→capacity→heal flow (``coord.py``);
- **trainer** — the round-17 continuous-learning daemon:
  :class:`ContinuousTrainer` welds the quarantined stream, the chunked
  fit loop, retried bundle exports, and the router's canary/promote
  seam into one train → bundle → canary → promote loop with a promotion
  ledger, automatic stay-on-last-good rollback, and the typed
  :class:`PromotionFailed` (``trainer.py``).

Crash-consistent rotating snapshots live with the checkpoint format in
``dislib_tpu.utils.checkpoint``; the deterministic fault-injection harness
driving ``tests/test_resilience.py`` is ``dislib_tpu.utils.faults``.
"""

from dislib_tpu.runtime import xla_flags  # noqa: F401
from dislib_tpu.runtime import health  # noqa: F401
from dislib_tpu.runtime.adoption import (Adoption, AdoptionRejected,
                                         adopt_latest, generation_token)
from dislib_tpu.runtime.bundle_io import (BundleIncompatible,
                                          BundleShardCorrupt, read_bundle,
                                          write_bundle)
from dislib_tpu.runtime.coord import (CapacityLedger, CoordinationTimeout,
                                      FileCoordinator, KVCoordinator,
                                      LeaseKeeper, LocalCoordinator,
                                      Membership, RankDead, TornCoordFile,
                                      barrier_timeout, current_membership,
                                      get_coordinator, lease_seconds,
                                      resilient_exchange, set_membership)
from dislib_tpu.runtime.elastic import AsyncFetch, fetch, repad_rows
from dislib_tpu.runtime.health import (ChunkGuard, HealthPolicy,
                                       NumericalDivergence, WatchdogTimeout)
from dislib_tpu.runtime.preemption import (
    Preempted, PreemptionWatcher, capacity_target, clear_capacity,
    clear_preemption, last_signal, preemption_requested,
    raise_if_preempted, request_capacity, request_preemption,
)
from dislib_tpu.runtime.retry import Retry, is_transient_error, retry_call
from dislib_tpu.runtime.fitloop import (ChunkedFitLoop, ChunkOutcome,
                                        Escalation, EscalationLadder,
                                        LoopState)
from dislib_tpu.runtime.trainer import ContinuousTrainer, PromotionFailed

__all__ = [
    "Preempted", "PreemptionWatcher", "preemption_requested",
    "request_preemption", "clear_preemption", "last_signal",
    "raise_if_preempted",
    "capacity_target", "request_capacity", "clear_capacity",
    "Retry", "retry_call", "is_transient_error",
    "repad_rows", "fetch", "AsyncFetch",
    "HealthPolicy", "ChunkGuard", "NumericalDivergence", "WatchdogTimeout",
    "Adoption", "AdoptionRejected", "adopt_latest", "generation_token",
    "BundleIncompatible", "BundleShardCorrupt", "read_bundle",
    "write_bundle",
    "CapacityLedger", "CoordinationTimeout", "get_coordinator",
    "LocalCoordinator", "FileCoordinator", "KVCoordinator",
    "Membership", "LeaseKeeper", "RankDead", "TornCoordFile",
    "set_membership", "current_membership", "resilient_exchange",
    "lease_seconds", "barrier_timeout",
    "ChunkedFitLoop", "ChunkOutcome", "LoopState", "Escalation",
    "EscalationLadder",
    "ContinuousTrainer", "PromotionFailed",
    "health", "xla_flags",
]
