"""Cross-process coordination primitives (round-19 data plane).

Two multi-host protocols in this library need hosts to AGREE on something
small before any of them acts: the sharded-bundle load barrier (every
host verifies its shard before ANY host serves) and the global capacity
level (a fleet shrinks and grows coherently, not one process at a time).
Both reduce to the same primitive — a named, ranked **exchange**: each
participant posts one small JSON-serializable value under a name, then
blocks until all ``n`` values are visible, and every participant returns
the same ``{rank: value}`` dict.

Three transports implement it, picked by :func:`get_coordinator`:

- :class:`KVCoordinator` — the ``jax.distributed`` coordination
  service's key-value store, when the process is part of an initialized
  distributed runtime.  This is the production transport: the KV store
  is platform-agnostic (it works on CPU rigs whose *collectives* are
  unsupported — the coordination channel and the compute channel are
  independent).
- :class:`FileCoordinator` — a shared directory (``DSLIB_COORD_DIR``);
  each post is an atomic tmp-write + rename, the gather polls.  The
  transport for fleets coordinated through a shared filesystem and for
  the two-process dryrun on rigs whose jaxlib predates multiprocess CPU.
- :class:`LocalCoordinator` — in-memory, thread-safe; the single-process
  default.  With the ``DSLIB_MOCK_HOSTS`` overlay, tier-1 tests drive
  every rank of a protocol through one of these, so the barrier logic
  itself is exercised on every run — not only on multi-host rigs.

The **capacity ledger** (:class:`CapacityLedger`) rides the same atomic
file discipline: one JSON record ``{epoch, target, writer, crc}``
rewritten in place by atomic rename.  Readers treat ANY incoherent state
(missing file, torn JSON, bad crc) as "no statement" — the fleet holds
its current size rather than acting on garbage — and concurrent writers
resolve by last-coherent-rename-wins, asserted by the ledger race test.

Round 20 adds **membership**: every rank renews a heartbeat *lease*
(:class:`Membership` / :class:`LeaseKeeper`, period from
``DSLIB_COORD_LEASE_MS``); an exchange whose missing peer holds an
EXPIRED lease raises the typed, attributed :class:`RankDead` instead of
a generic timeout, so survivors know *who* died and *when*.  A restarted
rank rejoins under a bumped **epoch** and values it posted under the old
epoch are fenced out of every gather — last-coherent-wins extended to
membership.  On a confirmed death the detecting survivor publishes a
shrunk target to the capacity ledger (``death:rank<r>``) and the
existing elastic rungs heal every surviving fit; a rejoin clears it
(``rejoin:rank<r>``) and the fleet grows back.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
import zlib

__all__ = ["CoordinationTimeout", "RankDead", "TornCoordFile",
           "LocalCoordinator", "FileCoordinator", "KVCoordinator",
           "get_coordinator", "CapacityLedger", "Membership",
           "LeaseKeeper", "set_membership", "current_membership",
           "resilient_exchange", "lease_seconds", "barrier_timeout"]

_POLL_S = 0.02

#: reserved exchange name under which leases are posted; never clear()ed
_LEASE_NAME = "__lease__"


def lease_seconds() -> float:
    """The lease TTL in seconds — ``DSLIB_COORD_LEASE_MS`` (default
    2000 ms).  A rank whose lease is older than this is presumed dead."""
    try:
        return max(1.0, float(os.environ.get("DSLIB_COORD_LEASE_MS",
                                             "2000"))) / 1000.0
    except ValueError:
        return 2.0


def barrier_timeout(default: float = 30.0) -> float:
    """Fleet-barrier deadline in seconds — ``DSLIB_BARRIER_TIMEOUT``.
    One dead host must abort ALL hosts typed within this budget."""
    try:
        return float(os.environ.get("DSLIB_BARRIER_TIMEOUT", default))
    except ValueError:
        return float(default)


class CoordinationTimeout(RuntimeError):
    """An exchange did not see all participants' values in time — a peer
    died, hung, or never reached the barrier.  Carries the ranks that
    were still missing for the postmortem."""

    def __init__(self, message, missing=()):
        super().__init__(message)
        self.missing = tuple(missing)


class RankDead(CoordinationTimeout):
    """A peer's heartbeat lease EXPIRED — not "slow", confirmed missing.
    Subclasses :class:`CoordinationTimeout` so existing barrier handlers
    still catch it, but classified FATAL by ``runtime.retry`` (retrying
    cannot resurrect a dead process; healing goes through the capacity
    ledger instead).  Attributed: carries ``rank``, ``last_seen`` (wall
    clock of the final heartbeat) and the lease ``epoch``."""

    def __init__(self, rank: int, last_seen: float, epoch: int = 0,
                 message: str | None = None):
        if message is None:
            message = (f"rank {int(rank)} is dead — lease (epoch "
                       f"{int(epoch)}) expired, last heartbeat at "
                       f"{float(last_seen):.3f}")
        super().__init__(message, missing=(int(rank),))
        self.rank = int(rank)
        self.last_seen = float(last_seen)
        self.epoch = int(epoch)


class TornCoordFile(CoordinationTimeout):
    """A coordination file existed but failed its CRC / JSON parse — a
    reader raced a (possibly killed) non-atomic writer.  TRANSIENT: the
    writer re-posting heals it, so readers retry through
    ``runtime.Retry`` rather than killing a healthy fleet."""

    def __init__(self, path: str, reason: str = "bad crc"):
        super().__init__(f"torn coordination file {path!r} ({reason})")
        self.path = str(path)
        self.reason = str(reason)


def _deadline(timeout: float) -> float:
    return time.monotonic() + float(timeout)


def _check_membership(missing) -> None:
    """Poll-loop hook shared by every transport's exchange: when a
    process-global :class:`Membership` is registered and one of the
    still-missing ranks holds an EXPIRED lease, abort the wait with the
    attributed :class:`RankDead` now — don't burn the rest of the
    timeout waiting for a process that cannot arrive."""
    m = _MEMBERSHIP
    if m is not None:
        m.raise_if_dead(missing)


class LocalCoordinator:
    """In-memory exchange — the single-process transport.  Thread-safe:
    concurrent ranks (mock hosts on threads, or a test pre-posting peer
    votes) rendezvous on one condition variable."""

    def __init__(self):
        self._lock = threading.Condition()
        self._store: dict = {}

    def post(self, name: str, rank: int, value) -> None:
        with self._lock:
            self._store[(str(name), int(rank))] = value
            self._lock.notify_all()

    def peek(self, name: str, rank: int):
        """The value posted under ``(name, rank)``, or None — never
        blocks (lease reads and fenced gathers poll through this)."""
        with self._lock:
            return self._store.get((str(name), int(rank)))

    def exchange(self, name: str, rank: int, value, n: int,
                 timeout: float = 30.0) -> dict:
        self.post(name, rank, value)
        end = _deadline(timeout)
        with self._lock:
            while True:
                got = {r: v for (nm, r), v in self._store.items()
                       if nm == str(name)}
                if len(got) >= int(n):
                    return {r: got[r] for r in sorted(got)}
                missing = sorted(set(range(int(n))) - set(got))
                _check_membership(missing)
                left = end - time.monotonic()
                # wait in lease-sized slices when membership is live so
                # an expiring peer is noticed mid-wait, not post-timeout
                slice_ = left if _MEMBERSHIP is None else min(left, 0.05)
                if left <= 0 or not self._lock.wait(slice_):
                    if time.monotonic() < end:
                        continue
                    raise CoordinationTimeout(
                        f"exchange {name!r}: {len(got)}/{n} values after "
                        f"{timeout}s — missing ranks {missing}", missing)

    def clear(self, name: str) -> None:
        with self._lock:
            for k in [k for k in self._store if k[0] == str(name)]:
                del self._store[k]


def _post_crc(value) -> str:
    payload = json.dumps(value)
    return f"{zlib.crc32(payload.encode()) & 0xFFFFFFFF:08x}"


class FileCoordinator:
    """Shared-directory exchange: each post is one atomically-renamed
    JSON file ``<dir>/<name>.<rank>.json``; the gather polls for all
    ``n``.  Rename atomicity means a healthy writer can never expose a
    torn post — but a chaos-injected or crashed NON-atomic writer can,
    so payloads carry a CRC (like the capacity ledger) and a file that
    exists-but-fails-verification is classified TRANSIENT
    (:class:`TornCoordFile`) and retried through ``runtime.Retry``: the
    writer re-posting heals it, and a reader racing a writer never
    kills a healthy fleet."""

    _MISSING = object()                 # peek sentinel: no file at all

    def __init__(self, directory: str):
        self.directory = str(directory)

    def _path(self, name, rank):
        return os.path.join(self.directory, f"{name}.{int(rank)}.json")

    def post(self, name: str, rank: int, value) -> None:
        os.makedirs(self.directory, exist_ok=True)
        payload = json.dumps({"crc": _post_crc(value), "v": value}).encode()
        fd, tmp = tempfile.mkstemp(dir=self.directory)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path(name, rank))
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise

    def _read_once(self, path: str):
        """One verification attempt: ``_MISSING`` when the file does not
        exist, the payload when coherent, :class:`TornCoordFile` when it
        exists but fails parse/CRC."""
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return self._MISSING
        try:
            rec = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError) as e:
            raise TornCoordFile(path, f"unparseable ({e})") from e
        if isinstance(rec, dict) and set(rec) == {"crc", "v"}:
            if rec["crc"] != _post_crc(rec["v"]):
                raise TornCoordFile(path, "crc mismatch")
            return rec["v"]
        return rec                      # pre-round-20 bare payload

    def _read(self, path: str):
        """Read one post, retrying a torn file through ``runtime.Retry``
        (``DSLIB_COORD_READ_RETRIES``, default 3 — a racing writer's
        re-post heals it within a poll or two).  Still torn after the
        budget → ``_MISSING``: the outer gather keeps polling and its
        eventual timeout names the rank, so a permanently-torn file
        degrades to "never posted", not a fleet kill."""
        from dislib_tpu.runtime.retry import Retry
        from dislib_tpu.utils.profiling import count_resilience
        attempts = int(os.environ.get("DSLIB_COORD_READ_RETRIES", "3"))
        try:
            return Retry(attempts=max(1, attempts), backoff=_POLL_S,
                         max_backoff=0.25, jitter=0.0).call(
                self._read_once, path)
        except TornCoordFile:
            count_resilience("coord_torn_reads")
            return self._MISSING

    def peek(self, name: str, rank: int):
        v = self._read(self._path(name, rank))
        return None if v is self._MISSING else v

    def exchange(self, name: str, rank: int, value, n: int,
                 timeout: float = 30.0) -> dict:
        self.post(name, rank, value)
        end = _deadline(timeout)
        while True:
            got = {}
            for r in range(int(n)):
                v = self._read(self._path(name, r))
                if v is not self._MISSING:
                    got[r] = v
            if len(got) >= int(n):
                return got
            missing = sorted(set(range(int(n))) - set(got))
            _check_membership(missing)
            if time.monotonic() >= end:
                raise CoordinationTimeout(
                    f"exchange {name!r} in {self.directory}: {len(got)}/"
                    f"{n} values after {timeout}s — missing ranks "
                    f"{missing}", missing)
            time.sleep(_POLL_S)

    def clear(self, name: str) -> None:
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for fn in names:
            if fn.startswith(f"{name}.") and fn.endswith(".json"):
                try:
                    os.remove(os.path.join(self.directory, fn))
                except OSError:
                    pass


class KVCoordinator:
    """Exchange over the ``jax.distributed`` coordination service's KV
    store — available whenever ``parallel.initialize()`` ran, on every
    platform (the KV channel does not require collective support)."""

    def __init__(self, client=None):
        if client is None:
            from jax._src import distributed as _jd
            client = _jd.global_state.client
        if client is None:
            raise RuntimeError(
                "KVCoordinator needs an initialized jax.distributed "
                "runtime (dislib_tpu.parallel.initialize())")
        self._client = client

    def post(self, name: str, rank: int, value) -> None:
        key = f"dslib/{name}/{int(rank)}"
        payload = json.dumps(value)
        try:
            # overwrite: lease renewals rewrite their key every beat,
            # and a retried exchange must be able to re-post its vote
            self._client.key_value_set(key, payload, True)
        except TypeError:               # jaxlib without allow_overwrite
            self._client.key_value_set(key, payload)

    def peek(self, name: str, rank: int):
        """Non-blocking single read via the directory listing — the KV
        store has no try-get, but ``key_value_dir_get`` returns only
        keys that exist."""
        try:
            entries = self._client.key_value_dir_get(f"dslib/{name}/")
        except Exception:               # noqa: BLE001 — absent prefix
            return None
        suffix = f"/{int(rank)}"
        for key, raw in entries:
            if key.endswith(suffix):
                return json.loads(raw)
        return None

    def exchange(self, name: str, rank: int, value, n: int,
                 timeout: float = 30.0) -> dict:
        self.post(name, rank, value)
        got = {}
        end = _deadline(timeout)
        # blocking gets run in lease-sized slices so an expired peer is
        # reported as RankDead mid-wait instead of a generic timeout
        slice_ms = 250 if _MEMBERSHIP is not None else None
        for r in range(int(n)):
            while True:
                left = end - time.monotonic()
                if left <= 0:
                    raise CoordinationTimeout(
                        f"exchange {name!r}: rank {r} never posted "
                        f"within {timeout}s", [r])
                ms = max(1, int(left * 1000))
                if slice_ms is not None:
                    ms = min(ms, slice_ms)
                try:
                    raw = self._client.blocking_key_value_get(
                        f"dslib/{name}/{r}", ms)
                    got[r] = json.loads(raw)
                    break
                except Exception as e:  # noqa: BLE001 — timeout is typed
                    _check_membership([r])
                    left = end - time.monotonic()
                    if left > 0:
                        time.sleep(min(_POLL_S, left))  # service-error pace
                        continue
                    raise CoordinationTimeout(
                        f"exchange {name!r}: rank {r} never posted "
                        f"within {timeout}s ({e})", [r]) from e
        return got

    def clear(self, name: str) -> None:
        pass                            # KV keys are epoch-named by callers


_LOCAL = LocalCoordinator()


def get_coordinator():
    """The transport for this process, by precedence: ``DSLIB_COORD_DIR``
    (shared filesystem — explicit wins, it also serves rigs whose jaxlib
    lacks multiprocess CPU), then the ``jax.distributed`` KV store when
    initialized, else the in-process :class:`LocalCoordinator` singleton
    (single-process deployments and the mock-host tier-1 tests)."""
    d = os.environ.get("DSLIB_COORD_DIR")
    if d:
        return FileCoordinator(d)
    try:
        from dislib_tpu.parallel import distributed as _dist
        if _dist.is_initialized():
            return KVCoordinator()
    except Exception:                   # noqa: BLE001 — fall to local
        pass
    return _LOCAL


# ---------------------------------------------------------------------------
# membership: heartbeat leases, epoch fencing, death → capacity
# ---------------------------------------------------------------------------

_MEMBERSHIP = None                     # process-global, set_membership()


def set_membership(membership) -> None:
    """Register (or clear, with None) the process-global membership.
    Once registered, EVERY coordinator exchange in this process becomes
    death-aware: a missing peer whose lease expired aborts the wait with
    :class:`RankDead` instead of burning the timeout."""
    global _MEMBERSHIP
    _MEMBERSHIP = membership


def current_membership():
    return _MEMBERSHIP


class Membership:
    """Lease-based fleet membership over any coordinator transport.

    Each live rank posts a lease record ``{"epoch", "t"}`` under the
    reserved exchange name ``__lease__`` and renews it every
    ``lease/3`` seconds (:class:`LeaseKeeper`).  A lease older than
    ``DSLIB_COORD_LEASE_MS`` is an expired peer: :meth:`raise_if_dead`
    raises the attributed :class:`RankDead` and :meth:`poll` converts
    the observation into fleet healing —

    - **death** → ``rank_deaths`` counted, and (``heal_capacity=True``)
      the shrunk per-host device target ``max(1, devices·live//n)`` is
      published through ``runtime.preemption.request_capacity`` with
      writer ``death:rank<r>`` — every surviving fit's elastic rungs
      take it from there;
    - **rejoin** (a dead rank's lease reappears — a restart under a
      bumped epoch, or a delayed heartbeat resuming) → ``rank_rejoins``
      counted and the capacity statement is recomputed (cleared when
      the whole fleet is back).

    **Epoch fencing**: :meth:`join` bumps the epoch found in any prior
    lease, :meth:`post`/:meth:`gather`/:meth:`exchange` stamp values
    with the writer's epoch, and a gather drops values whose epoch is
    older than the writer's CURRENT lease — a restarted rank's stale
    pre-crash posts can never satisfy a post-restart barrier
    (last-coherent-wins, extended to membership).

    ``clock``/``sleep`` are injectable so tier-1 tests drive expiry with
    a mocked clock — no real waits.  ``devices`` is the per-host device
    count used for shrunk targets (defaults to
    ``jax.local_device_count()`` at first use).
    """

    def __init__(self, rank: int, n: int, coord=None, lease_ms=None,
                 clock=time.time, sleep=time.sleep, devices=None,
                 heal_capacity: bool = True):
        self.rank = int(rank)
        self.n = int(n)
        self.coord = coord if coord is not None else get_coordinator()
        self.lease_s = (float(lease_ms) / 1000.0 if lease_ms is not None
                        else lease_seconds())
        self._clock = clock
        self._sleep = sleep
        self._devices = devices
        self.heal_capacity = bool(heal_capacity)
        self.epoch = 0
        self._dead: dict = {}           # rank -> epoch at death report
        self._lock = threading.Lock()   # poll() runs on the keeper thread

    # -- leases ------------------------------------------------------------

    def join(self) -> int:
        """Enter (or re-enter) the fleet: bump past any prior lease's
        epoch — a restart rejoins under a NEW epoch so its old posts are
        fenced — and publish the first heartbeat.  Returns the epoch."""
        prior = self.coord.peek(_LEASE_NAME, self.rank)
        prior_epoch = int(prior["epoch"]) if prior else 0
        self.epoch = prior_epoch + 1
        self.heartbeat()
        return self.epoch

    def heartbeat(self) -> None:
        """Renew this rank's lease (LeaseKeeper calls this every
        ``lease/3`` seconds; call it manually at natural boundaries in
        keeper-less deployments)."""
        self.coord.post(_LEASE_NAME, self.rank,
                        {"epoch": self.epoch, "t": float(self._clock())})

    def lease_of(self, rank: int):
        """``{"epoch", "t"}`` for a rank, or None when it never joined."""
        rec = self.coord.peek(_LEASE_NAME, int(rank))
        if isinstance(rec, dict) and "epoch" in rec and "t" in rec:
            return {"epoch": int(rec["epoch"]), "t": float(rec["t"])}
        return None

    def dead(self, ranks=None):
        """Expired peers among ``ranks`` (default: all peers) as
        ``[(rank, last_seen, epoch), ...]``.  A rank with NO lease is
        merely missing, not dead — only a lease that stopped renewing
        is evidence of death."""
        now = float(self._clock())
        if ranks is None:
            ranks = range(self.n)
        out = []
        for r in ranks:
            r = int(r)
            if r == self.rank:
                continue
            lease = self.lease_of(r)
            if lease is not None and now - lease["t"] > self.lease_s:
                out.append((r, lease["t"], lease["epoch"]))
        return out

    def raise_if_dead(self, ranks=None) -> None:
        """Raise :class:`RankDead` for the first expired peer among
        ``ranks`` (default all peers); no-op when everyone's fresh."""
        for r, last_seen, epoch in self.dead(ranks):
            raise RankDead(r, last_seen, epoch)

    # -- death / rejoin → capacity ------------------------------------------

    def _local_devices(self) -> int:
        if self._devices is None:
            import jax
            self._devices = int(jax.local_device_count())
        return int(self._devices)

    def _publish_capacity(self, writer: str) -> None:
        if not self.heal_capacity:
            return
        from dislib_tpu.runtime import preemption
        live = self.n - len(self._dead)
        if live >= self.n:
            preemption.clear_capacity(writer=writer)
        else:
            target = max(1, self._local_devices() * live // self.n)
            preemption.request_capacity(target, writer=writer)

    def poll(self):
        """One membership sweep: detect new deaths and rejoins, count
        them (``rank_deaths`` / ``rank_rejoins``), steer the capacity
        level, and return the events as
        ``[("death", rank, last_seen) | ("rejoin", rank, epoch), ...]``
        (idempotent — a death is reported once per lease epoch)."""
        from dislib_tpu.utils.profiling import count_resilience
        events = []
        now = float(self._clock())
        with self._lock:
            for r in range(self.n):
                if r == self.rank:
                    continue
                lease = self.lease_of(r)
                if lease is None:
                    continue
                expired = now - lease["t"] > self.lease_s
                if expired and r not in self._dead:
                    self._dead[r] = lease["epoch"]
                    count_resilience("rank_deaths")
                    self._publish_capacity(f"death:rank{r}")
                    events.append(("death", r, lease["t"]))
                elif not expired and r in self._dead:
                    del self._dead[r]
                    count_resilience("rank_rejoins")
                    self._publish_capacity(f"rejoin:rank{r}")
                    events.append(("rejoin", r, lease["epoch"]))
        return events

    def stats(self) -> dict:
        with self._lock:
            return {"rank": self.rank, "n": self.n, "epoch": self.epoch,
                    "lease_s": self.lease_s,
                    "dead_ranks": sorted(self._dead)}

    # -- epoch-fenced posts ---------------------------------------------------

    def post(self, name: str, value) -> None:
        """Post a value stamped with this rank's epoch."""
        self.coord.post(name, self.rank,
                        {"__epoch__": self.epoch, "v": value})

    def _fenced(self, rank: int, rec):
        """Unwrap an epoch-stamped value; STALE (epoch older than the
        rank's current lease) → fenced out, returns the ``_FENCED``
        sentinel.  Bare (pre-round-20) values pass through."""
        if not (isinstance(rec, dict) and "__epoch__" in rec):
            return rec
        lease = self.lease_of(rank)
        if lease is not None and int(rec["__epoch__"]) < lease["epoch"]:
            return _FENCED
        return rec.get("v")

    def gather(self, name: str, n=None) -> dict:
        """Non-blocking fenced gather: every currently-visible,
        non-stale value under ``name`` as ``{rank: value}``."""
        got = {}
        for r in range(int(n) if n is not None else self.n):
            rec = self.coord.peek(name, r)
            if rec is None:
                continue
            v = self._fenced(r, rec)
            if v is not _FENCED:
                got[r] = v
        return got

    def exchange(self, name: str, value, n=None, timeout: float = 30.0):
        """The ranked exchange, membership-hardened: posts are
        epoch-stamped, stale peers' values are fenced out, and a missing
        peer whose lease expired raises :class:`RankDead` immediately.
        Polls through the injected clock/sleep (mock-clock testable)."""
        n = int(n) if n is not None else self.n
        self.post(name, value)
        start = float(self._clock())
        while True:
            got = self.gather(name, n)
            if len(got) >= n:
                return {r: got[r] for r in sorted(got)}
            missing = sorted(set(range(n)) - set(got))
            self.raise_if_dead(missing)
            if float(self._clock()) - start >= float(timeout):
                raise CoordinationTimeout(
                    f"exchange {name!r}: {len(got)}/{n} values after "
                    f"{timeout}s — missing ranks {missing}", missing)
            self._sleep(_POLL_S)


_FENCED = object()


class LeaseKeeper(threading.Thread):
    """Daemon thread that renews this rank's lease and (``watch=True``)
    polls membership so deaths and rejoins are detected — and converted
    into capacity statements — while the main thread is deep inside a
    fit step.  ``gate`` is the fault-injection seam: a callable polled
    before each renewal; returning False SKIPS that beat (see
    ``utils.faults.LeaseExpiry``).  :meth:`step` runs one iteration
    synchronously for thread-free tests."""

    def __init__(self, membership: Membership, interval_s=None,
                 watch: bool = True, gate=None):
        super().__init__(daemon=True, name="dslib-lease-keeper")
        self.membership = membership
        self.interval_s = (float(interval_s) if interval_s is not None
                           else membership.lease_s / 3.0)
        self.watch = bool(watch)
        self.gate = gate
        # NOT self._stop: threading.Thread.join() calls a private
        # _stop() internally — shadowing it with an Event breaks join
        self._halt = threading.Event()

    def step(self) -> list:
        """One keeper iteration: renew (unless gated), then poll."""
        if self.gate is None or self.gate():
            self.membership.heartbeat()
        return self.membership.poll() if self.watch else []

    def run(self) -> None:
        while not self._halt.is_set():
            try:
                self.step()
            except Exception:           # noqa: BLE001 — keeper never dies
                pass
            self._halt.wait(self.interval_s)

    def stop(self, join: bool = True) -> None:
        self._halt.set()
        if join and self.is_alive():
            self.join(timeout=5.0)


def resilient_exchange(coord, name: str, rank: int, value, n: int,
                       timeout: float = 30.0, retry=None) -> dict:
    """Exchange with the round-20 degradation policy: transient
    :class:`CoordinationTimeout` s are retried through ``runtime.Retry``
    (a slow peer gets more chances), :class:`RankDead` escalates
    IMMEDIATELY (retrying cannot resurrect a process — healing belongs
    to the capacity ledger).  The total wall budget stays ≈ ``timeout``:
    each attempt gets ``timeout/attempts``, so barrier deadlines hold."""
    from dislib_tpu.runtime.retry import Retry
    if retry is None:
        attempts = max(1, int(os.environ.get("DSLIB_COORD_RETRIES", "2")))
        retry = Retry(attempts=attempts, backoff=min(0.05, _POLL_S * 2),
                      max_backoff=0.5, jitter=0.0)
    per_attempt = float(timeout) / retry.attempts
    return retry.call(coord.exchange, name, rank, value, n,
                      timeout=per_attempt)


# ---------------------------------------------------------------------------
# the global capacity ledger
# ---------------------------------------------------------------------------

def _ledger_crc(epoch: int, target, writer: str) -> int:
    return zlib.crc32(f"{epoch}:{target}:{writer}".encode()) & 0xFFFFFFFF


class CapacityLedger:
    """The fleet-wide capacity level as ONE shared, atomically-replaced
    JSON record: ``{"epoch", "target", "writer", "crc"}``.

    - :meth:`read` returns ``(target, epoch)``; a missing file, torn
      JSON, or crc mismatch is "no statement" — ``(None, 0)`` — so an
      incoherent ledger can never shrink a fleet.
    - :meth:`publish` stamps ``epoch = read_epoch + 1`` and replaces the
      record atomically.  Two racing writers both rename complete
      records; whichever rename lands LAST wins and the loser's record
      simply vanishes — last-coherent-wins, no torn state possible.
    """

    def __init__(self, path: str):
        self.path = str(path)

    def read(self):
        """``(target_devices | None, epoch)`` — the current coherent
        statement, or ``(None, 0)`` when there is none."""
        try:
            with open(self.path, "rb") as f:
                rec = json.loads(f.read().decode())
            epoch = int(rec["epoch"])
            target = rec["target"]
            if target is not None:
                target = int(target)
            if int(rec["crc"]) != _ledger_crc(epoch, target,
                                              str(rec["writer"])):
                return None, 0          # foreign or damaged record
            return target, epoch
        except (OSError, ValueError, KeyError, TypeError):
            return None, 0

    def publish(self, target, writer: str = "") -> int:
        """Publish a new capacity ``target`` (None = capacity unmanaged);
        returns the epoch stamped on the record."""
        _, epoch = self.read()
        epoch += 1
        if target is not None:
            target = int(target)
        rec = {"epoch": epoch, "target": target, "writer": str(writer),
               "crc": _ledger_crc(epoch, target, str(writer))}
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(json.dumps(rec).encode())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
        return epoch
