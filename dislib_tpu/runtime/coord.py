"""Cross-process coordination primitives (round-19 data plane).

Two multi-host protocols in this library need hosts to AGREE on something
small before any of them acts: the sharded-bundle load barrier (every
host verifies its shard before ANY host serves) and the global capacity
level (a fleet shrinks and grows coherently, not one process at a time).
Both reduce to the same primitive — a named, ranked **exchange**: each
participant posts one small JSON-serializable value under a name, then
blocks until all ``n`` values are visible, and every participant returns
the same ``{rank: value}`` dict.

Three transports implement it, picked by :func:`get_coordinator`:

- :class:`KVCoordinator` — the ``jax.distributed`` coordination
  service's key-value store, when the process is part of an initialized
  distributed runtime.  This is the production transport: the KV store
  is platform-agnostic (it works on CPU rigs whose *collectives* are
  unsupported — the coordination channel and the compute channel are
  independent).
- :class:`FileCoordinator` — a shared directory (``DSLIB_COORD_DIR``);
  each post is an atomic tmp-write + rename, the gather polls.  The
  transport for fleets coordinated through a shared filesystem and for
  the two-process dryrun on rigs whose jaxlib predates multiprocess CPU.
- :class:`LocalCoordinator` — in-memory, thread-safe; the single-process
  default.  With the ``DSLIB_MOCK_HOSTS`` overlay, tier-1 tests drive
  every rank of a protocol through one of these, so the barrier logic
  itself is exercised on every run — not only on multi-host rigs.

The **capacity ledger** (:class:`CapacityLedger`) rides the same atomic
file discipline: one JSON record ``{epoch, target, writer, crc}``
rewritten in place by atomic rename.  Readers treat ANY incoherent state
(missing file, torn JSON, bad crc) as "no statement" — the fleet holds
its current size rather than acting on garbage — and concurrent writers
resolve by last-coherent-rename-wins, asserted by the ledger race test.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
import zlib

__all__ = ["CoordinationTimeout", "LocalCoordinator", "FileCoordinator",
           "KVCoordinator", "get_coordinator", "CapacityLedger"]

_POLL_S = 0.02


class CoordinationTimeout(RuntimeError):
    """An exchange did not see all participants' values in time — a peer
    died, hung, or never reached the barrier.  Carries the ranks that
    were still missing for the postmortem."""

    def __init__(self, message, missing=()):
        super().__init__(message)
        self.missing = tuple(missing)


def _deadline(timeout: float) -> float:
    return time.monotonic() + float(timeout)


class LocalCoordinator:
    """In-memory exchange — the single-process transport.  Thread-safe:
    concurrent ranks (mock hosts on threads, or a test pre-posting peer
    votes) rendezvous on one condition variable."""

    def __init__(self):
        self._lock = threading.Condition()
        self._store: dict = {}

    def post(self, name: str, rank: int, value) -> None:
        with self._lock:
            self._store[(str(name), int(rank))] = value
            self._lock.notify_all()

    def exchange(self, name: str, rank: int, value, n: int,
                 timeout: float = 30.0) -> dict:
        self.post(name, rank, value)
        end = _deadline(timeout)
        with self._lock:
            while True:
                got = {r: v for (nm, r), v in self._store.items()
                       if nm == str(name)}
                if len(got) >= int(n):
                    return {r: got[r] for r in sorted(got)}
                left = end - time.monotonic()
                if left <= 0 or not self._lock.wait(left):
                    missing = sorted(set(range(int(n))) - set(got))
                    raise CoordinationTimeout(
                        f"exchange {name!r}: {len(got)}/{n} values after "
                        f"{timeout}s — missing ranks {missing}", missing)

    def clear(self, name: str) -> None:
        with self._lock:
            for k in [k for k in self._store if k[0] == str(name)]:
                del self._store[k]


class FileCoordinator:
    """Shared-directory exchange: each post is one atomically-renamed
    JSON file ``<dir>/<name>.<rank>.json``; the gather polls for all
    ``n``.  Rename atomicity means a reader can never observe a torn
    post — a file either doesn't exist yet or is complete."""

    def __init__(self, directory: str):
        self.directory = str(directory)

    def _path(self, name, rank):
        return os.path.join(self.directory, f"{name}.{int(rank)}.json")

    def post(self, name: str, rank: int, value) -> None:
        os.makedirs(self.directory, exist_ok=True)
        payload = json.dumps(value).encode()
        fd, tmp = tempfile.mkstemp(dir=self.directory)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path(name, rank))
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise

    def exchange(self, name: str, rank: int, value, n: int,
                 timeout: float = 30.0) -> dict:
        self.post(name, rank, value)
        end = _deadline(timeout)
        while True:
            got = {}
            for r in range(int(n)):
                p = self._path(name, r)
                try:
                    with open(p, "rb") as f:
                        got[r] = json.loads(f.read().decode())
                except (OSError, ValueError):
                    continue            # not posted yet (or mid-rename)
            if len(got) >= int(n):
                return got
            if time.monotonic() >= end:
                missing = sorted(set(range(int(n))) - set(got))
                raise CoordinationTimeout(
                    f"exchange {name!r} in {self.directory}: {len(got)}/"
                    f"{n} values after {timeout}s — missing ranks "
                    f"{missing}", missing)
            time.sleep(_POLL_S)

    def clear(self, name: str) -> None:
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for fn in names:
            if fn.startswith(f"{name}.") and fn.endswith(".json"):
                try:
                    os.remove(os.path.join(self.directory, fn))
                except OSError:
                    pass


class KVCoordinator:
    """Exchange over the ``jax.distributed`` coordination service's KV
    store — available whenever ``parallel.initialize()`` ran, on every
    platform (the KV channel does not require collective support)."""

    def __init__(self, client=None):
        if client is None:
            from jax._src import distributed as _jd
            client = _jd.global_state.client
        if client is None:
            raise RuntimeError(
                "KVCoordinator needs an initialized jax.distributed "
                "runtime (dislib_tpu.parallel.initialize())")
        self._client = client

    def post(self, name: str, rank: int, value) -> None:
        self._client.key_value_set(f"dslib/{name}/{int(rank)}",
                                   json.dumps(value))

    def exchange(self, name: str, rank: int, value, n: int,
                 timeout: float = 30.0) -> dict:
        self.post(name, rank, value)
        got = {}
        ms = max(1, int(float(timeout) * 1000))
        for r in range(int(n)):
            try:
                raw = self._client.blocking_key_value_get(
                    f"dslib/{name}/{r}", ms)
            except Exception as e:      # noqa: BLE001 — timeout is typed
                raise CoordinationTimeout(
                    f"exchange {name!r}: rank {r} never posted within "
                    f"{timeout}s ({e})", [r]) from e
            got[r] = json.loads(raw)
        return got

    def clear(self, name: str) -> None:
        pass                            # KV keys are epoch-named by callers


_LOCAL = LocalCoordinator()


def get_coordinator():
    """The transport for this process, by precedence: ``DSLIB_COORD_DIR``
    (shared filesystem — explicit wins, it also serves rigs whose jaxlib
    lacks multiprocess CPU), then the ``jax.distributed`` KV store when
    initialized, else the in-process :class:`LocalCoordinator` singleton
    (single-process deployments and the mock-host tier-1 tests)."""
    d = os.environ.get("DSLIB_COORD_DIR")
    if d:
        return FileCoordinator(d)
    try:
        from dislib_tpu.parallel import distributed as _dist
        if _dist.is_initialized():
            return KVCoordinator()
    except Exception:                   # noqa: BLE001 — fall to local
        pass
    return _LOCAL


# ---------------------------------------------------------------------------
# the global capacity ledger
# ---------------------------------------------------------------------------

def _ledger_crc(epoch: int, target, writer: str) -> int:
    return zlib.crc32(f"{epoch}:{target}:{writer}".encode()) & 0xFFFFFFFF


class CapacityLedger:
    """The fleet-wide capacity level as ONE shared, atomically-replaced
    JSON record: ``{"epoch", "target", "writer", "crc"}``.

    - :meth:`read` returns ``(target, epoch)``; a missing file, torn
      JSON, or crc mismatch is "no statement" — ``(None, 0)`` — so an
      incoherent ledger can never shrink a fleet.
    - :meth:`publish` stamps ``epoch = read_epoch + 1`` and replaces the
      record atomically.  Two racing writers both rename complete
      records; whichever rename lands LAST wins and the loser's record
      simply vanishes — last-coherent-wins, no torn state possible.
    """

    def __init__(self, path: str):
        self.path = str(path)

    def read(self):
        """``(target_devices | None, epoch)`` — the current coherent
        statement, or ``(None, 0)`` when there is none."""
        try:
            with open(self.path, "rb") as f:
                rec = json.loads(f.read().decode())
            epoch = int(rec["epoch"])
            target = rec["target"]
            if target is not None:
                target = int(target)
            if int(rec["crc"]) != _ledger_crc(epoch, target,
                                              str(rec["writer"])):
                return None, 0          # foreign or damaged record
            return target, epoch
        except (OSError, ValueError, KeyError, TypeError):
            return None, 0

    def publish(self, target, writer: str = "") -> int:
        """Publish a new capacity ``target`` (None = capacity unmanaged);
        returns the epoch stamped on the record."""
        _, epoch = self.read()
        epoch += 1
        if target is not None:
            target = int(target)
        rec = {"epoch": epoch, "target": target, "writer": str(writer),
               "crc": _ledger_crc(epoch, target, str(writer))}
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d)
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(json.dumps(rec).encode())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
        return epoch
