"""Environment-drift shims — keep the library importable and runnable
across the jax versions the fleet actually carries.

A resilience layer that only works on one exact jax build defeats its own
purpose: a preempted job frequently restarts on a machine imaged with a
different toolchain.  The one shim currently needed: ``jax.shard_map``
graduated from ``jax.experimental.shard_map`` (and its replication-check
kwarg was renamed ``check_rep`` → ``check_vma``) — on older jaxlibs the
top-level name is missing and every shard_map call site would die with
``AttributeError``.  :func:`ensure_jax_compat` installs a translating
alias ONLY when the top-level name is absent; on current jax it touches
nothing.
"""

from __future__ import annotations

__all__ = ["ensure_jax_compat"]


def ensure_jax_compat() -> None:
    """Install missing-API aliases on the imported ``jax`` module.
    Idempotent; a no-op on jax versions that already export the names."""
    import jax

    try:
        has_shard_map = hasattr(jax, "shard_map")
    except Exception:  # noqa: BLE001 — deprecation getattr can raise
        has_shard_map = False
    if not has_shard_map:
        from jax.experimental.shard_map import shard_map as _legacy_shard_map

        def _shard_map(f, /, *args, **kwargs):
            # check_vma's predecessor (check_rep) cannot express these
            # programs — it has no replication rule for while_loop, which
            # every convergence kernel here carries — so the replication
            # SANITIZER is off on legacy jax; current jax still runs it
            # (this shim only installs when jax.shard_map is absent)
            kwargs.pop("check_vma", None)
            kwargs["check_rep"] = False
            return _legacy_shard_map(f, *args, **kwargs)

        jax.shard_map = _shard_map

    # lax.pcast belongs to the same varying-axes (vma) machinery: on new
    # jax it marks a replicated value as varying for the replication
    # checker; computationally it is the identity.  Old shard_map's
    # check_rep tracks replication without explicit casts, so identity is
    # the faithful translation.
    from jax import lax
    if not hasattr(lax, "pcast"):
        def _pcast(x, axes, to=None):  # noqa: ARG001 — checker-only args
            return x
        lax.pcast = _pcast

    # jax.enable_x64 (context-manager form) graduated from
    # jax.experimental.enable_x64 — alias it where missing
    try:
        has_x64 = hasattr(jax, "enable_x64")
    except Exception:  # noqa: BLE001 — deprecation getattr can raise
        has_x64 = False
    if not has_x64:
        from jax.experimental import enable_x64 as _enable_x64
        jax.enable_x64 = _enable_x64
