"""Runtime health layer — fused numerical-health guards, chunk watchdog,
rollback-to-last-good remediation (the robustness counterpart of the
round-7 perf layer; SURVEY §6 "Failure detection / elastic recovery").

PR-1 made fits survive *external* faults (preemption, crash, flaky IO).
This layer makes them survive *internal* ones: a NaN/Inf that appears in a
loop carry, a diverging loss/inertia, a carry norm blowing up, or a chunk
whose force point never returns (hung collective).  Long-running
multi-chip jobs die most often to exactly these unguarded failures
(arXiv:2112.09017); DrJAX's lesson (PAPERS.md) is that the health signal
should ride INSIDE the compiled program, not as host round-trips.

Design, in the order a chunked fit loop meets it:

- **fused guards** — each chunk kernel computes a tiny health vector
  (:func:`health_vec`) from its final carries *inside the existing fused
  dispatch*: any-nonfinite over carries and inputs, the worst
  monotonicity violation over the chunk's loss history, and the carry
  norm.  Guarding therefore costs ZERO extra dispatches per chunk (the
  ``dispatch_count`` counters prove it in ``tests/test_health.py``).
- **watchdogged read** — :meth:`ChunkGuard.check` reads the vector
  through ``runtime.fetch(blocking=False)`` semantics (the copy is
  enqueued first) and resolves it under an optional deadline
  (``DSLIB_CHUNK_DEADLINE_S``).  A chunk whose force point hangs trips a
  typed :class:`WatchdogTimeout`; the resolution is escalated through the
  PR-1 :class:`~dislib_tpu.runtime.retry.Retry` policy before the fit
  aborts cleanly.
- **gated snapshots** — :meth:`ChunkGuard.save_async` refuses to write a
  snapshot for a chunk whose check tripped, so a bad state can never
  rotate the last GOOD generation out of the checkpoint.
- **remediation** — :meth:`ChunkGuard.remediate` applies the configured
  :class:`HealthPolicy` action: roll back to the last-good generation and
  re-run (``retry``), re-run with a doubled damping knob (``halve`` — the
  estimators that have one: GMM ``reg_covar``, ALS ``lambda_``), re-run
  with a seeded perturbation of the restored carries (``reseed``), or
  raise a diagnostic :class:`NumericalDivergence` carrying the estimator,
  iteration, tripped guard, and offending-carry coordinates (``raise``,
  and always once ``max_restarts`` is exhausted or no checkpoint exists
  to roll back to).

Only the nonfinite guards are armed by default: the monotonicity and
norm-growth thresholds are opt-in (``monotone_rtol`` / ``grow_limit``)
because legitimate fits may cross loose versions of them.  The
deterministic fault injectors driving every path live in
``dislib_tpu.utils.faults`` (NaN-at-chunk-k, divergence ramps, hung
chunks).
"""

from __future__ import annotations

import os
import threading

import numpy as np

__all__ = ["NumericalDivergence", "WatchdogTimeout", "HealthPolicy",
           "ChunkGuard", "Verdict", "Remediation", "NO_REMEDIATION",
           "guard", "health_vec", "check_snapshot", "HEALTH_BASE_LEN"]

# fixed slots of a health vector; per-carry (count, first_flat_index)
# pairs follow, one pair per guarded carry
HEALTH_BASE_LEN = 9
_SLOT_CARRY_NF = 0      # nonfinite total over carries
_SLOT_INPUT_NF = 1      # nonfinite total over inputs (not remediable)
_SLOT_RISE = 2          # worst monotonicity violation over the chunk
_SLOT_SCALE = 3         # max |loss| over the chunk (rise's reference scale)
_SLOT_MAX_ABS = 4       # max |carry| (norm-growth guard)
_SLOT_LOSS_NF = 5       # nonfinite entries in the chunk's loss history —
#                         catches a transient blow-up that washed out of
#                         the carries (e.g. one garbage E-step) but left
#                         the trajectory poisoned
_SLOT_LOSS_VALID = 6    # 1.0 when the chunk produced a loss history (an
#                         explicit flag, NOT a NaN sentinel in the value
#                         slots: fits run under jax.debug_nans in the
#                         sanitizer tier, which would flag the sentinel)
_SLOT_LOSS_FIRST = 7    # chunk's first loss value — the guard compares it
#                         against the PREVIOUS chunk's last loss so the
#                         monotone guard sees cross-chunk jumps too (and
#                         is not structurally dead at every=1, where each
#                         chunk has a single-entry history)
_SLOT_LOSS_LAST = 8     # chunk's last loss value (host-side carry-over)


class NumericalDivergence(RuntimeError):
    """A fit's numerical state went bad (non-finite carries, diverging
    loss, exploding norms) and the remediation policy could not (or was
    configured not to) heal it.  Carries everything a postmortem needs:
    the estimator, the iteration the guard tripped at, which guard, and
    the offending carry coordinates."""

    def __init__(self, message, estimator=None, iteration=None, guard=None,
                 detail=None):
        super().__init__(message)
        self.estimator = estimator
        self.iteration = iteration
        self.guard = guard
        self.detail = detail or {}


class WatchdogTimeout(TimeoutError):
    """A chunk's force point (the health-vector read) exceeded its
    deadline — a hung collective/dispatch.  Subclasses ``TimeoutError``
    so the default ``Retry`` classification treats it as transient, which
    is what lets the watchdog escalate through the PR-1 retry policy
    before the clean abort."""


class HealthPolicy:
    """Configuration for a fit's health guards.

    Parameters (env default in parentheses; the constructor wins)
    ----------
    action : 'retry' | 'halve' | 'reseed' | 'raise' (``DSLIB_HEALTH_ACTION``,
        default 'retry') — what :meth:`ChunkGuard.remediate` does on a
        recoverable trip.  'halve' doubles the guard's ``damping`` factor
        per restart (estimators with a damping knob apply it); 'reseed'
        perturbs the restored carries with a seeded jitter; both fall
        back to plain rollback-and-retry semantics where the estimator
        has no such knob.
    max_restarts : int (``DSLIB_HEALTH_MAX_RESTARTS``, default 2) —
        rollbacks allowed before the typed raise.
    deadline_s : float | None (``DSLIB_CHUNK_DEADLINE_S``, default off) —
        chunk watchdog deadline on the health read's force point.
    first_deadline_s : float | None (``DSLIB_CHUNK_FIRST_DEADLINE_S``,
        default ``10 * deadline_s``) — deadline for the guard's FIRST
        check only: that force point usually blocks on XLA compilation
        (tens of seconds for the larger kernels), which a steady-state
        deadline would misread as a hang.  Note a later chunk with a new
        static length (e.g. the final short chunk) also compiles — keep
        ``deadline_s`` above worst-case compile+chunk, not just chunk.
    monotone_rtol : float | None (``DSLIB_HEALTH_MONOTONE_RTOL``, default
        off) — trip when the chunk's loss history rises (falls, for
        increasing metrics) by more than ``rtol * max(|loss|, 1)``.
    grow_limit : float | None (``DSLIB_HEALTH_GROW_LIMIT``, default off)
        — trip when ``max|carry|`` exceeds this.
    enabled : bool (``DSLIB_HEALTH``, default on) — master switch; a
        disabled policy's guard admits everything and never trips.
    seed : int — base seed of the 'reseed' perturbation stream.
    elastic_attempts : int (``DSLIB_HEALTH_ELASTIC_ATTEMPTS``, default 0)
        — rollback attempts the fit-loop escalation ladder may spend at
        the elastic mesh-shrink tier (the LAST rungs of the shared
        ``max_restarts`` budget; see ``runtime.fitloop``).  Only fits
        whose estimator supports the on-device data rebind offer the
        tier.
    grow_attempts : int (``DSLIB_HEALTH_GROW_ATTEMPTS``, default 2) —
        mesh GROW-back resizes one fit may perform when the capacity
        watcher (``runtime.preemption.capacity_target``) reports
        returned devices.  Growing is free of rollback budget (the state
        re-pads from the last snapshot, no work is lost) but each resize
        retraces the fit kernels — the budget bounds thrash under a
        flapping capacity source.
    """

    def __init__(self, action=None, max_restarts=None, deadline_s=None,
                 monotone_rtol=None, grow_limit=None, enabled=None, seed=0,
                 first_deadline_s=None, elastic_attempts=None,
                 grow_attempts=None):
        env = os.environ
        if action is None:
            action = env.get("DSLIB_HEALTH_ACTION", "retry")
        if action not in ("retry", "halve", "reseed", "raise"):
            raise ValueError(f"unknown health action {action!r}")
        self.action = action
        self.max_restarts = int(env.get("DSLIB_HEALTH_MAX_RESTARTS", 2)) \
            if max_restarts is None else int(max_restarts)
        if deadline_s is None and env.get("DSLIB_CHUNK_DEADLINE_S"):
            deadline_s = float(env["DSLIB_CHUNK_DEADLINE_S"])
        self.deadline_s = deadline_s
        if first_deadline_s is None and env.get("DSLIB_CHUNK_FIRST_DEADLINE_S"):
            first_deadline_s = float(env["DSLIB_CHUNK_FIRST_DEADLINE_S"])
        if first_deadline_s is None and deadline_s is not None:
            first_deadline_s = 10.0 * deadline_s   # compile-time grace
        self.first_deadline_s = first_deadline_s
        if monotone_rtol is None and env.get("DSLIB_HEALTH_MONOTONE_RTOL"):
            monotone_rtol = float(env["DSLIB_HEALTH_MONOTONE_RTOL"])
        self.monotone_rtol = monotone_rtol
        if grow_limit is None and env.get("DSLIB_HEALTH_GROW_LIMIT"):
            grow_limit = float(env["DSLIB_HEALTH_GROW_LIMIT"])
        self.grow_limit = grow_limit
        self.enabled = (env.get("DSLIB_HEALTH", "1") != "0") \
            if enabled is None else bool(enabled)
        self.seed = int(seed)
        self.elastic_attempts = \
            int(env.get("DSLIB_HEALTH_ELASTIC_ATTEMPTS", 0)) \
            if elastic_attempts is None else int(elastic_attempts)
        self.grow_attempts = \
            int(env.get("DSLIB_HEALTH_GROW_ATTEMPTS", 2)) \
            if grow_attempts is None else int(grow_attempts)

    def make_guard(self, name, checkpoint=None):
        """Build the per-fit guard.  Fault-injection policies
        (``dislib_tpu.utils.faults``) override this to hand the fit a
        corrupting/hanging guard — the deterministic injection seam."""
        return ChunkGuard(name, self, checkpoint)


class Verdict:
    """Outcome of one chunk check: ``ok``, the tripped ``guard`` name
    (``None`` when ok), whether rollback can help (``recoverable``), and
    a ``detail`` dict naming the offending carries/coordinates."""

    __slots__ = ("ok", "guard", "recoverable", "detail")

    def __init__(self, ok, guard=None, recoverable=True, detail=None):
        self.ok = bool(ok)
        self.guard = guard
        self.recoverable = bool(recoverable)
        self.detail = detail or {}

    def __repr__(self):
        return (f"Verdict(ok={self.ok}, guard={self.guard!r}, "
                f"recoverable={self.recoverable}, detail={self.detail})")


class Remediation:
    """What the fit loop should do after rolling back to last-good:
    ``attempt`` (1-based restart count), ``damping`` (multiplier for the
    estimator's damping knob — 2**attempt under the 'halve' action, 1.0
    otherwise), and :meth:`perturb` (seeded jitter for 'reseed')."""

    __slots__ = ("attempt", "action", "damping", "seed")

    def __init__(self, attempt, action, seed):
        self.attempt = int(attempt)
        self.action = action
        self.damping = float(2 ** attempt) if action == "halve" else 1.0
        self.seed = int(seed)

    def perturb(self, arr, scale=1e-3):
        """Seeded relative jitter of a restored carry ('reseed' action;
        identity under every other action).  Deterministic in
        (policy.seed, attempt) so a remediated fit is reproducible."""
        arr = np.asarray(arr)
        if self.action != "reseed":
            return arr
        rng = np.random.RandomState((self.seed + 0x9E37) ^ self.attempt)
        span = np.maximum(np.abs(arr), 1.0)
        return (arr + scale * span * rng.standard_normal(arr.shape)) \
            .astype(arr.dtype, copy=False)


class _NoRemediation(Remediation):
    """The identity remediation: attempt 0, no damping, no perturbation —
    what a clean (non-rollback) state load applies."""

    def __init__(self):
        super().__init__(0, "none", 0)

    @staticmethod
    def perturb(arr, scale=1e-3):
        return arr


NO_REMEDIATION = _NoRemediation()


def guard(name, health=None, checkpoint=None):
    """Normalise a ``fit(..., health=...)`` argument into a per-fit
    :class:`ChunkGuard`: ``None`` builds the env-default policy, a
    :class:`HealthPolicy` (or fault-injection subclass) builds its own
    guard, and an existing guard passes through."""
    if isinstance(health, ChunkGuard):
        return health
    policy = health if isinstance(health, HealthPolicy) else HealthPolicy()
    return policy.make_guard(name, checkpoint)


class ChunkGuard:
    """Per-fit health guard: admits carries into each chunk (the fault
    injectors' corruption seam), checks the chunk's fused health vector
    under the watchdog, gates snapshot writes on the verdict, and runs
    the remediation bookkeeping."""

    def __init__(self, name, policy, checkpoint=None):
        self.name = name
        self.policy = policy
        self.checkpoint = checkpoint
        self.chunk_index = 0            # admits seen (0-based chunk counter)
        self.restarts = 0
        self.last_verdict = Verdict(True)
        self._prev_loss_last = None     # last HEALTHY chunk's final loss —
        #                                 the cross-chunk monotone reference
        self._checks_done = 0           # first check gets the compile grace

    # -- carry admission (fault-injection seam) -------------------------

    def admit(self, *carries):
        """Pass the chunk's input carries through the guard.  Production
        guards return them unchanged; fault-injection guards corrupt them
        at an exact chunk index.  Always call it once per chunk — it is
        also the chunk counter."""
        self.chunk_index += 1
        return carries

    # -- the watchdogged check ------------------------------------------

    def _resolve(self, handle):
        """Blocking resolution of one health read (the chunk's force
        point).  Fault injectors override this to simulate a hung
        collective."""
        return handle.result() if hasattr(handle, "result") \
            else np.asarray(handle)

    def _watched_resolve(self, handle):
        # the guard's first check usually blocks on XLA compilation, not
        # a hung collective — give it the compile-grace deadline
        deadline = self.policy.first_deadline_s if self._checks_done == 0 \
            else self.policy.deadline_s
        if deadline is None:
            return self._resolve(handle)
        box = {}

        def run():
            try:
                box["value"] = self._resolve(handle)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["exc"] = e

        t = threading.Thread(target=run, name="dslib-chunk-watchdog",
                             daemon=True)
        t.start()
        t.join(deadline)
        if t.is_alive():
            from dislib_tpu.utils.profiling import count_resilience
            count_resilience("watchdog_trips")
            raise WatchdogTimeout(
                f"{self.name}: chunk {self.chunk_index} force point "
                f"exceeded its {deadline}s deadline — hung collective or "
                "dispatch")
        if "exc" in box:
            raise box["exc"]
        return box["value"]

    def check(self, hvec, carry_names=(), carry_shapes=(), it=None,
              increasing=False):
        """Classify one chunk's health vector (device array, AsyncFetch
        handle, or host ndarray) into a :class:`Verdict`.

        The device→host copy is enqueued asynchronously first
        (``fetch(blocking=False)`` semantics) and resolved under the
        watchdog deadline; resolution failures escalate through the PR-1
        ``Retry`` policy (``WatchdogTimeout`` classifies transient) and
        re-raise typed once attempts are exhausted.  ``increasing``
        states the loss direction (must match the ``health_vec`` call)
        so the cross-chunk monotone comparison is signed correctly."""
        if not self.policy.enabled:
            self.last_verdict = Verdict(True)
            return self.last_verdict
        from dislib_tpu.runtime.elastic import AsyncFetch
        from dislib_tpu.runtime.retry import Retry
        if isinstance(hvec, np.ndarray):
            handle = hvec
        elif isinstance(hvec, AsyncFetch):
            handle = hvec
        else:
            handle = AsyncFetch(hvec)   # copy enqueued before resolution
        try:
            h = np.asarray(Retry.from_env().call(
                lambda: self._watched_resolve(handle)), np.float64).ravel()
        finally:
            self._checks_done += 1
        v = self._classify(h, carry_names, carry_shapes, it, increasing)
        self.last_verdict = v
        if v.ok and len(h) > _SLOT_LOSS_LAST and \
                h[_SLOT_LOSS_VALID] > 0:
            self._prev_loss_last = float(h[_SLOT_LOSS_LAST])
        return v

    def check_host(self, values, it=None):
        """Host-value variant for loops whose per-chunk state is already
        on host (the cascade SVM's level merges): ``values`` maps carry
        name → ndarray/scalar; trips the nonfinite guard only."""
        if not self.policy.enabled:
            self.last_verdict = Verdict(True)
            return self.last_verdict
        bad = {}
        for name, val in values.items():
            arr = np.asarray(val, np.float64)
            nf = ~np.isfinite(arr)
            if nf.any():
                bad[name] = {"count": int(nf.sum()),
                             "first_index": int(np.flatnonzero(nf.ravel())[0])}
        if bad:
            v = Verdict(False, guard="nonfinite", recoverable=True,
                        detail={"carries": bad, "iteration": it})
        else:
            v = Verdict(True)
        self.last_verdict = v
        return v

    def _classify(self, h, carry_names, carry_shapes, it,
                  increasing=False):
        pol = self.policy
        detail = {"hvec": h.tolist(), "iteration": it}
        if h[_SLOT_CARRY_NF] > 0 or h[_SLOT_INPUT_NF] > 0 \
                or h[_SLOT_LOSS_NF] > 0:
            carries = {}
            for i in range(max(0, (len(h) - HEALTH_BASE_LEN) // 2)):
                cnt = h[HEALTH_BASE_LEN + 2 * i]
                if cnt <= 0:
                    continue
                name = carry_names[i] if i < len(carry_names) else f"carry{i}"
                info = {"count": int(cnt),
                        "first_index": int(h[HEALTH_BASE_LEN + 2 * i + 1])}
                if i < len(carry_shapes) and carry_shapes[i]:
                    info["coords"] = tuple(
                        int(c) for c in np.unravel_index(
                            min(info["first_index"],
                                int(np.prod(carry_shapes[i])) - 1),
                            carry_shapes[i]))
                carries[name] = info
            detail["carries"] = carries
            if h[_SLOT_LOSS_NF] > 0:
                detail["loss_nonfinite"] = int(h[_SLOT_LOSS_NF])
            if h[_SLOT_INPUT_NF] > 0:
                detail["input_nonfinite"] = int(h[_SLOT_INPUT_NF])
                # bad *input* data: a rollback re-reads the same data, so
                # remediation cannot help — quarantine at ingest instead
                return Verdict(False, guard="input-nonfinite",
                               recoverable=False, detail=detail)
            return Verdict(False, guard="nonfinite", detail=detail)
        if pol.monotone_rtol is not None:
            rise = float(h[_SLOT_RISE])
            # cross-chunk jump: previous healthy chunk's last loss vs this
            # chunk's first — the boundary the in-chunk diffs cannot see
            # (and at every=1 the ONLY signal, each history being length 1)
            if self._prev_loss_last is not None \
                    and len(h) > _SLOT_LOSS_FIRST \
                    and h[_SLOT_LOSS_VALID] > 0:
                step = h[_SLOT_LOSS_FIRST] - self._prev_loss_last
                rise = max(rise, float(-step if increasing else step))
            if rise > pol.monotone_rtol * max(h[_SLOT_SCALE], 1.0):
                detail["rise"] = rise
                detail["scale"] = float(h[_SLOT_SCALE])
                return Verdict(False, guard="divergence", detail=detail)
        if pol.grow_limit is not None and h[_SLOT_MAX_ABS] > pol.grow_limit:
            detail["max_abs"] = float(h[_SLOT_MAX_ABS])
            return Verdict(False, guard="norm-growth", detail=detail)
        return Verdict(True)

    def on_escalation(self, escalation) -> None:
        """Notification hook the fit-loop driver fires after every
        ladder escalation (``runtime.fitloop.Escalation``).  Production
        guards ignore it; tier-targeted fault injectors
        (``utils.faults.FaultAtTier``) use it to stop firing once the
        right remediation tier is reached."""

    # -- gated snapshot writes ------------------------------------------

    def save_async(self, checkpoint, state):
        """Snapshot gate: forward to ``checkpoint.save_async`` ONLY when
        the last check was healthy — an unhealthy chunk's state must
        never rotate the last good generation away."""
        if not self.last_verdict.ok:
            return None
        return checkpoint.save_async(state)

    def save(self, checkpoint, state):
        """Blocking variant of the gated write."""
        if not self.last_verdict.ok:
            return None
        return checkpoint.save(state)

    # -- remediation ------------------------------------------------------

    def remediate(self, verdict=None, it=None):
        """Decide the response to a tripped guard: return a
        :class:`Remediation` (the caller rolls back to last-good and
        re-runs), or raise :class:`NumericalDivergence` when the policy
        says raise, the trip is not recoverable (bad input data), there
        is no checkpoint to roll back to, or ``max_restarts`` is spent."""
        v = verdict if verdict is not None else self.last_verdict
        it = v.detail.get("iteration") if it is None else it
        reasons = []
        if self.policy.action == "raise":
            reasons.append("policy action is 'raise'")
        if not v.recoverable:
            reasons.append("non-finite input data cannot be healed by "
                           "rollback (quarantine it at ingest)")
        if self.checkpoint is None:
            reasons.append("no checkpoint to roll back to (pass "
                           "checkpoint= to enable self-healing)")
        if self.restarts >= self.policy.max_restarts:
            reasons.append(f"max_restarts={self.policy.max_restarts} "
                           "exhausted")
        if reasons:
            raise NumericalDivergence(
                f"{self.name}: health guard {v.guard!r} tripped at "
                f"iteration {it} — {'; '.join(reasons)} "
                f"(detail: {v.detail})",
                estimator=self.name, iteration=it, guard=v.guard,
                detail=v.detail)
        self.restarts += 1
        # the rollback (and any halve/reseed perturbation) breaks loss
        # continuity — drop the cross-chunk monotone reference so the
        # re-run chunk is not judged against the pre-rollback trajectory
        self._prev_loss_last = None
        return Remediation(self.restarts, self.policy.action,
                           self.policy.seed + self.restarts)

    def rollback(self, restore, scratch, remediation=None, checkpoint=None,
                 expect=None):
        """Load the newest good snapshot and hand it to
        ``restore(snap, remediation)``; fall back to
        ``scratch(remediation)`` when no snapshot exists (or there is no
        checkpoint at all).  The ONE state-(re)load path every rollback,
        elastic resize, and initial warm start of the fit loop funnels
        through — so the snapshot-vs-scratch dispatch and the remediation
        threading cannot drift between call sites.  ``checkpoint``
        overrides the guard's own (the fit-loop driver passes its sink:
        an injected guard may carry none).

        ``expect`` declares what a compatible snapshot must contain (see
        :func:`check_snapshot`); a mismatch raises the shared
        "stale or foreign snapshot" ``ValueError`` BEFORE ``restore``
        runs — the estimators' five copy-pasted validation blocks
        collapsed here (round 19), and the health-guard lint keeps them
        from growing back."""
        rem = NO_REMEDIATION if remediation is None else remediation
        ck = self.checkpoint if checkpoint is None else checkpoint
        snap = ck.load() if ck is not None else None
        if snap is not None and expect:
            check_snapshot(self.name, snap, expect)
        return restore(snap, rem) if snap is not None else scratch(rem)


def check_snapshot(name, snap, expect):
    """Validate a loaded snapshot against the estimator's declared
    expectations — the one place the "stale or foreign snapshot" raise
    lives.  ``expect`` maps snapshot key -> spec:

    - a tuple is a required shape; ``None`` dims are wildcards (elastic
      factor rows repadded per mesh, e.g. ALS's ``(None, n_f)``);
    - an int is a required scalar value (logical dims like ALS's
      ``m``/``n``, which outlive any padding).

    A missing key or a mismatch raises ``ValueError`` mentioning
    "stale or foreign snapshot" (tests and callers match on the phrase).
    Estimators declare this via ``ChunkedFitLoop(snapshot_expect=...)``
    rather than hand-checking in their ``restore`` callbacks.
    """
    for key, spec in expect.items():
        if key not in snap:
            raise ValueError(
                f"{name}: checkpoint is missing {key!r} — stale or "
                "foreign snapshot")
        if isinstance(spec, tuple):
            got = tuple(np.asarray(snap[key]).shape)
            want = tuple(spec)
            if len(got) != len(want) or any(
                    w is not None and g != w for g, w in zip(got, want)):
                shown = tuple("*" if w is None else w for w in want)
                raise ValueError(
                    f"{name}: checkpoint {key!r} shape {got} does not "
                    f"match this estimator/data {shown} — stale or "
                    "foreign snapshot")
        else:
            got = int(np.asarray(snap[key]))
            if got != int(spec):
                raise ValueError(
                    f"{name}: checkpoint {key!r} = {got} does not match "
                    f"this estimator/data ({int(spec)}) — stale or "
                    "foreign snapshot")


def health_vec(carries=(), inputs=(), hist=None, n_done=None,
               increasing=False):
    """Build the (HEALTH_BASE_LEN + 2·len(carries),) float32 health vector
    INSIDE a fit kernel — call it from traced code only, on the chunk's
    final carries, so the guard rides the existing fused dispatch.

    Layout (``HEALTH_BASE_LEN`` = 9 base slots, then one pair per carry):
    ``[carry_nonfinite_total, input_nonfinite_total, rise, scale,
    max_abs_carry, loss_nonfinite, loss_valid, loss_first, loss_last,
    (count, first_flat_index) per carry]``.  ``loss_valid`` flags whether
    the chunk produced a (finite) loss history — an explicit flag rather
    than a NaN sentinel, because sanitizer-tier fits run under
    ``jax.debug_nans``; the guard carries ``loss_last`` across chunks
    host-side so the monotone guard also sees a jump that lands exactly
    on a chunk boundary (including the ``every=1`` cadence, where every
    in-chunk history has length 1).

    ``hist``/``n_done``: the chunk's per-iteration loss history (slots
    beyond ``n_done`` ignored); ``rise`` is the worst consecutive
    violation of monotonicity (losses must fall, or rise when
    ``increasing=True``) and ``scale`` its reference magnitude.  Integer
    and boolean carries contribute nothing (they can hold neither a
    non-finite value nor a meaningful norm blow-up) — pass them for the
    chunk-counting seam only.
    """
    import jax.numpy as jnp

    def _nf_pair(c):
        c = jnp.asarray(c)
        if not jnp.issubdtype(c.dtype, jnp.floating):
            z = jnp.float32(0)
            return z, z
        bad = ~jnp.isfinite(c.ravel())
        count = jnp.sum(bad).astype(jnp.float32)
        first = jnp.argmax(bad).astype(jnp.float32)  # 0 when count == 0
        return count, first

    pairs = [_nf_pair(c) for c in carries]
    carry_nf = sum((p[0] for p in pairs), jnp.float32(0))
    input_nf = sum((_nf_pair(x)[0] for x in inputs), jnp.float32(0))
    max_abs = jnp.float32(0)
    for c in carries:
        c = jnp.asarray(c)
        if jnp.issubdtype(c.dtype, jnp.floating):
            # NaNs must not mask a finite blow-up elsewhere; they already
            # trip the nonfinite guard themselves
            a = jnp.abs(c.ravel())
            max_abs = jnp.maximum(
                max_abs,
                jnp.max(jnp.where(jnp.isfinite(a), a, 0.0),
                        initial=0.0).astype(jnp.float32))
    rise = jnp.float32(0)
    scale = jnp.float32(0)
    loss_nf = jnp.float32(0)
    loss_valid = jnp.float32(0)         # 0 = "no loss this chunk": the
    loss_first = jnp.float32(0)         # guard skips the comparison (an
    loss_last = jnp.float32(0)          # explicit flag — a NaN sentinel
    #                                     would trip jax.debug_nans)
    if hist is not None:
        hist = jnp.asarray(hist, jnp.float32).ravel()
        n = hist.shape[0]
        if n >= 1:
            idx = jnp.arange(n)
            done = hist.shape[0] if n_done is None else n_done
            valid = idx < done
            loss_nf = jnp.sum(valid & ~jnp.isfinite(hist)) \
                .astype(jnp.float32)
            scale = jnp.max(jnp.where(valid & jnp.isfinite(hist),
                                      jnp.abs(hist), 0.0), initial=0.0)
            ran = jnp.asarray(done, jnp.int32) >= 1
            loss_valid = ran.astype(jnp.float32)
            # NaNs in hist itself already trip the loss_nf guard before
            # any monotone comparison, but keep the carried values clean
            # of them so debug_nans-audited paths stay silent
            h0 = hist[0]
            hl = hist[jnp.maximum(jnp.asarray(done, jnp.int32) - 1, 0)]
            loss_first = jnp.where(ran & jnp.isfinite(h0), h0, 0.0)
            loss_last = jnp.where(ran & jnp.isfinite(hl), hl, 0.0)
            loss_valid = jnp.where(
                jnp.isfinite(h0) & jnp.isfinite(hl), loss_valid, 0.0)
            if n >= 2:
                diffs = hist[1:] - hist[:-1]
                dvalid = (idx[1:] < done) & jnp.isfinite(diffs)
                viol = -diffs if increasing else diffs
                rise = jnp.max(jnp.where(dvalid, viol, 0.0), initial=0.0)
    out = [carry_nf, input_nf, rise.astype(jnp.float32),
           scale.astype(jnp.float32), max_abs, loss_nf,
           jnp.asarray(loss_valid, jnp.float32),
           jnp.asarray(loss_first, jnp.float32),
           jnp.asarray(loss_last, jnp.float32)]
    for count, first in pairs:
        out.extend([count, first])
    return jnp.stack(out)
