"""Unified resilient chunk-fit runtime (round-12 robustness PR; ROADMAP
item 2 — the extraction PR 3's review flagged twice).

Before this module, every chunked estimator hand-wired the same per-chunk
protocol: register a guard, admit carries (the fault-injection seam), run
the fused chunk kernel, judge its health vector under the watchdog, gate
the snapshot write on the verdict, roll back to the last-good generation
on a trip, and poll the preemption flag at the boundary — five
near-identical rollback blocks across seven estimators.
:class:`ChunkedFitLoop` owns the whole protocol; an estimator supplies
only the three things the runtime cannot know (DrJAX's per-shard-update +
cross-shard-reduce decomposition is the chunk-step shape, PAPERS.md
arXiv:2403.07128):

- ``init(rem)``    — build a fresh :class:`LoopState` (``rem`` perturbs /
  damps after a rollback with no snapshot; the initial call passes a
  neutral remediation, so closures never branch on None);
- ``restore(snap, rem)`` — rebuild state from a snapshot dict (validate,
  re-pad for the CURRENT mesh, apply ``rem``); raise ``ValueError`` on a
  stale/foreign snapshot;
- ``step(state, chunk)`` — run ONE chunk kernel on ``state.carries``
  (already passed through the guard's admit seam) and return a
  :class:`ChunkOutcome` whose ``hvec`` (fused health vector) or
  ``host_values`` the driver judges;

plus a ``snapshot(state) -> dict`` builder, called only at save
boundaries (build it lazily — it is where the device→host fetches live).

On top of the extraction, the driver adds what copy-pasted blocks could
never coordinate: a cross-attempt **escalation ladder**
(:class:`EscalationLadder`) with a shared fault budget
(``HealthPolicy.max_restarts``).  Successive trips of one fit escalate
deterministically through tiers instead of burning the whole budget at
one level:

1. **retry** — plain rollback-to-last-good and re-run (transient bit
   flips, one-off collective glitches);
2. **remediate** — rollback plus the policy action: ``halve`` doubles the
   estimator's damping knob per tier attempt, ``reseed`` perturbs the
   restored carries (systematic numerical trouble);
3. **elastic** — shrink the mesh to half its row axis (the
   PR-1/PR-6 elastic machinery: state re-pads via ``repad_rows``, data
   re-lays out on device via the estimator's ``elastic`` rebind hook) and
   resume from last-good — the "a device is bad" tier.  Opt-in:
   ``HealthPolicy(elastic_attempts=1)`` / ``DSLIB_HEALTH_ELASTIC_ATTEMPTS``,
   and only offered when the estimator passes an ``elastic`` hook;
4. **raise** — the typed ``NumericalDivergence`` / ``WatchdogTimeout``
   diagnostics, exactly as before, once the budget is spent (or
   immediately for non-recoverable trips / the 'raise' action / no
   checkpoint).

The ladder preserves the pre-extraction budget semantics exactly:
``max_restarts`` rollbacks total, then the typed raise — the tiers only
decide WHAT each rollback does.  Streaming estimators call
:meth:`ChunkedFitLoop.run_one` (one committed chunk per ``partial_fit``
call, protocol identical, budget and cadence stream-wide) — the recipe
that makes a new estimator resilient by construction
(``cluster.kmeans.MiniBatchKMeans`` is the acceptance test).

Elasticity is BIDIRECTIONAL (round-16): alongside the fault-driven
shrink tier, the driver polls the **capacity watcher**
(``runtime.preemption.capacity_target`` — the ``DSLIB_CAPACITY_FILE`` /
``request_capacity`` level) at the same chunk boundaries as the
preemption flag.  When the published device target drops, the fit
snapshots and shrinks to the largest halving-reachable mesh that fits;
when capacity RETURNS, it grows back toward the mesh it started on —
state re-pads from the snapshot via ``repad_rows``, data re-lays out on
device through the estimator's ``elastic`` hook (the ``ds.rechunk``
deviceput/panels router — never the host).  Capacity resizes spend no
rollback budget (nothing failed; the chunk just committed), but grows
are bounded by ``HealthPolicy.grow_attempts`` against a flapping
source.  Both directions report in ``info`` (``mesh_shrinks`` /
``mesh_grows``) and the process-wide resilience counters.
"""

from __future__ import annotations

from dislib_tpu.runtime import health as _health
from dislib_tpu.runtime.health import NO_REMEDIATION
from dislib_tpu.runtime.preemption import (capacity_target,
                                           preemption_requested,
                                           raise_if_preempted)
from dislib_tpu.utils.profiling import count_resilience

__all__ = ["ChunkedFitLoop", "LoopState", "ChunkOutcome", "Escalation",
           "EscalationLadder", "NO_REMEDIATION", "TIERS", "data_rebind",
           "stream_state"]

TIERS = ("retry", "remediate", "elastic")


def stream_state(checkpoint, key="n_batches"):
    """``(consumed, snapshot_dict)`` of a STREAMING fit's checkpoint —
    ``(0, None)`` when there is no usable snapshot.  The producer-side
    resume point: the driver restores the MODEL state, but only the
    producer knows the batch order, so it must feed ``run_one`` batches
    from this position on (re-feeding consumed batches would apply them
    twice); a fully consumed stream adopts the snapshot as the fitted
    state.  Lives here so estimator code never reads checkpoints
    directly (the driver lint forbids it)."""
    snap = checkpoint.load() if checkpoint is not None else None
    if snap is None or key not in snap:
        return 0, None
    return int(snap[key]), snap


def data_rebind(holder, key="x"):
    """The standard elastic-tier rebind hook over a mutable data holder
    (``{key: ds_array}``): force the pending op chain BEFORE the mesh
    switch (the fusion layer's device-set contract — the driver calls the
    hook with ``mesh=None`` for this phase), re-canonicalize onto the new
    mesh after.  SPARSE holders (``SparseArray``) re-land their sharded
    buffers through the sparse rechunk schedules instead (no op chains
    to force, still never the host) — the round-14 sparse elastic rung.
    Objects exposing ``rebind_mesh(mesh)`` (round 20: an ``IVFIndex``'s
    mesh-pinned inverted-list layout) own their re-layout and are
    delegated to.  Estimators with extra rebinding (ALS's padded test
    matrix) wrap or replace it."""
    def hook(mesh):
        from dislib_tpu.data.array import ensure_canonical
        from dislib_tpu.data.sparse import SparseArray
        x = holder[key]
        if hasattr(x, "rebind_mesh"):
            x.rebind_mesh(mesh)         # the object owns its re-layout
            return
        if isinstance(x, SparseArray):
            if mesh is not None:
                x.sharded(mesh)         # on-device reshard of the backing
            return
        holder[key] = x.force() if mesh is None else ensure_canonical(x)
    return hook


class LoopState:
    """One point of a chunked fit: ``carries`` (the device arrays that
    flow chunk-to-chunk — the guard's admit/poison seam), ``it``
    (completed iterations/levels/rounds), ``done`` (converged), and
    ``extra`` (estimator-owned scalars riding along, e.g. the current
    loss)."""

    __slots__ = ("carries", "it", "done", "extra")

    def __init__(self, carries=(), it=0, done=False, extra=None):
        self.carries = tuple(carries)
        self.it = int(it)
        self.done = bool(done)
        self.extra = extra


class ChunkOutcome:
    """What one chunk produced: the successor ``state``, the fused
    health ``hvec`` (device array — judged under the watchdog) or
    ``host_values`` (name → ndarray, for loops whose state is host-side),
    and ``history`` (this chunk's per-iteration loss values; the driver
    owns the cross-rollback trimming).

    ``state`` and ``history`` may each be a CALLABLE (deferred commit):
    the driver invokes them only AFTER the chunk's verdict passed.  Step
    closures whose successor state needs device scalars (``int(n_done)``,
    ``float(shift)``, a fetched ``changed`` flag) MUST defer them this
    way: the hvec is an output of the same fused program, so resolving it
    first — under the watchdog deadline — forces the whole chunk, and a
    hung collective trips a typed ``WatchdogTimeout`` instead of blocking
    forever in an estimator-side sync (review-found: the eager ports left
    real kernel hangs outside the watchdog).  A deferred state also
    cannot leak a faulted chunk's side effects — its closure never runs
    on the rollback path.  ``check_on='save'`` loops (the forest) must
    keep ``state`` eager: the save-boundary decision reads ``state.done``
    before any check."""

    __slots__ = ("state", "hvec", "host_values", "history")

    def __init__(self, state, hvec=None, host_values=None, history=()):
        self.state = state
        self.hvec = hvec
        self.host_values = host_values
        self.history = history


class Escalation:
    """One rung of the ladder: the ``tier`` this attempt runs at, the
    global ``attempt`` number (1-based, = the guard's restart count), the
    1-based ``tier_attempt`` within the tier, and the tier-adjusted
    ``remediation`` the estimator's restore/init closures apply."""

    __slots__ = ("tier", "tier_index", "attempt", "tier_attempt",
                 "remediation")

    def __init__(self, tier, attempt, tier_attempt, remediation):
        self.tier = tier
        self.tier_index = TIERS.index(tier)
        self.attempt = attempt
        self.tier_attempt = tier_attempt
        self.remediation = remediation


class EscalationLadder:
    """Maps the guard's restart counter onto tiers.  The schedule spends
    the shared budget (``max_restarts``) as: 1 plain retry, then policy
    remediation, then ``elastic_attempts`` mesh-shrink attempts (last —
    most disruptive), then the typed raise.  The raise conditions
    (non-recoverable trip, 'raise' action, no checkpoint, spent budget)
    stay with :meth:`ChunkGuard.remediate` so diagnostics cannot drift."""

    def __init__(self, guard, elastic_ok=False):
        self.guard = guard
        pol = guard.policy
        budget = max(0, int(pol.max_restarts))
        retry_n = min(1, budget)
        elastic_n = min(max(0, int(getattr(pol, "elastic_attempts", 0))),
                        budget - retry_n) if elastic_ok else 0
        self.schedule = (["retry"] * retry_n
                         + ["remediate"] * (budget - retry_n - elastic_n)
                         + ["elastic"] * elastic_n)

    def escalate(self, verdict, it=None) -> Escalation:
        rem = self.guard.remediate(verdict, it=it)   # typed-raise gate
        a = rem.attempt
        tier = self.schedule[a - 1] if 0 < a <= len(self.schedule) \
            else "remediate"
        tier_attempt = self.schedule[: a].count(tier) or 1
        action = self.guard.policy.action if tier == "remediate" else "retry"
        esc = Escalation(tier, a, tier_attempt,
                         _health.Remediation(tier_attempt, action, rem.seed))
        count_resilience("rollbacks")
        count_resilience("escalations_" + tier)
        if tier == "retry":
            count_resilience("chunk_retries")
        self.guard.on_escalation(esc)
        return esc


class ChunkedFitLoop:
    """The one driver every chunked fit runs on.

    Parameters
    ----------
    name : str — estimator name for guards/diagnostics.
    checkpoint : FitCheckpoint | None — rollback target + save sink; None
        runs the protocol without snapshots (a recoverable trip then
        raises typed, as before).
    health : HealthPolicy | ChunkGuard | None — the fit's policy (fault
        injectors are policy subclasses; see ``utils.faults``).
    max_iter : int | None — iteration budget; None = run until a chunk
        reports ``done`` (propagation/extraction loops).
    chunk_iters : int | None — iterations per chunk; None = the
        checkpoint's ``every`` (whole budget when no checkpoint).  Loops
        whose natural chunk is one host iteration/level (cascade SVM,
        forest) pass 1 and move the cadence to ``save_every``.
    save_every : int — snapshot every N committed chunks (1 = each).
    check_on : 'chunk' | 'save' — judge every chunk, or only at save
        boundaries (the forest's cadence: its per-level health vector is
        read once per snapshot chunk, one sync per chunk either way).
        With ``check_on='save'`` and no checkpoint the loop never judges
        (the forest defers to its adoption-time check).
    save_final : bool — whether the converged/final state snapshots
        (the forest's growth loop snapshots only resumable mid-points).
    carry_names / carry_shapes / increasing — forwarded to
        ``guard.check`` for diagnostics and the monotone direction.
    snapshot_expect : dict | None — the snapshot compatibility contract
        (key -> required shape tuple with ``None`` wildcards, or required
        scalar); validated by ``health.check_snapshot`` inside the one
        rollback funnel before any ``restore`` callback runs, raising the
        shared "stale or foreign snapshot" ``ValueError`` on mismatch.
    elastic : callable(mesh) | None — rebind hook for the elastic tier
        AND the capacity-driven resizes: called after the driver changes
        the mesh; re-lay out the fit's data for the new topology
        (``ds.ensure_canonical`` / the sparse and estimator-specific
        re-staging).  None disables both for this fit.

    ``info`` carries the fit's resilience summary (chunks, rollbacks,
    escalations per tier, mesh shrinks/grows) — estimators expose it as
    ``fit_info_``; the same events also feed the process-wide
    ``utils.profiling`` resilience counters at zero extra dispatches.
    """

    def __init__(self, name, *, checkpoint=None, health=None, max_iter=None,
                 chunk_iters=None, save_every=1, check_on="chunk",
                 save_final=True, carry_names=(), carry_shapes=(),
                 increasing=False, elastic=None, snapshot_expect=None):
        self.name = name
        self.checkpoint = checkpoint
        self.guard = _health.guard(name, health, checkpoint)
        self.max_iter = max_iter
        self.chunk_iters = chunk_iters
        self.save_every = max(1, int(save_every))
        self.check_on = check_on
        self.save_final = bool(save_final)
        self.carry_names = tuple(carry_names)
        self.carry_shapes = tuple(carry_shapes)
        self.increasing = bool(increasing)
        self.elastic = elastic
        # snapshot compatibility contract, validated by the ONE rollback
        # funnel (guard.rollback -> health.check_snapshot) before any
        # restore callback sees the snapshot; streaming estimators may
        # reassign it per call (the stream's width can change the want)
        self.snapshot_expect = dict(snapshot_expect) if snapshot_expect \
            else None
        self.ladder = EscalationLadder(self.guard,
                                       elastic_ok=elastic is not None)
        self.history: list = []
        self.info = {"chunks": 0, "rollbacks": 0, "mesh_shrinks": 0,
                     "mesh_grows": 0,
                     "escalations": dict.fromkeys(TIERS, 0)}
        self._state = None
        self._esc = None
        self._it0 = None
        self._cadence = 0
        self._preempt = False
        self._cap_plan = None
        self._cap_shrunk = False
        self._grows_left = max(0, int(getattr(self.guard.policy,
                                              "grow_attempts", 0)))
        # the mesh this fit STARTED on is "home": capacity shrinks keep a
        # device prefix of it, and grow-back re-forms prefixes of it (a
        # fit never grows past its entry mesh — returned devices beyond
        # that belong to the next fit / a fresh process)
        from dislib_tpu.parallel import mesh as _mesh
        m = _mesh.get_mesh()
        self._home_shape = _mesh.mesh_shape(m)
        self._home_devices = list(m.devices.reshape(-1))

    # -- protocol pieces -------------------------------------------------

    def _load_state(self, init, restore, rem=NO_REMEDIATION) -> LoopState:
        st = self.guard.rollback(restore, init, rem,
                                 checkpoint=self.checkpoint,
                                 expect=self.snapshot_expect)
        if self._it0 is None:
            self._it0 = st.it           # this-run history starts here
        del self.history[max(0, st.it - self._it0):]
        self._cadence = 0               # snapshot cadence re-anchors
        return st

    def _plan(self, state):
        if self.max_iter is None:
            return None
        left = self.max_iter - state.it
        if self.chunk_iters is not None:
            return min(self.chunk_iters, left)
        return left if self.checkpoint is None \
            else min(self.checkpoint.every, left)

    def _one_chunk(self, st, step, chunk):
        """admit → step → judge (watchdogged) → materialize the deferred
        commit.  Returns ``(state, history)``, or None after a rollback
        was decided (``self._esc`` holds the escalation).  The preemption
        flag is polled ONCE here and reused by ``_commit`` — two
        independent polls could let a flag arriving between them snapshot
        a chunk whose health vector was never judged (check_on='save')."""
        carries = self.guard.admit(*st.carries)
        out = step(LoopState(carries, st.it, st.done, st.extra), chunk)
        self._preempt = preemption_requested()
        self._cap_plan = self._capacity_plan()
        if self.check_on == "chunk":
            do_check = True
        else:                           # 'save': judge at save boundaries
            # a pending capacity resize forces the boundary: the resize
            # snapshots this chunk's state, so it must be judged first
            boundary = out.state.done \
                or (self._cadence + 1) % self.save_every == 0 \
                or self._preempt or self._cap_plan is not None
            do_check = self.checkpoint is not None and boundary
        if do_check:
            if out.host_values is not None:
                verdict = self.guard.check_host(out.host_values, it=st.it)
            elif out.hvec is not None:
                verdict = self.guard.check(
                    out.hvec, carry_names=self.carry_names,
                    carry_shapes=self.carry_shapes, it=st.it,
                    increasing=self.increasing)
            else:
                verdict = None
            if verdict is not None and not verdict.ok:
                esc = self.ladder.escalate(verdict, it=st.it)  # may raise
                self.info["rollbacks"] += 1
                self.info["escalations"][esc.tier] += 1
                if esc.tier == "elastic":
                    self._shrink_mesh()
                self._esc = esc
                return None
        state = out.state() if callable(out.state) else out.state
        hist = out.history() if callable(out.history) else out.history
        return state, hist

    def _commit(self, st, hist, snapshot):
        self.info["chunks"] += 1
        self._cadence += 1
        if hist is not None and len(hist):
            self.history.extend(hist)
        if self.checkpoint is None:
            return
        boundary = st.done or self._cadence % self.save_every == 0
        if (boundary or self._preempt or self._cap_plan is not None) \
                and (not st.done or self.save_final):
            self.guard.save_async(self.checkpoint, snapshot(st))
        if self._preempt and not st.done \
                and (self.max_iter is None or st.it < self.max_iter):
            raise_if_preempted(self.checkpoint)

    def _capacity_plan(self):
        """Compare the published capacity level against the current mesh
        and return ``("shrink"|"grow", new_rows)`` — or None when nothing
        to do.  The plan keeps the mesh a halving-reachable prefix of the
        HOME mesh (column count fixed; rows move by powers of two), so a
        shrink-then-grow sequence walks back through the exact shapes it
        came down by.  Grows additionally need budget (``grow_attempts``)
        so a flapping capacity source cannot thrash resizes forever;
        shrinks always honour the target (running over capacity risks
        eviction).  Stable at the fixpoint: once rows match the target,
        every poll returns None."""
        if self.elastic is None or self.checkpoint is None:
            return None
        cap = capacity_target()
        if cap is None:
            # No target published.  If a CAPACITY shrink brought us below
            # home, a cleared target means the pressure LIFTED (round-20
            # rejoin heal clears rather than publishing a bigger level) —
            # head home through the same grow rungs, same budget.  An
            # elastic-tier remediation shrink never sets the flag: nothing
            # says the bad device came back, so it stays sticky.
            if not self._cap_shrunk:
                return None
            cap = self._home_shape[0] * self._home_shape[1]
        from dislib_tpu.parallel import mesh as _mesh
        r, c = _mesh.mesh_shape(_mesh.get_mesh())
        home_r, home_c = self._home_shape
        cap = max(c, min(int(cap), home_r * home_c))
        want = cap // c                 # usable full rows at this level
        if want < r:
            new_r = r
            while new_r > 1 and new_r > want:
                new_r //= 2
            return ("shrink", new_r) if new_r < r else None
        if want > r and r < home_r and self._grows_left > 0:
            new_r = r
            while new_r * 2 <= min(want, home_r):
                new_r *= 2
            if new_r > r:
                return ("grow", new_r)
        return None

    def _resize_mesh(self, new_r, kind):
        """Re-form the mesh at ``new_r`` rows over the home-device prefix
        and rebind the fit's data.  The hook is called TWICE: once with
        ``None`` BEFORE the switch — force any pending op chains under
        the mesh they were built for (the fusion layer's force-first
        contract for device-set changes) — and once with the new mesh to
        re-lay the data out (``ds.ensure_canonical`` / the rechunk
        schedules)."""
        from dislib_tpu.parallel import mesh as _mesh
        r, c = _mesh.mesh_shape(_mesh.get_mesh())
        if new_r == r:
            return
        if self.elastic is not None:
            self.elastic(None)          # pre-switch: force pending chains
        _mesh.init((new_r, c), devices=self._home_devices[: new_r * c])
        # drop the jit caches: a kernel whose PADDED shape is unchanged
        # across the switch would otherwise hit the trace cache and
        # replay a sharding constraint baked for the dead mesh (the PR-6
        # stale-constraint failure mode; a real elastic resume is a
        # fresh process with cold caches, so the recompile is the honest
        # cost of a resize)
        import jax
        jax.clear_caches()
        key = "mesh_shrinks" if kind == "shrink" else "mesh_grows"
        self.info[key] += 1
        count_resilience(key)
        if self.elastic is not None:
            self.elastic(_mesh.get_mesh())

    def _shrink_mesh(self):
        """Elastic tier: halve the mesh's row axis (first half of the
        device grid survives — the 'a device went bad' drill).  An
        unshrinkable mesh (single row) keeps the current one: the
        attempt degrades to a plain retry, deterministically — the hook
        still runs both phases so pending chains are forced."""
        from dislib_tpu.parallel import mesh as _mesh
        r, c = _mesh.mesh_shape(_mesh.get_mesh())
        if r >= 2:
            self._resize_mesh(r // 2, "shrink")
        elif self.elastic is not None:
            self.elastic(None)
            self.elastic(_mesh.get_mesh())

    def _apply_capacity(self, st, init, restore) -> LoopState:
        """Execute the pending capacity plan AFTER the chunk committed:
        flush the just-written snapshot (the resize's resume point),
        re-form the mesh, and reload state through the one rollback
        funnel — ``restore`` re-pads for the new mesh exactly as an
        elastic-tier resume would, but with the neutral remediation
        (nothing failed) and no budget spent."""
        kind, new_r = self._cap_plan
        self._cap_plan = None
        if self.checkpoint is not None:
            self.checkpoint.flush()     # resume point must be on disk
        if kind == "grow":
            self._grows_left -= 1
        self._resize_mesh(new_r, kind)
        self._cap_shrunk = new_r < self._home_shape[0]
        return self._load_state(init, restore)

    # -- entry points ----------------------------------------------------

    def run(self, *, init, step, restore=None, snapshot=None) -> LoopState:
        """Drive a whole fit: chunks until converged/budget-spent, the
        full protocol per chunk.  Returns the final state (also kept as
        ``self.state``); flushes the checkpoint before returning."""
        st = self._load_state(init, restore)
        while not st.done:
            chunk = self._plan(st)
            if chunk is not None and chunk <= 0:
                break
            got = self._one_chunk(st, step, chunk)
            if got is None:             # rolled back: reload last-good
                st = self._load_state(init, restore, self._esc.remediation)
                continue
            st, hist = got
            self._commit(st, hist, snapshot)
            if self._cap_plan is not None and not st.done:
                st = self._apply_capacity(st, init, restore)
        if self.checkpoint is not None:
            self.checkpoint.flush()     # last snapshot lands before return
        self._state = st
        return st

    def run_one(self, *, init, step, restore=None, snapshot=None) -> LoopState:
        """Streaming entry (``partial_fit``): ONE committed chunk per
        call, protocol identical — admit, judge, rollback/escalate until
        the chunk commits (or the typed raise), gated save at the
        cadence, preemption poll.  The loop object persists across calls,
        so the fault budget, save cadence, and escalation state are
        stream-wide; the first call restores from the checkpoint (a
        preempted stream resumes where it snapshot)."""
        st = self._state if self._state is not None \
            else self._load_state(init, restore)
        while True:
            got = self._one_chunk(st, step, None)
            if got is None:
                st = self._load_state(init, restore, self._esc.remediation)
                continue
            st, hist = got
            self._commit(st, hist, snapshot)
            if self._cap_plan is not None and not st.done:
                st = self._apply_capacity(st, init, restore)
            self._state = st
            return st

    @property
    def state(self):
        return self._state
