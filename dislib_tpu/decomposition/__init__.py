from dislib_tpu.decomposition.tsqr import tsqr
from dislib_tpu.decomposition.randomsvd import random_svd
from dislib_tpu.decomposition.lanczos import lanczos_svd
from dislib_tpu.decomposition.pca import PCA

__all__ = ["tsqr", "random_svd", "lanczos_svd", "PCA"]
