"""Tall-skinny QR (reference: `dislib/decomposition/tsqr` — per-block local QR
plus a pairwise tree reduction of R factors; SURVEY.md §3.2).

TPU-native design (BASELINE config 3: "tsQR on 65536x256 — _little_qr +
all_gather(R) over ICI"): one `shard_map` over the mesh 'rows' axis.

    per shard:  A_i = Q1_i R_i           (local Householder QR, MXU)
    collective: R_stack = all_gather(R_i)  — ONE all_gather over ICI; with
                n cols small this is the whole communication volume
    per shard:  R_stack = Q2 R ;  Q_i = Q1_i @ Q2[i]   (local GEMM)

The reference's arity-2 reduction tree is log2(p) rounds of pairwise R
merges shipped between workers; the all_gather collapses that tree into a
single ICI collective, after which every shard redundantly factors the tiny
(p·n, n) stack — redundant FLOPs are free next to saved latency hops.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from dislib_tpu.data.array import Array
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.ops import precision as px
from dislib_tpu.ops.base import precise


def tsqr(a: Array, mode: str = "reduced", indexes=None, precision=None):
    """Tall-skinny QR.

    mode='reduced' → (Q (m,n), R (n,n));  mode='r' → R only.
    ``indexes`` (reference parity): restrict the returned Q to these column
    indices after factorisation.

    ``precision``: mixed-precision policy (None → the
    ``DSLIB_MATMUL_PRECISION`` default).  The policy governs the Q
    assembly/application GEMMs (the FLOP-dominant tall products); the
    local panel factorisations and the R-stack merge stay float32 —
    bounds in ``ops/precision.ERROR_BOUNDS``.
    """
    if mode not in ("reduced", "r"):
        raise ValueError(f"unsupported mode {mode!r}")
    policy = px.resolve(precision)
    m, n = a.shape
    if m < n:
        raise ValueError("tsqr requires a tall-skinny array (m >= n)")
    mesh = _mesh.get_mesh()
    p = mesh.shape[_mesh.ROWS]
    av = px.f32(a._data[:, :n])  # keep padded rows (zeros), crop cols
    # each shard must be at least n tall for its local R to be (n, n);
    # grow with zero rows if not (zero rows leave Q's logical rows and R exact)
    if av.shape[0] // p < n:
        extra = p * n - av.shape[0]
        av = jnp.pad(av, ((0, extra), (0, 0)))
        av = jax.device_put(av, _mesh.row_sharding())
    q_pad, r = _tsqr_shardmap(av, mesh, p, cholqr=_use_cholqr(),
                              policy=policy)
    if mode == "r":
        return Array._from_logical(r)
    q = Array._from_logical_padded(_col_repad(q_pad), (m, n), a._reg_shape)
    if indexes is not None:
        q = q[:, list(indexes)]
    return q, Array._from_logical(r)


def _use_cholqr() -> bool:
    """Policy for the CholeskyQR2 local factorisation: DSLIB_TSQR_CHOLQR
    in {auto (default), 1, 0}.  'auto' enables it on TPU only — on the MXU
    the 2 GEMM rounds (~3× the Householder FLOPs, but all matmul) beat a
    column-sequential factorisation by an order of magnitude; on CPU
    LAPACK's blocked Householder wins, so the rig keeps the tree unless a
    test forces the path."""
    import os
    v = os.environ.get("DSLIB_TSQR_CHOLQR", "auto")
    if v == "auto":
        return jax.default_backend() == "tpu"
    return v == "1"


def _cholqr2(a):
    """CholeskyQR2: two rounds of Gram → Cholesky → triangular solve.

    (Lit.: 'Large Scale Distributed Linear Algebra With Tensor Processing
    Units', arXiv:2112.09017 — QR via Cholesky of AᵀA is the TPU-native
    tall-skinny factorisation; the second round restores orthogonality to
    O(u) whenever the first Cholesky succeeds, i.e. cond(A) ≲ u^(-1/2).)

    Returns (Q, R, ok): ``ok`` is False when the result is unusable — the
    Gram Cholesky broke down (NaN/inf), OR round 1's orthogonality error
    was too large for round 2's O(u) restoration to apply.  The latter is
    measured from the ALREADY-COMPUTED second factor: by construction
    R₂ᵀR₂ = Q₁ᵀQ₁ (to Cholesky rounding), so ‖R₂ᵀR₂ − I‖_max IS round 1's
    orthogonality error at O(n³) cost — no m-sized Gram of Q₂ needed.
    The CholeskyQR2 guarantee (final orthogonality O(u)) holds whenever
    that error is ≪ 1; the 0.1 threshold is conservative.  The explicit
    check matters because in the cond(A) band around u^(-1/2) the
    Cholesky can stay finite while orthogonality quietly degrades —
    finiteness alone does not guarantee quality.  The caller falls back
    to the Householder tree on ok=False, so ill-conditioned inputs lose
    speed, never accuracy."""
    def one_round(q):
        g = q.T @ q
        ell = jnp.linalg.cholesky(g)                 # G = L Lᵀ, R = Lᵀ
        q_next = jax.scipy.linalg.solve_triangular(ell, q.T, lower=True).T
        return q_next, ell.T

    q1, r1 = one_round(a)
    q2, r2 = one_round(q1)
    r = r2 @ r1
    n = a.shape[1]
    round1_err = jnp.max(jnp.abs(r2.T @ r2 - jnp.eye(n, dtype=r2.dtype)))
    ok = jnp.all(jnp.isfinite(q2)) & jnp.all(jnp.isfinite(r)) \
        & (round1_err < 0.1)
    return q2, r, ok


def _local_qr(a, cholqr, policy=px.FLOAT32):
    """Shard-local tall-skinny QR: CholeskyQR2 when ``cholqr`` (with an
    in-program fallback to the Householder tree on Cholesky breakdown),
    the batched Householder reduction tree otherwise.  ``cholqr`` is a
    trace-time static (threaded from `_use_cholqr()` through the jit cache
    key, so flipping the env var retraces instead of being ignored).
    ``policy`` governs only the reduction tree's batched Q-apply GEMMs;
    the Householder/Cholesky factorisations themselves are pinned f32."""
    if not cholqr:
        return _local_tsqr(a, policy)
    q_c, r_c, ok = _cholqr2(a)
    # tuple(): jnp.linalg.qr yields a QRResult NamedTuple — a different
    # pytree type than the true branch's plain tuple
    return lax.cond(ok,
                    lambda op: (q_c, r_c),
                    lambda op: tuple(_local_tsqr(op, policy)),
                    a)


def _split_count(rows: int, n: int, target: int = 8) -> int:
    """Largest power-of-two ``s`` dividing ``rows`` with panels ≥ target·n tall."""
    s = 1
    while rows % (2 * s) == 0 and rows // (2 * s) >= target * max(n, 1):
        s *= 2
    return s


def _local_tsqr(a, policy=px.FLOAT32):
    """Shard-LOCAL tall-skinny QR as a batched reduction tree.

    A single Householder QR of an (M, n) panel is a column-sequential
    factorisation — each of the n reflector steps is a skinny matvec +
    rank-1 update, far below MXU occupancy for M ≫ n.  This applies the
    reference's tsQR reduction tree (SURVEY §3.2: per-block QR + pairwise
    R merges) *within* one chip: factor ``s`` sub-panels as ONE batched QR
    (the batch dimension feeds the MXU), then recurse on the (s·n, n)
    R-stack until it is short enough to factor directly.  Same
    Householder-tree numerics as the cross-shard tsQR, so stability is
    unchanged; shapes are static so the whole tree is one traced program.
    Degrades to a plain ``jnp.linalg.qr`` when the input is too short to
    split (the CPU-rig test shapes and the p·n R-stack at small p).
    """
    rows, n = a.shape
    s = _split_count(rows, n)
    if s == 1:
        return jnp.linalg.qr(a, mode="reduced")
    q0, r0 = jnp.linalg.qr(a.reshape(s, rows // s, n), mode="reduced")
    q1, r = _local_tsqr(r0.reshape(s * n, n), policy)
    q = px.pdot(q0, q1.reshape(s, n, n), policy)             # batched GEMM
    return q.reshape(rows, n), r


@partial(jax.jit, static_argnames=("mesh", "p", "cholqr", "policy"))
@precise
def _tsqr_shardmap(av, mesh, p, *, cholqr, policy=px.FLOAT32):
    """``cholqr`` is REQUIRED (no default): every caller must resolve
    `_use_cholqr()` at its own trace boundary and thread it through its
    jit cache key, otherwise an env flip after the first trace would be
    silently ignored."""
    n = av.shape[1]

    def local(a_shard):
        q1, r1 = _local_qr(a_shard, cholqr, policy)          # (m/p, n), (n, n)
        r_stack = lax.all_gather(r1, _mesh.ROWS)             # (p, n, n) — ICI
        r_stack = r_stack.reshape(p * n, n)
        q2, r = _local_qr(r_stack, cholqr, policy)           # redundant per shard
        idx = lax.axis_index(_mesh.ROWS)
        q2_i = lax.dynamic_slice(q2, (idx * n, 0), (n, n))
        # R is computed identically on every shard, but the static
        # varying-axes analysis can't see that through the local QR; a
        # psum/p makes the replication PROVABLE so check_vma stays ON
        # (SURVEY §6 race-detection row: shard_map replication checking is
        # the collective-correctness sanitizer).  Cost: one (n, n) psum.
        r = lax.psum(r, _mesh.ROWS) / p
        return px.pdot(q1, q2_i, policy), r

    q, r = jax.shard_map(
        local, mesh=mesh,
        in_specs=P(_mesh.ROWS, None),
        out_specs=(P(_mesh.ROWS, None), P(None, None)),
        check_vma=True,
    )(av)
    return q, r


def _col_repad(q_pad):
    """Pad Q's column dim back to the mesh quantum (rows already padded)."""
    import math
    q = _mesh.pad_quantum()
    n = q_pad.shape[1]
    target = max(q, int(math.ceil(n / q)) * q)
    if target != n:
        q_pad = jnp.pad(q_pad, ((0, 0), (0, target - n)))
    return jax.device_put(q_pad, _mesh.data_sharding())
