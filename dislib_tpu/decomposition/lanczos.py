"""Block Lanczos SVD (reference: `dislib/decomposition/lanczos` — block
Lanczos bidiagonalisation for truncated SVD; SURVEY.md §3.2).

TPU-native: Golub–Kahan–Lanczos bidiagonalisation with full
reorthogonalisation, run as sharded GEMVs/GEMMs on the row-sharded operand;
the small bidiagonal system is solved replicated on every device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from dislib_tpu.data.array import Array
from dislib_tpu.ops import precision as px


def lanczos_svd(a: Array, k: int = 6, bs: int | None = None, rank: int | None = None,
                num_iterations: int | None = None, tol: float = 1e-8,
                epsilon: float | None = None, max_num_iterations: int | None = None,
                singular_values: int | None = None, random_state=None,
                verbose: bool = False, precision=None):
    """Truncated SVD via Golub–Kahan–Lanczos bidiagonalisation.

    Returns (U, S, V): U (m, k), S (1, k), V (n, k).  ``singular_values`` /
    ``rank`` are reference-parity aliases for ``k``.

    ``precision``: mixed-precision policy (None → the
    ``DSLIB_MATMUL_PRECISION`` default) for the A·v / Aᵀ·u products (the
    O(mn) work per step); reorthogonalisation and the bidiagonal solve
    stay float32 — bounds in ``ops/precision.ERROR_BOUNDS``.
    """
    policy = px.resolve(precision)
    k = singular_values or rank or k
    m, n = a.shape
    steps = min(num_iterations or max(2 * k, k + 8), min(m, n))
    # run on the padded sharded backing (pad rows/cols are zero, so GEMVs
    # are exact and the operand never gathers; the Lanczos vector v is
    # masked once at init and its pad entries stay exactly zero)
    u, s, v = _gkl(px.f32(a._data), n, steps,
                   jnp.uint32(0 if random_state is None else random_state),
                   policy)
    return (Array._from_logical(u[:m, :k]),
            Array._from_logical(s[:k].reshape(1, -1)),
            Array._from_logical(v[:n, :k]))


@partial(jax.jit, static_argnames=("n_valid", "steps", "policy"))
def _gkl(a, n_valid, steps, seed, policy=px.FLOAT32):
    m, n = a.shape
    key = jax.random.PRNGKey(seed)
    v0 = jax.random.normal(key, (n,), dtype=jnp.float32)
    v0 = v0 * (lax.broadcasted_iota(jnp.int32, (n,), 0) < n_valid)
    v0 = v0 / jnp.linalg.norm(v0)

    def body(j, carry):
        vs, us, alphas, betas, v, u, beta = carry
        vs = vs.at[:, j].set(v)
        u = px.pdot(a, v, policy) - beta * u
        # full reorthogonalisation against previous U (unfilled cols are
        # zero and contribute nothing)
        u = u - us @ (us.T @ u)
        alpha = jnp.linalg.norm(u)
        u = u / jnp.where(alpha < 1e-30, 1.0, alpha)
        us = us.at[:, j].set(u)
        alphas = alphas.at[j].set(alpha)

        w = px.pdot(a.T, u, policy) - alpha * v
        w = w - vs @ (vs.T @ w)
        beta = jnp.linalg.norm(w)
        betas = betas.at[j].set(beta)
        v = w / jnp.where(beta < 1e-30, 1.0, beta)
        return vs, us, alphas, betas, v, u, beta

    init = (jnp.zeros((n, steps), jnp.float32),
            jnp.zeros((m, steps), jnp.float32),
            jnp.zeros((steps,), jnp.float32),
            jnp.zeros((steps,), jnp.float32),
            v0, jnp.zeros((m,), jnp.float32), jnp.float32(0.0))
    vs, us, alphas, betas, _, _, _ = lax.fori_loop(0, steps, body, init)

    # bidiagonal B: alphas on diag, betas[0:-1] on superdiag
    b = jnp.diag(alphas) + jnp.diag(betas[:-1], k=1)
    ub, s, vbt = jnp.linalg.svd(b)
    return us @ ub, s, vs @ vbt.T
