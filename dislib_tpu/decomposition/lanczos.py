"""Block Lanczos SVD (reference: `dislib/decomposition/lanczos` — block
Lanczos bidiagonalisation for truncated SVD; SURVEY.md §3.2).

TPU-native: Golub–Kahan–Lanczos bidiagonalisation with full
reorthogonalisation, run as sharded GEMVs/GEMMs on the row-sharded operand;
the small bidiagonal system is solved replicated on every device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dislib_tpu.data.array import Array


def lanczos_svd(a: Array, k: int = 6, bs: int | None = None, rank: int | None = None,
                num_iterations: int | None = None, tol: float = 1e-8,
                epsilon: float | None = None, max_num_iterations: int | None = None,
                singular_values: int | None = None, random_state=None,
                verbose: bool = False):
    """Truncated SVD via Golub–Kahan–Lanczos bidiagonalisation.

    Returns (U, S, V): U (m, k), S (1, k), V (n, k).  ``singular_values`` /
    ``rank`` are reference-parity aliases for ``k``.
    """
    k = singular_values or rank or k
    m, n = a.shape
    steps = min(num_iterations or max(2 * k, k + 8), min(m, n))
    av = a._data[:m, :n].astype(jnp.float32)
    u, s, v = _gkl(av, steps, int(0 if random_state is None else random_state))
    return (Array._from_logical(u[:, :k]),
            Array._from_logical(s[:k].reshape(1, -1)),
            Array._from_logical(v[:, :k]))


def _gkl(a, steps, seed):
    m, n = a.shape
    key = jax.random.PRNGKey(seed)
    v0 = jax.random.normal(key, (n,), dtype=jnp.float32)
    v0 = v0 / jnp.linalg.norm(v0)

    vs = jnp.zeros((n, steps), jnp.float32)
    us = jnp.zeros((m, steps), jnp.float32)
    alphas = jnp.zeros((steps,), jnp.float32)
    betas = jnp.zeros((steps,), jnp.float32)

    v = v0
    beta = jnp.float32(0.0)
    u = jnp.zeros((m,), jnp.float32)
    # python loop: steps is static & modest; each iteration is sharded GEMV
    for j in range(steps):
        vs = vs.at[:, j].set(v)
        u = a @ v - beta * u
        # full reorthogonalisation against previous U
        u = u - us @ (us.T @ u)
        alpha = jnp.linalg.norm(u)
        u = u / jnp.where(alpha < 1e-30, 1.0, alpha)
        us = us.at[:, j].set(u)
        alphas = alphas.at[j].set(alpha)

        w = a.T @ u - alpha * v
        w = w - vs @ (vs.T @ w)
        beta = jnp.linalg.norm(w)
        betas = betas.at[j].set(beta)
        v = w / jnp.where(beta < 1e-30, 1.0, beta)

    # bidiagonal B: alphas on diag, betas[0:-1] on superdiag
    b = jnp.diag(alphas) + jnp.diag(betas[:-1], k=1)
    ub, s, vbt = jnp.linalg.svd(b)
    return us @ ub, s, vs @ vbt.T
