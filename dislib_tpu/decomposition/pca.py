"""PCA (reference: `dislib/decomposition/pca` — SURVEY.md §3.2: covariance
path = blocked mean-centering → scatter-matrix partial sums → eigh in one
task; svd path delegates to dislib's SVD).

TPU-native: the scatter matrix XᵀX is one sharded GEMM whose partial-sum
reduction over the row axis IS the reference's arity-tree of partial-sum
tasks, emitted by XLA as a psum over ICI.  The (n_features, n_features) eigh
runs replicated.  The svd path uses one-sided Jacobi (dislib_tpu.math.svd).
The reference's ``arity`` knob (reduction-tree fan-in) is intentionally
dropped: reduction topology is the compiler's job now (SURVEY §6 config row).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array
from dislib_tpu.ops import precision as px
from dislib_tpu.ops.base import precise


class PCA(BaseEstimator):
    """Principal component analysis.

    Parameters
    ----------
    n_components : int or None — defaults to n_features.
    arity : int — accepted for reference API parity; ignored (reduction
        topology is XLA's).
    method : 'eig' | 'svd' — covariance+eigh path or SVD path.
    precision : mixed-precision policy for the scatter-matrix GEMM (the
        O(mn²) work); None → the ``DSLIB_MATMUL_PRECISION`` default.  The
        (n, n) eigh/SVD stays float32.

    Attributes
    ----------
    components_ : Array (n_components, n_features)
    explained_variance_ : Array (1, n_components)
    mean_ : Array (1, n_features)
    """

    def __init__(self, n_components=None, arity=50, method="eig", eps=1e-9,
                 precision=None):
        self.n_components = n_components
        self.arity = arity
        self.method = method
        self.eps = eps
        self.precision = precision

    def fit(self, x: Array, y=None):
        m, n = x.shape
        k = self.n_components or n
        if self.method not in ("eig", "svd"):
            raise ValueError(f"unknown method {self.method!r}")
        xv = x._data  # padded; zero rows don't perturb sums
        mean, comps, var = _pca_fit(xv, x.shape, self.method == "svd",
                                    px.resolve(self.precision))
        self.mean_ = Array._from_logical(mean.reshape(1, -1))
        self.components_ = Array._from_logical(comps[:k])
        self.explained_variance_ = Array._from_logical(var[:k].reshape(1, -1))
        return self

    def fit_transform(self, x: Array, y=None) -> Array:
        return self.fit(x).transform(x)

    def transform(self, x: Array) -> Array:
        from dislib_tpu.math import matmul
        xc = x - self.mean_
        return matmul(xc, self.components_, transpose_b=True)

    def inverse_transform(self, y: Array) -> Array:
        from dislib_tpu.math import matmul
        return matmul(y, self.components_) + self.mean_


from functools import partial


@partial(jax.jit, static_argnames=("shape", "use_svd", "policy"))
@precise
def _pca_fit(xp, shape, use_svd, policy=px.FLOAT32):
    m, n = shape
    xv = xp[:, :n]  # crop cols; padded rows are zero
    total = jnp.sum(xv, axis=0)
    mean = total / m
    # centered scatter without materialising centered X for padded rows:
    # Σ (x-μ)(x-μ)ᵀ over logical rows = XᵀX - m μμᵀ   (padded zero rows add 0 to XᵀX)
    scatter = px.pdot(xv.T, xv, policy) - m * jnp.outer(mean, mean)
    cov = scatter / (m - 1)
    if use_svd:
        # SVD of covariance (symmetric PSD): singular values = eigenvalues
        u, s, _ = jnp.linalg.svd(cov)
        return mean, u.T, s
    w, v = jnp.linalg.eigh(cov)
    order = jnp.argsort(-w)
    return mean, v[:, order].T, w[order]
