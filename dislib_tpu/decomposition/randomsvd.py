"""Randomized SVD (reference: `dislib/decomposition/randomsvd` — Gaussian
test matrix, power iterations with QR re-orthonormalisation, small dense SVD
of the projected matrix; SURVEY.md §3.2, BASELINE config 4).

TPU-native: the sketch Y = A Ω and the power iterations are sharded GEMMs
(MXU-bound); re-orthonormalisation uses the tsQR tree so the only collective
per iteration is the all_gather(R) + the GEMM's own partial-sum psum — the
survey's "power-iteration psum" pattern.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from dislib_tpu.data.array import Array, random_array
from dislib_tpu.math import matmul
from dislib_tpu.decomposition.tsqr import tsqr


def random_svd(a: Array, iters: int = 2, epsilon: float | None = None,
               tol: float = 1e-3, nsv: int | None = None, k: int | None = None,
               oversample: int = 10, random_state=None, verbose: bool = False):
    """Truncated randomized SVD of ``a``.

    Returns (U, S, V) with U (m, k), S (1, k), V (n, k); ``k`` defaults to
    ``nsv`` (number of singular values) + oversampling, truncated to nsv.
    """
    m, n = a.shape
    nsv = nsv if nsv is not None else (k if k is not None else min(m, n, 6))
    sketch = min(n, nsv + oversample)
    seed = 0 if random_state is None else int(np.random.RandomState(random_state).randint(2**31 - 1)) \
        if not isinstance(random_state, (int, np.integer)) else int(random_state)

    omega_h = jax.random.normal(jax.random.PRNGKey(seed), (n, sketch), dtype=jnp.float32)
    omega = Array._from_logical(omega_h)

    y = matmul(a, omega)                     # (m, sketch) sharded GEMM
    q, _ = tsqr(y) if m >= sketch else _qr_fallback(y)
    for _ in range(iters):
        z = matmul(a, q, transpose_a=True)   # (n, sketch)
        qz, _ = tsqr(z) if n >= sketch else _qr_fallback(z)
        y = matmul(a, qz)
        q, _ = tsqr(y) if m >= sketch else _qr_fallback(y)

    b = matmul(q, a, transpose_a=True)       # (sketch, n) small projected matrix
    bv = b._data[: b.shape[0], : b.shape[1]]
    ub, s, vt = jnp.linalg.svd(bv, full_matrices=False)
    u = matmul(q, Array._from_logical(ub))
    u = u[:, :nsv]
    v = Array._from_logical(vt.T[:, :nsv])
    s_arr = Array._from_logical(s[:nsv].reshape(1, -1))
    return u, s_arr, v


def _qr_fallback(y: Array):
    from dislib_tpu.math.qr import qr as _qr
    return _qr(y, mode="economic")
