"""Randomized SVD (reference: `dislib/decomposition/randomsvd` — Gaussian
test matrix, power iterations with QR re-orthonormalisation, small dense SVD
of the projected matrix; SURVEY.md §3.2, BASELINE config 4).

TPU-native: the sketch Y = A Ω and the power iterations are sharded GEMMs
(MXU-bound); re-orthonormalisation uses the tsQR tree so the only collective
per iteration is the all_gather(R) + the GEMM's own partial-sum psum — the
survey's "power-iteration psum" pattern.

The whole pipeline (sketch → power iterations → projection → small SVD →
back-multiplication) is ONE jitted program — the same one-compiled-program
design the iterative estimators use for their fit loops.  A host-level
composition of the stages costs one dispatch per GEMM/tsQR (~15 for
iters=2); measured through the axon tunnel's ~69 ms per-dispatch round
trip that was ~0.3 s of pure latency on BASELINE config 4.  Shapes are
static, so fusing is free.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.data.array import Array, _repad
from dislib_tpu.math import matmul
from dislib_tpu.decomposition.tsqr import (tsqr, _tsqr_shardmap,
                                           _use_cholqr)
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.ops import precision as px
from dislib_tpu.ops.base import precise


def random_svd(a: Array, iters: int = 2, epsilon: float | None = None,
               tol: float = 1e-3, nsv: int | None = None, k: int | None = None,
               oversample: int = 10, random_state=None, verbose: bool = False,
               precision=None):
    """Truncated randomized SVD of ``a``.

    Returns (U, S, V) with U (m, k), S (1, k), V (n, k); ``k`` defaults to
    ``nsv`` (number of singular values) + oversampling, truncated to nsv.

    ``precision``: mixed-precision policy (None → the
    ``DSLIB_MATMUL_PRECISION`` default).  The policy governs the sketch /
    power-iteration / projection / back-multiplication GEMMs (all the
    O(mn·sketch) FLOPs); the tsQR re-orthonormalisations and the small
    (sketch, n) SVD stay float32 — bounds in
    ``ops/precision.ERROR_BOUNDS``.
    """
    policy = px.resolve(precision)
    m, n = a.shape
    nsv = nsv if nsv is not None else (k if k is not None else min(m, n, 6))
    sketch = min(n, nsv + oversample)
    nsv = min(nsv, sketch)  # only `sketch` directions exist in the subspace
    seed = 0 if random_state is None else int(np.random.RandomState(random_state).randint(2**31 - 1)) \
        if not isinstance(random_state, (int, np.integer)) else int(random_state)

    if type(a) is Array and m >= sketch and a._data.dtype == jnp.float32:
        # fused single-dispatch path (sketch ≤ n always holds); f64 inputs
        # (x64-mode CPU rig) keep the composed path's dtype fidelity
        mesh = _mesh.get_mesh()
        p = mesh.shape[_mesh.ROWS]
        u_log, s, vt = _random_svd_fused(
            a._data, jax.random.PRNGKey(seed), a.shape, iters, sketch,
            nsv, mesh, p, cholqr=_use_cholqr(), policy=policy)
        u = Array._from_logical_padded(_repad(u_log, (m, nsv)), (m, nsv))
        v = Array._from_logical(vt.T[:, :nsv])
        return u, Array._from_logical(s[:nsv].reshape(1, -1)), v

    omega = Array._from_logical(_omega_of(jax.random.PRNGKey(seed), n, sketch))

    # the orthonormalisations are PINNED f32 (matching the fused path and
    # the docstring contract) — explicitly, so an ambient
    # DSLIB_MATMUL_PRECISION can never leak into them when the caller
    # asked for float32 (review-found env-leak)
    y = matmul(a, omega, precision=policy)   # (m, sketch) sharded GEMM
    q, _ = tsqr(y, precision=px.FLOAT32) if m >= sketch else _qr_fallback(y)
    for _ in range(iters):
        z = matmul(a, q, transpose_a=True, precision=policy)   # (n, sketch)
        qz, _ = tsqr(z, precision=px.FLOAT32) if n >= sketch \
            else _qr_fallback(z)
        y = matmul(a, qz, precision=policy)
        q, _ = tsqr(y, precision=px.FLOAT32) if m >= sketch \
            else _qr_fallback(y)

    b = matmul(q, a, transpose_a=True,
               precision=policy)             # (sketch, n) small projected matrix
    bv = b._data[: b.shape[0], : b.shape[1]]
    ub, s, vt = jnp.linalg.svd(bv, full_matrices=False)
    u = matmul(q, Array._from_logical(ub), precision=policy)
    u = u[:, :nsv]
    v = Array._from_logical(vt.T[:, :nsv])
    s_arr = Array._from_logical(s[:nsv].reshape(1, -1))
    return u, s_arr, v


@partial(jax.jit, static_argnames=("a_shape", "iters", "sketch", "nsv",
                                   "cholqr", "policy",
                                   "mesh", "p"))
@precise
def _random_svd_fused(a_pad, key, a_shape, iters, sketch, nsv, mesh, p,
                      *, cholqr, policy=px.FLOAT32):
    """Sketch + power iterations + projection + SVD as one XLA program.

    Quantum-padded rows/cols of ``a_pad`` are zero, so they contribute
    nothing to any GEMM; tsQR's Q rows at zero input rows are zero for a
    full-column-rank sketch (Q_i R = 0 with R invertible ⇒ Q_i = 0), which
    keeps the returned U's logical crop exact."""
    m, n = a_shape
    av = px.f32(a_pad[:, :n])
    av = lax.with_sharding_constraint(av, _mesh.row_sharding())

    def ortho(y):
        # rows must be ≥ sketch per shard AND divisible by p for shard_map
        rows = y.shape[0]
        target = max(p * sketch, -(-rows // p) * p)
        if target != rows:
            y = jnp.pad(y, ((0, target - rows), (0, 0)))
        y = lax.with_sharding_constraint(y, _mesh.row_sharding())
        q, _ = _tsqr_shardmap(y, mesh, p, cholqr=cholqr)
        return q[:rows]

    q = ortho(px.pdot(av, _omega_of(key, n, sketch), policy))
    for _ in range(iters):
        qz = ortho(px.pdot(av.T, q, policy))
        q = ortho(px.pdot(av, qz, policy))

    b = px.pdot(q.T, av, policy)             # (sketch, n), replicated
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = px.pdot(q, ub[:, :nsv], policy)      # (M_pad, nsv)
    return u[:m], s, vt


def _omega_of(key, n, sketch):
    """Gaussian test matrix — single definition shared by both paths so the
    fused and composed pipelines provably start from the same draw."""
    return jax.random.normal(key, (n, sketch), dtype=jnp.float32)


def _qr_fallback(y: Array):
    from dislib_tpu.math.qr import qr as _qr
    # pinned f32 like the tsqr orthonormalisations (env must not leak in)
    return _qr(y, mode="economic", precision=px.FLOAT32)
