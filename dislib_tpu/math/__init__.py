from dislib_tpu.math.base import matmul, kron, svd
from dislib_tpu.math.polar import polar
from dislib_tpu.math.qr import qr

__all__ = ["matmul", "kron", "svd", "qr", "polar"]
