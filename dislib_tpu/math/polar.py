"""Distributed polar decomposition via Newton–Schulz iteration.

A = U H with U orthonormal columns (the closest partial isometry to A)
and H = UᵀA symmetric positive semi-definite.  The TPU-native fit
(arXiv:2112.09017 §polar): Newton–Schulz is PURE GEMM —

    X₀      = A / ‖A‖_F                      (spectrum scaled into (0, 1])
    G_k     = X_kᵀ X_k                        (one (n, n) Gram GEMM)
    X_{k+1} = 1.5·X_k − 0.5·X_k G_k           (one (m, n)×(n, n) GEMM)

— two MXU-shaped products per iteration and nothing else, which makes it
both a capability (polar factors feed subspace orthogonalisation, the
symmetric eigenproblem via the matrix sign function, and Procrustes
alignment) and the library's canonical sustained-GFLOPS workload
(``bench.py::bench_polar``: 4·m·n² FLOPs/iteration, no factorisation on
the critical path).

The whole loop — scaling, every iteration, the convergence test, and the
final H = UᵀA — runs inside ONE jitted program (``lax.while_loop``), so a
polar call costs ONE dispatch regardless of iteration count; the
per-iteration dispatch cost of 0 extra is counter-pinned by
``tests/test_precision.py`` and the bench tier.

Mixed precision: the GEMMs route through the library precision policy
(``ops/precision``) — ``precision="bfloat16"`` contracts bf16-compute /
f32-accumulate.  Newton–Schulz is self-correcting (each step contracts
the orthogonality error), so reduced-precision iterates converge to the
COMPUTE dtype's orthogonality floor rather than diverging: ~1e-6 at
float32, ~2e-2 at bfloat16 (``ops/precision.ERROR_BOUNDS``).  ``tol``
below the active policy's floor is clamped with a warning (the
``math.svd`` eps precedent).

Convergence needs σ(X₀) ⊂ (0, √3); the Frobenius scaling guarantees
σ ≤ 1.  Rank-deficient A: exact zero singular directions stay exactly
zero (0 is a fixed point), so U converges to a partial isometry on
range(A) but the convergence test — driven by ‖G − I‖ on the logical
block — never reaches ``tol``; the loop then runs ``max_iter``
iterations and returns the partial isometry.  Quantum-padded rows/cols
are zero and stay exactly zero through every iterate (σ = 0 fixed
point), so padding never perturbs the logical factors.
"""

from __future__ import annotations

from functools import partial

import jax.numpy as jnp
from jax import lax

from dislib_tpu.data.array import Array
from dislib_tpu.ops import precision as px
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.utils.profiling import profiled_jit as _pjit

# orthogonality floors per policy: a tol below the compute dtype's
# reachable ‖XᵀX − I‖_max is unreachable and would burn max_iter on every
# call (the math.svd eps-clamp precedent)
_TOL_FLOOR = {"float32": 1e-6, "bfloat16": 5e-3}
_TOL_DEFAULT = {"float32": 1e-5, "bfloat16": 1e-2}


def polar(a: Array, precision=None, max_iter: int = 30, tol: float | None = None,
          info: bool = False):
    """Polar decomposition ``A = U @ H`` of a tall (m ≥ n) ds-array.

    Returns ``(U, H)`` ds-arrays — U (m, n) with orthonormal columns,
    H (n, n) symmetric PSD — or ``(U, H, info_dict)`` when ``info=True``
    with ``{"iterations": k, "ortho_err": ‖UᵀU − I‖_max}``.

    ``precision``: mixed-precision policy (None → ``DSLIB_MATMUL_PRECISION``
    default); ``tol``: convergence threshold on ‖X_kᵀX_k − I‖_max,
    defaulting per policy (1e-5 float32, 1e-2 bfloat16) and clamped to the
    policy's orthogonality floor.  ``max_iter`` bounds the on-device loop.
    """
    m, n = a.shape
    if m < n:
        raise ValueError(
            f"polar needs a tall or square array (m >= n), got {a.shape}; "
            "factorise a.T and transpose the identity A = (Uᵀ H)ᵀ = H Uᵀ "
            "for the left polar form")
    policy = px.resolve(precision)
    if tol is None:
        tol = _TOL_DEFAULT[policy.name]
    floor = _TOL_FLOOR[policy.name]
    if float(tol) < floor:
        import warnings
        warnings.warn(
            f"polar: tol={tol:g} is below the {policy.name} orthogonality "
            f"floor; clamping to {floor:g}", RuntimeWarning, stacklevel=2)
    tol = max(float(tol), floor)
    u_pad, h, iters, err = _polar_kernel(a._data, a.shape, policy,
                                         int(max_iter), float(tol))
    u_arr = Array._from_logical_padded(u_pad, (m, n), a._reg_shape)
    h_arr = Array._from_logical_padded(h, (n, n))
    if not info:
        return u_arr, h_arr
    return u_arr, h_arr, {"iterations": int(iters),
                          "ortho_err": float(err)}


@partial(_pjit, static_argnames=("shape", "policy", "max_iter"),
         name="polar_ns")
@px.precise
def _polar_kernel(ap, shape, policy, max_iter, tol):
    """The whole Newton–Schulz loop as one program.  Operates on the full
    padded backing: pad rows/cols are zero, contribute nothing to the
    Grams, and stay zero through every update (σ = 0 is a fixed point of
    the iteration), so the logical crop of the result is exact."""
    m, n = shape
    x = px.f32(ap)
    np_pad = x.shape[1]
    shard = _mesh.data_sharding()
    # Frobenius norm over the padded canvas == over the logical block
    # (pads are zero); scale so every singular value lies in (0, 1]
    alpha = jnp.sqrt(jnp.sum(x * x))
    x = x / jnp.maximum(alpha, jnp.asarray(1e-30, x.dtype))
    # pad-aware identity: ones only on the logical diagonal, so the
    # convergence measure ‖G − I‖ is exactly the logical orthogonality
    # error (pad rows/cols of G are zero on both sides of the subtraction)
    di = lax.broadcasted_iota(jnp.int32, (np_pad, np_pad), 0)
    dj = lax.broadcasted_iota(jnp.int32, (np_pad, np_pad), 1)
    eye = jnp.where((di == dj) & (di < n), jnp.ones((), x.dtype),
                    jnp.zeros((), x.dtype))

    def cond(carry):
        _, err, it = carry
        return (err > tol) & (it < max_iter)

    def body(carry):
        x, _, it = carry
        g = px.pdot(x.T, x, policy)                       # Gram, (n, n)
        err = jnp.max(jnp.abs(g - eye))
        x_new = 1.5 * x - 0.5 * px.pdot(x, g, policy)
        x_new = lax.with_sharding_constraint(x_new, shard)
        # a converged x must pass through unchanged: once err ≤ tol the
        # update is skipped so the returned U matches the reported err
        x = jnp.where(err > tol, x_new, x)
        return x, err, it + 1

    x, err, iters = lax.while_loop(
        cond, body, (x, jnp.asarray(jnp.inf, x.dtype), 0))
    # the loop-carried err describes the PRE-update iterate; on a
    # max_iter exit (the documented rank-deficient case) that would
    # overstate the returned U's error by one whole contraction — report
    # the RETURNED factor's Gram instead (one extra (n, n) GEMM,
    # accounted in bench_polar's FLOP formula)
    g_final = px.pdot(x.T, x, policy)
    err = jnp.max(jnp.abs(g_final - eye))
    h = px.pdot(x.T, px.f32(ap), policy)                  # H = Uᵀ A
    h = 0.5 * (h + h.T)                                   # exact symmetry
    return x, h, iters, err
