"""Blocked math: matmul, kron, svd.

Reference capabilities (SURVEY.md §3.2):
- `dislib.math.matmul` — blocked GEMM, one `_multiply` task per (i,j,k) block
  triple with INOUT accumulation (SURVEY §4.3).
- `dislib.math.kron` — Kronecker product, one scaled-copy task per block pair.
- `dislib.math.svd`  — one-sided block-Jacobi SVD: round-robin pairing of
  column blocks, rotations until convergence.

TPU-native redesign: the O(p^3) task loop IS a distributed GEMM schedule —
on TPU that schedule belongs to the XLA SPMD partitioner.  `matmul` is a
single `jnp.dot` over 2-D-sharded global arrays with a sharding constraint on
the result; XLA emits the SUMMA-style collective_permute/all_gather pattern
over ICI (the survey's §4.3 TPU mapping).  Zero padding makes the contraction
exact with no masking.  `svd` keeps the reference's one-sided Jacobi
*algorithm* (it is communication-friendly and converges quadratically) but
runs the rotation sweeps as jitted device loops — scalar column pairs at
small n, the reference's column-BLOCK pairs (batched QR + small SVD per
pair, MXU-shaped) at n ≥ 128.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

import os

from dislib_tpu.data.array import (
    Array, _LazyExpr, _eager_mode, _lazy_array, _matmul_body,
    ensure_canonical as _ensure_canonical,
)
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.ops import overlap as _ov
from dislib_tpu.ops import precision as px
from dislib_tpu.ops.base import precise
from dislib_tpu.ops.summa import summa_matmul, summa_supported
from dislib_tpu.utils import profiling as _prof
from dislib_tpu.utils.profiling import profiled_jit as _pjit


# ---------------------------------------------------------------------------
# matmul
# ---------------------------------------------------------------------------

@partial(_pjit, static_argnames=("ta", "tb", "a_shape", "b_shape", "policy"),
         name="matmul")
@precise
def _matmul_kernel(a, b, ta, tb, a_shape, b_shape, policy):
    del a_shape, b_shape
    # zero-padding invariant ⇒ padded contraction == logical contraction
    return _matmul_body(a, b, ta, tb, policy)


# auto-SUMMA size gate: below this min logical dimension an explicit
# panel schedule buys nothing over the partitioner's fused dot, and a
# small product is usually mid-chain where leaving the fusion graph would
# cost a whole extra dispatch (module-level so tests can shrink it;
# ``DSLIB_SUMMA_MIN_DIM`` overrides at runtime — the bench overlap tier
# sweeps small dims on host rigs without editing source)
_SUMMA_MIN_DIM = 256


def _summa_min_dim() -> int:
    """The auto-SUMMA size gate the router actually enforces: the
    ``DSLIB_SUMMA_MIN_DIM`` env knob when set, else the module default
    (read per call so an env flip re-routes immediately — routing is a
    host decision, no retrace subtlety)."""
    env = os.environ.get("DSLIB_SUMMA_MIN_DIM")
    return int(env) if env else _SUMMA_MIN_DIM


def _pick_algorithm(algorithm, a, b, a_shape, b_shape, dense,
                    transpose_a, transpose_b):
    """The matmul routing rule: which schedule owns this product.

    - explicit ``algorithm=`` wins; ``"auto"`` consults ``DSLIB_MATMUL_ALGO``
      and then the mesh shape AND operand layout;
    - ``"summa"`` = the explicit panel-broadcast schedule (``ops/summa``),
      picked automatically on a genuinely 2-D mesh (both axes > 1) for
      dense, untransposed, CONCRETE operands at paper-scale sizes (every
      logical dim ≥ ``_SUMMA_MIN_DIM``) — a standalone big product.
      Lazy (fusion-graph) operands stay on the XLA path under auto: the
      PR-2/PR-4 one-dispatch-per-chain contracts hold on every mesh, and
      routing a mid-chain GEMM to an eager kernel would force the chain
      (review-found: estimator predict pipelines must not silently gain
      dispatches when the mesh goes 2-D);
    - ``"xla"`` = one sharded dot, schedule owned by the SPMD partitioner
      (optimal on 1-D meshes, and a fusion-graph node).
    """
    if algorithm not in ("auto", "summa", "xla"):
        raise ValueError(f"unknown matmul algorithm {algorithm!r}: "
                         "expected 'auto', 'summa' or 'xla'")
    if algorithm == "auto":
        env = os.environ.get("DSLIB_MATMUL_ALGO", "auto")
        if env not in ("auto", "summa", "xla"):
            raise ValueError(f"bad DSLIB_MATMUL_ALGO={env!r}")
        algorithm = env
    if algorithm == "auto":
        big = min(a_shape[0], a_shape[1], b_shape[1]) >= _summa_min_dim()
        standalone = dense and not (a.is_lazy or b.is_lazy)
        return "summa" if (standalone and big and summa_supported()
                           and not (transpose_a or transpose_b)) else "xla"
    return algorithm


def matmul(a: Array, b: Array, transpose_a: bool = False,
           transpose_b: bool = False, *, algorithm: str = "auto",
           precision=None) -> Array:
    """Distributed GEMM (reference: dislib.math.matmul, `_multiply` task).

    One entry, two schedules, picked from the mesh shape (override with
    ``algorithm=`` or ``DSLIB_MATMUL_ALGO``):

    - 2-D mesh (both axes > 1): an explicit SUMMA panel-broadcast schedule
      (``ops/summa``) — the arXiv:2112.09017 regime, one dispatch;
    - 1-D mesh / single device: one XLA dot over the 2-D-sharded operands;
      the partitioner owns the communication schedule the reference
      expressed as O(p^3) COMPSs tasks.  On dense ds-array operands this
      is a fusion-graph node: the dot joins the operands' deferred chains
      and dispatches with the first force.

    ``precision``: the mixed-precision policy (None → the
    ``DSLIB_MATMUL_PRECISION`` default) — ``"bfloat16"`` contracts
    bf16-compute / f32-accumulate with the documented error bounds
    (``ops/precision.ERROR_BOUNDS``); the default is float32-faithful.

    SPARSE lhs (:class:`~dislib_tpu.data.sparse.SparseArray`): a second
    router — ``algorithm="auto"|"spmm"|"densify"`` — keyed on density ×
    the densify budget.  ``"spmm"`` runs the sharded masked-psum SpMM
    (``ops/spmm``, O(nnz) memory, one dispatch, overlap-scheduled);
    ``"densify"`` materialises the dense operand on device (budget-
    guarded) and takes the dense path; ``"auto"`` picks spmm at or below
    ``DSLIB_SPMM_MAX_DENSITY`` (default 0.1) or whenever densifying
    would blow ``DSLIB_SPARSE_DENSIFY_BUDGET``, densify otherwise."""
    from dislib_tpu.data.sparse import SparseArray
    if isinstance(a, SparseArray) or isinstance(b, SparseArray):
        return _matmul_sparse(a, b, transpose_a, transpose_b, algorithm,
                              precision)
    policy = px.resolve(precision)
    a_shape = (a.shape[1], a.shape[0]) if transpose_a else a.shape
    b_shape = (b.shape[1], b.shape[0]) if transpose_b else b.shape
    if a_shape[1] != b_shape[0]:
        raise ValueError(f"matmul shape mismatch: {a_shape} @ {b_shape}")
    out_shape = (a_shape[0], b_shape[1])
    reg = (a._reg_shape[1] if transpose_a else a._reg_shape[0],
           b._reg_shape[0] if transpose_b else b._reg_shape[1])
    dense = type(a) is Array and type(b) is Array
    algo = _pick_algorithm(algorithm, a, b, a_shape, b_shape, dense,
                           transpose_a, transpose_b)
    if algo == "summa":
        if not dense:
            raise ValueError("algorithm='summa' needs dense ds-array "
                             "operands")
        return _matmul_summa(a, b, transpose_a, transpose_b, policy,
                             out_shape, reg)
    if dense and not _eager_mode():
        pa, pb = a._pshape, b._pshape
        out_pshape = (pa[1] if transpose_a else pa[0],
                      pb[0] if transpose_b else pb[1])
        dtype = jnp.promote_types(jnp.promote_types(a.dtype, b.dtype),
                                  jnp.float32)
        expr = _LazyExpr("matmul", (transpose_a, transpose_b, policy.name),
                         (a._node(), b._node()), out_pshape, dtype)
        return _lazy_array(expr, out_shape, reg, False)
    # padded inner dims must agree for the padded dot; repad if quantum differs
    ad, bd = a._data, b._data
    ad, bd = _match_inner(ad, bd, transpose_a, transpose_b)
    out = _matmul_kernel(ad, bd, transpose_a, transpose_b, a_shape, b_shape,
                         policy)
    return Array(_crop_or_keep(out, out_shape), out_shape, reg, False)


def _spmm_max_density() -> float:
    """The density at which auto stops preferring SpMM over one dense
    GEMM: SpMM's arithmetic is ~nnz · panel-count scatter work vs the
    MXU-shaped m·n dense contraction, so the crossover sits around
    1/steps — 0.1 covers the common mesh row counts.
    ``DSLIB_SPMM_MAX_DENSITY`` overrides at runtime."""
    return float(os.environ.get("DSLIB_SPMM_MAX_DENSITY", "0.1"))


def _pick_sparse_algorithm(a, algorithm):
    """The sparse matmul routing rule: explicit ``algorithm=`` wins;
    auto keys on density × the densify budget — spmm at/below the
    density threshold, densify above it UNLESS the dense materialisation
    would blow the byte budget (then spmm regardless: O(nnz) always
    fits where the data itself fits)."""
    from dislib_tpu.data.array import _padded_shape
    from dislib_tpu.data.sparse import densify_budget_bytes
    if algorithm not in ("auto", "spmm", "densify"):
        raise ValueError(
            f"unknown sparse matmul algorithm {algorithm!r}: expected "
            "'auto', 'spmm' or 'densify'")
    if algorithm != "auto":
        return algorithm
    m, n = a.shape
    density = a.nnz / max(m * n, 1)
    if density <= _spmm_max_density():
        return "spmm"
    pm, pn = _padded_shape(a.shape, _mesh.pad_quantum())
    return "spmm" if 4 * pm * pn > densify_budget_bytes() else "densify"


def _matmul_sparse(a, b, transpose_a, transpose_b, algorithm, precision):
    """The sparse fast-path entry: SparseArray @ dense ds-array via the
    spmm/densify router.  Transposed and sparse-rhs/sparse-sparse forms
    have no sharded schedule — they densify EXPLICITLY (never silently:
    a typed error names the escape hatch)."""
    from dislib_tpu.data.array import Array
    from dislib_tpu.data.sparse import SparseArray
    from dislib_tpu.ops.spmm import spmm as _spmm_entry
    if isinstance(b, SparseArray) or not isinstance(a, SparseArray) \
            or transpose_a or transpose_b:
        raise TypeError(
            "the sparse matmul fast path covers sparse @ dense with no "
            "transposes — transpose via SparseArray.T (sparse, O(nnz)) "
            "or densify explicitly with .to_dense() for other forms")
    if not isinstance(b, Array):
        raise TypeError(f"matmul rhs must be a dense ds-array, "
                        f"got {type(b).__name__}")
    if a.shape[1] != b.shape[0]:
        raise ValueError(f"matmul shape mismatch: {a.shape} @ {b.shape}")
    algo = _pick_sparse_algorithm(a, algorithm)
    if algo == "spmm":
        return _spmm_entry(a, b, precision=precision)
    return matmul(a.to_dense(), b, precision=precision)


def _matmul_summa(a, b, transpose_a, transpose_b, policy, out_shape, reg):
    """The SUMMA route: canonical (rows, cols)-sharded operands through the
    explicit panel schedule.  Requested transposes materialise first (one
    extra dispatch each — the auto policy never picks SUMMA for transposed
    operands; an explicit ``algorithm='summa'`` accepts the cost)."""
    if transpose_a:
        a = a.transpose()
    if transpose_b:
        b = b.transpose()
    # operands built under an OLDER mesh can carry a pad quantum (or
    # layout) the current grid doesn't divide — the panel loop would
    # silently drop the K tail (and shard_map reject the row/col split);
    # the on-device rechunk ingest guard re-lays them out first
    a = _ensure_canonical(a)
    b = _ensure_canonical(b)
    ad, bd = a._data, b._data
    ad, bd = _match_inner(ad, bd, False, False)
    # panel schedule: resolved HERE (the host routing boundary) so a
    # DSLIB_OVERLAP flip retraces via the kernel's static, and the run
    # is observable through the schedule counters
    sched = _ov.resolve()
    _prof.count_schedule("summa_matmul", sched)
    out = summa_matmul(ad, bd, _mesh.get_mesh(), policy, overlap=sched)
    return Array(_crop_or_keep(out, out_shape), out_shape, reg, False)


def _match_inner(ad, bd, transpose_a, transpose_b):
    """Equalize the padded contraction dims of the two backings (quantum
    mismatch between operands built under different meshes/paddings)."""
    inner_a = ad.shape[0] if transpose_a else ad.shape[1]
    inner_b = bd.shape[1] if transpose_b else bd.shape[0]
    if inner_a != inner_b:
        pad_to = max(inner_a, inner_b)
        if transpose_a:
            ad = _grow(ad, (pad_to, ad.shape[1]))
        else:
            ad = _grow(ad, (ad.shape[0], pad_to))
        if transpose_b:
            bd = _grow(bd, (bd.shape[0], pad_to))
        else:
            bd = _grow(bd, (pad_to, bd.shape[1]))
    return ad, bd


def _grow(data, shape):
    """Host-level grow to a larger padded canvas: the traced zero-fill
    core (:func:`grow_canvas`) + the canonical resharding device_put."""
    return jax.device_put(grow_canvas(data, shape), _mesh.data_sharding())


def grow_canvas(data, shape, valid=None):
    """THE shared pad/crop-helper core (traced): place ``data`` on a zero
    canvas of ``shape`` and — when ``valid`` = (rows, cols) is given —
    re-zero everything outside the valid region.  Every blocked-linalg
    kernel that grows an operand (blocked QR panels, block-Jacobi column
    blocks, matmul quantum repads) routes through here so a padded tail
    can never enter a reduced-precision accumulation as garbage: the
    canvas is zero by construction and zero is exact in every policy
    dtype (pinned by tests/test_precision.py)."""
    grown = data
    if tuple(data.shape) != tuple(shape):
        canvas = jnp.zeros(shape, data.dtype)
        grown = lax.dynamic_update_slice(
            canvas, data[: shape[0], : shape[1]], (0, 0))
    if valid is not None:
        r = lax.broadcasted_iota(jnp.int32, grown.shape, 0) < valid[0]
        c = lax.broadcasted_iota(jnp.int32, grown.shape, 1) < valid[1]
        grown = jnp.where(r & c, grown, jnp.zeros((), grown.dtype))
    return grown


def _crop_or_keep(padded, logical_shape):
    """The dot of two quantum-padded operands is already quantum-padded for
    the output logical shape (padded dims are quantum multiples ≥ logical)."""
    return padded


# ---------------------------------------------------------------------------
# kron
# ---------------------------------------------------------------------------

def kron(a: Array, b: Array, block_size=None) -> Array:
    """Kronecker product (reference: dislib.math.kron — one scaled-copy task
    per (block of a) × (block of b)).

    Computed directly into the sharded output via the index lattice
    ``out[r, c] = a[r//mb, c//nb] · b[r%mb, c%nb]`` — row/column gathers of
    the (small) operands, never the 4-D broadcast intermediate ``jnp.kron``
    builds, so per-device peak memory is O(output shard + operands)."""
    from dislib_tpu.data.array import _padded_shape
    (ma, na), (mb, nb) = a.shape, b.shape
    shape = (ma * mb, na * nb)
    pshape = _padded_shape(shape, _mesh.pad_quantum())
    out = _kron_kernel(a._data, b._data, (a.shape, b.shape), pshape)
    return Array(out, shape, reg_shape=block_size)


@partial(_pjit, static_argnames=("shapes", "pshape"), name="kron")
def _kron_kernel(ap, bp, shapes, pshape):
    (ma, na), (mb, nb) = shapes
    av, bv = ap[:ma, :na], bp[:mb, :nb]
    ri = lax.iota(jnp.int32, pshape[0])
    ci = lax.iota(jnp.int32, pshape[1])
    # clip keeps the pad-region gathers in bounds; the mask re-zeroes them
    a_exp = av[jnp.clip(ri // mb, 0, ma - 1)][:, jnp.clip(ci // nb, 0, na - 1)]
    b_til = bv[ri % mb][:, ci % nb]
    valid = (ri < ma * mb)[:, None] & (ci < na * nb)[None, :]
    out = jnp.where(valid, a_exp * b_til, 0.0)
    return lax.with_sharding_constraint(out, _mesh.data_sharding())


# ---------------------------------------------------------------------------
# svd — one-sided block-Jacobi, the reference's algorithm, device-resident
# ---------------------------------------------------------------------------

# per-policy convergence floors (the polar tol-floor precedent): the
# off-diagonal measure can't fall below the pair-update GEMMs' own
# rounding — under the bfloat16 policy that is ~2^-9 per operand, so
# demanding 1e-6 would burn max_sweeps in full every call
_SVD_EPS_FLOOR = {"float32": 1e-6, "bfloat16": 5e-3}


def svd(a: Array, compute_uv: bool = True, sort: bool = True,
        copy: bool = True, eps: float = 1e-6, max_sweeps: int = 30,
        precision=None):
    """One-sided Jacobi SVD (reference: dislib.math.svd — round-robin
    rotations of column pairs until all pairs are ε-orthogonal; the
    reference pairs column BLOCKS, SURVEY §3.2 svd row).

    Returns (U, S, V) ds-arrays with S of shape (1, n) — or S alone when
    ``compute_uv=False``.  The sweep loop runs on device in a while_loop.
    Two tiers, both batching every disjoint pair of a round-robin round:

    - n < 2·64: scalar column pairs, one Givens rotation per pair.
    - n ≥ 2·64: the reference's COLUMN-BLOCK pairing — per pair, one
      batched tall QR, a small SVD of R, and a tall
      (m, 2b) GEMM apply.  A sweep is n/b−1 rounds instead of n−1, and
      every round is MXU-shaped GEMM work instead of skinny
      gather/scatter — the block structure is exactly why the reference
      chose block pairs too.  For rank-deficient input the null-space
      columns of V (σ = 0) are implementation-defined on this tier;
      singular vectors for σ > 0 are exact.

    ``eps`` defaults to 1e-6 (not the reference's 1e-9, which presumes
    float64 blocks): the kernels run float32, whose pairwise-orthogonality
    floor is ~5e-8, so tighter requests are unreachable and are clamped to
    1e-6 with a warning.

    ``precision`` — the mixed-precision policy (None → the
    ``DSLIB_MATMUL_PRECISION`` default).  Scope follows the round-10
    policy contract: the FLOP-dominant block-tier PAIR-UPDATE GEMMs (the
    tall ``Q_w·U_rΣ`` apply and the ``V·V_r`` rotation apply) contract at
    the policy's compute dtype with f32 accumulation; the pair QR, the
    small (2b, 2b) SVD and the convergence Gram stay pinned float32
    (factorisation interiors).  The scalar tier (n < 128) is always
    float32 — below the block threshold there is no FLOP-dominant GEMM to
    round.  Under ``bfloat16`` the convergence tolerance has a per-policy
    floor (``5e-3``, the ``polar`` precedent) and the documented error
    bounds are ``precision.ERROR_BOUNDS[("svd_values"|"svd_resid",
    policy)]``.
    """
    policy = px.resolve(precision)
    m, n = a.shape
    # Operate on the full padded backing: pad rows/cols are zero under the
    # pad-and-mask invariant, so they contribute nothing to column dot
    # products and their rotations are exact no-ops (off-diagonal = 0) —
    # the input stays row-sharded on the mesh instead of being gathered by
    # an eager logical slice (round-2 fix for the replicated-SVD ceiling).
    # the kernels run float32: an eps below f32's pairwise-orthogonality
    # floor (~5e-8 observed) is unreachable and would burn max_sweeps in
    # full every call — clamp to a floor a converged f32 sweep does reach
    if float(eps) < 1e-6:
        import warnings
        warnings.warn(
            f"svd: eps={eps:g} is below the float32 convergence floor; "
            "clamping to 1e-6 (the 1e-9-style defaults presume float64 "
            "blocks)", RuntimeWarning, stacklevel=2)
    eps = max(float(eps), 1e-6)
    # shared pad/crop helper at ingest: re-assert the zero-pad invariant
    # before ANY rotation math — a garbage padded tail would otherwise mix
    # into valid columns through the pair rotations (and at reduced
    # precision a large tail swamps small singular values outright);
    # pinned by tests/test_precision.py::test_poisoned_pad_tail_cannot_leak
    av = grow_canvas(px.f32(a._data), a._data.shape, valid=(m, n))
    # the block tier factors (m, 2b) pair panels with a reduced QR — for
    # m < 2b that QR is rank-limited and the pair update shapes collapse
    # (found by the round-10 precision suite at (80, 130)); short-wide
    # inputs take the scalar tier, which has no such constraint
    if av.shape[1] >= 2 * _JACOBI_BLOCK and av.shape[0] >= 2 * _JACOBI_BLOCK:
        # per-policy convergence floor applies HERE, where the policy
        # rounds the pair updates (silently: the default eps=1e-6 under
        # bfloat16 means "as converged as bf16 pair updates get"); the
        # scalar tier below ignores the policy, so it keeps the f32 floor
        eps = max(eps, _SVD_EPS_FLOOR.get(policy.name, 1e-6))
        u, s, v = _jacobi_svd_block(av, n, sort,
                                    eps, max_sweeps, policy)
    else:
        u, s, v = _jacobi_svd(av, n, sort, eps,
                              max_sweeps)
    s_arr = Array._from_logical(s[:n].reshape(1, -1))
    if not compute_uv:
        return s_arr
    u_arr = Array._from_logical_padded(u, (m, n), None, False)
    # v already satisfies the (n, n) pad-and-mask invariant: pad rows/cols
    # zeroed in-kernel and the stable sort keeps valid columns first
    v_arr = Array._from_logical_padded(v, (n, n), None, False)
    return (u_arr, s_arr, v_arr)


@partial(_pjit, static_argnames=("n_valid", "sort", "max_sweeps"),
         name="jacobi_svd")
@precise
def _jacobi_svd(a, n_valid, sort, eps, max_sweeps):
    m, n = a.shape
    # round-robin pairings: n-1 rounds, each pairing all columns once
    pairs = _round_robin_pairs(n)
    shard = _mesh.data_sharding()

    def rotate_round(carry, pr):
        u, v = carry
        i, j = pr[:, 0], pr[:, 1]
        ui, uj = u[:, i], u[:, j]
        aii = jnp.sum(ui * ui, axis=0)
        ajj = jnp.sum(uj * uj, axis=0)
        aij = jnp.sum(ui * uj, axis=0)
        # Jacobi rotation angle per pair
        tau = (ajj - aii) / (2.0 * jnp.where(jnp.abs(aij) < 1e-30, 1e-30, aij))
        t = jnp.sign(tau) / (jnp.abs(tau) + jnp.sqrt(1.0 + tau * tau))
        c = 1.0 / jnp.sqrt(1.0 + t * t)
        s_ = c * t
        # skip near-orthogonal pairs
        off = jnp.abs(aij) / jnp.sqrt(jnp.maximum(aii * ajj, 1e-30))
        c = jnp.where(off < eps, 1.0, c)
        s_ = jnp.where(off < eps, 0.0, s_)
        new_ui = c * ui - s_ * uj
        new_uj = s_ * ui + c * uj
        u = u.at[:, i].set(new_ui).at[:, j].set(new_uj)
        vi, vj = v[:, i], v[:, j]
        v = v.at[:, i].set(c * vi - s_ * vj).at[:, j].set(s_ * vi + c * vj)
        return (u, v), jnp.max(off)

    def sweep(carry):
        u, v, _, it = carry
        (u, v), offs = lax.scan(rotate_round, (u, v), pairs)
        # keep U row-sharded across sweeps (rotations are column-local, so
        # the mesh's row axis carries through each round; without the
        # constraint SPMD may gather the carry after the column scatters)
        u = lax.with_sharding_constraint(u, shard)
        return u, v, jnp.max(offs), it + 1

    def cond(carry):
        _, _, off, it = carry
        return (off > eps) & (it < max_sweeps)

    u0 = a
    v0 = jnp.eye(n, dtype=a.dtype)
    u, v, _, _ = lax.while_loop(cond, sweep, (u0, v0, jnp.asarray(jnp.inf), 0))
    s = jnp.linalg.norm(u, axis=0)
    u = u / jnp.where(s < 1e-30, 1.0, s)[None, :]
    # re-zero the pad block: rotations keep pad columns exactly zero in U,
    # but V's pad diagonal starts at 1 (eye) and must not leak into the
    # pad-and-mask invariant of the returned arrays
    col_ok = lax.broadcasted_iota(jnp.int32, (n,), 0) < n_valid
    s = jnp.where(col_ok, s, 0.0)
    u = u * col_ok[None, :].astype(u.dtype)
    v = v * (col_ok[None, :] & col_ok[:, None]).astype(v.dtype)
    if sort:
        order = jnp.argsort(-s, stable=True)   # pad zeros stay behind valid
        s = s[order]
        u = u[:, order]
        v = v[:, order]
    return u, s, v


_JACOBI_BLOCK = 64


@partial(_pjit, static_argnames=("n_valid", "sort", "max_sweeps", "policy"),
         name="jacobi_svd_block")
@precise
def _jacobi_svd_block(a, n_valid, sort, eps, max_sweeps, policy=px.FLOAT32):
    """One-sided BLOCK Jacobi: round-robin over column blocks of width b.

    Per disjoint block pair (I, J), batched over the round's pairs:
    W = [U_I | U_J] is factored W = Q_w R (one batched tall QR), the
    small R gets a batched SVD R = U_r Σ V_rᵀ, and the pair updates are
    U_pair ← Q_w U_r Σ (tall GEMM) and V_pair ← V_pair V_r.  V_r is
    orthogonal, so this is a valid one-sided Jacobi step, and — unlike
    the Gram+eigh formulation — the new columns are orthogonal to
    machine precision INDEPENDENT of the pair's conditioning (a Gram
    eigh's residual scales with λmax, wrecking small-σ columns; R's SVD
    is σ-relative).  Convergence follows the same cyclic-Jacobi argument
    as the scalar tier, measured on G = RᵀR.  Zero (padding) columns
    stay exactly zero (σ = 0 scales them out); V starts with pad columns
    zeroed (not identity) so degenerate null-space shuffling moves only
    zeros.  Column order migrates across rounds (each pair sorts by σ);
    the final global sort restores it, and positions ≥ n_valid are
    re-masked after the sort.
    """
    m, n_in = a.shape
    b = _JACOBI_BLOCK
    nb = -(-n_in // b)
    n = nb * b
    # shared pad/crop helper: the grown column tail is zero BY CONSTRUCTION
    # and columns ≥ n_valid are re-zeroed — a padded tail can never enter
    # the rotation Grams as garbage (tests/test_precision.py pins this)
    u0 = grow_canvas(a, (m, n), valid=(m, n_valid))
    col_ok0 = lax.broadcasted_iota(jnp.int32, (n,), 0) < n_valid
    v0 = jnp.eye(n, dtype=a.dtype) * col_ok0[None, :].astype(a.dtype)
    pairs = _round_robin_pairs(nb)            # (rounds, width, 2) block ids
    shard = _mesh.data_sharding()

    def rotate_round(carry, pr):
        u, v = carry
        i, j = pr[:, 0], pr[:, 1]                                # (w,)
        ur = u.reshape(m, nb, b)
        vr = v.reshape(n, nb, b)
        w_u = jnp.concatenate([ur[:, i], ur[:, j]], axis=-1)     # (m, w, 2b)
        qw, r = jnp.linalg.qr(w_u.transpose(1, 0, 2),
                              mode="reduced")      # (w, m, 2b), (w, 2b, 2b)
        g = jnp.einsum("wki,wkj->wij", r, r)       # G = RᵀR, small
        d = jnp.diagonal(g, axis1=1, axis2=2)
        # clamp the PRODUCT, not the factors: clamped factors of 1e-30
        # multiply to exactly 0 in f32 (underflow) and 0/0 = NaN — a NaN
        # off makes `off > eps` false and silently ends the sweep loop
        # after one iteration (the scalar tier's formula, same reason)
        denom = jnp.sqrt(jnp.maximum(d[:, :, None] * d[:, None, :], 1e-30))
        off_d = jnp.where(jnp.eye(2 * b, dtype=bool)[None],
                          0.0, jnp.abs(g) / denom)
        u_r, s_r, vh = jnp.linalg.svd(r)           # batched (2b, 2b) SVD
        # the two FLOP-dominant pair-update GEMMs follow the precision
        # policy (bf16-compute / f32-accumulate when opted in); the QR,
        # Gram and small SVD above stay pinned f32 — rounding a
        # factorisation interior buys no FLOPs and costs stability
        u_new = px.peinsum("wmi,wij->mwj", qw, u_r * s_r[:, None, :],
                           policy)
        w_v = jnp.concatenate([vr[:, i], vr[:, j]], axis=-1)
        v_new = px.peinsum("nwi,wji->nwj", w_v, vh, policy)      # V · V_r
        # a duplicated (padding) pair in a round recomputes the identical
        # q from the identical pre-round blocks — the duplicate .set
        # writes identical values (idempotent), as in the scalar tier
        u = ur.at[:, i].set(u_new[..., :b]).at[:, j].set(u_new[..., b:]) \
            .reshape(m, n)
        v = vr.at[:, i].set(v_new[..., :b]).at[:, j].set(v_new[..., b:]) \
            .reshape(n, n)
        return (u, v), jnp.max(off_d)

    def sweep(carry):
        u, v, _, it = carry
        (u, v), offs = lax.scan(rotate_round, (u, v), pairs)
        u = lax.with_sharding_constraint(u, shard)
        return u, v, jnp.max(offs), it + 1

    def cond(carry):
        _, _, off, it = carry
        return (off > eps) & (it < max_sweeps)

    u, v, _, _ = lax.while_loop(cond, sweep,
                                (u0, v0, jnp.asarray(jnp.inf), 0))
    s = jnp.linalg.norm(u, axis=0)
    u = u / jnp.where(s < 1e-30, 1.0, s)[None, :]
    if sort:
        order = jnp.argsort(-s, stable=True)
        s = s[order]
        u = u[:, order]
        v = v[:, order]
    # post-sort positional mask: σ>0 columns sort into [0, rank); anything
    # at positions ≥ n_valid is padding or null space — zero it to restore
    # the pad-and-mask invariant of the returned canvases
    keep = lax.broadcasted_iota(jnp.int32, (n,), 0) < n_valid
    s = jnp.where(keep, s, 0.0)
    u = u * keep[None, :].astype(u.dtype)
    v = v * (keep[None, :] & keep[:, None]).astype(v.dtype)
    return u[:, :n_in], s[:n_in], v[:n_in, :n_in]


def _round_robin_pairs(n):
    """Static round-robin schedule: (n-1) rounds × (n//2) disjoint pairs."""
    import numpy as np
    m = n if n % 2 == 0 else n + 1
    idx = list(range(m))
    rounds = []
    for _ in range(m - 1):
        pr = [(idx[k], idx[m - 1 - k]) for k in range(m // 2)]
        pr = [(min(i, j), max(i, j)) for i, j in pr if i < n and j < n]
        rounds.append(pr)
        idx = [idx[0]] + [idx[-1]] + idx[1:-1]
    width = max(len(r) for r in rounds)
    # Pad short rounds by repeating their last pair.  Safe because
    # rotate_round gathers all pair columns from the PRE-round matrix and
    # scatters with .set semantics: both copies of a duplicated pair compute
    # the identical rotation from identical inputs and write identical
    # values, so the duplicate write is idempotent (it does NOT rotate
    # twice).
    padded = []
    for r in rounds:
        while len(r) < width:
            r = r + [r[-1]]
        padded.append(r)
    return jnp.asarray(np.array(padded, dtype=np.int32))
