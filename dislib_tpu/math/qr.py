"""QR decomposition (reference: `dislib/math/qr` — blocked Householder with
`_little_qr` per diagonal block and `_multiply_single_block` trailing updates;
SURVEY.md §3.2 / §4.4).

TPU-native redesign: the reference's task-per-block elimination order exists
because each block lives on a different worker.  On TPU the whole matrix is
one sharded array, so:

- tall-skinny inputs (the shape QR is actually hot for in dislib workloads —
  tsQR is BASELINE config 3) route to :func:`dislib_tpu.decomposition.tsqr`'s
  shard_map tree;
- the general case lowers to XLA's native Householder QR over the global
  array (`jnp.linalg.qr`), which XLA blocks and tiles for the MXU itself —
  re-expressing the reference's hand-written block elimination would
  hand-schedule what the compiler already does (SURVEY §8 design stance).

Modes follow the reference: 'full' (Q m×m, R m×n), 'economic' (Q m×n, R n×n),
'r' (R only).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from dislib_tpu.data.array import Array
from dislib_tpu.ops.base import precise


@partial(jax.jit, static_argnames=("mode", "shape"))
@precise
def _qr_kernel(a, mode, shape):
    return jnp.linalg.qr(a, mode=mode)


def qr(a: Array, mode: str = "full", overwrite_a: bool = False):
    """QR factorisation of a ds-array.

    mode='full':     returns (Q, R) with Q (m, m), R (m, n)
    mode='economic': returns (Q, R) with Q (m, k), R (k, n), k=min(m,n)
    mode='r':        returns R (k, n)
    """
    if mode not in ("full", "economic", "r"):
        raise ValueError(f"unsupported mode {mode!r}")
    m, n = a.shape
    av = a._data[:m, :n].astype(jnp.float32)
    if mode == "full":
        q, r = _qr_kernel(av, "complete", (m, n))
        return Array._from_logical(q), Array._from_logical(r)
    q, r = _qr_kernel(av, "reduced", (m, n))
    if mode == "r":
        return Array._from_logical(r)
    return Array._from_logical(q), Array._from_logical(r)
