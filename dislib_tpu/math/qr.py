"""QR decomposition (reference: `dislib/math/qr` — blocked Householder with
`_little_qr` per diagonal block and `_multiply_single_block` trailing updates;
SURVEY.md §3.2 / §4.4).

TPU-native redesign — a distributed blocked factorisation, not a gather:

- tall-skinny inputs (n ≤ panel width) route to
  :func:`dislib_tpu.decomposition.tsqr`'s shard_map tree (BASELINE config 3);
- wider economic/r factorisations run a **panel loop**: each panel is
  tsQR-factored in a `shard_map` (local QR + one `all_gather(R)` over ICI),
  and the trailing matrix is updated with sharded GEMMs — the reference's
  `_little_qr` / `_multiply_single_block` elimination order, re-expressed as
  right-looking block Gram–Schmidt with a re-orthogonalisation pass
  ("twice is enough") for stability.  The full operand is NEVER gathered:
  every step touches row-sharded (m, b) panels and small replicated (b, n)
  coefficient blocks.  All panel steps share ONE compiled program — the
  panel offset is a traced `dynamic_slice` index inside a `lax.fori_loop`,
  and the accumulated-Q buffer is full width with not-yet-computed columns
  held at zero so shapes never change.
- mode='full' (square Q) at blocked sizes runs DISTRIBUTED too: economic
  blocked QR gives Q₁ (m, n); the orthonormal complement Q₂ (m, m−n) comes
  from a random Gaussian block projected against Q₁ twice ("twice is
  enough") and then blocked-QR-factored — Q = [Q₁ | Q₂] stays row-sharded
  throughout, and the random completion is deterministic (fixed seed).
  Small/short-wide inputs delegate to XLA's native Householder QR over the
  global array — a replicated fallback, appropriate at sizes where that is
  cheaper than two panel sweeps.

Modes follow the reference: 'full' (Q m×m, R m×n), 'economic' (Q m×n, R n×n),
'r' (R only).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from dislib_tpu.data.array import Array
from dislib_tpu.decomposition.tsqr import (_tsqr_shardmap,
                                           _use_cholqr)
from dislib_tpu.math.base import grow_canvas
from dislib_tpu.ops import precision as px
from dislib_tpu.ops.base import precise
from dislib_tpu.parallel import mesh as _mesh

# panel width for the blocked path (module-level so tests can shrink it)
_PANEL = 256


@partial(jax.jit, static_argnames=("mode", "shape"))
@precise
def _qr_kernel(a, mode, shape):
    return jnp.linalg.qr(a, mode=mode)


def qr(a: Array, mode: str = "full", overwrite_a: bool = False,
       precision=None):
    """QR factorisation of a ds-array.

    mode='full':     returns (Q, R) with Q (m, m), R (m, n)
    mode='economic': returns (Q, R) with Q (m, k), R (k, n), k=min(m,n)
    mode='r':        returns R (k, n)

    ``precision``: mixed-precision policy (None → the
    ``DSLIB_MATMUL_PRECISION`` default).  The policy governs the blocked
    path's FLOP-dominant GEMMs (re-orthogonalisation projections,
    trailing updates); panel factorisations stay float32 — error bounds
    in ``ops/precision.ERROR_BOUNDS``.  The small/short-wide fallback is
    a native f32 Householder QR and ignores the policy.
    """
    if mode not in ("full", "economic", "r"):
        raise ValueError(f"unsupported mode {mode!r}")
    policy = px.resolve(precision)
    m, n = a.shape
    mesh = _mesh.get_mesh()
    p = mesh.shape[_mesh.ROWS]
    mp = a._data.shape[0]
    blocked_ok = m >= n and n > _PANEL and mp // p >= _PANEL and mp % p == 0
    if mode in ("economic", "r") and blocked_ok:
        q_pad, r = _qr_blocked(a._data, (m, n), mesh, p, _PANEL,
                            cholqr=_use_cholqr(), policy=policy)
        if mode == "r":
            return Array._from_logical(r[:n, :n])
        return (Array._from_logical_padded(q_pad, (m, n), a._reg_shape),
                Array._from_logical(r[:n, :n]))
    if mode == "full" and blocked_ok and m - n > _PANEL:
        return _qr_full_distributed(a, m, n, mesh, p, policy)
    av = px.f32(a._data[:m, :n])
    if mode == "full":
        q, r = _qr_kernel(av, "complete", (m, n))
        return Array._from_logical(q), Array._from_logical(r)
    q, r = _qr_kernel(av, "reduced", (m, n))
    if mode == "r":
        return Array._from_logical(r)
    return Array._from_logical(q), Array._from_logical(r)


def _qr_full_distributed(a: Array, m, n, mesh, p, policy=px.FLOAT32):
    """mode='full' without gathering: Q₁ from the economic panel loop, then
    an orthonormal complement Q₂ from a deterministic random block projected
    against Q₁ (twice) and blocked-QR-factored.  Everything row-sharded; the
    only replicated object is the (n, n) R.  Rank-deficient A carries the
    same conditioning caveat as the economic path (Gram–Schmidt panels)."""
    q1, r = _qr_blocked(a._data, (m, n), mesh, p, _PANEL,
                            cholqr=_use_cholqr(), policy=policy)
    k = m - n
    g = _qr_complement_seed(q1, (m, n), k, mesh, policy)
    q2, _ = _qr_blocked(g, (m, k), mesh, p, _PANEL,
                         cholqr=_use_cholqr(), policy=policy)
    q_full = jnp.concatenate([q1[:, :n], q2[:, :k]], axis=1)[:m]
    r_full = jnp.zeros((m, n), jnp.float32).at[:n, :n].set(r[:n, :n])
    return (Array._from_logical(q_full, a._reg_shape),
            Array._from_logical(r_full))


@partial(jax.jit, static_argnames=("shape", "k", "mesh", "policy"))
@precise
def _qr_complement_seed(q1, shape, k, mesh, policy=px.FLOAT32):
    """Row-sharded (mp, k) Gaussian block orthogonal to q1's columns up to
    roundoff: two projection passes I − Q₁Q₁ᵀ ("twice is enough").  q1's
    padded columns (≥ n) are zero, so they drop out of the projections."""
    mp = q1.shape[0]
    m, _ = shape
    g = jax.random.normal(jax.random.PRNGKey(0), (mp, k), jnp.float32)
    row = lax.broadcasted_iota(jnp.int32, (mp, 1), 0)
    g = jnp.where(row < m, g, 0.0)
    g = lax.with_sharding_constraint(g, _mesh.row_sharding(mesh))
    for _ in range(2):
        g = g - px.pdot(q1, px.pdot(q1.T, g, policy), policy)
    return g


@partial(jax.jit, static_argnames=("shape", "mesh", "p", "panel",
                                   "cholqr", "policy"))
@precise
def _qr_blocked(ap, shape, mesh, p, panel, *, cholqr, policy=px.FLOAT32):
    """Right-looking blocked QR over the row-sharded padded operand.

    Invariants inside the loop (panel j, offset off = j·panel):
    - Q columns ≥ off are zero, so the re-orthogonalisation projection
      ``C = Qᵀ P`` is exact with fixed shapes;
    - T columns < off are spent (never read again); columns ≥ off hold the
      trailing matrix with all previous panels' updates applied.
    """
    m, n = shape
    b = panel
    n_panels = -(-n // b)
    n_pad = n_panels * b
    mp = ap.shape[0]
    # shared pad/crop helper (math/base.grow_canvas): the panel canvas is
    # zero-grown AND re-masked past the logical columns in one audited
    # place — the zero-panel algebra (and any reduced-precision
    # accumulation under the policy) can never see a garbage tail
    av = grow_canvas(ap, (mp, n_pad), valid=(mp, n))
    col = lax.broadcasted_iota(jnp.int32, (1, n_pad), 1)
    av = lax.with_sharding_constraint(av, _mesh.row_sharding(mesh))

    def step(j, carry):
        t, q, r = carry
        off = j * b
        p_blk = lax.dynamic_slice(t, (0, off), (mp, b))
        # re-orthogonalisation pass against accumulated Q (cols ≥ off
        # zero); the projections are the policy-routed GEMMs
        c = px.pdot(q.T, p_blk, policy)          # (n_pad, b), row-axis psum
        p_blk = p_blk - px.pdot(q, c, policy)
        r = lax.dynamic_update_slice(
            r, lax.dynamic_slice(r, (0, off), (n_pad, b)) + c, (0, off))
        # panel factorisation: shard-local QR + all_gather(R) over ICI
        qs, rs = _tsqr_shardmap(p_blk, mesh, p, cholqr=cholqr)  # (mp, b), (b, b)
        # trailing update as policy-routed sharded GEMMs:
        # G = Qsᵀ T, T -= Qs G (cols > off+b)
        g = px.pdot(qs.T, t, policy)             # (b, n_pad)
        trailing = col >= off + b
        g_trail = jnp.where(trailing, g, 0.0)
        t = t - px.pdot(qs, g_trail, policy)
        # R row block [off:off+b) = [Rs at panel cols | G on trailing cols]
        row_blk = lax.dynamic_update_slice(g_trail, rs, (0, off))
        r = lax.dynamic_update_slice(r, row_blk, (off, 0))
        q = lax.dynamic_update_slice(q, qs, (0, off))
        return t, q, r

    q0 = jnp.zeros((mp, n_pad), jnp.float32)
    q0 = lax.with_sharding_constraint(q0, _mesh.row_sharding(mesh))
    r0 = jnp.zeros((n_pad, n_pad), jnp.float32)
    _, q, r = lax.fori_loop(0, n_panels, step, (av, q0, r0))
    # fully-padded shards can leave garbage in Q's padded rows (local QR of a
    # zero block is implementation-defined); enforce the zero-row invariant
    row = lax.broadcasted_iota(jnp.int32, (mp, 1), 0)
    q = jnp.where(row < m, q, 0.0)
    q = jnp.where(col < n, q, 0.0)
    return q, r
