"""Fitted-model checkpointing (reference: `dislib/utils/saving.py` —
`save_model`/`load_model` with JSON or CBOR encodings of fitted estimators,
syncing all futures first; SURVEY.md §3.3 and §6 "Checkpoint / resume").

TPU-native: same semantics — save syncs device state to host (`collect()`)
and encodes hyperparameters + trailing-underscore fitted attributes.  No
pickle (portability, same stance as the reference's JSON/CBOR choice).
Formats: 'json' (reference parity), 'cbor' (reference parity — uses cbor2
when importable, else the in-tree RFC 8949 subset codec
`dislib_tpu.utils.cbor_lite`, byte-compatible for these payloads),
'npz' (compact binary, numpy-native).

Mid-fit checkpointing of iterative estimators (TPU preemption reality) lives
in `dislib_tpu.utils.checkpoint`.
"""

from __future__ import annotations

import base64
import importlib
import json
import struct
import zipfile

import numpy as np

from dislib_tpu.data.array import Array, array as _make_array

_ALLOWED_MODULES = ("dislib_tpu.",)


def _encode(obj):
    if isinstance(obj, Array):
        coll = obj.collect()
        import scipy.sparse as sp
        if sp.issparse(coll):
            coll = coll.toarray()
        return {"__dsarray__": _np_payload(coll), "block_size": list(obj._reg_shape)}
    if isinstance(obj, np.ndarray):
        return {"__ndarray__": _np_payload(obj)}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (list, tuple)):
        return {"__seq__": [_encode(o) for o in obj], "tuple": isinstance(obj, tuple)}
    if isinstance(obj, dict):
        return {"__dict__": {k: _encode(v) for k, v in obj.items()}}
    if hasattr(obj, "get_params") and hasattr(obj, "_fitted_attrs"):
        return {"__estimator__": _estimator_state(obj)}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    try:
        import jax
        if isinstance(obj, jax.Array):
            return {"__ndarray__": _np_payload(np.asarray(obj))}
    except Exception:
        pass
    raise TypeError(f"cannot serialise {type(obj).__name__}")


def _np_payload(a):
    a = np.ascontiguousarray(a)
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "data": base64.b64encode(a.tobytes()).decode("ascii")}


def _np_restore(p):
    a = np.frombuffer(base64.b64decode(p["data"]), dtype=np.dtype(p["dtype"]))
    return a.reshape(p["shape"]).copy()


def _decode(obj):
    if isinstance(obj, dict):
        if "__dsarray__" in obj:
            return _make_array(_np_restore(obj["__dsarray__"]),
                               block_size=tuple(obj["block_size"]))
        if "__ndarray__" in obj:
            return _np_restore(obj["__ndarray__"])
        if "__seq__" in obj:
            seq = [_decode(o) for o in obj["__seq__"]]
            return tuple(seq) if obj.get("tuple") else seq
        if "__dict__" in obj:
            return {k: _decode(v) for k, v in obj["__dict__"].items()}
        if "__estimator__" in obj:
            return _estimator_restore(obj["__estimator__"])
    return obj


def _estimator_state(model):
    cls = type(model)
    return {
        "module": cls.__module__,
        "cls": cls.__qualname__,
        "params": {k: _encode(v) for k, v in model.get_params().items()},
        "fitted": {k: _encode(v) for k, v in model._fitted_attrs().items()},
    }


def _estimator_restore(state):
    module = state["module"]
    if not module.startswith(_ALLOWED_MODULES):
        raise ValueError(f"refusing to load estimator from module {module!r}")
    cls = getattr(importlib.import_module(module), state["cls"])
    model = cls(**{k: _decode(v) for k, v in state["params"].items()})
    for k, v in state["fitted"].items():
        setattr(model, k, _decode(v))
    return model


def _cbor():
    """cbor2 when available (interop with reference-written files), else
    the in-tree RFC 8949 subset codec."""
    try:
        import cbor2
        return cbor2
    except ImportError:
        from dislib_tpu.utils import cbor_lite
        return cbor_lite


def save_model(model, filepath: str, overwrite: bool = True,
               save_format: str = "json") -> None:
    """Persist a fitted dislib_tpu estimator (reference: utils.saving.save_model)."""
    import os
    if os.path.exists(filepath) and not overwrite:
        raise FileExistsError(filepath)
    state = {"__estimator__": _estimator_state(model)}
    if save_format == "json":
        with open(filepath, "w") as f:
            json.dump(state, f)
    elif save_format == "cbor":
        with open(filepath, "wb") as f:
            _cbor().dump(state, f)
    elif save_format == "npz":
        flat = json.dumps(state).encode()
        # write through the open file handle: np.savez_compressed APPENDS
        # ".npz" to a bare path, silently saving `model` as `model.npz`
        # and breaking the load_model round trip for any other extension
        with open(filepath, "wb") as f:
            np.savez_compressed(
                f, state=np.frombuffer(flat, dtype=np.uint8))
    else:
        raise ValueError(f"unknown save_format {save_format!r}")


def load_model(filepath: str, load_format: str | None = None):
    """Load a model saved by :func:`save_model` (reference parity)."""
    if load_format is None:
        load_format = "json"
        if filepath.endswith(".cbor"):
            load_format = "cbor"
        elif filepath.endswith(".npz"):
            load_format = "npz"
    if load_format == "json":
        with open(filepath) as f:
            state = json.load(f)
    elif load_format == "cbor":
        with open(filepath, "rb") as f:
            try:
                state = _cbor().load(f)
            except (ValueError, struct.error, UnicodeDecodeError) as e:
                raise ValueError(
                    f"{filepath} is not a dislib_tpu cbor model (truncated "
                    f"or foreign file: {e})") from e
    elif load_format == "npz":
        # allow_pickle stays OFF explicitly: a model file must never be a
        # pickle-execution vector, and the payload is a plain uint8 buffer
        try:
            with np.load(filepath, allow_pickle=False) as z:
                raw = z["state"].tobytes()
        except (OSError, ValueError, KeyError, zipfile.BadZipFile) as e:
            raise ValueError(
                f"{filepath} is not a dislib_tpu npz model (truncated, "
                f"foreign, or pickled file: {e})") from e
        state = json.loads(raw.decode())
    else:
        raise ValueError(f"unknown load_format {load_format!r}")
    return _decode(state)
