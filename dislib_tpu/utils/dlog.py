"""`dslib.*` logging namespace (SURVEY.md §6 metrics/logging row: "Python
`logging` under `dslib.*` namespace with per-estimator `verbose`").

The reference leaves logging to the COMPSs runtime's log tree; here each
estimator logs fit summaries under ``dslib.<estimator>``.  ``verbose=True``
on an estimator attaches a stderr handler at INFO for its logger (idempotent)
so per-fit progress is visible without any logging config.
"""

from __future__ import annotations

import logging

_ROOT = "dslib"


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"{_ROOT}.{name}")


def verbose_logger(name: str, verbose: bool) -> logging.Logger:
    """Logger for an estimator fit; verbose=True ensures INFO is emitted."""
    log = get_logger(name)
    if verbose and not getattr(log, "_dslib_handler", False):
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter("%(name)s: %(message)s"))
        log.addHandler(h)
        log._dslib_handler = True
    if verbose:
        log.setLevel(logging.INFO)
    return log
