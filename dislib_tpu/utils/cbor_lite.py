"""Self-contained CBOR codec (RFC 8949 definite-length subset).

The reference's `save_model(..., save_format='cbor')` depends on the
`cbor2` package (`dislib/utils/saving.py`, SURVEY §3.3).  This environment
does not ship cbor2, so the format would be unusable; this module makes
'cbor' work everywhere.  `dislib_tpu.utils.saving` prefers cbor2 when it
is importable (byte-compatible interop with reference-written files) and
falls back to this codec otherwise.

Scope: exactly the types `saving._encode` emits — None, bool, int, float,
str, bytes, list/tuple, dict — with definite lengths, the encoding cbor2
itself produces for these values.  The decoder additionally accepts
half/single-precision floats and 64-bit length arguments so files written
by cbor2 elsewhere load here.  Indefinite-length items and tags are
rejected with a clear error rather than silently misread.
"""

from __future__ import annotations

import struct


def dumps(obj) -> bytes:
    out = bytearray()
    _enc(obj, out)
    return bytes(out)


def dump(obj, f) -> None:
    f.write(dumps(obj))


def loads(data: bytes):
    obj, off = _dec(memoryview(data), 0)
    if off != len(data):
        raise ValueError(f"trailing bytes after CBOR item ({len(data) - off})")
    return obj


def load(f):
    return loads(f.read())


# -- encoding ---------------------------------------------------------------

def _head(major: int, arg: int, out: bytearray) -> None:
    if arg < 24:
        out.append((major << 5) | arg)
    elif arg < 1 << 8:
        out.append((major << 5) | 24); out.append(arg)
    elif arg < 1 << 16:
        out.append((major << 5) | 25); out.extend(arg.to_bytes(2, "big"))
    elif arg < 1 << 32:
        out.append((major << 5) | 26); out.extend(arg.to_bytes(4, "big"))
    elif arg < 1 << 64:
        out.append((major << 5) | 27); out.extend(arg.to_bytes(8, "big"))
    else:
        raise OverflowError("integer exceeds 64-bit CBOR argument")


def _enc(obj, out: bytearray) -> None:
    if obj is False:
        out.append(0xF4)
    elif obj is True:
        out.append(0xF5)
    elif obj is None:
        out.append(0xF6)
    elif isinstance(obj, int):
        if obj >= 0:
            _head(0, obj, out)
        else:
            _head(1, -1 - obj, out)
    elif isinstance(obj, float):
        out.append(0xFB); out.extend(struct.pack(">d", obj))
    elif isinstance(obj, bytes):
        _head(2, len(obj), out); out.extend(obj)
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        _head(3, len(b), out); out.extend(b)
    elif isinstance(obj, (list, tuple)):
        _head(4, len(obj), out)
        for o in obj:
            _enc(o, out)
    elif isinstance(obj, dict):
        _head(5, len(obj), out)
        for k, v in obj.items():
            _enc(k, out); _enc(v, out)
    else:
        raise TypeError(f"cbor_lite cannot encode {type(obj).__name__}")


# -- decoding ---------------------------------------------------------------
#
# Every length/argument read is BOUNDS-CHECKED: a truncated file used to
# surface as an IndexError from `mv[off]`, or worse, a short `mv[off:off+n]`
# slice silently decoding to a wrong (smaller) length argument — the
# "length decode" failure class.  All damage now raises ValueError with a
# position, which `saving.load_model` wraps into a clear model-file error.

def _need(mv, off, n):
    if off + n > len(mv):
        raise ValueError(
            f"truncated CBOR: need {n} byte(s) at offset {off}, "
            f"have {len(mv) - off}")


def _arg(mv, off, info):
    if info < 24:
        return info, off
    if info == 24:
        _need(mv, off, 1)
        return mv[off], off + 1
    if info == 25:
        _need(mv, off, 2)
        return int.from_bytes(mv[off:off + 2], "big"), off + 2
    if info == 26:
        _need(mv, off, 4)
        return int.from_bytes(mv[off:off + 4], "big"), off + 4
    if info == 27:
        _need(mv, off, 8)
        return int.from_bytes(mv[off:off + 8], "big"), off + 8
    raise ValueError(f"unsupported CBOR additional info {info} "
                     "(indefinite lengths are out of scope)")


def _dec(mv, off):
    _need(mv, off, 1)
    ib = mv[off]; off += 1
    major, info = ib >> 5, ib & 0x1F
    if major == 0:
        return _arg(mv, off, info)
    if major == 1:
        n, off = _arg(mv, off, info)
        return -1 - n, off
    if major == 2:
        n, off = _arg(mv, off, info)
        _need(mv, off, n)
        return bytes(mv[off:off + n]), off + n
    if major == 3:
        n, off = _arg(mv, off, info)
        _need(mv, off, n)
        return bytes(mv[off:off + n]).decode("utf-8"), off + n
    if major == 4:
        n, off = _arg(mv, off, info)
        items = []
        for _ in range(n):
            o, off = _dec(mv, off)
            items.append(o)
        return items, off
    if major == 5:
        n, off = _arg(mv, off, info)
        d = {}
        for _ in range(n):
            k, off = _dec(mv, off)
            v, off = _dec(mv, off)
            d[k] = v
        return d, off
    if major == 7:
        if info == 20:
            return False, off
        if info == 21:
            return True, off
        if info in (22, 23):          # null / undefined
            return None, off
        if info == 25:
            _need(mv, off, 2)
            return float(struct.unpack(">e", mv[off:off + 2])[0]), off + 2
        if info == 26:
            _need(mv, off, 4)
            return float(struct.unpack(">f", mv[off:off + 4])[0]), off + 4
        if info == 27:
            _need(mv, off, 8)
            return float(struct.unpack(">d", mv[off:off + 8])[0]), off + 8
        raise ValueError(f"unsupported CBOR simple value {info}")
    raise ValueError(f"unsupported CBOR major type {major} (tags are out "
                     "of scope)")
