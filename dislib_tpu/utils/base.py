"""Global shuffle and train/test helpers (reference: `dislib/utils` —
`shuffle(x, y, random_state)` is a random global permutation via
partition-and-rebuild tasks; SURVEY.md §3.3).

TPU-native: a global permutation of a row-sharded array is an all-to-all over
shards.  We express it as a gather with a permuted index vector — XLA lowers
the cross-shard gather to its collective machinery (ppermute/all-to-all) —
rather than re-building the reference's partition/merge task pipeline.
"""

from __future__ import annotations

import numpy as np

from dislib_tpu.data.array import Array


def shuffle(x: Array, y: Array | None = None, random_state=None):
    """Randomly permute rows of ``x`` (and ``y`` with the same permutation)."""
    rng = random_state if isinstance(random_state, np.random.RandomState) \
        else np.random.RandomState(random_state)
    perm = rng.permutation(x.shape[0])
    xs = x[perm, :]
    if y is None:
        return xs
    if y.shape[0] != x.shape[0]:
        raise ValueError("x and y must have the same number of rows")
    return xs, y[perm, :]


def train_test_split(x: Array, y: Array | None = None, test_size: float = 0.25,
                     train_size: float | None = None, random_state=None):
    """Split rows into train/test ds-arrays (sklearn-style convenience)."""
    n = x.shape[0]
    n_test = int(round(n * test_size))
    n_train = n - n_test if train_size is None else int(round(n * train_size))
    rng = np.random.RandomState(random_state)
    perm = rng.permutation(n)
    tr, te = perm[:n_train], perm[n_train:n_train + n_test]
    if y is None:
        return x[tr, :], x[te, :]
    return x[tr, :], x[te, :], y[tr, :], y[te, :]
