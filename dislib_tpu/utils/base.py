"""Global shuffle and train/test helpers (reference: `dislib/utils` —
`shuffle(x, y, random_state)` is a random global permutation via
partition-and-rebuild tasks; SURVEY.md §3.3).

TPU-native: a global row permutation of a row-sharded array IS an
all-to-all over shards (SURVEY §3.7 "all-to-all reshuffle" row).  The
permutation is drawn on host (it is O(m) index bookkeeping, the same place
the reference plans its partition/rebuild tasks), routing is precomputed
per (source shard → destination shard) pair, and the data movement is ONE
`lax.all_to_all` over the mesh 'rows' axis inside a `shard_map`:

    per shard:  send[d] = local rows destined for shard d   (local gather)
    collective: recv = all_to_all(send)                     — ICI
    per shard:  out[dst slots] = recv                       (local scatter)

Per-device memory is O(shard + exchange buffers) — the operand is never
gathered onto one device, which the memory/HLO tests pin.  For a uniform
random permutation the (s, d) bucket sizes concentrate at m/p², so the
padded exchange buffer is ~1 shard with a small slack factor.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from dislib_tpu.data.array import Array
from dislib_tpu.parallel import mesh as _mesh


def shuffle(x: Array, y: Array | None = None, random_state=None):
    """Randomly permute rows of ``x`` (and ``y`` with the same permutation)."""
    rng = random_state if isinstance(random_state, np.random.RandomState) \
        else np.random.RandomState(random_state)
    perm = rng.permutation(x.shape[0])
    if y is not None and y.shape[0] != x.shape[0]:
        raise ValueError("x and y must have the same number of rows")
    xs, plan = _apply_perm(x, perm)
    if y is None:
        return xs
    # y has the same padded row count (one mesh quantum), so it reuses x's
    # routing plan instead of re-planning the identical exchange
    ys, _ = _apply_perm(y, perm, plan)
    return xs, ys


def _apply_perm(x: Array, perm: np.ndarray, plan=None):
    """Apply ``out[i] = x[perm[i]]`` via the exchange; returns (Array, plan)
    so a same-length companion array can reuse the routing plan.  Sparse
    arrays permute through their sparsity-preserving row indexing instead
    (no dense exchange buffers)."""
    from dislib_tpu.data.sparse import SparseArray
    if isinstance(x, SparseArray):
        return x[perm, :], plan
    mesh = _mesh.get_mesh()
    p = mesh.shape[_mesh.ROWS]
    m_loc = x._data.shape[0] // p
    if plan is None:
        send_idx, dst_idx = _routing(perm, m_loc, p)
        plan = (jnp.asarray(send_idx), jnp.asarray(dst_idx))
    out = _shuffle_exchange(x._data, plan[0], plan[1], mesh, p)
    return Array(out, x._shape, x._reg_shape, x._sparse), plan


def _routing(perm, m_loc, p):
    """Host-side routing plan for ``out[i] = x[perm[i]]`` on contiguous
    row shards of height ``m_loc``.

    Returns (send_idx, dst_idx), both (p, p, cap) int32:
    - ``send_idx[s, d, c]``: local row (within shard s) of the c-th row
      shard s sends to shard d; padding slots repeat row 0.
    - ``dst_idx[d, s, c]``: local output slot (within shard d) for the
      c-th row received from shard s; padding slots hold ``m_loc``
      (out of range → dropped by the scatter).
    """
    m = len(perm)
    i = np.arange(m)
    src = perm
    s_shard = src // m_loc
    d_shard = i // m_loc
    order = np.lexsort((i, d_shard, s_shard))   # group by (s, d), stable in i
    s_sorted, d_sorted = s_shard[order], d_shard[order]
    counts = np.zeros((p, p), np.int64)
    np.add.at(counts, (s_sorted, d_sorted), 1)
    cap = max(1, int(counts.max()))
    send_idx = np.zeros((p, p, cap), np.int32)
    dst_idx = np.full((p, p, cap), m_loc, np.int32)
    # slot index of each routed row within its (s, d) bucket
    flat = s_sorted * p + d_sorted
    bucket_sizes = np.bincount(flat, minlength=p * p)
    starts = np.concatenate([[0], np.cumsum(bucket_sizes)[:-1]])
    slot = np.arange(m) - starts[flat]
    send_idx[s_sorted, d_sorted, slot] = (src[order] % m_loc).astype(np.int32)
    dst_idx[d_sorted, s_sorted, slot] = (i[order] % m_loc).astype(np.int32)
    return send_idx, dst_idx


@partial(jax.jit, static_argnames=("mesh", "p"))
def _shuffle_exchange(xp, send_idx, dst_idx, mesh, p):
    m_loc = xp.shape[0] // p

    def shard_fn(x_s, send_s, dst_s):
        send = x_s[0][send_s[0]]                       # (p, cap, n) gather
        recv = lax.all_to_all(send, _mesh.ROWS, split_axis=0, concat_axis=0)
        n = x_s.shape[-1]
        cap = send_s.shape[-1]
        out = jnp.zeros((m_loc, n), x_s.dtype)
        out = out.at[dst_s[0].reshape(p * cap)].set(
            recv.reshape(p * cap, n), mode="drop")
        return out[None]

    out = jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(_mesh.ROWS, None), P(_mesh.ROWS), P(_mesh.ROWS)),
        out_specs=P(_mesh.ROWS, None),
        check_vma=True,
    )(xp.reshape(p, m_loc, -1), send_idx, dst_idx)
    # re-establish the canonical (rows, cols) layout: the exchange's
    # out_specs is row-only, which on a cols>1 mesh would leave the result
    # column-replicated until some later op reshards it (round-3 advisor)
    return lax.with_sharding_constraint(out.reshape(xp.shape),
                                        _mesh.data_sharding(mesh))


def train_test_split(x: Array, y: Array | None = None, test_size: float = 0.25,
                     train_size: float | None = None, random_state=None):
    """Split rows into train/test ds-arrays (sklearn-style convenience)."""
    n = x.shape[0]
    n_test = int(round(n * test_size))
    n_train = n - n_test if train_size is None else int(round(n * train_size))
    rng = np.random.RandomState(random_state)
    perm = rng.permutation(n)
    # permute once via the bounded all-to-all exchange, then take contiguous
    # row slices — identical values to fancy-gathering perm[:n_train] etc.,
    # without a full-size gather per split
    xs, plan = _apply_perm(x, perm)
    if y is None:
        return xs[:n_train, :], xs[n_train:n_train + n_test, :]
    if y.shape[0] != n:
        raise ValueError("x and y must have the same number of rows")
    ys, _ = _apply_perm(y, perm, plan)
    return (xs[:n_train, :], xs[n_train:n_train + n_test, :],
            ys[:n_train, :], ys[n_train:n_train + n_test, :])
