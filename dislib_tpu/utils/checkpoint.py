"""Mid-fit checkpointing (SURVEY.md §6 "Failure detection / elastic
recovery" + "Checkpoint / resume").

The reference's fault tolerance is runtime-level (COMPSs resubmits failed
tasks; `dislib/utils/saving.py` snapshots only *fitted* models).  On TPU a
chip failure kills the whole SPMD job, so mid-fit checkpointing of the
iteration state is first-class: iterative estimators (`KMeans`,
`GaussianMixture`, `ALS`, `CascadeSVM`) accept ``checkpoint=FitCheckpoint(path, every=k)``
and then run their device loop in k-iteration chunks, snapshotting the
host-readable iteration state (centers / responsibilities stats / factors +
iteration counter) after each chunk.  A re-run with the same checkpoint
resumes from the snapshot and produces the same result as an uninterrupted
fit (deterministic iterations) — asserted by the kill+resume fault-injection
test (`tests/test_checkpoint.py`).

Format: ``.npz`` written atomically (tmp file + rename), no pickle.
Crash consistency (round-6 robustness PR): every snapshot embeds a
checksum over its arrays, the last ``keep`` generations rotate
(``path`` newest, ``path.1`` previous, ...), and ``load()`` detects a
truncated/corrupt/foreign file and falls back to the newest good
generation instead of surfacing an opaque zipfile error — a kill
mid-write (or mid-rotation) never costs more than one generation.
"""

from __future__ import annotations

import os
import threading
import warnings
import zipfile
import zlib

import numpy as np

# npz entry holding the CRC-32 of every other entry; reserved key
_CRC_KEY = "_dslib_crc32"


class SnapshotCorrupt(ValueError):
    """A snapshot file that cannot be trusted: truncated/corrupt ``.npz``,
    checksum mismatch (bit corruption), or a foreign ``.npz`` with no
    integrity record."""


def _state_crc(arrs: dict) -> int:
    """CRC-32 over every entry's name, dtype, shape, and raw bytes, in
    key order — what `save` embeds and `load` verifies."""
    crc = 0
    for k in sorted(arrs):
        if k == _CRC_KEY:
            continue
        a = np.ascontiguousarray(arrs[k])
        for piece in (k.encode(), a.dtype.str.encode(),
                      np.asarray(a.shape, np.int64).tobytes()):
            crc = zlib.crc32(piece, crc)
        try:
            # zlib takes the array's buffer directly — no tobytes() copy
            # of what may be a multi-GB factor matrix
            crc = zlib.crc32(a, crc)
        except (TypeError, ValueError, BufferError):
            crc = zlib.crc32(a.tobytes(), crc)  # exotic dtypes
    return crc & 0xFFFFFFFF


def _fsync_dir(path: str) -> None:
    """Best-effort directory fsync so a rename survives power loss."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _load_verified(path: str) -> dict:
    """Read one generation, verifying npz integrity AND the embedded
    checksum; raises :class:`SnapshotCorrupt` on any damage.  A
    ``FileNotFoundError`` propagates UNWRAPPED: the file vanishing
    between the caller's ``exists()`` and the open here means a
    concurrent ``save`` is mid-rotation (hot-swap readers poll live
    checkpoints) — that is "look at the next generation", not
    corruption, and it must never reach the corrupt-file cleanup, which
    would otherwise ``os.remove`` the name a racing writer has just
    re-pointed at a brand-new good generation."""
    try:
        with np.load(path, allow_pickle=False) as z:
            state = {k: z[k] for k in z.files}
    except FileNotFoundError:
        raise
    except (zipfile.BadZipFile, EOFError, OSError, ValueError, KeyError) as e:
        raise SnapshotCorrupt(
            f"snapshot {path} is truncated or corrupt ({e})") from e
    crc = state.pop(_CRC_KEY, None)
    if crc is None:
        raise SnapshotCorrupt(
            f"snapshot {path} has no integrity record — foreign .npz or "
            "written by a pre-rotation library version")
    if int(np.asarray(crc).ravel()[0]) != _state_crc(state):
        raise SnapshotCorrupt(
            f"snapshot {path} failed its checksum — bit corruption on disk")
    return state


class _PendingSave:
    """Handle for one in-flight `save_async`; `wait()` blocks until the
    snapshot is on disk and re-raises any write-side failure."""

    def __init__(self):
        self._done = threading.Event()
        self._exc: BaseException | None = None

    def wait(self) -> None:
        self._done.wait()
        if self._exc is not None:
            raise self._exc


# One in-flight async write per PATH, across FitCheckpoint instances: a
# preemption-recovery re-run builds a FRESH FitCheckpoint on the same
# file, and its load() must not read around the PREVIOUS fit's still-
# in-flight save — the resumed stream reads the checkpoint twice
# (stream_state, then the loop's restore) and a write landing between
# the two makes them disagree, re-consuming a batch (review-found flaky
# resume).  flush() drains the registered write before any read; the
# owning instance still re-raises its own write failure.
_PENDING_BY_PATH: dict = {}
_PENDING_LOCK = threading.Lock()


class FitCheckpoint:
    """Snapshot/restore of in-flight fit state.

    Parameters
    ----------
    path : str — target ``.npz`` file (newest generation; older ones
        rotate to ``path.1``, ``path.2``, ...).
    every : int, default 10 — checkpoint every `every` iterations.
    keep : int, default 2 — generations retained; ``load()`` falls back
        to the newest generation that verifies.

    ``save`` blocks until the snapshot is on disk; checkpointed fit loops
    use :meth:`save_async` instead, which runs the SAME save (device→host
    resolution of any ``AsyncFetch`` values, checksum, atomic write,
    rotation) on a worker thread so it overlaps the next chunk's device
    compute.  At most one write is in flight per checkpoint — the next
    ``save_async`` (and ``load``/``delete``/:meth:`flush`) waits for it
    first, so generation rotation order and the crash-consistency
    guarantees are exactly those of the blocking path.
    """

    def __init__(self, path: str, every: int = 10, keep: int = 2):
        self.path = str(path)
        self.every = int(every)
        self.keep = int(keep)
        self._pending: _PendingSave | None = None
        self._pending_thread: threading.Thread | None = None
        if self.every < 1:
            raise ValueError("every must be >= 1")
        if self.keep < 1:
            raise ValueError("keep must be >= 1")

    def _gen_path(self, i: int) -> str:
        return self.path if i == 0 else f"{self.path}.{i}"

    def save_async(self, state: dict) -> _PendingSave:
        """Start :meth:`save` on a worker thread and return immediately.

        Waits for any previous in-flight save first (writes never
        reorder), then hands ``state`` — ndarrays, scalars, or
        ``AsyncFetch`` handles whose device→host copies are already in
        flight — to the worker.  A failed write surfaces at the next
        ``flush()``/``save_async()``/``load()``, i.e. still inside
        ``fit``."""
        self.flush()
        pending = _PendingSave()

        def run():
            try:
                self.save(state)
            except BaseException as e:  # noqa: BLE001 — re-raised at flush
                pending._exc = e
            finally:
                pending._done.set()

        worker = threading.Thread(target=run, name="dslib-snapshot",
                                  daemon=True)
        self._pending = pending
        self._pending_thread = worker
        with _PENDING_LOCK:
            _PENDING_BY_PATH[os.path.abspath(self.path)] = (worker, pending)
        worker.start()
        return pending

    def flush(self) -> None:
        """Block until the in-flight `save_async` (if any) is on disk;
        re-raises its failure.  Estimators call this at fit exit and
        before raising `Preempted`, so the snapshot-first contract holds
        with the write off the hot path.  A no-op on the snapshot worker
        itself (its `save` re-enters here and must not wait on its own
        completion).  Also waits out a write started by ANOTHER
        FitCheckpoint on the same path (the re-run-on-a-fresh-instance
        case) — without adopting its failure, which the owning instance
        re-raises at its own next flush."""
        if self._pending_thread is threading.current_thread():
            return
        with _PENDING_LOCK:
            entry = _PENDING_BY_PATH.pop(os.path.abspath(self.path), None)
        if entry is not None:
            thread, foreign = entry
            if thread is not threading.current_thread() \
                    and thread is not self._pending_thread:
                foreign._done.wait()
        pending, self._pending = self._pending, None
        self._pending_thread = None
        if pending is not None:
            pending.wait()

    def save(self, state: dict) -> None:
        """Atomically persist a dict of ndarrays/scalars, embedding a
        checksum and rotating the previous generations.

        A unique tmp file (mkstemp) in the target directory keeps concurrent
        fits sharing a path from clobbering each other's staging file, and
        the fsync-before-replace ensures the rename never lands ahead of the
        data on power loss.  Rotation shifts oldest-first, so a crash
        between renames leaves every file a complete snapshot of SOME
        generation — `load()` takes the newest that verifies."""
        import tempfile
        # mixing the blocking and async APIs on one checkpoint must not
        # race the rotation chain: wait out any in-flight async write
        # first (no-op when this call IS the async worker's)
        self.flush()
        from dislib_tpu.runtime.elastic import AsyncFetch
        arrs = {k: np.asarray(v.result() if isinstance(v, AsyncFetch) else v)
                for k, v in state.items()}
        if _CRC_KEY in arrs:
            raise ValueError(f"{_CRC_KEY!r} is a reserved snapshot key")
        arrs[_CRC_KEY] = np.asarray([_state_crc(arrs)], np.uint32)
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        fd, tmp = tempfile.mkstemp(suffix=".npz", dir=d)
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrs)
                f.flush()
                os.fsync(f.fileno())
            for i in range(self.keep - 1, 0, -1):
                src = self._gen_path(i - 1)
                if os.path.exists(src):
                    os.replace(src, self._gen_path(i))
            os.replace(tmp, self.path)
            _fsync_dir(d)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise

    def load(self) -> dict | None:
        """Return the newest snapshot generation that verifies, or None if
        no generation exists at all.  A corrupt/truncated/foreign newest
        file falls back (with a warning) to the previous generation;
        :class:`SnapshotCorrupt` raises only when EVERY generation on disk
        is damaged."""
        self.flush()                    # never read around an in-flight write
        seen = 0
        first_err: SnapshotCorrupt | None = None
        bad: list[tuple[str, tuple]] = []
        for i in range(self.keep):
            p = self._gen_path(i)
            if not os.path.exists(p):
                continue
            try:
                read_stat = os.stat(p)
                state = _load_verified(p)
            except FileNotFoundError:
                # vanished between exists() and open(): a concurrent
                # save's rotation is in flight (hot-swap reader on a live
                # checkpoint).  Not corruption and not "seen" — the next
                # generation (or the next poll) holds a complete file.
                continue
            except SnapshotCorrupt as e:
                seen += 1
                if first_err is None:
                    first_err = e
                bad.append((p, (read_stat.st_ino, read_stat.st_mtime_ns)))
                continue
            seen += 1
            if first_err is not None:
                warnings.warn(
                    f"checkpoint {self.path}: newest snapshot unusable "
                    f"({first_err}); falling back to generation {i}",
                    RuntimeWarning, stacklevel=2)
                # drop the corrupt newer generation(s) NOW: otherwise the
                # next save() would rotate a known-corrupt file over this
                # good one, and a crash mid-save would then leave nothing
                # usable — exactly the >1-generation loss save() promises
                # never to cause.  Guard: only remove the exact inode we
                # read as corrupt — a racing writer may have re-pointed
                # the name at a brand-new good generation since.
                for b, (ino, mt) in bad:
                    try:
                        st = os.stat(b)
                        if (st.st_ino, st.st_mtime_ns) == (ino, mt):
                            os.remove(b)
                    except OSError:
                        pass
            return state
        if seen == 0:
            return None
        raise SnapshotCorrupt(
            f"checkpoint {self.path}: all {seen} snapshot generation(s) are "
            "corrupt, truncated, or foreign — delete the file(s) to restart "
            "the fit from scratch") from first_err

    def delete(self) -> None:
        self.flush()
        for i in range(self.keep):
            p = self._gen_path(i)
            if os.path.exists(p):
                os.remove(p)


def data_digest(xp, stats=None):
    """Order-sensitive float64 digest of a (padded) device matrix — plain
    and index-weighted sums, so a row permutation changes it.  Pad rows are
    zero under the pad-and-mask invariant, so padded sums equal logical
    sums.  Best-effort (a tiny relative perturbation at very large m can
    evade a sum digest); NaN digests never match → NaN data fails closed.
    ``stats`` (host per-row stats, e.g. tree label encodings) contributes
    the same two sums when given.  The digest array leads with a format
    version so a snapshot written under an older formula fails validation
    with an accurate message instead of blaming the user's data."""
    total, wsum = digest_sums(xp)
    extras = []
    if stats is not None:
        extras = [float(np.sum(stats)),
                  float(np.arange(stats.shape[0]) @ np.sum(stats, axis=1))]
    return versioned_digest(total, wsum, *extras)


def versioned_digest(*vals):
    """Assemble a digest array in the shared version-led layout
    ``[_DIGEST_VERSION, *vals]`` — the ONE place that owns the format, so
    estimators composing their own digest terms (e.g. CSVM's x+y sums)
    cannot drift from it."""
    return np.asarray([_DIGEST_VERSION, *vals], np.float64)


# v2: index weights split into high/low f32 parts (2026-08-01).  v1 (no
# version element) used a single f32 iota, which collides adjacent indices
# above ~2^24 rows.
_DIGEST_VERSION = 2.0

_digest_kernel = None  # module-level so repeat fits hit the jit cache


def digest_sums(xp):
    """``(plain sum, index-weighted sum)`` of a device matrix as host
    floats — the shared order-sensitive reduction for checkpoint digests
    (also used directly by estimators that build composite digests, e.g.
    CSVM).  The index weights are split as i = 4096*hi + lo in one fused
    on-device program: a single f32 iota collides adjacent indices above
    ~2^24 rows, silently weakening the documented permutation
    sensitivity; each part stays exactly representable (lo < 4096,
    hi < m/4096).  Built with on-device iota (no O(m) host buffers or
    transfers); the partial sums recombine in float64 on host (f64 is
    unavailable on device without x64 mode)."""
    import jax
    import jax.numpy as jnp
    global _digest_kernel
    if _digest_kernel is None:
        @jax.jit
        def sums(x):
            r = jnp.arange(x.shape[0], dtype=jnp.int32)
            hi = (r // 4096).astype(jnp.float32)
            lo = (r % 4096).astype(jnp.float32)
            return (jnp.sum(x), jnp.einsum("ij,i->", x, hi),
                    jnp.einsum("ij,i->", x, lo))
        _digest_kernel = sums
    total, shi, slo = (float(v) for v in jax.device_get(_digest_kernel(xp)))
    return total, 4096.0 * shi + slo


def validate_snapshot(snap, fp, digest):
    """Refuse a snapshot whose fingerprint/digest doesn't match this fit —
    shared by every checkpointed estimator so the guard can't drift.
    Foreign .npz files (missing keys) fail the same way."""
    ok = ("fp" in snap and "digest" in snap
          and np.array_equal(snap["fp"], fp)
          and np.shape(snap["digest"]) == np.shape(digest)
          and np.allclose(snap["digest"], digest, rtol=1e-5, atol=1e-6))
    if not ok:
        # a LENGTH mismatch from a snapshot that does NOT lead with the
        # current version element means the formula itself changed between
        # library versions (v1's unversioned 2/4-element digests vs v2's
        # version-led ones).  A length mismatch WITH a current version
        # lead is a cross-estimator snapshot (e.g. a DBSCAN checkpoint
        # path reused for a forest fit) — that keeps the generic message,
        # as do value mismatches at equal length.
        # (fp length is NOT used here: fp widths legitimately differ
        # ACROSS estimators, so a length mismatch can't distinguish a
        # version change from cross-estimator path reuse — an estimator
        # that widens its own fp raises the version error at its call
        # site, where its fp history is known; see trees._grow_forest)
        old = ("digest" in snap and np.ndim(snap["digest"]) == 1
               and np.size(snap["digest"]) != np.size(digest)
               and not (np.size(snap["digest"]) >= 1
                        and snap["digest"][0] == _DIGEST_VERSION))
        if old:
            raise ValueError(
                "checkpoint was written by a different library version "
                "(data-digest format changed) — delete the snapshot file "
                "to restart the fit from scratch")
        raise ValueError(
            "checkpoint does not match this data/estimator (shape, data "
            "content or hyperparameters differ) — stale or foreign snapshot")
