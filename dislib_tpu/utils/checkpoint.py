"""Mid-fit checkpointing (SURVEY.md §6 "Failure detection / elastic
recovery" + "Checkpoint / resume").

The reference's fault tolerance is runtime-level (COMPSs resubmits failed
tasks; `dislib/utils/saving.py` snapshots only *fitted* models).  On TPU a
chip failure kills the whole SPMD job, so mid-fit checkpointing of the
iteration state is first-class: iterative estimators (`KMeans`,
`GaussianMixture`, `ALS`, `CascadeSVM`) accept ``checkpoint=FitCheckpoint(path, every=k)``
and then run their device loop in k-iteration chunks, snapshotting the
host-readable iteration state (centers / responsibilities stats / factors +
iteration counter) after each chunk.  A re-run with the same checkpoint
resumes from the snapshot and produces the same result as an uninterrupted
fit (deterministic iterations) — asserted by the kill+resume fault-injection
test (`tests/test_checkpoint.py`).

Format: ``.npz`` written atomically (tmp file + rename), no pickle.
"""

from __future__ import annotations

import os

import numpy as np


class FitCheckpoint:
    """Snapshot/restore of in-flight fit state.

    Parameters
    ----------
    path : str — target ``.npz`` file.
    every : int, default 10 — checkpoint every `every` iterations.
    """

    def __init__(self, path: str, every: int = 10):
        self.path = str(path)
        self.every = int(every)
        if self.every < 1:
            raise ValueError("every must be >= 1")

    def save(self, state: dict) -> None:
        """Atomically persist a dict of ndarrays/scalars.

        A unique tmp file (mkstemp) in the target directory keeps concurrent
        fits sharing a path from clobbering each other's staging file, and
        the fsync-before-replace ensures the rename never lands ahead of the
        data on power loss."""
        import tempfile
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        fd, tmp = tempfile.mkstemp(suffix=".npz", dir=d)
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **state)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise

    def load(self) -> dict | None:
        """Return the saved state, or None if no checkpoint exists."""
        if not os.path.exists(self.path):
            return None
        with np.load(self.path, allow_pickle=False) as z:
            return {k: z[k] for k in z.files}

    def delete(self) -> None:
        if os.path.exists(self.path):
            os.remove(self.path)


def data_digest(xp, stats=None):
    """Order-sensitive float64 digest of a (padded) device matrix — plain
    and index-weighted sums, so a row permutation changes it.  Pad rows are
    zero under the pad-and-mask invariant, so padded sums equal logical
    sums.  Best-effort (a tiny relative perturbation at very large m can
    evade a sum digest); NaN digests never match → NaN data fails closed.
    ``stats`` (host per-row stats, e.g. tree label encodings) contributes
    the same two sums when given."""
    import jax
    import jax.numpy as jnp
    riota = jnp.arange(xp.shape[0], dtype=jnp.float32)
    vals = [float(jax.device_get(jnp.sum(xp))),
            float(jax.device_get(jnp.einsum("ij,i->", xp, riota)))]
    if stats is not None:
        vals += [float(np.sum(stats)),
                 float(np.arange(stats.shape[0]) @ np.sum(stats, axis=1))]
    return np.asarray(vals, np.float64)


def validate_snapshot(snap, fp, digest):
    """Refuse a snapshot whose fingerprint/digest doesn't match this fit —
    shared by every checkpointed estimator so the guard can't drift.
    Foreign .npz files (missing keys) fail the same way."""
    ok = ("fp" in snap and "digest" in snap
          and np.array_equal(snap["fp"], fp)
          and np.shape(snap["digest"]) == np.shape(digest)
          and np.allclose(snap["digest"], digest, rtol=1e-5, atol=1e-6))
    if not ok:
        raise ValueError(
            "checkpoint does not match this data/estimator (shape, data "
            "content or hyperparameters differ) — stale or foreign snapshot")
