"""Mid-fit checkpointing (SURVEY.md §6 "Failure detection / elastic
recovery" + "Checkpoint / resume").

The reference's fault tolerance is runtime-level (COMPSs resubmits failed
tasks; `dislib/utils/saving.py` snapshots only *fitted* models).  On TPU a
chip failure kills the whole SPMD job, so mid-fit checkpointing of the
iteration state is first-class: iterative estimators (`KMeans`,
`GaussianMixture`, `ALS`, `CascadeSVM`) accept ``checkpoint=FitCheckpoint(path, every=k)``
and then run their device loop in k-iteration chunks, snapshotting the
host-readable iteration state (centers / responsibilities stats / factors +
iteration counter) after each chunk.  A re-run with the same checkpoint
resumes from the snapshot and produces the same result as an uninterrupted
fit (deterministic iterations) — asserted by the kill+resume fault-injection
test (`tests/test_checkpoint.py`).

Format: ``.npz`` written atomically (tmp file + rename), no pickle.
"""

from __future__ import annotations

import os

import numpy as np


class FitCheckpoint:
    """Snapshot/restore of in-flight fit state.

    Parameters
    ----------
    path : str — target ``.npz`` file.
    every : int, default 10 — checkpoint every `every` iterations.
    """

    def __init__(self, path: str, every: int = 10):
        self.path = str(path)
        self.every = int(every)
        if self.every < 1:
            raise ValueError("every must be >= 1")

    def save(self, state: dict) -> None:
        """Atomically persist a dict of ndarrays/scalars.

        A unique tmp file (mkstemp) in the target directory keeps concurrent
        fits sharing a path from clobbering each other's staging file, and
        the fsync-before-replace ensures the rename never lands ahead of the
        data on power loss."""
        import tempfile
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        fd, tmp = tempfile.mkstemp(suffix=".npz", dir=d)
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **state)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise

    def load(self) -> dict | None:
        """Return the saved state, or None if no checkpoint exists."""
        if not os.path.exists(self.path):
            return None
        with np.load(self.path, allow_pickle=False) as z:
            return {k: z[k] for k in z.files}

    def delete(self) -> None:
        if os.path.exists(self.path):
            os.remove(self.path)


def data_digest(xp, stats=None):
    """Order-sensitive float64 digest of a (padded) device matrix — plain
    and index-weighted sums, so a row permutation changes it.  Pad rows are
    zero under the pad-and-mask invariant, so padded sums equal logical
    sums.  Best-effort (a tiny relative perturbation at very large m can
    evade a sum digest); NaN digests never match → NaN data fails closed.
    ``stats`` (host per-row stats, e.g. tree label encodings) contributes
    the same two sums when given.  The digest array leads with a format
    version so a snapshot written under an older formula fails validation
    with an accurate message instead of blaming the user's data."""
    total, wsum = digest_sums(xp)
    extras = []
    if stats is not None:
        extras = [float(np.sum(stats)),
                  float(np.arange(stats.shape[0]) @ np.sum(stats, axis=1))]
    return versioned_digest(total, wsum, *extras)


def versioned_digest(*vals):
    """Assemble a digest array in the shared version-led layout
    ``[_DIGEST_VERSION, *vals]`` — the ONE place that owns the format, so
    estimators composing their own digest terms (e.g. CSVM's x+y sums)
    cannot drift from it."""
    return np.asarray([_DIGEST_VERSION, *vals], np.float64)


# v2: index weights split into high/low f32 parts (2026-08-01).  v1 (no
# version element) used a single f32 iota, which collides adjacent indices
# above ~2^24 rows.
_DIGEST_VERSION = 2.0

_digest_kernel = None  # module-level so repeat fits hit the jit cache


def digest_sums(xp):
    """``(plain sum, index-weighted sum)`` of a device matrix as host
    floats — the shared order-sensitive reduction for checkpoint digests
    (also used directly by estimators that build composite digests, e.g.
    CSVM).  The index weights are split as i = 4096*hi + lo in one fused
    on-device program: a single f32 iota collides adjacent indices above
    ~2^24 rows, silently weakening the documented permutation
    sensitivity; each part stays exactly representable (lo < 4096,
    hi < m/4096).  Built with on-device iota (no O(m) host buffers or
    transfers); the partial sums recombine in float64 on host (f64 is
    unavailable on device without x64 mode)."""
    import jax
    import jax.numpy as jnp
    global _digest_kernel
    if _digest_kernel is None:
        @jax.jit
        def sums(x):
            r = jnp.arange(x.shape[0], dtype=jnp.int32)
            hi = (r // 4096).astype(jnp.float32)
            lo = (r % 4096).astype(jnp.float32)
            return (jnp.sum(x), jnp.einsum("ij,i->", x, hi),
                    jnp.einsum("ij,i->", x, lo))
        _digest_kernel = sums
    total, shi, slo = (float(v) for v in jax.device_get(_digest_kernel(xp)))
    return total, 4096.0 * shi + slo


def validate_snapshot(snap, fp, digest):
    """Refuse a snapshot whose fingerprint/digest doesn't match this fit —
    shared by every checkpointed estimator so the guard can't drift.
    Foreign .npz files (missing keys) fail the same way."""
    ok = ("fp" in snap and "digest" in snap
          and np.array_equal(snap["fp"], fp)
          and np.shape(snap["digest"]) == np.shape(digest)
          and np.allclose(snap["digest"], digest, rtol=1e-5, atol=1e-6))
    if not ok:
        # a LENGTH mismatch from a snapshot that does NOT lead with the
        # current version element means the formula itself changed between
        # library versions (v1's unversioned 2/4-element digests vs v2's
        # version-led ones).  A length mismatch WITH a current version
        # lead is a cross-estimator snapshot (e.g. a DBSCAN checkpoint
        # path reused for a forest fit) — that keeps the generic message,
        # as do value mismatches at equal length.
        # (fp length is NOT used here: fp widths legitimately differ
        # ACROSS estimators, so a length mismatch can't distinguish a
        # version change from cross-estimator path reuse — an estimator
        # that widens its own fp raises the version error at its call
        # site, where its fp history is known; see trees._grow_forest)
        old = ("digest" in snap and np.ndim(snap["digest"]) == 1
               and np.size(snap["digest"]) != np.size(digest)
               and not (np.size(snap["digest"]) >= 1
                        and snap["digest"][0] == _DIGEST_VERSION))
        if old:
            raise ValueError(
                "checkpoint was written by a different library version "
                "(data-digest format changed) — delete the snapshot file "
                "to restart the fit from scratch")
        raise ValueError(
            "checkpoint does not match this data/estimator (shape, data "
            "content or hyperparameters differ) — stale or foreign snapshot")
