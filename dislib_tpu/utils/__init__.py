"""dislib_tpu.utils — shuffle/split, model saving, checkpointing, profiling.

`shuffle`/`train_test_split`/`save_model`/`load_model` resolve lazily
(PEP 562): their home modules import `dislib_tpu.data.array`, while
`data/array.py` itself imports `dislib_tpu.utils.profiling` for the
dispatch counters — an eager import here would close that cycle mid-way
through the array module's initialisation.
"""

from dislib_tpu.utils.checkpoint import FitCheckpoint
from dislib_tpu.utils.profiling import (
    annotate, counters, dispatch_count, memory_stats, op_graph,
    profiled_jit, reset_counters, start_trace, stop_trace, trace,
    trace_count,
)

_LAZY_ATTRS = {
    "shuffle": "dislib_tpu.utils.base",
    "train_test_split": "dislib_tpu.utils.base",
    "save_model": "dislib_tpu.utils.saving",
    "load_model": "dislib_tpu.utils.saving",
}


def __getattr__(name):
    mod = _LAZY_ATTRS.get(name)
    if mod is None:
        raise AttributeError(
            f"module 'dislib_tpu.utils' has no attribute {name!r}")
    import importlib
    value = getattr(importlib.import_module(mod), name)
    globals()[name] = value
    return value


__all__ = ["shuffle", "train_test_split", "save_model", "load_model",
           "FitCheckpoint",
           "start_trace", "stop_trace", "trace", "annotate", "op_graph",
           "memory_stats",
           "profiled_jit", "dispatch_count", "trace_count", "counters",
           "reset_counters"]
