from dislib_tpu.utils.base import shuffle, train_test_split
from dislib_tpu.utils.saving import save_model, load_model
from dislib_tpu.utils.checkpoint import FitCheckpoint
from dislib_tpu.utils.profiling import (
    start_trace, stop_trace, trace, annotate, op_graph, memory_stats,
)

__all__ = ["shuffle", "train_test_split", "save_model", "load_model",
           "FitCheckpoint",
           "start_trace", "stop_trace", "trace", "annotate", "op_graph",
           "memory_stats"]
