from dislib_tpu.utils.base import shuffle, train_test_split
from dislib_tpu.utils.saving import save_model, load_model

__all__ = ["shuffle", "train_test_split", "save_model", "load_model"]
