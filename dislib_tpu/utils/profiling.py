"""Profiling / tracing (SURVEY.md §6 "Tracing / profiling").

Reference mechanism: COMPSs `runcompss --tracing` LD_PRELOADs Extrae into
master+workers and merges Paraver timelines; `--graph` dumps the task DAG.
dislib code is unmodified — tracing hooks the runtime.

TPU-native equivalent, same layering (estimator code stays unmodified, the
profiler hooks the runtime):

- `start_trace(logdir)` / `stop_trace()` / `trace(logdir)` — wrap
  `jax.profiler`; produces XPlane/Perfetto timelines (per-op HLO, ICI
  collectives) — the Paraver analog.
- `annotate(name)` — `jax.named_scope` + `jax.profiler.TraceAnnotation`;
  user-event markers on both the XLA op names and the host timeline — the
  Extrae user-events analog.  Estimators wrap their phases with it.
- `op_graph(fn, *args)` — compiled-HLO text of a jitted function — the
  `--graph` task-DAG analog.
"""

from __future__ import annotations

import contextlib

import jax


def start_trace(logdir: str) -> None:
    """Begin a profiler capture; view with TensorBoard/Perfetto."""
    jax.profiler.start_trace(logdir)


def stop_trace() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(logdir: str):
    """Context-managed capture: ``with dslib.utils.trace('/tmp/tb'): fit()``."""
    start_trace(logdir)
    try:
        yield
    finally:
        stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Mark a phase on both the device op names and the host trace timeline."""
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


def op_graph(fn, *args, **kwargs) -> str:
    """Compiled-HLO text of `fn(*args)` — the task-DAG dump analog."""
    return jax.jit(fn).lower(*args, **kwargs).compile().as_text()


def memory_stats():
    """Per-device memory stats (SURVEY §6 observability row — the COMPSs
    monitoring resource-load view's analog).

    Returns ``{device_str: stats_dict_or_None}``; keys of each stats dict
    are backend-defined (TPU reports e.g. ``bytes_in_use``,
    ``bytes_limit``, ``peak_bytes_in_use``), and devices whose backend
    exposes no allocator stats (CPU) map to None.
    """
    return {str(d): d.memory_stats() for d in jax.local_devices()}
