"""Profiling / tracing (SURVEY.md §6 "Tracing / profiling").

Reference mechanism: COMPSs `runcompss --tracing` LD_PRELOADs Extrae into
master+workers and merges Paraver timelines; `--graph` dumps the task DAG.
dislib code is unmodified — tracing hooks the runtime.

TPU-native equivalent, same layering (estimator code stays unmodified, the
profiler hooks the runtime):

- `start_trace(logdir)` / `stop_trace()` / `trace(logdir)` — wrap
  `jax.profiler`; produces XPlane/Perfetto timelines (per-op HLO, ICI
  collectives) — the Paraver analog.
- `annotate(name)` — `jax.named_scope` + `jax.profiler.TraceAnnotation`;
  user-event markers on both the XLA op names and the host timeline — the
  Extrae user-events analog.  Estimators wrap their phases with it.
- `op_graph(fn, *args)` — compiled-HLO text of a jitted function — the
  `--graph` task-DAG analog.
- dispatch/retrace counters (round-7 fusion PR): every library kernel is
  wrapped by :func:`profiled_jit`, which counts one *dispatch* per call
  and one *trace* per (re)compilation.  `dispatch_count()` is how the
  fusion layer's "a chain of ops is ONE XLA program" claim becomes a
  measured number (and a test assertion), and `trace_count()` is the
  retrace guard — a cache-key regression shows up as extra traces, not
  as a silent 20-second recompile on chip.
"""

from __future__ import annotations

import contextlib
import functools
import threading

import jax


def start_trace(logdir: str) -> None:
    """Begin a profiler capture; view with TensorBoard/Perfetto."""
    jax.profiler.start_trace(logdir)


def stop_trace() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(logdir: str):
    """Context-managed capture: ``with dslib.utils.trace('/tmp/tb'): fit()``."""
    start_trace(logdir)
    try:
        yield
    finally:
        stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Mark a phase on both the device op names and the host trace timeline."""
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


def op_graph(fn, *args, **kwargs) -> str:
    """Compiled-HLO text of `fn(*args)` — the task-DAG dump analog."""
    return jax.jit(fn).lower(*args, **kwargs).compile().as_text()


# ---------------------------------------------------------------------------
# dispatch / retrace counters
# ---------------------------------------------------------------------------

class _Counters:
    """Process-wide dispatch/trace/transfer tallies, total and per kernel
    name (transfers are total-only: one per host↔device boundary crossing
    at the blessed sync points), plus the round-12 resilience tallies —
    host-side integers bumped by the fit-loop driver, the watchdog, and
    the ingest quarantine, so surfacing them costs ZERO extra dispatches
    (asserted against the dispatch counters in ``tests/test_fitloop``)."""

    __slots__ = ("dispatches", "traces", "transfers", "dispatch_by",
                 "trace_by", "resilience", "schedules")

    def __init__(self):
        self.dispatches = 0
        self.traces = 0
        self.transfers = 0
        self.dispatch_by: dict[str, int] = {}
        self.trace_by: dict[str, int] = {}
        self.resilience: dict[str, int] = {}
        self.schedules: dict[str, int] = {}


_COUNTERS = _Counters()
_COUNTERS_LOCK = threading.Lock()


def profiled_jit(fn=None, *, name: str | None = None, **jit_kwargs):
    """``jax.jit`` plus the library's dispatch/retrace counters.

    Every call of the returned function counts one dispatch; every run of
    the traced Python body (i.e. a compilation-cache miss, including AOT
    lowering) counts one trace, both under ``name`` (default: the
    function's ``__name__``).  All remaining keyword arguments —
    ``static_argnames``, ``donate_argnames``, ... — pass through to
    ``jax.jit`` unchanged.  The underlying jitted callable is exposed as
    ``.jitted`` for ``.lower()``-style AOT access.
    """
    if fn is None:
        return lambda f: profiled_jit(f, name=name, **jit_kwargs)
    label = name or getattr(fn, "__name__", "jit")

    @functools.wraps(fn)
    def traced(*args, **kwargs):
        with _COUNTERS_LOCK:
            _COUNTERS.traces += 1
            _COUNTERS.trace_by[label] = _COUNTERS.trace_by.get(label, 0) + 1
        return fn(*args, **kwargs)

    jitted = jax.jit(traced, **jit_kwargs)

    @functools.wraps(fn)
    def dispatch(*args, **kwargs):
        with _COUNTERS_LOCK:
            _COUNTERS.dispatches += 1
            _COUNTERS.dispatch_by[label] = \
                _COUNTERS.dispatch_by.get(label, 0) + 1
        return jitted(*args, **kwargs)

    dispatch.jitted = jitted
    dispatch.lower = jitted.lower       # AOT access (HLO audits) counts a
    dispatch.eval_shape = jitted.eval_shape  # trace, never a dispatch
    dispatch.profiled_name = label
    return dispatch


def count_dispatch(name: str, n: int = 1) -> None:
    """Record ``n`` device dispatches that bypass :func:`profiled_jit` —
    the round-15 deployment-bundle path invokes DESERIALIZED compiled
    executables directly (no jit wrapper exists to count for it), and
    the serving layer's one-dispatch-per-batch invariant must stay a
    counter assertion there too.  Never counts a trace: a deserialized
    executable cannot retrace by construction."""
    with _COUNTERS_LOCK:
        _COUNTERS.dispatches += n
        _COUNTERS.dispatch_by[name] = _COUNTERS.dispatch_by.get(name, 0) + n


def count_transfer(n: int = 1) -> None:
    """Record ``n`` host↔device transfers.  Called by the library's
    blessed sync boundaries — ``runtime.fetch``, ``Array.collect``,
    ``Array.__float__``, the host tiers of ``apply_along_axis`` and
    ``repad_rows`` — so "this pipeline stage boundary costs ZERO host
    transfers" is a counter assertion, not prose (round-11 rechunk PR)."""
    with _COUNTERS_LOCK:
        _COUNTERS.transfers += n


def transfer_count() -> int:
    """Total host↔device transfers through the library's blessed sync
    boundaries since the last `reset_counters()`."""
    return _COUNTERS.transfers


def count_resilience(key: str, n: int = 1) -> None:
    """Record ``n`` resilience events under ``key`` — the blessed keys are
    ``rollbacks``, ``chunk_retries``, ``escalations_<tier>``,
    ``mesh_shrinks`` / ``mesh_grows`` (the fit-loop driver's elastic
    resizes, escalation- or capacity-driven), ``watchdog_trips`` (the
    chunk guard), ``quarantined_rows`` (ingest), and the round-20
    membership tallies: ``rank_deaths`` / ``rank_rejoins`` (lease
    expiries confirmed and healed by ``runtime.coord.Membership``),
    ``coord_torn_reads`` (torn coordination files survived),
    ``serve_shard_drains`` (a ``PredictServer`` refusing torn fleet
    results while a peer shard is dead), and ``retrieval_rebinds``
    (an ``IVFIndex`` re-laying its device layout after a mesh change)."""
    with _COUNTERS_LOCK:
        _COUNTERS.resilience[key] = _COUNTERS.resilience.get(key, 0) + n


def resilience_counters() -> dict:
    """Resilience tallies since the last ``reset_counters()`` — rollbacks,
    chunk retries, watchdog trips, escalations per ladder tier, mesh
    shrinks/grows, quarantined rows (keys absent until their first
    event)."""
    with _COUNTERS_LOCK:
        return dict(_COUNTERS.resilience)


def count_schedule(kernel: str, schedule: str, n: int = 1) -> None:
    """Record that ``kernel`` ran under panel ``schedule`` (round-13
    overlap PR) — bumped host-side by the routing boundaries (SUMMA's
    matmul entry, ``panel_rechunk``, the ring estimators' tier pickers),
    so "which schedule did the router actually run" is a counter
    assertion, not prose.  Keys are ``f"{kernel}:{schedule}"``."""
    with _COUNTERS_LOCK:
        key = f"{kernel}:{schedule}"
        _COUNTERS.schedules[key] = _COUNTERS.schedules.get(key, 0) + n


def schedule_counters() -> dict:
    """``{"kernel:schedule": count}`` tallies since the last
    ``reset_counters()`` — the overlap router's observability surface
    (``DSLIB_OVERLAP`` routing is asserted through this in
    ``tests/test_overlap.py`` and the bench overlap tier)."""
    with _COUNTERS_LOCK:
        return dict(_COUNTERS.schedules)


def dispatch_count() -> int:
    """Total library-kernel dispatches since the last `reset_counters()`."""
    return _COUNTERS.dispatches


def trace_count() -> int:
    """Total library-kernel (re)compilations since `reset_counters()`."""
    return _COUNTERS.traces


def counters() -> dict:
    """Snapshot of the tallies: ``{dispatches, traces, dispatch_by,
    trace_by}`` with per-kernel-name breakdowns (plain dict copies)."""
    with _COUNTERS_LOCK:
        return {"dispatches": _COUNTERS.dispatches,
                "traces": _COUNTERS.traces,
                "transfers": _COUNTERS.transfers,
                "dispatch_by": dict(_COUNTERS.dispatch_by),
                "trace_by": dict(_COUNTERS.trace_by),
                "resilience": dict(_COUNTERS.resilience),
                "schedules": dict(_COUNTERS.schedules)}


def reset_counters() -> None:
    """Zero the dispatch/trace tallies (tests and bench regions)."""
    with _COUNTERS_LOCK:
        _COUNTERS.dispatches = 0
        _COUNTERS.traces = 0
        _COUNTERS.transfers = 0
        _COUNTERS.dispatch_by.clear()
        _COUNTERS.trace_by.clear()
        _COUNTERS.resilience.clear()
        _COUNTERS.schedules.clear()


def memory_stats():
    """Per-device memory stats (SURVEY §6 observability row — the COMPSs
    monitoring resource-load view's analog).

    Returns ``{device_str: stats_dict_or_None}``; keys of each stats dict
    are backend-defined (TPU reports e.g. ``bytes_in_use``,
    ``bytes_limit``, ``peak_bytes_in_use``), and devices whose backend
    exposes no allocator stats (CPU) map to None.
    """
    return {str(d): d.memory_stats() for d in jax.local_devices()}
