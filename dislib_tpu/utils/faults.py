"""Deterministic fault-injection harness (SURVEY §6 — the kill+resume /
chaos-drill side of "Failure detection / elastic recovery").

Everything here is schedule-driven: faults fire at an exact save count, an
exact byte position, or an exact call count — never on a timer or an RNG —
so ``tests/test_resilience.py`` reproduces bit-identically on any rig.
Three fault families:

- **kill-at-iteration-k** — :class:`CallbackCheckpoint` /
  :class:`SigtermAtNthSave` fire right AFTER the n-th snapshot reaches
  disk, the state a preempted job leaves behind;
- **snapshot damage** — :func:`corrupt_snapshot` flips a byte, truncates,
  or replaces a snapshot with a foreign ``.npz``;
- **flaky IO / RPC** — :class:`FlakyCall` and :class:`FlakyOpen` fail the
  first n invocations with a transient error, exercising the
  :class:`~dislib_tpu.runtime.retry.Retry` policy;
- **numerical / liveness faults** (round-8 health PR) —
  :class:`NaNAtChunk` poisons a loop carry at an exact chunk index,
  :class:`DivergenceRamp` scales it into a blow-up, :class:`HangAtChunk`
  stalls a chunk's force point past the watchdog deadline, and
  :class:`TripAtChunk` forces a guard verdict where no float carry exists
  to poison (the cascade SVM's host-side state).  All four are
  :class:`~dislib_tpu.runtime.health.HealthPolicy` subclasses: pass them
  as ``fit(..., health=...)`` and the estimator's own guard becomes the
  injector — the production code path is exercised unchanged.
- **multi-host membership faults** (round-20 survival PR) —
  :class:`KillRankAt` delivers a real SIGKILL at an exact call count (the
  rank death), :class:`LeaseExpiry` gates a
  :class:`~dislib_tpu.runtime.coord.LeaseKeeper` to skip an exact window
  of heartbeats (the delayed/flapping host), and :class:`TornCoordWrite`
  writes one coordination post torn and NON-atomically onto its final
  path (the crashed writer rename atomicity normally makes impossible).
  All three are call-count driven, so the chaos matrix reproduces
  bit-identically.
"""

from __future__ import annotations

import builtins
import os
import signal as _signal
import time as _time

import numpy as np

from dislib_tpu.runtime.health import ChunkGuard, HealthPolicy, Verdict
from dislib_tpu.utils.checkpoint import FitCheckpoint

__all__ = ["CallbackCheckpoint", "SigtermAtNthSave", "sigterm_self",
           "corrupt_snapshot", "FlakyCall", "FlakyOpen",
           "NaNAtChunk", "DivergenceRamp", "HangAtChunk", "TripAtChunk",
           "FaultAtTier", "CapacityAtSave", "oscillation_schedule",
           "TornBundleWrite", "CanaryGateTrip",
           "KillRankAt", "LeaseExpiry", "TornCoordWrite"]


class CallbackCheckpoint(FitCheckpoint):
    """Runs ``callback()`` right AFTER the ``after``-th successful save —
    the snapshot is on disk when the fault fires, exactly the state a
    preempted/killed job leaves behind."""

    def __init__(self, path, every: int = 1, after: int = 1, callback=None,
                 keep: int = 2):
        super().__init__(path, every=every, keep=keep)
        self._left = int(after)
        self._callback = callback

    def save(self, state):
        super().save(state)
        self._left -= 1
        if self._left == 0 and self._callback is not None:
            self._callback()


def sigterm_self() -> None:
    """Deliver SIGTERM to this process — the real preemption notice."""
    os.kill(os.getpid(), _signal.SIGTERM)


class SigtermAtNthSave(CallbackCheckpoint):
    """SIGTERM lands right after the n-th snapshot: with a
    :class:`~dislib_tpu.runtime.preemption.PreemptionWatcher` installed the
    fit raises ``Preempted`` at the NEXT chunk boundary."""

    def __init__(self, path, every: int = 1, after: int = 1, keep: int = 2):
        super().__init__(path, every=every, after=after,
                         callback=sigterm_self, keep=keep)


def corrupt_snapshot(path, mode: str = "flip", position: int | None = None):
    """Deterministically damage a snapshot file in place.

    - ``"flip"`` — XOR one byte (the middle one unless ``position``);
    - ``"truncate"`` — keep only the first half of the file;
    - ``"foreign"`` — replace with a plain ``np.savez`` carrying no
      integrity record (a non-dislib ``.npz``).
    """
    path = str(path)
    if mode == "foreign":
        np.savez(path, junk=np.arange(3))
        return
    with open(path, "rb") as f:
        data = bytearray(f.read())
    if mode == "truncate":
        data = data[: max(1, len(data) // 2)]
    elif mode == "flip":
        pos = len(data) // 2 if position is None else int(position)
        data[pos] ^= 0xFF
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    with open(path, "wb") as f:
        f.write(bytes(data))


class FlakyCall:
    """Wraps a callable: the first ``failures`` invocations raise a
    transient error (``exc_factory()``), later ones delegate.  ``calls``
    counts every invocation — assert on it to pin the retry schedule."""

    def __init__(self, fn, failures: int = 1, exc_factory=None):
        self.fn = fn
        self.failures = int(failures)
        self.calls = 0
        self.exc_factory = exc_factory or (
            lambda: ConnectionResetError("injected transient failure"))

    def __call__(self, *args, **kwargs):
        self.calls += 1
        if self.calls <= self.failures:
            raise self.exc_factory()
        return self.fn(*args, **kwargs)


class FlakyOpen:
    """``builtins.open`` stand-in that fails the first ``failures`` opens
    of one specific ``path`` with a transient ``OSError`` (EIO) — flaky
    shared-filesystem injection for the ingest retry path.  Install with
    ``monkeypatch.setattr(builtins, "open", FlakyOpen(path, 2))``."""

    def __init__(self, path, failures: int = 1, exc_factory=None):
        self._path = os.path.abspath(str(path))
        self._real = builtins.open
        self.failures = int(failures)
        self.fails = 0
        self.exc_factory = exc_factory or (
            lambda: OSError(5, "injected flaky read"))  # errno 5 = EIO

    def __call__(self, file, *args, **kwargs):
        try:
            same = os.path.abspath(os.fspath(file)) == self._path
        except TypeError:
            same = False  # fd-based open: never injected
        if same and self.fails < self.failures:
            self.fails += 1
            raise self.exc_factory()
        return self._real(file, *args, **kwargs)


# ---------------------------------------------------------------------------
# numerical / liveness fault injection (round-8 health PR)
# ---------------------------------------------------------------------------

def _poison_carry(carries, where, mutate):
    """Apply ``mutate(host_ndarray) -> host_ndarray`` to the ``where``-th
    float-dtype array among ``carries`` (None/ints/scalars skipped),
    returning ``(new_tuple, hit)``.  The poisoned carry re-enters the
    device as a fresh array — exactly what a corrupted HBM buffer or a
    bad collective would hand the next chunk.  ``hit`` is False when no
    eligible carry exists (e.g. a first chunk that admits no state) —
    callers keep the fault ARMED then, so an injection can never be
    silently lost and a resilience test can never vacuously pass against
    an unfaulted run."""
    import jax
    import jax.numpy as jnp
    out = list(carries)
    fi = 0
    for i, c in enumerate(carries):
        dt = getattr(c, "dtype", None)
        if dt is None or not np.issubdtype(np.dtype(dt), np.floating) \
                or getattr(c, "ndim", 0) == 0:
            continue
        if fi == where:
            host = np.array(jax.device_get(c))
            out[i] = jnp.asarray(mutate(host))
            return tuple(out), True
        fi += 1
    return tuple(out), False


class NaNAtChunk(HealthPolicy):
    """Health policy whose guard poisons one carry with NaN right before
    the ``at_chunk``-th chunk dispatches (1-based admit count) — the
    deterministic stand-in for a numerical blow-up inside that chunk.
    Fires once: after a rollback the re-run chunk is clean, so a fit
    under the default 'retry' action must land on the unfaulted model.

    ``where`` selects the n-th float carry, ``position`` the flat element
    poisoned (middle when None)."""

    def __init__(self, at_chunk=2, where=0, position=None, **kw):
        super().__init__(**kw)
        self.at_chunk = int(at_chunk)
        self.where = int(where)
        self.position = position
        self.fired = 0

    def make_guard(self, name, checkpoint=None):
        return _NaNAtChunkGuard(name, self, checkpoint)


class _NaNAtChunkGuard(ChunkGuard):
    def admit(self, *carries):
        carries = super().admit(*carries)
        pol = self.policy
        # >= keeps the fault ARMED past a chunk with no eligible carry
        # (e.g. ALS's first fresh chunk admits no state): it lands on the
        # first admit that CAN be poisoned instead of silently fizzling
        if self.chunk_index >= pol.at_chunk and not pol.fired:
            def mutate(host):
                pos = host.size // 2 if pol.position is None \
                    else int(pol.position) % max(host.size, 1)
                host.flat[pos] = np.nan
                return host
            carries, hit = _poison_carry(carries, pol.where, mutate)
            pol.fired += int(hit)
        return carries


class DivergenceRamp(HealthPolicy):
    """Health policy whose guard scales one carry by ``factor`` at every
    chunk from ``at_chunk`` on (or once, with ``repeat=False``) — a
    deterministic divergence ramp for the norm-growth / monotonicity
    guards (arm them: ``grow_limit=`` or ``monotone_rtol=``)."""

    def __init__(self, at_chunk=1, factor=1e4, repeat=True, **kw):
        super().__init__(**kw)
        self.at_chunk = int(at_chunk)
        self.factor = float(factor)
        self.repeat = bool(repeat)
        self.fired = 0

    def make_guard(self, name, checkpoint=None):
        return _DivergenceRampGuard(name, self, checkpoint)


class _DivergenceRampGuard(ChunkGuard):
    def admit(self, *carries):
        carries = super().admit(*carries)
        pol = self.policy
        if self.chunk_index >= pol.at_chunk and (pol.repeat or not pol.fired):
            carries, hit = _poison_carry(
                carries, 0, lambda host: host * pol.factor)
            pol.fired += int(hit)
        return carries


class HangAtChunk(HealthPolicy):
    """Health policy whose guard stalls the ``at_chunk``-th chunk's force
    point (the health read) for ``hang_s`` seconds, ``times`` attempts in
    a row — the deterministic stand-in for a hung collective/dispatch.
    With ``deadline_s < hang_s`` the watchdog trips a typed
    ``WatchdogTimeout``; the PR-1 ``Retry`` policy re-attempts the
    resolution, so ``times=1`` self-heals on the second attempt and a
    large ``times`` exhausts the attempts and aborts cleanly.

    The stall fires at the first CHECK at-or-after ``at_chunk`` (loops
    like the forest's only check at snapshot boundaries, so an exact
    match could silently never inject — the same armed-fault rule as
    ``_poison_carry``), and the injector pins ``first_deadline_s`` to
    the steady-state deadline so the production compile-grace on a
    guard's first check cannot mask the injected hang."""

    def __init__(self, at_chunk=1, hang_s=0.4, times=1, deadline_s=0.05,
                 **kw):
        kw.setdefault("first_deadline_s", deadline_s)
        super().__init__(deadline_s=deadline_s, **kw)
        self.at_chunk = int(at_chunk)
        self.hang_s = float(hang_s)
        self.times = int(times)
        self.stalls = 0

    def make_guard(self, name, checkpoint=None):
        return _HangAtChunkGuard(name, self, checkpoint)


class _HangAtChunkGuard(ChunkGuard):
    def _resolve(self, handle):
        pol = self.policy
        if self.chunk_index >= pol.at_chunk and pol.stalls < pol.times:
            pol.stalls += 1
            _time.sleep(pol.hang_s)
        return super()._resolve(handle)


class TripAtChunk(HealthPolicy):
    """Health policy whose guard forces an unhealthy verdict at the
    ``at_chunk``-th chunk regardless of the actual values — for loops
    whose numeric state offers nothing to poison (the cascade SVM's
    host-side SV indices) and for exercising the gating/rollback
    machinery in isolation.  Fires at the first ``times`` checks from
    ``at_chunk`` on (``times`` > max_restarts exhausts the remediation
    budget and forces the typed raise)."""

    def __init__(self, at_chunk=1, guard_name="injected", times=1, **kw):
        super().__init__(**kw)
        self.at_chunk = int(at_chunk)
        self.guard_name = guard_name
        self.times = int(times)
        self.fired = 0

    def make_guard(self, name, checkpoint=None):
        return _TripAtChunkGuard(name, self, checkpoint)


class _TripAtChunkGuard(ChunkGuard):
    def _maybe_trip(self, it):
        pol = self.policy
        if self.chunk_index >= pol.at_chunk and pol.fired < pol.times:
            pol.fired += 1
            v = Verdict(False, guard=pol.guard_name,
                        detail={"iteration": it, "injected": True})
            self.last_verdict = v
            return v
        return None

    def check(self, hvec, carry_names=(), carry_shapes=(), it=None,
              increasing=False):
        return self._maybe_trip(it) or super().check(
            hvec, carry_names, carry_shapes, it, increasing)

    def check_host(self, values, it=None):
        return self._maybe_trip(it) or super().check_host(values, it)


class CapacityAtSave(HealthPolicy):
    """Oscillating-capacity injector (round-16 bidirectional elasticity):
    walk a ``{save_index: n_devices}`` schedule, publishing each capacity
    level via :func:`~dislib_tpu.runtime.preemption.request_capacity` at
    the moment the ``save_index``-th gated snapshot write STARTS — i.e.
    synchronously at the chunk boundary, so the NEXT chunk's capacity
    poll sees the level deterministically (a callback on the async write
    worker races the poll).  A value of ``None`` clears the override.
    Remember to :func:`~dislib_tpu.runtime.preemption.clear_capacity` at
    teardown (the level is process-wide)."""

    def __init__(self, schedule, **kw):
        super().__init__(**kw)
        self.schedule = {int(k): v for k, v in dict(schedule).items()}
        self.saves = 0

    def make_guard(self, name, checkpoint=None):
        return _CapacityAtSaveGuard(name, self, checkpoint)


class _CapacityAtSaveGuard(ChunkGuard):
    def save_async(self, checkpoint, state):
        out = super().save_async(checkpoint, state)
        if out is None:                 # gated off: unhealthy chunk
            return out
        pol = self.policy
        pol.saves += 1
        if pol.saves in pol.schedule:
            from dislib_tpu.runtime.preemption import (clear_capacity,
                                                       request_capacity)
            cap = pol.schedule[pol.saves]
            if cap is None:
                clear_capacity()
            else:
                request_capacity(cap)
        return out


def oscillation_schedule(home_devices, seed, period=2, swings=2):
    """A seeded shrink → heal → grow capacity walk for the chaos tiers:
    ``swings`` dips to a (seeded) fraction of ``home_devices``, each
    held for ``period`` saves before the grow-back to full capacity,
    ending with a final ``None`` to clear the override.  Deterministic
    per seed — the whole chaos matrix stays bit-reproducible."""
    rng = np.random.RandomState(int(seed))
    sched, at = {}, 1
    for _ in range(int(swings)):
        dip = max(1, int(home_devices) >> int(rng.randint(1, 3)))
        sched[at] = dip
        sched[at + int(period)] = int(home_devices)
        at += 2 * int(period)
    sched[at] = None
    return sched


class TornBundleWrite:
    """Bundle-export seam injector (round-17 trainer): a drop-in for
    ``dislib_tpu.serving.bundle.write_bundle`` whose first ``failures``
    calls complete the REAL atomic write and then damage the published
    artifact in place (:func:`corrupt_snapshot` ``mode``) — the
    post-rename torn/bit-rotted bundle a crash-mid-export or a flaky
    filesystem leaves behind.  This is deliberately *worse* than a tear
    the atomic rename can mask: the damage lands on the final path, so
    only the CRC-verified read-back (``SnapshotCorrupt``) can catch it.
    Later calls delegate untouched; ``calls`` counts every invocation.
    Install with ``monkeypatch.setattr("dislib_tpu.serving.bundle."
    "write_bundle", TornBundleWrite(failures=1))``."""

    def __init__(self, failures: int = 1, mode: str = "truncate"):
        from dislib_tpu.runtime.bundle_io import write_bundle
        self._real = write_bundle       # captured BEFORE any patching
        self.failures = int(failures)
        self.mode = mode
        self.calls = 0

    def __call__(self, path, arrays):
        self.calls += 1
        out = self._real(path, arrays)
        if self.calls <= self.failures:
            corrupt_snapshot(path, mode=self.mode)
        return out


class CanaryGateTrip:
    """Promotion-seam injector: a ``health_gate(loaded, generation)``
    callable that refuses the first ``times`` checks (the unhealthy
    canary) and delegates to ``then`` — or accepts — afterwards.
    ``checks`` counts every gate evaluation; schedule-driven like every
    injector here, so the trainer's reject → stay-on-last-good →
    budget-exhaustion path reproduces bit-identically."""

    def __init__(self, times: int = 1, then=None):
        self.times = int(times)
        self.then = then
        self.checks = 0

    def __call__(self, loaded, generation) -> bool:
        self.checks += 1
        if self.checks <= self.times:
            return False
        if self.then is not None:
            return bool(self.then(loaded, generation))
        return True


# ---------------------------------------------------------------------------
# multi-host membership fault injection (round-20 survival PR)
# ---------------------------------------------------------------------------

class KillRankAt:
    """Callable seam injector that delivers ``sig`` (default SIGKILL — no
    handlers, no cleanup, the real rank death) to ``pid`` (default: this
    process) at exactly the ``at_call``-th invocation.  Plant it wherever
    the harness needs the death to land — a chunk callback, a
    ``CallbackCheckpoint(callback=...)``, a heartbeat gate — and the kill
    fires at a deterministic point in the work stream, never on a timer.

    ``kill=`` is injectable so tier-1 unit tests pin the schedule without
    killing the test runner; ``calls``/``fired`` count invocations and
    deliveries for assertions."""

    def __init__(self, at_call: int = 1, pid=None, sig=_signal.SIGKILL,
                 kill=os.kill):
        self.at_call = int(at_call)
        self.pid = pid
        self.sig = sig
        self._kill = kill
        self.calls = 0
        self.fired = 0

    def __call__(self, *args, **kwargs):
        self.calls += 1
        if self.calls == self.at_call:
            self.fired += 1
            pid = os.getpid() if self.pid is None else int(self.pid)
            self._kill(pid, self.sig)


class LeaseExpiry:
    """A :class:`~dislib_tpu.runtime.coord.LeaseKeeper` ``gate=`` that
    SKIPS heartbeats ``after+1 .. after+beats`` (1-based beat count) and
    heartbeats normally otherwise — the deterministic stand-in for a
    stalled or network-partitioned host whose lease expires while the
    process is still alive.  With ``beats`` long enough to outlive the
    lease, peers observe a death (``RankDead``) followed by a REJOIN when
    beating resumes — the flap scenario; ``beats`` large keeps the rank
    dead forever.  ``calls`` counts every gate evaluation."""

    def __init__(self, after: int = 1, beats: int = 2):
        self.after = int(after)
        self.beats = int(beats)
        self.calls = 0

    def __call__(self) -> bool:
        self.calls += 1
        return not (self.after < self.calls <= self.after + self.beats)


class TornCoordWrite:
    """Coordinator drop-in whose first ``failures`` matching posts are
    written TORN (first half of the JSON payload) and NON-atomically onto
    the final ``<name>.<rank>.json`` path — the partial write a killed or
    crashing writer leaves when it bypasses the tmp-write + rename
    discipline.  Readers must classify it :class:`TornCoordFile`
    (transient), retry through ``runtime.Retry``, and degrade to
    "missing" — never a fleet kill.  Later posts delegate to the real
    atomic write, which is also the healing story: the writer's clean
    re-post replaces the torn file.  All other coordinator methods
    (``peek``/``exchange``/…) pass through untouched.  ``name=`` narrows
    the tear to one exchange name; ``calls``/``fails`` pin the schedule.
    Wraps a :class:`~dislib_tpu.runtime.coord.FileCoordinator` (the only
    transport with an on-disk surface to tear)."""

    def __init__(self, coord, failures: int = 1, name=None):
        self._coord = coord
        self.failures = int(failures)
        self.name = name
        self.calls = 0
        self.fails = 0

    def __getattr__(self, attr):
        return getattr(self._coord, attr)

    def post(self, name, rank, value):
        import json
        from dislib_tpu.runtime.coord import _post_crc
        self.calls += 1
        if (self.name is None or name == self.name) \
                and self.fails < self.failures:
            self.fails += 1
            os.makedirs(self._coord.directory, exist_ok=True)
            payload = json.dumps(
                {"crc": _post_crc(value), "v": value}).encode()
            with open(self._coord._path(name, rank), "wb") as f:
                f.write(payload[: max(1, len(payload) // 2)])
            return
        return self._coord.post(name, rank, value)


class FaultAtTier(HealthPolicy):
    """Health policy whose guard trips EVERY check (from ``at_chunk`` on)
    until the fit-loop escalation ladder reaches remediation tier
    ``tiers`` — i.e. the fault "defeats" exactly the first ``tiers``
    ladder tiers (0 = healed by the first plain chunk retry, 1 = defeats
    retry, healed by policy remediation, 2 = defeats retry AND
    remediation, healed only by the elastic mesh-shrink, 3 = defeats the
    whole ladder and forces the typed raise).  The healing signal is the
    driver's :meth:`~dislib_tpu.runtime.health.ChunkGuard.on_escalation`
    notification, so the injector tracks the LADDER's actual tier — not a
    guessed attempt count — and a schedule change cannot silently turn a
    tier-2 drill into a tier-1 one.  Give the policy a budget that makes
    the target tier reachable (e.g. ``max_restarts=3,
    elastic_attempts=1`` for tier 2)."""

    def __init__(self, tiers=1, at_chunk=1, guard_name="fault-at-tier",
                 **kw):
        super().__init__(**kw)
        self.tiers = int(tiers)
        self.at_chunk = int(at_chunk)
        self.guard_name = guard_name
        self.fired = 0
        self.healed = False

    def make_guard(self, name, checkpoint=None):
        return _FaultAtTierGuard(name, self, checkpoint)


class _FaultAtTierGuard(ChunkGuard):
    def _maybe_trip(self, it):
        pol = self.policy
        if self.chunk_index >= pol.at_chunk and not pol.healed:
            pol.fired += 1
            v = Verdict(False, guard=pol.guard_name,
                        detail={"iteration": it, "injected": True,
                                "defeats_tiers": pol.tiers})
            self.last_verdict = v
            return v
        return None

    def on_escalation(self, escalation):
        # the re-run AFTER an escalation that reached tier `tiers` passes
        if escalation.tier_index >= self.policy.tiers:
            self.policy.healed = True

    def check(self, hvec, carry_names=(), carry_shapes=(), it=None,
              increasing=False):
        return self._maybe_trip(it) or super().check(
            hvec, carry_names, carry_shapes, it, increasing)

    def check_host(self, values, it=None):
        return self._maybe_trip(it) or super().check_host(values, it)
