"""Deterministic fault-injection harness (SURVEY §6 — the kill+resume /
chaos-drill side of "Failure detection / elastic recovery").

Everything here is schedule-driven: faults fire at an exact save count, an
exact byte position, or an exact call count — never on a timer or an RNG —
so ``tests/test_resilience.py`` reproduces bit-identically on any rig.
Three fault families:

- **kill-at-iteration-k** — :class:`CallbackCheckpoint` /
  :class:`SigtermAtNthSave` fire right AFTER the n-th snapshot reaches
  disk, the state a preempted job leaves behind;
- **snapshot damage** — :func:`corrupt_snapshot` flips a byte, truncates,
  or replaces a snapshot with a foreign ``.npz``;
- **flaky IO / RPC** — :class:`FlakyCall` and :class:`FlakyOpen` fail the
  first n invocations with a transient error, exercising the
  :class:`~dislib_tpu.runtime.retry.Retry` policy.
"""

from __future__ import annotations

import builtins
import os
import signal as _signal

import numpy as np

from dislib_tpu.utils.checkpoint import FitCheckpoint

__all__ = ["CallbackCheckpoint", "SigtermAtNthSave", "sigterm_self",
           "corrupt_snapshot", "FlakyCall", "FlakyOpen"]


class CallbackCheckpoint(FitCheckpoint):
    """Runs ``callback()`` right AFTER the ``after``-th successful save —
    the snapshot is on disk when the fault fires, exactly the state a
    preempted/killed job leaves behind."""

    def __init__(self, path, every: int = 1, after: int = 1, callback=None,
                 keep: int = 2):
        super().__init__(path, every=every, keep=keep)
        self._left = int(after)
        self._callback = callback

    def save(self, state):
        super().save(state)
        self._left -= 1
        if self._left == 0 and self._callback is not None:
            self._callback()


def sigterm_self() -> None:
    """Deliver SIGTERM to this process — the real preemption notice."""
    os.kill(os.getpid(), _signal.SIGTERM)


class SigtermAtNthSave(CallbackCheckpoint):
    """SIGTERM lands right after the n-th snapshot: with a
    :class:`~dislib_tpu.runtime.preemption.PreemptionWatcher` installed the
    fit raises ``Preempted`` at the NEXT chunk boundary."""

    def __init__(self, path, every: int = 1, after: int = 1, keep: int = 2):
        super().__init__(path, every=every, after=after,
                         callback=sigterm_self, keep=keep)


def corrupt_snapshot(path, mode: str = "flip", position: int | None = None):
    """Deterministically damage a snapshot file in place.

    - ``"flip"`` — XOR one byte (the middle one unless ``position``);
    - ``"truncate"`` — keep only the first half of the file;
    - ``"foreign"`` — replace with a plain ``np.savez`` carrying no
      integrity record (a non-dislib ``.npz``).
    """
    path = str(path)
    if mode == "foreign":
        np.savez(path, junk=np.arange(3))
        return
    with open(path, "rb") as f:
        data = bytearray(f.read())
    if mode == "truncate":
        data = data[: max(1, len(data) // 2)]
    elif mode == "flip":
        pos = len(data) // 2 if position is None else int(position)
        data[pos] ^= 0xFF
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    with open(path, "wb") as f:
        f.write(bytes(data))


class FlakyCall:
    """Wraps a callable: the first ``failures`` invocations raise a
    transient error (``exc_factory()``), later ones delegate.  ``calls``
    counts every invocation — assert on it to pin the retry schedule."""

    def __init__(self, fn, failures: int = 1, exc_factory=None):
        self.fn = fn
        self.failures = int(failures)
        self.calls = 0
        self.exc_factory = exc_factory or (
            lambda: ConnectionResetError("injected transient failure"))

    def __call__(self, *args, **kwargs):
        self.calls += 1
        if self.calls <= self.failures:
            raise self.exc_factory()
        return self.fn(*args, **kwargs)


class FlakyOpen:
    """``builtins.open`` stand-in that fails the first ``failures`` opens
    of one specific ``path`` with a transient ``OSError`` (EIO) — flaky
    shared-filesystem injection for the ingest retry path.  Install with
    ``monkeypatch.setattr(builtins, "open", FlakyOpen(path, 2))``."""

    def __init__(self, path, failures: int = 1, exc_factory=None):
        self._path = os.path.abspath(str(path))
        self._real = builtins.open
        self.failures = int(failures)
        self.fails = 0
        self.exc_factory = exc_factory or (
            lambda: OSError(5, "injected flaky read"))  # errno 5 = EIO

    def __call__(self, file, *args, **kwargs):
        try:
            same = os.path.abspath(os.fspath(file)) == self._path
        except TypeError:
            same = False  # fd-based open: never injected
        if same and self.fails < self.failures:
            self.fails += 1
            raise self.exc_factory()
        return self._real(file, *args, **kwargs)
