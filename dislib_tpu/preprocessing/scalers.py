"""Feature scalers (reference: `dislib/preprocessing` — blocked mean/var or
min/max partial sums in fit, per-block affine transform tasks in transform /
inverse_transform; SURVEY.md §3.3).

TPU-native: fit statistics are the Array reductions (one psum over the row
axis); transform is a broadcasted elementwise op on the sharded data — no
communication at all.

Sparse awareness (reference parity, SURVEY §3.3 scalers row: "sparse-aware,
no centering of sparse unless dense"): StandardScaler accepts a SparseArray
when ``with_mean=False`` — fit uses sparsity-preserving moment sums and
transform scales columns without densifying; centering a sparse input
raises, as in sklearn.  MinMaxScaler is dense-only (its affine shift
destroys sparsity).
"""

from __future__ import annotations

import numpy as np

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array


def _is_sparse(x):
    from dislib_tpu.data.sparse import SparseArray
    return isinstance(x, SparseArray)


class StandardScaler(BaseEstimator):
    """Standardise features to zero mean / unit variance.

    Attributes: mean_ (Array 1×n), var_ (Array 1×n).
    """

    def __init__(self, with_mean=True, with_std=True):
        self.with_mean = with_mean
        self.with_std = with_std

    def fit(self, x: Array, y=None):
        if _is_sparse(x):
            if self.with_mean:
                raise ValueError(
                    "cannot center a SparseArray (densifies); use "
                    "with_mean=False or x.to_dense()")
            # one-pass moments are the sparse tradeoff (centering would
            # densify); acceptable exactly because with_mean=False use
            # implies data not far off the origin
            self.mean_ = x.mean(axis=0)
            ex2 = x.square().mean(axis=0)
            self.var_ = ex2 - self.mean_ * self.mean_
            return self
        m = x.shape[0]
        mean = x.mean(axis=0)
        # two-pass variance: mean((x-μ)²), biased (ddof=0) like the reference.
        # (the one-pass E[x²]−μ² form cancels catastrophically in float32 when
        # |μ| ≫ σ)
        xc = x - mean
        self.mean_ = mean
        self.var_ = (xc * xc).sum(axis=0) * (1.0 / m)
        return self

    def fit_transform(self, x: Array, y=None) -> Array:
        return self.fit(x).transform(x)

    def _scale_array(self) -> Array:
        """`_safe_sqrt(var_)` cached by var_ identity: the derived array
        costs a pad kernel + eager sqrt program to build — once per fit,
        not once per transform (the serving hot path calls transform per
        request batch, where the rebuild was a hidden per-call dispatch)."""
        cached = getattr(self, "_scale_cache", None)
        if cached is None or cached[0] is not self.var_:
            self._scale_cache = (self.var_, _safe_sqrt(self.var_))
        return self._scale_cache[1]

    def transform(self, x: Array) -> Array:
        self._check_fitted()
        if _is_sparse(x):
            if self.with_mean:
                raise ValueError("cannot center a SparseArray")
            if not self.with_std:
                return x
            return x.scale_cols(1.0 / _sqrt_vec(self.var_))
        out = x
        if self.with_mean:
            out = out - self.mean_
        if self.with_std:
            out = out / self._scale_array()
        return out

    def inverse_transform(self, x: Array) -> Array:
        self._check_fitted()
        if _is_sparse(x):
            if self.with_mean:
                raise ValueError("cannot center a SparseArray")
            if not self.with_std:
                return x
            return x.scale_cols(_sqrt_vec(self.var_))
        out = x
        if self.with_std:
            out = out * self._scale_array()
        if self.with_mean:
            out = out + self.mean_
        return out

    def _check_fitted(self):
        if not hasattr(self, "mean_"):
            raise RuntimeError("StandardScaler is not fitted")


class MinMaxScaler(BaseEstimator):
    """Scale features to a [lo, hi] range (reference parity: feature_range)."""

    def __init__(self, feature_range=(0, 1)):
        self.feature_range = feature_range

    def fit(self, x: Array, y=None):
        if _is_sparse(x):
            raise TypeError("MinMaxScaler is dense-only (its affine shift "
                            "densifies); use x.to_dense()")
        self.data_min_ = x.min(axis=0)
        self.data_max_ = x.max(axis=0)
        return self

    def fit_transform(self, x: Array, y=None) -> Array:
        return self.fit(x).transform(x)

    def _range_array(self) -> Array:
        """`_nonzero(max - min)` cached by the (min_, max_) identities —
        same per-transform rebuild cost story as StandardScaler's scale."""
        cached = getattr(self, "_range_cache", None)
        key = (self.data_min_, self.data_max_)
        if cached is None or cached[0][0] is not key[0] \
                or cached[0][1] is not key[1]:
            self._range_cache = (key,
                                 _nonzero(self.data_max_ - self.data_min_))
        return self._range_cache[1]

    def transform(self, x: Array) -> Array:
        self._check_fitted()
        lo, hi = self.feature_range
        scaled = (x - self.data_min_) / self._range_array()
        return scaled * (hi - lo) + float(lo)

    def inverse_transform(self, x: Array) -> Array:
        self._check_fitted()
        lo, hi = self.feature_range
        return (x - float(lo)) / (hi - lo) * self._range_array() + self.data_min_

    def _check_fitted(self):
        if not hasattr(self, "data_min_"):
            raise RuntimeError("MinMaxScaler is not fitted")


def _sqrt_vec(v: Array):
    """1-D jnp vector of sqrt(max(v, 0)) with zeros → 1 (no-op scale)."""
    import jax.numpy as jnp
    d = jnp.sqrt(jnp.maximum(v._data[: 1, : v._shape[1]].reshape(-1), 0.0))
    return jnp.where(d == 0.0, 1.0, d)


def _safe_sqrt(v: Array) -> Array:
    """`_sqrt_vec` as a padded (1, n) Array (dense transform shape)."""
    from dislib_tpu.data.array import _repad
    d = _sqrt_vec(v).reshape(1, -1)
    return Array(_repad(d, v._shape), v._shape, v._reg_shape)


def _nonzero(v: Array) -> Array:
    import jax.numpy as jnp
    from dislib_tpu.data.array import _zero_pad
    d = jnp.where(v._data == 0.0, 1.0, v._data)
    return Array(_zero_pad(d, v._shape), v._shape, v._reg_shape)
