"""Feature scalers (reference: `dislib/preprocessing` — blocked mean/var or
min/max partial sums in fit, per-block affine transform tasks in transform /
inverse_transform; SURVEY.md §3.3).

TPU-native: fit statistics are the Array reductions (one psum over the row
axis); transform is a broadcasted elementwise op on the sharded data — no
communication at all.
"""

from __future__ import annotations

import numpy as np

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array


class StandardScaler(BaseEstimator):
    """Standardise features to zero mean / unit variance.

    Attributes: mean_ (Array 1×n), var_ (Array 1×n).
    """

    def __init__(self, with_mean=True, with_std=True):
        self.with_mean = with_mean
        self.with_std = with_std

    def fit(self, x: Array, y=None):
        m = x.shape[0]
        mean = x.mean(axis=0)
        # two-pass variance: mean((x-μ)²), biased (ddof=0) like the reference.
        # (the one-pass E[x²]−μ² form cancels catastrophically in float32 when
        # |μ| ≫ σ)
        xc = x - mean
        self.mean_ = mean
        self.var_ = (xc * xc).sum(axis=0) * (1.0 / m)
        return self

    def fit_transform(self, x: Array, y=None) -> Array:
        return self.fit(x).transform(x)

    def transform(self, x: Array) -> Array:
        self._check_fitted()
        out = x
        if self.with_mean:
            out = out - self.mean_
        if self.with_std:
            out = out / _safe_sqrt(self.var_)
        return out

    def inverse_transform(self, x: Array) -> Array:
        self._check_fitted()
        out = x
        if self.with_std:
            out = out * _safe_sqrt(self.var_)
        if self.with_mean:
            out = out + self.mean_
        return out

    def _check_fitted(self):
        if not hasattr(self, "mean_"):
            raise RuntimeError("StandardScaler is not fitted")


class MinMaxScaler(BaseEstimator):
    """Scale features to a [lo, hi] range (reference parity: feature_range)."""

    def __init__(self, feature_range=(0, 1)):
        self.feature_range = feature_range

    def fit(self, x: Array, y=None):
        self.data_min_ = x.min(axis=0)
        self.data_max_ = x.max(axis=0)
        return self

    def fit_transform(self, x: Array, y=None) -> Array:
        return self.fit(x).transform(x)

    def transform(self, x: Array) -> Array:
        self._check_fitted()
        lo, hi = self.feature_range
        rng = self.data_max_ - self.data_min_
        scaled = (x - self.data_min_) / _nonzero(rng)
        return scaled * (hi - lo) + float(lo)

    def inverse_transform(self, x: Array) -> Array:
        self._check_fitted()
        lo, hi = self.feature_range
        rng = self.data_max_ - self.data_min_
        return (x - float(lo)) / (hi - lo) * _nonzero(rng) + self.data_min_

    def _check_fitted(self):
        if not hasattr(self, "data_min_"):
            raise RuntimeError("MinMaxScaler is not fitted")


def _safe_sqrt(v: Array) -> Array:
    import jax.numpy as jnp
    from dislib_tpu.data.array import _zero_pad
    d = jnp.sqrt(jnp.maximum(v._data, 0.0))
    d = jnp.where(d == 0.0, 1.0, d)
    return Array(_zero_pad(d, v._shape), v._shape, v._reg_shape)


def _nonzero(v: Array) -> Array:
    import jax.numpy as jnp
    from dislib_tpu.data.array import _zero_pad
    d = jnp.where(v._data == 0.0, 1.0, v._data)
    return Array(_zero_pad(d, v._shape), v._shape, v._reg_shape)
