from dislib_tpu.preprocessing.scalers import StandardScaler, MinMaxScaler

__all__ = ["StandardScaler", "MinMaxScaler"]
