"""dislib_tpu.retrieval — the IVF-ANN candidate-retrieval tier
(ROADMAP item 3(b): million-item vector search from parts the library
already owns, served in one dispatch).

An exact ``NearestNeighbors`` ring pass is O(catalog) FLOPs per query
batch — the right tool for a training-time kNN graph, the wrong one for
a serving tier answering "which ~10 of a million catalog items is this
user embedding closest to" thousands of times a second.  The classic
answer is IVF (inverted-file) approximate search: cluster the catalog
once (coarse quantizer), keep one *inverted list* of catalog vectors per
centroid, and at query time scan only the ``nprobe`` lists whose
centroids are nearest — O(nprobe · list) work for recall@10 ≥ 0.95.

Every part is something the library already owns:

- **coarse quantizer** = :class:`~dislib_tpu.cluster.KMeans`, driven by
  the chunked fit loop (checkpoint/rollback/elastic resume apply to
  index builds for free);
- **inverted lists** = the ``ShardedSparse`` pad discipline: rectangular
  per-shard buffers with sentinel pads and slot<count masks, every
  length host-computed so no device sync ever decides a shape;
- **the scan** = the ring top-k idiom (``ops/ring.ring_kneighbors``)
  riding ``ops/overlap.panel_pipeline`` under the ``DSLIB_OVERLAP``
  router — db/seq schedules bit-equal, ONE jitted ``shard_map`` for the
  whole probe→gather→score→merge path (full-program-compilation
  discipline, arXiv:1810.09868);
- **serving** = :class:`RetrievalPipeline` through the ``PredictServer``
  bucket ladder, bundled by ``serving.bundle.export_bundle`` so a fresh
  process answers ``[ids | scores]`` rows with zero retraces.

See the user guide's "Vector retrieval serving" section for the index
layout, the nprobe/recall trade-off, and the pad-waste knob
(``DSLIB_IVF_LIST_QUANTUM``).
"""

from dislib_tpu.retrieval.ivf import IVFIndex
from dislib_tpu.retrieval.serving import RetrievalPipeline

__all__ = ["IVFIndex", "RetrievalPipeline"]
