"""Retrieval serving — IVF search through the PredictServer bucket
ladder and the AOT deployment-bundle path.

:class:`RetrievalPipeline` is the ``pipeline=`` drop-in for
:class:`~dislib_tpu.serving.server.PredictServer`: a request row is a
query embedding (``n_features = index.d``), a response row is
``[ids | scores]`` — the k retrieved catalog ids (float32-encoded,
exact below 2²⁴ — guarded at construction) followed by their k
distances.  ``predict_bucket`` is the dense serving contract: stage
into the bucket's padded canvas, ONE fused search dispatch
(``ivf_serve``), one blessed fetch, slice.

``capture_bucket`` is the deployment-bundle half: the serve kernel is a
``shard_map`` program (not a fusion-chain lazy array), so instead of
linearizing a deferred chain like ``ServePipeline``, the pipeline AOT
``lower().compile()``s its own kernel per bucket and hands
``serving.bundle.export_bundle`` the serialized executable plus its
operand leaves (query placeholder + the sharded list buffers +
centroids) — the artifact carries the WHOLE index, and a fresh process
serves retrieval with zero retraces through the standard
``load_bundle`` path.
"""

from __future__ import annotations

from functools import partial

import jax.numpy as jnp
import numpy as np

from dislib_tpu.ops import overlap as _ov
from dislib_tpu.ops import precision as px
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.retrieval.ivf import IVFIndex, _ivf_topk
from dislib_tpu.runtime import fetch as _fetch
from dislib_tpu.serving.buckets import BucketTemplate
from dislib_tpu.utils import profiling as _prof

__all__ = ["RetrievalPipeline"]

_ID_CEIL = 1 << 24          # float32 carries integers exactly below this


@partial(_prof.profiled_jit, name="ivf_serve",
         static_argnames=("mesh", "k", "nprobe", "cap", "overlap",
                          "policy"))
def _ivf_serve(qp, vecs, ids, vsq, offs, cnts, cents, mesh, k, nprobe, cap,
               overlap="db", policy=px.FLOAT32):
    # the serving response kernel: ONE output array so the bundle path's
    # single-leaf output contract holds ([ids | dists] rows, float32).
    # Padded query rows carry garbage — the host slice drops them.
    d2, idx = _ivf_topk(qp, vecs, ids, vsq, offs, cnts, cents, mesh=mesh,
                        k=k, nprobe=nprobe, cap=cap, overlap=overlap,
                        policy=policy)
    return jnp.concatenate([px.f32(idx), px.f32(jnp.sqrt(d2))], axis=1)


class RetrievalPipeline:
    """A fitted :class:`~dislib_tpu.retrieval.IVFIndex` served as
    ``[ids | scores]`` rows — the ``pipeline=`` drop-in for
    :class:`~dislib_tpu.serving.server.PredictServer` (same
    ``n_features`` / ``predict_bucket`` / ``out_cols`` surface as
    ``ServePipeline``, so micro-batching, the bucket ladder, tenancy,
    canaries, and quotas compose unchanged).

    Parameters
    ----------
    index : fitted :class:`IVFIndex`.
    k : int, default 10 — retrieved candidates per query; the response
        width is ``2·k``.
    nprobe : int or None — lists probed per query (None → the index's
        default).
    precision : policy for the scoring contractions (None → the
        ``DSLIB_MATMUL_PRECISION`` default).

    Unfillable slots carry id −1 and score +inf (same contract as
    ``IVFIndex.search``).
    """

    def __init__(self, index: IVFIndex, k=10, nprobe=None, precision=None):
        index._check_fitted()
        if index.n_items >= _ID_CEIL:
            raise ValueError("catalog ids ≥ 2^24 don't ride the float32 "
                             "[ids|scores] response encoding")
        self.index = index
        self.k = int(k)
        if self.k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        nprobe = index.nprobe if nprobe is None else int(nprobe)
        self.nprobe = max(1, min(nprobe, index.n_lists_))
        self.policy = px.resolve(precision)
        self.n_features = int(index.d)
        self.out_cols = 2 * self.k
        self._templates: dict[int, BucketTemplate] = {}
        self._templates_key = None      # (mesh shape, quantum) they fit

    def _pshape(self, bucket: int):
        from dislib_tpu.data.array import _padded_shape
        return _padded_shape((bucket, self.n_features),
                             _mesh.pad_quantum())

    def _template(self, bucket: int) -> BucketTemplate:
        # canvases are PAD-QUANTUM-shaped, and the quantum follows the
        # mesh: when the mesh moved under us (the index auto-rebinds in
        # ``_check_fitted`` — round 20's capacity heal), a cached canvas
        # would stage queries into the OLD pad and every request would
        # tear on a shape mismatch.  Key the cache on the mesh epoch.
        key = (_mesh.mesh_shape(_mesh.get_mesh()), _mesh.pad_quantum())
        if key != self._templates_key:
            self._templates.clear()
            self._templates_key = key
        tmpl = self._templates.get(bucket)
        if tmpl is None:
            tmpl = self._templates[bucket] = BucketTemplate(
                self._pshape(bucket))
        return tmpl

    def rebind_mesh(self, mesh):
        """Elastic rebind (round 20): delegate the index's re-stripe,
        then drop the bucket canvases — their padded shapes follow the
        mesh quantum, so a stale template would stage queries into the
        wrong pad.  This is what ``PredictServer(elastic=...)`` wraps,
        and what ``fitloop.data_rebind`` finds on a retrieval holder."""
        rebound = self.index.rebind_mesh(mesh)
        if mesh is not None and rebound:
            self._templates.clear()
        return rebound

    def _kernel_args(self, dev):
        ix = self.index
        return ((dev, ix._vecs, ix._ids, ix._vsq, ix._offs, ix._cnts,
                 ix._cents),
                dict(mesh=_mesh.get_mesh(), k=self.k, nprobe=self.nprobe,
                     cap=ix._cap, policy=self.policy))

    def predict_bucket(self, rows: np.ndarray, bucket: int) -> np.ndarray:
        """Serve one query batch: stage into the bucket canvas, ONE
        fused IVF search dispatch, one blessed fetch, slice — the dense
        ``ServePipeline.predict_bucket`` contract."""
        import jax
        self.index._check_fitted()
        rows = np.asarray(rows, np.float32)
        if rows.ndim == 1:
            rows = rows.reshape(1, -1)
        if rows.shape[1] != self.n_features:
            raise ValueError(f"request has {rows.shape[1]} features, the "
                             f"index holds {self.n_features}")
        if rows.shape[0] > bucket:
            raise ValueError(f"{rows.shape[0]} rows exceed bucket {bucket}")
        buf = self._template(bucket).fill(rows)
        dev = jax.device_put(buf, _mesh.data_sharding())
        sched = _ov.resolve()
        _prof.count_schedule("ivf_search", sched)
        args, kw = self._kernel_args(dev)
        out = _ivf_serve(*args, overlap=sched, **kw)
        host = _fetch(out)                  # force: ONE fused dispatch
        return host[: rows.shape[0], : self.out_cols]

    # -- deployment-bundle capture ------------------------------------------

    def capture_bucket(self, bucket: int) -> dict:
        """AOT-capture this bucket's serve program for
        :func:`~dislib_tpu.serving.bundle.export_bundle` WITHOUT
        executing it: ``lower().compile()`` the serve kernel on a
        placeholder query canvas and serialize the compiled executable.
        The operand leaves are the placeholder (the input slot) plus the
        index's sharded list buffers and centroids — the bundle carries
        the WHOLE index, so ``load_bundle`` serves retrieval in a fresh
        process with zero retraces."""
        import jax
        from jax.experimental.serialize_executable import serialize
        self.index._check_fitted()
        pshape = self._pshape(bucket)
        placeholder = jax.device_put(np.zeros(pshape, np.float32),
                                     _mesh.data_sharding())
        sched = _ov.resolve()
        args, kw = self._kernel_args(placeholder)
        # .lower counts a trace, never a dispatch (profiled_jit contract)
        compiled = _ivf_serve.lower(*args, overlap=sched, **kw).compile()
        payload, _in_tree, out_tree = serialize(compiled)
        canon = [jnp.asarray(leaf) for leaf in args]
        return {
            "payload": np.frombuffer(payload, np.uint8),
            "leaves": canon,
            "input_slot": 0,
            "n_outs": out_tree.num_leaves,
            "out_cols": self.out_cols,
            "pshape": list(pshape),
        }
