"""IVF-ANN index: KMeans coarse quantizer + striped inverted lists +
ONE fused probe→gather→score→merge search dispatch.

**Index layout (the tentpole's data structure).**  ``fit`` clusters the
catalog with the library's own :class:`~dislib_tpu.cluster.KMeans`
(chunked-fit-loop driven — index builds inherit checkpoint/rollback and
elastic resume), then lays the inverted lists out HOST-side (no device
sync ever decides a shape) as rectangular per-shard buffers in the
``ShardedSparse`` pad discipline:

- every list's entries are **striped round-robin over the mesh row
  shards** (entry rank j of list ℓ lands on shard ``j % p``), so every
  shard holds a ~1/p sub-list of EVERY list.  Striping kills the two
  classic IVF layout pathologies at once: the static scan width per ring
  step is ``cap ≈ max_list/p`` instead of ``max_list`` (a probe costs
  nprobe·cap·d FLOPs per step, p steps — total ≈ nprobe·max_list·d, no
  p× replication of masked work), and list-length skew load-balances
  itself (a hot list's entries spread over all shards);
- each (shard, list) sub-list pads to a multiple of the
  ``DSLIB_IVF_LIST_QUANTUM`` pad quantum (default 8) — the skew knob:
  bigger quantum = fewer distinct list offsets (friendlier gathers),
  more pad slots.  The measured cost lives in :attr:`IVFIndex.pad_waste`;
- pad slots carry sentinel id −1, zero vectors, zero norms, and every
  scan masks ``slot < count | id < 0`` — pads are provably
  non-load-bearing (the poisoned-slot regression in
  ``tests/test_retrieval.py`` fills them with garbage per schedule).

**Search (ONE dispatch).**  ``search`` is a single profiled jitted
``shard_map``: centroid-distance GEMM → static ``lax.top_k`` over
``nprobe`` → per-ring-step masked gather of the probed sub-lists →
scored partial top-k → cross-shard merge on the
:func:`~dislib_tpu.ops.ring.ring_kneighbors` idiom.  The ring step loop
rides :func:`~dislib_tpu.ops.overlap.panel_pipeline` under the
``DSLIB_OVERLAP`` router (db/seq bit-equal by construction, routing
observable as ``ivf_search:<sched>`` schedule counters), contractions
route through the precision policy layer (``precision=``), and the
kernel emits ALREADY-PADDED ``(mq_pad, k_pad)`` outputs with zeroed pad
regions so the host wrapper is ``Array._from_logical_padded`` — no
repad dispatch, exactly one program per search call.

``nprobe = n_lists`` scans every list exactly once — the exact
kneighbors result (up to top-k tie order) through the same program.
"""

from __future__ import annotations

import math
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from dislib_tpu.data.array import (Array, _padded_shape, array as _mk_array,
                                   ensure_canonical)
from dislib_tpu.ops import overlap as _ov
from dislib_tpu.ops import precision as px
from dislib_tpu.ops.base import precise
from dislib_tpu.ops.ring import _rotate
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.utils import profiling as _prof

__all__ = ["IVFIndex"]

_DEFAULT_LIST_QUANTUM = 8

# candidate columns gathered per probe-chunk merge: the static bound on
# the scan's live gather panel (mq_loc × ~this × d_loc elements)
_PROBE_BLOCK = 1024


def _list_quantum(explicit=None) -> int:
    """The skew/pad knob: explicit wins, else ``DSLIB_IVF_LIST_QUANTUM``,
    else 8 (measured waste for any choice lands in ``pad_waste``)."""
    if explicit is not None:
        q = int(explicit)
    else:
        q = int(os.environ.get("DSLIB_IVF_LIST_QUANTUM",
                               str(_DEFAULT_LIST_QUANTUM)))
    if q < 1:
        raise ValueError(f"list quantum must be >= 1, got {q}")
    return q


@partial(jax.jit, static_argnames=("mesh", "k", "nprobe", "cap", "overlap",
                                   "policy"))
@precise
def _ivf_topk(qp, vecs, ids, vsq, offs, cnts, cents, mesh, k, nprobe, cap,
              overlap="db", policy=px.FLOAT32):
    """(d², catalog ids) of the approx k nearest catalog rows per padded
    query row — the fused IVF scan (plain ``jax.jit``: invoked from the
    outer profiled kernels, which own the dispatch-count boundary).

    Unfillable slots (fewer than k live candidates in the probed lists)
    carry distance +inf and id −1.
    """
    nrows = mesh.shape[_mesh.ROWS]

    def local(q, v, i, s, of, cn, ce):
        e_pad = v.shape[0]
        # full squared norms (features col-sharded → psum over 'cols')
        q_sq = lax.psum(jnp.sum(q * q, axis=1), _mesh.COLS)
        # -- phase 1: coarse quantizer — centroid distances, static top-k
        c_sq = lax.psum(jnp.sum(ce * ce, axis=1), _mesh.COLS)
        if overlap == "pallas":
            from dislib_tpu.ops import pallas_kernels as _pk
            cpart = lax.psum(_pk.panel_gemm(q, ce.T), _mesh.COLS)
        else:
            cpart = lax.psum(px.pdot(q, ce.T, policy), _mesh.COLS)
        cd = q_sq[:, None] - 2.0 * cpart + c_sq[None, :]
        _, probes = lax.top_k(-cd, nprobe)          # (mq_loc, nprobe)

        # -- phase 2: ring scan of the probed striped sub-lists.
        # Probes are scanned in CHUNKS of pc lists — one fused gather +
        # einsum + top-k merge per chunk instead of one per probe: big
        # ops amortize per-op latency (the whole point of the tier),
        # while the chunk width keeps the gathered panel's live memory
        # statically bounded at ~mq_loc × _PROBE_BLOCK × d_loc.
        of0, cn0 = of[0], cn[0]                     # (nlist,) this shard
        perm = [(r, (r + 1) % nrows) for r in range(nrows)]

        def fetch(t, prev):
            return _rotate(perm, *prev)     # one ICI hop per carried array

        pan0 = (v, i, s, of0, cn0)
        pc = max(1, min(nprobe, _PROBE_BLOCK // max(cap, 1)))
        n_chunks = -(-nprobe // pc)
        npb = n_chunks * pc
        # chunk padding repeats probe slots — masked dead below so a
        # duplicated list can never seat the same entry twice in the top-k
        probes_p = jnp.pad(probes, ((0, 0), (0, npb - nprobe)))
        probe_ok = lax.broadcasted_iota(jnp.int32, (1, npb), 1) < nprobe
        slot_iota = lax.broadcasted_iota(jnp.int32, (1, 1, cap), 2)
        acc_dt = jnp.promote_types(q.dtype, v.dtype)

        def consume(t, carry, pan):
            pv, pi, ps, pof, pcn = pan

            def chunk_body(r, acc):
                best_d, best_i = acc
                pr = lax.dynamic_slice_in_dim(probes_p, r * pc, pc,
                                              axis=1)      # (mq_loc, pc)
                ok = lax.dynamic_slice_in_dim(probe_ok, r * pc, pc,
                                              axis=1)       # (1, pc)
                off = pof[pr]
                cnt = jnp.where(ok, pcn[pr], 0)
                ridx = jnp.clip(off[:, :, None] + slot_iota, 0, e_pad - 1)
                flat = ridx.reshape(q.shape[0], pc * cap)
                g = jnp.take(pv, flat, axis=0)  # (mq_loc, pc·cap, d_loc)
                gi = jnp.take(pi, flat, axis=0)
                gs = jnp.take(ps, flat, axis=0)
                cross = lax.psum(px.peinsum("qd,qcd->qc", q, g, policy),
                                 _mesh.COLS)
                d2 = q_sq[:, None] - 2.0 * cross + gs
                # the pad/ownership mask: a slot is live iff it is below
                # its list's count on THIS shard and not a sentinel pad
                live = (slot_iota < cnt[:, :, None]).reshape(
                    q.shape[0], pc * cap) & (gi >= 0)
                d2 = jnp.where(live, d2, jnp.inf)
                cand_d = jnp.concatenate(
                    [best_d, d2.astype(best_d.dtype)], axis=1)
                cand_i = jnp.concatenate([best_i, gi], axis=1)
                neg, pos = lax.top_k(-cand_d, k)
                return -neg, jnp.take_along_axis(cand_i, pos, axis=1)

            if n_chunks == 1:
                return chunk_body(0, carry)
            return lax.fori_loop(0, n_chunks, chunk_body, carry)

        # constant top-k seeds become row-varying on the first merge;
        # declaring it up front keeps check_vma provable (ring idiom)
        acc0 = (lax.pcast(jnp.full((q.shape[0], k), jnp.inf, acc_dt),
                          (_mesh.ROWS,), to="varying"),
                lax.pcast(jnp.full((q.shape[0], k), -1, jnp.int32),
                          (_mesh.ROWS,), to="varying"))
        best_d, best_i = _ov.panel_pipeline(nrows, pan0, fetch, consume,
                                            acc0, _ov.overlapped(overlap))
        return jnp.maximum(best_d, 0.0), best_i

    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(_mesh.ROWS, _mesh.COLS),     # queries
                  P(_mesh.ROWS, _mesh.COLS),     # striped list vectors
                  P(_mesh.ROWS),                 # entry ids (−1 = pad)
                  P(_mesh.ROWS),                 # entry ‖x‖²
                  P(_mesh.ROWS, None),           # per-shard list offsets
                  P(_mesh.ROWS, None),           # per-shard list counts
                  P(None, _mesh.COLS)),          # centroids
        out_specs=(P(_mesh.ROWS, None), P(_mesh.ROWS, None)),
        check_vma=True,
    )(qp, vecs, ids, vsq, offs, cnts, cents)


@partial(_prof.profiled_jit, name="ivf_search",
         static_argnames=("mesh", "k", "k_pad", "nprobe", "cap", "mq",
                          "overlap", "policy"))
def _ivf_search(qp, vecs, ids, vsq, offs, cnts, cents, mesh, k, k_pad,
                nprobe, cap, mq, overlap="db", policy=px.FLOAT32):
    # profiled: this is THE host dispatch boundary — one program per
    # search call (counter-asserted).  The kernel pads its own output to
    # (mq_pad, k_pad) with zeroed pad regions, so the host wrapper is
    # Array._from_logical_padded directly — no repad dispatch.
    d2, idx = _ivf_topk(qp, vecs, ids, vsq, offs, cnts, cents, mesh=mesh,
                        k=k, nprobe=nprobe, cap=cap, overlap=overlap,
                        policy=policy)
    dist = jnp.sqrt(d2)                  # d² ≥ 0 by the kernel's clamp
    valid_q = lax.broadcasted_iota(jnp.int32, (dist.shape[0], 1), 0) < mq
    dist = jnp.where(valid_q, dist, 0.0)
    idx = jnp.where(valid_q, idx, 0)
    if k_pad > k:
        dist = jnp.pad(dist, ((0, 0), (0, k_pad - k)))
        idx = jnp.pad(idx, ((0, 0), (0, k_pad - k)))
    return dist, idx


class IVFIndex:
    """Inverted-file ANN index over a catalog of item vectors.

    Parameters
    ----------
    n_lists : int or None — inverted-list count (the KMeans cluster
        count).  None → ``round(sqrt(n_items))`` at fit time, the
        classic IVF heuristic.
    nprobe : int, default 8 — lists scanned per query (the recall/speed
        dial; ``search`` accepts a per-call override).
    list_quantum : int or None — per-(shard, list) pad quantum; None →
        ``DSLIB_IVF_LIST_QUANTUM`` (default 8).
    kmeans_max_iter, random_state, verbose — forwarded to the coarse
        quantizer's :class:`~dislib_tpu.cluster.KMeans`.

    Attributes
    ----------
    quantizer_ : the fitted KMeans (None when built through the layout
        seam ``_build``).
    n_lists_, n_items, d : fitted geometry.
    pad_waste : dict — the measured layout overhead: logical
        ``entries``, device ``buffer_rows``, quantum pad and
        shard-balance pad split out, ``waste_frac``, the static scan
        width ``cap``, and per-shard entry totals.
    """

    def __init__(self, n_lists=None, nprobe=8, list_quantum=None,
                 kmeans_max_iter=10, random_state=None, verbose=False):
        self.n_lists = None if n_lists is None else int(n_lists)
        self.nprobe = int(nprobe)
        self.list_quantum = None if list_quantum is None \
            else int(list_quantum)
        self.kmeans_max_iter = int(kmeans_max_iter)
        self.random_state = random_state
        self.verbose = verbose
        self.quantizer_ = None

    # -- build ---------------------------------------------------------------

    def fit(self, items, y=None, checkpoint=None, health=None):
        """Build the index: KMeans coarse quantizer (chunked-fit-loop
        driven — ``checkpoint=``/``health=`` buy rollback and elastic
        resume exactly as for any estimator fit), one labels pass, then
        the host-computed striped layout.  Offline by definition: the
        build syncs; the search path never does."""
        from dislib_tpu.cluster import KMeans
        arr = items if isinstance(items, Array) \
            else _mk_array(np.atleast_2d(np.asarray(items)))
        arr = ensure_canonical(arr)
        n = arr.shape[0]
        if n < 1:
            raise ValueError("cannot index an empty catalog")
        nlist = self.n_lists if self.n_lists is not None \
            else max(1, int(round(math.sqrt(n))))
        nlist = min(int(nlist), n)
        km = KMeans(n_clusters=nlist, max_iter=self.kmeans_max_iter,
                    random_state=self.random_state, verbose=self.verbose)
        km.fit(arr, checkpoint=checkpoint, health=health)
        labels = km.predict(arr).collect().ravel()
        self._build(arr.collect(), labels, km.centers_)
        self.quantizer_ = km
        return self

    def _build(self, items_h, labels_h, centers_h):
        """The striped-layout seam (host data in, device buffers out) —
        ``fit`` lands here, and tests craft labels/centroids through it
        (empty lists, x64 catalogs) without a KMeans run.

        All lengths/offsets are host numpy; nothing here reads a device
        value, so no sync ever decides a shape."""
        mesh = _mesh.get_mesh()
        p, c = _mesh.mesh_shape(mesh)
        mq_quant = _mesh.pad_quantum(mesh)
        items_h = np.atleast_2d(np.asarray(items_h))
        labels_h = np.asarray(labels_h).ravel().astype(np.int64)
        centers_h = np.atleast_2d(np.asarray(centers_h))
        n, d = items_h.shape
        nlist = centers_h.shape[0]
        if labels_h.shape[0] != n:
            raise ValueError(f"{n} items but {labels_h.shape[0]} labels")
        if centers_h.shape[1] != d:
            raise ValueError(f"centroid width {centers_h.shape[1]} != "
                             f"item width {d}")
        if n and (labels_h.min() < 0 or labels_h.max() >= nlist):
            raise ValueError(f"labels must lie in [0, {nlist})")
        quantum = _list_quantum(self.list_quantum)
        dtype = items_h.dtype if np.issubdtype(items_h.dtype, np.floating) \
            else np.dtype(np.float32)
        d_pad = _padded_shape((1, d), mq_quant)[1]

        # striped sub-list lengths: entry rank j of list ℓ → shard j % p
        counts_l = np.bincount(labels_h, minlength=nlist)      # (nlist,)
        sh = np.arange(p, dtype=np.int64)
        cnt_ls = np.clip((counts_l[:, None] - sh[None, :] + p - 1) // p,
                         0, None)                              # (nlist, p)
        pad_ls = -(-cnt_ls // quantum) * quantum
        cap = max(int(pad_ls.max(initial=0)), quantum)
        offs_ls = np.zeros((nlist, p), np.int64)
        offs_ls[1:] = np.cumsum(pad_ls, axis=0)[:-1]
        shard_tot = pad_ls.sum(axis=0)                         # (p,)
        e_pad = max(int(shard_tot.max(initial=0)), cap)

        # vectorized fill: order entries by (list, original id), compute
        # each entry's (shard, slot) in closed form, scatter once
        order = np.argsort(labels_h, kind="stable")
        lbl_sorted = labels_h[order]
        starts = np.zeros(nlist + 1, np.int64)
        starts[1:] = np.cumsum(counts_l)
        rank = np.arange(n, dtype=np.int64) - starts[lbl_sorted]
        shard = rank % p
        slot = offs_ls[lbl_sorted, shard] + rank // p
        vecs_h = np.zeros((p, e_pad, d_pad), dtype)
        ids_h = np.full((p, e_pad), -1, np.int32)
        vecs_h[shard, slot, :d] = items_h[order]     # ndarray-assign casts
        ids_h[shard, slot] = order
        vsq_h = np.einsum("sed,sed->se", vecs_h, vecs_h)  # pads stay 0
        cents_h = np.zeros((nlist, d_pad), dtype)
        cents_h[:, :d] = centers_h

        self._vecs = jax.device_put(vecs_h.reshape(p * e_pad, d_pad),
                                    _mesh.data_sharding(mesh))
        self._ids = jax.device_put(ids_h.reshape(p * e_pad),
                                   NamedSharding(mesh, P(_mesh.ROWS)))
        self._vsq = jax.device_put(vsq_h.reshape(p * e_pad),
                                   NamedSharding(mesh, P(_mesh.ROWS)))
        self._offs = jax.device_put(
            np.ascontiguousarray(offs_ls.T).astype(np.int32),
            NamedSharding(mesh, P(_mesh.ROWS, None)))
        self._cnts = jax.device_put(
            np.ascontiguousarray(cnt_ls.T).astype(np.int32),
            NamedSharding(mesh, P(_mesh.ROWS, None)))
        self._cents = jax.device_put(cents_h,
                                     NamedSharding(mesh, P(None, _mesh.COLS)))
        self._cap = int(cap)
        self.d = int(d)
        self.n_items = int(n)
        self.n_lists_ = int(nlist)
        self._fitted_mesh = (p, c)
        self._fitted_quantum = int(mq_quant)
        # elastic rebind seam (round 20): the striped buffers above are
        # mesh-SHAPED, so a capacity resize invalidates them — keep the
        # host-side layout inputs (they were already materialized to
        # build from; no extra peak) and rebind_mesh() re-stripes onto
        # whatever mesh the elastic rung lands on
        self._items_h = items_h
        self._labels_h = labels_h
        self._centers_h = centers_h
        list_pad = int(pad_ls.sum() - counts_l.sum())
        self.pad_waste = {
            "entries": int(n),
            "buffer_rows": int(p * e_pad),
            "list_pad_entries": list_pad,
            "balance_pad_rows": int(p * e_pad - pad_ls.sum()),
            "waste_frac": float(1.0 - n / float(p * e_pad)),
            "cap": int(cap),
            "quantum": int(quantum),
            "per_shard_entries": [int(v) for v in cnt_ls.sum(axis=0)],
        }
        return self

    def rebind_mesh(self, mesh) -> bool:
        """The elastic rebind hook (``fitloop.data_rebind`` delegates
        here): re-stripe the inverted lists onto the CURRENT mesh from
        the retained host layout inputs.  ``mesh=None`` (the driver's
        pre-switch "force pending work" phase) is a no-op — the index
        buffers are committed arrays, nothing is pending.  Returns True
        when a re-layout actually happened (counted
        ``retrieval_rebinds``)."""
        if mesh is None or getattr(self, "n_items", None) is None:
            return False
        now = _mesh.mesh_shape(_mesh.get_mesh())
        if now == self._fitted_mesh and \
                _mesh.pad_quantum(_mesh.get_mesh()) == self._fitted_quantum:
            return False
        if getattr(self, "_items_h", None) is None:
            raise RuntimeError(
                f"IVFIndex was built on mesh {self._fitted_mesh} but the "
                f"current mesh is {now}, and the host layout inputs were "
                "dropped — refit (or rebuild via _build) on the new mesh")
        self._build(self._items_h, self._labels_h, self._centers_h)
        from dislib_tpu.utils.profiling import count_resilience
        count_resilience("retrieval_rebinds")
        return True

    def _check_fitted(self):
        if getattr(self, "n_items", None) is None:
            raise RuntimeError("IVFIndex is not fitted — call fit() first")
        mesh = _mesh.get_mesh()
        now = _mesh.mesh_shape(mesh)
        if now != self._fitted_mesh \
                or _mesh.pad_quantum(mesh) != self._fitted_quantum:
            # a capacity resize moved the mesh under us: the striped
            # list buffers are mesh-shaped, so re-stripe from the host
            # layout inputs (round 20 — heals like every other
            # estimator) rather than refusing to serve
            if getattr(self, "_items_h", None) is not None:
                self.rebind_mesh(mesh)
                return
            raise RuntimeError(
                f"IVFIndex was built on mesh {self._fitted_mesh} (quantum "
                f"{self._fitted_quantum}) but the current mesh is {now} "
                f"(quantum {_mesh.pad_quantum(mesh)}) — the striped list "
                "buffers are mesh-shaped; refit (or rebuild via _build) "
                "on the new mesh")

    # -- query ---------------------------------------------------------------

    def search(self, queries, k=10, nprobe=None, precision=None,
               overlap=None):
        """Approximate k-nearest catalog rows per query — ONE fused
        dispatch for the whole probe→gather→score→merge path.

        Returns ``(distances, ids)`` — both ``(n_queries, k)`` ds-arrays
        (euclidean distance, int32 catalog row ids), nearest first.
        Slots the probed lists could not fill carry id −1 and distance
        +inf.  ``nprobe=n_lists_`` scans everything (exact up to top-k
        tie order); ``precision=``/``overlap=`` route through the policy
        layer and the ``DSLIB_OVERLAP`` schedule router.
        """
        self._check_fitted()
        q = queries if isinstance(queries, Array) \
            else _mk_array(np.atleast_2d(np.asarray(queries)))
        q = ensure_canonical(q)
        if q.shape[1] != self.d:
            raise ValueError(f"queries have {q.shape[1]} features, the "
                             f"index holds {self.d}")
        k = int(k)
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        nprobe = self.nprobe if nprobe is None else int(nprobe)
        nprobe = max(1, min(nprobe, self.n_lists_))
        mq = q.shape[0]
        k_pad = _padded_shape((1, k), self._fitted_quantum)[1]
        # schedule resolved at this host boundary so a DSLIB_OVERLAP flip
        # retraces via the kernel static (observable via the counters)
        sched = _ov.resolve(overlap)
        _prof.count_schedule("ivf_search", sched)
        policy = px.resolve(precision)
        dist, idx = _ivf_search(q._data, self._vecs, self._ids, self._vsq,
                                self._offs, self._cnts, self._cents,
                                mesh=_mesh.get_mesh(), k=k, k_pad=k_pad,
                                nprobe=nprobe, cap=self._cap, mq=mq,
                                overlap=sched, policy=policy)
        return (Array._from_logical_padded(dist, (mq, k)),
                Array._from_logical_padded(idx, (mq, k)))
