"""dislib_tpu — a TPU-native distributed machine-learning library.

Capabilities of the reference (Alfredu/dislib — sklearn-style estimators over
one block-partitioned distributed 2-D array; see SURVEY.md), rebuilt TPU-first
on JAX/XLA: the ds-array is a sharded ``jax.Array`` on a named device mesh,
per-block NumPy kernels become jitted sharded compute, COMPSs arity-tree
reductions become ``lax.psum``/``all_gather`` over ICI, and convergence loops
run on-device in ``lax.while_loop``.

Public API parity contract: SURVEY.md §8 "API parity contract".
"""

import os as _os


def _cpu_destined() -> bool:
    """True when this process is headed for the cpu backend (explicit env
    or jax config) — the only case the timeout mutation below targets."""
    if "cpu" in _os.environ.get("JAX_PLATFORMS", ""):
        return True
    try:
        import jax as _j
        return "cpu" in (_j.config.jax_platforms or "")
    except Exception:  # noqa: BLE001 — unknown platform: leave flags alone
        return False


# XLA:CPU aborts the process when a collective participant waits >40 s
# (rendezvous terminate timeout).  On constrained hosts — this build's CI
# rig runs 8 virtual devices on ONE core — a long compile or any co-tenant
# load can legitimately stall a participant that long, turning a slow
# moment into a hard crash.  Raise the abort threshold well past plausible
# stalls (the warn log stays early).  Must be in XLA_FLAGS before the
# backend initialises, hence at import — and only for cpu-destined
# processes, so a TPU job's (or an embedding application's) environment
# is never mutated behind its back.  The injection itself lives in
# runtime.xla_flags (the one site allowed to mutate XLA_FLAGS) and is
# GATED on jaxlib version: builds that predate the flags treat them as
# fatal unknown flags and abort at first backend init.
from dislib_tpu.runtime import xla_flags as _xla_flags

if _cpu_destined():
    _xla_flags.inject_cpu_collective_timeouts()

# API-drift shims (jax.shard_map alias on older jaxlibs) — a preempted job
# may resume on a host imaged with a different toolchain, so importability
# across jax versions is part of the resilience contract
from dislib_tpu.runtime.compat import ensure_jax_compat as _ensure_jax_compat

_ensure_jax_compat()

from dislib_tpu.parallel.mesh import init, get_mesh, set_mesh
from dislib_tpu.data.array import (
    Array, array, random_array, zeros, full, ones, identity, eye,
    apply_along_axis, concat_rows, concat_cols, rechunk, ensure_canonical,
)
from dislib_tpu.data.io import (
    load_txt_file, load_svmlight_file, load_npy_file, load_mdcrd_file, save_txt,
    QuarantineLedger, QuarantineReport, last_quarantine_report,
    quarantine_ledger, quarantine_batch,
)
from dislib_tpu.data.sparse import SparseArray
from dislib_tpu.math import matmul, kron, svd, qr, polar
from dislib_tpu.ops.overlap import resolve as overlap_schedule
from dislib_tpu.decomposition import tsqr, random_svd, lanczos_svd, PCA
from dislib_tpu.utils.base import shuffle, train_test_split
from dislib_tpu.utils.saving import save_model, load_model

# subpackages (sklearn-style namespaces, reference parity; `runtime` is
# the preemption/retry/elastic resilience layer, `serving` the
# low-latency predict path with micro-batching and model hot-swap)
from dislib_tpu import cluster, classification, regression, neighbors, \
    preprocessing, optimization, model_selection, recommendation, \
    trees, runtime, serving, retrieval  # noqa: E402,F401

# estimator classes re-exported at top level so every name in the SURVEY §8
# parity contract is importable from `dislib_tpu` directly (their canonical
# homes stay the reference-parity submodules above)
from dislib_tpu.cluster import (KMeans, MiniBatchKMeans, GaussianMixture,
                                DBSCAN, Daura)
from dislib_tpu.classification import CascadeSVM, KNeighborsClassifier
from dislib_tpu.trees import (
    RandomForestClassifier, RandomForestRegressor,
    DecisionTreeClassifier, DecisionTreeRegressor,
)
from dislib_tpu.neighbors import NearestNeighbors
from dislib_tpu.regression import LinearRegression, Lasso
from dislib_tpu.optimization import ADMM
from dislib_tpu.recommendation import ALS
from dislib_tpu.preprocessing import StandardScaler, MinMaxScaler
from dislib_tpu.model_selection import (
    KFold, GridSearchCV, RandomizedSearchCV,
)

__version__ = "0.1.0"

__all__ = [
    "init", "get_mesh", "set_mesh",
    "Array", "array", "random_array", "zeros", "full", "ones", "identity",
    "eye", "apply_along_axis", "concat_rows", "concat_cols", "rechunk",
    "ensure_canonical", "SparseArray",
    "load_txt_file", "load_svmlight_file", "load_npy_file", "load_mdcrd_file",
    "save_txt",
    "QuarantineReport", "QuarantineLedger", "last_quarantine_report",
    "quarantine_ledger", "quarantine_batch",
    "matmul", "kron", "svd", "qr", "polar", "overlap_schedule",
    "tsqr", "random_svd", "lanczos_svd", "PCA",
    "shuffle", "train_test_split", "save_model", "load_model",
    "KMeans", "MiniBatchKMeans", "GaussianMixture", "DBSCAN", "Daura",
    "CascadeSVM", "KNeighborsClassifier",
    "RandomForestClassifier", "RandomForestRegressor",
    "DecisionTreeClassifier", "DecisionTreeRegressor",
    "NearestNeighbors", "LinearRegression", "Lasso", "ADMM", "ALS",
    "StandardScaler", "MinMaxScaler",
    "KFold", "GridSearchCV", "RandomizedSearchCV",
    "runtime", "serving", "retrieval",
]
