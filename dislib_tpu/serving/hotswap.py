"""ModelPool — serve checkpoint generation N while N+1 trains.

A trainer keeps saving fit state into a rotating
:class:`~dislib_tpu.utils.checkpoint.FitCheckpoint` (PR-1: atomic
renames, embedded checksums, keep-k generations).  The pool polls that
path and swaps the served pipeline through the ``runtime.adoption`` gate:

1. the checksum-verified ``load()`` — a torn or corrupt newest
   generation silently falls back to the previous good one, so the pool
   can never build a model from damaged bytes;
2. the **health-gated AOT warmup**: the candidate pipeline runs one zero
   batch through EVERY serving bucket (filling the program cache, so the
   post-swap hot path never compiles) and the concatenated outputs pass
   the PR-3 non-finite guard — a generation that predicts NaN is
   rejected with a typed :class:`~dislib_tpu.runtime.AdoptionRejected`
   and the pool keeps serving the old generation;
3. the swap itself is one atomic reference assignment — in-flight
   batches finish on the old pipeline, the next batch takes the new one.

All checkpoint reads go through :func:`dislib_tpu.runtime.adopt_latest`
— enforced by the adoption-gate lint in ``tests/test_serving.py``.
"""

from __future__ import annotations

import os
import threading
import time

from dislib_tpu.runtime import (AdoptionRejected, adopt_latest,
                                generation_token)
from dislib_tpu.serving.buckets import bucket_ladder
from dislib_tpu.serving.cache import ProgramCache


def _default_poll_s() -> float:
    return float(os.environ.get("DSLIB_SERVE_POLL_S", "0.25"))


class ModelPool:
    """The served-model slot, refreshed from a rotating checkpoint.

    Parameters
    ----------
    checkpoint : FitCheckpoint — the path a trainer rotates (the pool
        only ever reads; build a separate FitCheckpoint instance on the
        same path as the writer's, exactly as a cross-process reader
        would).
    build : callable(state_dict) -> ServePipeline — turn a verified
        snapshot into a servable pipeline.
    buckets : bucket ladder warmed (and health-gated) before every swap;
        default per :func:`~dislib_tpu.serving.buckets.bucket_ladder`.
    poll_interval_s : float — minimum seconds between disk polls
        (``DSLIB_SERVE_POLL_S``, default 0.25); :meth:`poll` calls inside
        the window are free no-ops, so the server can poll every batch.
    """

    def __init__(self, checkpoint, build, buckets=None,
                 poll_interval_s=None, name="serving"):
        self.checkpoint = checkpoint
        self.build = build
        self.buckets = bucket_ladder(buckets)
        self.poll_interval_s = _default_poll_s() \
            if poll_interval_s is None else float(poll_interval_s)
        self.name = name
        self.cache = ProgramCache()
        self.adoptions = 0
        self.rejections = 0
        self.last_rejection: Exception | None = None
        self._lock = threading.Lock()
        self._poll_lock = threading.Lock()  # serializes whole adoptions
        self._current = (None, None)        # (token, pipeline)
        self._last_poll = 0.0
        self._rejected_token = None         # don't re-gate a known-bad gen
        self._skip_token = None             # last no-op poll's disk state
        self._adopted_mtime = None          # monotonicity floor (adoption)

    # -- the served slot ----------------------------------------------------

    def current(self):
        """Atomic read of ``(generation_token, pipeline)``; pipeline is
        None until the first successful adoption."""
        return self._current

    @property
    def adopting(self) -> bool:
        """True while some thread is inside an adoption attempt (its
        load/build/warm phase) — waiters use this to keep waiting
        instead of declaring the pool empty."""
        return self._poll_lock.locked()

    # -- polling / adoption --------------------------------------------------

    def poll(self, force: bool = False) -> bool:
        """Adopt the newest verified+healthy generation if one appeared;
        returns True when a swap happened.  Rate-limited to
        ``poll_interval_s`` unless ``force``; a rejected generation
        (health gate) or an all-corrupt checkpoint is counted, remembered
        in ``last_rejection``, and serving continues on the old model.

        Whole-poll serialization: two pollers (a second server sharing
        the pool, or an operator's force-poll next to the worker's)
        interleaving their slow adopt/warm phases could otherwise assign
        ``_current`` out of order and roll the served generation
        BACKWARDS — a concurrent poll simply yields to the in-flight
        one."""
        if not self._poll_lock.acquire(blocking=False):
            return False                    # an adoption is in flight
        try:
            return self._poll_locked(force)
        finally:
            self._poll_lock.release()

    def _poll_locked(self, force: bool) -> bool:
        now = time.monotonic()
        if not force and now - self._last_poll < self.poll_interval_s:
            return False
        self._last_poll = now
        token, _ = self._current
        disk = generation_token(self.checkpoint)
        if disk is not None and disk in (self._rejected_token,
                                         self._skip_token):
            return False    # a gen that already failed the gate, or a
        try:                # disk state a full poll already deemed a no-op
            adoption = adopt_latest(
                self.checkpoint, self.build, probe=self._warm_probe,
                last_token=token, min_mtime_ns=self._adopted_mtime,
                name=self.name)
        except Exception as e:  # noqa: BLE001 — typed below, serving goes on
            self.rejections += 1
            self.last_rejection = e
            if isinstance(e, AdoptionRejected):
                # memoize the SETTLED disk state, not e.token: when the
                # rejected state was a fallback behind a corrupt newest
                # file, load() already cleaned that file up, so e.token
                # names a file that no longer exists and would never
                # match — the pool would re-run the full load+build+gate
                # every interval.  A fresh write still changes the token
                # and re-arms the gate.
                self._rejected_token = generation_token(self.checkpoint)
            else:
                # corrupt-beyond-repair checkpoints etc. — keep serving,
                # but surface loudly for the operator
                import warnings
                warnings.warn(f"{self.name}: generation adoption failed "
                              f"({type(e).__name__}: {e}); continuing on "
                              "the current generation", RuntimeWarning,
                              stacklevel=2)
            return False
        if adoption is None:
            # remember the PRE-poll disk state so polls until the next
            # real write cost one stat, not a full load+build (covers the
            # fallback case where the monotonicity guard keeps the
            # in-memory gen).  It must be the token captured BEFORE the
            # adoption attempt: re-statting here could capture a
            # generation written DURING the attempt and skip it forever.
            self._skip_token = disk
            return False
        with self._lock:
            self._current = (adoption.token, adoption.model)
        self.cache.rekey("warming", adoption.token)
        self._adopted_mtime = adoption.mtime_ns
        self._skip_token = None
        self.adoptions += 1
        return True

    def _warm_probe(self, pipeline):
        """The adoption probe: AOT-warm every bucket on the CANDIDATE
        pipeline and hand the concatenated outputs to the health gate.
        Runs before the swap, so a post-swap batch never compiles and a
        NaN-predicting generation never reaches the served slot.  The
        generation token is not known yet — warm under a provisional key
        and re-key after adoption."""
        return self.cache.warm(pipeline, "warming", self.buckets)

    def stats(self) -> dict:
        token, pipe = self._current
        return {"generation": repr(token), "live": pipe is not None,
                "adoptions": self.adoptions, "rejections": self.rejections,
                "cache": self.cache.stats()}
