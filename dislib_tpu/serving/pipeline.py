"""ServePipeline — a fitted preprocessing + estimator chain as ONE
cached XLA dispatch per served bucket.

The whole predict pipeline (scaler transform → estimator predict →
argmax/decision/class lookup) linearizes through the round-7 dispatch
fusion layer: every transform is an elementwise graph node and every
estimator predict is a ``fused_kernel`` node since this round, so the
first force point compiles and runs the chain as one ``_exec_program``
executable, cached by (program, bucket shape).  The hot path is

    host staging buffer → device_put → one fused dispatch → device_get

with zero per-request tracing, zero pad kernels (the staging buffer is
pre-padded on host), and zero model-parameter transfers (leaves are
device-cached per generation via ``BaseEstimator._predict_leaves``).
"""

from __future__ import annotations

import numpy as np

from dislib_tpu.data.array import Array, _padded_shape
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.runtime import fetch as _fetch
from dislib_tpu.serving.buckets import BucketTemplate

# attributes probed, in order, to infer the feature width of a fitted
# model when the caller does not pass n_features explicitly
_FEATURE_ATTRS = ("centers_", "means_", "_sv_x")


def _infer_features(model, transforms):
    for t in transforms:
        # explicit None checks — `or` would probe ndarray truthiness on
        # duck-typed (sklearn-style) scalers and raise
        m = getattr(t, "mean_", None)
        if m is None:
            m = getattr(t, "data_min_", None)
        if m is not None and hasattr(m, "shape"):
            return int(np.shape(m)[-1])
    for attr in _FEATURE_ATTRS:
        v = getattr(model, attr, None)
        if v is not None:
            return int(np.shape(v)[1])
    coef = getattr(model, "coef_", None)
    if coef is not None:
        return int(np.shape(coef)[0])
    nf = getattr(model, "n_features_", None)
    if nf is not None:
        return int(nf)
    raise ValueError(
        "could not infer the pipeline's feature width — pass "
        "n_features= to ServePipeline")


class ServePipeline:
    """A fitted chain ``transforms → model.<method>`` executable per
    bucket as one fused dispatch.

    Parameters
    ----------
    model : fitted estimator — its ``method`` (default ``"predict"``)
        must return a ds-array (all library estimators do).
    transforms : sequence of fitted transformers applied in order
        (``.transform``), e.g. a StandardScaler.
    method : str — the model entry point: ``"predict"``,
        ``"predict_proba"``, ``"decision_function"``, ...
    n_features : int — request feature width; inferred from the fitted
        attributes when omitted.

    Not thread-safe: the serving worker (or one caller) drives it.
    """

    def __init__(self, model, transforms=(), method="predict",
                 n_features=None):
        self.model = model
        self.transforms = tuple(transforms)
        self.method = method
        self.n_features = int(n_features) if n_features is not None \
            else _infer_features(model, self.transforms)
        self._templates: dict[int, BucketTemplate] = {}
        self.out_cols: int | None = None    # discovered at first execute

    def __call__(self, x: Array) -> Array:
        for t in self.transforms:
            x = t.transform(x)
        return getattr(self.model, self.method)(x)

    def _template(self, bucket: int) -> BucketTemplate:
        tmpl = self._templates.get(bucket)
        if tmpl is None:
            pshape = _padded_shape((bucket, self.n_features),
                                   _mesh.pad_quantum())
            tmpl = self._templates[bucket] = BucketTemplate(pshape)
        return tmpl

    def predict_bucket(self, rows: np.ndarray, bucket: int) -> np.ndarray:
        """Serve one batch padded into ``bucket``: returns the logical
        (n_rows, out_cols) host result.  This is the one-dispatch hot
        path — stage, transfer, force the fused chain, fetch, slice."""
        import jax
        rows = np.asarray(rows, np.float32)
        if rows.ndim == 1:
            rows = rows.reshape(1, -1)
        if rows.shape[1] != self.n_features:
            raise ValueError(f"request has {rows.shape[1]} features, "
                             f"pipeline serves {self.n_features}")
        if rows.shape[0] > bucket:
            raise ValueError(f"{rows.shape[0]} rows exceed bucket {bucket}")
        buf = self._template(bucket).fill(rows)
        dev = jax.device_put(buf, _mesh.data_sharding())
        out = self(Array(dev, (bucket, self.n_features)))
        host = _fetch(out)                  # force: ONE fused dispatch
        self.out_cols = out.shape[1]
        return host[: rows.shape[0], : out.shape[1]]
