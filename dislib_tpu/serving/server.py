"""PredictServer — request micro-batching with a latency deadline.

Requests arrive one at a time (a row, or a small row block) but the
hardware wants batches: a single fused dispatch over 512 rows costs
barely more than over 1 (the per-dispatch RTT dominates small batches —
BENCH_local_r05 measured ~70 ms/dispatch through the chip tunnel).  The
server queues submissions and flushes a batch when EITHER

- the queued rows fill the largest bucket (throughput bound), OR
- the OLDEST queued request has waited ``deadline_ms``
  (``DSLIB_SERVE_DEADLINE_MS``, default 5) — the latency bound.

A flush coalesces whole requests into the smallest covering bucket (a
request's rows never split across batches; an oversize request is
chunked internally at largest-bucket granularity) and runs ONE fused
dispatch.  Between batches the server polls its :class:`ModelPool` (when
serving one) so generation hot-swaps happen at batch boundaries — a
response is always computed entirely by one generation, never torn
across two.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from dislib_tpu.serving.buckets import bucket_for, bucket_ladder, split_rows
from dislib_tpu.serving.cache import ProgramCache
from dislib_tpu.utils import profiling as _prof

_LATENCY_WINDOW = 8192      # completions kept for the p50/p95/p99 estimate


def _default_deadline_s() -> float:
    return float(os.environ.get("DSLIB_SERVE_DEADLINE_MS", "5")) / 1e3


class QueueFull(RuntimeError):
    """Backpressure, typed (round 15): the server's queue already holds
    ``max_queue_rows`` rows — the request rate is outrunning the device
    and THIS submission was shed (the queue never grows until the
    process OOMs).  Subclasses ``RuntimeError`` so pre-round-15 callers
    matching that still catch it; carries the ``tenant`` whose request
    was shed so a router's admission layer can attribute the rejection."""

    def __init__(self, message, tenant=None):
        super().__init__(message)
        self.tenant = tenant


class ShardDrained(RuntimeError):
    """This server is part of a sharded fleet and a peer host's lease
    EXPIRED (round 20): until the fleet heals, responses assembled here
    would silently miss the dead host's shard of the model — torn
    results.  The server DRAINS instead: queued and new requests fail
    with this typed error (carrying the dead ``rank`` and ``last_seen``)
    so the caller's load balancer re-routes, and serving resumes
    automatically when the peer's lease is renewed or a restart rejoins."""

    def __init__(self, message, rank=None, last_seen=None):
        super().__init__(message)
        self.rank = rank
        self.last_seen = last_seen


class ServeResponse:
    """One request's result: ``values`` (n_rows, out_cols ndarray), the
    ``generation`` token that computed it (None for a static pipeline),
    and the request's ``latency_s`` (submit → response)."""

    __slots__ = ("values", "generation", "latency_s")

    def __init__(self, values, generation, latency_s):
        self.values = values
        self.generation = generation
        self.latency_s = latency_s

    def __repr__(self):
        return (f"ServeResponse(shape={self.values.shape}, "
                f"generation={self.generation!r}, "
                f"latency_ms={1e3 * self.latency_s:.3f})")


class _Pending:
    __slots__ = ("rows", "future", "t_submit", "tenant")

    def __init__(self, rows, tenant=None):
        self.rows = rows
        self.future = Future()
        self.t_submit = time.perf_counter()
        self.tenant = tenant


class PredictServer:
    """Micro-batching front of a :class:`ServePipeline` or
    :class:`ModelPool`.

    Use as a context manager (``with PredictServer(...) as srv``) or call
    :meth:`start`/:meth:`stop`.  ``submit`` returns a
    ``concurrent.futures.Future`` resolving to :class:`ServeResponse`;
    ``predict`` is the blocking convenience returning just the values.
    """

    def __init__(self, pipeline=None, pool=None, buckets=None,
                 deadline_ms=None, max_queue_rows=65536, name="serve",
                 elastic=None, capacity_poll_s=0.25, grow_attempts=8,
                 membership=None):
        if (pipeline is None) == (pool is None):
            raise ValueError("pass exactly one of pipeline= or pool=")
        if elastic is not None and not callable(elastic):
            if elastic and pipeline is not None and \
                    hasattr(pipeline, "rebind_mesh"):
                # elastic=True on a pipeline that owns its re-layout
                # (round 20: RetrievalPipeline/IVFIndex): the default
                # hook delegates to it — same pipeline object, re-laid
                elastic = (lambda mesh, _p=pipeline:
                           (_p.rebind_mesh(mesh), None)[1])
            else:
                elastic = (lambda mesh: None) if elastic else None
        if elastic is not None and pipeline is None:
            raise ValueError(
                "elastic= serving needs pipeline mode — a ModelPool's "
                "generations re-warm through adoption, not a rebind hook")
        self._pipeline = pipeline
        self._pool = pool
        # elastic capacity re-layout (round 19, ROADMAP 3(c)): between
        # batches the worker polls the capacity level (process override /
        # DSLIB_CAPACITY_FILE / the fleet-wide DSLIB_CAPACITY_LEDGER) and
        # re-forms the serving mesh over the home-device prefix exactly
        # as the fit loop's elastic tier does — hook(None) pre-switch,
        # mesh re-init, cache drop, hook(new_mesh) post-switch.  The hook
        # may return a REPLACEMENT pipeline (its model re-laid-out for
        # the new mesh via the rechunk schedules); the server re-warms
        # the bucket ladder before the next batch so the request hot
        # path never compiles.  The hook is optional: ``elastic=True``
        # (normalized above, before the pool-mode check) enables the
        # re-layout with the default rebind — re-warm the same pipeline
        # on the new mesh; a non-callable must never reach the worker
        # thread, where a TypeError would kill serving and strand every
        # queued future.
        self._elastic = elastic
        self.capacity_poll_s = float(capacity_poll_s)
        self._grows_left = int(grow_attempts)
        self._cap_shrunk = False        # a CAPACITY shrink is below home
        self._home_shape = None
        self._home_devices = None
        self._last_cap_poll = None
        self._mesh_resizes = 0
        # dead-shard drain (round 20): when this server fronts one shard
        # of a fleet, `membership=` (a runtime.coord.Membership) makes
        # the worker poll the peers' leases on the same cadence as
        # capacity — a confirmed-dead peer DRAINS this server (queued +
        # new requests fail typed ShardDrained, never torn fleet
        # results), a renewed lease or a rejoin resumes it
        self._membership = membership
        self._drained_rank = None       # (rank, last_seen) while draining
        self._shard_drains = 0
        if pool is not None:
            # the served ladder must be ⊆ the pool's warmed+health-gated
            # ladder: routing a request to a bucket adoption never warmed
            # would pay a trace+compile on the hot path AND run a shape
            # the health gate never validated
            self.buckets = pool.buckets if buckets is None \
                else bucket_ladder(buckets)
            extra = set(self.buckets) - set(pool.buckets)
            if extra:
                raise ValueError(
                    f"server buckets {sorted(extra)} are not in the "
                    f"pool's warmed ladder {pool.buckets} — every served "
                    "bucket must be AOT-warmed and health-gated at "
                    "adoption")
        else:
            self.buckets = bucket_ladder(buckets)
        self.deadline_s = _default_deadline_s() if deadline_ms is None \
            else float(deadline_ms) / 1e3
        self.name = name
        self.max_queue_rows = int(max_queue_rows)
        self.cache = pool.cache if pool is not None else ProgramCache()
        self._cv = threading.Condition()
        self._queue: deque[_Pending] = deque()
        self._queued_rows = 0               # backpressure accounting
        self._running = False
        self._thread: threading.Thread | None = None
        # accounting
        self._lat = deque(maxlen=_LATENCY_WINDOW)
        self._batches = 0
        self._requests = 0
        self._rows = 0
        self._dispatch_hist: deque[int] = deque(maxlen=_LATENCY_WINDOW)
        self._t_first = None
        self._t_last = None
        # per-tenant observability (round 15): latency windows, request
        # tallies, and shed counts keyed by the submit() tenant label —
        # the fleet bench and the router read THESE numbers rather than
        # timing around the server
        self._shed = 0
        self._tenant_lat: dict[str, deque] = {}
        self._tenant_requests: dict[str, int] = {}
        self._tenant_shed: dict[str, int] = {}
        # per-bucket wall-clock cost model (round 18): measured
        # predict_bucket walls keyed by bucket, learned from the server's
        # own serving — the admission layer (ModelRouter deadline shed)
        # reads predict_latency() instead of guessing
        self._bucket_wall: dict[int, deque] = {}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "PredictServer":
        if self._running:
            return self
        if self._elastic is not None:
            from dislib_tpu.parallel import mesh as _mesh
            m = _mesh.get_mesh()
            self._home_shape = _mesh.mesh_shape(m)
            self._home_devices = list(m.devices.flat)
        if self._pipeline is not None:
            # static pipeline: AOT-warm every bucket up front so the
            # request path never compiles (a ModelPool warms at adoption)
            self.cache.warm(self._pipeline, None, self.buckets)
        else:
            self._pool.poll(force=True)
        self._running = True
        self._thread = threading.Thread(target=self._worker,
                                        name=f"dslib-{self.name}",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Drain the queue (every accepted request gets a response), then
        stop the worker."""
        if not self._running:
            return
        with self._cv:
            self._running = False
            self._cv.notify_all()
        self._thread.join()
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- request side --------------------------------------------------------

    def submit(self, rows, tenant=None) -> Future:
        """Queue one request (a (k, n_features) block or a single (n,)
        row); the Future resolves to a :class:`ServeResponse`.  Raises
        :class:`QueueFull` when the queue already holds
        ``max_queue_rows`` rows — backpressure: a client outrunning the
        device must hear about it instead of growing the queue until the
        process OOMs.  ``tenant`` labels the request for the per-tenant
        latency/shed accounting in :meth:`stats` (a
        :class:`~dislib_tpu.serving.router.ModelRouter` sets it)."""
        rows = np.asarray(rows, np.float32)
        if rows.ndim == 1:
            rows = rows.reshape(1, -1)
        if rows.ndim != 2 or rows.shape[0] < 1:
            raise ValueError(f"a request is a (k, n_features) row block, "
                             f"got shape {rows.shape}")
        p = _Pending(rows, tenant)
        with self._cv:
            if not self._running:
                raise RuntimeError("PredictServer is not running — use "
                                   "start() or a with-block")
            if self._drained_rank is not None:
                r, seen = self._drained_rank
                raise ShardDrained(
                    f"{self.name}: draining — fleet peer rank {r} is "
                    f"dead (lease expired, last heartbeat {seen:.3f}); "
                    "a response computed now would be missing its shard",
                    rank=r, last_seen=seen)
            if self._queued_rows + rows.shape[0] > self.max_queue_rows:
                self._shed += 1
                if tenant is not None:
                    self._tenant_shed[tenant] = \
                        self._tenant_shed.get(tenant, 0) + 1
                raise QueueFull(
                    f"{self.name}: queue full ({self._queued_rows} rows "
                    f"queued, max_queue_rows={self.max_queue_rows}) — "
                    "the request rate is outrunning the device; back off "
                    "and retry", tenant=tenant)
            self._queued_rows += rows.shape[0]
            self._queue.append(p)
            self._cv.notify_all()
        return p.future

    def predict(self, rows, tenant=None) -> np.ndarray:
        return self.submit(rows, tenant=tenant).result().values

    # -- worker side ---------------------------------------------------------

    def _poll_membership(self):
        """Between batches: convert peer-lease state into the drain
        level.  ``membership.poll()`` also publishes the death→capacity
        statement, so a dead peer both drains THIS shard and shrinks the
        fleet's fit capacity through one observation."""
        if self._membership is None:
            return
        try:
            self._membership.poll()
            dead = self._membership.dead()
        except Exception:               # noqa: BLE001 — poll never kills serving
            return
        if dead and self._drained_rank is None:
            r, last_seen, _epoch = dead[0]
            stranded = []
            with self._cv:
                self._drained_rank = (r, last_seen)
                self._shard_drains += 1
                stranded = list(self._queue)
                self._queue.clear()
                self._queued_rows = 0
            _prof.count_resilience("serve_shard_drains")
            err = ShardDrained(
                f"{self.name}: fleet peer rank {r} died mid-serve "
                f"(lease expired, last heartbeat {last_seen:.3f}) — "
                "draining this shard instead of serving torn results",
                rank=r, last_seen=last_seen)
            for p in stranded:
                if p.future.set_running_or_notify_cancel():
                    p.future.set_exception(err)
        elif not dead and self._drained_rank is not None:
            with self._cv:
                self._drained_rank = None

    def _worker(self):
        top = self.buckets[-1]
        while True:
            self._maybe_resize()        # between batches, never mid-batch
            self._poll_membership()
            with self._cv:
                while self._running and not self._queue:
                    self._cv.wait(timeout=0.1)
                    if self._elastic is not None or \
                            self._membership is not None:
                        break   # idle: re-poll capacity / peer leases
                if not self._queue:
                    if not self._running:
                        return
                    continue
                # deadline window: wait for more work until the OLDEST
                # request's deadline, or until the largest bucket fills
                flush_at = self._queue[0].t_submit + self.deadline_s
                while self._running:
                    left = flush_at - time.perf_counter()
                    if self._queued_rows >= top or left <= 0:
                        break
                    self._cv.wait(timeout=left)
                # assemble: whole requests, smallest covering bucket
                batch = [self._queue.popleft()]
                total = batch[0].rows.shape[0]
                while self._queue and \
                        total + self._queue[0].rows.shape[0] <= top:
                    p = self._queue.popleft()
                    total += p.rows.shape[0]
                    batch.append(p)
                self._queued_rows -= total
            self._execute(batch, total)

    def _capacity_plan(self):
        """The fit loop's ``_capacity_plan`` rule, applied to the serving
        mesh: compare the published capacity level against the current
        rows and return ``("shrink"|"grow", new_rows)`` or None.  The
        mesh stays a halving-reachable row prefix of the HOME mesh;
        shrinks always honour the target, grows spend ``grow_attempts``
        budget so a flapping source cannot thrash resizes forever."""
        from dislib_tpu.parallel import mesh as _mesh
        from dislib_tpu.runtime.preemption import capacity_target
        cap = capacity_target()
        if cap is None:
            # pressure lifted (the round-20 rejoin heal CLEARS the target
            # rather than publishing a bigger level): a capacity-shrunk
            # server heads home through the same grow rungs, same budget
            if not self._cap_shrunk:
                return None
            cap = self._home_shape[0] * self._home_shape[1]
        r, c = _mesh.mesh_shape(_mesh.get_mesh())
        home_r, home_c = self._home_shape
        cap = max(c, min(int(cap), home_r * home_c))
        want = cap // c                 # usable full rows at this level
        if want < r:
            new_r = r
            while new_r > 1 and new_r > want:
                new_r //= 2
            return ("shrink", new_r) if new_r < r else None
        if want > r and r < home_r and self._grows_left > 0:
            new_r = r
            while new_r * 2 <= min(want, home_r):
                new_r *= 2
            if new_r > r:
                return ("grow", new_r)
        return None

    def _maybe_resize(self):
        """Worker-side capacity poll (throttled): re-form the serving
        mesh when the level moved, at a BATCH BOUNDARY — a response is
        always computed entirely on one mesh, never torn across two.
        Mirrors ``ChunkedFitLoop._resize_mesh``: hook(None) forces
        anything pending under the old mesh, the mesh re-forms over the
        home-device prefix, jit caches drop (stale sharding constraints
        must not replay), and the hook sees the new mesh — returning a
        replacement pipeline re-laid-out for it, which is re-warmed so
        the hot path stays compile-free."""
        if self._elastic is None:
            return
        now = time.perf_counter()
        if self._last_cap_poll is not None and \
                now - self._last_cap_poll < self.capacity_poll_s:
            return
        self._last_cap_poll = now
        plan = self._capacity_plan()
        if plan is None:
            return
        kind, new_r = plan
        import jax

        from dislib_tpu.parallel import mesh as _mesh
        if kind == "grow":
            self._grows_left -= 1
        self._elastic(None)             # pre-switch: force pending chains
        _, c = self._home_shape
        _mesh.init((new_r, c), devices=self._home_devices[: new_r * c])
        jax.clear_caches()
        self._cap_shrunk = new_r < self._home_shape[0]
        _prof.count_resilience("serve_mesh_shrinks" if kind == "shrink"
                               else "serve_mesh_grows")
        new_pipe = self._elastic(_mesh.get_mesh())
        if new_pipe is not None:
            self._pipeline = new_pipe
        # caches were dropped with the old mesh: re-warm the ladder so
        # the next batch is a cached dispatch, not a compile
        self.cache.warm(self._pipeline, None, self.buckets)
        with self._cv:
            self._mesh_resizes += 1

    def _serving(self):
        """(generation, pipeline) for the next batch — polls the pool so
        hot-swaps land at batch boundaries.  Before the FIRST adoption
        the worker waits briefly instead of failing the batch: another
        poller may hold the pool's adoption lock mid-warm (poll() yields
        to it), or the trainer may be a moment away from its first
        save."""
        if self._pool is None:
            return None, self._pipeline
        deadline = time.perf_counter() + 2.0
        while True:
            self._pool.poll()
            gen, pipe = self._pool.current()
            if pipe is not None:
                return gen, pipe
            # never expire while an adoption is actually in flight on
            # another thread: its warm phase AOT-compiles the whole
            # bucket ladder, which routinely outlives any fixed deadline
            # (first compile on a real chip is tens of seconds)
            if time.perf_counter() >= deadline and not self._pool.adopting:
                raise RuntimeError(
                    f"{self.name}: no model generation has been adopted "
                    "yet (is the checkpoint path empty?)")
            self._pool.poll(force=True)
            time.sleep(0.01)

    def _execute(self, batch, total):
        try:
            gen, pipe = self._serving()
        except Exception as e:  # noqa: BLE001 — no model: fail the batch
            for p in batch:
                if p.future.set_running_or_notify_cancel():
                    p.future.set_exception(e)
            return
        # per-request validation BEFORE the fused dispatch: one malformed
        # request must fail ITS future, not poison the whole batch
        good = []
        for p in batch:
            if p.rows.shape[1] != pipe.n_features:
                if p.future.set_running_or_notify_cancel():
                    p.future.set_exception(ValueError(
                        f"request has {p.rows.shape[1]} features, "
                        f"pipeline serves {pipe.n_features}"))
            else:
                good.append(p)
        if not good:
            return
        batch = good
        total = sum(p.rows.shape[0] for p in batch)
        try:
            rows = batch[0].rows if len(batch) == 1 else \
                np.concatenate([p.rows for p in batch], axis=0)
            pieces = []
            walls = []
            d0 = _prof.dispatch_count()
            for size in split_rows(total, self.buckets):
                bucket = bucket_for(size, self.buckets)
                t_piece = time.perf_counter()
                pieces.append(pipe.predict_bucket(rows[:size], bucket))
                walls.append((bucket, time.perf_counter() - t_piece))
                self.cache.record_hit(gen, bucket)
                rows = rows[size:]
            dispatches = _prof.dispatch_count() - d0
            out = pieces[0] if len(pieces) == 1 else \
                np.concatenate(pieces, axis=0)
        except Exception as e:  # noqa: BLE001 — fail the batch, keep serving
            for p in batch:
                if not p.future.set_running_or_notify_cancel():
                    continue
                p.future.set_exception(e)
            return
        t_done = time.perf_counter()
        # accounting mutates under the condition lock so a monitoring
        # thread's stats() snapshot never iterates a deque mid-append
        with self._cv:
            self._batches += 1
            self._dispatch_hist.append(dispatches)
            for bucket, wall in walls:
                self._bucket_wall.setdefault(
                    bucket, deque(maxlen=512)).append(wall)
            if self._t_first is None:
                self._t_first = t_done
            self._t_last = t_done
            lats = []
            for p in batch:
                lat = t_done - p.t_submit
                lats.append(lat)
                self._lat.append(lat)
                self._requests += 1
                self._rows += p.rows.shape[0]
                if p.tenant is not None:
                    self._tenant_lat.setdefault(
                        p.tenant,
                        deque(maxlen=_LATENCY_WINDOW)).append(lat)
                    self._tenant_requests[p.tenant] = \
                        self._tenant_requests.get(p.tenant, 0) + 1
        off = 0
        for p, lat in zip(batch, lats):
            k = p.rows.shape[0]
            if p.future.set_running_or_notify_cancel():
                p.future.set_result(
                    ServeResponse(out[off:off + k].copy(), gen, lat))
            off += k

    # -- cost model ----------------------------------------------------------

    def bucket_cost(self) -> dict:
        """The learned per-bucket cost model: ``{bucket: p95 wall
        seconds}`` over the measured ``predict_bucket`` walls of this
        server's own serving.  A bucket appears once it has ≥ 3 samples
        — before that the model declines to predict (None from
        :meth:`predict_latency`) rather than shed on a guess."""
        with self._cv:
            snap = {b: np.asarray(d, np.float64)
                    for b, d in self._bucket_wall.items()}
        return {b: float(np.percentile(w, 95))
                for b, w in sorted(snap.items()) if w.size >= 3}

    def predict_latency(self, n_rows: int) -> float | None:
        """Predicted submit→response seconds for an ``n_rows`` request
        arriving NOW: the deadline window the batcher may hold it, plus
        the predicted execute walls of the rows already queued ahead of
        it, plus its own bucket pieces — all read from the learned
        :meth:`bucket_cost` model.  Returns None when any needed bucket
        has no model yet (an admission layer must not shed on
        ignorance)."""
        costs = self.bucket_cost()
        with self._cv:
            backlog = self._queued_rows
        predicted = self.deadline_s

        def _pieces_cost(total: int) -> float | None:
            acc = 0.0
            for size in split_rows(int(total), self.buckets):
                c = costs.get(bucket_for(size, self.buckets))
                if c is None:
                    return None
                acc += c
            return acc

        for total in (backlog, int(n_rows)):
            if total:
                c = _pieces_cost(total)
                if c is None:
                    return None
                predicted += c
        return predicted

    # -- accounting ----------------------------------------------------------

    @staticmethod
    def _percentiles(lat: np.ndarray) -> dict:
        """p50/p95/p99 (ms) over one latency window, None when empty."""
        if not lat.size:
            return {"p50_ms": None, "p95_ms": None, "p99_ms": None}
        return {f"p{q}_ms": round(1e3 * float(np.percentile(lat, q)), 4)
                for q in (50, 95, 99)}

    def stats(self) -> dict:
        """Serving counters: request latency percentiles (p50/p95/p99
        ms, overall AND per tenant under ``tenants``), QPS over the
        completion window, rows/batches served, ``shed`` (submissions
        rejected by backpressure — total, and per tenant), and the
        per-batch dispatch distribution (the 1-dispatch-per-batch
        invariant as a number; oversize split requests legitimately cost
        one dispatch per piece).  The fleet bench reads ITS headline
        numbers from here — the server is its own observability source.
        Dispatch deltas read the process-wide profiling counters —
        concurrent non-serving device work in the same process would
        inflate them."""
        with self._cv:                      # consistent snapshot vs the
            lat = np.asarray(self._lat)     # worker's accounting block
            disp = np.asarray(self._dispatch_hist, np.int64)
            t_first, t_last = self._t_first, self._t_last
            requests, rows = self._requests, self._rows
            batches, depth = self._batches, len(self._queue)
            queued_rows = self._queued_rows
            shed = self._shed
            tenant_lat = {t: np.asarray(d, np.float64)
                          for t, d in self._tenant_lat.items()}
            tenant_requests = dict(self._tenant_requests)
            tenant_shed = dict(self._tenant_shed)
        lat = lat.astype(np.float64)
        window = (t_last - t_first) \
            if t_first is not None and t_last > t_first else None
        tenants = {}
        for t in sorted(set(tenant_lat) | set(tenant_shed)):
            tenants[t] = {"requests": tenant_requests.get(t, 0),
                          "shed": tenant_shed.get(t, 0),
                          **self._percentiles(
                              tenant_lat.get(t, np.empty(0)))}
        return {
            "requests": requests,
            "rows": rows,
            "batches": batches,
            **self._percentiles(lat),
            "qps": round(requests / window, 2) if window else None,
            "rows_per_s": round(rows / window, 2) if window else None,
            "dispatches_per_batch_max": int(disp.max()) if disp.size
            else None,
            "dispatches_per_batch_mean": round(float(disp.mean()), 3)
            if disp.size else None,
            "queue_depth": depth,
            "queued_rows": queued_rows,
            "shed": shed,
            "mesh_resizes": self._mesh_resizes,
            "shard_drains": self._shard_drains,
            "draining": self._drained_rank is not None,
            "bucket_cost_ms": {b: round(1e3 * c, 4)
                               for b, c in self.bucket_cost().items()},
            "tenants": tenants,
            "swaps": self._pool.adoptions if self._pool is not None
            else None,
            "rejected_swaps": self._pool.rejections
            if self._pool is not None else None,
        }
