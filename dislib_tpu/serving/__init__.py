"""dislib_tpu.serving — the low-latency predict path (ROADMAP item 1:
the "millions of users" serving mode).

Training hardened (PRs 1–3), `predict` was still a per-call afterthought:
every request paid tracing/compile risk for its exact shape, per-op
dispatch RTT (~70 ms/dispatch on the reference rig, BENCH_local_r05), and
there was no way to serve a model while its successor trains.  This
package makes one served batch cost **one cached XLA dispatch
end-to-end**, from four pieces that compose:

- **padded batch buckets** (``buckets.py``) — requests pad to a small
  ladder of fixed row counts (default 1/8/64/512,
  ``DSLIB_SERVE_BUCKETS``), so the entire serving lifetime touches a
  handful of program shapes, all compiled at warmup.  Predict is
  row-independent, so padded rows can never affect real rows' results;
  their outputs are sliced away before the response.
- **one-dispatch pipelines** (``pipeline.py``) — a scaler → estimator →
  argmax/decision chain linearizes through the round-7 fusion layer
  (every estimator predict is a ``fused_kernel`` graph node since this
  round) into ONE cached XLA program per bucket.
- **program cache + AOT warmup** (``cache.py``) — the (model generation,
  bucket shape) ledger over XLA's executable cache: a generation serves
  only after every bucket is warmed and health-gated, so the request hot
  path never compiles and never meets an unvalidated model.
- **micro-batching + hot-swap** (``server.py`` / ``hotswap.py``) — queued
  requests coalesce into the smallest covering bucket under a latency
  deadline (``DSLIB_SERVE_DEADLINE_MS``), and the served model follows a
  rotating ``FitCheckpoint`` through the ``runtime.adoption`` gate: serve
  generation N while N+1 trains, adopting N+1 only after its checksum
  verifies and its warmup predict passes the health guard.
- **sparse fold-in serving** (``sparse.py``, round 14) — recommender
  requests arrive as PADDED SPARSE batches (``[cols | vals]`` rows, the
  fixed-width encoding) and serve through the same bucket
  ladder/server/pool machinery as one fused ALS fold-in dispatch per
  batch: score a brand-new user against the trained factors with no
  refit and no densified request vector.
- **AOT deployment bundles** (``bundle.py``, round 15) — the compiled
  predict executables for the WHOLE bucket ladder serialize into one
  checksum-verified artifact (``export_bundle``); a fresh process
  rehydrates it into a ``PredictServer``-ready pipeline with ZERO
  retraces (``load_bundle``), refusing typed-and-loud
  (``BundleIncompatible``) when jax/topology fingerprints mismatch.
- **multi-tenant routing** (``router.py``, round 15) — ``ModelRouter``
  maps tenants onto shared servers (shared shape ladder → shared
  compiled executables, ~zero extra compiles), adds per-tenant
  admission quotas (typed ``TenantQuotaExceeded`` sheds only the
  offender), and hash-splits canary/A-B traffic with a health-gated
  ``promote``.

See the user guide's "Serving & hot-swap" and "Deployment bundles &
multi-tenant serving" sections for the end-to-end story and
`bench.py::bench_serving` / ``bench_serving_fleet`` for the
regression-gated numbers.
"""

from dislib_tpu.serving.buckets import (DEFAULT_BUCKETS, BucketLadderError,
                                        bucket_for, bucket_ladder,
                                        split_rows)
from dislib_tpu.serving.bundle import (BundlePipeline, LoadedBundle,
                                       export_bundle, load_bundle,
                                       runtime_fingerprint)
from dislib_tpu.serving.cache import ProgramCache
from dislib_tpu.serving.hotswap import ModelPool
from dislib_tpu.serving.pipeline import ServePipeline
from dislib_tpu.serving.router import (DeadlineShed, ModelRouter,
                                       TenantQuotaExceeded)
from dislib_tpu.serving.server import (PredictServer, QueueFull,
                                       ServeResponse, ShardDrained)
from dislib_tpu.serving.sparse import SparseFoldInPipeline, pack_sparse_rows

__all__ = [
    "DEFAULT_BUCKETS", "BucketLadderError", "bucket_ladder", "bucket_for",
    "split_rows",
    "ProgramCache", "ServePipeline", "PredictServer", "ServeResponse",
    "QueueFull", "ShardDrained", "ModelPool",
    "SparseFoldInPipeline", "pack_sparse_rows",
    "export_bundle", "load_bundle", "BundlePipeline", "LoadedBundle",
    "runtime_fingerprint",
    "ModelRouter", "TenantQuotaExceeded", "DeadlineShed",
]
