"""Padded batch buckets — the fixed shape ladder served programs compile
for.

XLA rewards ahead-of-time compilation of whole programs to FIXED shapes
(arXiv:1810.09868); a serving path that compiled per request row-count
would pay a fresh trace+compile for every new batch size it meets.  The
ladder quantizes every request batch up to a handful of row counts, so
the WHOLE serving lifetime touches ``len(ladder)`` program shapes — all
compiled once at warmup, none on the request path.

Pad correctness: predict is row-independent for every served estimator
(labels/decisions/votes are computed per row), and ds-array padding is
zero-filled, so a padded row is just a zero-row prediction that the
response slicing drops — padded rows can never affect real rows.
"""

from __future__ import annotations

import os

import numpy as np

DEFAULT_BUCKETS = (1, 8, 64, 512)


class BucketLadderError(ValueError):
    """``DSLIB_SERVE_BUCKETS`` failed validation at parse time: a token
    is not an integer, a bucket is non-positive, or the ladder is not
    strictly increasing (duplicates included).  Typed so a deployment
    with a fat-fingered env var fails AT STARTUP with the offending
    value in the message — not downstream as a silently reordered
    ladder, a bare ``int()`` traceback, or a mis-bucketed request."""


def _ladder_from_env(env: str):
    """Strictly validated parse of the ``DSLIB_SERVE_BUCKETS`` value: a
    comma-separated, strictly increasing list of positive row counts.
    Unlike a programmatic ``buckets=`` argument (normalised below — the
    caller wrote a Python literal and can see its order), an env var is
    deployment configuration: silently sorting/deduping ``512,64`` or
    ``8,8,64`` would mask a typo'd rollout, so any deviation raises
    :class:`BucketLadderError` naming the value."""
    ladder = []
    for tok in env.split(","):
        tok = tok.strip()
        if not tok:
            continue
        try:
            b = int(tok)
        except ValueError:
            raise BucketLadderError(
                f"DSLIB_SERVE_BUCKETS={env!r}: {tok!r} is not an integer "
                "row count") from None
        if b < 1:
            raise BucketLadderError(
                f"DSLIB_SERVE_BUCKETS={env!r}: bucket {b} is not positive")
        if ladder and b <= ladder[-1]:
            raise BucketLadderError(
                f"DSLIB_SERVE_BUCKETS={env!r}: ladder must be strictly "
                f"increasing ({b} after {ladder[-1]} — duplicates count)")
        ladder.append(b)
    if not ladder:
        raise BucketLadderError(
            f"DSLIB_SERVE_BUCKETS={env!r}: no buckets parsed")
    return tuple(ladder)


def bucket_ladder(buckets=None):
    """Normalised, ascending bucket ladder.  ``None`` reads
    ``DSLIB_SERVE_BUCKETS`` (comma-separated row counts, validated
    strictly — see :class:`BucketLadderError`) and falls back to
    :data:`DEFAULT_BUCKETS`."""
    if buckets is None:
        env = os.environ.get("DSLIB_SERVE_BUCKETS", "")
        if env.strip():
            return _ladder_from_env(env)
        buckets = DEFAULT_BUCKETS
    ladder = tuple(sorted({int(b) for b in buckets}))
    if not ladder or ladder[0] < 1:
        raise ValueError(f"bucket ladder must be positive row counts, got "
                         f"{buckets!r}")
    return ladder


def bucket_for(n_rows: int, ladder) -> int | None:
    """Smallest bucket covering ``n_rows``, or None when it exceeds the
    largest bucket (the caller splits via :func:`split_rows`)."""
    for b in ladder:
        if n_rows <= b:
            return b
    return None


def split_rows(n_rows: int, ladder):
    """Chunk an oversize request into full largest-bucket pieces plus one
    remainder piece (itself bucketed by the caller) — e.g. 1100 rows on
    (1, 8, 64, 512) serves as pieces of 512 + 512 + 76, the last padding
    into its covering 512 bucket.  Each piece costs one dispatch."""
    top = ladder[-1]
    sizes = []
    left = int(n_rows)
    while left > top:
        sizes.append(top)
        left -= top
    if left:
        sizes.append(left)
    return sizes


class BucketTemplate:
    """Preallocated zeroed host staging buffer for one bucket's padded
    shape.  ``fill`` writes the request rows and re-zeroes only the rows
    the PREVIOUS batch dirtied (high-water tracking) — the hot path
    never re-allocates or re-zeroes the whole canvas."""

    def __init__(self, pshape, dtype=np.float32):
        self.pshape = tuple(int(s) for s in pshape)
        self.buf = np.zeros(self.pshape, dtype)
        self._dirty_rows = 0
        self._dirty_cols = 0

    def fill(self, rows: np.ndarray) -> np.ndarray:
        k, n = rows.shape
        if k > self.pshape[0] or n > self.pshape[1]:
            raise ValueError(f"batch {rows.shape} exceeds bucket canvas "
                             f"{self.pshape}")
        if self._dirty_rows > k:
            self.buf[k:self._dirty_rows, : self._dirty_cols] = 0.0
        if self._dirty_cols > n:        # never runs in serving use — the
            self.buf[:k, n:self._dirty_cols] = 0.0  # pipeline pins one
        self.buf[:k, :n] = rows                     # feature width
        self._dirty_rows, self._dirty_cols = k, n
        return self.buf
