"""ModelRouter — one serving process, N tenants (round-15 tentpole,
leg 2).

One process per model wastes the machinery the previous rounds built:
the program cache keys on (generation, bucket shape), so N tenants whose
pipelines share a shape ladder can share ONE compiled-executable set —
N-tenant serving costs ~zero extra compiles (counter-asserted in
``tests/test_serving_fleet.py``).  The router is the thin front that
makes that sharing safe:

- **tenant → server mapping**: each tenant names a
  :class:`~dislib_tpu.serving.server.PredictServer` (several tenants may
  point at the SAME server — that is the executable-sharing case; a
  tenant with its own model points at its own server over a shared or
  private ladder).
- **admission control**: a per-tenant in-flight row quota.  A tenant
  outrunning its quota gets a typed :class:`TenantQuotaExceeded` on ITS
  submissions only — the noisy neighbour is shed, everyone else's
  futures are untouched (the server's own :class:`QueueFull`
  backpressure stays underneath as the global limit, tenant-attributed).
- **canary / A-B routing**: :meth:`set_canary` splits a tenant's traffic
  between its primary server (N) and a canary server (N+1) by REQUEST
  HASH — the same request key always lands on the same arm, so an A/B
  comparison is deterministic and a client's retries don't flap between
  generations.  :meth:`promote` makes the canary primary only while the
  canary's model is live through the ``runtime.adoption`` gate (a
  pool-backed canary whose adoption was rejected cannot be promoted);
  :meth:`abort_canary` routes 100% back to N.

Observability rides the server's own per-tenant accounting
(``PredictServer.stats()["tenants"]``): the router labels every
submission with its tenant (canary arms as ``tenant:canary``), so
per-tenant p50/p95/p99 and shed counts come from the serving layer
itself, not from timing wrapped around it.
"""

from __future__ import annotations

import os
import threading
import zlib

import numpy as np

from dislib_tpu.serving.server import PredictServer

_HASH_BUCKETS = 10_000      # canary fraction resolution: 0.01%


def _default_router_deadline_s() -> float | None:
    raw = os.environ.get("DSLIB_DEADLINE_MS")
    return None if raw is None else float(raw) / 1e3


class TenantQuotaExceeded(RuntimeError):
    """Admission control, typed: THIS tenant's in-flight rows would
    exceed its quota, so this submission is shed — other tenants'
    requests are untouched (noisy-neighbour isolation).  Carries the
    offending ``tenant`` and its ``quota_rows``."""

    def __init__(self, message, tenant=None, quota_rows=None):
        super().__init__(message)
        self.tenant = tenant
        self.quota_rows = quota_rows


class DeadlineShed(RuntimeError):
    """Latency-budget admission control, typed (round 18): the routed
    server's learned cost model (:meth:`PredictServer.predict_latency`)
    predicts this request would miss the router's latency budget
    (``deadline_ms`` / ``DSLIB_DEADLINE_MS``), so it is shed AT
    ADMISSION — before it queues, where it would also push every request
    behind it past its own deadline.  Subclasses ``RuntimeError`` like
    the other shed types; carries the ``tenant``, the ``predicted_ms``,
    and the ``deadline_ms`` that refused it."""

    def __init__(self, message, tenant=None, predicted_ms=None,
                 deadline_ms=None):
        super().__init__(message)
        self.tenant = tenant
        self.predicted_ms = predicted_ms
        self.deadline_ms = deadline_ms


class _Tenant:
    __slots__ = ("name", "server", "quota_rows", "inflight_rows",
                 "canary", "canary_fraction", "quota_shed", "promotions",
                 "promote_failures", "rollbacks", "deadline_shed")

    def __init__(self, name, server, quota_rows):
        self.name = name
        self.server = server
        self.quota_rows = quota_rows
        self.inflight_rows = 0
        self.canary: PredictServer | None = None
        self.canary_fraction = 0.0
        self.quota_shed = 0
        self.promotions = 0
        self.promote_failures = 0
        self.rollbacks = 0
        self.deadline_shed = 0


def _request_hash(rows: np.ndarray, key) -> int:
    """Deterministic per-request hash for the canary split: the caller's
    routing ``key`` when given (a user/session id — keeps one client on
    one arm), else the request bytes themselves."""
    if key is not None:
        data = key if isinstance(key, bytes) else str(key).encode()
    else:
        data = np.ascontiguousarray(rows).tobytes()
    return zlib.crc32(data) % _HASH_BUCKETS


class ModelRouter:
    """Multi-tenant front over shared :class:`PredictServer` instances.

    Use as a context manager: ``with ModelRouter() as r`` starts every
    distinct server exactly once on entry and drains/stops them on exit
    (servers already running are left to their owner).  All routing
    state is lock-protected; the heavy lifting stays in the servers.
    """

    def __init__(self, name="router", deadline_ms=None):
        self.name = name
        self._tenants: dict[str, _Tenant] = {}
        self._lock = threading.Lock()
        self._started: list[PredictServer] = []
        # latency budget (round 18): predicted-miss admission control.
        # None (and DSLIB_DEADLINE_MS unset) = no budget, never sheds.
        self.deadline_s = _default_router_deadline_s() \
            if deadline_ms is None else float(deadline_ms) / 1e3

    # -- tenancy -------------------------------------------------------------

    def add_tenant(self, tenant: str, server: PredictServer,
                   quota_rows: int | None = None) -> None:
        """Register ``tenant`` on ``server``.  Any number of tenants may
        share one server — that is the executable-sharing fast path (one
        compiled ladder serves them all).  ``quota_rows`` caps the
        tenant's in-flight rows (admission control); None = no per-tenant
        cap (the server's global backpressure still applies)."""
        if not isinstance(server, PredictServer):
            raise TypeError(f"tenant {tenant!r}: server must be a "
                            f"PredictServer, got {type(server).__name__}")
        with self._lock:
            if tenant in self._tenants:
                raise ValueError(f"tenant {tenant!r} already registered")
            self._tenants[tenant] = _Tenant(
                tenant, server,
                None if quota_rows is None else int(quota_rows))

    def tenants(self):
        with self._lock:
            return sorted(self._tenants)

    def _get(self, tenant) -> _Tenant:
        t = self._tenants.get(tenant)
        if t is None:
            raise KeyError(f"unknown tenant {tenant!r} — add_tenant first")
        return t

    # -- canary / A-B --------------------------------------------------------

    def set_canary(self, tenant: str, server: PredictServer,
                   fraction: float = 0.1) -> None:
        """Route ``fraction`` of ``tenant``'s requests (by request hash)
        to ``server`` — generation N+1 next to the primary's N.  The
        split is deterministic per request key: A/B comparisons are
        reproducible and one client sticks to one arm."""
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"canary fraction must be in (0, 1], got "
                             f"{fraction}")
        with self._lock:
            self._get(tenant)           # typed before any side effect
            active = bool(self._started)
        # a canary attached mid-flight starts under the router's
        # lifecycle like any other registered server — and BEFORE it is
        # published as a route target: a concurrent submit must never
        # meet a not-yet-running canary (start() is outside the lock; it
        # warms the whole bucket ladder)
        if active and not server._running:
            server.start()
            self._started.append(server)
        with self._lock:
            t = self._get(tenant)
            t.canary = server
            t.canary_fraction = float(fraction)

    def abort_canary(self, tenant: str, *, failed: bool = False) -> None:
        """Route 100% of ``tenant`` back to its primary (the canary
        server keeps running — its owner decides its fate).
        ``failed=True`` records the abort as a promotion failure (the
        health gate refused the candidate) in the tenant's counters."""
        with self._lock:
            t = self._get(tenant)
            t.canary = None
            t.canary_fraction = 0.0
            if failed:
                t.promote_failures += 1

    def promote(self, tenant: str) -> None:
        """Make ``tenant``'s canary its primary — but only while the
        canary's model is LIVE through the adoption gate: a pool-backed
        canary must have actually adopted a generation (checksum +
        health-gated warmup), otherwise the promotion is refused with a
        ``RuntimeError`` and traffic stays on the old primary.  The
        demoted primary server keeps running (it may serve other
        tenants); in-flight futures on either arm resolve normally —
        promotion only changes where NEW requests route."""
        with self._lock:
            t = self._get(tenant)
            if t.canary is None:
                t.promote_failures += 1
                raise RuntimeError(f"tenant {tenant!r} has no canary to "
                                   "promote")
            pool = t.canary._pool
            if pool is not None and pool.current()[1] is None:
                t.promote_failures += 1
                raise RuntimeError(
                    f"tenant {tenant!r}: canary has not adopted a live "
                    "generation through the adoption gate (last "
                    f"rejection: {pool.last_rejection!r}) — refusing to "
                    "promote an unvalidated model")
            t.server = t.canary
            t.canary = None
            t.canary_fraction = 0.0
            t.promotions += 1

    def rollback(self, tenant: str, server: PredictServer) -> None:
        """EXPLICITLY re-point ``tenant``'s primary at ``server`` — the
        one sanctioned way the served generation moves backward (an
        earlier generation's bundle reloaded by its owner, e.g.
        :meth:`~dislib_tpu.runtime.trainer.ContinuousTrainer.rollback`).
        Any pending canary is cleared (a rollback supersedes an A/B in
        flight); the demoted primary keeps running — its owner decides
        its fate.  Counted per tenant (``rollbacks`` in :meth:`stats`)."""
        if not isinstance(server, PredictServer):
            raise TypeError(f"tenant {tenant!r}: rollback target must be "
                            f"a PredictServer, got {type(server).__name__}")
        with self._lock:
            self._get(tenant)           # typed before any side effect
            active = bool(self._started)
        # same lifecycle rule as set_canary: never publish a
        # not-yet-running server as a route target
        if active and not server._running:
            server.start()
            self._started.append(server)
        with self._lock:
            t = self._get(tenant)
            t.server = server
            t.canary = None
            t.canary_fraction = 0.0
            t.rollbacks += 1

    def route(self, tenant: str, rows, key=None):
        """(server, label) this request would take — the canary split
        made inspectable (tests and dry-runs)."""
        rows = np.asarray(rows, np.float32)
        with self._lock:
            t = self._get(tenant)
            if t.canary is not None and \
                    _request_hash(rows, key) < \
                    t.canary_fraction * _HASH_BUCKETS:
                return t.canary, f"{tenant}:canary"
            return t.server, tenant

    # -- request side --------------------------------------------------------

    def submit(self, rows, tenant: str, key=None):
        """Admit, route, and queue one request for ``tenant``; returns
        the server's Future.  Sheds with :class:`TenantQuotaExceeded`
        when the tenant's in-flight rows would exceed its quota — only
        the offender's submission fails; the server's own
        :class:`~dislib_tpu.serving.server.QueueFull` backpressure can
        still fire underneath as the global limit.  With a latency
        budget set (``deadline_ms`` / ``DSLIB_DEADLINE_MS``), sheds with
        :class:`DeadlineShed` when the routed server's learned cost
        model predicts a budget miss; with no model yet (cold server)
        the request is ADMITTED — the budget never sheds on
        ignorance."""
        rows = np.asarray(rows, np.float32)
        if rows.ndim == 1:
            rows = rows.reshape(1, -1)
        k = rows.shape[0]
        with self._lock:
            t = self._get(tenant)
            if t.quota_rows is not None and \
                    t.inflight_rows + k > t.quota_rows:
                t.quota_shed += 1
                raise TenantQuotaExceeded(
                    f"{self.name}: tenant {tenant!r} has "
                    f"{t.inflight_rows} rows in flight; {k} more would "
                    f"exceed its quota ({t.quota_rows}) — request shed, "
                    "other tenants unaffected",
                    tenant=tenant, quota_rows=t.quota_rows)
            if t.canary is not None and \
                    _request_hash(rows, key) < \
                    t.canary_fraction * _HASH_BUCKETS:
                server, label = t.canary, f"{tenant}:canary"
            else:
                server, label = t.server, tenant
            t.inflight_rows += k
        # the latency-budget check runs OUTSIDE the router lock:
        # predict_latency takes the server's own condition lock, and the
        # router must never hold both at once (lock-order discipline).
        # The inflight reservation above keeps the quota sound meanwhile.
        if self.deadline_s is not None:
            predicted = server.predict_latency(k)
            if predicted is not None and predicted > self.deadline_s:
                with self._lock:
                    t.inflight_rows -= k
                    t.deadline_shed += 1
                raise DeadlineShed(
                    f"{self.name}: tenant {tenant!r} request predicted at "
                    f"{1e3 * predicted:.2f} ms against a "
                    f"{1e3 * self.deadline_s:.2f} ms budget — shed at "
                    "admission (queueing it would also push every request "
                    "behind it past its deadline)",
                    tenant=tenant, predicted_ms=1e3 * predicted,
                    deadline_ms=1e3 * self.deadline_s)
        try:
            fut = server.submit(rows, tenant=label)
        except BaseException:
            with self._lock:
                t.inflight_rows -= k
            raise
        def _release(_f, _t=t, _k=k):
            with self._lock:
                _t.inflight_rows -= _k
        fut.add_done_callback(_release)
        return fut

    def predict(self, rows, tenant: str, key=None) -> np.ndarray:
        return self.submit(rows, tenant, key=key).result().values

    # -- lifecycle -----------------------------------------------------------

    def _servers(self):
        seen, out = set(), []
        for t in self._tenants.values():
            for s in (t.server, t.canary):
                if s is not None and id(s) not in seen:
                    seen.add(id(s))
                    out.append(s)
        return out

    def start(self) -> "ModelRouter":
        """Start every distinct registered server exactly once (shared
        servers start once no matter how many tenants point at them);
        servers already running stay their owner's responsibility."""
        with self._lock:
            servers = self._servers()
        for s in servers:
            if not s._running:
                s.start()
                self._started.append(s)
        return self

    def stop(self) -> None:
        """Drain and stop only the servers :meth:`start` started."""
        started, self._started = self._started, []
        for s in started:
            s.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        """Per-tenant routing + serving view: quota shed counts and
        in-flight rows from the router, latency percentiles and
        backpressure shed from the underlying server's OWN per-tenant
        accounting (primary and canary arms reported separately)."""
        with self._lock:
            tenants = {name: (t.server, t.canary, t.canary_fraction,
                              t.inflight_rows, t.quota_rows, t.quota_shed,
                              t.promotions, t.promote_failures, t.rollbacks,
                              t.deadline_shed)
                       for name, t in self._tenants.items()}
        out = {}
        for name, (server, canary, frac, inflight, quota, shed,
                   promotions, promote_failures, rollbacks,
                   deadline_shed) in tenants.items():
            sstats = server.stats()
            entry = {"server": server.name,
                     "inflight_rows": inflight,
                     "quota_rows": quota,
                     "quota_shed": shed,
                     "deadline_shed": deadline_shed,
                     "promotions": promotions,
                     "promote_failures": promote_failures,
                     "rollbacks": rollbacks,
                     "serving": sstats["tenants"].get(
                         name, {"requests": 0, "shed": 0})}
            if canary is not None:
                entry["canary"] = {
                    "server": canary.name,
                    "fraction": frac,
                    "serving": canary.stats()["tenants"].get(
                        f"{name}:canary", {"requests": 0, "shed": 0})}
            out[name] = entry
        return out
