"""ProgramCache — the (model generation, bucket shape) ledger over XLA's
executable cache, plus the AOT warmup that fills it.

The compiled executables themselves live in jax's jit cache, keyed by
(fusion program, operand shapes): two generations of the SAME pipeline
share one executable per bucket (their parameters are dynamic operands),
which is what makes hot-swap free of recompiles.  What jax does NOT
track is whether a given generation has been compiled-and-validated for
a given bucket — that is this ledger.  It is ACCOUNTING, consulted by
tests and operators (``stats()``/``is_warm()``); the actual never-
compile-on-the-hot-path guarantees are structural: a static server
warms its whole ladder in ``start()``, a ModelPool warms every bucket
inside the adoption probe BEFORE the swap, and ``PredictServer``
refuses at construction a ladder wider than its pool's.

``warm()`` also records the trace-count delta per bucket from the
``utils.profiling`` counters: the FIRST generation compiles each bucket
once (delta ≥ 1), every later generation must re-use (delta 0) — the
serving soak and `tests/test_serving.py` pin that invariant.
"""

from __future__ import annotations

import time

import numpy as np

from dislib_tpu.utils import profiling as _prof


class _Entry:
    __slots__ = ("warm_wall_s", "traces", "hits")

    def __init__(self, warm_wall_s, traces):
        self.warm_wall_s = warm_wall_s
        self.traces = traces
        self.hits = 0


class ProgramCache:
    """Warmed-program ledger; one per server (or per standalone pipeline
    user).  Keys are ``(generation_token, bucket_rows)``."""

    def __init__(self):
        self._entries: dict[tuple, _Entry] = {}

    def is_warm(self, generation, bucket: int) -> bool:
        return (generation, int(bucket)) in self._entries

    def record_hit(self, generation, bucket: int) -> None:
        e = self._entries.get((generation, int(bucket)))
        if e is not None:
            e.hits += 1

    def warm(self, pipeline, generation, buckets) -> np.ndarray:
        """AOT-warm ``pipeline`` for every bucket under ``generation``:
        run one zero batch per bucket (compiling any program shape not
        yet in the jit cache) and return the concatenated flat outputs —
        the caller feeds them to the adoption health gate, so warmup and
        the non-finite check are the same pass over the same programs.

        Re-warming an already-warm (generation, bucket) is a cheap no-op
        probe (one dispatch, zero traces)."""
        outs = []
        for b in buckets:
            b = int(b)
            t0 = time.perf_counter()
            traces0 = _prof.trace_count()
            out = pipeline.predict_bucket(
                np.zeros((b, pipeline.n_features), np.float32), b)
            self._entries[(generation, b)] = _Entry(
                time.perf_counter() - t0, _prof.trace_count() - traces0)
            outs.append(np.asarray(out, np.float64).ravel())
        return np.concatenate(outs) if outs else np.zeros((0,))

    def rekey(self, old_generation, new_generation) -> None:
        """Move every bucket entry from a provisional generation key to
        the real one (hot-swap warms a candidate before its adoption
        token exists — see ``ModelPool._warm_probe``) and EVICT every
        other generation's entries: one generation serves at a time, and
        a long-running pool following a frequently-checkpointing trainer
        would otherwise grow the ledger (and every ``stats()`` snapshot)
        without bound."""
        self._entries = {
            (new_generation, b): e
            for (g, b), e in self._entries.items()
            if g in (old_generation, new_generation)}

    def stats(self) -> dict:
        """Per-entry ledger snapshot: ``{(generation, bucket): {...}}``
        flattened to string keys for JSON-friendliness."""
        return {f"gen={g!r}/bucket={b}": {
                    "warm_wall_s": round(e.warm_wall_s, 6),
                    "traces_at_warm": e.traces, "hits": e.hits}
                for (g, b), e in self._entries.items()}

    def __len__(self):
        return len(self._entries)
