"""Sparse predict/fold-in serving — padded sparse batches through the
PredictServer bucket ladder.

A recommender's serving request is inherently sparse: a user arrives as
a handful of (item, rating) pairs, and the served computation is the ALS
fold-in (solve the user's normal equations against the FROZEN item
factors, emit predicted ratings for every item) — no refit, no dense
(n_items,) request vector.

**The padded-sparse request encoding.**  One request row is the fixed
width ``[cols | vals]`` — ``nse_cap`` column ids followed by ``nse_cap``
values, pads at (column 0, value 0), all float32.  That makes a sparse
batch a PLAIN (k, 2·nse_cap) host matrix, so the WHOLE PR-4 serving
machinery — :class:`PredictServer` micro-batching, the bucket ladder's
AOT-warmed fixed shapes, `ProgramCache`, hot-swap pools — applies
unchanged: the ladder quantizes k (the user count), ``nse_cap`` is the
pipeline's feature-width analog (a deployment parameter, like
``n_features``), and a padded row is a zero-observation user whose
fold-in solves λI·u = 0 → zero predictions the response slicing drops.
Column ids ride float32 exactly below 2²⁴ — guarded at construction.

The hot path is one staged host buffer → device_put → ONE fused
dispatch (`recommendation.als._als_fold_in_packed`: split, cast,
normal-equation solve, predict GEMM) → fetch, with the item factors
device-cached per generation via the estimator leaf cache — the model
is never re-transferred per batch (counter-asserted).
"""

from __future__ import annotations

from functools import partial

import numpy as np

from dislib_tpu.serving.buckets import BucketTemplate
from dislib_tpu.runtime import fetch as _fetch
from dislib_tpu.utils import profiling as _prof

__all__ = ["SparseFoldInPipeline", "pack_sparse_rows"]

_COL_ID_CEIL = 1 << 24        # float32 carries integers exactly below this


@partial(_prof.profiled_jit, name="pack_sparse_rows",
         static_argnames=("nse_cap",))
def _pack_rows(dense, nse_cap):
    # device-side [cols | vals] encode of a dense (k, n_items) request
    # block.  The top_k key ranks observed columns DESCENDING by
    # (n_items - col), i.e. ascending by column id — CSR order — with
    # unobserved slots keyed 0 so they sort last.  The per-row observed
    # count rides as ONE extra packed column (exact in float32 below
    # 2^24) so the host pays a single fetch for data + overflow check.
    import jax.numpy as jnp
    from jax import lax
    from dislib_tpu.ops import precision as px
    n_items = dense.shape[1]
    observed = dense != 0
    col = lax.broadcasted_iota(jnp.int32, dense.shape, 1)
    key = jnp.where(observed, n_items - col, 0)
    kk = min(int(nse_cap), int(n_items))
    topkey, pos = lax.top_k(key, kk)
    valid = topkey > 0
    cols = jnp.where(valid, pos, 0)
    vals = jnp.where(valid, jnp.take_along_axis(dense, pos, axis=1), 0)
    if kk < int(nse_cap):
        padw = ((0, 0), (0, int(nse_cap) - kk))
        cols = jnp.pad(cols, padw)
        vals = jnp.pad(vals, padw)
    counts = jnp.sum(observed, axis=1).astype(jnp.int32)
    return jnp.concatenate(
        [px.f32(cols), px.f32(vals), px.f32(counts)[:, None]], axis=1)


def pack_sparse_rows(rows, nse_cap, n_items=None):
    """Pack per-user sparse ratings into the ``[cols | vals]`` request
    encoding: ``rows`` is a scipy sparse matrix, a list of
    ``(cols, vals)`` pairs, or a dense (k, n_items) ndarray (0 =
    unobserved).  Returns the (k, 2·nse_cap) float32 request block a
    :class:`PredictServer` over a :class:`SparseFoldInPipeline`
    accepts.  A user with more than ``nse_cap`` observed ratings is a
    typed error (pick the cap at deployment like a bucket ladder).

    The dense-ndarray path packs ON DEVICE — one jitted dispatch
    (``pack_sparse_rows`` counter), one blessed fetch — so request
    encode rides the same transfer discipline as the serve kernels;
    scipy/pair inputs are host metadata and pack in a host loop."""
    import scipy.sparse as sp
    if isinstance(rows, np.ndarray):
        import jax
        import jax.numpy as jnp
        dense = np.atleast_2d(np.asarray(rows, np.float32))
        if dense.shape[1] >= _COL_ID_CEIL:
            raise ValueError("item ids ≥ 2^24 don't ride float32 exactly")
        if n_items is not None and dense.shape[1] > n_items:
            bad = np.nonzero((dense[:, n_items:] != 0).any(axis=1))[0]
            if bad.size:
                raise ValueError(
                    f"request row {int(bad[0])}: item ids out of range")
            dense = dense[:, :n_items]
        packed = _pack_rows(jax.device_put(jnp.asarray(dense)),
                            nse_cap=int(nse_cap))
        host = _fetch(packed)               # ONE fused pack dispatch
        counts = host[:, -1].astype(np.int64)
        over = np.nonzero(counts > int(nse_cap))[0]
        if over.size:
            i = int(over[0])
            raise ValueError(
                f"request row {i} has {int(counts[i])} observed ratings > "
                f"nse_cap={nse_cap} — raise the pipeline's cap")
        return np.ascontiguousarray(host[:, :-1])
    if sp.issparse(rows):
        csr = rows.tocsr()
        pairs = [(csr.indices[csr.indptr[i]:csr.indptr[i + 1]],
                  csr.data[csr.indptr[i]:csr.indptr[i + 1]])
                 for i in range(csr.shape[0])]
        if n_items is None:
            n_items = csr.shape[1]
    else:
        pairs = list(rows)
    # host packing of HOST request data (the lint-scanned loop below must
    # stay free of array-conversion spellings that read as device syncs)
    pairs = [(np.asarray(c), np.asarray(v, np.float32)) for c, v in pairs]
    out = np.zeros((len(pairs), 2 * int(nse_cap)), np.float32)
    for i, (cols, vals) in enumerate(pairs):
        k = cols.size
        if k > nse_cap:
            raise ValueError(
                f"request row {i} has {k} observed ratings > "
                f"nse_cap={nse_cap} — raise the pipeline's cap")
        if k and (cols.min() < 0 or (n_items is not None
                                     and cols.max() >= n_items)):
            raise ValueError(f"request row {i}: item ids out of range")
        if k and cols.max() >= _COL_ID_CEIL:
            raise ValueError("item ids ≥ 2^24 don't ride float32 exactly")
        out[i, :k] = cols                   # ndarray assignment casts
        out[i, nse_cap:nse_cap + k] = vals
    return out


@partial(_prof.profiled_jit, name="als_fold_in_serve",
         static_argnames=("lambda_", "n_f", "policy", "top_n"))
def _fold_in_serve(buf, items, lambda_, n_f, policy, top_n=0):
    # the bundle-capture variant of `als._als_fold_in_packed`: same
    # split → solve → predict body, but ONE output array (the bundle
    # path's single-leaf response contract) — [ids | scores] rows when
    # ranking, the full score matrix otherwise.
    import jax.numpy as jnp
    from dislib_tpu.ops import precision as px
    from dislib_tpu.ops.base import precise
    from dislib_tpu.recommendation.als import _fold_in_body

    @precise
    def body(buf, items):
        s = buf.shape[1] // 2
        cols = buf[:, :s].astype(jnp.int32)
        vals = buf[:, s:]
        _, preds = _fold_in_body(vals, cols, items, lambda_, n_f, policy,
                                 top_n=top_n)
        if top_n:
            ids, scores = preds
            return jnp.concatenate([px.f32(ids), px.f32(scores)], axis=1)
        return preds

    return body(buf, items)


class SparseFoldInPipeline:
    """A fitted ALS model served as fold-in scoring over padded sparse
    batches — the drop-in `pipeline=` for :class:`PredictServer` (same
    ``n_features`` / ``predict_bucket`` surface as `ServePipeline`, so
    bucket warming, micro-batching, and hot-swap pools apply unchanged).

    Parameters
    ----------
    model : fitted :class:`~dislib_tpu.recommendation.ALS` (or any model
        exposing ``items_`` (n_items, f), ``lambda_`` and ``n_f``).
    nse_cap : int — observed ratings capacity per request row; the
        request width is ``2·nse_cap`` (the sparse ``n_features``).
    precision : mixed-precision policy for the fold-in contractions
        (None → the ``DSLIB_MATMUL_PRECISION`` default).
    top_n : int or None — when set, rank inside the fold-in dispatch
        (``lax.top_k`` fuses after the predict GEMM) and serve
        ``[item_ids | scores]`` rows of width ``2·top_n`` instead of the
        full score matrix — the response fetch shrinks from n_items to
        2·top_n floats per user.
    """

    def __init__(self, model, nse_cap=64, precision=None, top_n=None):
        from dislib_tpu.ops import precision as px
        if not hasattr(model, "items_"):
            raise ValueError("SparseFoldInPipeline needs a FITTED ALS "
                             "model (missing items_)")
        if model.items_.shape[0] >= _COL_ID_CEIL:
            raise ValueError("item count ≥ 2^24 doesn't ride the float32 "
                             "packed encoding")
        self.model = model
        self.nse_cap = int(nse_cap)
        self.n_features = 2 * self.nse_cap      # the packed request width
        self.policy = px.resolve(precision)
        self.top_n = None if top_n is None else int(top_n)
        self._templates: dict[int, BucketTemplate] = {}
        self.out_cols: int | None = None

    def pack(self, rows):
        """Convenience: :func:`pack_sparse_rows` at this pipeline's cap."""
        return pack_sparse_rows(rows, self.nse_cap,
                                self.model.items_.shape[0])

    def _template(self, bucket: int) -> BucketTemplate:
        tmpl = self._templates.get(bucket)
        if tmpl is None:
            # the packed encoding is shard-agnostic (the fold-in kernel
            # replicates the small factor matrix), so the staging canvas
            # is exactly the bucket shape — no mesh pad quantum
            tmpl = self._templates[bucket] = BucketTemplate(
                (bucket, self.n_features))
        return tmpl

    def predict_bucket(self, rows: np.ndarray, bucket: int) -> np.ndarray:
        """Serve one padded sparse batch: stage into the bucket canvas,
        ONE fused fold-in dispatch, fetch, slice — the dense
        ``ServePipeline.predict_bucket`` contract over the sparse
        encoding."""
        import jax
        import jax.numpy as jnp
        from dislib_tpu.recommendation.als import _als_fold_in_packed
        rows = np.asarray(rows, np.float32)
        if rows.ndim == 1:
            rows = rows.reshape(1, -1)
        if rows.shape[1] != self.n_features:
            raise ValueError(
                f"request width {rows.shape[1]} != 2·nse_cap="
                f"{self.n_features} — pack requests with pipeline.pack()")
        if rows.shape[0] > bucket:
            raise ValueError(f"{rows.shape[0]} rows exceed bucket {bucket}")
        buf = self._template(bucket).fill(rows)
        dev = jax.device_put(jnp.asarray(buf))
        (items,) = self.model._predict_leaves(self.model.items_)
        _, preds = _als_fold_in_packed(dev, items,
                                       float(self.model.lambda_),
                                       int(self.model.n_f), self.policy,
                                       top_n=int(self.top_n or 0))
        if self.top_n:
            # ranked serve: the SAME dispatch (top_k fused after the
            # predict GEMM) yields [item_ids | scores] response rows
            ids, scores = preds
            host = np.concatenate(
                [np.asarray(_fetch(ids), np.float32), _fetch(scores)],
                axis=1)
        else:
            host = _fetch(preds)            # force: ONE fused dispatch
        self.out_cols = int(host.shape[1])
        return host[: rows.shape[0]]

    # -- deployment-bundle capture ------------------------------------------

    def capture_bucket(self, bucket: int) -> dict:
        """AOT-capture this bucket's fold-in program for
        :func:`~dislib_tpu.serving.bundle.export_bundle` WITHOUT
        executing it: ``lower().compile()`` the single-output serve
        kernel on a placeholder request canvas and serialize the
        compiled executable.  The leaves are the placeholder (the input
        slot) plus the frozen item factors — the bundle carries the
        model, so a fresh process serves sparse fold-in with zero
        retraces through the standard ``load_bundle`` path."""
        import jax
        import jax.numpy as jnp
        from jax.experimental.serialize_executable import serialize
        placeholder = jax.device_put(
            jnp.asarray(np.zeros((int(bucket), self.n_features),
                                 np.float32)))
        (items,) = self.model._predict_leaves(self.model.items_)
        top_n = int(self.top_n or 0)
        # .lower counts a trace, never a dispatch (profiled_jit contract)
        compiled = _fold_in_serve.lower(
            placeholder, items, float(self.model.lambda_),
            int(self.model.n_f), self.policy, top_n=top_n).compile()
        payload, _in_tree, out_tree = serialize(compiled)
        out_cols = 2 * top_n if top_n else int(self.model.items_.shape[0])
        return {
            "payload": np.frombuffer(payload, np.uint8),
            "leaves": [placeholder, jnp.asarray(items)],
            "input_slot": 0,
            "n_outs": out_tree.num_leaves,
            "out_cols": out_cols,
            "pshape": [int(bucket), int(self.n_features)],
        }
