"""Sparse predict/fold-in serving — padded sparse batches through the
PredictServer bucket ladder.

A recommender's serving request is inherently sparse: a user arrives as
a handful of (item, rating) pairs, and the served computation is the ALS
fold-in (solve the user's normal equations against the FROZEN item
factors, emit predicted ratings for every item) — no refit, no dense
(n_items,) request vector.

**The padded-sparse request encoding.**  One request row is the fixed
width ``[cols | vals]`` — ``nse_cap`` column ids followed by ``nse_cap``
values, pads at (column 0, value 0), all float32.  That makes a sparse
batch a PLAIN (k, 2·nse_cap) host matrix, so the WHOLE PR-4 serving
machinery — :class:`PredictServer` micro-batching, the bucket ladder's
AOT-warmed fixed shapes, `ProgramCache`, hot-swap pools — applies
unchanged: the ladder quantizes k (the user count), ``nse_cap`` is the
pipeline's feature-width analog (a deployment parameter, like
``n_features``), and a padded row is a zero-observation user whose
fold-in solves λI·u = 0 → zero predictions the response slicing drops.
Column ids ride float32 exactly below 2²⁴ — guarded at construction.

The hot path is one staged host buffer → device_put → ONE fused
dispatch (`recommendation.als._als_fold_in_packed`: split, cast,
normal-equation solve, predict GEMM) → fetch, with the item factors
device-cached per generation via the estimator leaf cache — the model
is never re-transferred per batch (counter-asserted).
"""

from __future__ import annotations

import numpy as np

from dislib_tpu.serving.buckets import BucketTemplate
from dislib_tpu.runtime import fetch as _fetch

__all__ = ["SparseFoldInPipeline", "pack_sparse_rows"]

_COL_ID_CEIL = 1 << 24        # float32 carries integers exactly below this


def pack_sparse_rows(rows, nse_cap, n_items=None):
    """Pack per-user sparse ratings into the ``[cols | vals]`` request
    encoding: ``rows`` is a scipy sparse matrix, a list of
    ``(cols, vals)`` pairs, or a dense (k, n_items) ndarray (0 =
    unobserved).  Returns the (k, 2·nse_cap) float32 request block a
    :class:`PredictServer` over a :class:`SparseFoldInPipeline`
    accepts.  A user with more than ``nse_cap`` observed ratings is a
    typed error (pick the cap at deployment like a bucket ladder)."""
    import scipy.sparse as sp
    if isinstance(rows, np.ndarray):
        rows = sp.csr_matrix(np.atleast_2d(np.asarray(rows, np.float32)))
    if sp.issparse(rows):
        csr = rows.tocsr()
        pairs = [(csr.indices[csr.indptr[i]:csr.indptr[i + 1]],
                  csr.data[csr.indptr[i]:csr.indptr[i + 1]])
                 for i in range(csr.shape[0])]
        if n_items is None:
            n_items = csr.shape[1]
    else:
        pairs = list(rows)
    # host packing of HOST request data (the lint-scanned loop below must
    # stay free of array-conversion spellings that read as device syncs)
    pairs = [(np.asarray(c), np.asarray(v, np.float32)) for c, v in pairs]
    out = np.zeros((len(pairs), 2 * int(nse_cap)), np.float32)
    for i, (cols, vals) in enumerate(pairs):
        k = cols.size
        if k > nse_cap:
            raise ValueError(
                f"request row {i} has {k} observed ratings > "
                f"nse_cap={nse_cap} — raise the pipeline's cap")
        if k and (cols.min() < 0 or (n_items is not None
                                     and cols.max() >= n_items)):
            raise ValueError(f"request row {i}: item ids out of range")
        if k and cols.max() >= _COL_ID_CEIL:
            raise ValueError("item ids ≥ 2^24 don't ride float32 exactly")
        out[i, :k] = cols                   # ndarray assignment casts
        out[i, nse_cap:nse_cap + k] = vals
    return out


class SparseFoldInPipeline:
    """A fitted ALS model served as fold-in scoring over padded sparse
    batches — the drop-in `pipeline=` for :class:`PredictServer` (same
    ``n_features`` / ``predict_bucket`` surface as `ServePipeline`, so
    bucket warming, micro-batching, and hot-swap pools apply unchanged).

    Parameters
    ----------
    model : fitted :class:`~dislib_tpu.recommendation.ALS` (or any model
        exposing ``items_`` (n_items, f), ``lambda_`` and ``n_f``).
    nse_cap : int — observed ratings capacity per request row; the
        request width is ``2·nse_cap`` (the sparse ``n_features``).
    precision : mixed-precision policy for the fold-in contractions
        (None → the ``DSLIB_MATMUL_PRECISION`` default).
    top_n : int or None — when set, rank inside the fold-in dispatch
        (``lax.top_k`` fuses after the predict GEMM) and serve
        ``[item_ids | scores]`` rows of width ``2·top_n`` instead of the
        full score matrix — the response fetch shrinks from n_items to
        2·top_n floats per user.
    """

    def __init__(self, model, nse_cap=64, precision=None, top_n=None):
        from dislib_tpu.ops import precision as px
        if not hasattr(model, "items_"):
            raise ValueError("SparseFoldInPipeline needs a FITTED ALS "
                             "model (missing items_)")
        if model.items_.shape[0] >= _COL_ID_CEIL:
            raise ValueError("item count ≥ 2^24 doesn't ride the float32 "
                             "packed encoding")
        self.model = model
        self.nse_cap = int(nse_cap)
        self.n_features = 2 * self.nse_cap      # the packed request width
        self.policy = px.resolve(precision)
        self.top_n = None if top_n is None else int(top_n)
        self._templates: dict[int, BucketTemplate] = {}
        self.out_cols: int | None = None

    def pack(self, rows):
        """Convenience: :func:`pack_sparse_rows` at this pipeline's cap."""
        return pack_sparse_rows(rows, self.nse_cap,
                                self.model.items_.shape[0])

    def _template(self, bucket: int) -> BucketTemplate:
        tmpl = self._templates.get(bucket)
        if tmpl is None:
            # the packed encoding is shard-agnostic (the fold-in kernel
            # replicates the small factor matrix), so the staging canvas
            # is exactly the bucket shape — no mesh pad quantum
            tmpl = self._templates[bucket] = BucketTemplate(
                (bucket, self.n_features))
        return tmpl

    def predict_bucket(self, rows: np.ndarray, bucket: int) -> np.ndarray:
        """Serve one padded sparse batch: stage into the bucket canvas,
        ONE fused fold-in dispatch, fetch, slice — the dense
        ``ServePipeline.predict_bucket`` contract over the sparse
        encoding."""
        import jax
        import jax.numpy as jnp
        from dislib_tpu.recommendation.als import _als_fold_in_packed
        rows = np.asarray(rows, np.float32)
        if rows.ndim == 1:
            rows = rows.reshape(1, -1)
        if rows.shape[1] != self.n_features:
            raise ValueError(
                f"request width {rows.shape[1]} != 2·nse_cap="
                f"{self.n_features} — pack requests with pipeline.pack()")
        if rows.shape[0] > bucket:
            raise ValueError(f"{rows.shape[0]} rows exceed bucket {bucket}")
        buf = self._template(bucket).fill(rows)
        dev = jax.device_put(jnp.asarray(buf))
        (items,) = self.model._predict_leaves(self.model.items_)
        _, preds = _als_fold_in_packed(dev, items,
                                       float(self.model.lambda_),
                                       int(self.model.n_f), self.policy,
                                       top_n=int(self.top_n or 0))
        if self.top_n:
            # ranked serve: the SAME dispatch (top_k fused after the
            # predict GEMM) yields [item_ids | scores] response rows
            ids, scores = preds
            host = np.concatenate(
                [np.asarray(_fetch(ids), np.float32), _fetch(scores)],
                axis=1)
        else:
            host = _fetch(preds)            # force: ONE fused dispatch
        self.out_cols = int(host.shape[1])
        return host[: rows.shape[0]]
