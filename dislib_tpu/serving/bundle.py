"""AOT deployment bundles — kill serving cold-start (round-15 tentpole).

PR 4 made the warm path one cached dispatch, but a FRESH process still
pays the full trace+compile for every bucket shape before its first
response (~300 ms/bucket on this rig, tens of seconds per bucket at chip
scale).  The full-program-compilation discipline of arXiv:1810.09868
says the whole predict program is an ahead-of-time artifact — so make
it one: :func:`export_bundle` serializes the COMPILED predict
executables for the whole bucket ladder (``jax.jit`` AOT
``lower().compile()`` + ``jax.experimental.serialize_executable``),
their operand leaves (model parameters, padded exactly as the programs
expect), the bucket ladder, and the checksum-verified model state into
ONE versioned artifact; :func:`load_bundle` rehydrates a
``PredictServer``-ready pipeline in a fresh process with ZERO retraces
(trace-counter-pinned by ``tests/test_serving_fleet.py``).

Failure discipline, typed and loud:

- damaged bytes (truncation, bit rot, foreign file) raise
  ``SnapshotCorrupt`` from the verified reader — serving never builds a
  pipeline from bytes that fail their checksum;
- a fingerprint mismatch (different jax/jaxlib, platform, device kind or
  count, mesh shape, pad quantum — anything that invalidates a compiled
  executable) raises :class:`~dislib_tpu.runtime.BundleIncompatible`;
  pass ``build=`` to fall back LOUDLY to a fresh trace+compile from the
  bundle's embedded (still checksum-verified) model state instead.

All artifact bytes flow through ``runtime.bundle_io`` (the write/read
seam) and checkpoint state flows through the ``runtime.adoption`` gate —
both enforced by the serving lints in ``tests/test_serving.py``.
"""

from __future__ import annotations

import json
import os
import warnings

import numpy as np

from dislib_tpu.runtime import adopt_latest, fetch as _fetch
from dislib_tpu.runtime.bundle_io import (BundleIncompatible, read_bundle,
                                          write_bundle)
from dislib_tpu.serving.buckets import BucketTemplate, bucket_ladder
from dislib_tpu.utils import profiling as _prof

BUNDLE_FORMAT = 1

# meta entry key inside the artifact (everything else is per-bucket
# payload/leaf arrays and ``state__``-prefixed model state)
_META_KEY = "bundle_meta"
_STATE_PREFIX = "state__"

# fingerprint keys that MUST match for a serialized executable to run;
# anything else in the fingerprint is informational (statics provenance)
_HARD_KEYS = ("format", "jax", "jaxlib", "platform", "device_kind",
              "n_devices", "mesh_shape", "pad_quantum")


def runtime_fingerprint() -> dict:
    """The compatibility identity of THIS process for serialized
    executables: library format version, jax/jaxlib versions, device
    platform/kind/count, mesh shape, and pad quantum (it shapes every
    padded operand), plus informational statics (the overlap router
    mode and fusion cap the programs were traced under).  Hard keys
    (everything except ``statics``) must match between the exporting
    and loading process; ``load_bundle`` refuses typed-and-loud on any
    difference."""
    import jax
    import jaxlib

    from dislib_tpu.parallel import mesh as _mesh
    devs = jax.devices()
    return {
        "format": BUNDLE_FORMAT,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": devs[0].platform,
        "device_kind": devs[0].device_kind,
        "n_devices": len(devs),
        "mesh_shape": list(_mesh.mesh_shape(None)),
        "pad_quantum": int(_mesh.pad_quantum()),
        "statics": {
            "overlap": os.environ.get("DSLIB_OVERLAP", "db"),
            "fusion_cap": os.environ.get("DSLIB_FUSION_CAP", "96"),
        },
    }


def _capture_bucket(pipeline, bucket: int):
    """AOT-capture one bucket's predict program WITHOUT executing it:
    build the deferred chain on a placeholder input, linearize it, and
    ``lower().compile()`` the fused program exactly as the first warm
    dispatch would have.  Returns everything a fresh process needs to
    re-invoke the compiled executable: the serialized payload, the
    canonicalized operand leaves, the input leaf's slot, and the output
    metadata."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.serialize_executable import serialize

    from dislib_tpu.data.array import (Array, _exec_program, _linearize,
                                       _padded_shape)
    from dislib_tpu.parallel import mesh as _mesh

    pshape = _padded_shape((bucket, pipeline.n_features),
                           _mesh.pad_quantum())
    placeholder = jax.device_put(np.zeros(pshape, np.float32),
                                 _mesh.data_sharding())
    out = pipeline(Array(placeholder, (bucket, pipeline.n_features)))
    if not out.is_lazy:
        raise RuntimeError(
            "the predict chain forced during capture — the pipeline is "
            "not exportable as one fused program (DSLIB_EAGER=1, or the "
            "chain exceeds DSLIB_FUSION_CAP); disable eager mode or "
            "raise the cap to export a bundle")
    program, leaves, _shared = _linearize(out._lazy)
    slots = [i for i, leaf in enumerate(leaves) if leaf is placeholder]
    if len(slots) != 1:
        raise RuntimeError(
            f"bucket {bucket}: expected the request buffer to be exactly "
            f"one program leaf, found {len(slots)} — the pipeline does "
            "not consume its input as a single operand")
    # canonicalize every leaf to a committed device array so the lowered
    # avals (dtype, weak_type) match what a host→device round trip of
    # the stored leaf reproduces at load time
    canon = [jnp.asarray(leaf) for leaf in leaves]
    compiled = _exec_program.lower(program, *canon).compile()
    payload, _in_tree, out_tree = serialize(compiled)
    return {
        "payload": np.frombuffer(payload, np.uint8),
        "leaves": canon,
        "input_slot": slots[0],
        "n_outs": out_tree.num_leaves,
        "out_cols": int(out.shape[1]),
        "pshape": list(pshape),
    }


def export_bundle(pipeline, path: str, buckets=None, checkpoint=None,
                  state=None) -> dict:
    """Serialize ``pipeline``'s compiled predict executables for every
    ladder bucket into ONE versioned artifact at ``path``.

    Parameters
    ----------
    pipeline : ServePipeline — the fitted chain to export.  Its fused
        program per bucket is lowered and compiled ahead of time (the
        export pays the traces so the loading process never does).
    path : str — artifact file (atomic write, embedded checksum).
    buckets : bucket ladder; default per
        :func:`~dislib_tpu.serving.buckets.bucket_ladder`.
    checkpoint : FitCheckpoint, optional — embed the newest generation's
        model state, read THROUGH the ``runtime.adoption`` gate
        (checksum verify + non-finite state gate), so the artifact's
        state carries the same trust as a hot-swap adoption.
    state : dict, optional — embed an explicit state dict instead (the
        caller already holds verified state).  Mutually exclusive with
        ``checkpoint``.

    Returns the manifest dict (also embedded in the artifact).
    """
    if checkpoint is not None and state is not None:
        raise ValueError("pass at most one of checkpoint= or state=")
    buckets = bucket_ladder(buckets)
    if checkpoint is not None:
        adoption = adopt_latest(checkpoint, build=lambda s: s,
                                name="bundle-export")
        if adoption is None:
            raise ValueError(
                "checkpoint has no generation to embed — save one before "
                "exporting a bundle")
        state = adoption.state
    entries: dict = {}
    manifest: dict = {"format": BUNDLE_FORMAT,
                      "fingerprint": runtime_fingerprint(),
                      "buckets": list(buckets),
                      "n_features": int(pipeline.n_features),
                      "per_bucket": {}}
    for b in buckets:
        # capture protocol (round 18): pipelines whose predict program is
        # not a fusion-chain lazy array (the retrieval tier's shard_map
        # search, the sparse fold-in) AOT-capture their own kernel via a
        # ``capture_bucket`` method returning the same dict shape; the
        # fusion-chain linearizer stays the default
        if hasattr(pipeline, "capture_bucket"):
            cap = pipeline.capture_bucket(b)
        else:
            cap = _capture_bucket(pipeline, b)
        entries[f"exec_{b}"] = cap["payload"]
        for i, leaf in enumerate(cap["leaves"]):
            # one device→host sync per leaf at EXPORT time (offline by
            # definition); the serving hot path never comes through here
            entries[f"leaf_{b}_{i}"] = np.asarray(leaf)
        manifest["per_bucket"][str(b)] = {
            "input_slot": cap["input_slot"],
            "n_leaves": len(cap["leaves"]),
            "n_outs": cap["n_outs"],
            "out_cols": cap["out_cols"],
            "pshape": cap["pshape"],
        }
    if state is not None:
        for k, v in state.items():
            entries[_STATE_PREFIX + k] = np.asarray(v)
    entries[_META_KEY] = np.asarray(json.dumps(manifest))
    write_bundle(path, entries)
    return manifest


class _BucketExec:
    """One bucket's rehydrated executable: the loaded compiled program,
    its device-placed static leaves (model parameters — transferred once
    at load, never per request), the input slot, and output metadata."""

    __slots__ = ("call", "args", "input_slot", "in_sharding", "out_cols",
                 "template")

    def __init__(self, call, args, input_slot, in_sharding, out_cols,
                 pshape):
        self.call = call
        self.args = args
        self.input_slot = input_slot
        self.in_sharding = in_sharding
        self.out_cols = out_cols
        self.template = BucketTemplate(pshape)


class BundlePipeline:
    """A ``PredictServer``-ready pipeline rehydrated from a deployment
    bundle: ``predict_bucket`` is host staging → one input transfer →
    ONE deserialized-executable invocation → fetch → slice, with ZERO
    tracing anywhere (there is no traceable Python body left — the
    program is bytes).  Dispatches are counted under ``bundle_exec`` so
    the server's one-dispatch-per-batch invariant stays a counter
    assertion on this path too.

    Not thread-safe (same contract as ``ServePipeline``): the serving
    worker or one caller drives it.
    """

    def __init__(self, buckets, n_features, execs):
        self.buckets = tuple(buckets)
        self.n_features = int(n_features)
        self._execs = dict(execs)
        self.out_cols = next(iter(self._execs.values())).out_cols \
            if self._execs else None

    def predict_bucket(self, rows: np.ndarray, bucket: int) -> np.ndarray:
        import jax
        rows = np.asarray(rows, np.float32)
        if rows.ndim == 1:
            rows = rows.reshape(1, -1)
        if rows.shape[1] != self.n_features:
            raise ValueError(f"request has {rows.shape[1]} features, "
                             f"bundle serves {self.n_features}")
        ex = self._execs.get(int(bucket))
        if ex is None:
            raise ValueError(
                f"bucket {bucket} is not in the bundle's compiled ladder "
                f"{self.buckets} — a bundle serves exactly the shapes it "
                "was exported for")
        if rows.shape[0] > bucket:
            raise ValueError(f"{rows.shape[0]} rows exceed bucket {bucket}")
        buf = ex.template.fill(rows)
        dev = jax.device_put(buf, ex.in_sharding) \
            if ex.in_sharding is not None else jax.device_put(buf)
        args = list(ex.args)
        args[ex.input_slot] = dev
        _prof.count_dispatch("bundle_exec")
        outs = ex.call(*args)
        host = _fetch(outs[0])
        return host[: rows.shape[0], : ex.out_cols]


class LoadedBundle:
    """:func:`load_bundle`'s result: the servable ``pipeline`` (a
    :class:`BundlePipeline`, or a fresh ``build(state)`` pipeline when
    ``fallback`` is True), the embedded checksum-verified ``state``, the
    ``buckets`` ladder, the exporting process's ``fingerprint``, and
    ``fallback`` — True when the executables were unusable here and the
    pipeline will pay a fresh trace+compile per bucket instead."""

    __slots__ = ("pipeline", "state", "buckets", "fingerprint", "fallback")

    def __init__(self, pipeline, state, buckets, fingerprint, fallback):
        self.pipeline = pipeline
        self.state = state
        self.buckets = tuple(buckets)
        self.fingerprint = fingerprint
        self.fallback = fallback

    def __repr__(self):
        return (f"LoadedBundle(buckets={self.buckets}, "
                f"fallback={self.fallback})")


def _fallback(build, state, meta, err):
    """The loud typed fallback: the bundle's executables cannot run here
    but its model state is checksum-verified — rebuild fresh (paying
    trace+compile) when the caller gave us a builder, else raise."""
    if build is None:
        raise err
    if not state:
        raise BundleIncompatible(
            f"{err} — and the bundle embeds no model state to rebuild "
            "from (export with checkpoint= or state=)",
            expected=err.expected, found=err.found) from err
    warnings.warn(
        f"deployment bundle unusable here ({err}); falling back to a "
        "fresh trace+compile from the bundle's embedded model state — "
        "cold-start protection is LOST for this process",
        RuntimeWarning, stacklevel=3)
    return LoadedBundle(build(state), state, meta["buckets"],
                        meta["fingerprint"], fallback=True)


def load_bundle(path: str, build=None) -> LoadedBundle:
    """Rehydrate a deployment bundle into a ``PredictServer``-ready
    pipeline with zero retraces.

    The read verifies the artifact checksum (``SnapshotCorrupt`` on any
    damage — typed, never a half-read pipeline), then compares the
    embedded fingerprint against this process (:func:`runtime_fingerprint`
    hard keys).  On mismatch — or when executable deserialization itself
    fails — raises :class:`~dislib_tpu.runtime.BundleIncompatible`;
    pass ``build`` (``state_dict -> ServePipeline``) to instead fall
    back loudly to a fresh compile from the embedded state.
    """
    import jax.tree_util as jtu
    from jax.experimental.serialize_executable import deserialize_and_load

    raw = read_bundle(path)
    if _META_KEY not in raw:
        raise BundleIncompatible(
            f"{path} verifies but carries no bundle manifest — not a "
            "deployment bundle")
    meta = json.loads(str(raw[_META_KEY][()]))
    state = {k[len(_STATE_PREFIX):]: v for k, v in raw.items()
             if k.startswith(_STATE_PREFIX)}
    here = runtime_fingerprint()
    theirs = meta.get("fingerprint", {})
    mismatched = [k for k in _HARD_KEYS if theirs.get(k) != here.get(k)]
    if mismatched:
        diff = {k: {"bundle": theirs.get(k), "here": here.get(k)}
                for k in mismatched}
        return _fallback(build, state, meta, BundleIncompatible(
            f"bundle {path} was exported under a different runtime "
            f"({diff}) — its compiled executables cannot run here",
            expected=theirs, found=here))
    execs = {}
    try:
        for b in meta["buckets"]:
            pb = meta["per_bucket"][str(b)]
            payload = raw[f"exec_{b}"].tobytes()
            in_tree = jtu.tree_structure(
                (tuple(range(pb["n_leaves"])), {}))
            out_tree = jtu.tree_structure(tuple(range(pb["n_outs"])))
            loaded = deserialize_and_load(payload, in_tree, out_tree)
            shardings = getattr(loaded, "input_shardings", None)
            shardings = shardings[0] if shardings else None
            args = []
            import jax
            for i in range(pb["n_leaves"]):
                leaf = raw[f"leaf_{b}_{i}"]
                args.append(jax.device_put(leaf, shardings[i])
                            if shardings is not None else leaf)
            execs[int(b)] = _BucketExec(
                loaded, args, pb["input_slot"],
                shardings[pb["input_slot"]] if shardings is not None
                else None,
                pb["out_cols"], pb["pshape"])
    except BundleIncompatible:
        raise
    except Exception as e:  # noqa: BLE001 — deserialize failure is typed
        return _fallback(build, state, meta, BundleIncompatible(
            f"bundle {path} fingerprint matches but executable "
            f"deserialization failed ({type(e).__name__}: {e})",
            expected=theirs, found=here))
    pipe = BundlePipeline(meta["buckets"], meta["n_features"], execs)
    return LoadedBundle(pipe, state, meta["buckets"], theirs,
                        fallback=False)
