"""AOT deployment bundles — kill serving cold-start (round-15 tentpole).

PR 4 made the warm path one cached dispatch, but a FRESH process still
pays the full trace+compile for every bucket shape before its first
response (~300 ms/bucket on this rig, tens of seconds per bucket at chip
scale).  The full-program-compilation discipline of arXiv:1810.09868
says the whole predict program is an ahead-of-time artifact — so make
it one: :func:`export_bundle` serializes the COMPILED predict
executables for the whole bucket ladder (``jax.jit`` AOT
``lower().compile()`` + ``jax.experimental.serialize_executable``),
their operand leaves (model parameters, padded exactly as the programs
expect), the bucket ladder, and the checksum-verified model state into
ONE versioned artifact; :func:`load_bundle` rehydrates a
``PredictServer``-ready pipeline in a fresh process with ZERO retraces
(trace-counter-pinned by ``tests/test_serving_fleet.py``).

Failure discipline, typed and loud:

- damaged bytes (truncation, bit rot, foreign file) raise
  ``SnapshotCorrupt`` from the verified reader — serving never builds a
  pipeline from bytes that fail their checksum;
- a fingerprint mismatch (different jax/jaxlib, platform, device kind or
  count, mesh shape, pad quantum — anything that invalidates a compiled
  executable) raises :class:`~dislib_tpu.runtime.BundleIncompatible`;
  pass ``build=`` to fall back LOUDLY to a fresh trace+compile from the
  bundle's embedded (still checksum-verified) model state instead.

All artifact bytes flow through ``runtime.bundle_io`` (the write/read
seam) and checkpoint state flows through the ``runtime.adoption`` gate —
both enforced by the serving lints in ``tests/test_serving.py``.
"""

from __future__ import annotations

import json
import os
import warnings

import numpy as np

from dislib_tpu.runtime import adopt_latest, fetch as _fetch
from dislib_tpu.runtime.bundle_io import (BundleIncompatible,
                                          BundleShardCorrupt, file_crc,
                                          read_bundle, shard_path,
                                          write_bundle)
from dislib_tpu.serving.buckets import BucketTemplate, bucket_ladder
from dislib_tpu.utils import profiling as _prof

BUNDLE_FORMAT = 1

# meta entry key inside the artifact (everything else is per-bucket
# payload/leaf arrays and ``state__``-prefixed model state)
_META_KEY = "bundle_meta"
_STATE_PREFIX = "state__"

# fingerprint keys that MUST match for a serialized executable to run;
# anything else in the fingerprint is informational (statics provenance)
_HARD_KEYS = ("format", "jax", "jaxlib", "platform", "device_kind",
              "n_devices", "mesh_shape", "pad_quantum")

# a SHARDED bundle replaces the global-shape pins (device count, mesh
# shape) with the manifest's mesh CONTRACT — hosts × devices-per-host —
# so a bundle exported on one fleet layout loads on any fleet honoring
# the contract, not only a bit-identical process (round 19)
_SHARD_HARD_KEYS = tuple(k for k in _HARD_KEYS
                         if k not in ("n_devices", "mesh_shape"))


def runtime_fingerprint() -> dict:
    """The compatibility identity of THIS process for serialized
    executables: library format version, jax/jaxlib versions, device
    platform/kind/count, mesh shape, and pad quantum (it shapes every
    padded operand), plus informational statics (the overlap router
    mode and fusion cap the programs were traced under).  Hard keys
    (everything except ``statics``) must match between the exporting
    and loading process; ``load_bundle`` refuses typed-and-loud on any
    difference."""
    import jax
    import jaxlib

    from dislib_tpu.parallel import mesh as _mesh
    devs = jax.devices()
    return {
        "format": BUNDLE_FORMAT,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": devs[0].platform,
        "device_kind": devs[0].device_kind,
        "n_devices": len(devs),
        "mesh_shape": list(_mesh.mesh_shape(None)),
        "pad_quantum": int(_mesh.pad_quantum()),
        "statics": {
            "overlap": os.environ.get("DSLIB_OVERLAP", "db"),
            "fusion_cap": os.environ.get("DSLIB_FUSION_CAP", "96"),
        },
    }


def _capture_bucket(pipeline, bucket: int):
    """AOT-capture one bucket's predict program WITHOUT executing it:
    build the deferred chain on a placeholder input, linearize it, and
    ``lower().compile()`` the fused program exactly as the first warm
    dispatch would have.  Returns everything a fresh process needs to
    re-invoke the compiled executable: the serialized payload, the
    canonicalized operand leaves, the input leaf's slot, and the output
    metadata."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.serialize_executable import serialize

    from dislib_tpu.data.array import (Array, _exec_program, _linearize,
                                       _padded_shape)
    from dislib_tpu.parallel import mesh as _mesh

    pshape = _padded_shape((bucket, pipeline.n_features),
                           _mesh.pad_quantum())
    placeholder = jax.device_put(np.zeros(pshape, np.float32),
                                 _mesh.data_sharding())
    out = pipeline(Array(placeholder, (bucket, pipeline.n_features)))
    if not out.is_lazy:
        raise RuntimeError(
            "the predict chain forced during capture — the pipeline is "
            "not exportable as one fused program (DSLIB_EAGER=1, or the "
            "chain exceeds DSLIB_FUSION_CAP); disable eager mode or "
            "raise the cap to export a bundle")
    program, leaves, _shared = _linearize(out._lazy)
    slots = [i for i, leaf in enumerate(leaves) if leaf is placeholder]
    if len(slots) != 1:
        raise RuntimeError(
            f"bucket {bucket}: expected the request buffer to be exactly "
            f"one program leaf, found {len(slots)} — the pipeline does "
            "not consume its input as a single operand")
    # canonicalize every leaf to a committed device array so the lowered
    # avals (dtype, weak_type) match what a host→device round trip of
    # the stored leaf reproduces at load time
    canon = [jnp.asarray(leaf) for leaf in leaves]
    compiled = _exec_program.lower(program, *canon).compile()
    payload, _in_tree, out_tree = serialize(compiled)
    return {
        "payload": np.frombuffer(payload, np.uint8),
        "leaves": canon,
        "input_slot": slots[0],
        "n_outs": out_tree.num_leaves,
        "out_cols": int(out.shape[1]),
        "pshape": list(pshape),
    }


def _resolve_state(checkpoint, state):
    if checkpoint is not None and state is not None:
        raise ValueError("pass at most one of checkpoint= or state=")
    if checkpoint is not None:
        adoption = adopt_latest(checkpoint, build=lambda s: s,
                                name="bundle-export")
        if adoption is None:
            raise ValueError(
                "checkpoint has no generation to embed — save one before "
                "exporting a bundle")
        state = adoption.state
    return state


def _capture_entries(pipeline, buckets):
    """Run the per-bucket AOT capture loop once: the payload/leaf entry
    dict plus the manifest's ``per_bucket`` metadata."""
    entries: dict = {}
    per_bucket: dict = {}
    for b in buckets:
        # capture protocol (round 18): pipelines whose predict program is
        # not a fusion-chain lazy array (the retrieval tier's shard_map
        # search, the sparse fold-in) AOT-capture their own kernel via a
        # ``capture_bucket`` method returning the same dict shape; the
        # fusion-chain linearizer stays the default
        if hasattr(pipeline, "capture_bucket"):
            cap = pipeline.capture_bucket(b)
        else:
            cap = _capture_bucket(pipeline, b)
        entries[f"exec_{b}"] = cap["payload"]
        for i, leaf in enumerate(cap["leaves"]):
            # one device→host sync per leaf at EXPORT time (offline by
            # definition); the serving hot path never comes through here
            entries[f"leaf_{b}_{i}"] = np.asarray(leaf)
        per_bucket[str(b)] = {
            "input_slot": cap["input_slot"],
            "n_leaves": len(cap["leaves"]),
            "n_outs": cap["n_outs"],
            "out_cols": cap["out_cols"],
            "pshape": cap["pshape"],
        }
    return entries, per_bucket


def export_bundle(pipeline, path: str, buckets=None, checkpoint=None,
                  state=None, hosts=None) -> dict:
    """Serialize ``pipeline``'s compiled predict executables for every
    ladder bucket into ONE versioned artifact at ``path``.

    Parameters
    ----------
    pipeline : ServePipeline — the fitted chain to export.  Its fused
        program per bucket is lowered and compiled ahead of time (the
        export pays the traces so the loading process never does).
    path : str — artifact file (atomic write, embedded checksum).
    buckets : bucket ladder; default per
        :func:`~dislib_tpu.serving.buckets.bucket_ladder`.
    checkpoint : FitCheckpoint, optional — embed the newest generation's
        model state, read THROUGH the ``runtime.adoption`` gate
        (checksum verify + non-finite state gate), so the artifact's
        state carries the same trust as a hot-swap adoption.
    state : dict, optional — embed an explicit state dict instead (the
        caller already holds verified state).  Mutually exclusive with
        ``checkpoint``.
    hosts : int, optional — write a SHARDED bundle for an N-host fleet
        instead: one ``<path>.shard<r>`` artifact per host plus the
        manifest at ``path`` (per-shard checksums, runtime fingerprint,
        mesh contract).  ``load_bundle`` on such a manifest runs the
        coordinated load barrier — every host verifies its shard before
        ANY host serves.  In a multi-process job each process writes its
        own shard (``hosts`` must equal the process count, rank 0 writes
        the manifest); a single process writes all N shards — the mock
        fleet used by tier-1 and by offline export-for-a-fleet.

    Returns the manifest dict (also embedded in the artifact).
    """
    state = _resolve_state(checkpoint, state)
    buckets = bucket_ladder(buckets)
    if hosts is not None:
        return _export_sharded(pipeline, path, buckets, state, int(hosts))
    entries, per_bucket = _capture_entries(pipeline, buckets)
    manifest: dict = {"format": BUNDLE_FORMAT,
                      "fingerprint": runtime_fingerprint(),
                      "buckets": list(buckets),
                      "n_features": int(pipeline.n_features),
                      "per_bucket": per_bucket}
    if state is not None:
        for k, v in state.items():
            entries[_STATE_PREFIX + k] = np.asarray(v)
    entries[_META_KEY] = np.asarray(json.dumps(manifest))
    write_bundle(path, entries)
    return manifest


def _mesh_contract(hosts: int) -> dict:
    """What a loading fleet must LOOK like for the shards to serve: the
    host count, each host's device count, and the padded-layout facts
    (mesh shape, pad quantum) the executables were compiled against.
    This replaces the flat bundle's exact ``n_devices`` pin — any fleet
    honoring the contract can load, not only the exporting process."""
    import jax

    from dislib_tpu.parallel import mesh as _mesh
    n = len(jax.devices())
    if n % hosts:
        raise ValueError(
            f"export_bundle(hosts={hosts}): {n} devices do not split "
            f"evenly across {hosts} hosts — the mesh contract needs a "
            "uniform per-host device count")
    return {"hosts": int(hosts), "devices_per_host": n // hosts,
            "mesh_shape": list(_mesh.mesh_shape(None)),
            "pad_quantum": int(_mesh.pad_quantum())}


def _export_sharded(pipeline, path, buckets, state, hosts: int) -> dict:
    import jax

    from dislib_tpu.runtime.coord import get_coordinator
    if hosts < 1:
        raise ValueError(f"export_bundle(hosts={hosts}): need >= 1")
    pc = jax.process_count()
    if pc > 1 and hosts != pc:
        raise ValueError(
            f"export_bundle(hosts={hosts}) in a {pc}-process job: each "
            "process writes exactly its own shard, so hosts must equal "
            "the process count")
    contract = _mesh_contract(hosts)
    entries, per_bucket = _capture_entries(pipeline, buckets)
    if state is not None:
        for k, v in state.items():
            entries[_STATE_PREFIX + k] = np.asarray(v)
    common = {"format": BUNDLE_FORMAT, "sharded": True,
              "hosts": int(hosts),
              "fingerprint": runtime_fingerprint(),
              "buckets": list(buckets),
              "n_features": int(pipeline.n_features),
              "per_bucket": per_bucket,
              "mesh_contract": contract}
    my_ranks = [jax.process_index()] if pc > 1 else range(hosts)
    for r in my_ranks:
        shard_meta = dict(common, host=int(r), hosts=int(hosts))
        shard_entries = dict(entries)
        shard_entries[_META_KEY] = np.asarray(json.dumps(shard_meta))
        write_bundle(shard_path(path, r), shard_entries)
    # gather every shard's file checksum, then rank 0 publishes the
    # manifest; the exchange doubles as the export barrier (no manifest
    # can name a shard that is not fully on disk)
    base = os.path.basename(path)
    if pc > 1:
        from dislib_tpu.runtime.coord import resilient_exchange
        coord = get_coordinator()
        mine = file_crc(shard_path(path, jax.process_index()))
        crcs = resilient_exchange(coord, f"bundle-export:{base}",
                                  jax.process_index(), mine, hosts)
        shard_crcs = [int(crcs[r]) for r in range(hosts)]
    else:
        shard_crcs = [file_crc(shard_path(path, r)) for r in range(hosts)]
    manifest = dict(common, shard_crcs=shard_crcs)
    if pc <= 1 or jax.process_index() == 0:
        write_bundle(path, {_META_KEY: np.asarray(json.dumps(manifest))})
    if pc > 1:
        # all ranks block until the manifest is on disk (rank 0 posts
        # after its atomic write) — export returns only when loadable
        get_coordinator().exchange(f"bundle-manifest:{base}",
                                   jax.process_index(), True, n=hosts)
    return manifest


class _BucketExec:
    """One bucket's rehydrated executable: the loaded compiled program,
    its device-placed static leaves (model parameters — transferred once
    at load, never per request), the input slot, and output metadata."""

    __slots__ = ("call", "args", "input_slot", "in_sharding", "out_cols",
                 "template")

    def __init__(self, call, args, input_slot, in_sharding, out_cols,
                 pshape):
        self.call = call
        self.args = args
        self.input_slot = input_slot
        self.in_sharding = in_sharding
        self.out_cols = out_cols
        self.template = BucketTemplate(pshape)


class BundlePipeline:
    """A ``PredictServer``-ready pipeline rehydrated from a deployment
    bundle: ``predict_bucket`` is host staging → one input transfer →
    ONE deserialized-executable invocation → fetch → slice, with ZERO
    tracing anywhere (there is no traceable Python body left — the
    program is bytes).  Dispatches are counted under ``bundle_exec`` so
    the server's one-dispatch-per-batch invariant stays a counter
    assertion on this path too.

    Not thread-safe (same contract as ``ServePipeline``): the serving
    worker or one caller drives it.
    """

    def __init__(self, buckets, n_features, execs):
        self.buckets = tuple(buckets)
        self.n_features = int(n_features)
        self._execs = dict(execs)
        self.out_cols = next(iter(self._execs.values())).out_cols \
            if self._execs else None

    def predict_bucket(self, rows: np.ndarray, bucket: int) -> np.ndarray:
        import jax
        rows = np.asarray(rows, np.float32)
        if rows.ndim == 1:
            rows = rows.reshape(1, -1)
        if rows.shape[1] != self.n_features:
            raise ValueError(f"request has {rows.shape[1]} features, "
                             f"bundle serves {self.n_features}")
        ex = self._execs.get(int(bucket))
        if ex is None:
            raise ValueError(
                f"bucket {bucket} is not in the bundle's compiled ladder "
                f"{self.buckets} — a bundle serves exactly the shapes it "
                "was exported for")
        if rows.shape[0] > bucket:
            raise ValueError(f"{rows.shape[0]} rows exceed bucket {bucket}")
        buf = ex.template.fill(rows)
        dev = jax.device_put(buf, ex.in_sharding) \
            if ex.in_sharding is not None else jax.device_put(buf)
        args = list(ex.args)
        args[ex.input_slot] = dev
        _prof.count_dispatch("bundle_exec")
        outs = ex.call(*args)
        host = _fetch(outs[0])
        return host[: rows.shape[0], : ex.out_cols]


class LoadedBundle:
    """:func:`load_bundle`'s result: the servable ``pipeline`` (a
    :class:`BundlePipeline`, or a fresh ``build(state)`` pipeline when
    ``fallback`` is True), the embedded checksum-verified ``state``, the
    ``buckets`` ladder, the exporting process's ``fingerprint``, and
    ``fallback`` — True when the executables were unusable here and the
    pipeline will pay a fresh trace+compile per bucket instead.

    For a SHARDED bundle, ``hosts`` is the fleet size the bundle was
    exported for and ``host`` the shard this process serves; both are
    None for a flat bundle."""

    __slots__ = ("pipeline", "state", "buckets", "fingerprint", "fallback",
                 "hosts", "host")

    def __init__(self, pipeline, state, buckets, fingerprint, fallback,
                 hosts=None, host=None):
        self.pipeline = pipeline
        self.state = state
        self.buckets = tuple(buckets)
        self.fingerprint = fingerprint
        self.fallback = fallback
        self.hosts = hosts
        self.host = host

    def __repr__(self):
        shard = f", host={self.host}/{self.hosts}" \
            if self.hosts is not None else ""
        return (f"LoadedBundle(buckets={self.buckets}, "
                f"fallback={self.fallback}{shard})")


def _fallback(build, state, meta, err):
    """The loud typed fallback: the bundle's executables cannot run here
    but its model state is checksum-verified — rebuild fresh (paying
    trace+compile) when the caller gave us a builder, else raise."""
    if build is None:
        raise err
    if not state:
        raise BundleIncompatible(
            f"{err} — and the bundle embeds no model state to rebuild "
            "from (export with checkpoint= or state=)",
            expected=err.expected, found=err.found) from err
    warnings.warn(
        f"deployment bundle unusable here ({err}); falling back to a "
        "fresh trace+compile from the bundle's embedded model state — "
        "cold-start protection is LOST for this process",
        RuntimeWarning, stacklevel=3)
    return LoadedBundle(build(state), state, meta["buckets"],
                        meta["fingerprint"], fallback=True)


def load_bundle(path: str, build=None, timeout: float | None = None) \
        -> LoadedBundle:
    """Rehydrate a deployment bundle into a ``PredictServer``-ready
    pipeline with zero retraces.

    The read verifies the artifact checksum (``SnapshotCorrupt`` on any
    damage — typed, never a half-read pipeline), then compares the
    embedded fingerprint against this process (:func:`runtime_fingerprint`
    hard keys).  On mismatch — or when executable deserialization itself
    fails — raises :class:`~dislib_tpu.runtime.BundleIncompatible`;
    pass ``build`` (``state_dict -> ServePipeline``) to instead fall
    back loudly to a fresh compile from the embedded state.

    A SHARDED bundle (``export_bundle(hosts=N)``; ``path`` names the
    manifest) instead runs the coordinated load barrier first: this
    process verifies its own shard (manifest checksum + artifact CRC),
    exchanges the verdict with every peer through ``runtime.coord``,
    and only when ALL hosts verified does anyone deserialize — one
    corrupt shard raises the same typed
    :class:`~dislib_tpu.runtime.BundleShardCorrupt` on every host, and
    zero hosts serve.  ``timeout`` bounds the barrier wait — default
    ``DSLIB_BARRIER_TIMEOUT`` (30 s): one DEAD host aborts ALL hosts
    within this budget with the typed
    :class:`~dislib_tpu.runtime.RankDead` (when membership leases have
    confirmed who died) or :class:`~dislib_tpu.runtime.CoordinationTimeout`
    — never a hung fleet.
    """
    if timeout is None:
        from dislib_tpu.runtime.coord import barrier_timeout
        timeout = barrier_timeout()
    raw = read_bundle(path)
    if _META_KEY not in raw:
        raise BundleIncompatible(
            f"{path} verifies but carries no bundle manifest — not a "
            "deployment bundle")
    meta = json.loads(str(raw[_META_KEY][()]))
    if meta.get("sharded"):
        return _load_sharded(path, meta, build, timeout)
    state = {k[len(_STATE_PREFIX):]: v for k, v in raw.items()
             if k.startswith(_STATE_PREFIX)}
    here = runtime_fingerprint()
    theirs = meta.get("fingerprint", {})
    mismatched = [k for k in _HARD_KEYS if theirs.get(k) != here.get(k)]
    if mismatched:
        diff = {k: {"bundle": theirs.get(k), "here": here.get(k)}
                for k in mismatched}
        return _fallback(build, state, meta, BundleIncompatible(
            f"bundle {path} was exported under a different runtime "
            f"({diff}) — its compiled executables cannot run here",
            expected=theirs, found=here))
    try:
        execs = _build_execs(raw, meta)
    except BundleIncompatible:
        raise
    except Exception as e:  # noqa: BLE001 — deserialize failure is typed
        return _fallback(build, state, meta, BundleIncompatible(
            f"bundle {path} fingerprint matches but executable "
            f"deserialization failed ({type(e).__name__}: {e})",
            expected=theirs, found=here))
    pipe = BundlePipeline(meta["buckets"], meta["n_features"], execs)
    return LoadedBundle(pipe, state, meta["buckets"], theirs,
                        fallback=False)


def _build_execs(raw, meta) -> dict:
    """Rehydrate every bucket's compiled executable from a verified raw
    entry dict (the flat artifact, or this host's shard)."""
    import jax
    import jax.tree_util as jtu
    from jax.experimental.serialize_executable import deserialize_and_load

    execs = {}
    for b in meta["buckets"]:
        pb = meta["per_bucket"][str(b)]
        payload = raw[f"exec_{b}"].tobytes()
        in_tree = jtu.tree_structure(
            (tuple(range(pb["n_leaves"])), {}))
        out_tree = jtu.tree_structure(tuple(range(pb["n_outs"])))
        loaded = deserialize_and_load(payload, in_tree, out_tree)
        shardings = getattr(loaded, "input_shardings", None)
        shardings = shardings[0] if shardings else None
        args = []
        for i in range(pb["n_leaves"]):
            leaf = raw[f"leaf_{b}_{i}"]
            args.append(jax.device_put(leaf, shardings[i])
                        if shardings is not None else leaf)
        execs[int(b)] = _BucketExec(
            loaded, args, pb["input_slot"],
            shardings[pb["input_slot"]] if shardings is not None
            else None,
            pb["out_cols"], pb["pshape"])
    return execs


def _verify_shard(path, manifest, r):
    """One host's shard verification: manifest CRC over the artifact
    bytes, then the checksum-verified read.  Returns ``(vote, raw)`` —
    the vote is what goes through the barrier exchange."""
    from dislib_tpu.utils.checkpoint import SnapshotCorrupt
    sp = shard_path(path, r)
    try:
        crc = file_crc(sp)
    except OSError as e:
        return {"ok": False, "reason": f"shard unreadable: {e}"}, None
    want = int(manifest["shard_crcs"][r])
    if crc != want:
        return {"ok": False,
                "reason": f"shard CRC {crc:#010x} != manifest "
                          f"{want:#010x} — damaged or replaced"}, None
    try:
        raw = read_bundle(sp)
    except SnapshotCorrupt as e:
        return {"ok": False, "reason": f"shard fails its embedded "
                                       f"checksum: {e}"}, None
    return {"ok": True}, raw


def _barrier_exchange(coord, name, rank, vote, n, timeout, path):
    """The load-barrier exchange under the round-20 degradation policy:
    transient ``CoordinationTimeout`` s retry through ``runtime.Retry``
    inside the ``DSLIB_BARRIER_TIMEOUT`` budget (``resilient_exchange``
    splits it); a confirmed ``RankDead`` — or the budget running dry —
    ABORTS typed, counted ``bundle_barrier_abort``, on every surviving
    host.  A dead fleet member can delay a load by at most ``timeout``;
    it can never hang it."""
    from dislib_tpu.runtime.coord import (CoordinationTimeout,
                                          resilient_exchange)
    try:
        return resilient_exchange(coord, name, rank, vote, n,
                                  timeout=timeout)
    except CoordinationTimeout as e:    # includes the attributed RankDead
        _prof.count_resilience("bundle_barrier_abort")
        e.args = (f"sharded bundle {path}: load barrier ABORTED "
                  f"({e.args[0] if e.args else e}) — zero hosts serve",
                  *e.args[1:])
        raise


def _load_sharded(path, manifest, build, timeout) -> LoadedBundle:
    import jax

    from dislib_tpu.runtime.coord import get_coordinator

    hosts = int(manifest["hosts"])
    contract = manifest.get("mesh_contract", {})
    here = runtime_fingerprint()
    theirs = manifest.get("fingerprint", {})
    pc = jax.process_count()
    if pc > 1:
        if pc != hosts:
            raise BundleIncompatible(
                f"sharded bundle {path} carries {hosts} shards but this "
                f"fleet has {pc} processes — the mesh contract "
                f"{contract} is not honored", expected=contract,
                found={"hosts": pc})
        if contract.get("devices_per_host") is not None and \
                int(contract["devices_per_host"]) != len(jax.local_devices()):
            raise BundleIncompatible(
                f"sharded bundle {path} expects "
                f"{contract['devices_per_host']} devices per host, this "
                f"process has {len(jax.local_devices())}",
                expected=contract,
                found={"devices_per_host": len(jax.local_devices())})
        my_host = jax.process_index()
        votes_needed = hosts
        vote, raw_mine = _verify_shard(path, manifest, my_host)
        coord = get_coordinator()
        base = os.path.basename(path)
        votes = _barrier_exchange(coord, f"bundle-load:{base}", my_host,
                                  vote, votes_needed, timeout, path)
    else:
        # single process standing in for the fleet (mock hosts, offline
        # validation): verify EVERY shard and run the same barrier
        # exchange over the local transport — the protocol decision is
        # identical, only the transport is in-memory
        my_host = 0
        coord = get_coordinator()
        base = os.path.basename(path)
        coord.clear(f"bundle-load:{base}")
        raws, votes0 = {}, {}
        for r in range(hosts):
            votes0[r], raws[r] = _verify_shard(path, manifest, r)
            coord.post(f"bundle-load:{base}", r, votes0[r])
        raw_mine = raws[0]
        votes = _barrier_exchange(coord, f"bundle-load:{base}", 0,
                                  votes0[0], hosts, timeout, path)
    bad = sorted(r for r, v in votes.items() if not v.get("ok"))
    if bad:
        _prof.count_resilience("bundle_barrier_abort")
        r0 = bad[0]
        reason = votes[r0].get("reason", "unknown")
        raise BundleShardCorrupt(
            f"sharded bundle {path}: host {r0} failed shard "
            f"verification ({reason}) — load barrier ABORTS, zero hosts "
            f"serve (failed hosts: {bad})", host=r0, reason=reason)
    _prof.count_resilience("bundle_barrier_ok")
    state = {k[len(_STATE_PREFIX):]: v for k, v in raw_mine.items()
             if k.startswith(_STATE_PREFIX)}
    mismatched = [k for k in _SHARD_HARD_KEYS
                  if theirs.get(k) != here.get(k)]
    if mismatched:
        diff = {k: {"bundle": theirs.get(k), "here": here.get(k)}
                for k in mismatched}
        return _fallback(build, state, manifest, BundleIncompatible(
            f"sharded bundle {path} was exported under a different "
            f"runtime ({diff}) — its compiled executables cannot run "
            "here", expected=theirs, found=here))
    try:
        execs = _build_execs(raw_mine, manifest)
    except Exception as e:  # noqa: BLE001 — deserialize failure is typed
        return _fallback(build, state, manifest, BundleIncompatible(
            f"sharded bundle {path} passed its load barrier but "
            f"executable deserialization failed "
            f"({type(e).__name__}: {e})", expected=theirs, found=here))
    pipe = BundlePipeline(manifest["buckets"], manifest["n_features"],
                          execs)
    return LoadedBundle(pipe, state, manifest["buckets"], theirs,
                        fallback=False, hosts=hosts, host=my_host)
