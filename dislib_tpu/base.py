"""sklearn-style estimator plumbing: get_params / set_params / clone.

The reference inherits this behavior from scikit-learn conventions (SURVEY.md
§1: "scikit-learn's estimator API ... constructor hyperparameters,
trailing-underscore fitted attributes").  Implemented natively so the library
has no sklearn dependency in its compute path; GridSearchCV and save_model
rely on it.
"""

from __future__ import annotations

import inspect
from copy import deepcopy


#: classes already reported as lacking an async fit path (notice once each)
_ASYNC_FALLBACK_NOTICED: set = set()


class BaseEstimator:
    """Minimal sklearn-compatible base: constructor args are hyperparameters."""

    #: extra (leading-underscore) fitted state a subclass needs persisted by
    #: ``save_model`` beyond the trailing-underscore convention
    _private_fitted_attrs: tuple = ()

    @classmethod
    def _param_names(cls):
        sig = inspect.signature(cls.__init__)
        return [p.name for p in sig.parameters.values()
                if p.name != "self" and p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD)]

    def get_params(self, deep: bool = True) -> dict:
        return {name: getattr(self, name) for name in self._param_names()
                if hasattr(self, name)}

    def set_params(self, **params):
        valid = set(self._param_names())
        for k, v in params.items():
            if k not in valid:
                raise ValueError(f"invalid parameter {k!r} for {type(self).__name__}")
            setattr(self, k, v)
        return self

    def _fitted_attrs(self) -> dict:
        out = {k: v for k, v in vars(self).items()
               if k.endswith("_") and not k.startswith("_")}
        for k in self._private_fitted_attrs:
            if hasattr(self, k):
                out[k] = getattr(self, k)
        return out

    # -- async trial protocol (SURVEY §4.5: GridSearchCV submits all fits
    # before waiting on any; estimators opt in by overriding these) --------

    def _fit_async(self, x, y=None):
        """Dispatch this estimator's fit without reading device values back
        to the host, returning an opaque state handle for
        `_fit_finalize`/`_score_async`.  The default falls back to the
        synchronous `fit` and returns None (JAX async dispatch still
        overlaps the device work; the fallback only loses the cross-trial
        pipelining of convergence-scalar reads).  The degradation is logged
        once per class so a search that quietly serialises is visible."""
        cls = type(self).__name__
        if cls not in _ASYNC_FALLBACK_NOTICED:
            _ASYNC_FALLBACK_NOTICED.add(cls)
            from dislib_tpu.utils.dlog import get_logger
            get_logger("search").info(
                "%s does not implement _fit_async; search trials over it run "
                "synchronous fits (device work still overlaps, cross-trial "
                "pipelining of host reads is lost)", cls)
        self.fit(x, y) if y is not None else self.fit(x)
        return None

    def _fit_finalize(self, state):
        """Materialise fitted attributes from an async state handle (no-op
        for the synchronous fallback)."""

    def _score_async(self, state, x, y=None):
        """Score a trial from its async state; may return a device scalar —
        the caller converts to float only after every trial is dispatched.
        The fallback materialises the handle first, so an estimator that
        implements `_fit_async` without a custom `_score_async` still
        scores a FITTED model."""
        if state is not None:
            self._fit_finalize(state)
        if not hasattr(self, "score"):
            raise TypeError(f"{type(self).__name__} has no score(); "
                            "pass scoring=")
        return self.score(x, y) if y is not None else self.score(x)

    def __repr__(self):
        params = ", ".join(f"{k}={v!r}" for k, v in self.get_params().items())
        return f"{type(self).__name__}({params})"


def clone(estimator):
    """Fresh unfitted copy with the same hyperparameters (sklearn.clone)."""
    return type(estimator)(**deepcopy(estimator.get_params()))
