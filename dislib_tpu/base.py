"""sklearn-style estimator plumbing: get_params / set_params / clone.

The reference inherits this behavior from scikit-learn conventions (SURVEY.md
§1: "scikit-learn's estimator API ... constructor hyperparameters,
trailing-underscore fitted attributes").  Implemented natively so the library
has no sklearn dependency in its compute path; GridSearchCV and save_model
rely on it.
"""

from __future__ import annotations

import inspect
from copy import deepcopy


#: classes already reported as lacking an async fit path (notice once each)
_ASYNC_FALLBACK_NOTICED: set = set()


class BaseEstimator:
    """Minimal sklearn-compatible base: constructor args are hyperparameters."""

    #: extra (leading-underscore) fitted state a subclass needs persisted by
    #: ``save_model`` beyond the trailing-underscore convention
    _private_fitted_attrs: tuple = ()

    @classmethod
    def _param_names(cls):
        sig = inspect.signature(cls.__init__)
        return [p.name for p in sig.parameters.values()
                if p.name != "self" and p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD)]

    def get_params(self, deep: bool = True) -> dict:
        return {name: getattr(self, name) for name in self._param_names()
                if hasattr(self, name)}

    def set_params(self, **params):
        valid = set(self._param_names())
        for k, v in params.items():
            if k not in valid:
                raise ValueError(f"invalid parameter {k!r} for {type(self).__name__}")
            setattr(self, k, v)
        return self

    def _fitted_attrs(self) -> dict:
        out = {k: v for k, v in vars(self).items()
               if k.endswith("_") and not k.startswith("_")}
        for k in self._private_fitted_attrs:
            if hasattr(self, k):
                out[k] = getattr(self, k)
        return out

    # -- async trial protocol (SURVEY §4.5: GridSearchCV submits all fits
    # before waiting on any; estimators opt in by overriding these) --------

    def _fit_async(self, x, y=None):
        """Dispatch this estimator's fit without reading device values back
        to the host, returning an opaque state handle for
        `_fit_finalize`/`_score_async`.  The default falls back to the
        synchronous `fit` and returns None (JAX async dispatch still
        overlaps the device work; the fallback only loses the cross-trial
        pipelining of convergence-scalar reads).  The degradation is logged
        once per class so a search that quietly serialises is visible."""
        cls = type(self).__name__
        if cls not in _ASYNC_FALLBACK_NOTICED:
            _ASYNC_FALLBACK_NOTICED.add(cls)
            from dislib_tpu.utils.dlog import get_logger
            get_logger("search").info(
                "%s does not implement _fit_async; search trials over it run "
                "synchronous fits (device work still overlaps, cross-trial "
                "pipelining of host reads is lost)", cls)
        self.fit(x, y) if y is not None else self.fit(x)
        return None

    def _fit_finalize(self, state):
        """Materialise fitted attributes from an async state handle (no-op
        for the synchronous fallback)."""

    def _score_async(self, state, x, y=None):
        """Score a trial from its async state; may return a device scalar —
        the caller converts to float only after every trial is dispatched.
        The fallback materialises the handle first, so an estimator that
        implements `_fit_async` without a custom `_score_async` still
        scores a FITTED model."""
        if state is not None:
            self._fit_finalize(state)
        if not hasattr(self, "score"):
            raise TypeError(f"{type(self).__name__} has no score(); "
                            "pass scoring=")
        return self.score(x, y) if y is not None else self.score(x)

    # -- device-resident predict parameters (round-9 serving PR) ----------

    def _predict_leaves(self, *host_arrays):
        """Device copies of this model's predict-time parameters, cached by
        the identity of the host attribute objects.  A warm serving path
        calls predict once per batch; re-running ``jnp.asarray`` on every
        call would pay a host→device transfer of the whole model per
        request batch.  One cache entry PER LEAF TUPLE (predict and
        predict_proba pass different tuples — a single slot would thrash
        and re-upload the model on every alternation).  Each entry PINS
        its host arrays, which is what makes the id-tuple key sound: a
        cached id cannot be reused while its entry exists, and clearing
        drops the whole cache.  The cache invalidates when an attribute
        is REASSIGNED (a new fit, a hot-swap adoption) — in-place
        mutation of a fitted ndarray is not supported, as everywhere in
        the library.  The key also carries the current mesh: after an
        elastic ``ds.init`` resize, a leaf that is a COMMITTED device
        array from the old mesh (a fit's own output) would poison the
        predict program with mismatched device sets — such a leaf takes
        one host hop back onto the current mesh, once, here."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from dislib_tpu.parallel import mesh as _mesh
        mesh = _mesh.get_mesh()
        cache = getattr(self, "_predict_leaf_cache", None)
        if cache is None:
            cache = self._predict_leaf_cache = {}
        key = (id(mesh),) + tuple(id(h) for h in host_arrays)
        hit = cache.get(key)
        if hit is not None:
            return hit[1]
        mesh_devs = set(np.asarray(mesh.devices).ravel())
        dev = tuple(
            jnp.asarray(np.asarray(h)
                        if isinstance(h, jax.Array)
                        and not set(h.devices()) <= mesh_devs
                        else h)
            for h in host_arrays)
        if len(cache) >= 16:                # refit churn bound — a model
            cache.clear()                   # has a handful of live tuples
        cache[key] = (tuple(host_arrays), dev)  # [0] is the id pin
        return dev

    def _classes_leaf(self):
        """``classes_`` cast to the serving label dtype (int32 for integer
        classes — exact to 2^31 where float32 corrupts past 2^24 — else
        float32), cached by the identity of ``classes_`` so repeat predict
        calls reuse one host object and therefore one device transfer."""
        import numpy as np
        cached = getattr(self, "_classes_cast_cache", None)
        if cached is None or cached[0] is not self.classes_:
            dt = np.int32 if np.issubdtype(self.classes_.dtype, np.integer) \
                else np.float32
            self._classes_cast_cache = (self.classes_,
                                        self.classes_.astype(dt))
        return self._classes_cast_cache[1]

    def __repr__(self):
        params = ", ".join(f"{k}={v!r}" for k, v in self.get_params().items())
        return f"{type(self).__name__}({params})"


def clone(estimator):
    """Fresh unfitted copy with the same hyperparameters (sklearn.clone)."""
    return type(estimator)(**deepcopy(estimator.get_params()))
