"""Native (C++) host-side kernels — lazy build + ctypes bindings.

`fastio.cpp` is compiled on first use with the in-image g++ into
`_fastio-<tag>.so` next to this file (tag = compiler/source hash so a source
edit triggers a rebuild).  Every entry point returns None / raises
`NativeUnavailable` cleanly when the toolchain or the parse is unusable, and
callers in `dislib_tpu.data.io` fall back to the pure-NumPy path — the
native layer is a performance component, never a correctness dependency.

Set ``DSLIB_NO_NATIVE=1`` to disable entirely (forces the NumPy paths).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fastio.cpp")
_lock = threading.Lock()
_lib = None
_tried = False


class NativeUnavailable(RuntimeError):
    pass


def _build_and_load():
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:12]
    so = os.path.join(_HERE, f"_fastio-{tag}.so")
    if not os.path.exists(so):
        tmp = so + f".tmp{os.getpid()}"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
               _SRC, "-o", tmp]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)          # atomic: concurrent builders race safely
    lib = ctypes.CDLL(so)

    i64 = ctypes.c_int64
    pi64 = ctypes.POINTER(i64)
    pf32 = ctypes.POINTER(ctypes.c_float)
    lib.fastio_parse_text.restype = pf32
    lib.fastio_parse_text.argtypes = [ctypes.c_char_p, i64, ctypes.c_char,
                                      ctypes.c_int, pi64, pi64]
    lib.fastio_parse_svmlight.restype = ctypes.c_int
    lib.fastio_parse_svmlight.argtypes = [
        ctypes.c_char_p, i64, ctypes.POINTER(pf32), ctypes.POINTER(pi64),
        ctypes.POINTER(pi64), ctypes.POINTER(pf32), pi64, pi64]
    lib.fastio_parse_mdcrd.restype = pf32
    lib.fastio_parse_mdcrd.argtypes = [ctypes.c_char_p, i64, pi64]
    lib.fastio_free.restype = None
    lib.fastio_free.argtypes = [ctypes.c_void_p]
    return lib


def get_lib():
    """The loaded native library, or None if unavailable/disabled."""
    global _lib, _tried
    if os.environ.get("DSLIB_NO_NATIVE"):
        return None
    with _lock:
        if not _tried:
            _tried = True
            try:
                _lib = _build_and_load()
            except Exception:          # no toolchain / build failure → fallback
                _lib = None
    return _lib


def _take(lib, ptr, count, dtype):
    """Copy `count` elements out of a native buffer, then free it."""
    arr = np.ctypeslib.as_array(ptr, shape=(count,)).astype(dtype, copy=True)
    lib.fastio_free(ptr)
    return arr


def parse_text(buf: bytes, delimiter: str = ",", nthreads: int | None = None):
    """Parse delimited text → float32 (rows, cols) ndarray, or raise
    NativeUnavailable (caller falls back to np.loadtxt)."""
    lib = get_lib()
    if lib is None:
        raise NativeUnavailable
    if nthreads is None:
        nthreads = min(os.cpu_count() or 1, 16)
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    ptr = lib.fastio_parse_text(buf, len(buf),
                                delimiter.encode()[:1] or b",",
                                nthreads, ctypes.byref(rows),
                                ctypes.byref(cols))
    if rows.value < 0:
        raise NativeUnavailable("ragged rows — deferring to np.loadtxt")
    if not ptr:
        return np.zeros((0, 0), np.float32)
    flat = _take(lib, ptr, rows.value * cols.value, np.float32)
    return flat.reshape(rows.value, cols.value)


def parse_svmlight(buf: bytes):
    """Parse svmlight text → (labels, indptr, indices, data, n_features) in
    CSR form, or raise NativeUnavailable."""
    lib = get_lib()
    if lib is None:
        raise NativeUnavailable
    pf32 = ctypes.POINTER(ctypes.c_float)
    pi64 = ctypes.POINTER(ctypes.c_int64)
    labels_p, data_p = pf32(), pf32()
    indptr_p, indices_p = pi64(), pi64()
    nrows = ctypes.c_int64()
    nfeat = ctypes.c_int64()
    rc = lib.fastio_parse_svmlight(buf, len(buf),
                                   ctypes.byref(labels_p),
                                   ctypes.byref(indptr_p),
                                   ctypes.byref(indices_p),
                                   ctypes.byref(data_p),
                                   ctypes.byref(nrows), ctypes.byref(nfeat))
    if rc != 0:
        for p in (labels_p, indptr_p, indices_p, data_p):
            if p:
                lib.fastio_free(p)
        raise NativeUnavailable("malformed svmlight — deferring to Python")
    n = nrows.value
    if n == 0:
        for p in (labels_p, indptr_p, indices_p, data_p):
            if p:
                lib.fastio_free(p)
        return (np.zeros(0, np.float32), np.zeros(1, np.int64),
                np.zeros(0, np.int64), np.zeros(0, np.float32), 0)
    labels = _take(lib, labels_p, n, np.float32)
    indptr = _take(lib, indptr_p, n + 1, np.int64)
    nnz = int(indptr[-1])
    indices = _take(lib, indices_p, nnz, np.int64)
    data = _take(lib, data_p, nnz, np.float32)
    return labels, indptr, indices, data, int(nfeat.value)


def parse_mdcrd(buf: bytes):
    """Parse AMBER mdcrd body → flat float32 values, or raise
    NativeUnavailable."""
    lib = get_lib()
    if lib is None:
        raise NativeUnavailable
    nvals = ctypes.c_int64()
    ptr = lib.fastio_parse_mdcrd(buf, len(buf), ctypes.byref(nvals))
    if nvals.value < 0:
        raise NativeUnavailable("mdcrd allocation failure")
    if not ptr:
        return np.zeros(0, np.float32)
    return _take(lib, ptr, nvals.value, np.float32)
