// fastio — native (C++) parsers for the dslib data-loader.
//
// Role parity (SURVEY.md §3.5): the reference's ingest speed lives in native
// code outside its repo (NumPy's C parsers + the COMPSs C++/Java object
// transfer layer); per-block reader tasks make loading itself parallel
// (SURVEY §3.1 I/O row, §4.1).  This library is the TPU-build's native
// equivalent for the host-side parse: multi-threaded delimited-text,
// svmlight, and AMBER-mdcrd parsers callable via ctypes, each thread
// handling a line-aligned byte range of the input buffer — the same
// split-by-byte-range scheme `dislib_tpu.data.io` uses across hosts, applied
// across cores within a host.
//
// Build: g++ -O3 -march=native -shared -fPIC -pthread fastio.cpp -o _fastio.so
// (driven lazily by dislib_tpu/native/__init__.py; every Python entry point
// falls back to the pure-NumPy parser when the toolchain is unavailable).

#include <cstdlib>
#include <cstring>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

// Line-aligned [lo, hi) byte range for slice idx of count: a line belongs to
// the slice its first byte falls in (the in-buffer thread split; io.py's multi-host slab split
// uses an exact line-offset table instead).
void line_range(const char* buf, int64_t len, int idx, int count,
                int64_t* lo_out, int64_t* hi_out) {
    int64_t lo = len * (int64_t)idx / count;
    int64_t hi = len * (int64_t)(idx + 1) / count;
    if (lo > 0) {
        const char* p = (const char*)memchr(buf + lo - 1, '\n', len - lo + 1);
        lo = p ? (p - buf) + 1 : len;
    }
    if (hi < len) {
        const char* p = (const char*)memchr(buf + hi - 1, '\n', len - hi + 1);
        hi = p ? (p - buf) + 1 : len;
    }
    *lo_out = lo;
    *hi_out = hi < lo ? lo : hi;
}

struct Chunk {
    std::vector<float> vals;
    int64_t rows = 0;
    int64_t cols = -1;       // -1: unset; -2: ragged (error)
};

// Powers of ten for the fast float path (float32 output: |exp10| <= 63 with
// double intermediates is exact far beyond float32 precision).
const double kPow10[] = {
    1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,  1e8,  1e9,  1e10, 1e11,
    1e12, 1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

inline double pow10i(int e) {
    if (e >= 0)
        return e <= 22 ? kPow10[e] : __builtin_pow(10.0, e);
    return e >= -22 ? 1.0 / kPow10[-e] : __builtin_pow(10.0, e);
}

// Hand-rolled decimal float parse (locale-free, ~5-10x glibc strtof).  On
// ordinary decimal tokens sets *ok and returns one past the token; on
// anything unusual (inf/nan/hex/no digits) leaves *ok false and the caller
// falls back to strtof for that token.
inline const char* fast_float(const char* p, const char* end, float* out,
                              bool* ok) {
    const char* start = p;
    bool neg = false;
    if (p < end && (*p == '+' || *p == '-')) { neg = (*p == '-'); ++p; }
    double mant = 0.0;
    int digits = 0, exp10 = 0;
    while (p < end && *p >= '0' && *p <= '9') {
        mant = mant * 10.0 + (*p - '0');
        ++digits; ++p;
    }
    if (p < end && *p == '.') {
        ++p;
        while (p < end && *p >= '0' && *p <= '9') {
            mant = mant * 10.0 + (*p - '0');
            ++digits; --exp10; ++p;
        }
    }
    if (digits == 0 || digits > 17) { *ok = false; return start; }
    if (p < end && (*p == 'e' || *p == 'E')) {
        const char* ep = p + 1;
        bool eneg = false;
        if (ep < end && (*ep == '+' || *ep == '-')) {
            eneg = (*ep == '-'); ++ep;
        }
        int e = 0, ed = 0;
        while (ep < end && *ep >= '0' && *ep <= '9' && e < 10000) {
            e = e * 10 + (*ep - '0');
            ++ed; ++ep;
        }
        if (!ed) { *ok = false; return start; }
        exp10 += eneg ? -e : e;
        p = ep;
    }
    double v = exp10 ? mant * pow10i(exp10) : mant;
    *out = (float)(neg ? -v : v);
    *ok = true;
    return p;
}

// strtof fallback bounded to [p, eol): copies the token to a NUL-terminated
// scratch first (strtof needs termination; the buffer slice has none).
inline const char* slow_float(const char* p, const char* eol, float* out,
                              bool* ok) {
    char tmp[64];
    int w = (int)(eol - p < 63 ? eol - p : 63);
    memcpy(tmp, p, w);
    tmp[w] = '\0';
    char* q;
    *out = strtof(tmp, &q);
    *ok = (q != tmp);
    return p + (q - tmp);
}

inline bool blank_line(const char* p, const char* e) {
    for (; p < e; ++p)
        if (*p != ' ' && *p != '\t' && *p != '\r') return false;
    return true;
}

// Strict tokenization, matching np.loadtxt's contract: '#' starts a comment,
// fields are single-delimiter-separated (empty/trailing fields are errors),
// any unparseable token is an error.  Errors mark the chunk malformed
// (cols = -2) so the Python caller falls back to np.loadtxt, which raises
// the user-facing error — the native path never silently re-interprets
// input that NumPy would reject.
void parse_delim_chunk(const char* buf, int64_t lo, int64_t hi, char delim,
                       Chunk* out) {
    const char* p = buf + lo;
    const char* end = buf + hi;
    const bool ws_delim = (delim == ' ' || delim == '\t');
    while (p < end && out->cols != -2) {
        const char* nl = (const char*)memchr(p, '\n', end - p);
        const char* eol = nl ? nl : end;
        const char* cm = (const char*)memchr(p, '#', eol - p);
        const char* cend = cm ? cm : eol;        // truncate at comment
        if (!blank_line(p, cend)) {
            int64_t ncol = 0;
            const char* q = p;
            while (true) {
                while (q < cend && (*q == ' ' || *q == '\t' || *q == '\r'))
                    ++q;
                if (q >= cend) {
                    if (!ws_delim && ncol > 0) out->cols = -2;  // trailing delim
                    break;
                }
                float v;
                bool ok;
                const char* q2 = fast_float(q, cend, &v, &ok);
                if (!ok) q2 = slow_float(q, cend, &v, &ok);
                if (!ok) { out->cols = -2; break; }      // unparseable token
                out->vals.push_back(v);
                ++ncol;
                q = q2;
                while (q < cend && (*q == ' ' || *q == '\t' || *q == '\r'))
                    ++q;
                if (q >= cend) break;
                if (ws_delim) continue;                  // runs of ws = 1 sep
                if (*q != delim) { out->cols = -2; break; }
                ++q;                                     // exactly one delim
            }
            if (out->cols == -2) break;
            if (ncol > 0) {
                if (out->cols == -1) out->cols = ncol;
                else if (out->cols != ncol) out->cols = -2;
                ++out->rows;
            }
        }
        p = eol + 1;
    }
}

}  // namespace

extern "C" {

// Multi-threaded delimited-text parse.  Returns a malloc'd float32 buffer of
// rows*cols (caller frees via fastio_free); rows/cols through out-params.
// Returns nullptr with *rows = -1 on ragged rows, nullptr with *rows = 0 on
// empty input.
float* fastio_parse_text(const char* buf, int64_t len, char delim,
                         int nthreads, int64_t* rows, int64_t* cols) {
    if (nthreads < 1) nthreads = 1;
    std::vector<Chunk> chunks(nthreads);
    std::vector<std::thread> threads;
    for (int t = 0; t < nthreads; ++t) {
        int64_t lo, hi;
        line_range(buf, len, t, nthreads, &lo, &hi);
        threads.emplace_back(parse_delim_chunk, buf, lo, hi, delim,
                             &chunks[t]);
    }
    for (auto& th : threads) th.join();

    int64_t ncol = -1, nrow = 0;
    for (auto& c : chunks) {
        if (c.cols == -2 || (c.cols >= 0 && ncol >= 0 && c.cols != ncol)) {
            *rows = -1; *cols = 0;
            return nullptr;
        }
        if (c.cols >= 0) ncol = c.cols;
        nrow += c.rows;
    }
    *rows = nrow;
    *cols = ncol < 0 ? 0 : ncol;
    if (nrow == 0 || ncol <= 0) return nullptr;
    float* out = (float*)malloc(sizeof(float) * (size_t)nrow * (size_t)ncol);
    if (!out) { *rows = -1; *cols = 0; return nullptr; }
    float* w = out;
    for (auto& c : chunks) {
        memcpy(w, c.vals.data(), c.vals.size() * sizeof(float));
        w += c.vals.size();
    }
    return out;
}

// svmlight parse: single pass building CSR.  Outputs (all malloc'd, caller
// frees each via fastio_free): labels[nrows], indptr[nrows+1] (int64),
// indices[nnz] (int64, 0-based), data[nnz] (float32).  Returns 0 on success,
// -1 on malformed input.
int fastio_parse_svmlight(const char* buf, int64_t len,
                          float** labels_out, int64_t** indptr_out,
                          int64_t** indices_out, float** data_out,
                          int64_t* nrows_out, int64_t* nfeat_out) {
    std::vector<float> labels, data;
    std::vector<int64_t> indptr(1, 0), indices;
    int64_t maxfeat = 0;
    const char* p = buf;
    const char* end = buf + len;
    while (p < end) {
        const char* nl = (const char*)memchr(p, '\n', end - p);
        const char* eol = nl ? nl : end;
        while (p < eol && (*p == ' ' || *p == '\t')) ++p;
        if (p >= eol || *p == '#') { p = eol + 1; continue; }
        float y;
        bool ok;
        const char* q = fast_float(p, eol, &y, &ok);
        if (!ok) q = slow_float(p, eol, &y, &ok);
        if (!ok) return -1;
        labels.push_back(y);
        p = q;
        while (p < eol) {
            while (p < eol && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
            if (p >= eol || *p == '#') break;
            long long k = 0;
            int kd = 0;
            while (p < eol && *p >= '0' && *p <= '9') {
                k = k * 10 + (*p - '0');
                ++kd; ++p;
            }
            if (!kd || p >= eol || *p != ':') return -1;
            ++p;
            float v;
            q = fast_float(p, eol, &v, &ok);
            if (!ok) q = slow_float(p, eol, &v, &ok);
            if (!ok) return -1;
            p = q;
            indices.push_back(k - 1);              // svmlight is 1-indexed
            data.push_back(v);
            if (k > maxfeat) maxfeat = k;
        }
        indptr.push_back((int64_t)indices.size());
        p = eol + 1;
    }
    int64_t n = (int64_t)labels.size();
    *nrows_out = n;
    *nfeat_out = maxfeat;
    auto dup = [](const void* src, size_t bytes) -> void* {
        void* d = malloc(bytes ? bytes : 1);
        if (d && bytes) memcpy(d, src, bytes);
        return d;
    };
    *labels_out = (float*)dup(labels.data(), labels.size() * sizeof(float));
    *indptr_out = (int64_t*)dup(indptr.data(), indptr.size() * sizeof(int64_t));
    *indices_out = (int64_t*)dup(indices.data(),
                                 indices.size() * sizeof(int64_t));
    *data_out = (float*)dup(data.data(), data.size() * sizeof(float));
    if (!*labels_out || !*indptr_out || !*indices_out || !*data_out) return -1;
    return 0;
}

// AMBER mdcrd: fixed-width 8-char float columns after a title line.
// Returns malloc'd float32 values (count via *nvals); caller frees.
float* fastio_parse_mdcrd(const char* buf, int64_t len, int64_t* nvals) {
    const char* p = (const char*)memchr(buf, '\n', len);   // skip title
    p = p ? p + 1 : buf + len;
    const char* end = buf + len;
    std::vector<float> vals;
    vals.reserve((size_t)((end - p) / 8));
    while (p < end) {
        const char* nl = (const char*)memchr(p, '\n', end - p);
        const char* eol = nl ? nl : end;
        const char* q = p;
        while (q + 1 <= eol) {
            const char* f_end = q + 8 > eol ? eol : q + 8;
            const char* qs = q;
            while (qs < f_end && (*qs == ' ' || *qs == '\t' || *qs == '\r'))
                ++qs;
            if (qs < f_end) {                // non-blank field MUST parse —
                float v;                     // a dropped field would shift
                bool ok;                     // every later coordinate
                fast_float(qs, f_end, &v, &ok);
                if (!ok) slow_float(qs, f_end, &v, &ok);
                if (!ok) { *nvals = -2; return nullptr; }
                vals.push_back(v);
            }
            q = f_end;
        }
        p = eol + 1;
    }
    *nvals = (int64_t)vals.size();
    if (vals.empty()) return nullptr;
    float* out = (float*)malloc(vals.size() * sizeof(float));
    if (!out) { *nvals = -1; return nullptr; }
    memcpy(out, vals.data(), vals.size() * sizeof(float));
    return out;
}

void fastio_free(void* p) { free(p); }

}  // extern "C"
