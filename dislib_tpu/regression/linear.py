"""Linear regression via normal equations (reference:
`dislib/regression/linear` — blocked partial sums of XᵀX and Xᵀy, solve the
small system on master; SURVEY.md §3.3).

TPU-native: XᵀX and Xᵀy are sharded GEMMs whose row-axis reductions lower to
psum; the (n+1)×(n+1) solve runs replicated on device.  Supports
multi-output y (reference parity).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array, ensure_canonical, fused_kernel
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.ops.base import precise


class LinearRegression(BaseEstimator):
    """Ordinary least squares.

    Attributes
    ----------
    coef_ : ndarray (n_features, n_targets)
    intercept_ : ndarray (n_targets,)
    """

    def __init__(self, fit_intercept=True, arity=50):
        self.fit_intercept = fit_intercept
        self.arity = arity  # reference parity; ignored

    def fit(self, x: Array, y: Array):
        self._fit_finalize(self._fit_async(x, y))
        return self

    def predict(self, x: Array) -> Array:
        """ŷ = x @ coef + intercept as a fusion-graph node — one cached
        dispatch for a whole scaler → predict chain (serving hot path)."""
        self._check_fitted()
        # serve on the CURRENT mesh: an input built before an elastic
        # resize re-lands on device (never the host) — round 16
        x = ensure_canonical(x)
        coef, intercept = self._predict_leaves(self.coef_, self.intercept_)
        return fused_kernel(
            _linreg_predict_kernel, (x.shape,), (x, coef, intercept),
            (x.shape[0], self.coef_.shape[1]), jnp.float32,
            out_pshape=(x._pshape[0], self.coef_.shape[1]))

    def score(self, x: Array, y: Array) -> float:
        """R² score (sklearn convention); computed on device."""
        self._check_fitted()
        return float(_r2_score(x._data, y._data, x.shape, y.shape,
                               jnp.asarray(self.coef_),
                               jnp.asarray(self.intercept_)))

    # async trial protocol (SURVEY §4.5): the fit is one jitted program; the
    # handle is the (coef, intercept) device pair, read back only after
    # GridSearchCV has dispatched every trial
    def _fit_async(self, x, y=None):
        if y is None:
            raise ValueError("LinearRegression requires y")
        if x.shape[0] != y.shape[0]:
            raise ValueError("x and y row counts differ")
        return _linreg_fit(x._data, y._data, x.shape, y.shape,
                           self.fit_intercept)

    def _fit_finalize(self, state):
        if state is None:
            return
        coef, intercept = state
        self.coef_ = np.asarray(jax.device_get(coef))
        self.intercept_ = np.asarray(jax.device_get(intercept))

    def _score_async(self, state, x, y=None):
        if state is None:
            return super()._score_async(state, x, y)
        coef, intercept = state
        return _r2_score(x._data, y._data, x.shape, y.shape, coef, intercept)

    def _check_fitted(self):
        if not hasattr(self, "coef_"):
            raise RuntimeError("LinearRegression is not fitted")


@partial(jax.jit, static_argnames=("x_shape", "y_shape", "fit_intercept"))
@precise
def _linreg_fit(xp, yp, x_shape, y_shape, fit_intercept):
    m, n = x_shape
    t = y_shape[1]
    xv = xp[:, :n]
    yv = yp[:, :t]
    xv = lax.with_sharding_constraint(xv, _mesh.row_sharding())
    if fit_intercept:
        # padded rows are zero: augmenting with a masked ones-column keeps them inert
        valid = (lax.broadcasted_iota(jnp.int32, (xv.shape[0], 1), 0) < m).astype(xv.dtype)
        xa = jnp.concatenate([xv, valid], axis=1)
    else:
        xa = xv
    xtx = xa.T @ xa                                   # (n+1, n+1) psum over rows
    xty = xa.T @ yv                                   # (n+1, t)
    # small ridge for numerical safety on rank-deficient inputs
    sol = jnp.linalg.solve(xtx + 1e-7 * jnp.eye(xa.shape[1], dtype=xv.dtype), xty)
    if fit_intercept:
        return sol[:-1], sol[-1]
    return sol, jnp.zeros((t,), xv.dtype)


@partial(jax.jit, static_argnames=("x_shape", "y_shape"))
@precise
def _r2_score(xp, yp, x_shape, y_shape, coef, intercept):
    """R² of a linear predictor, summed over all targets (the host-side
    sklearn formula moved on-device so scoring never leaves the mesh)."""
    m, n = x_shape
    t = y_shape[1]
    xv = xp[:, :n]
    yv = yp[:, :t]
    w = (lax.broadcasted_iota(jnp.int32, (xv.shape[0], 1), 0) < m) \
        .astype(xv.dtype)
    pred = (xv @ coef + intercept[None, :]) * w
    resid = jnp.sum(((yv - pred) * w) ** 2)
    ymean = jnp.sum(yv * w, axis=0) / m
    total = jnp.sum(((yv - ymean[None, :]) * w) ** 2)
    return 1.0 - resid / jnp.maximum(total, 1e-12)


def _linreg_predict_kernel(cfg, xp, coef, intercept):
    """`predict` as a fusion-node body (cfg = (logical shape,))."""
    m, n = cfg[0]
    xv = xp[:, :n]
    out = xv @ coef + intercept[None, :]
    valid = lax.broadcasted_iota(jnp.int32, (xv.shape[0], 1), 0) < m
    return jnp.where(valid, out, 0.0)
