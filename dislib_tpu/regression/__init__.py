from dislib_tpu.regression.linear import LinearRegression
from dislib_tpu.regression.lasso import Lasso

__all__ = ["LinearRegression", "Lasso"]
