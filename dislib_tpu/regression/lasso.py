"""Lasso via consensus ADMM (reference: `dislib/regression/lasso` —
`Lasso(lmbd, rho, max_iter, atol, rtol)`: distributed per-block ridge solves,
global soft-threshold z-update, dual updates; SURVEY.md §3.3).

TPU-native: delegates to :class:`dislib_tpu.optimization.ADMM` with the L1
soft-threshold prox; the whole iteration loop runs on device (see admm.py).
"""

from __future__ import annotations

import numpy as np

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array
from dislib_tpu.optimization.admm import ADMM, soft_threshold


class Lasso(BaseEstimator):
    """L1-regularised least squares:  (1/2)‖Xw − y‖² + λ‖w‖₁.

    Attributes
    ----------
    coef_ : ndarray (n_features,)
    n_iter_ : int ;  converged_ : bool
    """

    def __init__(self, lmbd=1.0, rho=1.0, max_iter=100, atol=1e-4, rtol=1e-2):
        self.lmbd = lmbd
        self.rho = rho
        self.max_iter = max_iter
        self.atol = atol
        self.rtol = rtol

    def fit(self, x: Array, y: Array):
        from dislib_tpu.parallel import mesh as _mesh
        # global objective carries λ once; each of the p agents contributes ρ
        p = _mesh.mesh_shape()[0]
        kappa = float(self.lmbd) / (float(self.rho) * p)
        admm = ADMM(z_prox=soft_threshold, prox_kappa=kappa, rho=self.rho,
                    max_iter=self.max_iter, abstol=self.atol, reltol=self.rtol)
        admm.fit(x, y)
        self.coef_ = admm.z_
        self.n_iter_ = admm.n_iter_
        self.converged_ = admm.converged_
        return self

    def predict(self, x: Array) -> Array:
        self._check_fitted()
        from dislib_tpu.math import matmul
        w = Array._from_logical(np.asarray(self.coef_, np.float32).reshape(-1, 1))
        return matmul(x, w)

    def score(self, x: Array, y: Array) -> float:
        """R² (sklearn convention)."""
        pred = self.predict(x).collect()
        yv = y.collect()
        u = ((yv - pred) ** 2).sum()
        v = ((yv - yv.mean(0)) ** 2).sum()
        return float(1.0 - u / v)

    def _check_fitted(self):
        if not hasattr(self, "coef_"):
            raise RuntimeError("Lasso is not fitted")
