"""Lasso via consensus ADMM (reference: `dislib/regression/lasso` —
`Lasso(lmbd, rho, max_iter, atol, rtol)`: distributed per-block ridge solves,
global soft-threshold z-update, dual updates; SURVEY.md §3.3).

TPU-native: delegates to :class:`dislib_tpu.optimization.ADMM` with the L1
soft-threshold prox; the whole iteration loop runs on device (see admm.py).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from dislib_tpu.base import BaseEstimator
from dislib_tpu.data.array import Array
from dislib_tpu.optimization.admm import ADMM, soft_threshold
from dislib_tpu.regression.linear import _r2_score


class Lasso(BaseEstimator):
    """L1-regularised least squares:  (1/2)‖Xw − y‖² + λ‖w‖₁.

    Attributes
    ----------
    coef_ : ndarray (n_features,)
    n_iter_ : int ;  converged_ : bool
    """

    def __init__(self, lmbd=1.0, rho=1.0, max_iter=100, atol=1e-4, rtol=1e-2):
        self.lmbd = lmbd
        self.rho = rho
        self.max_iter = max_iter
        self.atol = atol
        self.rtol = rtol

    def _admm(self):
        from dislib_tpu.parallel import mesh as _mesh
        # global objective carries λ once; each of the p agents contributes ρ
        p = _mesh.mesh_shape()[0]
        kappa = float(self.lmbd) / (float(self.rho) * p)
        return ADMM(z_prox=soft_threshold, prox_kappa=kappa, rho=self.rho,
                    max_iter=self.max_iter, abstol=self.atol, reltol=self.rtol)

    def fit(self, x: Array, y: Array):
        self._fit_finalize(self._fit_async(x, y))
        return self

    # async trial protocol (SURVEY §4.5): delegate to ADMM's device handle
    def _fit_async(self, x, y=None):
        if y is None:
            raise ValueError("Lasso requires y")
        admm = self._admm()
        return (admm, admm._fit_async(x, y))

    def _fit_finalize(self, state):
        if state is None:
            return
        admm, admm_state = state
        admm._fit_finalize(admm_state)
        self.coef_ = admm.z_
        self.n_iter_ = admm.n_iter_
        self.converged_ = admm.converged_

    def _score_async(self, state, x, y=None):
        if state is None:
            return super()._score_async(state, x, y)
        z = state[1][0]                       # device consensus vector
        coef = z.reshape(-1, 1)
        return _r2_score(x._data, y._data, x.shape, y.shape, coef,
                         jnp.zeros((1,), coef.dtype))

    def predict(self, x: Array) -> Array:
        self._check_fitted()
        from dislib_tpu.math import matmul
        # the weight Array is cached by coef_ identity: matmul already
        # fuses, but rebuilding the ds-array per call paid a pad kernel +
        # transfer per predict (visible on the serving hot path)
        cached = getattr(self, "_w_cache", None)
        if cached is None or cached[0] is not self.coef_:
            w = Array._from_logical(
                np.asarray(self.coef_, np.float32).reshape(-1, 1))
            self._w_cache = (self.coef_, w)
        return matmul(x, self._w_cache[1])

    def score(self, x: Array, y: Array) -> float:
        """R² (sklearn convention); computed on device."""
        self._check_fitted()
        coef = jnp.asarray(np.asarray(self.coef_, np.float32)).reshape(-1, 1)
        return float(_r2_score(x._data, y._data, x.shape, y.shape, coef,
                               jnp.zeros((1,), coef.dtype)))

    def _check_fitted(self):
        if not hasattr(self, "coef_"):
            raise RuntimeError("Lasso is not fitted")
