from dislib_tpu.data.array import (
    Array, array, random_array, zeros, full, ones, identity, eye,
    apply_along_axis, concat_rows, concat_cols, rechunk, ensure_canonical,
)
from dislib_tpu.data.io import (
    load_txt_file, load_svmlight_file, load_npy_file, load_mdcrd_file, save_txt,
    QuarantineLedger, QuarantineReport, last_quarantine_report,
    quarantine_ledger, quarantine_batch,
)
from dislib_tpu.data.sparse import SparseArray

__all__ = [
    "Array", "array", "random_array", "zeros", "full", "ones", "identity",
    "eye", "apply_along_axis", "concat_rows", "concat_cols", "rechunk",
    "ensure_canonical",
    "load_txt_file", "load_svmlight_file", "load_npy_file", "load_mdcrd_file",
    "save_txt", "QuarantineReport", "QuarantineLedger",
    "last_quarantine_report", "quarantine_ledger", "quarantine_batch",
    "SparseArray",
]
