"""Array padding/trimming utilities (reference: `dislib/data/util/` —
`pad`, `pad_last_blocks_with_zeros`, `compute_bottom_right_shape`,
`remove_last_rows`, `remove_last_columns`; SURVEY.md §3.1).

In the TPU rebuild physical padding is automatic (every Array carries a
zero-padded canvas), so these helpers operate on the *logical* shape — they
exist for API parity and for QR-style algorithms that want logically-square
block grids.
"""

from __future__ import annotations

import numpy as np

from dislib_tpu.data.array import Array as _Array, array as _ds_array


def pad(x: _Array, pad_width, value=0.0) -> _Array:
    """Grow the logical shape by ((top, bottom), (left, right)) filled with
    ``value``."""
    (top, bottom), (left, right) = pad_width
    import jax.numpy as jnp
    logical = x._data[: x.shape[0], : x.shape[1]]
    out = jnp.pad(logical, ((top, bottom), (left, right)), constant_values=value)
    return _Array._from_logical(out, reg_shape=x._reg_shape, sparse=x._sparse)


def pad_last_blocks_with_zeros(x: _Array) -> _Array:
    """Pad so the logical shape is an exact multiple of the block size."""
    br, bc = x._reg_shape
    bottom = (-x.shape[0]) % br
    right = (-x.shape[1]) % bc
    if bottom == 0 and right == 0:
        return x
    return pad(x, ((0, bottom), (0, right)), 0.0)


def compute_bottom_right_shape(x: _Array):
    """Shape of the bottom-right (possibly ragged) block."""
    br, bc = x._reg_shape
    r = x.shape[0] % br or br
    c = x.shape[1] % bc or bc
    return r, c


def remove_last_rows(x: _Array, n: int) -> _Array:
    return x[: x.shape[0] - n, :]


def remove_last_columns(x: _Array, n: int) -> _Array:
    return x[:, : x.shape[1] - n]
