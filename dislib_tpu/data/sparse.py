"""Sparse ds-array — BCOO-backed storage (SURVEY.md §8 "Sparse support":
"TPU has no general CSR.  BCOO matvec covers ALS/svmlight ingestion;
dense-with-mask is the fallback; this decision gates ALS and sparse
KMeans/CSVM parity").

Reference capability: ds-array blocks may be SciPy CSR matrices
(`dislib/data/array.py`, `_sparse=True`); KMeans/CSVM/svmlight ingestion
accept them and per-block NumPy kernels dispatch to scipy.sparse ops.

TPU-native design and its honest limits:

- Storage is one `jax.experimental.sparse.BCOO` on device — O(nnz) memory,
  the role CSR plays for the reference.  Dense products against it
  materialise MXU-shaped results placed with the library sharding.
- **Row-sharded representation** (`ShardedRows`): the nonzeros are bucketed
  by row shard into rectangular (p, nnz_max) buffers — data, shard-local
  row ids, column ids — padded per shard with zero-valued entries so every
  shard is the same shape (BCOO's ragged buffers do not shard over a Mesh;
  rectangular buffers do).  `x @ B` is then shard-local (each shard owns
  disjoint output rows: gather B rows at the entry columns, scale,
  segment-sum by local row) and `xᵀ @ C` is a shard-local partial plus ONE
  `psum` over the rows axis — the identical communication structure to the
  dense KMeans path.  Sparse KMeans runs entirely on this representation.
- Per-estimator choice (recorded as SURVEY §8 directs):
  * KMeans — native sparse path (`fit`/`predict` accept SparseArray; the
    distance cross-term and the per-cluster sums are `bcoo_dot_general`
    contractions).
  * ALS — dense-with-mask (see `recommendation/als.py`: a zero rating IS
    the mask; the normal-equation GEMMs need the dense mask anyway).
  * CascadeSVM — sparse-native: host-CSR-staged per-node sub-Grams feed
    the device dual solves; queries classify via one spmm cross-term
    (`classification/csvm.py`).
  * trees / others — densify (`to_dense()`); same stance as the
    reference's per-block `.toarray()` escape hatches.
"""

from __future__ import annotations

import math
import os
from collections import namedtuple
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from dislib_tpu.data.array import Array
from dislib_tpu.ops.base import precise
from dislib_tpu.parallel import mesh as _mesh
from dislib_tpu.utils.profiling import count_transfer as _count_transfer
from dislib_tpu.utils.profiling import profiled_jit as _pjit

__all__ = ["SparseArray", "ShardedSparse", "SparsePanelView", "nse_quantum"]


def nse_quantum() -> int:
    """Per-shard nse (stored-entry) pad quantum: every shard's
    rectangular buffers are padded to a multiple of this, so two sparse
    arrays with similar per-shard fill share compiled kernel shapes (the
    dense pad-quantum discipline applied to the nse axis).
    ``DSLIB_SPARSE_NSE_QUANTUM`` overrides; default 64."""
    return max(1, int(os.environ.get("DSLIB_SPARSE_NSE_QUANTUM", "64")))


def densify_budget_bytes() -> int:
    """The byte budget above which densifying a SparseArray raises
    instead of silently OOMing a chip (``DSLIB_SPARSE_DENSIFY_BUDGET``,
    default 4 GiB) — consulted by the lazy dense escape hatch AND the
    ``math.matmul`` spmm/densify router."""
    return int(os.environ.get("DSLIB_SPARSE_DENSIFY_BUDGET", 4 << 30))


class ShardedSparse:
    """Row-panel-sharded sparse storage: the device-resident layout every
    sparse fast path (SpMM, sharded ALS, sharded KMeans, the ring tiers)
    consumes, and the unit the sparse ``ds.rechunk`` schedules move.

    Device buffers, each ``NamedSharding(mesh, P('rows'))``-sharded over
    the mesh row axis (``p`` = row-rank count):

    - ``data``  (p, nse) — entry values (float32, or float64 under x64);
    - ``lrows`` (p, nse) — shard-LOCAL row ids (global row − s·m_local);
    - ``cols``  (p, nse) — column ids;
    - ``counts_dev`` (p,) — per-shard live-entry count (the in-kernel
      slot-validity mask: ``iota < count`` — pads stay non-load-bearing
      even when poisoned).

    Layout invariants (what the rechunk schedules preserve/rebuild):

    - **canonical row split**: ``m_local = padded_rows(m) / p`` — the SAME
      row partition as a canonically sharded dense array, so SpMM's output
      block boundaries line up with the dense (rows, cols) sharding;
    - **row-sorted, tail-padded**: live entries are sorted by global row
      and occupy slots ``[0, counts[s])``; the global entry stream is the
      shard-major concatenation of the live slots (this is what makes
      relayout pure static addressing — arXiv:2112.01075's portable
      redistribution needs only offset tables);
    - **uniform nse pad** (``nse`` a :func:`nse_quantum` multiple, equal
      on every shard): pad entries are (value 0, row 0, column 0 — the
      sentinel column), so they are additive no-ops under every
      segment-sum even before the slot mask re-zeroes them — the
      poisoned-pad discipline.

    Host metadata (control plane only — never a device transfer):
    ``counts`` (tuple of per-shard ints), ``row_nnz`` (int64 (m,) per-row
    entry histogram, layout-independent: relayout target shapes are
    computed from it on host, so no device sync ever decides a shape),
    and ``cols_host`` (int32 (nnz,) global live-COLUMN stream in the
    row-sorted global entry order).  The column stream is as
    layout-independent as ``row_nnz`` — relayout permutes entries between
    shards but never reorders the global stream — so the rechunk
    schedules carry it through unchanged, and the col-partitioned panel
    view below sizes its slot ranges from it without a device sync.
    """

    __slots__ = ("data", "lrows", "cols", "_counts_dev", "counts",
                 "row_nnz", "shape", "mesh", "m_local", "nse", "_rowsq",
                 "cols_host", "_pviews", "_ell", "_rsteps")

    def __init__(self, data, lrows, cols, counts_dev, counts, row_nnz,
                 shape, mesh, cols_host=None):
        self.data = data
        self.lrows = lrows
        self.cols = cols
        self._counts_dev = counts_dev
        self.counts = tuple(int(c) for c in counts)
        self.row_nnz = row_nnz
        self.shape = (int(shape[0]), int(shape[1]))
        self.mesh = mesh
        self.m_local = _padded_rows(shape[0], mesh) // int(data.shape[0])
        self.nse = int(data.shape[1])
        self._rowsq = None
        self.cols_host = None if cols_host is None \
            else np.asarray(cols_host, np.int32)
        self._pviews = {}
        self._ell = None
        self._rsteps = {}

    @property
    def counts_dev(self):
        """Device (p,) per-shard live counts (the kernels' slot-mask
        operand), materialised LAZILY as a jit-embedded constant from
        the host metadata — a reshard-produced representation acquires
        it without a host→device transfer (transfer-guard clean)."""
        if self._counts_dev is None:
            self._counts_dev = _counts_kernel(self.counts, self.mesh)
        return self._counts_dev

    @property
    def p(self) -> int:
        return int(self.data.shape[0])

    @property
    def nnz(self) -> int:
        return int(sum(self.counts))

    def __repr__(self):
        return (f"ShardedSparse(shape={self.shape}, p={self.p}, "
                f"nse={self.nse}, nnz={self.nnz})")

    @classmethod
    def build(cls, rows, cols, vals, shape, mesh=None, nse=None):
        """Bucket host (row, col, val) triplets into the sharded layout
        (ingest: the one host-side construction path; on-device arrays
        move between layouts via the sparse rechunk schedules)."""
        mesh = mesh or _mesh.get_mesh()
        p = mesh.shape[_mesh.ROWS]
        m, n = (int(s) for s in shape)
        m_local = _padded_rows(m, mesh) // p
        rows = np.asarray(rows, np.int64)
        cols = np.asarray(cols, np.int64)
        vals = np.asarray(vals)
        if rows.size and (rows.min() < 0 or rows.max() >= m
                          or cols.min() < 0 or cols.max() >= n):
            raise ValueError(
                f"sparse indices out of range for shape {(m, n)} — "
                "quarantine the offending rows at ingest "
                "(load_svmlight_file / SparseArray.from_scipy do)")
        order = np.argsort(rows, kind="stable")
        rows, cols, vals = rows[order], cols[order], vals[order]
        row_nnz = np.bincount(rows, minlength=m).astype(np.int64)
        shard = rows // m_local
        counts = np.bincount(shard, minlength=p).astype(np.int64)
        nse_eff = _round_nse(int(counts.max(initial=0)), nse)
        data = np.zeros((p, nse_eff), vals.dtype if vals.dtype == np.float64
                        else np.float32)
        lr = np.zeros((p, nse_eff), np.int32)
        cc = np.zeros((p, nse_eff), np.int32)
        start = np.concatenate([[0], np.cumsum(counts)])
        slot = np.arange(rows.size) - start[shard]
        data[shard, slot] = vals
        lr[shard, slot] = rows - shard * m_local
        cc[shard, slot] = cols
        return cls._place(data, lr, cc, counts, row_nnz, (m, n), mesh,
                          cols_host=cols.astype(np.int32))

    @classmethod
    def _place(cls, data, lr, cc, counts, row_nnz, shape, mesh,
               cols_host=None):
        sh1 = jax.sharding.NamedSharding(mesh,
                                         jax.sharding.PartitionSpec(_mesh.ROWS))
        return cls(jax.device_put(jnp.asarray(data), sh1),
                   jax.device_put(jnp.asarray(lr), sh1),
                   jax.device_put(jnp.asarray(cc), sh1),
                   jax.device_put(jnp.asarray(np.asarray(counts, np.int32)),
                                  sh1),
                   counts, row_nnz, shape, mesh, cols_host=cols_host)

    def rowsq(self):
        """Device (p, m_local) per-row ‖x_i‖² — the KMeans/kNN distance
        term, derived ON DEVICE from the buffers (one jitted kernel,
        cached), so a rechunk-produced representation never touches the
        host to serve it."""
        if self._rowsq is None:
            self._rowsq = _rowsq_kernel(self.data, self.lrows,
                                        self.counts_dev, self.mesh,
                                        self.m_local)
        return self._rowsq

    def host_triplets(self):
        """(rows, cols, vals) global host triplets — the collect path
        (counts ONE host transfer via the blessed counter)."""
        _count_transfer()
        d = np.asarray(jax.device_get(self.data))
        lr = np.asarray(jax.device_get(self.lrows))
        cc = np.asarray(jax.device_get(self.cols))
        rows_l, cols_l, vals_l = [], [], []
        for s, k in enumerate(self.counts):
            rows_l.append(lr[s, :k].astype(np.int64) + s * self.m_local)
            cols_l.append(cc[s, :k].astype(np.int64))
            vals_l.append(d[s, :k])
        cat = (np.concatenate(x) if x else np.zeros(0)
               for x in (rows_l, cols_l, vals_l))
        return tuple(cat)

    # -- col-partitioned panel view (the SpMM slot-range layout) -------------

    def _cols_stream(self):
        """Host int32 (nnz,) global live-column stream — ``cols_host``,
        or (for a representation built before the stream metadata
        existed) ONE blessed fetch through the transfer counter, cached.
        The stream is shard-major over live slots, which by the
        row-sorted invariant IS the global row-sorted entry order."""
        if self.cols_host is None:
            _count_transfer()
            cc = np.asarray(jax.device_get(self.cols))
            self.cols_host = np.concatenate(
                [cc[s, :k] for s, k in enumerate(self.counts)]
            ).astype(np.int32)
        return self.cols_host

    def panel_counts(self, steps, h):
        """Host (p, steps) per-shard-per-PANEL live-entry histogram
        (panel t owns columns [t·h, (t+1)·h)) — the control-plane input
        that sizes the panel view's uniform slot ranges.  Pure host
        arithmetic over ``cols_host`` + ``counts``: no device sync ever
        decides a shape, the ``row_nnz`` discipline applied to the
        column axis."""
        cs = self._cols_stream()
        start = np.concatenate([[0], np.cumsum(self.counts)]).astype(np.int64)
        pc = np.zeros((self.p, steps), np.int64)
        for s in range(self.p):
            seg = cs[start[s]:start[s + 1]] // h
            if seg.size:
                pc[s, :] = np.bincount(seg, minlength=steps)[:steps]
        return pc

    def panel_view(self, steps, h):
        """Cached col-partitioned :class:`SparsePanelView` for a
        ``steps``-panel schedule of width ``h`` columns.

        Each shard's live entries are re-sorted (stably, so row order
        survives within a panel) into per-panel slot ranges: panel t owns
        slots [t·nse_p, (t+1)·nse_p) with nse_p the nse-quantum-rounded
        max per-(shard, panel) count.  An SpMM panel step then touches
        ONLY its own contiguous slot range — O(nse + steps·quantum) total
        masking work instead of re-masking all nse entries per panel
        (O(steps·nse)) — which is what makes ``DSLIB_SPMM_PANELS`` a pure
        memory knob.  Stored columns are PANEL-LOCAL (col − t·h); pads
        rebuild from the zero canvas (poisoned primary pads are dropped
        by the slot mask before the re-sort ever sees them).  Built on
        device in one jitted dispatch; derived + cached, so rechunk
        products simply rebuild it lazily."""
        key = (int(steps), int(h))
        if key not in self._pviews:
            pc = self.panel_counts(steps, h)
            nse_p = _round_nse(int(pc.max(initial=0)))
            d, lr, cc = _panel_view_kernel(self.data, self.lrows, self.cols,
                                           self.counts_dev, self.mesh,
                                           int(steps), int(h), nse_p)
            cdev = _pcounts_kernel(tuple(map(tuple, pc.tolist())), self.mesh)
            self._pviews[key] = SparsePanelView(d, lr, cc, cdev, nse_p,
                                                int(steps), int(h))
        return self._pviews[key]

    # -- estimator staging views (built on device, no host round-trip) -------

    def ell_buffers(self):
        """Padded ELL ``(vals (p·m_local, r), cols (p·m_local, r))`` with
        r = max row nnz, built ON DEVICE from the sharded buffers (one
        jitted shard-local scatter — the entries are row-sorted within a
        shard, so slot-within-row is position minus the row's first
        occurrence).  Rows stay P('rows')-sharded; padded rows past the
        logical m are all-zero, so a row gather past m contributes
        nothing.  Derived + cached: the device replacement for the host
        ``argsort``/bincount staging, which is what makes a sharded-backed
        CascadeSVM fit entry transfer-free."""
        if self._ell is None:
            r = max(1, int(self.row_nnz.max(initial=1)))
            self._ell = _ell_kernel(self.data, self.lrows, self.cols,
                                    self.counts_dev, self.mesh, r,
                                    self.m_local)
        return self._ell

    def row_step_plan(self, chunk):
        """Host ``(steps, budget)`` greedy row-step packing from
        ``row_nnz`` alone — identical math to the legacy host-CSR plan
        (same steps, same budget), but pure control-plane arithmetic:
        no device sync ever decides the step shapes.  Each step is
        ``(row_off, rows_in, nnz_lo, nnz_hi)`` over the global row-sorted
        entry stream; steps tile the stream contiguously."""
        m = self.shape[0]
        row_start = np.concatenate([[0], np.cumsum(self.row_nnz)])
        avg_chunk_nnz = max(1, int(np.ceil(int(row_start[-1]) * chunk
                                           / max(m, 1))))
        budget = max(64, 4 * avg_chunk_nnz, int(self.row_nnz.max(initial=1)))
        steps = []
        r = 0
        while r < m:
            r_end = r
            while (r_end < m and r_end - r < chunk
                   and (r_end == r
                        or row_start[r_end + 1] - row_start[r] <= budget)):
                r_end += 1
            steps.append((r, r_end - r, int(row_start[r]),
                          int(row_start[r_end])))
            r = r_end
        if not steps:
            steps = [(0, 0, 0, 0)]
        return steps, budget

    def row_step_buffers(self, chunk):
        """The kNN streaming buffers ``(data (s, budget), local_rows,
        cols, row_off (s,), rows_in (s,))`` gathered ON DEVICE: by the
        row-sorted invariant (and the canonical row split — shards own
        contiguous disjoint row ranges) the shard-major live stream IS the
        global row-sorted stream, so each shard scatters its own slice of
        every step and one psum replicates the result.  Bit-identical to
        the legacy host-CSR staging (same plan, same entry order).
        Cached per chunk."""
        key = int(chunk)
        if key not in self._rsteps:
            plan, budget = self.row_step_plan(chunk)
            starts = tuple(int(v) for v in
                           np.concatenate([[0], np.cumsum(self.counts)]))
            self._rsteps[key] = _row_steps_kernel(
                self.data, self.lrows, self.cols, self.counts_dev,
                self.mesh, tuple(plan), int(budget), self.m_local, starts)
        return self._rsteps[key]


def _padded_rows(m, mesh):
    from dislib_tpu.data.array import _padded_shape
    return _padded_shape((m, 1), _mesh.pad_quantum(mesh))[0]


def _round_nse(nse_min, explicit=None):
    q = nse_quantum()
    need = max(int(nse_min), 1)
    if explicit is not None:
        if int(explicit) < need:
            raise ValueError(
                f"requested nse {explicit} < the densest shard's "
                f"{need} live entries")
        need = int(explicit)
    return int(math.ceil(need / q) * q)


@partial(_pjit, static_argnames=("counts", "mesh"), name="sparse_counts")
def _counts_kernel(counts, mesh):
    tab = jnp.asarray(np.asarray(counts, np.int32))
    return jax.lax.with_sharding_constraint(
        tab, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(_mesh.ROWS)))


@partial(_pjit, static_argnames=("mesh", "m_local"), name="sparse_rowsq")
def _rowsq_kernel(data, lrows, counts, mesh, m_local):
    from jax.sharding import PartitionSpec as P

    def local(d_s, lr_s, cnt_s):
        d, lr, cnt = d_s[0], lr_s[0], cnt_s[0]
        ok = jax.lax.broadcasted_iota(jnp.int32, d.shape, 0) < cnt
        v = jnp.where(ok, d, jnp.zeros((), d.dtype))
        return jax.ops.segment_sum(v * v, lr,
                                   num_segments=m_local)[None, :]

    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(_mesh.ROWS), P(_mesh.ROWS), P(_mesh.ROWS)),
        out_specs=P(_mesh.ROWS),
        check_vma=True,
    )(data, lrows, counts)


SparsePanelView = namedtuple(
    "SparsePanelView",
    ("data", "lrows", "cols", "counts_dev", "nse_p", "steps", "h"))
SparsePanelView.__doc__ = """Col-partitioned derived view of a
:class:`ShardedSparse` (see :meth:`ShardedSparse.panel_view`): ``data`` /
``lrows`` / ``cols`` are (p, steps·nse_p) buffers whose panel-t live
entries occupy slots [t·nse_p, t·nse_p + counts_dev[s, t]); ``cols``
holds PANEL-LOCAL column ids (col − t·h); ``counts_dev`` is the (p,
steps) per-shard-per-panel live-count table (a jit-embedded constant —
transfer-guard clean)."""


@partial(_pjit, static_argnames=("pcounts", "mesh"), name="sparse_pcounts")
def _pcounts_kernel(pcounts, mesh):
    tab = jnp.asarray(np.asarray(pcounts, np.int32))
    return jax.lax.with_sharding_constraint(
        tab, jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(_mesh.ROWS)))


@partial(_pjit, static_argnames=("mesh", "steps", "h", "nse_p"),
         name="sparse_panel_view")
def _panel_view_kernel(data, lrows, cols, counts, mesh, steps, h, nse_p):
    """Device re-sort of each shard's live entries into per-panel slot
    ranges (ONE jitted dispatch, the staging half of the slot-range SpMM
    layout).  Stable within a panel: rank-within-panel comes from a
    cumulative one-hot count over the (row-sorted) live stream, so row
    order — and with it segment-sum determinism — survives.  Pads and
    anything the slot mask rejects scatter with ``mode="drop"`` onto the
    zero canvas: a poisoned primary-buffer tail cannot enter the view."""
    from jax.sharding import PartitionSpec as P

    def local(d_s, lr_s, cc_s, cnt_s):
        d, lr, cc, cnt = d_s[0], lr_s[0], cc_s[0], cnt_s[0]
        nse = d.shape[0]
        live = jax.lax.broadcasted_iota(jnp.int32, (nse,), 0) < cnt
        pan = jnp.where(live, cc // h, steps)          # sentinel for pads
        pan_c = jnp.clip(pan, 0, steps - 1)
        onehot = (pan[:, None] == jax.lax.broadcasted_iota(
            jnp.int32, (nse, steps), 1)).astype(jnp.int32)
        rank = jnp.take_along_axis(jnp.cumsum(onehot, axis=0),
                                   pan_c[:, None], axis=1)[:, 0] - 1
        dest = jnp.where(live, pan_c * nse_p + rank, steps * nse_p)

        def scat(src, dt):
            z = jnp.zeros((steps * nse_p,), dt)
            return z.at[dest].set(src.astype(dt), mode="drop")

        nd = scat(jnp.where(live, d, jnp.zeros((), d.dtype)), d.dtype)
        nlr = scat(jnp.where(live, lr, 0), jnp.int32)
        ncc = scat(jnp.where(live, cc - pan_c * h, 0), jnp.int32)
        return nd[None], nlr[None], ncc[None]

    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(_mesh.ROWS),) * 4,
        out_specs=(P(_mesh.ROWS),) * 3,
        check_vma=True,
    )(data, lrows, cols, counts)


@partial(_pjit, static_argnames=("mesh", "r", "m_local"), name="sparse_ell")
def _ell_kernel(data, lrows, cols, counts, mesh, r, m_local):
    """Shard-local ELL build: entries are row-sorted within a shard, so
    slot-within-row = position − searchsorted-first-occurrence (pads are
    pushed to the ``m_local`` sentinel row first, keeping the keys
    sorted).  Pads scatter with ``mode="drop"`` onto the zero canvas —
    poisoned tails never enter the view."""
    from jax.sharding import PartitionSpec as P

    def local(d_s, lr_s, cc_s, cnt_s):
        d, lr, cc, cnt = d_s[0], lr_s[0], cc_s[0], cnt_s[0]
        nse = d.shape[0]
        pos = jax.lax.broadcasted_iota(jnp.int32, (nse,), 0)
        live = pos < cnt
        keys = jnp.where(live, lr, m_local)
        slot = pos - jnp.searchsorted(keys, keys, side="left").astype(
            jnp.int32)
        dest = jnp.where(live, lr * r + slot, m_local * r)

        def scat(src, dt):
            z = jnp.zeros((m_local * r,), dt)
            return z.at[dest].set(src.astype(dt), mode="drop")

        vals = scat(jnp.where(live, d, jnp.zeros((), d.dtype)), d.dtype)
        ccc = scat(jnp.where(live, cc, 0), jnp.int32)
        return (vals.reshape(1, m_local, r), ccc.reshape(1, m_local, r))

    ev, ec = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(_mesh.ROWS),) * 4,
        out_specs=(P(_mesh.ROWS),) * 2,
        check_vma=True,
    )(data, lrows, cols, counts)
    p = data.shape[0]
    return ev.reshape(p * m_local, r), ec.reshape(p * m_local, r)


@partial(_pjit, static_argnames=("mesh", "plan", "budget", "m_local",
                                 "starts"),
         name="sparse_row_steps")
def _row_steps_kernel(data, lrows, cols, counts, mesh, plan, budget,
                      m_local, starts):
    """Device gather of the kNN row-step buffers: shard s owns global
    stream ids [starts[s], starts[s+1]) (shard-major live slots ARE the
    global row-sorted stream), so each shard scatters its slice of every
    step — destination step by searchsorted over the static step
    boundaries — and a psum over 'rows' replicates the (s, budget)
    rectangles.  Step tables are jit-embedded constants (transfer-guard
    clean)."""
    from jax.sharding import PartitionSpec as P

    s = len(plan)
    row_off_np = np.asarray([st[0] for st in plan], np.int32)
    rows_in_np = np.asarray([st[1] for st in plan], np.int32)
    nlo_np = np.asarray([st[2] for st in plan], np.int64)

    def local(d_s, lr_s, cc_s, cnt_s):
        d, lr, cc, cnt = d_s[0], lr_s[0], cc_s[0], cnt_s[0]
        nse = d.shape[0]
        my = jax.lax.axis_index(_mesh.ROWS)
        e0 = jnp.asarray(np.asarray(starts, np.int32))[my]
        pos = jax.lax.broadcasted_iota(jnp.int32, (nse,), 0)
        live = pos < cnt
        g = e0 + pos                                # global stream id
        nlo = jnp.asarray(nlo_np.astype(np.int32))
        step = jnp.clip(jnp.searchsorted(nlo, g, side="right").astype(
            jnp.int32) - 1, 0, s - 1)
        within = g - nlo[step]
        lrl = lr + my * m_local - jnp.asarray(row_off_np)[step]
        dest = jnp.where(live, step * budget + within, s * budget)

        def scat(src, dt):
            z = jnp.zeros((s * budget,), dt)
            return z.at[dest].set(src.astype(dt), mode="drop")

        out = tuple(
            jax.lax.psum(scat(jnp.where(live, v, jnp.zeros((), v.dtype)),
                              v.dtype).reshape(s, budget), _mesh.ROWS)
            for v in (d, lrl, cc))
        return out

    dta, lrl, ccl = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(_mesh.ROWS),) * 4,
        out_specs=(P(None, None),) * 3,
        check_vma=True,
    )(data, lrows, cols, counts)
    return (dta, lrl, ccl, jnp.asarray(row_off_np), jnp.asarray(rows_in_np))


class SparseArray:
    """A 2-D sparse matrix on device (the CSR-block role).

    Two backings, one API: a single-device BCOO (ingest / host staging),
    and/or the row-panel-sharded :class:`ShardedSparse` buffers (the fast
    path — SpMM, sharded fits, serving, the sparse ``ds.rechunk``
    schedules).  A sharded-only array (the product of an on-device
    rechunk) materialises its BCOO lazily, on host, ONLY when a legacy
    path asks for it — the fast paths never do."""

    def __init__(self, bcoo: jsparse.BCOO | None = None, reg_shape=None,
                 *, sharded: "ShardedSparse | None" = None):
        if (bcoo is None) == (sharded is None):
            if bcoo is None:
                raise ValueError("SparseArray needs a BCOO or a "
                                 "ShardedSparse backing")
        self._bcoo_val = bcoo
        self._sharded_rep = sharded
        src = bcoo if bcoo is not None else sharded
        self._shape = (int(src.shape[0]), int(src.shape[1]))
        self._reg_shape = reg_shape or self._shape
        self._sparse = True
        self._dense_cache = None

    @property
    def _bcoo(self) -> jsparse.BCOO:
        """The single-device BCOO view, built from the sharded buffers on
        first touch for sharded-only arrays (a host materialisation — the
        blessed legacy escape hatch, counted as a transfer)."""
        if self._bcoo_val is None:
            rows, cols, vals = self._sharded_rep.host_triplets()
            idx = np.stack([rows, cols], axis=1).astype(np.int32)
            self._bcoo_val = jsparse.BCOO(
                (jnp.asarray(vals), jnp.asarray(idx)), shape=self._shape)
        return self._bcoo_val

    # -- sharded representation (the fast-path backing) ----------------------

    def sharded(self, mesh=None) -> "ShardedSparse":
        """The :class:`ShardedSparse` buffers for ``mesh`` (default: the
        library mesh) — the sparse analog of ``ensure_canonical``.  A
        matching backing returns as-is; a backing laid out for ANOTHER
        mesh re-lands ON DEVICE through the sparse rechunk schedules
        (never the host, never dense); a BCOO-only array buckets its host
        triplets once (ingest) and caches the result."""
        mesh = mesh or _mesh.get_mesh()
        rep = self._sharded_rep
        if rep is not None:
            if rep.mesh is mesh:
                return rep
            from dislib_tpu.ops import rechunk as _rc
            rep = _rc.reshard_sparse(rep, mesh)
            self._sharded_rep = rep
            return rep
        idx = np.asarray(jax.device_get(self._bcoo.indices))
        val = np.asarray(jax.device_get(self._bcoo.data))
        rep = ShardedSparse.build(idx[:, 0], idx[:, 1], val, self._shape,
                                  mesh)
        self._sharded_rep = rep
        return rep

    def resharded(self, mesh=None, *, schedule="auto", nse=None,
                  overlap=None) -> "SparseArray":
        """A NEW SparseArray whose sharded backing is laid out for
        ``mesh`` / ``nse`` — the ``ds.rechunk`` sparse entry.  On-device
        for an already-sharded source (fused nse re-pad / masked-psum
        panel exchange / deviceput, per the schedule router)."""
        from dislib_tpu.ops import rechunk as _rc
        mesh = mesh or _mesh.get_mesh()
        src = self._sharded_rep
        if src is None:
            src = self.sharded(mesh if schedule in ("auto", "xla")
                               else _mesh.get_mesh())
        rep = _rc.reshard_sparse(src, mesh, schedule=schedule, nse=nse,
                                 overlap=overlap)
        return SparseArray(sharded=rep, reg_shape=self._reg_shape)

    @property
    def _data(self):
        """Lazy padded dense backing — the reference's per-block
        ``.toarray()`` escape hatch, so every non-sparse-aware estimator
        transparently accepts a SparseArray (at densification memory cost).
        Sparse-aware paths (KMeans, NearestNeighbors) dispatch on the type
        before touching this.  Guarded: densification past the
        ``DSLIB_SPARSE_DENSIFY_BUDGET`` byte budget (default 4 GiB) raises
        instead of silently OOMing a chip — raise the env var to opt out."""
        if self._dense_cache is None:
            from dislib_tpu.data.array import _padded_shape
            # the dense backing is PADDED to the mesh quantum — budget on
            # the real allocation, not the logical shape
            pm, pn = _padded_shape(self._shape, _mesh.pad_quantum())
            need = 4 * pm * pn                                  # f32 bytes
            budget = densify_budget_bytes()
            if need > budget:
                raise MemoryError(
                    f"densifying this {self._shape} SparseArray needs "
                    f"~{need / 2**30:.1f} GiB (> budget "
                    f"{budget / 2**30:.1f} GiB). This estimator has no "
                    "sparse-native path; use a sparse-aware one (KMeans, "
                    "NearestNeighbors, KNeighborsClassifier, CascadeSVM, "
                    "ALS, scalers) "
                    "or raise DSLIB_SPARSE_DENSIFY_BUDGET to densify "
                    "anyway.")
            self._dense_cache = self.to_dense()._data
        return self._dense_cache

    # -- construction --------------------------------------------------------

    @classmethod
    def from_scipy(cls, mat, block_size=None, dtype=None,
                   quarantine=False, labels=None) -> "SparseArray":
        """Build from a scipy sparse matrix.

        ``dtype`` — entry dtype (default float32; float64 passes through
        on x64 rigs for the full-precision grid).  ``quarantine=True``
        routes the rows through the ingest hygiene (non-finite stored
        values quarantined per row, reported to the process
        :class:`~dislib_tpu.data.io.QuarantineLedger` with a label-aligned
        ``keep_mask``) — the row-batch sparse STREAM entry: a
        ``partial_fit`` producer building one SparseArray per batch gets
        the same hygiene as the dense loaders.  Returns the array (its
        ``.quarantine_`` carries the report); pass ``labels`` to get
        ``(array, clean_labels)`` back, kept row-aligned."""
        report = None
        if quarantine:
            from dislib_tpu.data.io import _quarantine_csr
            mat = mat.tocsr()
            y = np.zeros(mat.shape[0], np.float32) if labels is None \
                else np.asarray(labels)
            mat, y, report = _quarantine_csr(mat, y, "SparseArray.from_scipy",
                                             True)
            labels = None if labels is None else y
        coo = mat.tocoo()
        dt = np.float64 if (dtype is not None
                            and np.dtype(dtype) == np.float64) else np.float32
        data = jnp.asarray(coo.data.astype(dt))
        idx = jnp.asarray(np.stack([coo.row, coo.col], axis=1).astype(np.int32))
        bcoo = jsparse.BCOO((data, idx), shape=mat.shape)
        out = cls(bcoo, reg_shape=block_size)
        out.quarantine_ = report
        return out if labels is None else (out, labels)

    @classmethod
    def from_dense(cls, x, block_size=None, dtype=None) -> "SparseArray":
        dt = np.float64 if (dtype is not None
                            and np.dtype(dtype) == np.float64) else np.float32
        x = np.asarray(x, dtype=dt)
        return cls(jsparse.BCOO.fromdense(jnp.asarray(x)), reg_shape=block_size)

    # -- metadata ------------------------------------------------------------

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self) -> int:
        if self._bcoo_val is None:      # sharded-only: exact host metadata
            return self._sharded_rep.nnz
        return int(self._bcoo.nse)

    @property
    def block_size(self):
        return self._reg_shape

    def __repr__(self):
        return (f"dslib.SparseArray(shape={self._shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")

    # -- sync / conversion ---------------------------------------------------

    def collect(self):
        """Materialise as scipy CSR on host (reference sparse collect)."""
        import scipy.sparse as sp
        data = np.asarray(jax.device_get(self._bcoo.data))
        idx = np.asarray(jax.device_get(self._bcoo.indices))
        return sp.csr_matrix((data, (idx[:, 0], idx[:, 1])), shape=self._shape)

    def to_dense(self) -> Array:
        """Densify onto the mesh (the reference's `.toarray()` escape
        hatch).  A sharded-backed array densifies ON DEVICE (one jitted
        scatter onto the canonical zero canvas — the matmul router's
        ``algorithm="densify"`` path never detours through the host)."""
        if self._sharded_rep is not None:
            from dislib_tpu.data.array import _padded_shape
            rep = self._sharded_rep
            pshape = _padded_shape(self._shape, _mesh.pad_quantum(rep.mesh))
            out = _densify_kernel(rep.data, rep.lrows, rep.cols,
                                  rep.counts_dev, pshape, rep.m_local,
                                  rep.mesh)
            return Array(out, self._shape, reg_shape=self._reg_shape)
        return Array._from_logical(self._bcoo.todense())

    def _csr(self):
        """Cached host CSR mirror (O(nnz)) — the staging layout for row
        selection and the CSVM sub-Gram path."""
        if getattr(self, "_csr_cache", None) is None:
            self._csr_cache = self.collect().tocsr()
        return self._csr_cache

    def __getitem__(self, key) -> "SparseArray":
        """Slice / fancy-index rows and columns, staying sparse.

        Selection is staged through the cached host CSR (scipy's indexed
        slicing keeps exactly the selected nonzeros — the same block
        movement the reference's KFold does between CSR blocks), then
        returns a new device SparseArray.  This is what KFold /
        train_test_split / shuffle use on sparse inputs.
        """
        from dislib_tpu.data.array import _split_key, _normalize_index
        rows, cols = _split_key(key)
        r_idx, r_len = _normalize_index(rows, self._shape[0])
        c_idx, c_len = _normalize_index(cols, self._shape[1])
        del r_len, c_len  # scipy's indexed shape is already exact
        sub = self._csr()[r_idx][:, c_idx]
        return SparseArray.from_scipy(sub.tocsr())

    # -- ops -----------------------------------------------------------------

    def transpose(self) -> "SparseArray":
        return SparseArray(self._bcoo.T, reg_shape=(self._reg_shape[1],
                                                    self._reg_shape[0]))

    @property
    def T(self) -> "SparseArray":
        return self.transpose()

    def __matmul__(self, other):
        """sparse @ dense → dense Array, through the ``math.matmul``
        spmm/densify router (the sharded masked-psum SpMM when density is
        low, one densified GEMM when it is not)."""
        from dislib_tpu.math import matmul as _matmul
        if not isinstance(other, Array):
            other = Array._from_logical(
                jnp.asarray(np.asarray(other, dtype=np.float32)))
        return _matmul(self, other)

    def sum(self, axis=0) -> Array:
        if axis not in (0, 1, None):
            raise ValueError("axis must be 0, 1 or None")
        data, idx = self._bcoo.data, self._bcoo.indices
        if axis is None:
            return Array._from_logical(jnp.sum(data).reshape(1, 1))
        keep = 1 - axis                     # reduce over `axis`, group by the other
        segs = jax.ops.segment_sum(data, idx[:, keep],
                                   num_segments=self._shape[keep])
        out = segs.reshape(1, -1) if axis == 0 else segs.reshape(-1, 1)
        return Array._from_logical(out)

    def mean(self, axis=0) -> Array:
        denom = self._shape[0] if axis == 0 else \
            self._shape[1] if axis == 1 else self._shape[0] * self._shape[1]
        return self.sum(axis) * (1.0 / denom)

    def row_norms_sq(self):
        """Device vector of per-row ‖x_i‖² (KMeans distance term)."""
        data, idx = self._bcoo.data, self._bcoo.indices
        return jax.ops.segment_sum(data * data, idx[:, 0],
                                   num_segments=self._shape[0])

    # -- elementwise (weak-#6 parity: keep sparsity where it is exact) -------

    def square(self) -> "SparseArray":
        """Elementwise x² — sparsity-preserving (0² = 0)."""
        bcoo = jsparse.BCOO((self._bcoo.data * self._bcoo.data,
                             self._bcoo.indices), shape=self._bcoo.shape)
        return SparseArray(bcoo, reg_shape=self._reg_shape)

    def scale_cols(self, v) -> "SparseArray":
        """Column-wise scaling x[:, j] * v[j] — sparsity-preserving (the
        scalers' sparse transform: no densification)."""
        v = jnp.asarray(v).reshape(-1)
        if v.shape[0] != self._shape[1]:
            raise ValueError(f"scale vector length {v.shape[0]} != "
                             f"{self._shape[1]} columns")
        bcoo = jsparse.BCOO((self._bcoo.data * v[self._bcoo.indices[:, 1]],
                             self._bcoo.indices), shape=self._bcoo.shape)
        return SparseArray(bcoo, reg_shape=self._reg_shape)

    def _scaled(self, factor):
        bcoo = jsparse.BCOO((self._bcoo.data * jnp.float32(factor),
                             self._bcoo.indices), shape=self._bcoo.shape)
        return SparseArray(bcoo, reg_shape=self._reg_shape)

    def __mul__(self, other):
        if np.isscalar(other):
            return self._scaled(other)
        return NotImplemented

    __rmul__ = __mul__

    def __truediv__(self, other):
        if np.isscalar(other):
            return self._scaled(1.0 / other)
        return NotImplemented

    def __neg__(self):
        return self._scaled(-1.0)

    def __add__(self, other):
        """sparse + sparse stays sparse (concatenated-duplicate BCOO);
        sparse + dense densifies (a dense result anyway)."""
        if isinstance(other, SparseArray):
            if other.shape != self.shape:
                raise ValueError(f"shape mismatch {self.shape} + {other.shape}")
            data = jnp.concatenate([self._bcoo.data, other._bcoo.data])
            idx = jnp.concatenate([self._bcoo.indices, other._bcoo.indices])
            bcoo = jsparse.BCOO((data, idx),
                                shape=self._bcoo.shape).sum_duplicates()
            return SparseArray(bcoo, reg_shape=self._reg_shape)
        if isinstance(other, Array):
            return self.to_dense() + other
        return NotImplemented

    def __sub__(self, other):
        if isinstance(other, SparseArray):
            return self + other._scaled(-1.0)
        if isinstance(other, Array):
            return self.to_dense() - other
        return NotImplemented

    # -- row-sharded representation ------------------------------------------

    def sharded_rows(self, mesh=None):
        """(data, local_rows, cols, rowsq) rectangular per-shard buffers,
        leading axis = shard over the mesh 'rows' axis; padding entries
        are (v=0, row=0, col=0) so they contribute nothing.  A view over
        :meth:`sharded` (the :class:`ShardedSparse` backing), kept for
        the kernels that predate it (sharded KMeans, the kNN ring
        tier)."""
        rep = self.sharded(mesh)
        return (rep.data, rep.lrows, rep.cols, rep.rowsq())


    def ell(self, budget=None):
        """Padded ELL buffers ``(vals (m, r), cols (m, r))`` with r = max
        row nnz — the device-resident row-GATHER layout: ``vals[i]`` /
        ``cols[i]`` densify row i by one scatter, so an estimator that
        needs arbitrary row subsets (CascadeSVM node staging) gathers them
        entirely on device instead of slicing a host CSR per node.
        Padding entries are (v=0, col=0) and scatter-add to nothing.

        Skew guard: one dense row inflates r to n, making the buffers
        O(m·n) — when the padded bytes exceed ``budget`` (default
        ``DSLIB_SPARSE_ELL_BUDGET``, 2 GiB) this returns None and callers
        fall back to host-CSR staging.  Cached.

        A sharded-backed array builds the buffers ON DEVICE from the
        :class:`ShardedSparse` buffers (`ell_buffers` — r and the budget
        check come from the host ``row_nnz`` metadata, so the whole
        staging is transfer-free); the host ``argsort`` path below is the
        BCOO-only ingest fallback."""
        import os
        if budget is None:
            budget = int(os.environ.get("DSLIB_SPARSE_ELL_BUDGET", 2 << 30))
        rep = self._sharded_rep
        if rep is not None:
            r = max(1, int(rep.row_nnz.max(initial=1)))
            # budget on the real (padded-rows) allocation; re-checked on
            # every call so lowering the budget between fits gets the
            # fallback, not the over-budget cache
            if rep.p * rep.m_local * r * 8 > budget:
                return None
            return rep.ell_buffers()
        # budget is re-checked against the CACHED buffers too: a caller
        # lowering the budget between fits must get the fallback, not the
        # over-budget cache
        cached = getattr(self, "_ell_cache", None)
        if cached is not None:
            m_c, r_c = cached[0].shape
            return cached if m_c * r_c * 8 <= budget else None
        m = self._shape[0]
        idx = np.asarray(jax.device_get(self._bcoo.indices))
        val = np.asarray(jax.device_get(self._bcoo.data))
        row_nnz = np.bincount(idx[:, 0], minlength=m)
        r = max(1, int(row_nnz.max(initial=1)))
        if m * r * 8 > budget:      # f32 vals + i32 cols
            return None
        vals = np.zeros((m, r), np.float32)
        cols = np.zeros((m, r), np.int32)
        order = np.argsort(idx[:, 0], kind="stable")
        slot = np.arange(len(val)) - np.concatenate(
            [[0], np.cumsum(row_nnz)])[idx[order, 0]]
        vals[idx[order, 0], slot] = val[order]
        cols[idx[order, 0], slot] = idx[order, 1]
        self._ell_cache = (jnp.asarray(vals), jnp.asarray(cols))
        return self._ell_cache

    def row_steps(self, chunk):
        """Equal-shape per-step triplet buffers for streaming a bounded
        dense window of the matrix (the kNN sparse path): rows are packed
        greedily into steps bounded BOTH by ``chunk`` rows and by an nnz
        budget (4× the average chunk's nonzeros, and never below the
        densest single row), so skewed sparsity cannot inflate the
        rectangles to O(n_steps · max_chunk_nnz) — total padding is at most
        ~one budget per step.  Returns (data (s, budget), local_rows,
        cols, row_off (s,), rows_in (s,)); padding entries are (v=0,
        row=0, col=0) and scatter-add to nothing.  Cached per chunk.

        A sharded-backed array plans the steps from host ``row_nnz``
        metadata and gathers the buffers ON DEVICE (`row_step_buffers` —
        bit-identical plan and entry order to the host staging, zero
        transfers); the host path below is the BCOO-only fallback."""
        if self._sharded_rep is not None:
            # sharded() (not the raw rep): a backing laid out for another
            # mesh re-lands on the library mesh first, on device
            return self.sharded().row_step_buffers(chunk)
        cached = getattr(self, "_row_steps_cache", None)
        if cached is not None and cached[0] == chunk:
            return cached[1]
        m = self._shape[0]
        idx = np.asarray(jax.device_get(self._bcoo.indices))
        val = np.asarray(jax.device_get(self._bcoo.data))
        order = np.argsort(idx[:, 0], kind="stable")
        rows_sorted = idx[order, 0]
        row_nnz = np.bincount(rows_sorted, minlength=m)
        row_start = np.concatenate([[0], np.cumsum(row_nnz)])
        avg_chunk_nnz = max(1, int(np.ceil(len(val) * chunk / max(m, 1))))
        budget = max(64, 4 * avg_chunk_nnz, int(row_nnz.max(initial=1)))
        steps = []                       # (row_off, rows_in, nnz_lo, nnz_hi)
        r = 0
        while r < m:
            r_end = r
            while (r_end < m and r_end - r < chunk
                   and (r_end == r
                        or row_start[r_end + 1] - row_start[r] <= budget)):
                r_end += 1
            steps.append((r, r_end - r, int(row_start[r]),
                          int(row_start[r_end])))
            r = r_end
        if not steps:
            steps = [(0, 0, 0, 0)]
        s = len(steps)
        data = np.zeros((s, budget), np.float32)
        lrows = np.zeros((s, budget), np.int32)
        cols = np.zeros((s, budget), np.int32)
        row_off = np.zeros(s, np.int32)
        rows_in = np.zeros(s, np.int32)
        for i, (ro, rc, nlo, nhi) in enumerate(steps):
            c = nhi - nlo
            sel = order[nlo:nhi]
            data[i, :c] = val[sel]
            lrows[i, :c] = idx[sel, 0] - ro
            cols[i, :c] = idx[sel, 1]
            row_off[i] = ro
            rows_in[i] = rc
        out = tuple(jnp.asarray(a)
                    for a in (data, lrows, cols, row_off, rows_in))
        self._row_steps_cache = (chunk, out)
        return out


@jax.jit
@precise
def _spmm(bcoo, rhs):
    return jsparse.bcoo_dot_general(
        bcoo, rhs, dimension_numbers=(([1], [0]), ([], [])))


@partial(_pjit, static_argnames=("pshape", "m_local", "mesh"),
         name="sparse_densify")
@precise
def _densify_kernel(data, lrows, cols, counts, pshape, m_local, mesh):
    """Sharded buffers → canonical dense padded canvas, ON DEVICE: one
    masked scatter-add onto zeros (the ``algorithm="densify"`` route and
    ``to_dense`` for sharded-backed arrays).  The slot mask keeps
    poisoned pads out; the canvas starts zero, so the pad-and-mask
    invariant holds by construction."""
    p, nse = data.shape
    slot_ok = jax.lax.broadcasted_iota(jnp.int32, (p, nse), 1) \
        < counts[:, None]
    v = jnp.where(slot_ok, data, jnp.zeros((), data.dtype))
    grow = lrows + (jax.lax.broadcasted_iota(jnp.int32, (p, nse), 0)
                    * m_local)
    out = jnp.zeros(pshape, data.dtype)
    out = out.at[grow.ravel(), cols.ravel()].add(v.ravel())
    return jax.lax.with_sharding_constraint(out, _mesh.data_sharding(mesh))
