"""Sparse ds-array — BCOO-backed storage (SURVEY.md §8 "Sparse support":
"TPU has no general CSR.  BCOO matvec covers ALS/svmlight ingestion;
dense-with-mask is the fallback; this decision gates ALS and sparse
KMeans/CSVM parity").

Reference capability: ds-array blocks may be SciPy CSR matrices
(`dislib/data/array.py`, `_sparse=True`); KMeans/CSVM/svmlight ingestion
accept them and per-block NumPy kernels dispatch to scipy.sparse ops.

TPU-native design and its honest limits:

- Storage is one `jax.experimental.sparse.BCOO` on device — O(nnz) memory,
  the role CSR plays for the reference.  Dense products against it
  materialise MXU-shaped results placed with the library sharding.
- **Row-sharded representation** (`ShardedRows`): the nonzeros are bucketed
  by row shard into rectangular (p, nnz_max) buffers — data, shard-local
  row ids, column ids — padded per shard with zero-valued entries so every
  shard is the same shape (BCOO's ragged buffers do not shard over a Mesh;
  rectangular buffers do).  `x @ B` is then shard-local (each shard owns
  disjoint output rows: gather B rows at the entry columns, scale,
  segment-sum by local row) and `xᵀ @ C` is a shard-local partial plus ONE
  `psum` over the rows axis — the identical communication structure to the
  dense KMeans path.  Sparse KMeans runs entirely on this representation.
- Per-estimator choice (recorded as SURVEY §8 directs):
  * KMeans — native sparse path (`fit`/`predict` accept SparseArray; the
    distance cross-term and the per-cluster sums are `bcoo_dot_general`
    contractions).
  * ALS — dense-with-mask (see `recommendation/als.py`: a zero rating IS
    the mask; the normal-equation GEMMs need the dense mask anyway).
  * CascadeSVM — sparse-native: host-CSR-staged per-node sub-Grams feed
    the device dual solves; queries classify via one spmm cross-term
    (`classification/csvm.py`).
  * trees / others — densify (`to_dense()`); same stance as the
    reference's per-block `.toarray()` escape hatches.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from dislib_tpu.data.array import Array
from dislib_tpu.ops.base import precise
from dislib_tpu.parallel import mesh as _mesh

__all__ = ["SparseArray"]


class SparseArray:
    """A 2-D sparse matrix on device, BCOO-backed (the CSR-block role)."""

    def __init__(self, bcoo: jsparse.BCOO, reg_shape=None):
        self._bcoo = bcoo
        self._shape = (int(bcoo.shape[0]), int(bcoo.shape[1]))
        self._reg_shape = reg_shape or self._shape
        self._sparse = True
        self._dense_cache = None

    @property
    def _data(self):
        """Lazy padded dense backing — the reference's per-block
        ``.toarray()`` escape hatch, so every non-sparse-aware estimator
        transparently accepts a SparseArray (at densification memory cost).
        Sparse-aware paths (KMeans, NearestNeighbors) dispatch on the type
        before touching this.  Guarded: densification past the
        ``DSLIB_SPARSE_DENSIFY_BUDGET`` byte budget (default 4 GiB) raises
        instead of silently OOMing a chip — raise the env var to opt out."""
        if self._dense_cache is None:
            import os
            from dislib_tpu.data.array import _padded_shape
            # the dense backing is PADDED to the mesh quantum — budget on
            # the real allocation, not the logical shape
            pm, pn = _padded_shape(self._shape, _mesh.pad_quantum())
            need = 4 * pm * pn                                  # f32 bytes
            budget = int(os.environ.get("DSLIB_SPARSE_DENSIFY_BUDGET",
                                        4 << 30))
            if need > budget:
                raise MemoryError(
                    f"densifying this {self._shape} SparseArray needs "
                    f"~{need / 2**30:.1f} GiB (> budget "
                    f"{budget / 2**30:.1f} GiB). This estimator has no "
                    "sparse-native path; use a sparse-aware one (KMeans, "
                    "NearestNeighbors, KNeighborsClassifier, CascadeSVM, "
                    "ALS, scalers) "
                    "or raise DSLIB_SPARSE_DENSIFY_BUDGET to densify "
                    "anyway.")
            self._dense_cache = self.to_dense()._data
        return self._dense_cache

    # -- construction --------------------------------------------------------

    @classmethod
    def from_scipy(cls, mat, block_size=None) -> "SparseArray":
        coo = mat.tocoo()
        data = jnp.asarray(coo.data.astype(np.float32))
        idx = jnp.asarray(np.stack([coo.row, coo.col], axis=1).astype(np.int32))
        bcoo = jsparse.BCOO((data, idx), shape=mat.shape)
        return cls(bcoo, reg_shape=block_size)

    @classmethod
    def from_dense(cls, x, block_size=None) -> "SparseArray":
        x = np.asarray(x, dtype=np.float32)
        return cls(jsparse.BCOO.fromdense(jnp.asarray(x)), reg_shape=block_size)

    # -- metadata ------------------------------------------------------------

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self) -> int:
        return int(self._bcoo.nse)

    @property
    def block_size(self):
        return self._reg_shape

    def __repr__(self):
        return (f"dslib.SparseArray(shape={self._shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")

    # -- sync / conversion ---------------------------------------------------

    def collect(self):
        """Materialise as scipy CSR on host (reference sparse collect)."""
        import scipy.sparse as sp
        data = np.asarray(jax.device_get(self._bcoo.data))
        idx = np.asarray(jax.device_get(self._bcoo.indices))
        return sp.csr_matrix((data, (idx[:, 0], idx[:, 1])), shape=self._shape)

    def to_dense(self) -> Array:
        """Densify onto the mesh (the reference's `.toarray()` escape hatch)."""
        return Array._from_logical(self._bcoo.todense())

    def _csr(self):
        """Cached host CSR mirror (O(nnz)) — the staging layout for row
        selection and the CSVM sub-Gram path."""
        if getattr(self, "_csr_cache", None) is None:
            self._csr_cache = self.collect().tocsr()
        return self._csr_cache

    def __getitem__(self, key) -> "SparseArray":
        """Slice / fancy-index rows and columns, staying sparse.

        Selection is staged through the cached host CSR (scipy's indexed
        slicing keeps exactly the selected nonzeros — the same block
        movement the reference's KFold does between CSR blocks), then
        returns a new device SparseArray.  This is what KFold /
        train_test_split / shuffle use on sparse inputs.
        """
        from dislib_tpu.data.array import _split_key, _normalize_index
        rows, cols = _split_key(key)
        r_idx, r_len = _normalize_index(rows, self._shape[0])
        c_idx, c_len = _normalize_index(cols, self._shape[1])
        del r_len, c_len  # scipy's indexed shape is already exact
        sub = self._csr()[r_idx][:, c_idx]
        return SparseArray.from_scipy(sub.tocsr())

    # -- ops -----------------------------------------------------------------

    def transpose(self) -> "SparseArray":
        return SparseArray(self._bcoo.T, reg_shape=(self._reg_shape[1],
                                                    self._reg_shape[0]))

    @property
    def T(self) -> "SparseArray":
        return self.transpose()

    def __matmul__(self, other):
        """sparse @ dense → dense Array (one bcoo_dot_general, MXU-lowered)."""
        if isinstance(other, Array):
            rhs = other._data[: other.shape[0], : other.shape[1]]
        else:
            rhs = jnp.asarray(np.asarray(other, dtype=np.float32))
        if self._shape[1] != rhs.shape[0]:
            raise ValueError(f"matmul shape mismatch {self._shape} @ {rhs.shape}")
        out = _spmm(self._bcoo, rhs)
        return Array._from_logical(out)

    def sum(self, axis=0) -> Array:
        if axis not in (0, 1, None):
            raise ValueError("axis must be 0, 1 or None")
        data, idx = self._bcoo.data, self._bcoo.indices
        if axis is None:
            return Array._from_logical(jnp.sum(data).reshape(1, 1))
        keep = 1 - axis                     # reduce over `axis`, group by the other
        segs = jax.ops.segment_sum(data, idx[:, keep],
                                   num_segments=self._shape[keep])
        out = segs.reshape(1, -1) if axis == 0 else segs.reshape(-1, 1)
        return Array._from_logical(out)

    def mean(self, axis=0) -> Array:
        denom = self._shape[0] if axis == 0 else \
            self._shape[1] if axis == 1 else self._shape[0] * self._shape[1]
        return self.sum(axis) * (1.0 / denom)

    def row_norms_sq(self):
        """Device vector of per-row ‖x_i‖² (KMeans distance term)."""
        data, idx = self._bcoo.data, self._bcoo.indices
        return jax.ops.segment_sum(data * data, idx[:, 0],
                                   num_segments=self._shape[0])

    # -- elementwise (weak-#6 parity: keep sparsity where it is exact) -------

    def square(self) -> "SparseArray":
        """Elementwise x² — sparsity-preserving (0² = 0)."""
        bcoo = jsparse.BCOO((self._bcoo.data * self._bcoo.data,
                             self._bcoo.indices), shape=self._bcoo.shape)
        return SparseArray(bcoo, reg_shape=self._reg_shape)

    def scale_cols(self, v) -> "SparseArray":
        """Column-wise scaling x[:, j] * v[j] — sparsity-preserving (the
        scalers' sparse transform: no densification)."""
        v = jnp.asarray(v).reshape(-1)
        if v.shape[0] != self._shape[1]:
            raise ValueError(f"scale vector length {v.shape[0]} != "
                             f"{self._shape[1]} columns")
        bcoo = jsparse.BCOO((self._bcoo.data * v[self._bcoo.indices[:, 1]],
                             self._bcoo.indices), shape=self._bcoo.shape)
        return SparseArray(bcoo, reg_shape=self._reg_shape)

    def _scaled(self, factor):
        bcoo = jsparse.BCOO((self._bcoo.data * jnp.float32(factor),
                             self._bcoo.indices), shape=self._bcoo.shape)
        return SparseArray(bcoo, reg_shape=self._reg_shape)

    def __mul__(self, other):
        if np.isscalar(other):
            return self._scaled(other)
        return NotImplemented

    __rmul__ = __mul__

    def __truediv__(self, other):
        if np.isscalar(other):
            return self._scaled(1.0 / other)
        return NotImplemented

    def __neg__(self):
        return self._scaled(-1.0)

    def __add__(self, other):
        """sparse + sparse stays sparse (concatenated-duplicate BCOO);
        sparse + dense densifies (a dense result anyway)."""
        if isinstance(other, SparseArray):
            if other.shape != self.shape:
                raise ValueError(f"shape mismatch {self.shape} + {other.shape}")
            data = jnp.concatenate([self._bcoo.data, other._bcoo.data])
            idx = jnp.concatenate([self._bcoo.indices, other._bcoo.indices])
            bcoo = jsparse.BCOO((data, idx),
                                shape=self._bcoo.shape).sum_duplicates()
            return SparseArray(bcoo, reg_shape=self._reg_shape)
        if isinstance(other, Array):
            return self.to_dense() + other
        return NotImplemented

    def __sub__(self, other):
        if isinstance(other, SparseArray):
            return self + other._scaled(-1.0)
        if isinstance(other, Array):
            return self.to_dense() - other
        return NotImplemented

    # -- row-sharded representation ------------------------------------------

    def sharded_rows(self, mesh=None):
        """(data, local_rows, cols, rowsq) rectangular per-shard buffers,
        leading axis = shard over the mesh 'rows' axis; padding entries are
        (v=0, row=0, col=0) so they contribute nothing.  Cached per mesh
        OBJECT (not shard count): a re-initialised mesh with the same p but
        a different device order would otherwise be handed buffers
        device_put with the stale mesh's NamedSharding."""
        mesh = mesh or _mesh.get_mesh()
        p = mesh.shape[_mesh.ROWS]
        cached = getattr(self, "_sharded_cache", None)
        if cached is not None and cached[0] is mesh:
            return cached[1]
        m = self._shape[0]
        m_local = -(-m // p)
        idx = np.asarray(jax.device_get(self._bcoo.indices))
        val = np.asarray(jax.device_get(self._bcoo.data))
        shard = idx[:, 0] // m_local
        counts = np.bincount(shard, minlength=p)
        nnz_max = max(1, int(counts.max()))
        data = np.zeros((p, nnz_max), np.float32)
        lrows = np.zeros((p, nnz_max), np.int32)
        cols = np.zeros((p, nnz_max), np.int32)
        for s in range(p):
            sel = shard == s
            k = int(counts[s])
            data[s, :k] = val[sel]
            lrows[s, :k] = idx[sel, 0] - s * m_local
            cols[s, :k] = idx[sel, 1]
        rowsq = np.zeros((p, m_local), np.float32)
        np.add.at(rowsq, (shard, idx[:, 0] - shard * m_local), val * val)
        sh = jax.sharding.NamedSharding(mesh,
                                        jax.sharding.PartitionSpec(_mesh.ROWS))
        out = tuple(jax.device_put(jnp.asarray(a), sh)
                    for a in (data, lrows, cols, rowsq))
        self._sharded_cache = (mesh, out)
        return out


    def ell(self, budget=None):
        """Padded ELL buffers ``(vals (m, r), cols (m, r))`` with r = max
        row nnz — the device-resident row-GATHER layout: ``vals[i]`` /
        ``cols[i]`` densify row i by one scatter, so an estimator that
        needs arbitrary row subsets (CascadeSVM node staging) gathers them
        entirely on device instead of slicing a host CSR per node.
        Padding entries are (v=0, col=0) and scatter-add to nothing.

        Skew guard: one dense row inflates r to n, making the buffers
        O(m·n) — when the padded bytes exceed ``budget`` (default
        ``DSLIB_SPARSE_ELL_BUDGET``, 2 GiB) this returns None and callers
        fall back to host-CSR staging.  Cached."""
        import os
        if budget is None:
            budget = int(os.environ.get("DSLIB_SPARSE_ELL_BUDGET", 2 << 30))
        # budget is re-checked against the CACHED buffers too: a caller
        # lowering the budget between fits must get the fallback, not the
        # over-budget cache
        cached = getattr(self, "_ell_cache", None)
        if cached is not None:
            m_c, r_c = cached[0].shape
            return cached if m_c * r_c * 8 <= budget else None
        m = self._shape[0]
        idx = np.asarray(jax.device_get(self._bcoo.indices))
        val = np.asarray(jax.device_get(self._bcoo.data))
        row_nnz = np.bincount(idx[:, 0], minlength=m)
        r = max(1, int(row_nnz.max(initial=1)))
        if m * r * 8 > budget:      # f32 vals + i32 cols
            return None
        vals = np.zeros((m, r), np.float32)
        cols = np.zeros((m, r), np.int32)
        order = np.argsort(idx[:, 0], kind="stable")
        slot = np.arange(len(val)) - np.concatenate(
            [[0], np.cumsum(row_nnz)])[idx[order, 0]]
        vals[idx[order, 0], slot] = val[order]
        cols[idx[order, 0], slot] = idx[order, 1]
        self._ell_cache = (jnp.asarray(vals), jnp.asarray(cols))
        return self._ell_cache

    def row_steps(self, chunk):
        """Equal-shape per-step triplet buffers for streaming a bounded
        dense window of the matrix (the kNN sparse path): rows are packed
        greedily into steps bounded BOTH by ``chunk`` rows and by an nnz
        budget (4× the average chunk's nonzeros, and never below the
        densest single row), so skewed sparsity cannot inflate the
        rectangles to O(n_steps · max_chunk_nnz) — total padding is at most
        ~one budget per step.  Returns (data (s, budget), local_rows,
        cols, row_off (s,), rows_in (s,)); padding entries are (v=0,
        row=0, col=0) and scatter-add to nothing.  Cached per chunk."""
        cached = getattr(self, "_row_steps_cache", None)
        if cached is not None and cached[0] == chunk:
            return cached[1]
        m = self._shape[0]
        idx = np.asarray(jax.device_get(self._bcoo.indices))
        val = np.asarray(jax.device_get(self._bcoo.data))
        order = np.argsort(idx[:, 0], kind="stable")
        rows_sorted = idx[order, 0]
        row_nnz = np.bincount(rows_sorted, minlength=m)
        row_start = np.concatenate([[0], np.cumsum(row_nnz)])
        avg_chunk_nnz = max(1, int(np.ceil(len(val) * chunk / max(m, 1))))
        budget = max(64, 4 * avg_chunk_nnz, int(row_nnz.max(initial=1)))
        steps = []                       # (row_off, rows_in, nnz_lo, nnz_hi)
        r = 0
        while r < m:
            r_end = r
            while (r_end < m and r_end - r < chunk
                   and (r_end == r
                        or row_start[r_end + 1] - row_start[r] <= budget)):
                r_end += 1
            steps.append((r, r_end - r, int(row_start[r]),
                          int(row_start[r_end])))
            r = r_end
        if not steps:
            steps = [(0, 0, 0, 0)]
        s = len(steps)
        data = np.zeros((s, budget), np.float32)
        lrows = np.zeros((s, budget), np.int32)
        cols = np.zeros((s, budget), np.int32)
        row_off = np.zeros(s, np.int32)
        rows_in = np.zeros(s, np.int32)
        for i, (ro, rc, nlo, nhi) in enumerate(steps):
            c = nhi - nlo
            sel = order[nlo:nhi]
            data[i, :c] = val[sel]
            lrows[i, :c] = idx[sel, 0] - ro
            cols[i, :c] = idx[sel, 1]
            row_off[i] = ro
            rows_in[i] = rc
        out = tuple(jnp.asarray(a)
                    for a in (data, lrows, cols, row_off, rows_in))
        self._row_steps_cache = (chunk, out)
        return out


@jax.jit
@precise
def _spmm(bcoo, rhs):
    return jsparse.bcoo_dot_general(
        bcoo, rhs, dimension_numbers=(([1], [0]), ([], [])))
