"""Data ingest / export.

Reference capability (SURVEY.md §3.1 "I/O", `dislib/data/io.py`): per-block
reader tasks over a shared filesystem so loading is itself parallel —
`load_txt_file`, `load_svmlight_file` (sparse-capable), `load_npy_file`,
`load_mdcrd_file` (AMBER mdcrd MD trajectories), `save_txt`.

TPU-native shape (SURVEY §4.1 mapping): in a multi-host job each host scans
the file for line offsets (cheap byte pass, no float parse), parses ONLY the
row slab its addressable shards cover, and the global array is assembled
shard-by-shard with `jax.make_array_from_single_device_arrays` — no
collective at ingest and no host ever materialises the full logical array.
Single-host (this build's test rig) parses locally and `device_put`s with
the canonical sharding.  Parsing itself is host-side C-speed (numpy loadtxt
/ native fastio), matching the reference where parsing was also CPU-side
inside tasks.

**Ingest quarantine** (round-8 health PR): a single NaN row in a loaded
file would poison every block it lands in — distances go NaN, ε/cutoff
comparisons silently fail, and the runtime health guards can only refuse
the fit after the fact.  The loaders therefore detect non-finite rows at
parse time, ISOLATE them into a :class:`QuarantineReport` (attached to
the returned array as ``.quarantine_`` and readable via
:func:`last_quarantine_report`), and build the ds-array from the clean
rows only.  Opt out per call (``quarantine=False``) or globally
(``DSLIB_QUARANTINE=0``) to load the raw rows — the health guards then
raise their typed diagnostic instead.  Multi-process sharded ingest
skips quarantine (dropping rows host-locally would desync the global
shape) — scrub files offline for multi-host jobs.
"""

from __future__ import annotations

import functools
import io as _io
import os
import warnings

import numpy as np

from dislib_tpu.data.array import (Array as _Array, array as _ds_array,
                                   _padded_shape)
from dislib_tpu.parallel import mesh as _mesh


class QuarantineReport:
    """What the ingest quarantine isolated from one load: the 0-based
    ``rows`` (in the file's row order), the offending ``values`` rows
    themselves (for offline triage), the ``labels`` that rode along
    (svmlight), the ``source`` path, and ``n_loaded`` clean rows.

    **Paired files.** Dropping rows changes row numbering, so arrays
    loaded from SEPARATE files that pair row-by-row (features.csv +
    labels.csv) silently misalign if either file quarantined rows.
    ``load_svmlight_file`` keeps its own x/y aligned; for separately
    loaded pairs, apply this report's :attr:`keep_mask` to the partner
    (``y = y[report.keep_mask, :]``) — and the partner's report to this
    array — or load both with ``quarantine=False`` and let the runtime
    health guards raise their typed diagnostic instead."""

    def __init__(self, source, rows, values, n_loaded, labels=None):
        self.source = str(source)
        self.rows = np.asarray(rows, np.int64)
        self.values = values
        self.labels = labels
        self.n_loaded = int(n_loaded)

    @property
    def n_quarantined(self):
        return int(self.rows.size)

    @property
    def n_total(self):
        """Rows in the source file (loaded + quarantined)."""
        return self.n_loaded + self.n_quarantined

    @property
    def keep_mask(self):
        """Boolean mask over the ORIGINAL file's rows (True = kept) —
        apply it to a row-paired array from another file to restore
        row correspondence after this load's quarantine."""
        mask = np.ones(self.n_total, bool)
        mask[self.rows] = False
        return mask

    def __repr__(self):
        return (f"QuarantineReport(source={self.source!r}, "
                f"n_quarantined={self.n_quarantined}, "
                f"n_loaded={self.n_loaded}, rows={self.rows.tolist()})")


class QuarantineLedger:
    """Stream-wide accumulation of ingest quarantines (round-12 fix: the
    module-level report used to be OVERWRITTEN per load, so a streaming
    job — repeated ``load → partial_fit`` batches — could only ever see
    its LAST batch's quarantine).  Every load that quarantines rows
    appends its :class:`QuarantineReport` here, in arrival order, so the
    steady-state stream can audit total losses and re-align the affected
    row-paired batches.  :meth:`reset` is the escape hatch between
    logically separate streams.

    Two bounds keep the infinite-stream case honest: the COUNT totals
    (``n_quarantined``/``n_loaded``) are exact accumulators for the whole
    stream, while ``reports`` (which pin each load's offending-row value
    arrays) retain only the newest ``max_reports``
    (``DSLIB_QUARANTINE_LEDGER_CAP``, default 256) — a service ingesting
    occasionally-dirty batches for days must not leak every bad row it
    ever saw."""

    def __init__(self, max_reports=None):
        self.reports: list[QuarantineReport] = []
        self.max_reports = int(os.environ.get(
            "DSLIB_QUARANTINE_LEDGER_CAP", 256)) \
            if max_reports is None else int(max_reports)
        self._totals = [0, 0]

    def append(self, report: QuarantineReport) -> None:
        self.reports.append(report)
        self._totals[0] += report.n_quarantined
        self._totals[1] += report.n_loaded
        del self.reports[: max(0, len(self.reports) - self.max_reports)]

    @property
    def n_quarantined(self) -> int:
        """Total rows quarantined across every load since the last reset
        (exact even past the retained-report cap)."""
        return self._totals[0]

    @property
    def n_loaded(self) -> int:
        """Total clean rows loaded by the quarantining loads."""
        return self._totals[1]

    @property
    def keep_masks(self) -> list:
        """Per-report keep-masks of the RETAINED reports, in load order —
        apply each to its batch's row-paired partner
        (``QuarantineReport.keep_mask`` semantics, preserved per batch
        instead of overwritten)."""
        return [r.keep_mask for r in self.reports]

    def keep_mask_all(self):
        """The retained reports' masks concatenated in load order.  NOTE:
        loads that quarantined NOTHING never enter the ledger, so this
        spans only the affected batches — re-align a mixed stream batch
        by batch (match each report's ``source`` to its partner batch),
        not by slicing one global mask over every batch ever loaded."""
        masks = self.keep_masks
        return np.concatenate(masks) if masks else np.zeros(0, bool)

    def reset(self) -> None:
        self.reports.clear()
        self._totals = [0, 0]

    def __repr__(self):
        return (f"QuarantineLedger(loads={len(self.reports)}, "
                f"n_quarantined={self.n_quarantined}, "
                f"n_loaded={self.n_loaded})")


_LAST_QUARANTINE: QuarantineReport | None = None
_LEDGER = QuarantineLedger()


def last_quarantine_report() -> QuarantineReport | None:
    """The :class:`QuarantineReport` of the most recent load that
    quarantined rows in this process, or None."""
    return _LAST_QUARANTINE


def quarantine_ledger() -> QuarantineLedger:
    """The process-wide :class:`QuarantineLedger` — quarantine outcomes
    ACCUMULATED across repeated ingest/``partial_fit`` calls (the
    streaming steady state), with ``reset()`` as the escape hatch."""
    return _LEDGER


def _quarantine_enabled(opt) -> bool:
    if opt is not None:
        return bool(opt)
    return os.environ.get("DSLIB_QUARANTINE", "1") != "0"


def _emit_quarantine(source, rows, bad_values, n_clean, bad_labels=None):
    """The shared report/warn/refuse tail of both quarantine paths (dense
    rows and CSR) — one place owns the report registration and the user
    messages so they cannot drift."""
    global _LAST_QUARANTINE
    report = QuarantineReport(source, rows, bad_values, n_clean,
                              labels=bad_labels)
    _LAST_QUARANTINE = report
    _LEDGER.append(report)
    from dislib_tpu.utils.profiling import count_resilience
    count_resilience("quarantined_rows", report.n_quarantined)
    warnings.warn(
        f"{source}: quarantined {report.n_quarantined} bad row(s) "
        "(non-finite values/labels, or out-of-range feature indices) "
        f"(indices {rows[:8].tolist()}{'...' if len(rows) > 8 else ''}) — "
        "see last_quarantine_report() / the returned array's .quarantine_; "
        "pass quarantine=False (or DSLIB_QUARANTINE=0) to load them raw. "
        "If this file pairs row-by-row with another (features/labels), "
        "re-align the partner with report.keep_mask or row numbering "
        "silently shifts",
        RuntimeWarning, stacklevel=4)
    if n_clean == 0:
        raise ValueError(
            f"{source}: every row is non-finite — nothing left to load "
            "after quarantine (pass quarantine=False to load raw)")
    return report


def _quarantine_rows(data, source, opt, labels=None):
    """Split non-finite rows out of a parsed host matrix (and the labels
    vector riding along, svmlight).  Returns ``(clean, clean_labels,
    report_or_None)``; multi-process jobs skip (see module docstring)."""
    import jax
    if not _quarantine_enabled(opt) or jax.process_count() > 1 \
            or data.size == 0:
        return data, labels, None
    bad = ~np.isfinite(data).all(axis=1)
    if labels is not None:
        bad |= ~np.isfinite(np.asarray(labels, np.float64)).ravel()
    if not bad.any():
        return data, labels, None
    rows = np.nonzero(bad)[0]
    clean = data[~bad]
    clean_labels = labels[~bad] if labels is not None else None
    report = _emit_quarantine(
        source, rows, data[bad], clean.shape[0],
        bad_labels=None if labels is None else labels[bad])
    return clean, clean_labels, report


def quarantine_batch(batch, source="stream", quarantine=None):
    """Screen one host batch of a streaming fit through the ingest
    quarantine — the per-batch face of the same machinery the file
    loaders ride (round-17 trainer seam).  Non-finite rows are split
    out, reported to the process-wide :class:`QuarantineLedger` (exact
    totals accumulate across batches and generations; retained reports
    stay bounded by ``DSLIB_QUARANTINE_LEDGER_CAP``), and counted in
    the resilience counters.  Returns ``(clean_rows, report_or_None)``;
    raises ``ValueError`` when EVERY row is dirty (nothing to learn
    from — callers skip the batch and keep the stream alive).  1-D
    input is treated as a single row; multi-process jobs skip the
    screen (module docstring)."""
    data = np.asarray(batch, np.float32)
    if data.ndim == 1:
        data = data.reshape(1, -1)
    clean, _, report = _quarantine_rows(data, source, quarantine)
    return clean, report


def _retrying_loader(fn):
    """Retry a whole loader under the env-tunable transient-failure policy
    (``dislib_tpu.runtime.Retry``): a flaky shared filesystem (EIO,
    connection reset, stale NFS handle) re-reads; parse errors and missing
    files classify fatal and raise immediately.  Loaders are pure (parse →
    device_put), so a re-run is safe.  Multi-process jobs run a SINGLE
    attempt: the sharded ingest paths contain collectives, and one host
    retrying alone would desync the job — resubmit the whole job instead."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        import jax
        if jax.process_count() > 1:
            return fn(*args, **kwargs)
        from dislib_tpu.runtime import Retry
        return Retry.from_env(attempts=3, backoff=0.25).call(
            fn, *args, **kwargs)
    return wrapped


def _native_parse(parser_name, path):
    """Run a `dislib_tpu.native` parser over a whole file, or return None
    when the native layer is unavailable or defers (malformed input — the
    Python fallback then raises the user-facing error)."""
    from dislib_tpu import native as _native
    if _native.get_lib() is None:
        return None
    try:
        with open(path, "rb") as f:
            return getattr(_native, parser_name)(f.read())
    except _native.NativeUnavailable:
        return None


def _parse_txt_buf(buf, delimiter, dtype):
    """Parse a delimited-text byte buffer: native multi-threaded parser
    (dislib_tpu.native fastio, C++) when available and the target dtype is
    float32, NumPy otherwise — the native layer is never a correctness
    dependency."""
    if not buf.strip():
        return np.zeros((0, 0), dtype=dtype)
    if np.dtype(dtype) == np.float32:
        from dislib_tpu import native as _native
        if _native.get_lib() is not None:
            try:
                return _native.parse_text(buf, delimiter=delimiter)
            except _native.NativeUnavailable:
                pass     # ragged/malformed: np.loadtxt raises the real error
    return np.loadtxt(_io.BytesIO(buf), delimiter=delimiter, dtype=dtype,
                      ndmin=2)


def _scan_line_offsets(path):
    """Byte offset of every line start (one chunked pass, no float parse).
    Assumes one sample per line (the loaders' contract); a trailing newline
    does not produce a phantom row."""
    chunks = [np.zeros(1, np.int64)]
    pos = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(1 << 24)
            if not buf:
                break
            nls = np.flatnonzero(np.frombuffer(buf, np.uint8) == 10) \
                .astype(np.int64) + pos + 1
            chunks.append(nls)
            pos += len(buf)
    starts = np.concatenate(chunks)
    if len(starts) > 1 and starts[-1] >= pos:
        starts = starts[:-1]
    return starts, pos


def _read_rows(path, starts, fsize, rlo, rhi):
    """Raw bytes of rows [rlo, rhi) given the line-offset table."""
    if rlo >= rhi:
        return b""
    b0 = int(starts[rlo])
    b1 = int(starts[rhi]) if rhi < len(starts) else fsize
    with open(path, "rb") as f:
        f.seek(b0)
        return f.read(b1 - b0)


def _parse_rows(path, starts, fsize, rlo, rhi, delimiter, dtype, n):
    """Parse rows [rlo, rhi) of a delimited text file (per-host slab work)."""
    if rlo >= rhi:
        return np.zeros((0, n), dtype)
    return _parse_txt_buf(_read_rows(path, starts, fsize, rlo, rhi),
                          delimiter, dtype)


def _check_no_blank_lines(starts, fsize):
    """Raise if the offset table shows blank lines (two adjacent newlines,
    or a newline at byte 0).  Every host scans the SAME whole-file offsets,
    so this raises deterministically on all hosts — unlike slab-local parse
    errors, which would kill one process and hang its peers at the next
    collective."""
    del fsize
    if len(starts) > 1 and bool((np.diff(starts) == 1).any()):
        raise ValueError(
            "multi-process text ingest requires one sample per line "
            "(blank lines found) — load single-process instead")


def _process_row_slab(m, n):
    """Padded-row range [lo, hi) this process's addressable shards cover
    under the canonical data sharding for a logical (m, n) array."""
    import jax
    pshape = _padded_shape((m, n), _mesh.pad_quantum())
    imap = _mesh.data_sharding().devices_indices_map(pshape)
    mine = [idx for d, idx in imap.items()
            if d.process_index == jax.process_index()]
    lo = min(s[0].indices(pshape[0])[0] for s in mine)
    hi = max(s[0].indices(pshape[0])[1] for s in mine)
    return lo, hi


def _from_local_rows(local, lo, shape, block_size, dtype):
    """Assemble a global ds-array from this process's parsed row slab
    ``local`` (rows [lo, lo+len(local)) of the logical array) — one
    device_put per addressable shard, zero collectives, no host ever holds
    more than its slab.  Sharded ingest skips quarantine (module
    docstring), but the returned array still carries ``quarantine_=None``
    so `x.quarantine_` is readable on every load path."""
    import jax
    m, n = shape
    pshape = _padded_shape((m, n), _mesh.pad_quantum())
    sh = _mesh.data_sharding()
    arrs = []
    for d, idx in sh.devices_indices_map(pshape).items():
        if d.process_index != jax.process_index():
            continue
        r0, r1, _ = idx[0].indices(pshape[0])
        c0, c1, _ = idx[1].indices(pshape[1])
        blk = np.zeros((r1 - r0, c1 - c0), dtype)
        rr0, rr1 = max(r0, lo), min(r1, lo + local.shape[0])
        cc1 = min(c1, n)
        if rr0 < rr1 and c0 < cc1:
            blk[rr0 - r0: rr1 - r0, : cc1 - c0] = \
                local[rr0 - lo: rr1 - lo, c0:cc1]
        arrs.append(jax.device_put(blk, d))
    garr = jax.make_array_from_single_device_arrays(pshape, sh, arrs)
    out = _Array(garr, (m, n), reg_shape=block_size)
    out.quarantine_ = None
    return out


@_retrying_loader
def load_txt_file(path, block_size=None, delimiter=",", dtype=np.float32,
                  quarantine=None):
    """Load a delimited text file into a ds-array (reference: load_txt_file).

    Multi-process jobs (``jax.process_count() > 1``): each host scans line
    offsets (byte pass), parses only the rows its shards cover, and places
    them shard-locally — ingest parallelism AND ingest memory both scale
    with hosts (SURVEY §4.1).  Single-process parses locally.

    ``quarantine`` — non-finite rows are isolated into the returned
    array's ``.quarantine_`` report instead of poisoning blocks (module
    docstring); ``False`` loads them raw, ``None`` reads
    ``DSLIB_QUARANTINE``."""
    import jax
    if jax.process_count() <= 1:
        with open(path, "rb") as f:
            data = _parse_txt_buf(f.read(), delimiter, dtype)
        if data.size == 0:
            data = np.loadtxt(path, delimiter=delimiter, dtype=dtype, ndmin=2)
        data, _, report = _quarantine_rows(data, path, quarantine)
        out = _ds_array(data, block_size=block_size, dtype=dtype)
        out.quarantine_ = report
        return out
    from dislib_tpu.data.array import _require_dtype_support
    _require_dtype_support(dtype)
    starts, fsize = _scan_line_offsets(path)
    _check_no_blank_lines(starts, fsize)   # deterministic across hosts
    m = len(starts)
    with open(path, "rb") as f:
        n = _parse_txt_buf(f.readline(), delimiter, dtype).shape[1]
    if n == 0:
        raise ValueError(
            "multi-process text ingest reads the column count from the "
            "first line, which parsed to no columns (comment/header "
            "line?) — load single-process instead")
    lo, hi = _process_row_slab(m, n)
    rlo, rhi = min(lo, m), min(hi, m)
    local = _parse_rows(path, starts, fsize, rlo, rhi, delimiter, dtype, n)
    if local.shape[0] != rhi - rlo:
        # np.loadtxt skips comment lines the offset table counted —
        # silently zero-filling the shortfall would fabricate rows.  NOTE:
        # this check is slab-local, so only hosts whose slab holds the bad
        # lines raise; keep files comment-free for multi-host ingest.
        raise ValueError(
            "multi-process text ingest requires one sample per line "
            "(comment lines found) — load single-process instead")
    if local.size and local.shape[1] != n:
        # a width different from the first line would be silently cropped
        # or zero-filled by the shard assembly — refuse instead
        raise ValueError(
            f"rows {rlo}:{rhi} parsed {local.shape[1]} columns but the "
            f"first line has {n} — ragged text files are not supported")
    return _from_local_rows(local, rlo, (m, n), block_size, dtype)


@_retrying_loader
def load_npy_file(path, block_size=None, dtype=None, quarantine=None):
    """Load a .npy file into a ds-array (reference: load_npy_file).

    Multi-process jobs memory-map the file and materialise only this
    host's row slab (same shard-local contract as `load_txt_file`).
    ``quarantine``: see `load_txt_file`."""
    import jax
    from dislib_tpu.data.array import _coerce_dtype
    mm = np.load(path, allow_pickle=False, mmap_mode="r")
    if mm.ndim != 2:
        raise ValueError("load_npy_file expects a 2-D array")
    if jax.process_count() <= 1:
        data, _, report = _quarantine_rows(np.asarray(mm), path, quarantine)
        out = _ds_array(data, block_size=block_size, dtype=dtype)
        out.quarantine_ = report
        return out
    m, n = mm.shape
    lo, hi = _process_row_slab(m, n)
    rlo, rhi = min(lo, m), min(hi, m)
    local = _coerce_dtype(np.asarray(mm[rlo:rhi]), dtype)
    return _from_local_rows(local, rlo, (m, n), block_size, local.dtype)


def _parse_svmlight_text(lines):
    """Pure-Python svmlight parse of an iterable of text lines →
    (rows: list of {feat: val}, labels, max_feat).  Duplicate feature
    indices sum (CSR semantics, = sklearn's loader)."""
    rows, labels = [], []
    max_feat = 0
    for line in lines:
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        labels.append(float(parts[0]))
        feats = {}
        for tok in parts[1:]:
            if tok.startswith("#"):
                break
            k, v = tok.split(":")
            feats[int(k)] = feats.get(int(k), 0.0) + float(v)
        if feats:
            max_feat = max(max_feat, max(feats))
        rows.append(feats)
    return rows, labels, max_feat


def _require_in_range(csr, source):
    """A raw (quarantine-off) load may still not ship out-of-range
    indices to device — they would alias wrong columns or crash the
    dense scatter.  Raise the typed ingest error instead."""
    if csr.nnz and (int(csr.indices.min(initial=0)) < 0
                    or int(csr.indices.max(initial=0)) >= csr.shape[1]):
        raise ValueError(
            f"{source}: feature indices outside n_features={csr.shape[1]} "
            "— raise n_features, or enable quarantine to isolate the "
            "offending rows")


def _svmlight_dense(rows, m_feats):
    dense = np.zeros((len(rows), m_feats), dtype=np.float32)
    for i, feats in enumerate(rows):
        for k, v in feats.items():
            dense[i, k - 1] = v  # svmlight is 1-indexed
    return dense


def _load_svmlight_sharded(path, block_size, n_features):
    """Multi-process dense svmlight: parse only this host's row slab
    (requires one sample per line — no blank/comment lines — so the line
    offset table indexes rows exactly).  When ``n_features`` is None one
    tiny scalar allgather establishes the global feature count."""
    import jax
    from jax.experimental import multihost_utils
    starts, fsize = _scan_line_offsets(path)
    _check_no_blank_lines(starts, fsize)   # deterministic across hosts
    m = len(starts)
    lo, hi = _process_row_slab(m, n_features or 1)
    rlo, rhi = min(lo, m), min(hi, m)
    buf = _read_rows(path, starts, fsize, rlo, rhi)
    rows, labels, max_feat = _parse_svmlight_text(
        buf.decode().splitlines())
    slab_bad = len(rows) != rhi - rlo
    if n_features is None:
        # one scalar allgather establishes the feature count AND carries a
        # per-host error flag: if any slab had comment lines, EVERY host
        # raises together instead of one dying and its peers hanging at
        # this very collective
        agreed = np.asarray(multihost_utils.process_allgather(
            np.asarray([max_feat, int(slab_bad)], np.int64)))
        if agreed.reshape(-1, 2)[:, 1].any():
            raise ValueError(
                "multi-process svmlight ingest requires one sample per "
                "line (comment lines found) — load single-process instead")
        n_features = int(agreed.reshape(-1, 2)[:, 0].max())
    elif slab_bad:
        # no collective in this branch: the raise is slab-local (see the
        # txt loader note) — keep files comment-free for multi-host ingest
        raise ValueError(
            "multi-process svmlight ingest requires one sample per line "
            "(comment lines found) — load single-process instead")
    dense = _svmlight_dense(rows, n_features)
    x = _from_local_rows(dense, rlo, (m, n_features), block_size, np.float32)
    yloc = np.asarray(labels, np.float32).reshape(-1, 1)
    y = _from_local_rows(yloc, rlo, (m, 1),
                         (block_size[0], 1) if block_size else None,
                         np.float32)
    return x, y


def _quarantine_csr(csr, labels, source, opt):
    """CSR-path quarantine: a row is bad when any stored value — or its
    label — is non-finite, OR any stored column index falls outside the
    declared shape (a truncating ``n_features=`` or a corrupt stream
    batch: out-of-range entries would otherwise crash the dense scatter
    or silently alias a wrong column on device).  Returns
    (clean_csr, clean_labels, report)."""
    import jax
    if not _quarantine_enabled(opt) or jax.process_count() > 1 \
            or csr.shape[0] == 0:
        return csr, labels, None
    bad_rows = np.zeros(csr.shape[0], bool)
    bad_ent = np.nonzero(~np.isfinite(csr.data)
                         | (csr.indices < 0)
                         | (csr.indices >= csr.shape[1]))[0]
    if bad_ent.size:
        # entry i lives in the row whose indptr window contains i
        bad_rows[np.searchsorted(csr.indptr, bad_ent, side="right") - 1] = \
            True
    bad_rows |= ~np.isfinite(np.asarray(labels, np.float64))
    if not bad_rows.any():
        return csr, labels, None
    rows = np.nonzero(bad_rows)[0]
    # row selection by raw indptr surgery, NOT csr[mask]: scipy's indexed
    # slicing validates/clones through code paths that may choke on the
    # very out-of-range indices being quarantined
    clean = _csr_take_rows(csr, ~bad_rows)
    bad = _csr_take_rows(csr, bad_rows, clip=True)
    report = _emit_quarantine(source, rows, bad, clean.shape[0],
                              bad_labels=labels[bad_rows])
    return clean, labels[~bad_rows], report


def _csr_take_rows(csr, mask, clip=False):
    """Row subset of a CSR by direct indptr/indices surgery (no scipy
    fancy indexing — see `_quarantine_csr`).  ``clip`` clamps column
    indices into range so the OFFENDING-rows matrix is still a valid
    scipy object for offline triage."""
    import scipy.sparse as sp
    keep = np.nonzero(mask)[0]
    lens = np.diff(csr.indptr)[keep]
    indptr = np.concatenate([[0], np.cumsum(lens)])
    sel = np.concatenate([np.arange(csr.indptr[r], csr.indptr[r + 1])
                          for r in keep]) if keep.size else \
        np.zeros(0, np.int64)
    indices = csr.indices[sel]
    if clip:
        indices = np.clip(indices, 0, csr.shape[1] - 1)
    return sp.csr_matrix((csr.data[sel], indices, indptr),
                         shape=(keep.size, csr.shape[1]))


@_retrying_loader
def load_svmlight_file(path, block_size=None, n_features=None,
                       store_sparse=True, quarantine=None):
    """Load a svmlight/libsvm file -> (x, y) ds-arrays (reference parity).

    Hand-rolled parser (no sklearn dependency in the library path); native
    C++ single-pass CSR parser (`dislib_tpu.native.parse_svmlight`) when
    available, pure-Python fallback otherwise.  Duplicate feature indices
    sum (CSR semantics, = sklearn's loader) on both paths.

    Multi-process jobs with ``store_sparse=False`` ingest shard-locally
    (each host parses only its row slab, like `load_txt_file`); the sparse
    path parses the whole file per process — the BCOO backing is
    process-replicated by design (`SparseArray` docstring), so there is no
    shard-local placement to exploit."""
    import jax
    if jax.process_count() > 1 and not store_sparse:
        return _load_svmlight_sharded(path, block_size, n_features)
    parsed = _native_parse("parse_svmlight", path)
    if parsed is not None:
        labels_a, indptr, indices, data, nfeat = parsed
        n = labels_a.shape[0]
        m = n_features if n_features is not None else nfeat
        import scipy.sparse as sp
        csr = sp.csr_matrix((data, indices, indptr), shape=(n, m))
        csr, labels_a, report = _quarantine_csr(csr, labels_a, path,
                                                quarantine)
        _require_in_range(csr, path)
        if store_sparse:
            from dislib_tpu.data.sparse import SparseArray
            x = SparseArray.from_scipy(csr, block_size=block_size)
        else:
            x = _ds_array(csr.toarray().astype(np.float32),
                          block_size=block_size)
        x.quarantine_ = report
        y = _ds_array(labels_a.reshape(-1, 1),
                      block_size=(block_size[0], 1) if block_size else None)
        return x, y
    import scipy.sparse as sp
    with open(path) as f:
        rows, labels, max_feat = _parse_svmlight_text(f)
    m = n_features if n_features is not None else max_feat
    # build the CSR at the DECLARED width first — a truncating
    # n_features= leaves out-of-range entries visible for the quarantine
    # to isolate per row (the same hygiene the values get)
    indptr = np.zeros(len(rows) + 1, np.int64)
    idx_l, dat_l = [], []
    for i, feats in enumerate(rows):
        idx_l.extend(k - 1 for k in feats)      # svmlight is 1-indexed
        dat_l.extend(feats.values())
        indptr[i + 1] = len(idx_l)
    csr = sp.csr_matrix((np.asarray(dat_l, np.float32),
                         np.asarray(idx_l, np.int64), indptr),
                        shape=(len(rows), m))
    labels_a = np.asarray(labels, np.float32)
    csr, labels_a, report = _quarantine_csr(csr, labels_a, path, quarantine)
    _require_in_range(csr, path)
    if store_sparse:
        from dislib_tpu.data.sparse import SparseArray
        x = SparseArray.from_scipy(csr, block_size=block_size)
    else:
        x = _ds_array(csr.toarray().astype(np.float32),
                      block_size=block_size)
    x.quarantine_ = report
    y = _ds_array(labels_a.reshape(-1, 1),
                  block_size=(block_size[0], 1) if block_size else None)
    return x, y


@_retrying_loader
def load_mdcrd_file(path, block_size=None, n_atoms=None, copy_first=False,
                    quarantine=None):
    """Load an AMBER .mdcrd trajectory: one row per frame, 3*n_atoms coords
    (reference: load_mdcrd_file for the Daura/MD pipeline).
    ``quarantine``: non-finite FRAMES are isolated (see `load_txt_file`);
    the ``copy_first`` duplicate is taken from the cleaned trajectory."""
    if n_atoms is None:
        raise ValueError("n_atoms is required for mdcrd parsing")
    values = _native_parse("parse_mdcrd", path)
    if values is None:
        vals = []
        with open(path) as f:
            next(f)  # title line
            for line in f:
                vals.extend(float(line[i:i + 8])
                            for i in range(0, len(line.rstrip("\n")), 8)
                            if line[i:i + 8].strip())
        values = np.asarray(vals, dtype=np.float32)
    per_frame = 3 * n_atoms
    n_frames = len(values) // per_frame
    data = np.asarray(values[: n_frames * per_frame], dtype=np.float32)
    data = data.reshape(n_frames, per_frame)
    data, _, report = _quarantine_rows(data, path, quarantine)
    if copy_first and data.shape[0] > 0:
        data = np.vstack([data, data[:1]])
    out = _ds_array(data, block_size=block_size)
    out.quarantine_ = report
    return out


def save_txt(x, path, merge_rows=True, delimiter=","):
    """Save a ds-array to text (reference: save_txt). ``merge_rows=True``
    writes one file; ``False`` writes one file per row-block stripe, the
    reference's per-block layout."""
    data = x.collect()
    import scipy.sparse as sp
    if sp.issparse(data):
        data = data.toarray()
    if merge_rows:
        np.savetxt(path, data, delimiter=delimiter)
    else:
        import os
        os.makedirs(path, exist_ok=True)
        step = x._reg_shape[0]
        for bi, start in enumerate(range(0, data.shape[0], step)):
            np.savetxt(os.path.join(path, f"{bi}"), data[start:start + step],
                       delimiter=delimiter)
