"""Data ingest / export.

Reference capability (SURVEY.md §3.1 "I/O", `dislib/data/io.py`): per-block
reader tasks over a shared filesystem so loading is itself parallel —
`load_txt_file`, `load_svmlight_file` (sparse-capable), `load_npy_file`,
`load_mdcrd_file` (AMBER mdcrd MD trajectories), `save_txt`.

TPU-native shape: in a multi-host job each host parses only the byte-range /
row-range that lands in its local shards and the global array is assembled
with `jax.make_array_from_process_local_data`; single-host (this build's test
rig) parses locally and `device_put`s with the canonical sharding.  Parsing
itself is host-side C-speed (numpy loadtxt / buffer ops), matching the
reference where parsing was also CPU-side inside tasks.
"""

from __future__ import annotations

import io as _io
import os

import numpy as np

from dislib_tpu.data.array import Array as _Array, array as _ds_array


def _read_line_range(path, idx, count):
    """Bytes of the idx-th of `count` byte-range slices of a text file,
    adjusted to whole lines: a line belongs to the slice its FIRST byte
    falls in (the classic shared-FS split — the reference's per-block
    reader tasks partition files the same way, SURVEY §3.1 I/O row)."""
    size = os.path.getsize(path)
    lo = size * idx // count
    hi = size * (idx + 1) // count
    with open(path, "rb") as f:
        if lo > 0:
            f.seek(lo - 1)
            f.readline()              # skip the line straddling the boundary
            lo = f.tell()
        if hi < size:
            f.seek(hi - 1)
            f.readline()              # extend to cover the straddling line
            hi = f.tell()
        else:
            hi = size
        if lo >= hi:
            return b""
        f.seek(lo)
        return f.read(hi - lo)


def _native_parse(parser_name, path):
    """Run a `dislib_tpu.native` parser over a whole file, or return None
    when the native layer is unavailable or defers (malformed input — the
    Python fallback then raises the user-facing error)."""
    from dislib_tpu import native as _native
    if _native.get_lib() is None:
        return None
    try:
        with open(path, "rb") as f:
            return getattr(_native, parser_name)(f.read())
    except _native.NativeUnavailable:
        return None


def _parse_txt_buf(buf, delimiter, dtype):
    """Parse a delimited-text byte buffer: native multi-threaded parser
    (dislib_tpu.native fastio, C++) when available and the target dtype is
    float32, NumPy otherwise — the native layer is never a correctness
    dependency."""
    if not buf.strip():
        return np.zeros((0, 0), dtype=dtype)
    if np.dtype(dtype) == np.float32:
        from dislib_tpu import native as _native
        if _native.get_lib() is not None:
            try:
                return _native.parse_text(buf, delimiter=delimiter)
            except _native.NativeUnavailable:
                pass     # ragged/malformed: np.loadtxt raises the real error
    return np.loadtxt(_io.BytesIO(buf), delimiter=delimiter, dtype=dtype,
                      ndmin=2)


def _parse_txt_range(path, idx, count, delimiter, dtype):
    """Parse one byte-range slice of a delimited text file (per-host work)."""
    return _parse_txt_buf(_read_line_range(path, idx, count), delimiter,
                          dtype)


def load_txt_file(path, block_size=None, delimiter=",", dtype=np.float32):
    """Load a delimited text file into a ds-array (reference: load_txt_file).

    Multi-process jobs (``jax.process_count() > 1``) parse per-host byte
    ranges (`_parse_txt_range`) so ingest scales with hosts; the global
    array is assembled from the per-host row counts.  Single-process (this
    build's test rig) parses locally — same code path as one range."""
    import jax
    pcount = jax.process_count()
    if pcount <= 1:
        with open(path, "rb") as f:
            data = _parse_txt_buf(f.read(), delimiter, dtype)
        if data.size == 0:
            data = np.loadtxt(path, delimiter=delimiter, dtype=dtype, ndmin=2)
        return _ds_array(data, block_size=block_size)
    from jax.experimental import multihost_utils
    local = _parse_txt_range(path, jax.process_index(), pcount, delimiter,
                             dtype)
    dims = np.asarray(multihost_utils.process_allgather(
        np.asarray([local.shape[0], local.shape[1]], np.int64)))
    dims = dims.reshape(pcount, 2)
    counts, nf = dims[:, 0], int(dims[:, 1].max())
    # pad ragged per-host slices to a common shape for the allgather, then
    # reassemble in host order; each host ends with the full logical array
    # (device placement is still the canonical mesh sharding in _ds_array —
    # the per-host win is the parse, which is the expensive part)
    nmax = int(counts.max())
    pad = np.zeros((nmax, nf), dtype=dtype)
    pad[: local.shape[0], : local.shape[1]] = local
    gathered = np.asarray(multihost_utils.process_allgather(pad, tiled=False))
    data = np.concatenate([gathered[i, : int(c)]
                           for i, c in enumerate(counts) if c], axis=0)
    return _ds_array(data, block_size=block_size)


def load_npy_file(path, block_size=None):
    """Load a .npy file into a ds-array (reference: load_npy_file)."""
    data = np.load(path, allow_pickle=False)
    if data.ndim != 2:
        raise ValueError("load_npy_file expects a 2-D array")
    return _ds_array(data, block_size=block_size)


def load_svmlight_file(path, block_size=None, n_features=None, store_sparse=True):
    """Load a svmlight/libsvm file -> (x, y) ds-arrays (reference parity).

    Hand-rolled parser (no sklearn dependency in the library path); native
    C++ single-pass CSR parser (`dislib_tpu.native.parse_svmlight`) when
    available, pure-Python fallback otherwise.  Duplicate feature indices
    sum (CSR semantics, = sklearn's loader) on both paths."""
    parsed = _native_parse("parse_svmlight", path)
    if parsed is not None:
        labels_a, indptr, indices, data, nfeat = parsed
        n = labels_a.shape[0]
        m = n_features if n_features is not None else nfeat
        import scipy.sparse as sp
        csr = sp.csr_matrix((data, indices, indptr), shape=(n, m))
        if store_sparse:
            from dislib_tpu.data.sparse import SparseArray
            x = SparseArray.from_scipy(csr, block_size=block_size)
        else:
            x = _ds_array(csr.toarray().astype(np.float32),
                          block_size=block_size)
        y = _ds_array(labels_a.reshape(-1, 1),
                      block_size=(block_size[0], 1) if block_size else None)
        return x, y
    rows, labels = [], []
    max_feat = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            feats = {}
            for tok in parts[1:]:
                if tok.startswith("#"):
                    break
                k, v = tok.split(":")
                feats[int(k)] = feats.get(int(k), 0.0) + float(v)
            if feats:
                max_feat = max(max_feat, max(feats))
            rows.append(feats)
    n = len(rows)
    m = n_features if n_features is not None else max_feat
    dense = np.zeros((n, m), dtype=np.float32)
    for i, feats in enumerate(rows):
        for k, v in feats.items():
            dense[i, k - 1] = v  # svmlight is 1-indexed
    if store_sparse:
        import scipy.sparse as sp
        from dislib_tpu.data.sparse import SparseArray
        x = SparseArray.from_scipy(sp.csr_matrix(dense), block_size=block_size)
    else:
        x = _ds_array(dense, block_size=block_size)
    y = _ds_array(np.asarray(labels, dtype=np.float32).reshape(-1, 1),
                   block_size=(block_size[0], 1) if block_size else None)
    return x, y


def load_mdcrd_file(path, block_size=None, n_atoms=None, copy_first=False):
    """Load an AMBER .mdcrd trajectory: one row per frame, 3*n_atoms coords
    (reference: load_mdcrd_file for the Daura/MD pipeline)."""
    if n_atoms is None:
        raise ValueError("n_atoms is required for mdcrd parsing")
    values = _native_parse("parse_mdcrd", path)
    if values is None:
        vals = []
        with open(path) as f:
            next(f)  # title line
            for line in f:
                vals.extend(float(line[i:i + 8])
                            for i in range(0, len(line.rstrip("\n")), 8)
                            if line[i:i + 8].strip())
        values = np.asarray(vals, dtype=np.float32)
    per_frame = 3 * n_atoms
    n_frames = len(values) // per_frame
    data = np.asarray(values[: n_frames * per_frame], dtype=np.float32)
    data = data.reshape(n_frames, per_frame)
    if copy_first and n_frames > 0:
        data = np.vstack([data, data[:1]])
    return _ds_array(data, block_size=block_size)


def save_txt(x, path, merge_rows=True, delimiter=","):
    """Save a ds-array to text (reference: save_txt). ``merge_rows=True``
    writes one file; ``False`` writes one file per row-block stripe, the
    reference's per-block layout."""
    data = x.collect()
    import scipy.sparse as sp
    if sp.issparse(data):
        data = data.toarray()
    if merge_rows:
        np.savetxt(path, data, delimiter=delimiter)
    else:
        import os
        os.makedirs(path, exist_ok=True)
        step = x._reg_shape[0]
        for bi, start in enumerate(range(0, data.shape[0], step)):
            np.savetxt(os.path.join(path, f"{bi}"), data[start:start + step],
                       delimiter=delimiter)
